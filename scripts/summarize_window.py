#!/usr/bin/env python
"""Collate a live-chip session's JSON artifacts into one markdown
summary — the post-window bookkeeping (BASELINE.md "Measured TPU
results" refresh, PERF_NOTES hypothesis verdicts) reduced to a read.

Purely offline: reads the artifacts `scripts/chip_session.sh` commits
(BENCH_live/snapshot, double_spot, tune_hbm*, int_op_spot_*,
tune_mxu_*, tune_fine, examples/tpu_run averages) and prints what
landed, what PASSED, and how each row compares to the reference
scoreboard (mpi/CUdata.txt:2-8). Missing artifacts print as absent —
a half-window is summarized honestly, not padded.

Usage: python scripts/summarize_window.py [repo_root]
"""

from __future__ import annotations

import json
import sys
from pathlib import Path

REF = {("DOUBLE", "SUM"): 92.7729, ("DOUBLE", "MIN"): 92.6014,
       ("DOUBLE", "MAX"): 92.7552, ("INT", "SUM"): 90.8413,
       ("INT", "MIN"): 90.7905, ("INT", "MAX"): 90.7969}
V5E_ROOF = 819.0


def _load(path: Path):
    try:
        return json.loads(path.read_text())
    except (OSError, ValueError):
        return None


def _fmt_gbps(g):
    return "n/a" if g is None else f"{g:.1f}"


def _spot_lines(data, ref_dtype) -> list[str]:
    out = []
    for r in data.get("rows", []):
        ref = REF.get((ref_dtype, r["method"]))
        ratio = (f" = {r['gbps'] / ref:.1f}x ref" if ref and r.get("gbps")
                 else "")
        out.append(f"  {ref_dtype} {r['method']:>4} "
                   f"k{r.get('kernel')}/{r.get('threads')}: "
                   f"{_fmt_gbps(r.get('gbps'))} GB/s "
                   f"[{r['status']}]{ratio}")
    if not data.get("complete", True):
        out.append("  (artifact INCOMPLETE — session died mid-step)")
    return out


def _race_lines(data, label) -> list[str]:
    rows = data.get("ranked", [])
    out = []
    xla = next((r for r in rows if r.get("backend") == "xla"), None)
    for r in rows[:5]:
        depth = (f" depth={r['stream_buffers']}"
                 if r.get("stream_buffers") is not None else "")
        geom = ("(xla)" if r.get("backend") == "xla"
                else f"k{r.get('kernel')}/{r.get('threads')}{depth}")
        frac = (f" = {r['gbps'] / V5E_ROOF:.0%} roof"
                if r.get("gbps") and "hbm" in label else "")
        out.append(f"  {geom:>18}: {_fmt_gbps(r.get('gbps'))} GB/s "
                   f"[{r['status']}]{frac}")
    best = data.get("best")
    if best and xla and best.get("gbps") and xla.get("gbps"):
        rel = best["gbps"] / xla["gbps"]
        out.append(f"  best pallas vs XLA comparator: {rel:.2f}x "
                   f"({'WIN' if rel >= 1 else 'LOSS'})")
    if not data.get("complete", True):
        out.append("  (artifact INCOMPLETE — race died mid-run)")
    return out


def main(argv=None) -> int:
    root = Path((argv or sys.argv[1:] or ["."])[0])
    sections = []

    fr = _load(root / "FIRSTROW.json")
    if fr:
        row = fr.get("row", {})
        lines = ["## first row (step 0: time-to-first-artifact)",
                 f"  {fr.get('candidate')}: "
                 f"{_fmt_gbps(row.get('gbps'))} GB/s "
                 f"[{row.get('status')}] (chain_reps="
                 f"{fr.get('chain_reps')})"]
        for m in fr.get("timeline", []):
            lines.append(f"  T+{m['t_rel_s']:7.1f}s {m['label']}")
        persisted = [m["t_rel_s"] for m in fr.get("timeline", [])
                     if "int row persisted" in m["label"]]
        if persisted:
            verdict = ("inside" if persisted[0] < 90 else "OUTSIDE")
            lines.append(f"  -> first persisted row at "
                         f"T+{persisted[0]:.1f}s ({verdict} the 90 s "
                         "target)")
        if not fr.get("complete", True):
            lines.append("  (artifact INCOMPLETE — step died mid-run)")
        sections.append(lines)

    bench = _load(root / "BENCH_live.json") or _load(
        root / "BENCH_snapshot.json")
    if bench:
        stale = " (STALE snapshot fallback)" if bench.get("stale") else ""
        sections.append(
            ["## Headline",
             f"  {bench['metric']}: {bench['value']} {bench['unit']} "
             f"= {bench.get('vs_baseline')}x reference{stale}"])

    smoke = _load(root / "smoke.json")
    if smoke:
        lines = ["## lowering smoke (pre-race manifest)"]
        for c in smoke.get("cases", []):
            err = f" — {c['error']}" if c.get("error") else ""
            lines.append(f"  {c['name']:<22} {c['status']:<7} "
                         f"{c.get('seconds', 0):.1f}s{err}")
        ok = sum(1 for c in smoke.get("cases", []) if c.get("ok"))
        lines.append(f"  {ok}/{len(smoke.get('cases', []))} lowered")
        if not smoke.get("complete", True):
            lines.append("  (artifact INCOMPLETE — smoke died mid-case)")
        sections.append(lines)

    for name, dtype, title in (("double_spot.json", "DOUBLE",
                                "## DOUBLE scoreboard (VERDICT item 1)"),
                               ("BENCH_doubles.json", "DOUBLE",
                                "## DOUBLE opportunistic rows "
                                "(bench.py, flagship-grid contract)"),
                               ("int_op_spot_k7.json", "INT",
                                "## int op parity k7/384 (item 5)"),
                               ("int_op_spot_k6.json", "INT",
                                "## int op parity k6/512"),
                               ("int_op_spot_xla.json", "INT",
                                "## int op parity XLA comparator"),
                               ("bf16_spot.json", "BFLOAT16",
                                "## bf16 existence spot (weak #5: the "
                                "dtype's first on-chip rows)")):
        d = _load(root / name)
        if d:
            sections.append([title] + _spot_lines(d, dtype))

    for name, title in (("tune_hbm.json", "## hbm race 2^26 (item 2)"),
                        ("tune_hbm27.json", "## hbm race 2^27"),
                        ("tune_mxu_f32.json", "## MXU race f32 2^24 (item 6)"),
                        ("tune_mxu_f32_hbm.json", "## MXU race f32 2^26"),
                        ("tune_mxu_bf16.json", "## MXU race bf16 2^24"),
                        ("tune_fine.json", "## fine race 7-rep (item 7)")):
        d = _load(root / name)
        if d:
            sections.append([title] + _race_lines(d, title))

    avgs = _load(root / "examples/tpu_run/single_chip/averages.json")
    if avgs:
        lines = ["## flagship grid averages (examples/tpu_run)"]
        for key, gbps in sorted(avgs.items()):
            dt, op = key.split()
            ref = REF.get((dt, op))
            ratio = f" = {gbps / ref:.1f}x ref" if ref else ""
            lines.append(f"  {key}: {gbps:.1f} GB/s{ratio}")
        sections.append(lines)

    cal = _load(root / "calibration_live.json")
    if cal:
        # --ladder output: the verdict comes from the deciding rung
        # (utils/calibrate.py); a plain calibration carries honest_gbps
        # at top level
        hg = cal.get("honest_gbps")
        if hg is None:
            deciding = cal.get("deciding_n")
            rungs = cal.get("rungs", [])
            match = [r for r in rungs if r.get("n") == deciding]
            if not match and rungs:
                # no deciding_n recorded: per CLAUDE.md the HBM (last)
                # rung is the one that decides, not the first
                match = [rungs[-1]]
            if match:
                hg = match[-1].get("honest_gbps")
        sections.append(
            ["## calibration",
             f"  block_awaits_execution="
             f"{cal.get('block_awaits_execution', '?')} "
             f"honest_gbps={_fmt_gbps(hg)}"])

    if not sections:
        print("no window artifacts found under", root)
        return 1
    for s in sections:
        print("\n".join(s))
        print()
    return 0


if __name__ == "__main__":
    sys.exit(main())
