#!/usr/bin/env bash
# Full experiment pipeline — the submit_all.sh + getAvgs.sh + makePlots.gp
# chain as one entry point (see SURVEY.md §3.3 for the reference pipeline).
#
# Usage: scripts/run_experiment.sh [OUT_DIR] [--platform cpu]
#
# On a machine with a TPU attached this sweeps the real chip; pass
# "--platform cpu" (with optional DEVICES=k env) to run the whole pipeline
# on virtual host devices.
set -euo pipefail

OUT=${1:-out}
shift || true
PLATFORM_ARGS=("$@")
DEVICES=${DEVICES:-8}

python - "$OUT" "$DEVICES" "${PLATFORM_ARGS[@]}" <<'PY'
import sys

out_dir, devices = sys.argv[1], int(sys.argv[2])
platform = None
if "--platform" in sys.argv:
    platform = sys.argv[sys.argv.index("--platform") + 1]

import jax
if platform:
    jax.config.update("jax_platforms", platform)
    if platform == "cpu":
        jax.config.update("jax_num_cpu_devices", devices)

from pathlib import Path

from tpu_reductions.bench.aggregate import average, collect, pipeline
from tpu_reductions.bench.plot import plot_vs_ranks
from tpu_reductions.bench.report import generate_report
from tpu_reductions.bench.sweep import sweep_all, sweep_collective
from tpu_reductions.utils.logging import BenchLogger

out = Path(out_dir)
log = BenchLogger(None, None)
n_avail = len(jax.devices())
ranks = [k for k in (2, 4, 8, 16, 32) if k <= n_avail] or [1]
# On the tunneled TPU, per-launch synced timing reads the dispatch-ack
# floor, not the kernel (utils/calibrate.py): use the chained slope mode
# there; the CPU's sync is honest and periter keeps reference parity.
timing = "chained" if jax.default_backend() == "tpu" else "periter"
log.log(f"timing discipline: {timing}")

# measure + record the sync-trust calibration the report cites; persist
# it so `python -m tpu_reductions.bench.report out/ --calibration
# out/calibration.json` can regenerate the writeup offline
import json
from tpu_reductions.utils.calibrate import calibrate
cal = calibrate(n=1 << 20, iters=8, reps=3, chain_span=8).to_dict()
log.log("calibration: block_awaits_execution="
        f"{cal['block_awaits_execution']}")
out.mkdir(parents=True, exist_ok=True)
(out / "calibration.json").write_text(json.dumps(cal, indent=1))

# 1) single-chip grid (runTest analog) -> single-chip overlay numbers.
# Lands in its own raw dir: single-chip rows use a per-kernel-iteration
# timing convention incomparable with the collective rows, so they must
# not leak into the vs-ranks averages.
sc_rows = sweep_all(n=1 << 22, repeats=2, iterations=10, timing=timing,
                    out_dir=str(out / "single_chip"), logger=log)
sc = {}
for r in sc_rows:
    if r["status"] == "PASSED":
        dt = {"int32": "INT", "float64": "DOUBLE"}.get(r["dtype"],
                                                       r["dtype"].upper())
        sc.setdefault((dt, r["method"]), []).append(r["gbps"])
sc = {k: sum(v) / len(v) for k, v in sc.items()}

# 2) collective rank sweep (submit_all.sh analog)
sweep_collective(rank_counts=ranks, n=1 << 20, retries=3, timing=timing,
                 out_dir=str(out), logger=log)

# 3) aggregate (getAvgs.sh analog)
pipeline(out / "raw_output", out)
avgs = average(collect(out / "raw_output"))

# 3b) node-mode comparison sweep (the virtual_node_interesting.eps
# analog): the same INT SUM sweep in CO mode — one rank per CHIP
# (ccni_vn.sh:6's -mode VN|CO) — overlaid on the VN curve below. CO
# capacity is DERIVED from the real chip granularity: per-core device
# generations (and the CPU simulation) halve, single-device-per-chip
# generations (v4/v5e) do not (parallel/mesh.coarsen_to_chips).
from tpu_reductions.parallel.mesh import coarsen_to_chips
co_capacity = len(coarsen_to_chips(jax.devices()))
co_ranks = [k for k in ranks if k <= co_capacity]
co_avgs = {}
if co_ranks:
    sweep_collective(rank_counts=co_ranks, methods=("SUM",),
                     dtypes=("int32",), n=1 << 20, retries=3,
                     timing=timing, mode="co", out_dir=str(out / "co"),
                     logger=log)
    co_avgs = average(collect(out / "co" / "raw_output"))

# 4) plots (makePlots.gp analog) with single-chip overlays
figures = []
for dt in sorted({k[0] for k in avgs}):
    lines = {f"single-chip {op}": g for (d, op), g in sc.items() if d == dt}
    figures += plot_vs_ranks(avgs, dt, out / dt.lower(),
                             single_chip_lines=lines or None)
if co_avgs:
    from tpu_reductions.bench.plot import plot_vn_vs_co
    figures += plot_vn_vs_co(
        {"VN (every device a rank)": avgs,
         "CO (one rank per chip)": co_avgs},
        "INT", "SUM", out / "vn_vs_co")

# 5) report (writeup.tex analog)
paths = generate_report(avgs, single_chip=sc, figures=figures,
                        out_dir=out, platform=jax.default_backend(),
                        calibration=cal)
print("report:", paths["md"], paths["tex"])

# 6) the compiled writeup (writeup.pdf analog; no TeX stack in this
# image, so bench.pdf authors the PDF directly via matplotlib)
from tpu_reductions.bench.pdf import generate_pdf

pdf_data = {"avgs": avgs, "single_chip": sc or None, "calibration": cal,
            "figures": list(figures), "roofline": None,
            "annotated_rows": None}
print("writeup:", generate_pdf(out, platform=jax.default_backend(),
                               data=pdf_data))
PY
