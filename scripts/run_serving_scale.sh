#!/usr/bin/env bash
# Refresh the committed open-loop serving SCALING curve (ISSUE 13;
# docs/SERVING.md "scaling tier") — off-chip by construction, safe
# with the relay dead: the loadgen's --scale grid drives
# sequential / coalesced / routerN (serve/router.py replica tier)
# over the same seeded open-loop workload (Poisson + bursty) on
# --platform=cpu with 8 virtual devices, every series gating launches
# through one local chaos relay in `slow` mode, and lands the
# device-parallel sharded row (an oversized request split across the
# 8 devices and finished with the selected collective — the
# collective.select evidence parses back out of the armed ledger into
# the artifact). Then the curve is folded into the flagship report
# next to the closed-loop serving curve (bench/regen.py).
#
# Usage: bash scripts/run_serving_scale.sh [out.json] [experiment_dir]
set -euo pipefail
cd "$(dirname "$0")/.."

exp="${2:-examples/tpu_run}"
out="${1:-$exp/serving_scale.json}"

python -m tpu_reductions.serve.loadgen --platform=cpu --devices=8 \
    --scale --scale-clients=64,256,1024 --replicas=4 --seed=0 \
    --out="$out"

if [ -d "$exp" ]; then
    python -m tpu_reductions.bench.regen "$exp"
fi
