#!/usr/bin/env bash
# Rank-scaling experiment: the bandwidth-vs-ranks axis at EVERY rank
# count the reference published (64/256/1024 — mpi/submit_all.sh:3-4
# sweeps sbatch --nodes {32,128,512} with VN doubling; results rows in
# mpi/results/INT_SUM.txt:2-4), plus the full doubling curve below 64.
#
# One physical chip cannot host a rank sweep, so this runs the REAL
# ring/halving shard_map implementations over virtual CPU devices
# (jax_num_cpu_devices — the same code path the TPU mesh compiles).
# Absolute GB/s on a virtual mesh are meaningless (round-3 verdict,
# missing #5); the product is the SCALING SHAPE: whether aggregate
# bandwidth grows with rank count the way the reference's torus curves
# do, and where the collective's constant overheads bend the curve.
#
# Usage: scripts/run_rank_scaling.sh [OUT_DIR=examples/rank_scaling]
set -euo pipefail
cd "$(dirname "$0")/.."

OUT=${1:-examples/rank_scaling}
MAX_RANKS=${MAX_RANKS:-1024}

python - "$OUT" "$MAX_RANKS" <<'PY'
import json
import sys
from pathlib import Path

out, max_ranks = Path(sys.argv[1]), int(sys.argv[2])

import jax

# virtual mesh BEFORE first backend touch (the axon plugin ignores
# JAX_PLATFORMS — CLAUDE.md); this experiment is off-chip by design
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_num_cpu_devices", max_ranks)

from tpu_reductions.bench.aggregate import average, collect, pipeline
from tpu_reductions.bench.plot import plot_vs_ranks
from tpu_reductions.bench.sweep import sweep_collective
from tpu_reductions.utils.logging import BenchLogger

log = BenchLogger(None, None)
ranks = [k for k in (2, 4, 8, 16, 32, 64, 128, 256, 512, 1024)
         if k <= max_ranks]
log.log(f"rank-scaling sweep over {ranks} virtual CPU devices")

# reference op order (MAX, MIN, SUM — reduce.c:73), both headline
# dtypes; n=2^20 keeps the whole sweep seconds-cheap on one core. At
# the high rank counts the per-rank shards (1K elements at 1024 ranks)
# sit far BELOW any per-device floor — that dispatch-overhead regime
# is expected there, and the amortization probe below is what
# separates it from the ring's algorithmic cost
sweep_collective(rank_counts=ranks, n=1 << 20, retries=3,
                 timing="periter", out_dir=str(out), logger=log)

pipeline(out / "raw_output", out)
avgs = average(collect(out / "raw_output"))

figures = []
for dt in sorted({k[0] for k in avgs}):
    figures += plot_vs_ranks(avgs, dt, out / dt.lower())

# normalized shape figure: ours next to the reference's published
# 64/256/1024 rows (shapes comparable; absolute GB/s are not)
from tpu_reductions.bench.plot import plot_scaling_shape

REFERENCE_ROWS = {"INT SUM": [(64, 9.182), (256, 38.6484),
                              (1024, 146.818)],
                  "DOUBLE SUM": [(64, 3.8102), (256, 15.3126),
                                 (1024, 60.9754)]}
shape_series = {}
for op_dt in ("INT SUM", "DOUBLE SUM"):
    dt, op = op_dt.split()
    pts = [(k, g) for (d, o, k), g in sorted(avgs.items())
           if d == dt and o == op]
    if pts:
        shape_series[f"{op_dt} (this framework, serialized "
                     "virtual mesh)"] = pts
    shape_series[f"{op_dt} (reference torus)"] = REFERENCE_ROWS[op_dt]
figures += plot_scaling_shape(shape_series, out / "scaling_shape")

# payload-amortization probe at the largest rank count: if the
# high-rank droop were pure fixed dispatch overhead, bandwidth would
# recover fully with payload; the residual gap is the ring's O(k)
# serialized latency steps — the algorithmic cost a 1-core mesh
# surfaces instead of hiding (parallel/collectives.py ring docstring)
from tpu_reductions.bench.collective_driver import run_collective_benchmark
from tpu_reductions.config import CollectiveConfig

probe = []
for n in (1 << 20, 1 << 22, 1 << 24):
    res = run_collective_benchmark(
        CollectiveConfig(method="SUM", dtype="int32", n=n, retries=3,
                         num_devices=max_ranks, timing="periter"),
        logger=log)
    gb = [r.reference_gbps for r in res if r.status.name == "PASSED"]
    if gb:
        probe.append([n, round(sum(gb) / len(gb), 3)])

# the shape verdict, derived mechanically: aggregate bandwidth ratio
# across each rank doubling, ours vs the reference's 64->256->1024
# quadruplings (mpi/results/*_SUM.txt)
shape = {}
for (dt, op, k), g in sorted(avgs.items()):
    shape.setdefault(f"{dt} {op}", []).append((k, round(g, 3)))
(out / "scaling_shape.json").write_text(json.dumps(
    {"ranks": ranks, "series": shape,
     "amortization_probe_ranks": max_ranks,
     "amortization_probe": probe,
     "reference_rows": {k: [list(p) for p in v]
                        for k, v in REFERENCE_ROWS.items()},
     "note": "virtual-CPU mesh on one core: absolute GB/s meaningless; "
             "the curve SHAPE (aggregate bandwidth vs ranks) is the "
             "product"}, indent=1) + "\n")
print("figures:", ", ".join(str(f) for f in figures))
print("wrote", out / "scaling_shape.json")
PY

# refresh the quantized suite's accuracy-vs-bandwidth curve next to the
# rank-scaling evidence (same rank ladder, same off-chip virtual mesh;
# bench/regen folds it into report.md from here — docs/COLLECTIVES.md)
python -m tpu_reductions.bench.quant_curve --platform=cpu \
    --out="$OUT/quant_curve.json"

# refresh the reshard engine's redistribution curve (ISSUE 15;
# docs/RESHARD.md): planner programs executed + oracle-verified +
# memory-accounted over the same rank ladder, committed next to the
# rank-scaling evidence; bench/regen folds it into report.md from here
python -m tpu_reductions.bench.reshard_curve --platform=cpu \
    --out="$OUT/reshard_curve.json"
