#!/bin/bash
# redlint convenience wrapper: the same invocation the tier-1 gate
# (tests/test_lint_clean.py) enforces. Exit 0 = clean, 1 = findings.
# Runs the whole-program flow + concurrency layers (RED017-RED024) by
# default with the fact cache armed at .lint_cache.json (untracked), so
# a warm re-run is sub-second; --no-flow / --flow-cache= opt out
# (docs/LINT.md).
#
#   bash scripts/lint.sh              # lint the gate surface
#   bash scripts/lint.sh --format=json
#   bash scripts/lint.sh --graph=dot  # the flow/conc call graph
#   bash scripts/lint.sh --changed-only  # per-file rules on git-dirty
#                                     # files only; flow/conc still
#                                     # whole-program (pre-commit loop)
#   bash scripts/lint.sh path.py ...  # lint specific files instead
set -euo pipefail
cd "$(dirname "$0")/.."

args=()
paths=()
for a in "$@"; do
    case "$a" in
        --*) args+=("$a") ;;
        *)   paths+=("$a") ;;
    esac
done
if [ "${#paths[@]}" -eq 0 ]; then
    paths=(tpu_reductions scripts bench.py __graft_entry__.py)
fi
exec python -m tpu_reductions.lint "${paths[@]}" "${args[@]+"${args[@]}"}"
