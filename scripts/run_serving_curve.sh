#!/usr/bin/env bash
# Refresh the committed serving curve (docs/SERVING.md) — off-chip by
# construction, safe with the relay dead: the loadgen runs the engine
# on --platform=cpu with the per-launch tunnel RTT modeled through a
# local chaos relay in `slow` mode, then the curve is folded into the
# flagship report next to the GB/s tables (bench/regen.py).
#
# The ONE committed copy lives in the experiment dir (PR 6 left a
# duplicate at the repo root; bench/regen.py only ever reads the
# experiment dir's copy, so the loadgen now writes there directly).
#
# Usage: bash scripts/run_serving_curve.sh [out.json] [experiment_dir]
set -euo pipefail
cd "$(dirname "$0")/.."

exp="${2:-examples/tpu_run}"
out="${1:-$exp/serving_curve.json}"

python -m tpu_reductions.serve.loadgen --platform=cpu --clients=8 \
    --requests=32 --n=65536 --out="$out"

if [ -d "$exp" ]; then
    python -m tpu_reductions.bench.regen "$exp"
fi
