#!/usr/bin/env bash
# Refresh the committed ELASTIC serving curve (ISSUE 17;
# docs/SERVING.md "elastic fleet") — off-chip by construction, safe
# with the relay dead: the loadgen's --elastic mode drives the
# autoscaler control loop (serve/autoscale.py) against the seeded
# diurnal open-loop arrival plan at 64/256/1024 clients on
# --platform=cpu with 8 virtual devices, the per-launch tunnel RTT
# modeled through a local chaos relay in `slow` mode, then runs the
# drain-vs-kill contract pair on the same seeded burst: the planned
# drain hands warm bucket keys to survivors, moves sharded partials
# via an oracle-verified redistribution program under the declared
# peak-memory bound, and sheds ZERO requests where the SIGKILL
# control row sheds in-flight ones. Then the curve is folded into the
# flagship report next to the scaling curve (bench/regen.py).
#
# Usage: bash scripts/run_serving_elastic.sh [out.json] [experiment_dir]
set -euo pipefail
cd "$(dirname "$0")/.."

exp="${2:-examples/tpu_run}"
out="${1:-$exp/serving_elastic.json}"

python -m tpu_reductions.serve.loadgen --platform=cpu --devices=8 \
    --elastic --plan=diurnal --scale-clients=64,256,1024 --seed=0 \
    --out="$out"

if [ -d "$exp" ]; then
    python -m tpu_reductions.bench.regen "$exp"
fi
