#!/usr/bin/env bash
# Refresh the committed crash-recovery instrument (ISSUE 18;
# docs/SERVING.md "crash-consistent control plane") — off-chip by
# construction, safe with the relay dead: the loadgen's --recovery
# mode runs three disruptions on ONE seeded idem-keyed workload on
# --platform=cpu. kill_router spawns a REAL `serve.router --journal`
# subprocess over process-per-replica children, kills the controller
# via the scripted router.crash os._exit mid-burst, restarts it
# against the same fleet journal (replicas re-adopted, not
# respawned), and the TCP clients retry with their original
# idempotency keys — the ledger-joined claim is ZERO duplicate device
# executions and MTTR in fractions of a second. kill_replica and
# drain run the in-process contrast pair. Then the table is folded
# into the flagship report next to the elastic curve (bench/regen.py).
#
# Usage: bash scripts/run_serving_recovery.sh [out.json] [experiment_dir]
set -euo pipefail
cd "$(dirname "$0")/.."

exp="${2:-examples/tpu_run}"
out="${1:-$exp/serving_recovery.json}"

python -m tpu_reductions.serve.loadgen --platform=cpu \
    --recovery --recovery-requests=48 --crash-after=16 --seed=0 \
    --out="$out"

if [ -d "$exp" ]; then
    python -m tpu_reductions.bench.regen "$exp"
fi
