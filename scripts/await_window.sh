#!/usr/bin/env bash
# Poll the tunnel relay; the moment it answers, run the value-ordered
# live-chip session (scripts/chip_session.sh), teeing to a session log
# (round-2 lesson: the log enabled curve recovery after a mid-run relay
# death — examples/tpu_run/RECOVERY.md).
#
# The probe demands a REAL connect (unlike watchdog.relay_alive's
# inconclusive-counts-as-alive semantics): a watcher that fires the
# session on an EMFILE would burn the window's first minutes failing at
# device discovery. Untunneled hosts (no relay marker) exit immediately
# — there is no window to await.
#
# Usage: bash scripts/await_window.sh [poll_seconds=20] [max_hours=11]
set -uo pipefail
cd "$(dirname "$0")/.."

POLL=${1:-20}
MAX_HOURS=${2:-11}

if [ ! -e /root/.relay.py ]; then
    echo "await_window: untunneled host (no relay marker); nothing to await"
    exit 0
fi

probe() {
    # -S skips site init (~2 s in this venv); stdlib sockets only
    python -S -c '
import socket, sys
for port in (8082, 8083):
    try:
        socket.create_connection(("127.0.0.1", port), timeout=2).close()
        sys.exit(0)
    except OSError:
        continue
sys.exit(1)'
}

deadline=$(( $(date +%s) + MAX_HOURS * 3600 ))
echo "await_window: polling relay every ${POLL}s (giving up after ${MAX_HOURS}h)"
while true; do
    if probe; then
        echo "await_window: relay ALIVE at $(date -u +%FT%TZ); starting chip session"
        bash scripts/chip_session.sh 2>&1 | tee -a chip_session_r03.log
        rc=${PIPESTATUS[0]}
        echo "await_window: chip session exited rc=$rc at $(date -u +%FT%TZ)"
        exit "$rc"
    fi
    if [ "$(date +%s)" -ge "$deadline" ]; then
        echo "await_window: no window opened within ${MAX_HOURS}h; giving up"
        exit 4
    fi
    sleep "$POLL"
done
