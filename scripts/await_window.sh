#!/usr/bin/env bash
# Poll the tunnel relay; the moment it answers, run the value-ordered
# live-chip session (scripts/chip_session.sh), teeing to a session log
# (round-2 lesson: the log enabled curve recovery after a mid-run relay
# death — examples/tpu_run/RECOVERY.md).
#
# The probe demands a REAL connect (unlike watchdog.relay_alive's
# inconclusive-counts-as-alive semantics): a watcher that fires the
# session on an EMFILE would burn the window's first minutes failing at
# device discovery. Untunneled hosts (no relay marker) exit immediately
# — there is no window to await.
#
# Round-long invariant (round-3 verdict item 8): the watcher RE-ARMS.
# A chip session that aborts mid-window (relay re-wedge, rc=3) puts the
# watcher back into polling — a second window resumes the remaining
# value; only a session that runs to completion (rc=0) retires it. The
# default horizon (13 h) outlasts a round, and a heartbeat line lands
# in the log every ~10 min so "armed" is verifiable afterwards.
#
# Usage: bash scripts/await_window.sh [poll_seconds=20] [max_hours=13]
#   CHIP_LOG=chip_session_rNN.log overrides the session log name
#   (default: derived from the highest ROUND<N>.md in the repo — the
#   round in flight — so nobody has to bump a hardcoded pin per round).
#   Chaos-harness overrides (docs/RESILIENCE.md):
#     TPU_REDUCTIONS_RELAY_MARKER  tunneled-host marker file
#     TPU_REDUCTIONS_RELAY_PORTS   comma-separated probe ports
#     AWAIT_ROOT                   repo root to run in (rehearsal repos)
#     SESSION_BIN                  session script (tests substitute one)
set -uo pipefail
cd "${AWAIT_ROOT:-$(dirname "$0")/..}"

POLL=${1:-20}
MAX_HOURS=${2:-13}
RELAY_MARKER=${TPU_REDUCTIONS_RELAY_MARKER:-/root/.relay.py}
SESSION_BIN=${SESSION_BIN:-scripts/chip_session.sh}

current_round() {
    # highest ROUND<N>.md names the round in flight; r00 when none
    # (rehearsal repos) — the round-5 fix for the stale r04 pin this
    # default used to hardcode
    local n=0 f k
    for f in ROUND[0-9]*.md; do
        [ -e "$f" ] || continue
        k=${f#ROUND}; k=${k%.md}
        case "$k" in *[!0-9]*) continue ;; esac
        [ "$k" -gt "$n" ] && n=$k
    done
    printf 'r%02d' "$n"
}
LOG=${CHIP_LOG:-chip_session_$(current_round).log}

if [ ! -e "$RELAY_MARKER" ]; then
    echo "await_window: untunneled host (no relay marker); nothing to await"
    exit 0
fi

probe() {
    # -S skips site init (~2 s in this venv); stdlib sockets only.
    # Ports come from the same env override the watchdog honors, so
    # the chaos harness's fake relay (faults/relay.py) is probed by
    # the identical machinery a real window would use.
    python -S -c '
import os, socket, sys
ports = [int(p) for p in os.environ.get("TPU_REDUCTIONS_RELAY_PORTS",
                                        "8082,8083").split(",") if p.strip()]
for port in ports:
    try:
        socket.create_connection(("127.0.0.1", port), timeout=2).close()
        sys.exit(0)
    except OSError:
        continue
sys.exit(1)'
}

deadline=$(( $(date +%s) + MAX_HOURS * 3600 ))
# ~10-min heartbeat, derived from the poll interval
beat_every=$(( (600 + POLL - 1) / POLL )); [ "$beat_every" -lt 1 ] && beat_every=1
probes=0
echo "await_window: polling relay every ${POLL}s (horizon ${MAX_HOURS}h," \
     "session log ${LOG}, re-arming after aborted sessions)"
while true; do
    if probe; then
        echo "await_window: relay ALIVE at $(date -u +%FT%TZ); starting chip session"
        bash "$SESSION_BIN" 2>&1 | tee -a "$LOG"
        rc=${PIPESTATUS[0]}
        echo "await_window: chip session exited rc=$rc at $(date -u +%FT%TZ)"
        # commit the session log itself: round 2's curve recovery came
        # FROM this log (examples/tpu_run/RECOVERY.md) — it must survive
        # even if nobody is attending when the watcher fires
        if [ -s "$LOG" ] && git add -- "$LOG" \
                && ! git diff --cached --quiet -- "$LOG"; then
            git commit -q -m "Chip session log ($(date -u +%FT%TZ), rc=$rc)" \
                -- "$LOG" || true
        fi
        if [ "$rc" -eq 0 ]; then
            exit 0
        fi
        # aborted session: the window closed early — re-arm for the next
        echo "await_window: re-arming (session rc=$rc; remaining value" \
             "can land in a later window)"
    fi
    probes=$(( probes + 1 ))
    if [ $(( probes % beat_every )) -eq 0 ]; then
        echo "await_window: still armed at $(date -u +%FT%TZ) (${probes} probes, relay dead)"
    fi
    if [ "$(date +%s)" -ge "$deadline" ]; then
        echo "await_window: no completed session within ${MAX_HOURS}h; giving up"
        exit 4
    fi
    sleep "$POLL"
done
