#!/usr/bin/env bash
# Poll the tunnel relay; the moment it answers, run the value-ordered
# live-chip session (scripts/chip_session.sh), teeing to a session log
# (round-2 lesson: the log enabled curve recovery after a mid-run relay
# death — examples/tpu_run/RECOVERY.md).
#
# The probe demands a REAL connect (unlike watchdog.relay_alive's
# inconclusive-counts-as-alive semantics): a watcher that fires the
# session on an EMFILE would burn the window's first minutes failing at
# device discovery. Untunneled hosts (no relay marker) exit immediately
# — there is no window to await.
#
# Round-long invariant (round-3 verdict item 8): the watcher RE-ARMS.
# A chip session that aborts mid-window (relay re-wedge, rc=3) puts the
# watcher back into polling — a second window resumes the remaining
# value; only a session that runs to completion (rc=0) retires it. The
# default horizon (13 h) outlasts a round, and a heartbeat line lands
# in the log every ~10 min so "armed" is verifiable afterwards.
#
# Wedge-aware arming (ISSUE 3): a TCP probe only proves the relay's
# PORTS answer — a stalled relay (accepts, never services) or a wedged
# device lease (jax.devices() hangs machine-wide) both pass it and then
# hang the session forever. Before firing, the hang-proof preflight
# (python -m tpu_reductions.utils.preflight: sacrificial subprocess
# under a hard timeout, never a JAX call in THIS process tree's
# foreground) must classify the chip LIVE; its verdict persists to the
# health file both supervisors consume. A session that exits 4 (the
# watchdog's heartbeat HANG trigger, distinct from the dead-relay 3)
# defers re-arm until the health verdict clears instead of burning
# window minutes on back-to-back hangs.
#
# Usage: bash scripts/await_window.sh [poll_seconds=20] [max_hours=13]
#   CHIP_LOG=chip_session_rNN.log overrides the session log name
#   (default: derived from the highest ROUND<N>.md in the repo — the
#   round in flight — so nobody has to bump a hardcoded pin per round).
#   Chaos-harness overrides (docs/RESILIENCE.md):
#     TPU_REDUCTIONS_RELAY_MARKER  tunneled-host marker file
#     TPU_REDUCTIONS_RELAY_PORTS   comma-separated probe ports
#     AWAIT_ROOT                   repo root to run in (rehearsal repos)
#     SESSION_BIN                  session script (tests substitute one)
#     PREFLIGHT_CMD                preflight command (tests substitute)
#     TPU_REDUCTIONS_PREFLIGHT=0   skip the preflight gate entirely
#     TPU_REDUCTIONS_HEALTH_FILE / _HEALTH_TTL_S   health-file seam
set -uo pipefail
REPO_DIR=$(cd "$(dirname "$0")/.." && pwd)
cd "${AWAIT_ROOT:-$REPO_DIR}"

# Flight-recorder shell emitter (docs/OBSERVABILITY.md): arm/re-arm/
# defer decisions land in the same ledger the chip session appends to,
# so the timeline CLI can reconstruct the WHOLE window — watcher
# included. No-op unless TPU_REDUCTIONS_LEDGER is set.
# shellcheck disable=SC1091
source "$REPO_DIR/scripts/obs_event.sh" 2>/dev/null \
    || obs_event() { :; }

POLL=${1:-20}
MAX_HOURS=${2:-13}
RELAY_MARKER=${TPU_REDUCTIONS_RELAY_MARKER:-/root/.relay.py}
SESSION_BIN=${SESSION_BIN:-scripts/chip_session.sh}
PREFLIGHT_CMD=${PREFLIGHT_CMD:-}
HEALTH_FILE=${TPU_REDUCTIONS_HEALTH_FILE:-.chip_health.json}
HEALTH_TTL_S=${TPU_REDUCTIONS_HEALTH_TTL_S:-300}

current_round() {
    # highest ROUND<N>.md names the round in flight; r00 when none
    # (rehearsal repos) — the round-5 fix for the stale r04 pin this
    # default used to hardcode
    local n=0 f k
    for f in ROUND[0-9]*.md; do
        [ -e "$f" ] || continue
        k=${f#ROUND}; k=${k%.md}
        case "$k" in *[!0-9]*) continue ;; esac
        [ "$k" -gt "$n" ] && n=$k
    done
    printf 'r%02d' "$n"
}
LOG=${CHIP_LOG:-chip_session_$(current_round).log}

if [ ! -e "$RELAY_MARKER" ]; then
    echo "await_window: untunneled host (no relay marker); nothing to await"
    exit 0
fi

probe() {
    # -S skips site init (~2 s in this venv); stdlib sockets only.
    # The default port list comes from the ONE canonical source
    # (tpu_reductions/utils/relay_env.py, exec'd by path — no package
    # import) so this probe cannot drift from the watchdog's; the
    # TPU_REDUCTIONS_RELAY_PORTS env override the chaos harness points
    # at its fake relay (faults/relay.py) wins inside env_ports().
    # An unreadable canonical source counts as "not alive": the watcher
    # keeps polling (conservative) instead of firing a session from a
    # broken checkout.
    RELAY_ENV_PY="$REPO_DIR/tpu_reductions/utils/relay_env.py" \
    python -S -c '
import os, socket, sys
g = {}
try:
    exec(open(os.environ["RELAY_ENV_PY"]).read(), g)
except OSError:
    sys.exit(1)
for port in g["env_ports"]():
    try:
        socket.create_connection(("127.0.0.1", port), timeout=2).close()
        sys.exit(0)
    except OSError:
        continue
sys.exit(1)'
}

preflight() {
    # The wedge gate the port probe cannot be (header): hang-proof by
    # construction — utils/preflight.py spawns a sacrificial discovery
    # subprocess under a hard timeout, so this call is bounded even
    # against a stalled relay or a wedged lease. rc 0=LIVE, 3=NO_RELAY,
    # 4=STALLED/WEDGED. TPU_REDUCTIONS_PREFLIGHT=0 skips (and tests
    # substitute PREFLIGHT_CMD).
    [ "${TPU_REDUCTIONS_PREFLIGHT:-1}" = 0 ] && return 0
    if [ -n "$PREFLIGHT_CMD" ]; then
        $PREFLIGHT_CMD
        return $?
    fi
    PYTHONPATH="$REPO_DIR${PYTHONPATH:+:$PYTHONPATH}" \
        python -m tpu_reductions.utils.preflight
}

health_verdict() {
    # fresh verdict from the preflight health file; '' when absent,
    # stale (mtime past the TTL — a wedge verdict must never outlive
    # the flap that caused it) or unparseable
    [ -f "$HEALTH_FILE" ] || return 0
    local mt now
    mt=$(stat -c %Y "$HEALTH_FILE" 2>/dev/null) || return 0
    now=$(date +%s)
    [ $(( now - mt )) -le "$HEALTH_TTL_S" ] || return 0
    sed -n 's/.*"verdict": *"\([A-Z_]*\)".*/\1/p' "$HEALTH_FILE" | head -1
}

wait_health_clear() {
    # a STALLED/WEDGED verdict means the next session can only hang:
    # hold re-arm until the verdict clears (a fresh LIVE preflight or
    # TTL expiry), instead of burning window minutes on repeat hangs
    local v
    v=$(health_verdict)
    case "$v" in STALLED|WEDGED) ;; *) return 0 ;; esac
    echo "await_window: health verdict $v; deferring until it clears" \
         "(TTL ${HEALTH_TTL_S}s)"
    while v=$(health_verdict); do
        case "$v" in STALLED|WEDGED) sleep "$POLL" ;; *) break ;; esac
    done
    echo "await_window: health verdict cleared at $(date -u +%FT%TZ); resuming polling"
}

deadline=$(( $(date +%s) + MAX_HOURS * 3600 ))
# ~10-min heartbeat, derived from the poll interval
beat_every=$(( (600 + POLL - 1) / POLL )); [ "$beat_every" -lt 1 ] && beat_every=1
probes=0
echo "await_window: polling relay every ${POLL}s (horizon ${MAX_HOURS}h," \
     "session log ${LOG}, re-arming after aborted sessions)"
obs_event watcher.arm poll_s="$POLL" horizon_h="$MAX_HOURS"
while true; do
    if probe; then
        pf_rc=0
        preflight || pf_rc=$?
        if [ "$pf_rc" -ne 0 ]; then
            # ports answer but the chip is not usable — the hang the
            # port probe cannot see (rc 3=NO_RELAY: it died between
            # probes; rc 4=STALLED/WEDGED: firing would hang forever)
            echo "await_window: relay ports answer but preflight says" \
                 "NOT LIVE (rc=$pf_rc; 3=relay dead, 4=stall/wedge);" \
                 "not firing a session"
            obs_event watcher.defer reason=preflight rc="$pf_rc"
            [ "$pf_rc" -eq 4 ] && wait_health_clear
        else
            echo "await_window: relay ALIVE at $(date -u +%FT%TZ); starting chip session"
            obs_event watcher.fire probes="$probes"
            bash "$SESSION_BIN" 2>&1 | tee -a "$LOG"
            rc=${PIPESTATUS[0]}
            echo "await_window: chip session exited rc=$rc at $(date -u +%FT%TZ)"
            obs_event watcher.session_end rc="$rc"
            # commit the session log itself: round 2's curve recovery
            # came FROM this log (examples/tpu_run/RECOVERY.md) — it
            # must survive even if nobody is attending at fire time
            if [ -s "$LOG" ] && git add -- "$LOG" \
                    && ! git diff --cached --quiet -- "$LOG"; then
                git commit -q -m "Chip session log ($(date -u +%FT%TZ), rc=$rc)" \
                    -- "$LOG" || true
            fi
            if [ "$rc" -eq 0 ]; then
                obs_event watcher.retire rc=0
                exit 0
            fi
            # aborted session: the window closed early — re-arm for the
            # next, distinguishing the watchdog's two exits: 3 = relay
            # DEAD (polling finds the next window), 4 = HANG with live
            # ports (stalled relay / wedged lease — re-arming straight
            # away would fire into the same hang; hold until the health
            # verdict clears)
            if [ "$rc" -eq 3 ]; then
                echo "await_window: re-arming (session rc=3: relay DEAD" \
                     "mid-session; remaining value can land in a later window)"
                obs_event watcher.rearm rc=3
            elif [ "$rc" -eq 4 ]; then
                echo "await_window: session rc=4: HANG with relay alive" \
                     "(stalled relay or wedged lease — heartbeat watchdog);" \
                     "deferring re-arm until the health verdict clears"
                obs_event watcher.defer reason=hang rc=4
                wait_health_clear
                obs_event watcher.rearm rc=4
            else
                echo "await_window: re-arming (session rc=$rc; remaining value" \
                     "can land in a later window)"
                obs_event watcher.rearm rc="$rc"
            fi
        fi
    fi
    probes=$(( probes + 1 ))
    if [ $(( probes % beat_every )) -eq 0 ]; then
        echo "await_window: still armed at $(date -u +%FT%TZ) (${probes} probes, relay dead)"
    fi
    if [ "$(date +%s)" -ge "$deadline" ]; then
        echo "await_window: no completed session within ${MAX_HOURS}h; giving up"
        obs_event watcher.expire hours="$MAX_HOURS" probes="$probes"
        exit 4
    fi
    sleep "$POLL"
done
