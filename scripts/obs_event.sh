#!/usr/bin/env bash
# obs_event — the SHELL producer of flight-recorder events
# (docs/OBSERVABILITY.md). The supervisors are deliberately python-free
# (scripts/supervise_watcher.sh: nothing in them may hang on a dead
# relay or pay a jax import), so they cannot route through
# tpu_reductions/obs/ledger.py; this sourced helper is the one
# sanctioned shell-side emitter, held to the same row grammar
# (lint/grammar.py EVENT_ROW_RE — tests/test_obs.py validates its
# output against the python schema).
#
# Usage (after `source scripts/obs_event.sh`):
#   obs_event <event> [key=value ...]
#
# No-op unless TPU_REDUCTIONS_LEDGER names the ledger file (and
# TPU_REDUCTIONS_OBS_DISABLE != 1). One printf >> append = one write
# syscall for these line-sized events, so concurrent python/shell
# producers interleave at line granularity — the same no-torn-lines
# contract as the python emitter. Values that look numeric pass through
# as JSON numbers; everything else is escaped into a JSON string.
# Failures are swallowed (`|| true`): observability must never abort a
# session step.

obs_event() {
    [ -n "${TPU_REDUCTIONS_LEDGER:-}" ] || return 0
    [ "${TPU_REDUCTIONS_OBS_DISABLE:-0}" = 1 ] && return 0
    local ev=$1 fields="" kv k v
    shift
    # causal identity (ISSUE 12): when TPU_REDUCTIONS_TRACE_CTX carries
    # the session's `trace:span` wire form (obs/trace.py), shell events
    # stamp it too — same trailing-field position as the python emitter,
    # so EVENT_ROW_RE's leading keys stay untouched. The id grammar
    # check mirrors obs/trace._ID_RE: a corrupt env var is dropped, it
    # can never tear the JSON row.
    if printf '%s' "${TPU_REDUCTIONS_TRACE_CTX:-}" \
            | grep -Eq '^[A-Za-z0-9][A-Za-z0-9._-]*:[A-Za-z0-9][A-Za-z0-9._-]*$'; then
        fields=", \"trace\": \"${TPU_REDUCTIONS_TRACE_CTX%%:*}\""
        fields="$fields, \"span\": \"${TPU_REDUCTIONS_TRACE_CTX#*:}\""
    fi
    for kv in "$@"; do
        k=${kv%%=*}
        v=${kv#*=}
        if printf '%s' "$v" | grep -Eq '^-?[0-9]+(\.[0-9]+)?$'; then
            fields="$fields, \"$k\": $v"
        else
            v=$(printf '%s' "$v" | sed -e 's/\\/\\\\/g' -e 's/"/\\"/g')
            fields="$fields, \"$k\": \"$v\""
        fi
    done
    printf '{"t": %s, "ev": "%s", "pid": %d, "src": "shell"%s}\n' \
        "$(date +%s.%N)" "$ev" "$$" "$fields" \
        >> "$TPU_REDUCTIONS_LEDGER" 2>/dev/null || true
}
