#!/usr/bin/env bash
# One command for a live-chip session. The step SEQUENCE is no longer a
# hand-ordered list: the window scheduler (python -m tpu_reductions.sched,
# docs/SCHEDULER.md) plans value-per-expected-second against the
# remaining-window estimate and re-plans after every task — a window
# that opens mid-plan resumes the PLAN (sched_state.json), not a script
# prefix. This script keeps what must stay shell-side: the JAX-free
# relay gate, the per-step artifact commits, the wall-clock budget
# enforcement (timeout -s INT) and the collating exit trap. The
# pre-scheduler static list survives as fallback_static_session (used
# only when the scheduler itself cannot run — redlint RED013 waivers
# mark every hardcoded budget there as the sanctioned exception).
# The drivers drain their device queues (results materialize on host),
# so interrupting BETWEEN steps cannot strand in-flight work.
set -uo pipefail
cd "$(dirname "$0")/.."

# repo root resolved via BASH_SOURCE so lib-mode sourcing (tests) finds
# helper files regardless of cwd
CHIP_SESSION_REPO="$(cd "$(dirname "${BASH_SOURCE[0]}")/.." && pwd)"

# Flight-recorder shell emitter (docs/OBSERVABILITY.md): a missing
# helper degrades to a no-op — observability must never be the reason
# a live window aborts.
# shellcheck disable=SC1091
source "$(dirname "${BASH_SOURCE[0]}")/obs_event.sh" 2>/dev/null \
    || obs_event() { :; }

# Quick relay gate (no JAX import, ~instant): on the tunneled box a
# dead relay can never come back in-session (CLAUDE.md), so starting —
# or continuing to — any on-chip step would either hang at device
# discovery or silently run the wrong platform. Non-tunneled hosts
# (no relay by construction) always pass.
# Inline socket probe, NOT an import of tpu_reductions.utils.watchdog:
# the package's heavy modules pull in jax (~2 s, and the axon plugin is
# the machinery a dead relay hangs) — this gate must stay genuinely
# JAX-free. The canonical port/marker DEFAULTS come from the ONE source
# (tpu_reductions/utils/relay_env.py), exec'd by path under python -S
# so no package import happens and the list cannot drift from the
# watchdog's (ISSUE 5 satellite); the TPU_REDUCTIONS_RELAY_MARKER/
# _PORTS env overrides the chaos harness points at its fake relay
# (faults/relay.py, docs/RESILIENCE.md) still win inside env_*().
# Semantics mirror watchdog.tunneled_environment/relay_alive (marker
# file; any port connecting, or an inconclusive local error, counts as
# alive).
relay_ok() {
    # -S: skip site initialization (~2 s in this venv) — stdlib only
    RELAY_ENV_PY="$CHIP_SESSION_REPO/tpu_reductions/utils/relay_env.py" \
    python -S -c '
import os, socket, sys
g = {}
try:
    exec(open(os.environ["RELAY_ENV_PY"]).read(), g)
except OSError:
    sys.exit(0)   # canonical source unreadable: inconclusive => alive
                  # (the per-step gates and the watchdog still protect)
if not os.path.exists(g["env_marker"]()):
    sys.exit(0)      # untunneled host: no relay by construction
inconclusive = False
for port in g["env_ports"]():
    try:
        socket.create_connection(("127.0.0.1", port), timeout=2).close()
        sys.exit(0)
    except (ConnectionRefusedError, ConnectionResetError, TimeoutError):
        continue
    except OSError:
        inconclusive = True
sys.exit(0 if inconclusive else 3)'
}

STEP_LAST_RC=0  # the last step's command rc, for the scheduler loop's
                # --record feedback (step() itself keeps its abort/
                # continue semantics)

step() {  # step <name> <budget_seconds> <artifact...> -- <cmd...>
    local name=$1 budget=$2; shift 2
    local arts=()
    while [ $# -gt 0 ] && [ "$1" != "--" ]; do arts+=("$1"); shift; done
    if [ $# -eq 0 ]; then
        echo "step '$name': missing -- sentinel" >&2
        return 1
    fi
    shift
    echo "=== chip_session: $name (budget ${budget}s) ==="
    if [ "$SESSION_RAN" = 0 ]; then
        # the last commit touching the flagship example BEFORE the
        # session's first step: the exit trap regenerates the report
        # when this moves (the flagship step commits its own artifacts,
        # so worktree dirtiness alone would miss them). Recorded here —
        # in the cwd the steps commit from — not at source time.
        TPU_RUN_HEAD=$(git log -1 --format=%H -- examples/tpu_run \
                       2>/dev/null || echo none)
    fi
    SESSION_RAN=1
    if ! relay_ok; then
        # a step that exited 1 for its own reasons (e.g. bench.py's
        # stale-snapshot outage contract) does not carry the rc=3
        # signal — this probe catches a relay that died between steps
        # regardless of how the previous step reported it
        echo "=== chip_session: ABORT — relay died before step '$name'; remaining steps skipped ==="
        obs_event session.abort reason=relay-dead-between-steps step="$name"
        exit 3
    fi
    obs_event step.start name="$name" budget="$budget"
    local status=ok rc=0
    # Per-step wall-clock budget (round-3 verdict, weak #2): a
    # slow-but-alive stall — a Mosaic lowering pileup, a multi-minute
    # tunnel stall — must not consume the whole window; the next step
    # gets its chance. SIGINT first so python raises KeyboardInterrupt
    # and the drivers' per-row persistence + queue drain run (CLAUDE.md:
    # a SIGKILLed process with in-flight device work can wedge the
    # chip); the 120 s kill-after is the backstop for a process too
    # wedged to honor the interrupt.
    timeout --signal=INT --kill-after=120 "$budget" "$@" || rc=$?
    STEP_LAST_RC=$rc
    if [ "$rc" -eq 124 ] || [ "$rc" -eq 137 ]; then
        status=FAILED
        echo "=== chip_session: $name TIMED OUT after ${budget}s (committing any artifacts it DID produce) ==="
    elif [ "$rc" -ne 0 ]; then
        status=FAILED
        echo "=== chip_session: $name FAILED rc=$rc (committing any artifacts it DID produce) ==="
        # a failing step can still have written real data (e.g. the HBM
        # race writes tune_hbm.json with every row FAILED, then exits 1
        # because no Pallas candidate passed — the exact hypothesis the
        # step probes); losing it to a later wedge would defeat the
        # script's commit-between-steps contract
    fi
    obs_event step.end name="$name" rc="$rc" status="$status"
    # the ledger itself is a per-step artifact: commit it with whatever
    # the step produced, so the postmortem record survives a window
    # death exactly like the measurement rows do — and so does the
    # scheduler's plan state (the plan must resume across windows)
    if [ -n "${TPU_REDUCTIONS_LEDGER:-}" ] \
            && [ -e "${TPU_REDUCTIONS_LEDGER}" ]; then
        arts+=("${TPU_REDUCTIONS_LEDGER}")
    fi
    # the compile observatory's per-surface record rides along the same
    # way: every step's entry point appended its cold/warm observations
    # (obs/compile.py), and a window death must not lose them
    if [ -n "${TPU_REDUCTIONS_COMPILE_LEDGER:-}" ] \
            && [ -e "${TPU_REDUCTIONS_COMPILE_LEDGER}" ]; then
        arts+=("${TPU_REDUCTIONS_COMPILE_LEDGER}")
    fi
    if [ -n "${SCHED_STATE:-}" ] && [ -e "${SCHED_STATE:-}" ]; then
        arts+=("$SCHED_STATE")
    fi
    # add per artifact, and commit only the ones that exist: one
    # missing path must block neither the add nor the commit of the
    # artifacts that were produced
    local a
    local have=()
    for a in "${arts[@]}"; do
        if [ ! -e "$a" ]; then
            echo "=== chip_session: $name: no artifact $a ==="
        elif git add -- "$a"; then   # real add failures stay loud
            have+=("$a")
        fi
    done
    if [ ${#have[@]} -gt 0 ] \
            && ! git diff --cached --quiet -- "${have[@]}"; then
        # commit restricted to the produced artifacts: pre-existing
        # staged work must never be swept into an artifact commit
        local msg="On-chip artifacts: $name"
        [ "$status" = FAILED ] && msg="$msg (step FAILED; partial artifacts)"
        git commit -q -m "$msg" -- "${have[@]}"
    else
        echo "=== chip_session: $name produced no new artifact ==="
    fi
    if [ "$rc" -eq 3 ]; then
        # exit code 3 = accelerator unavailable (run_tpu_experiment's
        # device probe / utils/watchdog.py relay death; bench.py's
        # outage contract is exit 1 + stale snapshot, which the
        # per-step relay_ok probe above covers instead): the relay
        # cannot come back in-session (CLAUDE.md), so every later
        # on-chip step could only hang — stop here with the artifacts
        # committed. The scheduler's plan state persists as-is: the
        # next window's invocation resumes the plan (sched/state.py).
        echo "=== chip_session: ABORT — accelerator gone (rc=3); remaining steps skipped ==="
        exit 3
    fi
}

# However the session ends — completed, budget-cut, relay abort — it
# leaves a collated WINDOW_SUMMARY.md committed: the post-window
# bookkeeping must not depend on anyone being present when the watcher
# fires (summarize_window.py is pure offline collation; no relay gate
# applies to it).
SESSION_RAN=0   # set by step(): an abort BEFORE any step must not
                # collate a "window summary" out of stale artifacts
TPU_RUN_HEAD="" # recorded by the first step() call (see there)
SCHED_STATE=${TPU_REDUCTIONS_SCHED_STATE:-sched_state.json}
SCHED_ARGS=${TPU_REDUCTIONS_SCHED_ARGS:-}   # tests inject --tasks/--platform
SCHED_TASKS_RUN=0   # scheduled steps completed (fallback guard)
summarize_on_exit() {
    [ "$SESSION_RAN" = 1 ] || return 0
    # Offline evidence collation FIRST (pure disk work — safe after the
    # relay dies, which is exactly when this trap usually runs): spot
    # rows measured at the flagship contract seed the grid cache, and
    # if anything under examples/tpu_run changed this window (seeded
    # cells, curve cells from a budget-cut flagship step whose own
    # report regeneration never ran — the flagship step COMMITS those
    # cells itself, so the dirty-worktree test alone would miss them;
    # the recorded pre-session commit hash catches the committed case)
    # the report is re-collated from disk and committed. Both calls
    # carry the same budget discipline as the steps: the trap usually
    # runs with the relay dead, and an import stall here would pin the
    # watcher instead of re-arming it.
    # redlint: disable=RED013 -- exit-trap collation cap (offline, no device): not a window plan
    timeout 300 python -m tpu_reductions.bench.seed_cache \
        double_spot.json int_op_spot_k6.json BENCH_doubles.json \
        --grid-dir examples/tpu_run/single_chip || true
    # Flight-recorder collation (pure disk work, same as the rest of
    # this trap): the machine summary lands next to the flagship
    # evidence so regen appends the window-utilization table to
    # report.md (bench/regen.py), and the dirty dir triggers the regen
    # below even when nothing else changed this window.
    if [ -n "${TPU_REDUCTIONS_LEDGER:-}" ] \
            && [ -s "${TPU_REDUCTIONS_LEDGER}" ]; then
        timeout 120 python -m tpu_reductions.obs.timeline "$TPU_REDUCTIONS_LEDGER" --json examples/tpu_run/obs_timeline.json --quiet \
            || true
    fi
    # the scheduler's plan-vs-actual record travels WITH the evidence:
    # regen folds it into report.md (bench/regen.py; ISSUE 5 satellite)
    if [ -s "$SCHED_STATE" ]; then
        cp -f -- "$SCHED_STATE" examples/tpu_run/sched_state.json \
            2>/dev/null || true
    fi
    # ...and so does the compile observatory's cold/warm record
    # (ISSUE 8): regen folds the per-surface compile-latency table
    if [ -n "${TPU_REDUCTIONS_COMPILE_LEDGER:-}" ] \
            && [ -s "${TPU_REDUCTIONS_COMPILE_LEDGER}" ]; then
        cp -f -- "$TPU_REDUCTIONS_COMPILE_LEDGER" \
            examples/tpu_run/compile_ledger.json 2>/dev/null || true
    fi
    if [ -n "$(git status --porcelain -- examples/tpu_run)" ] \
            || [ "$(git log -1 --format=%H -- examples/tpu_run)" \
                 != "$TPU_RUN_HEAD" ]; then
        # redlint: disable=RED013 -- exit-trap collation cap (offline, no device): not a window plan
        timeout 600 python -m tpu_reductions.bench.regen \
            examples/tpu_run || true
        git add -- examples/tpu_run \
            && git commit -q -m "Window evidence collated into examples/tpu_run (offline regen)" \
                -- examples/tpu_run || true
        # our own commit moved the head: re-record it so a re-entrant
        # trap (or a later manual call) doesn't re-collate a no-op
        TPU_RUN_HEAD=$(git log -1 --format=%H -- examples/tpu_run \
                       2>/dev/null || echo none)
    fi
    python scripts/summarize_window.py . > WINDOW_SUMMARY.md 2>/dev/null \
        || true
    # the per-window utilization table is COMPUTED from the ledger
    # (obs/timeline.py --summary-md), never hand-written — appended so
    # the summary commit below carries it; with a scheduler run in the
    # ledger it now includes the per-task planned/actual/skipped table
    if [ -n "${TPU_REDUCTIONS_LEDGER:-}" ] \
            && [ -s "${TPU_REDUCTIONS_LEDGER}" ]; then
        echo >> WINDOW_SUMMARY.md
        timeout 120 python -m tpu_reductions.obs.timeline "$TPU_REDUCTIONS_LEDGER" --summary-md >> WINDOW_SUMMARY.md \
            || true
    fi
    if [ -s WINDOW_SUMMARY.md ] && git add -- WINDOW_SUMMARY.md \
            && ! git diff --cached --quiet -- WINDOW_SUMMARY.md; then
        git commit -q -m "Window summary (auto-collated at session exit)" \
            -- WINDOW_SUMMARY.md || true
    fi
}

# The scheduler-driven session (the round-5 tentpole): ask the planner
# for one value-ranked pick at a time, run it through the SAME step()
# machinery (relay gate, budget, artifact commits), feed the outcome
# back (--record) so the duration priors update online, replan. The
# plan state (sched_state.json) persists every decision atomically —
# a watchdog exit 3/4 or a flap mid-task resumes the plan, not the
# script (docs/SCHEDULER.md).
# Returns 0 when the plan runs dry, 20 when the scheduler ITSELF is
# broken (caller falls back to the static list — but only if no
# scheduled task ran yet: a mid-plan fallback would re-measure).
run_scheduled_session() {
    local nexttext rc t_start elapsed
    while :; do
        nexttext=$(PYTHONPATH="$CHIP_SESSION_REPO${PYTHONPATH:+:$PYTHONPATH}" \
                   python -m tpu_reductions.sched --next --emit=shell \
                       --state="$SCHED_STATE" $SCHED_ARGS ;) && rc=0 || rc=$?
        if [ "$rc" -eq 10 ]; then
            echo "=== chip_session: scheduler plan complete ==="
            return 0
        fi
        if [ "$rc" -ne 0 ] || [ -z "$nexttext" ]; then
            echo "=== chip_session: scheduler --next failed (rc=$rc) ===" >&2
            return 20
        fi
        eval "$nexttext" || return 20
        t_start=$(date +%s)
        # shellcheck disable=SC2086 -- artifact list is word-split on purpose
        step "$SCHED_TASK_NAME" "$SCHED_TASK_BUDGET" $SCHED_TASK_ARTIFACTS -- \
            bash -c "$SCHED_TASK_CMD"
        elapsed=$(( $(date +%s) - t_start ))
        SCHED_TASKS_RUN=$((SCHED_TASKS_RUN + 1))
        # outcome feedback: priors sharpen online; a failed record must
        # not kill the session (the next --next reconciles from the
        # task's own artifacts)
        PYTHONPATH="$CHIP_SESSION_REPO${PYTHONPATH:+:$PYTHONPATH}" \
        python -m tpu_reductions.sched --record "$SCHED_TASK_SLUG" \
            --rc="$STEP_LAST_RC" --elapsed="$elapsed" \
            --state="$SCHED_STATE" $SCHED_ARGS || true
        if [ "$STEP_LAST_RC" -eq 4 ]; then
            # heartbeat hang (utils/watchdog.py exit 4): the chip is
            # stalled/wedged with live ports — an un-settled task would
            # be re-picked immediately and hang again; stop here, the
            # plan resumes next window (rc 3 aborts inside step())
            echo "=== chip_session: ABORT — heartbeat hang (rc=4); plan resumes next window ==="
            obs_event session.abort reason=hang-exit-4
            exit 4
        fi
    done
}

# ---------------------------------------------------------------------------
# The pre-scheduler static list (round-5 ordering), kept ONLY as the
# no-scheduler fallback. Budgets here are the sanctioned RED013
# exception (waivers below); their live copies are sched/tasks.py's
# budget_s fields, which the fallback must mirror. Never extended:
# new measurement units go in the registry.
# ---------------------------------------------------------------------------
fallback_static_session() {
    # pipefail INSIDE each bash -c: the child shell does not inherit
    # the outer setting, and without it a crashed python is masked by
    # tee/tail
    # redlint: disable=RED013 -- no-scheduler fallback path: the static budget mirrors sched/tasks.py firstrow
    step "first row" 300 FIRSTROW.json BENCH_snapshot.json BENCH_doubles.json -- \
        python -m tpu_reductions.bench.firstrow

    # BENCH_SKIP_PROBE: relay_ok just verified the relay seconds ago;
    # the probe subprocess would re-pay a full jax init (~30-40 s of
    # window) to learn the same thing. BENCH_DOUBLES=0 when a COMPLETE
    # f64 scoreboard with a VERIFIED row landed THIS SESSION (mtime vs
    # FIRSTROW_T0) — re-measuring rows written seconds ago would spend
    # window minutes on redundant rows (round-5 ADVICE).
    # redlint: disable=RED013 -- no-scheduler fallback path: mirrors sched/tasks.py headline_bench
    step "headline bench" 240 BENCH_live.json BENCH_snapshot.json BENCH_doubles.json -- \
        bash -c 'set -o pipefail; d=1; \
                 if grep -q "\"complete\": true" BENCH_doubles.json 2>/dev/null \
                    && grep -q "\"status\": \"PASSED\"" BENCH_doubles.json 2>/dev/null \
                    && [ "$(stat -c %Y BENCH_doubles.json)" -ge "${FIRSTROW_T0%.*}" ]; then d=0; fi; \
                 BENCH_SKIP_PROBE=1 BENCH_DOUBLES=$d python bench.py | tee BENCH_live.json'

    # all-device f64 (ops/dd_reduce.device_finish_pairs): the DOUBLE
    # SUM/MIN/MAX scoreboard; --chainreps=5 matches sweep.FLAGSHIP_GRID
    # so these rows seed the flagship grid's resume cache at session
    # exit (seed_cache)
    # redlint: disable=RED013 -- no-scheduler fallback path: mirrors sched/tasks.py double_spot
    step "double scoreboard" 300 double_spot.json -- \
        python -m tpu_reductions.bench.spot --type=double \
            --methods=SUM,MIN,MAX --n=16777216 --iterations=256 \
            --chainreps=5 --out=double_spot.json

    # --out persists per rung (partial until the deciding HBM rung
    # lands): a budget cut or relay death mid-ladder keeps the VMEM rung
    # redlint: disable=RED013 -- no-scheduler fallback path: mirrors sched/tasks.py calibrate_ladder
    step "calibration ladder" 240 calibration_live.json -- \
        python -m tpu_reductions.utils.calibrate --ladder \
            --chainspan 256 --reps 7 --out=calibration_live.json

    # every never-lowered kernel surface compiles+runs once at tiny n
    # BEFORE the races that depend on it
    # redlint: disable=RED013 -- no-scheduler fallback path: mirrors sched/tasks.py smoke
    step "lowering smoke" 420 smoke.json -- \
        python -m tpu_reductions.bench.smoke --out=smoke.json

    # redlint: disable=RED013 -- no-scheduler fallback path: mirrors sched/tasks.py hbm26
    step "hbm regime race 2^26" 420 tune_hbm.json -- \
        python -m tpu_reductions.bench.autotune --method=SUM --type=int \
            --n=67108864 --grid=hbm --comparator --out=tune_hbm.json

    # 2^27 was round 2's weakest HBM point (621 vs 779 GB/s)
    # redlint: disable=RED013 -- no-scheduler fallback path: mirrors sched/tasks.py hbm27
    step "hbm regime race 2^27" 420 tune_hbm27.json -- \
        python -m tpu_reductions.bench.autotune --method=SUM --type=int \
            --n=134217728 --grid=hbm --comparator --out=tune_hbm27.json

    # MIN trailed SUM by 23% in round 2 with no recorded cause; rc
    # accumulates across the probes so a crash of the first is not
    # masked by a clean second
    # redlint: disable=RED013 -- no-scheduler fallback path: mirrors sched/tasks.py int_op_parity
    step "int op parity probe" 420 \
            int_op_spot_k7.json int_op_spot_k6.json int_op_spot_xla.json -- \
        bash -c 'rc=0; \
                 python -m tpu_reductions.bench.spot --type=int \
                     --methods=SUM,MIN,MAX --n=16777216 --kernel=7 \
                     --threads=384 --iterations=256 --chainreps=5 \
                     --out=int_op_spot_k7.json || rc=$?; \
                 python -m tpu_reductions.bench.spot --type=int \
                     --methods=SUM,MIN,MAX --n=16777216 --kernel=6 \
                     --threads=512 --iterations=256 --chainreps=5 \
                     --out=int_op_spot_k6.json || rc=$?; \
                 python -m tpu_reductions.bench.spot --type=int \
                     --methods=SUM,MIN,MAX --n=16777216 --backend=xla \
                     --iterations=256 --chainreps=5 \
                     --out=int_op_spot_xla.json || rc=$?; \
                 exit $rc'

    # first on-chip evidence for the streaming pipeline that erases
    # the 4 GiB staging hazard (ISSUE 7; docs/STREAMING.md); the ONE
    # committed probe lives in the experiment dir (PR-6 serving_curve
    # dedup rule), where bench/regen.py folds it into report.md
    # redlint: disable=RED013 -- no-scheduler fallback path: mirrors sched/tasks.py stream_probe
    step "streaming pipeline probe" 300 examples/tpu_run/stream_probe.json -- \
        python -m tpu_reductions.bench.stream --method=SUM --type=int \
            --n=268435456 --chunk-bytes=67108864 --sync-every=4 \
            --out=examples/tpu_run/stream_probe.json

    # bf16's first on-chip rows (round-3 weak #5)
    # redlint: disable=RED013 -- no-scheduler fallback path: mirrors sched/tasks.py bf16_spot
    step "bf16 existence spot" 180 bf16_spot.json -- \
        python -m tpu_reductions.bench.spot --type=bfloat16 \
            --methods=SUM,MIN,MAX --n=16777216 --iterations=256 \
            --chainreps=5 --out=bf16_spot.json

    # kernel 9 (MXU) in both regimes (2^24 VMEM-resident, 2^26 HBM)
    # redlint: disable=RED013 -- no-scheduler fallback path: mirrors sched/tasks.py mxu_f32
    step "mxu race f32" 420 tune_mxu_f32.json tune_mxu_f32_hbm.json -- \
        bash -c 'rc=0; \
                 python -m tpu_reductions.bench.autotune --method=SUM \
                     --type=float --n=16777216 --iterations=256 --grid=mxu \
                     --comparator --out=tune_mxu_f32.json || rc=$?; \
                 python -m tpu_reductions.bench.autotune --method=SUM \
                     --type=float --n=67108864 --grid=mxu \
                     --comparator --out=tune_mxu_f32_hbm.json || rc=$?; \
                 exit $rc'

    # redlint: disable=RED013 -- no-scheduler fallback path: mirrors sched/tasks.py mxu_bf16
    step "mxu race bf16" 300 tune_mxu_bf16.json -- \
        python -m tpu_reductions.bench.autotune --method=SUM --type=bfloat16 \
            --n=16777216 --iterations=256 --grid=mxu --comparator \
            --out=tune_mxu_bf16.json

    # 5+ slope reps so the round-2 single-rep 22.7 TB/s k7/384 claim
    # gets a quotable repeat-averaged confirmation (or a retraction)
    # redlint: disable=RED013 -- no-scheduler fallback path: mirrors sched/tasks.py fine_race
    step "fine tile race" 420 tune_fine.json -- \
        python -m tpu_reductions.bench.autotune --method=SUM --type=int \
            --n=16777216 --iterations=256 --chainreps=7 --grid=fine \
            --out=tune_fine.json

    # off-chip by design (--platform=cpu): the accuracy-vs-bandwidth
    # curve needs no live chip, so it is honest flap-time filler here
    # exactly as it is in the scheduler's plan (docs/COLLECTIVES.md)
    # redlint: disable=RED013 -- no-scheduler fallback path: mirrors sched/tasks.py quant_curve
    step "accuracy-vs-bandwidth curve" 300 \
            examples/rank_scaling/quant_curve.json -- \
        python -m tpu_reductions.bench.quant_curve --platform=cpu \
            --out=examples/rank_scaling/quant_curve.json

    # off-chip by design: the redistribution curve runs the reshard
    # planner's programs on the virtual mesh (docs/RESHARD.md), so it
    # is flap-time filler exactly as the scheduler prices it
    # redlint: disable=RED013 -- no-scheduler fallback path: mirrors sched/tasks.py reshard_curve
    step "redistribution curve" 420 \
            examples/rank_scaling/reshard_curve.json -- \
        python -m tpu_reductions.bench.reshard_curve --platform=cpu \
            --out=examples/rank_scaling/reshard_curve.json

    # off-chip by design too: the open-loop serving scale grid rides
    # virtual devices + the local chaos relay, so it is flap-time
    # filler exactly as the scheduler prices it (docs/SERVING.md
    # scaling tier)
    # redlint: disable=RED013 -- no-scheduler fallback path: mirrors sched/tasks.py serving_scale
    step "open-loop serving scale curve" 600 \
            examples/tpu_run/serving_scale.json -- \
        bash scripts/run_serving_scale.sh

    # off-chip by design as well: the elastic autoscaler curve drives
    # in-process fleets + the local chaos relay on virtual devices,
    # flap-time filler exactly as the scheduler prices it
    # (docs/SERVING.md elastic fleet)
    # redlint: disable=RED013 -- no-scheduler fallback path: mirrors sched/tasks.py serving_elastic
    step "elastic autoscaler curve" 600 \
            examples/tpu_run/serving_elastic.json -- \
        bash scripts/run_serving_elastic.sh

    # the reduction family's first on-chip rows (ISSUE 20;
    # docs/FAMILY.md): SCAN racing mxu-scan vs xla-cumsum, segmented
    # reduce, argmin/argmax — every cell chained + oracle-verified,
    # plus the serving proof rows; the committed artifact is what
    # exec/cost.pick_scan prices from (smoke lowered mxu-scan above)
    # redlint: disable=RED013 -- no-scheduler fallback path: mirrors sched/tasks.py family_spot
    step "reduction-family spot" 300 examples/tpu_run/family_spot.json -- \
        python -m tpu_reductions.bench.family_spot --n=16777216 \
            --out=examples/tpu_run/family_spot.json

    # off-chip by design: the crash-recovery instrument kills and
    # restarts a journaled router subprocess + the in-process
    # kill-replica/drain contrast pair on cpu, flap-time filler
    # exactly as the scheduler prices it (docs/SERVING.md
    # crash-consistent control plane)
    # redlint: disable=RED013 -- no-scheduler fallback path: mirrors sched/tasks.py serving_recovery
    step "crash-recovery instrument" 420 \
            examples/tpu_run/serving_recovery.json -- \
        bash scripts/run_serving_recovery.sh

    # 3 h: the long tail (hazard cells last), and the watcher re-arms
    # on abort — a flagship that wedges slow-but-alive must not pin the
    # watcher past the round
    # redlint: disable=RED013 -- no-scheduler fallback path: mirrors sched/tasks.py flagship
    step "flagship experiment" 10800 examples/tpu_run -- \
        bash scripts/run_tpu_experiment.sh examples/tpu_run
}

# Sourceable-lib mode: `CHIP_SESSION_LIB=1 source scripts/chip_session.sh`
# stops here with relay_ok/step/summarize_on_exit/run_scheduled_session
# defined — the rehearsal tests (tests/test_chip_session.py) drive the
# step machinery against toy commands in a temp repo, so a bash bug is
# found off-chip, not in a live window.
if [ "${CHIP_SESSION_LIB:-0}" = 1 ]; then
    return 0 2>/dev/null || exit 0
fi

trap summarize_on_exit EXIT

# Flight recorder armed for the whole session (docs/OBSERVABILITY.md):
# every step's entry point inherits the ledger path and appends typed
# events; step() commits the ledger alongside each step's artifacts.
# An explicit env wins (the chaos harness points it at a tmp file).
: "${TPU_REDUCTIONS_LEDGER:=obs_ledger.jsonl}"
export TPU_REDUCTIONS_LEDGER
# The compile observatory's persistent store (obs/compile.py): every
# step's compiles append their surface/verdict rows here; step()
# commits it with the step's artifacts and the exit trap copies it
# next to the flagship evidence for the report fold (ISSUE 8).
: "${TPU_REDUCTIONS_COMPILE_LEDGER:=compile_ledger.json}"
export TPU_REDUCTIONS_COMPILE_LEDGER
# Causal trace context (ISSUE 12, obs/trace.py): ONE trace per round —
# a re-invocation after a watchdog exit 3/4 reuses the sidecar's
# context (marking the seam with trace.cut) so the resumed session
# continues the SAME trace; a fresh round mints new ids and persists
# the sidecar for whoever dies next. Every step subprocess and
# obs_event call inherits the exported TPU_REDUCTIONS_TRACE_CTX.
trace_sidecar="${TPU_REDUCTIONS_LEDGER}.trace"
if [ -z "${TPU_REDUCTIONS_TRACE_CTX:-}" ]; then
    if [ -s "$trace_sidecar" ]; then
        TPU_REDUCTIONS_TRACE_CTX=$(head -n1 "$trace_sidecar")
        export TPU_REDUCTIONS_TRACE_CTX
        obs_event trace.cut reason=session-reinvocation
    else
        TPU_REDUCTIONS_TRACE_CTX="$(od -An -N8 -tx1 /dev/urandom | tr -d ' \n'):$(od -An -N6 -tx1 /dev/urandom | tr -d ' \n')"
        export TPU_REDUCTIONS_TRACE_CTX
        printf '%s\n' "$TPU_REDUCTIONS_TRACE_CTX" > "$trace_sidecar" || true
    fi
fi
obs_event session.start prog=chip_session

if ! relay_ok; then
    echo "=== chip_session: relay is dead before the session started; nothing on-chip can run — aborting (rc=3) ==="
    obs_event session.abort reason=relay-dead-at-start
    exit 3
fi

# FIRSTROW_T0 = the session-start epoch: every firstrow stage logs
# T+x.xs against it and the timeline lands inside FIRSTROW.json, so
# every window (and every rehearsal) commits its own time-to-first-
# artifact measurement (round-4 verdict do-this #3; target: int row
# < 90 s). The scheduler's value model guarantees firstrow is the
# first pick of a fresh plan (sched/tasks.py).
export FIRSTROW_T0
FIRSTROW_T0=$(date +%s.%N)

run_scheduled_session && sched_rc=0 || sched_rc=$?
if [ "$sched_rc" -eq 20 ]; then
    if [ "$SCHED_TASKS_RUN" -gt 0 ]; then
        # mid-plan scheduler failure: falling back would re-measure
        # the tasks the plan already ran — abort instead; the watcher
        # re-arms and the next invocation resumes the plan
        echo "=== chip_session: scheduler failed mid-plan; aborting (plan state persisted) ==="
        obs_event session.abort reason=scheduler-failed-midplan
        exit 1
    fi
    echo "=== chip_session: scheduler unavailable; falling back to the static step list ==="
    obs_event session.fallback reason=scheduler-unavailable
    fallback_static_session
fi

obs_event session.end prog=chip_session
# a cleanly-ended round retires its trace: the sidecar only exists to
# let an exit-3/4 re-invocation continue a trace a death left open —
# the NEXT round should mint a fresh one
rm -f "$trace_sidecar" 2>/dev/null || true
echo "=== chip_session: done ==="
exit 0
