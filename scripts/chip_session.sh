#!/usr/bin/env bash
# One command for a live-chip session, ordered by value-per-minute so a
# tunnel that re-wedges mid-run still leaves the most important
# artifacts committed (round-1 VERDICT: "measure early, snapshot
# mid-round, re-verify at the end"; step list + budgets below at the
# step invocations). Each step git-commits ONLY its own artifacts
# before the next starts, and runs under a wall-clock budget (timeout
# -s INT) so a slow-but-alive stall cannot consume the window. The
# drivers drain their device queues (results materialize on host), so
# interrupting BETWEEN steps cannot strand in-flight work.
set -uo pipefail
cd "$(dirname "$0")/.."

# Flight-recorder shell emitter (docs/OBSERVABILITY.md): resolved via
# BASH_SOURCE so lib-mode sourcing (tests) finds it regardless of cwd;
# a missing helper degrades to a no-op — observability must never be
# the reason a live window aborts.
# shellcheck disable=SC1091
source "$(dirname "${BASH_SOURCE[0]}")/obs_event.sh" 2>/dev/null \
    || obs_event() { :; }

# Quick relay gate (no JAX import, ~instant): on the tunneled box a
# dead relay can never come back in-session (CLAUDE.md), so starting —
# or continuing to — any on-chip step would either hang at device
# discovery or silently run the wrong platform. Non-tunneled hosts
# (no relay by construction) always pass.
# Inline socket probe, NOT an import of tpu_reductions.utils.watchdog:
# the package __init__ pulls in jax (~2 s, and the axon plugin is the
# machinery a dead relay hangs) — this gate must stay genuinely
# JAX-free. Semantics mirror watchdog.tunneled_environment/relay_alive
# (marker file; any port connecting, or an inconclusive local error,
# counts as alive), including the TPU_REDUCTIONS_RELAY_MARKER/_PORTS
# env overrides the chaos harness (faults/relay.py,
# docs/RESILIENCE.md) points at its fake relay.
relay_ok() {
    # -S: skip site initialization (~2 s in this venv) — stdlib only
    python -S -c '
import os, socket, sys
marker = os.environ.get("TPU_REDUCTIONS_RELAY_MARKER", "/root/.relay.py")
if not os.path.exists(marker):
    sys.exit(0)      # untunneled host: no relay by construction
ports = [int(p) for p in os.environ.get("TPU_REDUCTIONS_RELAY_PORTS",
                                        "8082,8083").split(",") if p.strip()]
inconclusive = False
for port in ports:
    try:
        socket.create_connection(("127.0.0.1", port), timeout=2).close()
        sys.exit(0)
    except (ConnectionRefusedError, ConnectionResetError, TimeoutError):
        continue
    except OSError:
        inconclusive = True
sys.exit(0 if inconclusive else 3)'
}

step() {  # step <name> <budget_seconds> <artifact...> -- <cmd...>
    local name=$1 budget=$2; shift 2
    local arts=()
    while [ $# -gt 0 ] && [ "$1" != "--" ]; do arts+=("$1"); shift; done
    if [ $# -eq 0 ]; then
        echo "step '$name': missing -- sentinel" >&2
        return 1
    fi
    shift
    echo "=== chip_session: $name (budget ${budget}s) ==="
    if [ "$SESSION_RAN" = 0 ]; then
        # the last commit touching the flagship example BEFORE the
        # session's first step: the exit trap regenerates the report
        # when this moves (step 11 commits its own artifacts, so
        # worktree dirtiness alone would miss them). Recorded here —
        # in the cwd the steps commit from — not at source time.
        TPU_RUN_HEAD=$(git log -1 --format=%H -- examples/tpu_run \
                       2>/dev/null || echo none)
    fi
    SESSION_RAN=1
    if ! relay_ok; then
        # a step that exited 1 for its own reasons (e.g. bench.py's
        # stale-snapshot outage contract) does not carry the rc=3
        # signal — this probe catches a relay that died between steps
        # regardless of how the previous step reported it
        echo "=== chip_session: ABORT — relay died before step '$name'; remaining steps skipped ==="
        obs_event session.abort reason=relay-dead-between-steps step="$name"
        exit 3
    fi
    obs_event step.start name="$name" budget="$budget"
    local status=ok rc=0
    # Per-step wall-clock budget (round-3 verdict, weak #2): a
    # slow-but-alive stall — a Mosaic lowering pileup, a multi-minute
    # tunnel stall — must not consume the whole window; the next step
    # gets its chance. SIGINT first so python raises KeyboardInterrupt
    # and the drivers' per-row persistence + queue drain run (CLAUDE.md:
    # a SIGKILLed process with in-flight device work can wedge the
    # chip); the 120 s kill-after is the backstop for a process too
    # wedged to honor the interrupt.
    timeout --signal=INT --kill-after=120 "$budget" "$@" || rc=$?
    if [ "$rc" -eq 124 ] || [ "$rc" -eq 137 ]; then
        status=FAILED
        echo "=== chip_session: $name TIMED OUT after ${budget}s (committing any artifacts it DID produce) ==="
    elif [ "$rc" -ne 0 ]; then
        status=FAILED
        echo "=== chip_session: $name FAILED rc=$rc (committing any artifacts it DID produce) ==="
        # a failing step can still have written real data (e.g. the HBM
        # race writes tune_hbm.json with every row FAILED, then exits 1
        # because no Pallas candidate passed — the exact hypothesis the
        # step probes); losing it to a later wedge would defeat the
        # script's commit-between-steps contract
    fi
    obs_event step.end name="$name" rc="$rc" status="$status"
    # the ledger itself is a per-step artifact: commit it with whatever
    # the step produced, so the postmortem record survives a window
    # death exactly like the measurement rows do
    if [ -n "${TPU_REDUCTIONS_LEDGER:-}" ] \
            && [ -e "${TPU_REDUCTIONS_LEDGER}" ]; then
        arts+=("${TPU_REDUCTIONS_LEDGER}")
    fi
    # add per artifact, and commit only the ones that exist: one
    # missing path must block neither the add nor the commit of the
    # artifacts that were produced
    local a
    local have=()
    for a in "${arts[@]}"; do
        if [ ! -e "$a" ]; then
            echo "=== chip_session: $name: no artifact $a ==="
        elif git add -- "$a"; then   # real add failures stay loud
            have+=("$a")
        fi
    done
    if [ ${#have[@]} -gt 0 ] \
            && ! git diff --cached --quiet -- "${have[@]}"; then
        # commit restricted to the produced artifacts: pre-existing
        # staged work must never be swept into an artifact commit
        local msg="On-chip artifacts: $name"
        [ "$status" = FAILED ] && msg="$msg (step FAILED; partial artifacts)"
        git commit -q -m "$msg" -- "${have[@]}"
    else
        echo "=== chip_session: $name produced no new artifact ==="
    fi
    if [ "$rc" -eq 3 ]; then
        # exit code 3 = accelerator unavailable (run_tpu_experiment's
        # device probe / utils/watchdog.py relay death; bench.py's
        # outage contract is exit 1 + stale snapshot, which the
        # per-step relay_ok probe above covers instead): the relay
        # cannot come back in-session (CLAUDE.md), so every later
        # on-chip step could only hang — stop here with the artifacts
        # committed
        echo "=== chip_session: ABORT — accelerator gone (rc=3); remaining steps skipped ==="
        exit 3
    fi
}

# However the session ends — completed, budget-cut, relay abort — it
# leaves a collated WINDOW_SUMMARY.md committed: the post-window
# bookkeeping must not depend on anyone being present when the watcher
# fires (summarize_window.py is pure offline collation; no relay gate
# applies to it).
SESSION_RAN=0   # set by step(): an abort BEFORE any step must not
                # collate a "window summary" out of stale artifacts
TPU_RUN_HEAD="" # recorded by the first step() call (see there)
summarize_on_exit() {
    [ "$SESSION_RAN" = 1 ] || return 0
    # Offline evidence collation FIRST (pure disk work — safe after the
    # relay dies, which is exactly when this trap usually runs): spot
    # rows measured at the flagship contract seed the grid cache, and
    # if anything under examples/tpu_run changed this window (seeded
    # cells, curve cells from a budget-cut flagship step whose own
    # report regeneration never ran — step 11 COMMITS those cells
    # itself, so the dirty-worktree test alone would miss them; the
    # recorded pre-session commit hash catches the committed case) the
    # report is re-collated from disk and committed. Both calls carry
    # the same budget discipline as the steps: the trap usually runs
    # with the relay dead, and an import stall here would pin the
    # watcher instead of re-arming it.
    timeout 300 python -m tpu_reductions.bench.seed_cache \
        double_spot.json int_op_spot_k6.json BENCH_doubles.json \
        --grid-dir examples/tpu_run/single_chip || true
    # Flight-recorder collation (pure disk work, same as the rest of
    # this trap): the machine summary lands next to the flagship
    # evidence so regen appends the window-utilization table to
    # report.md (bench/regen.py), and the dirty dir triggers the regen
    # below even when nothing else changed this window.
    if [ -n "${TPU_REDUCTIONS_LEDGER:-}" ] \
            && [ -s "${TPU_REDUCTIONS_LEDGER}" ]; then
        timeout 120 python -m tpu_reductions.obs.timeline "$TPU_REDUCTIONS_LEDGER" --json examples/tpu_run/obs_timeline.json --quiet \
            || true
    fi
    if [ -n "$(git status --porcelain -- examples/tpu_run)" ] \
            || [ "$(git log -1 --format=%H -- examples/tpu_run)" \
                 != "$TPU_RUN_HEAD" ]; then
        timeout 600 python -m tpu_reductions.bench.regen \
            examples/tpu_run || true
        git add -- examples/tpu_run \
            && git commit -q -m "Window evidence collated into examples/tpu_run (offline regen)" \
                -- examples/tpu_run || true
        # our own commit moved the head: re-record it so a re-entrant
        # trap (or a later manual call) doesn't re-collate a no-op
        TPU_RUN_HEAD=$(git log -1 --format=%H -- examples/tpu_run \
                       2>/dev/null || echo none)
    fi
    python scripts/summarize_window.py . > WINDOW_SUMMARY.md 2>/dev/null \
        || true
    # the per-window utilization table is COMPUTED from the ledger
    # (obs/timeline.py --summary-md), never hand-written — appended so
    # the summary commit below carries it
    if [ -n "${TPU_REDUCTIONS_LEDGER:-}" ] \
            && [ -s "${TPU_REDUCTIONS_LEDGER}" ]; then
        echo >> WINDOW_SUMMARY.md
        timeout 120 python -m tpu_reductions.obs.timeline "$TPU_REDUCTIONS_LEDGER" --summary-md >> WINDOW_SUMMARY.md \
            || true
    fi
    if [ -s WINDOW_SUMMARY.md ] && git add -- WINDOW_SUMMARY.md \
            && ! git diff --cached --quiet -- WINDOW_SUMMARY.md; then
        git commit -q -m "Window summary (auto-collated at session exit)" \
            -- WINDOW_SUMMARY.md || true
    fi
}

# Sourceable-lib mode: `CHIP_SESSION_LIB=1 source scripts/chip_session.sh`
# stops here with relay_ok/step/summarize_on_exit defined — the
# rehearsal tests (tests/test_chip_session.py) drive the step machinery
# against toy commands in a temp repo, so a bash bug is found off-chip,
# not in a live window.
if [ "${CHIP_SESSION_LIB:-0}" = 1 ]; then
    return 0 2>/dev/null || exit 0
fi

trap summarize_on_exit EXIT

# Flight recorder armed for the whole session (docs/OBSERVABILITY.md):
# every step's entry point inherits the ledger path and appends typed
# events; step() commits the ledger alongside each step's artifacts.
# An explicit env wins (the chaos harness points it at a tmp file).
: "${TPU_REDUCTIONS_LEDGER:=obs_ledger.jsonl}"
export TPU_REDUCTIONS_LEDGER
obs_event session.start prog=chip_session

if ! relay_ok; then
    echo "=== chip_session: relay is dead before the session started; nothing on-chip can run — aborting (rc=3) ==="
    obs_event session.abort reason=relay-dead-at-start
    exit 3
fi

# pipefail INSIDE each bash -c: the child shell does not inherit the
# outer setting, and without it a crashed python is masked by tee/tail
#
# Round-5 ordering = round-4 ordering with a step 0 in front (the
# round-4 verdict's do-this #3: first persisted row below the observed
# ~6-minute flap length). Every step carries a wall-clock budget sized
# so steps 0-3 land inside ~12 minutes even if each exhausts it:
#   0. first row (300 s): one init, crowned candidate, reduced reps;
#      int row + partial snapshot target < 90 s, then the f64
#      scoreboard at the flagship contract
#   1. fresh BENCH row (240 s)
#   2. DOUBLE scoreboard (300 s — THE gap: beat 92.77 GB/s on-chip)
#   3. calibration ladder (240 s; trust gate for everything after)
#   4. lowering smoke (420 s): tiny-n compile+run of k9, k10@{2,4,8},
#      big-tile k8, dd pair paths — a systematic Mosaic failure costs
#      seconds here instead of the window's middle (verdict weak #3)
#   5+6. HBM-regime races at 2^26 and the 2^27 weak point
#   7. int op-parity probe (MIN vs SUM vs MAX, same geometry)
#   8. bf16 existence spot (weak #5: the dtype's first on-chip rows)
#   9+10. kernel-9 MXU races, f32 + bf16
#   11. fine tile race (7-rep repeat confirmation)
#   12. flagship experiment (3 h; re-verified int curve + bf16/f64
#       curves + the 2^30 hazard cells last; DOUBLE rows land in the
#       report's flagship table via sweep_all)
# Step 0 (round-4 verdict do-this #3): the minimal path from "relay
# answers" to "verified row on disk" — ONE process, ONE jax init, the
# crowned candidate only at reduced slope reps, persisted + snapshotted
# the moment it verifies, then the f64 scoreboard at the flagship-grid
# contract. FIRSTROW_T0 = the session-start epoch: every firstrow
# stage logs T+x.xs against it and the timeline lands inside
# FIRSTROW.json, so every window (and every rehearsal) commits its own
# time-to-first-artifact measurement. Target: int row < 90 s.
export FIRSTROW_T0
FIRSTROW_T0=$(date +%s.%N)
step "first row" 300 FIRSTROW.json BENCH_snapshot.json BENCH_doubles.json -- \
    python -m tpu_reductions.bench.firstrow

# BENCH_SKIP_PROBE: relay_ok just verified the relay seconds ago; the
# probe subprocess would re-pay a full jax init (~30-40 s of window)
# to learn the same thing. A wedged-but-ports-open tunnel (the rare
# case the probe exists for) is bounded by this step's budget instead.
# BENCH_DOUBLES=0 when step 0 already landed a COMPLETE f64 scoreboard
# THIS SESSION with at least one VERIFIED row (grep + an
# mtime-vs-FIRSTROW_T0 check: a complete scoreboard committed by a
# PREVIOUS window must not suppress this window's fresh rows, and an
# all-FAILED/WAIVED step-0 scoreboard — e.g. a flap mid-dd-compile —
# must not suppress step 1's fresh attempt either; round-5 ADVICE) —
# re-measuring a scoreboard of verified rows written seconds ago would
# spend window minutes on redundant rows.
step "headline bench" 240 BENCH_live.json BENCH_snapshot.json BENCH_doubles.json -- \
    bash -c 'set -o pipefail; d=1; \
             if grep -q "\"complete\": true" BENCH_doubles.json 2>/dev/null \
                && grep -q "\"status\": \"PASSED\"" BENCH_doubles.json 2>/dev/null \
                && [ "$(stat -c %Y BENCH_doubles.json)" -ge "${FIRSTROW_T0%.*}" ]; then d=0; fi; \
             BENCH_SKIP_PROBE=1 BENCH_DOUBLES=$d python bench.py | tee BENCH_live.json'

# all-device f64 (ops/dd_reduce.device_finish_pairs): the DOUBLE
# SUM/MIN/MAX scoreboard — expected near the INT roof fraction instead
# of the transfer-bound 0.9 GB/s round 2 measured through the tunnel
# --chainreps=5 matches sweep.FLAGSHIP_GRID exactly, so these rows
# seed the flagship grid's resume cache at session exit (seed_cache)
# and replace the 0.87-0.90 GB/s legacy DOUBLE rows in the report even
# when the window never reaches the 3 h flagship step
step "double scoreboard" 300 double_spot.json -- \
    python -m tpu_reductions.bench.spot --type=double \
        --methods=SUM,MIN,MAX --n=16777216 --iterations=256 \
        --chainreps=5 --out=double_spot.json

# --out persists per rung (partial until the deciding HBM rung lands):
# a budget cut or relay death mid-ladder keeps the VMEM rung
step "calibration ladder" 240 calibration_live.json -- \
    python -m tpu_reductions.utils.calibrate --ladder \
        --chainspan 256 --reps 7 --out=calibration_live.json

# every never-lowered kernel surface compiles+runs once at tiny n
# BEFORE the races that depend on it; the manifest (committed even on
# failure) tells the session log which race rows are live
step "lowering smoke" 420 smoke.json -- \
    python -m tpu_reductions.bench.smoke --out=smoke.json

# does any Pallas geometry close the 5-8% gap to XLA in the HBM regime?
# kernel 10 races its DMA pipeline depth — the knob it exists for
step "hbm regime race 2^26" 420 tune_hbm.json -- \
    python -m tpu_reductions.bench.autotune --method=SUM --type=int \
        --n=67108864 --grid=hbm --comparator --out=tune_hbm.json

# 2^27 was round 2's weakest HBM point (621 vs 779 GB/s)
step "hbm regime race 2^27" 420 tune_hbm27.json -- \
    python -m tpu_reductions.bench.autotune --method=SUM --type=int \
        --n=134217728 --grid=hbm --comparator --out=tune_hbm27.json

# MIN trailed SUM by 23% in round 2 (5002.6 vs 6497.2 GB/s) with no
# recorded cause: measure all three ops at the two winning geometries
# rc accumulates across the two probes: a crash of the first must not
# be masked by a clean second (the same masking the pipefail note above
# guards against, at the command level)
step "int op parity probe" 420 \
        int_op_spot_k7.json int_op_spot_k6.json int_op_spot_xla.json -- \
    bash -c 'rc=0; \
             python -m tpu_reductions.bench.spot --type=int \
                 --methods=SUM,MIN,MAX --n=16777216 --kernel=7 \
                 --threads=384 --iterations=256 --chainreps=5 \
                 --out=int_op_spot_k7.json || rc=$?; \
             python -m tpu_reductions.bench.spot --type=int \
                 --methods=SUM,MIN,MAX --n=16777216 --kernel=6 \
                 --threads=512 --iterations=256 --chainreps=5 \
                 --out=int_op_spot_k6.json || rc=$?; \
             python -m tpu_reductions.bench.spot --type=int \
                 --methods=SUM,MIN,MAX --n=16777216 --backend=xla \
                 --iterations=256 --chainreps=5 \
                 --out=int_op_spot_xla.json || rc=$?; \
             exit $rc'

# bf16's FIRST on-chip rows (round-3 weak #5: an advertised dtype with
# zero hardware evidence): one cheap fixed-geometry scoreboard well
# before the k9/flagship steps that would otherwise carry it ~70 min
# into a window. 2 B/element stream, f32 accumulator — the "~2x int32
# elements/s" claim gets its measurement here.
step "bf16 existence spot" 180 bf16_spot.json -- \
    python -m tpu_reductions.bench.spot --type=bfloat16 \
        --methods=SUM,MIN,MAX --n=16777216 --iterations=256 \
        --chainreps=5 --out=bf16_spot.json

# kernel 9 (MXU) has never lowered on-chip; rank it against the VPU
# winners in both regimes (2^24 VMEM-resident, 2^26 HBM-bound)
step "mxu race f32" 420 tune_mxu_f32.json tune_mxu_f32_hbm.json -- \
    bash -c 'rc=0; \
             python -m tpu_reductions.bench.autotune --method=SUM \
                 --type=float --n=16777216 --iterations=256 --grid=mxu \
                 --comparator --out=tune_mxu_f32.json || rc=$?; \
             python -m tpu_reductions.bench.autotune --method=SUM \
                 --type=float --n=67108864 --grid=mxu \
                 --comparator --out=tune_mxu_f32_hbm.json || rc=$?; \
             exit $rc'

step "mxu race bf16" 300 tune_mxu_bf16.json -- \
    python -m tpu_reductions.bench.autotune --method=SUM --type=bfloat16 \
        --n=16777216 --iterations=256 --grid=mxu --comparator \
        --out=tune_mxu_bf16.json

# 5+ slope reps so the round-2 single-rep 22.7 TB/s k7/384 claim gets a
# quotable repeat-averaged confirmation (or a retraction)
step "fine tile race" 420 tune_fine.json -- \
    python -m tpu_reductions.bench.autotune --method=SUM --type=int \
        --n=16777216 --iterations=256 --chainreps=7 --grid=fine \
        --out=tune_fine.json

# 3 h: the long tail, and the watcher re-arms on abort — a flagship
# that wedges slow-but-alive must not pin the watcher past the round
step "flagship experiment" 10800 examples/tpu_run -- \
    bash scripts/run_tpu_experiment.sh examples/tpu_run

obs_event session.end prog=chip_session
echo "=== chip_session: done ==="
