"""Recover shmoo timing rows from a chip_session log.

The 2026-07-30 live window died mid-experiment: the tunnel relay
process exited while staging the int32 n=2^30 (4 GiB) cell, after the
int32 curve through 2^29 had been timed but BEFORE the batch's
deferred verification phase and shmoo.json write ran. The timed rows
exist only as `Reduction, Throughput = ...` lines (the reference's own
row grammar, reduction.cpp:744-745) in the session log.

This tool re-materializes those rows into the shmoo.json schema with
explicit provenance: status=RECOVERED (never PASSED — their oracle
check did not run; the driver verifies after timing in batch mode) and
verified=false. Downstream plot/roofline stages consume gbps/n/dtype
only and are status-agnostic (roofline.summarize flags unverified rows
in its report lines); the report's comparison tables read only
single_chip/raw_output, so recovered rows can never masquerade as
verified grid results.

The `threads` field is taken from each row's own `Workgroup = %u`
column (the grammar carries it), never from a flag. A log holding more
than one shmoo curve (e.g. the relay died in the SECOND dtype's sweep)
is refused: span lines carry no dtype, so attribution would be a
guess — slice the log to one curve first.

Usage:
    python scripts/recover_shmoo_from_log.py LOG OUT.json \
        --method SUM --dtype int32 --kernel 6
"""

from __future__ import annotations

import argparse
import re
import sys

ROW = re.compile(r"Reduction, Throughput = ([0-9.]+) GB/s, "
                 r"Time = ([0-9.]+) s, Size = (\d+) Elements, "
                 r"NumDevsUsed = \d+, Workgroup = (\d+)")
SPAN = re.compile(r"shmoo n=(\d+): chained span (\d+)")


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("log")
    p.add_argument("out")
    p.add_argument("--method", default="SUM")
    p.add_argument("--dtype", default="int32")
    p.add_argument("--kernel", type=int, default=6)
    p.add_argument("--provenance", default=None,
                   help="free-text provenance note recorded per row")
    ns = p.parse_args(argv)

    text = open(ns.log).read()
    # the shmoo section starts at the first span line; rows before it
    # belong to the bench/tune/grid stages and must not be swept in
    spans = {}
    start = None
    for m in SPAN.finditer(text):
        if start is None:
            start = m.start()
        n = int(m.group(1))
        if n in spans:
            print(f"log holds more than one shmoo curve (span line for "
                  f"n={n} repeats) and span lines carry no dtype — "
                  "slice the log to a single curve before recovering",
                  file=sys.stderr)
            return 1
        spans[n] = int(m.group(2))
    if start is None:
        print("no shmoo span lines found", file=sys.stderr)
        return 1

    # The shmoo batch emits its rows contiguously in ascending-n
    # submission order; any row that breaks that pattern (an n with no
    # span, a repeat, or a descent) marks the end of the shmoo section
    # — later stages in the same log print the identical row grammar,
    # and adopting one as a lost cell's timing would be silently wrong
    # provenance. Stop there instead of scanning to end-of-log.
    bytes_per_el = {"bfloat16": 2, "float16": 2, "int32": 4,
                    "float32": 4, "float64": 8, "int64": 8}[ns.dtype]
    rows = []
    last_n = -1
    for m in ROW.finditer(text, start):
        gbps = float(m.group(1))
        n, workgroup = int(m.group(3)), int(m.group(4))
        if n not in spans or n <= last_n:
            break  # first non-shmoo row ends the section
        last_n = n
        # the log's Time column is rounded to 5 decimals (0.00000 for
        # every small-N cell) — recompute the per-iteration time from
        # the full-precision gbps so the row stays self-consistent
        # (gbps = n*bytes / avg_s / 1e9, the driver's own relation)
        avg_s = (n * bytes_per_el / gbps / 1e9) if gbps > 0 else None
        rows.append({
            "method": ns.method, "dtype": ns.dtype, "n": n,
            "backend": "pallas", "kernel": ns.kernel, "gbps": gbps,
            "avg_s": avg_s, "iterations": spans[n],
            "status": "RECOVERED", "device_result": None,
            "oracle_result": None, "abs_diff": None,
            "waived_reason": None, "timing": "chained", "repeat": 0,
            "threads": workgroup, "chain_reps": 5,
            "verified": False,
            "provenance": ns.provenance or
                "timing recovered from chip_session log; relay died "
                "before the batch verify phase ran",
        })
    if not rows:
        print("span lines found but zero throughput rows matched — "
              "nothing recovered; refusing to write an empty curve",
              file=sys.stderr)
        return 1
    missing = sorted(set(spans) - {r["n"] for r in rows})
    from tpu_reductions.utils.jsonio import atomic_json_dump
    atomic_json_dump(ns.out, rows)
    print(f"recovered {len(rows)} rows -> {ns.out}; "
          f"unmeasured cells: {missing}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
