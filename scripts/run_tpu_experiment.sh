#!/usr/bin/env bash
# Flagship on-chip experiment -> examples/tpu_run (VERDICT r1 items 1-3):
# calibration at honest scale, the tuned single-chip grid at n=2^24, and
# the full bandwidth-vs-N curve to 2^30 (BASELINE config #5; the
# reference's dead shmoo swept to 32M, reduction.cpp:581-657), with
# plots and the generated report — the TPU twin of examples/cpu_demo.
#
# Usage: scripts/run_tpu_experiment.sh [OUT_DIR=examples/tpu_run]
# Resumable: interrupted sweeps reuse verified cached cells (sweep_all)
# on the next invocation.
set -euo pipefail

OUT=${1:-examples/tpu_run}
cd "$(dirname "$0")/.."

# A wedged axon tunnel hangs jax device discovery in-process (CLAUDE.md);
# probe in a killable subprocess first, like bench.py does, instead of
# hanging the whole experiment with no diagnostic. (DRYRUN=1 runs on the
# CPU backend and never touches the tunnel — no probe needed.)
if [ "${DRYRUN:-0}" != "1" ]; then
python - <<'PY'
import sys

sys.path.insert(0, ".")
from bench import _device_probe

outage = _device_probe()
if outage is not None:
    print(f"accelerator unavailable: {outage}", file=sys.stderr)
    sys.exit(3)
PY
fi

python - "$OUT" <<'PY'
import json
import sys
from pathlib import Path

out = Path(sys.argv[1])
out.mkdir(parents=True, exist_ok=True)

import os

import jax

# DRYRUN=1: rehearse the whole flow on the CPU backend with tiny sizes
# (smoke coverage for the one-shot on-chip run; artifacts land in OUT
# but carry CPU numbers — do not commit them as TPU data)
dryrun = os.environ.get("DRYRUN") == "1"
if dryrun:
    jax.config.update("jax_platforms", "cpu")
else:
    assert jax.default_backend() == "tpu", (
        "this is the on-chip experiment; run scripts/run_experiment.sh "
        "out/ --platform cpu for the host pipeline (or DRYRUN=1 to "
        "rehearse this script on CPU)")
    # both round-2 windows ended hung on a dead relay mid-batch; the
    # watchdog exits promptly instead (per-curve persistence below
    # bounds the loss to one curve)
    from tpu_reductions.utils.watchdog import maybe_arm_for_tpu
    maybe_arm_for_tpu()

from tpu_reductions.bench.plot import plot_vs_n
from tpu_reductions.bench.report import generate_report
from tpu_reductions.bench.sweep import run_shmoo, sweep_all
from tpu_reductions.config import ReduceConfig
from tpu_reductions.utils.calibrate import calibrate
from tpu_reductions.utils.logging import BenchLogger

log = BenchLogger(None, None)

# 1) calibration at HONEST scale: >= 2^26 f32 so the working set exceeds
# VMEM and the real per-iteration time clears the dispatch-ack floor
# (docs/TIMING.md "Round-2 on-chip calibration findings")
cal_file = out / "calibration.json"
cal_n = 1 << (18 if dryrun else 26)
cal = None
if cal_file.exists():
    prior = json.loads(cal_file.read_text())
    # resume only a calibration of THIS platform at THIS scale — a CPU
    # dryrun's calibration.json must never stand in for the chip's (its
    # honest-sync verdict is the OPPOSITE of the tunnel's)
    if (prior.get("platform") == jax.default_backend()
            and prior.get("n") == cal_n):
        cal = prior
        log.log("calibration: resumed from file")
if cal is None:
    cal = calibrate(n=cal_n, iters=8, reps=7 if not dryrun else 3,
                    chain_span=64 if not dryrun else 8).to_dict()
    cal_file.write_text(json.dumps(cal, indent=1))
# honest_gbps serializes as null when calibration is indeterminate
# (noise-swamped slope) — format it conditionally, and refuse to bench
# against an indeterminate calibration on the real chip: the whole
# point of step 1 is a trustworthy timing verdict
gbps = cal.get("honest_gbps")
log.log(f"calibration: block_awaits_execution="
        f"{cal['block_awaits_execution']} "
        f"honest_gbps={'n/a' if gbps is None else format(gbps, '.1f')}")
if cal.get("indeterminate") and not dryrun:
    sys.exit("calibration indeterminate (noise-swamped slope) — "
             "delete the out dir's calibration.json and retry in a "
             "quieter window; refusing to bench against it")

# 2) the tuned flagship grid at the reference's n=2^24
# (reduction.cpp:665): kernel 6 threads=512 won the committed tile race
# (tune_r02.json) at 6238 GB/s
# The grid contract lives in ONE place (sweep.FLAGSHIP_GRID — float64
# FIRST: the report's DOUBLE rows are the committed story's weakest
# numbers, VERDICT r3 item 1, and must land before a flapping-relay
# window cuts the grid); averaging/plot constants are shared with the
# offline regenerator (bench/regen.py) so a post-window regen can
# never drift from what this live run renders.
from tpu_reductions.bench.regen import collect_averages
from tpu_reductions.bench.sweep import FLAGSHIP_GRID

grid = dict(FLAGSHIP_GRID)
if dryrun:
    grid.update(n=1 << 18, repeats=2)
sweep_all(**grid, out_dir=str(out / "single_chip"), logger=log)
# averages from the on-disk cells sweep_all just wrote/resumed — the
# same collection regen.py runs offline (dryrun cells differ from the
# contract n, so the dryrun collects at its own geometry)
dry_grid = grid if dryrun else None
sc = collect_averages(out / "single_chip", grid=dry_grid,
                      log=lambda m: log.log(m))
(out / "single_chip" / "averages.json").write_text(
    json.dumps({f"{d} {m}": g for (d, m), g in sorted(sc.items())},
               indent=1))

# 3) bandwidth-vs-N: int32 SUM, bf16 SUM (2 B/element — the bandwidth
# win curve), f64 SUM to 2^28 (the dd planes double the footprint;
# 2^28 keeps headroom in 16 GiB HBM). Spans auto-size per payload
# (ops/chain.auto_chain_span).
#
# Hard-won ordering (examples/tpu_run/RECOVERY.md): BOTH round-2
# relay deaths happened while staging a 4 GiB (2^30) buffer, and rows
# held only in memory died with the process. So (a) curves that have
# never been measured run FIRST, (b) shmoo.json and the plots are
# rewritten after EVERY curve so a mid-run death loses at most one
# curve, and (c) the relay-hazardous 2^30 cells run LAST, one cell
# per process-visible step, gated by HAZARD_CELLS=0 when a window
# wants to skip them entirely.
hazard_pow = 30
hazard = os.environ.get("HAZARD_CELLS", "1") == "1" and not dryrun
# bf16 runs its full curve to 2^30 inline: at 2 B/element that cell is
# a 2 GiB transfer, the message class that always survived the relay
# (and staging now chunks to 256 MiB regardless) — only the 4 GiB
# int32 cell is the demonstrated killer and waits for the hazard tail
curves = (("bfloat16", 14 if dryrun else hazard_pow),
          ("float64", 13 if dryrun else 28),
          ("int32", 14 if dryrun else hazard_pow - 1))

# Merge-not-erase persistence + cross-window resume: shmoo.json may
# already hold rows (fresh-PASSED from an earlier window of THIS
# round, or round-2 RECOVERED rows). A fresh row replaces its
# (dtype, n) predecessor; rows not yet re-measured stay visible (a
# half-window must never ERASE the committed curve). Fresh PASSED
# rows at the same geometry/discipline are skipped on resume;
# RECOVERED rows never block re-measurement (re-verifying them is the
# point). Every cell persists the merge the moment it lands —
# run_shmoo runs chained cells one at a time, so a mid-curve relay
# death keeps every completed cell (round 2 lost a whole in-memory
# curve this way).
from tpu_reductions.utils.jsonio import atomic_json_dump

shmoo_file = out / "shmoo.json"
prior = {}
if shmoo_file.exists():
    try:
        for r in json.loads(shmoo_file.read_text()):
            prior[(r["dtype"], r["n"])] = r
    except (ValueError, KeyError, TypeError):
        prior = {}
fresh: dict = {}


def merged_rows():
    return [row for key, row in
            sorted({**prior, **fresh}.items(),
                   key=lambda kv: (kv[0][0], kv[0][1]))]


def persist_json(_cfg=None, res=None):
    if res is not None:
        if not res.passed:
            return
        fresh[(res.dtype, res.n)] = res.to_dict()
    atomic_json_dump(shmoo_file, merged_rows())


def make_plots():
    from tpu_reductions.bench.regen import PLOT_HLINES, PLOT_TITLE
    return plot_vs_n(merged_rows(), out / "bandwidth_vs_n",
                     title=PLOT_TITLE, hlines=PLOT_HLINES)


def shmoo_cfg(dtype):
    return ReduceConfig(method="SUM", dtype=dtype, n=1 << 20,
                        backend="pallas", kernel=6, threads=512,
                        timing="chained", chain_reps=2 if dryrun else 5,
                        stat="median", iterations=4096, log_file=None)


def done_ns(dtype):
    c = shmoo_cfg(dtype)
    return {n for (dt, n), r in prior.items()
            if dt == c.dtype and r.get("status") == "PASSED"
            and r.get("timing") == "chained"
            and r.get("kernel") == c.kernel
            and r.get("backend") == c.backend}


for dtype, max_pow in curves:
    run_shmoo(shmoo_cfg(dtype), min_pow=10, max_pow=max_pow,
              skip_ns=done_ns(dtype), on_result=persist_json,
              logger=log)
    figures = make_plots()
if hazard and (1 << hazard_pow) not in done_ns("int32"):
    log.log(f"hazard cell: int32 n=2^{hazard_pow} (the 4 GiB cell "
            "that killed the relay in both round-2 windows; running "
            "it last, alone, chunk-staged)")
    run_shmoo(shmoo_cfg("int32"), min_pow=hazard_pow,
              max_pow=hazard_pow, on_result=persist_json, logger=log)
figures = make_plots()
shmoo_rows = merged_rows()

# 4) report: single-chip tables + curves + the calibration note + the
# mechanical roofline analysis (VERDICT r1 item 2: "state the TPU
# roofline and the achieved fraction in the report"). No multi-chip
# rank sweep here — one physical chip; the CPU-mesh collective example
# lives in examples/cpu_demo.
from tpu_reductions.bench.roofline import annotate, summarize

kind = jax.devices()[0].device_kind if not dryrun else "TPU v5 lite"
ann = annotate(shmoo_rows, device_kind=kind)
roof_lines = summarize(ann)
(out / "roofline.json").write_text(json.dumps(ann, indent=1))
paths = generate_report({}, single_chip=sc, figures=figures,
                        out_dir=out, platform=jax.default_backend(),
                        calibration=cal, roofline=roof_lines,
                        annotated_rows=ann)
print("report:", paths["md"], paths["tex"])

# 6) the compiled writeup (writeup.pdf analog; no TeX stack in this
# image, so bench.pdf authors the PDF directly via matplotlib). The
# IN-MEMORY data is passed through so the PDF renders exactly what
# generate_report just rendered — never a disk re-parse (this out_dir's
# raw_output/ holds a recovered session log, not collective rows).
from tpu_reductions.bench.pdf import generate_pdf

pdf_data = {"avgs": {}, "single_chip": sc or None, "calibration": cal,
            "figures": list(figures), "roofline": roof_lines,
            "annotated_rows": ann}
print("writeup:", generate_pdf(out, platform=jax.default_backend(),
                               data=pdf_data))
PY
