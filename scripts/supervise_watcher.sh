#!/usr/bin/env bash
# Watcher supervisor: makes "armed" a process-level invariant instead of
# a best-effort (round-4 verdict, weak #3 / do-this #2).
#
# await_window.sh alone is a single unsupervised process, and that cost
# round 4 its only live window: the watcher had died with the driver
# shell that spawned it, the 03:43Z relay flap was spotted by hand at
# 03:46, and ~4 of ~6 live minutes were lost; separately its 12 h
# horizon expired unattended at 15:41Z with nobody to re-arm it. This
# supervisor closes both gaps:
#   - watcher death (killed, crashed, horizon rc=4) -> respawned within
#     RESPAWN_DELAY_S + CHECK_S (default 1+2 s, well under one 20 s poll
#     interval), with a fresh horizon on every respawn so expiry can
#     never strand the round;
#   - a COMPLETED chip session (rc=0) retires the supervisor — the same
#     only-completion-retires contract await_window.sh already has;
#   - the watch log is committed every COMMIT_EVERY_S (default hourly,
#     path-restricted so concurrent foreground staging is never swept
#     in) so armed-ness is verifiable in git history afterwards, even if
#     nobody is attending when the round ends.
#
# Process-group discipline (round-5 review findings): the watcher is
# spawned as its own process group (set -m), and every kill is a GROUP
# kill — a watcher bash that dies mid-chip-session leaves the session
# subtree (chip_session.sh, tee, python) alive, and respawning a second
# session against the same relay window is the documented machine-wide
# chip-wedge hazard (CLAUDE.md: overlapping in-flight device work).
# Group reaping is INT-first with a grace period so an in-flight python
# raises KeyboardInterrupt and drains its device queue (the same
# discipline as chip_session's per-step timeout), KILL only as backstop.
# A flock single-instance guard makes "armed" a SINGULAR invariant —
# two supervisors would fire two concurrent sessions at the same window.
#
# The supervisor itself is deliberately boring: pure bash + date + git,
# no python, no JAX — nothing in it can hang on a dead relay. Launch it
# DETACHED (setsid, </dev/null) so driver-session teardown — the thing
# that killed round 4's watcher — cannot reach it:
#
#   setsid nohup bash scripts/supervise_watcher.sh \
#       >> round5_watch.log 2>&1 < /dev/null &
#
# Usage: bash scripts/supervise_watcher.sh [poll_seconds=20] [arm_hours=13]
#   Env: CHIP_LOG       chip-session log name (default chip_session_r05.log)
#        WATCH_LOG      watcher output + supervisor notes (round5_watch.log)
#        AWAIT_BIN      watcher script (tests substitute a fake)
#        CHECK_S        liveness-check cadence  (default 2 s)
#        RESPAWN_DELAY_S pause before a respawn (default 1 s)
#        COMMIT_EVERY_S log-commit cadence, 0 disables (default 3600)
#        SUP_HORIZON_H  supervisor self-horizon (default 20 h — outlasts
#                       a round; bounded so a forgotten supervisor does
#                       not commit into the next round forever)
set -uo pipefail
# Flight-recorder shell emitter (docs/OBSERVABILITY.md) — resolved
# BEFORE the SUP_ROOT cd so rehearsal repos still find it. Pure bash
# like the rest of this script: nothing here may pay a python/jax
# import, and obs_event is a printf append (scripts/obs_event.sh).
_OBS_LIB="$(cd "$(dirname "$0")" && pwd)/obs_event.sh"
# SUP_ROOT: the rehearsal tests (tests/test_supervisor.py) point this at
# a temp git repo so kill/retire/re-arm behavior is provable off-chip
# without touching the real round log
cd "${SUP_ROOT:-$(dirname "$0")/..}"
# shellcheck disable=SC1090
source "$_OBS_LIB" 2>/dev/null || obs_event() { :; }

POLL=${1:-20}
ARM_HOURS=${2:-13}
current_round() {
    # highest ROUND<N>.md names the round in flight (same derivation as
    # await_window.sh — the fix for the per-round hardcoded log pins)
    local n=0 f k
    for f in ROUND[0-9]*.md; do
        [ -e "$f" ] || continue
        k=${f#ROUND}; k=${k%.md}
        case "$k" in *[!0-9]*) continue ;; esac
        [ "$k" -gt "$n" ] && n=$k
    done
    printf '%d' "$n"
}
ROUND_N=$(current_round)
CHIP_LOG=${CHIP_LOG:-$(printf 'chip_session_r%02d.log' "$ROUND_N")}
WATCH_LOG=${WATCH_LOG:-round${ROUND_N}_watch.log}
AWAIT_BIN=${AWAIT_BIN:-scripts/await_window.sh}
CHECK_S=${CHECK_S:-2}
RESPAWN_DELAY_S=${RESPAWN_DELAY_S:-1}
COMMIT_EVERY_S=${COMMIT_EVERY_S:-3600}
SUP_HORIZON_H=${SUP_HORIZON_H:-20}
# INT-to-KILL grace for group reaps: generous, because the only process
# that ever needs it is a python draining its device queue after
# KeyboardInterrupt (idle watchers exit the instant INT lands, so the
# grace costs nothing in the common case)
GRACE_S=${GRACE_S:-60}
# same untunneled-host marker await_window.sh keys off; overridable so
# the rehearsal tests can run on any host (the chaos harness sets
# TPU_REDUCTIONS_RELAY_MARKER for the whole stack at once)
RELAY_MARKER=${RELAY_MARKER:-${TPU_REDUCTIONS_RELAY_MARKER:-/root/.relay.py}}
# preflight health file (utils/preflight.py; same seam await_window.sh
# reads): a fresh STALLED/WEDGED verdict means sessions can only hang —
# respawning a watcher against it burns window minutes on back-to-back
# hangs (exit 4), so respawn DEFERS until the verdict clears
HEALTH_FILE=${TPU_REDUCTIONS_HEALTH_FILE:-.chip_health.json}
HEALTH_TTL_S=${TPU_REDUCTIONS_HEALTH_TTL_S:-300}

if [ ! -e "$RELAY_MARKER" ]; then
    echo "supervisor: untunneled host (no $RELAY_MARKER); nothing to supervise" >&2
    exit 0
fi

# single-instance guard: a second supervisor must refuse to arm, not
# race this one to fire duplicate chip sessions at the same window.
# -w 5, not -n: a SIGKILLed predecessor can leave the lock briefly held
# by an orphaned foreground child (its in-flight `sleep` inherits fd 9
# for up to CHECK_S seconds) — a replacement launched in that window
# must wait the transient out, not be refused as a "double-arm"
exec 9>"$WATCH_LOG.sup.lock"
if ! flock -w "${FLOCK_WAIT_S:-5}" 9; then
    echo "supervisor: another supervisor already holds $WATCH_LOG.sup.lock; refusing to double-arm" >&2
    exit 1
fi

# job control: each background watcher becomes its OWN process group,
# so group kills can reap its whole subtree without touching us
set -m

note() {
    echo "supervisor: $* [$(date -u +%FT%TZ)]" >> "$WATCH_LOG"
}

commit_file() {  # commit_file <path> <message>
    # path-restricted add+commit: a foreground build mid-staging must
    # never have its index swept into a watcher-log commit; an
    # index.lock collision just skips this beat (the next one catches up)
    [ -s "$1" ] || return 0
    git add -- "$1" 2>/dev/null || return 0
    git diff --cached --quiet -- "$1" && return 0
    git commit -q -m "$2" -- "$1" 2>/dev/null || true
}

commit_log() {
    [ "$COMMIT_EVERY_S" -gt 0 ] || return 0
    commit_file "$WATCH_LOG" \
        "Round map: watcher log through $(date -u +%H:%MZ)"
}

child=
armed_at=0
PIDFILE="$WATCH_LOG.watcher.pid"
spawn() {
    # 9>&-: the child must NOT inherit the single-instance lock fd — a
    # SIGKILLed supervisor would otherwise leave the lock held by the
    # orphan subtree, refusing every replacement supervisor while zero
    # supervision actually exists (review finding)
    CHIP_LOG="$CHIP_LOG" bash "$AWAIT_BIN" "$POLL" "$ARM_HOURS" \
        >> "$WATCH_LOG" 2>&1 < /dev/null 9>&- &
    child=$!
    armed_at=$(date +%s)
    # recorded so a REPLACEMENT supervisor (after this one is
    # SIGKILLed, skipping the EXIT trap) can find and reap the orphaned
    # watcher instead of arming a second one next to it
    echo "$child" > "$PIDFILE" 2>/dev/null || true
    note "watcher armed (pid $child, poll ${POLL}s, horizon ${ARM_HOURS}h)"
    obs_event supervisor.spawn watcher_pid="$child" poll_s="$POLL" \
        horizon_h="$ARM_HOURS"
}

reap_predecessor() {
    # A SIGKILLed/OOM-killed predecessor leaves its watcher (and any
    # session subtree) orphaned and polling; arming next to it would
    # let the next relay flap fire TWO chip sessions at one tunnel —
    # the machine-wide wedge hazard. The pid is verified against the
    # watcher's cmdline before the group kill so pid reuse can never
    # target an innocent process group.
    [ -f "$PIDFILE" ] || return 0
    local old
    old=$(cat "$PIDFILE" 2>/dev/null) || return 0
    case "$old" in ''|*[!0-9]*) return 0 ;; esac
    local reap=0
    if [ -r "/proc/$old/cmdline" ] \
            && tr '\0' ' ' < "/proc/$old/cmdline" 2>/dev/null \
               | grep -qF "$(basename "$AWAIT_BIN")"; then
        note "reaping orphaned predecessor watcher (pid $old) before arming"
        reap=1
    elif kill -0 -- "-$old" 2>/dev/null && _session_work_in "$old"; then
        # the watcher pid itself died, but session work (the session
        # script OR a still-draining benchmark python) survives in the
        # group (a pgid cannot be reused while members remain, so this
        # is safe from pid reuse): reap it, or the new watcher would
        # fire a SECOND session next to it
        note "predecessor watcher (pid $old) is dead but its session subtree survives; reaping group"
        reap=1
    fi
    if [ "$reap" = 1 ] && ! reap_group "$old"; then
        # the predecessor's session refuses to drain: arming next to it
        # would fire a second session at the same tunnel — BLOCK until
        # the group empties (an unarmed watcher is recoverable; two
        # sessions may wedge the machine)
        note "predecessor session group refuses to drain; waiting before arming"
        wait_for_group_drain "$old"
        note "predecessor session group drained"
    fi
    rm -f "$PIDFILE"
}

session_in_flight() {
    # a live chip session inside the watcher's process group: the one
    # state where teardown is genuinely hazardous (INT/KILL mid-device-
    # queue is the documented machine-wide wedge) — used to DEFER the
    # self-horizon disarm until the session ends
    [ -n "$child" ] || return 1
    pgrep -g "$child" -f chip_session.sh > /dev/null 2>&1
}

reap_group() {
    # Kill the watcher's ENTIRE process group — the watcher bash dying
    # does not take its chip-session subtree with it (a bash's
    # foreground child survives its parent's death), and an orphaned
    # session sharing the tunnel with a freshly-fired one is the
    # machine-wide wedge hazard. INT first so an in-flight python
    # drains its device queue; KILL after GRACE_S as backstop — UNLESS
    # the survivors include session/benchmark work, which must never be
    # SIGKILLed mid-device-queue (CLAUDE.md wedge): those get an
    # extended no-KILL drain wait instead, and if they outlive even
    # that, we return 1 so the caller can refuse to arm a second
    # session next to them.
    local pg=$1
    [ -n "$pg" ] || return 0
    kill -INT -- "-$pg" 2>/dev/null || return 0   # group already gone
    local i=0
    while [ "$i" -lt "$GRACE_S" ]; do
        kill -0 -- "-$pg" 2>/dev/null || return 0
        sleep 1 9>&-
        i=$(( i + 1 ))
    done
    if _session_work_in "$pg"; then
        note "group $pg still has session work after ${GRACE_S}s; extended no-KILL drain wait"
        while [ "$i" -lt "${TEARDOWN_WAIT_S:-600}" ] \
                && _session_work_in "$pg"; do
            sleep 1 9>&-
            i=$(( i + 1 ))
        done
        if _session_work_in "$pg"; then
            note "group $pg still draining after ${TEARDOWN_WAIT_S:-600}s; leaving it (no KILL — wedge hazard)"
            return 1
        fi
        # session work drained; fall through to reap any non-session
        # stragglers (e.g. a blocked tee) the INT didn't take
    fi
    # redlint: disable=RED008 -- last resort AFTER the INT-first reap and the extended no-KILL drain wait above; only non-session stragglers can still be in this group
    kill -KILL -- "-$pg" 2>/dev/null || true
}

_session_work_in() {
    # session/benchmark processes in group $1 — the ones that must
    # never be SIGKILLed mid-device-queue; keyed on cmdlines, not
    # whole-group liveness, so a non-session straggler can neither
    # block the KILL backstop nor strand the respawn defer loop
    pgrep -g "$1" -f 'chip_session\.sh|tpu_reductions|bench\.py' \
        > /dev/null 2>&1
}

wait_for_group_drain() {
    # block until group $1 is empty, keeping the hourly log-commit
    # cadence alive (the header promises armed-ness is verifiable in
    # git history even while a drain defers everything else)
    local pg=$1 now
    while kill -0 -- "-$pg" 2>/dev/null; do
        sleep "$CHECK_S" 9>&-
        now=$(date +%s)
        if [ "$COMMIT_EVERY_S" -gt 0 ] \
                && [ $(( now - last_commit )) -ge "$COMMIT_EVERY_S" ]; then
            commit_log
            last_commit=$now
        fi
    done
}

health_verdict() {
    # fresh verdict from the preflight health file; '' when absent,
    # stale (mtime past TTL) or unparseable — same derivation as
    # await_window.sh so both layers read one source of truth
    [ -f "$HEALTH_FILE" ] || return 0
    local mt now
    mt=$(stat -c %Y "$HEALTH_FILE" 2>/dev/null) || return 0
    now=$(date +%s)
    [ $(( now - mt )) -le "$HEALTH_TTL_S" ] || return 0
    sed -n 's/.*"verdict": *"\([A-Z_]*\)".*/\1/p' "$HEALTH_FILE" | head -1
}

wait_health_clear() {
    # defer a respawn while the chip is known-wedged (exit-4 territory:
    # hang with live ports), keeping the hourly log-commit cadence
    # alive like wait_for_group_drain does; clears on a fresh LIVE
    # preflight or TTL expiry
    local v now
    v=$(health_verdict)
    case "$v" in STALLED|WEDGED) ;; *) return 0 ;; esac
    note "health verdict $v (hang with live ports); deferring watcher respawn until it clears"
    obs_event supervisor.defer reason=health verdict="$v"
    while v=$(health_verdict); do
        case "$v" in STALLED|WEDGED) ;; *) break ;; esac
        sleep "$CHECK_S" 9>&-
        now=$(date +%s)
        if [ "$COMMIT_EVERY_S" -gt 0 ] \
                && [ $(( now - last_commit )) -ge "$COMMIT_EVERY_S" ]; then
            commit_log
            last_commit=$now
        fi
    done
    note "health verdict cleared; proceeding to respawn"
}

commit_chip_log() {
    # await_window.sh commits the chip log after a session IT saw end;
    # when the supervisor reaps an orphaned session subtree that commit
    # never ran — do it here so the log survives unattended teardown
    # (round 2's curve recovery came from exactly this log)
    commit_file "$CHIP_LOG" \
        "Chip session log (supervisor teardown, $(date -u +%FT%TZ))"
}

retire() {
    # on supervisor exit for any reason, never leave an orphan watcher
    # (or session subtree) — it would be exactly the unsupervised
    # process tree this script exists to eliminate.
    local clean=1
    # group liveness, not watcher-pid liveness: a watcher bash that died
    # seconds ago can leave its session subtree alive in the group, and
    # skipping the reap for it would delete the pidfile the next
    # supervisor needs to find that orphan (review finding)
    if [ -n "$child" ] && kill -0 -- "-$child" 2>/dev/null; then
        # disown first: set -m would otherwise print a job-termination
        # notice into the committed watch log. reap_group handles the
        # in-flight-session case itself (extended INT-only drain wait,
        # never a KILL mid-device-queue — the CLAUDE.md wedge hazard).
        disown "$child" 2>/dev/null || true
        reap_group "$child" || clean=0
    fi
    if [ "$clean" = 1 ]; then
        rm -f "$PIDFILE"
    else
        # a live session group is deliberately left draining: KEEP the
        # pidfile so the next supervisor's reap_predecessor can find it
        # — deleting it would make the orphan undiscoverable and re-
        # create the double-session hazard the pidfile exists to stop
        note "session group left draining; pidfile kept for the next supervisor"
    fi
    commit_chip_log
    commit_log
}
trap retire EXIT

deadline=$(( $(date +%s) + SUP_HORIZON_H * 3600 ))
last_commit=$(date +%s)
rapid_deaths=0
defer_noted=0
note "supervising $AWAIT_BIN (check ${CHECK_S}s, respawn ${RESPAWN_DELAY_S}s, self-horizon ${SUP_HORIZON_H}h)"
reap_predecessor
spawn
while true; do
    if ! kill -0 "$child" 2>/dev/null; then
        rc=0; wait "$child" 2>/dev/null || rc=$?
        if [ "$rc" -eq 0 ] && [ -e "$RELAY_MARKER" ]; then
            note "chip session COMPLETED (watcher rc=0); retiring"
            obs_event supervisor.retire rc=0
            child=
            exit 0
        elif [ "$rc" -eq 0 ]; then
            # await_window also exits 0 on a missing relay marker
            # ("nothing to await") — retiring on that would leave the
            # round silently unarmed while the log claims completion
            note "watcher exited 0 but $RELAY_MARKER is gone (marker removed mid-round?); treating as anomaly, re-arming"
        elif [ "$rc" -eq 4 ]; then
            note "watcher horizon expired (rc=4); re-arming with a fresh horizon"
        else
            note "watcher DIED (rc=$rc); respawning"
        fi
        obs_event supervisor.respawn watcher_rc="$rc"
        # reap any survivors of the dead watcher's group BEFORE arming a
        # successor: a respawned watcher that finds the relay alive —
        # because an orphaned session is still using it — would fire a
        # SECOND concurrent session (review finding; chip-wedge hazard).
        # If session work outlives even the extended drain (reap_group
        # rc=1), BLOCK until the group empties: an unarmed watcher is
        # recoverable, two sessions on one tunnel may wedge the machine.
        if ! reap_group "$child"; then
            note "respawn deferred until the predecessor session group drains"
            wait_for_group_drain "$child"
            note "predecessor session group drained; proceeding to respawn"
        fi
        # wedge gate (ISSUE 3): a fresh STALLED/WEDGED preflight
        # verdict means a respawned watcher would fire sessions that
        # exit 4 (hang with live ports) in a loop — hold the respawn
        # until the health file clears; the deferral lands in the
        # watch log instead of as back-to-back hang exits
        wait_health_clear
        # capped exponential backoff on rapid deaths (a broken AWAIT_BIN
        # exiting instantly must not grind out ~50k armed/DIED log lines
        # over the horizon); a watcher that lived >=30 s resets it
        if [ $(( $(date +%s) - armed_at )) -lt 30 ]; then
            rapid_deaths=$(( rapid_deaths + 1 ))
        else
            rapid_deaths=0
        fi
        backoff=$RESPAWN_DELAY_S
        if [ "$rapid_deaths" -gt 0 ]; then
            backoff=$(( RESPAWN_DELAY_S + (1 << (rapid_deaths < 9 ? rapid_deaths : 9)) ))
            [ "$backoff" -gt 300 ] && backoff=300
            note "watcher died ${rapid_deaths}x rapidly; backing off ${backoff}s"
        fi
        sleep "$backoff" 9>&-
        spawn
    fi
    now=$(date +%s)
    if [ "$COMMIT_EVERY_S" -gt 0 ] \
            && [ $(( now - last_commit )) -ge "$COMMIT_EVERY_S" ]; then
        commit_log
        last_commit=$now
    fi
    if [ "$now" -ge "$deadline" ]; then
        if session_in_flight; then
            # disarming now would INT/KILL a python mid-device-queue
            # (the wedge hazard); the session's own per-step budgets +
            # watchdog bound how long this defer can last
            if [ "$defer_noted" -eq 0 ]; then
                note "self-horizon reached but a chip session is in flight; deferring disarm until it ends"
                defer_noted=1
            fi
        else
            note "supervisor self-horizon (${SUP_HORIZON_H}h) reached; disarming"
            exit 4
        fi
    fi
    # 9>&-: a supervisor SIGKILLed mid-sleep orphans this child; it must
    # not carry the single-instance lock into its afterlife
    sleep "$CHECK_S" 9>&-
done
