// Native host oracle for tpu_reductions — the framework's C++ runtime
// component, mirroring the role of the reference's native CPU reference
// reductions (Kahan-compensated sum + linear min/max scans,
// reference cuda/C/src/reduction/reduction.cpp:206-249) and its vendored
// MT19937 + cycle-timer header (mpi/externalfunctions.h). Written from
// scratch: MT19937 comes from the C++ standard library, the timer from
// std::chrono — no vendored numerics.
//
// Built as a plain shared library (see csrc/Makefile) and loaded from
// Python via ctypes (tpu_reductions/ops/oracle.py). All entry points are
// extern "C" with flat pointer+length signatures so the ctypes layer stays
// trivial.

#include <chrono>
#include <cstdint>
#include <limits>
#include <random>
#include <thread>
#include <vector>

extern "C" {

// ---------------------------------------------------------------------------
// Kahan-compensated sums (the float/double oracle; reduction.cpp:214-227
// uses the same compensation so the oracle stays accurate at n = 2^24).
// ---------------------------------------------------------------------------

double oracle_kahan_sum_f32(const float* data, int64_t n) {
  double sum = 0.0, c = 0.0;
  for (int64_t i = 0; i < n; ++i) {
    double y = static_cast<double>(data[i]) - c;
    double t = sum + y;
    c = (t - sum) - y;
    sum = t;
  }
  return sum;
}

double oracle_kahan_sum_f64(const double* data, int64_t n) {
  double sum = 0.0, c = 0.0;
  for (int64_t i = 0; i < n; ++i) {
    double y = data[i] - c;
    double t = sum + y;
    c = (t - sum) - y;
    sum = t;
  }
  return sum;
}

// int32 sum with two's-complement wraparound, matching the device's int32
// accumulator (XLA int32 reduce wraps; so did the reference's int path,
// reduction.cpp:748,776-777). Unsigned arithmetic avoids UB.
int32_t oracle_sum_i32(const int32_t* data, int64_t n) {
  uint32_t acc = 0;
  for (int64_t i = 0; i < n; ++i) acc += static_cast<uint32_t>(data[i]);
  return static_cast<int32_t>(acc);
}

// ---------------------------------------------------------------------------
// Linear min/max scans (reduction.cpp:228-249 analog).
// ---------------------------------------------------------------------------

#define DEFINE_MINMAX(SUFFIX, T)                                     \
  T oracle_min_##SUFFIX(const T* data, int64_t n) {                  \
    T best = data[0];                                                \
    for (int64_t i = 1; i < n; ++i)                                  \
      if (data[i] < best) best = data[i];                            \
    return best;                                                     \
  }                                                                  \
  T oracle_max_##SUFFIX(const T* data, int64_t n) {                  \
    T best = data[0];                                                \
    for (int64_t i = 1; i < n; ++i)                                  \
      if (data[i] > best) best = data[i];                            \
    return best;                                                     \
  }

DEFINE_MINMAX(i32, int32_t)
DEFINE_MINMAX(f32, float)
DEFINE_MINMAX(f64, double)
#undef DEFINE_MINMAX

}  // extern "C" (templates below need C++ linkage)

// ---------------------------------------------------------------------------
// Threaded oracles — native threads put to the one real use they have
// here: large-payload host verification. (The reference vendored a
// pthreads wrapper, cutil multithreading, that the benchmark linked but
// never invoked — SURVEY.md §2.3.)
// ---------------------------------------------------------------------------

template <typename T>
static double kahan_chunk(const T* data, int64_t n) {
  double sum = 0.0, c = 0.0;
  for (int64_t i = 0; i < n; ++i) {
    double y = static_cast<double>(data[i]) - c;
    double t = sum + y;
    c = (t - sum) - y;
    sum = t;
  }
  return sum;
}

template <typename T>
static double kahan_sum_mt(const T* data, int64_t n, int nthreads) {
  if (nthreads < 2 || n < nthreads * 4096) return kahan_chunk(data, n);
  std::vector<double> partial(nthreads, 0.0);
  std::vector<std::thread> threads;
  const int64_t chunk = (n + nthreads - 1) / nthreads;
  for (int t = 0; t < nthreads; ++t) {
    const int64_t lo = t * chunk;
    const int64_t len = std::min<int64_t>(chunk, n - lo);
    if (len <= 0) break;
    threads.emplace_back(
        [&partial, data, lo, len, t] { partial[t] = kahan_chunk(data + lo, len); });
  }
  for (auto& th : threads) th.join();
  // combine the per-thread partials with one more compensated pass
  return kahan_chunk(partial.data(), static_cast<int64_t>(partial.size()));
}

extern "C" {

double oracle_kahan_sum_f32_mt(const float* data, int64_t n, int nthreads) {
  return kahan_sum_mt(data, n, nthreads);
}

double oracle_kahan_sum_f64_mt(const double* data, int64_t n, int nthreads) {
  return kahan_sum_mt(data, n, nthreads);
}

int oracle_hw_threads(void) {
  unsigned hc = std::thread::hardware_concurrency();
  return hc == 0 ? 1 : static_cast<int>(hc);
}

// ---------------------------------------------------------------------------
// MT19937 payload generation (externalfunctions.h analog, via std::mt19937).
// Fills the same masked-byte distributions the drivers use
// (reduction.cpp:698-705): ints in [0,255]; reals byte/RAND_MAX.
// ---------------------------------------------------------------------------

static std::mt19937 make_engine(uint32_t rank, uint32_t seed) {
  // Rank-offset seeding discipline (reduce.c:38-41 analog).
  std::seed_seq seq{0x1571u + rank + seed, 0x2662u, 0x3753u, 0x4844u};
  return std::mt19937(seq);
}

void oracle_fill_i32(int32_t* out, int64_t n, uint32_t rank, uint32_t seed) {
  std::mt19937 eng = make_engine(rank, seed);
  for (int64_t i = 0; i < n; ++i)
    out[i] = static_cast<int32_t>(eng() & 0xFFu);
}

void oracle_fill_f32(float* out, int64_t n, uint32_t rank, uint32_t seed) {
  std::mt19937 eng = make_engine(rank, seed);
  const float inv = 1.0f / 2147483647.0f;  // 1/RAND_MAX
  for (int64_t i = 0; i < n; ++i)
    out[i] = static_cast<float>(eng() & 0xFFu) * inv;
}

void oracle_fill_f64(double* out, int64_t n, uint32_t rank, uint32_t seed) {
  std::mt19937 eng = make_engine(rank, seed);
  const double inv = 1.0 / 2147483647.0;
  for (int64_t i = 0; i < n; ++i)
    out[i] = static_cast<double>(eng() & 0xFFu) * inv;
}

// ---------------------------------------------------------------------------
// Monotonic nanosecond clock (the rdtsc/CLOCK_RATE analog,
// externalfunctions.h:7-43 + constants.h:4 — but a real clock, never a
// hard-coded frequency; SURVEY.md §5 tracing note).
// ---------------------------------------------------------------------------

int64_t oracle_now_ns(void) {
  return std::chrono::duration_cast<std::chrono::nanoseconds>(
             std::chrono::steady_clock::now().time_since_epoch())
      .count();
}

}  // extern "C"
