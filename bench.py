"""Round benchmark: one JSON line for the driver.

Headline metric (BASELINE.json): single-chip reduction bandwidth, int32
SUM at n=2^24 — the reference's flagship CUDA configuration
(reduction.cpp:665: n=1<<24; mpi/CUdata.txt:6: 90.8413 GB/s on the
course's GPU). vs_baseline = our GB/s / 90.8413.

Runs the Pallas kernel path on the real chip via the standard
self-verifying driver (verification included; a FAILED verify zeroes the
metric so a wrong-but-fast kernel can't score).
"""

from __future__ import annotations

import json
import sys

BASELINE_GBPS = 90.8413  # CUDA int SUM, n=2^24 (mpi/CUdata.txt:6)


def main() -> int:
    from tpu_reductions.bench.driver import run_benchmark
    from tpu_reductions.config import ReduceConfig
    from tpu_reductions.utils.logging import BenchLogger

    cfg = ReduceConfig(method="SUM", dtype="int32", n=1 << 24,
                       iterations=50, warmup=2, log_file=None)
    res = run_benchmark(cfg, logger=BenchLogger(None, None,
                                                console=sys.stderr))
    value = res.gbps if res.passed else 0.0
    print(json.dumps({
        "metric": "single-chip int32 SUM reduction bandwidth, n=2^24",
        "value": round(value, 4),
        "unit": "GB/s",
        "vs_baseline": round(value / BASELINE_GBPS, 4),
    }))
    return 0 if res.passed else 1


if __name__ == "__main__":
    sys.exit(main())
