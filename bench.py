"""Round benchmark: one JSON line for the driver.

Headline metric (BASELINE.json): single-chip reduction bandwidth, int32
SUM at n=2^24 — the reference's flagship CUDA configuration
(reduction.cpp:665: n=1<<24; mpi/CUdata.txt:6: 90.8413 GB/s on the
course's GPU). vs_baseline = our GB/s / 90.8413.

Autotunes over a small candidate set — the (kernel, threads) knobs the
reference exposes as --kernel/--threads — and reports the fastest
VERIFIED configuration. Timing is the chained slope mode
(--timing=chained, ops/chain.py): K data-dependent iterations inside one
compiled program, timed to host materialization at two trip counts, per
-iteration time = the slope. This is the only honest mode on this
platform — its tunneled PJRT backend acknowledges dispatches without
awaiting execution, so per-launch synced timing reads a flat ~20-30 us
ack floor regardless of N (utils/calibrate.py measures and flags this).
The per-slope statistic is the median, which shrugs off multi-ms tunnel
stalls; a FAILED verify disqualifies a candidate so a wrong-but-fast
kernel can't score.
"""

from __future__ import annotations

import dataclasses
import json
import subprocess
import sys

BASELINE_GBPS = 90.8413  # CUDA int SUM, n=2^24 (mpi/CUdata.txt:6)

# The tunneled TPU can wedge machine-wide (jax.devices() hangs in every
# process — see CLAUDE.md "hard-won environment facts"); a benchmark that
# hangs at device discovery is worse than one that reports the outage.
DEVICE_PROBE_TIMEOUT_S = 180


def _device_probe(platform: str | None = None) -> str | None:
    """Probe device discovery in a subprocess so a wedged tunnel can't
    hang THIS process; the probe is tiny and drains itself (one scalar
    materialization) before exiting. `platform` forces the backend the
    probe tests to the one main() will actually use (the axon plugin
    ignores JAX_PLATFORMS, so this goes through jax.config). Returns
    None when healthy, else a one-line diagnostic distinguishing a hang
    (wedged tunnel) from an init failure (whose traceback tail is
    surfaced, not swallowed)."""
    force = (f"jax.config.update('jax_platforms', {platform!r}); "
             if platform else "")
    code = ("import jax; " + force
            + "print(len(jax.devices()), flush=True); "
            "import jax.numpy as jnp; "
            "print(int(jnp.asarray(1) + 1))")
    try:
        r = subprocess.run([sys.executable, "-c", code],
                           timeout=DEVICE_PROBE_TIMEOUT_S,
                           capture_output=True, text=True)
    except subprocess.TimeoutExpired:
        return (f"device discovery hung >{DEVICE_PROBE_TIMEOUT_S}s "
                "(wedged tunnel lease?)")
    if r.returncode != 0:
        tail = (r.stderr or "").strip().splitlines()[-3:]
        return ("device init failed (not a hang): "
                + (" | ".join(tail) or f"exit {r.returncode}"))
    return None

# (backend, kernel, threads) candidates: the tops of the committed
# chained-timing tile races run on the real chip (tune_r02.json round-2
# first pass, 16 geometries all PASSED; tune_fine.json 2026-07-30 fine
# pass, 21 geometries, 20 PASSED / 1 WAIVED — every candidate listed
# below PASSED its oracle check in its race). The fine race crowned
# kernel 7 threads=384 (maxblocks=64, the config default) at 22.7 TB/s
# in the VMEM-resident regime, with kernel 6 threads=512 (the first
# pass's 6238 GB/s winner) next. The runners-up and the XLA baseline
# stay in the race so a regression in the leader is caught by a
# verified fallback, not silence.
CANDIDATES = (
    ("pallas", 7, 384),
    ("pallas", 6, 512),
    ("pallas", 7, 256),
    ("xla", 6, 256),
)


# Written by a SUCCESSFUL fresh run (main) and read back by the outage
# fallback — the mid-round "measure early, snapshot immediately"
# discipline as a mechanical side effect instead of a hand-kept file.
SNAPSHOT_BASENAME = "BENCH_snapshot.json"


def _write_snapshot(payload: dict, per_candidate: dict) -> None:
    """Persist a fresh verified measurement next to this file, with the
    capture time and the per-candidate rows as provenance. Atomic
    (temp+rename) and best-effort: snapshot failure must never fail the
    bench run that produced the value."""
    import datetime
    import os

    from tpu_reductions.utils.jsonio import atomic_json_dump
    snap = {**payload,
            "captured": datetime.datetime.now(datetime.timezone.utc)
                        .strftime("%Y-%m-%dT%H:%M:%SZ (fresh bench.py run)"),
            "timing": ("chained slope (ops/chain.py), median, every "
                       "PASSED row verified vs the host oracle"),
            "provenance": {"candidates": per_candidate}}
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        SNAPSHOT_BASENAME)
    try:
        atomic_json_dump(path, snap)
    except OSError as e:
        print(f"# snapshot write failed (non-fatal): {e}",
              file=sys.stderr)


def _snapshot_fallback(outage: str, snap: str | None = None) -> dict:
    """On an accelerator outage, surface the round's committed verified
    measurement (captured and snapshotted mid-round per VERDICT r1 item
    1's 'measure early' discipline) instead of a bare 0.0 — clearly
    labeled as the snapshot, never passed off as a fresh run.
    `snap` overrides the snapshot path (tests). Default resolution
    prefers the freshest mechanical snapshot (SNAPSHOT_BASENAME, written
    by the last successful run) over the hand-kept round-2 one."""
    import os
    if snap is None:
        here = os.path.dirname(os.path.abspath(__file__))
        snap = os.path.join(here, SNAPSHOT_BASENAME)
        if not os.path.exists(snap):
            snap = os.path.join(here, "BENCH_r02_snapshot.json")
    try:
        with open(snap) as f:
            s = json.load(f)
        best = float(s["value"])
        out = {
            "metric": s["metric"],
            "value": best,
            "unit": s["unit"],
            "vs_baseline": round(best / BASELINE_GBPS, 4),
            "stale": True,     # machine-readable outage flag: value is
                               # NOT from a fresh run (exit code is 1 too)
            "source": os.path.basename(snap),
            "note": (f"accelerator unavailable at collection time "
                     f"({outage}); value is the mid-round VERIFIED "
                     f"measurement from {os.path.basename(snap)} "
                     f"(captured {s['captured']}, chained slope, "
                     "oracle-checked) — not a fresh run"),
        }
        if s.get("partial"):
            # the snapshotted race died before its runner-ups ran
            # (flapping relay): the value is verified but only the
            # leading candidate(s) raced — say so, machine-readably
            out["partial"] = True
            out["note"] += (" (partial race: the window died before "
                            "the runner-up candidates ran)")
        return out
    except (OSError, ValueError, KeyError, TypeError):
        return {
            "metric": "single-chip int32 SUM reduction bandwidth, n=2^24",
            "value": 0.0,
            "unit": "GB/s",
            "vs_baseline": 0.0,
            "note": f"accelerator unavailable: {outage}",
        }


def _on_flagship_geometry(n: int) -> bool:
    """Real chip at the headline n: the gate for snapshot writes and
    the opportunistic doubles. Checks the ACTUAL backend (not a flag —
    a CPU-default box must never clobber the snapshot with a host-speed
    number) and the headline n (a --n smoke run is not the flagship
    metric). A function so the off-chip tests can pin the incremental
    persistence order without a chip."""
    import jax
    return jax.default_backend() == "tpu" and n == 1 << 24


def main(argv=None) -> int:
    """The round metric. No arguments = the flagship on-chip run; the
    flags exist so the metric path itself is testable off-chip
    (tests/test_bench_metric.py) — they do not change the headline
    semantics."""
    import argparse
    p = argparse.ArgumentParser(prog="bench.py")
    p.add_argument("--n", type=int, default=1 << 24)
    p.add_argument("--iterations", type=int, default=256)
    p.add_argument("--platform", type=str, default=None,
                   choices=("cpu", "tpu"))
    ns = p.parse_args(argv)
    if ns.n <= 0:
        p.error("--n must be positive")

    import os
    # flight recorder FIRST (before the device probe): the outage path
    # must land in the run record too (docs/OBSERVABILITY.md)
    from tpu_reductions.obs.ledger import arm_session, emit
    arm_session("bench.py", argv=list(argv) if argv else sys.argv[1:])
    # BENCH_SKIP_PROBE=1: the caller (chip_session.sh) verified the
    # relay seconds ago; the probe subprocess would re-pay a full jax
    # init (~30-40 s of a window that may only be minutes long) to
    # learn the same thing. The rare wedged-but-ports-open tunnel the
    # probe guards against is bounded by the session's step budget.
    outage = (None if ns.platform == "cpu"
              or os.environ.get("BENCH_SKIP_PROBE") == "1"
              else _device_probe(platform=ns.platform))
    if outage is not None:
        print(f"accelerator unavailable: {outage}; reporting the outage "
              "instead of hanging", file=sys.stderr)
        payload = _snapshot_fallback(outage)
        # the preflight verdict used to be only on disk
        # (.chip_health.json) — the outage event carries it into the
        # run record, fresh or stale (staleness is itself evidence)
        emit("bench.outage", outage=outage, health=_health_record())
        emit("bench.metric", **payload)
        print(json.dumps(payload))
        return 1

    from tpu_reductions.config import _apply_platform
    _apply_platform(ns)

    # a candidate race hung on a mid-run relay death reports nothing;
    # the watchdog exits promptly instead (utils/watchdog.py)
    from tpu_reductions.utils.watchdog import maybe_arm_for_tpu
    maybe_arm_for_tpu()

    from tpu_reductions.bench.driver import run_benchmark_batch
    from tpu_reductions.config import ReduceConfig
    from tpu_reductions.utils.logging import BenchLogger

    # iterations = the chained span (driver.py: k_hi = 1 + iterations).
    # On this tunnel the slope needs >= ~5 ms of in-program signal to
    # clear multi-ms materialization jitter: span 16 measured a NEGATIVE
    # median slope at n=2^24, span 256 a stable one (calibration_r02.json);
    # at ~24 us/iter (VMEM-resident at this size) 256 iters = ~6 ms.
    base = ReduceConfig(method="SUM", dtype="int32", n=ns.n,
                        iterations=ns.iterations, warmup=2, stat="median",
                        timing="chained", chain_reps=7,
                        log_file=None)
    cfgs = [dataclasses.replace(base, backend=b, kernel=k, threads=t)
            for b, k, t in CANDIDATES]
    logger = BenchLogger(None, None, console=sys.stderr)

    import math
    flagship_geom = _on_flagship_geometry(ns.n)
    label = (f"2^{ns.n.bit_length() - 1}" if ns.n & (ns.n - 1) == 0
             else str(ns.n))

    def _payload(rs):
        best = max((r.gbps for r in rs if r.passed), default=0.0)
        return {
            "metric": f"single-chip int32 SUM reduction bandwidth, "
                      f"n={label}",
            "value": round(best, 4),
            "unit": "GB/s",
            "vs_baseline": round(best / BASELINE_GBPS, 4),
        }

    def _provenance(done):
        out = {}
        for cfg, res in done:
            # crash/WAIVE rows carry nan gbps: serialize null, not
            # the non-RFC-8259 NaN literal (same guard as
            # autotune._row / BenchResult.to_dict)
            entry = {"gbps": (round(res.gbps, 1)
                              if math.isfinite(res.gbps) else None),
                     "status": res.status.name}
            pos = [s for s in (getattr(res, "slope_samples_s", None) or [])
                   if isinstance(s, (int, float)) and s > 0]
            if pos:
                # per-rep spread (round-4 judge, weak #7: the flagship
                # VMEM rate spanned 2.7x across reps in one grid — the
                # quoted median travels with its min/max from now on)
                entry["gbps_spread"] = [round(cfg.nbytes / max(pos) / 1e9, 1),
                                        round(cfg.nbytes / min(pos) / 1e9, 1)]
            out[f"{cfg.backend} k{cfg.kernel} threads={cfg.threads}"] = entry
        return out

    # Candidates run ONE AT A TIME, best-known-first, persisting after
    # each: the tunnel relay FLAPS (round 4 observed a ~6-minute window
    # die mid-step after two rounds of none), and chained timing does
    # its device work at dispatch — a 4-candidate batch would persist
    # nothing until all four had run. Value order inside the window:
    # candidate 0 (the round-2/round-3 crowned winner) -> partial
    # snapshot on disk -> headline stdout line -> the f64 DOUBLE
    # scoreboard (the verdict's #1 gap for three rounds) -> runner-ups
    # -> final snapshot. On flagship geometry the ONE stdout JSON line
    # prints as soon as a candidate verifies — before the doubles —
    # so a death later in the run cannot lose it; the candidates are
    # ranked by the committed races, so first-verified is best-known
    # (an upset by a runner-up still lands in the final snapshot's
    # provenance). Off-chip runs keep the end-of-race print: there the
    # metric is "best of the full race", and there is no window to
    # die on.
    results = []
    printed_value = None

    def _print_headline_once():
        nonlocal printed_value
        if printed_value is None:
            payload = _payload(results)
            print(json.dumps(payload), flush=True)
            # the round-metric line, in the run record as well as on
            # stdout (obs/timeline.py; docs/OBSERVABILITY.md)
            emit("bench.metric", **payload)
            printed_value = payload["value"]

    for i, cfg in enumerate(cfgs):
        res = run_benchmark_batch([cfg], logger=logger)[0]
        results.append(res)
        print(f"# {cfg.backend} k{cfg.kernel} threads={cfg.threads}: "
              f"{res.gbps:.1f} GB/s [{res.status.name}]", file=sys.stderr)
        if flagship_geom and any(r.passed for r in results):
            # fresh verified on-chip value AT THE FLAGSHIP CONFIG:
            # snapshot immediately so a relay death between candidates
            # (or a later outage in the round) reports THIS measurement
            snap = _payload(results)
            if i < len(cfgs) - 1:
                snap["partial"] = True   # race still in flight
            _write_snapshot(snap, _provenance(zip(cfgs, results)))
            _print_headline_once()
        if flagship_geom and i == 0:
            # Opportunistic DOUBLE scoreboard (VERDICT item 1, the
            # round's #1 gap) directly after the first candidate:
            # stderr + artifact files only, strictly best-effort (a
            # doubles failure can neither change the exit code nor
            # block the runner-ups), and NOT gated on candidate 0
            # passing — the dd path is independent of the int race.
            # BENCH_DOUBLES=0 skips it (a window that wants the
            # fastest possible bench).
            _maybe_double_spots()
    passed = [r for r in results if r.passed]
    _print_headline_once()
    final_best = _payload(results)["value"]
    if printed_value is not None and final_best > printed_value:
        # the early headline line (printed the moment the first
        # candidate verified, so a window death can't lose it) was
        # upset by a runner-up: say so loudly — the final
        # BENCH_snapshot.json carries the best verified value and is
        # authoritative when the two differ (round-4 ADVICE 1)
        print(f"# NOTE: headline line printed {printed_value} GB/s "
              f"(first verified candidate); the completed race's best "
              f"is {final_best} GB/s — BENCH_snapshot.json is "
              "authoritative", file=sys.stderr)
    return 0 if passed else 1


def _health_record() -> dict | None:
    """The raw preflight verdict record (.chip_health.json) for the
    outage event — deliberately NOT TTL-gated like preflight.read_health:
    a stale verdict in an outage report is still evidence (it says the
    wedge predates this run), it just must be labeled stale."""
    import os
    import time as _time

    from tpu_reductions.utils.preflight import (DEFAULT_HEALTH_TTL_S,
                                                health_file_path)
    try:
        with open(health_file_path()) as f:
            record = json.load(f)
    except (OSError, ValueError):
        return None
    if not isinstance(record, dict):
        return None
    ts = record.get("ts")
    if isinstance(ts, (int, float)):
        try:
            ttl = float(os.environ.get("TPU_REDUCTIONS_HEALTH_TTL_S",
                                       DEFAULT_HEALTH_TTL_S))
        except ValueError:
            ttl = DEFAULT_HEALTH_TTL_S
        record["stale"] = _time.time() - ts > ttl
    return record


def _maybe_double_spots(n: int | None = None, iterations: int | None = None,
                        reps: int | None = None,
                        path: str | None = None) -> None:
    """Best-effort f64 SUM/MIN/MAX chained spots at the flagship n ->
    BENCH_doubles.json next to this file. All-device dd path (pair-tree
    finish), oracle-verified, median slope reps — the rows that must
    beat the reference's own headline doubles (92.7729/92.6014/92.7552
    GB/s, mpi/CUdata.txt:2-4). Defaults come from sweep.FLAGSHIP_GRID
    so the rows are seedable into the flagship grid cache
    (bench/seed_cache.py): even a window that dies before the session's
    spot step then carries report-grade DOUBLE evidence. The size/path
    parameters exist for tests; main() always calls with defaults."""
    import os
    if os.environ.get("BENCH_DOUBLES", "1") != "1":
        return
    try:
        from tpu_reductions.bench.spot import _write, run_spots
        from tpu_reductions.bench.sweep import FLAGSHIP_GRID
        from tpu_reductions.config import ReduceConfig
        from tpu_reductions.utils.logging import BenchLogger

        n = FLAGSHIP_GRID["n"] if n is None else n
        iterations = (FLAGSHIP_GRID["iterations"] if iterations is None
                      else iterations)
        reps = FLAGSHIP_GRID["chain_reps"] if reps is None else reps
        print("# doubles: f64 SUM/MIN/MAX chained spots (dd path, "
              "flagship-grid contract)", file=sys.stderr)
        base = ReduceConfig(method="SUM", dtype="float64", n=n,
                            threads=FLAGSHIP_GRID["threads"],
                            kernel=FLAGSHIP_GRID["kernel"],
                            iterations=iterations, warmup=2,
                            timing="chained", chain_reps=reps,
                            stat="median", log_file=None)
        if path is None:
            path = os.path.join(
                os.path.dirname(os.path.abspath(__file__)),
                "BENCH_doubles.json")
        meta = {"n": base.n, "timing": "chained", "stat": "median",
                "reference": {"SUM": 92.7729, "MIN": 92.6014,
                              "MAX": 92.7552}}
        rows: list = []

        def persist(row):
            rows.append(row)
            print(f"# doubles: {row['method']} "
                  f"{row['gbps'] if row['gbps'] is not None else 'n/a'}"
                  f" GB/s [{row['status']}]", file=sys.stderr)
            _write(path, meta, rows, complete=False)

        run_spots(base, ["SUM", "MIN", "MAX"],
                  logger=BenchLogger(None, None, console=sys.stderr),
                  on_result=persist)
        _write(path, meta, rows, complete=True)
        print(f"# doubles: wrote {path}", file=sys.stderr)
    except Exception as e:  # best-effort by contract
        print(f"# doubles spot failed (non-fatal): "
              f"{type(e).__name__}: {e}", file=sys.stderr)


if __name__ == "__main__":
    sys.exit(main())
