"""CLI: `python -m tpu_reductions --method=SUM -type is spelled --type here`.

The reduction-benchmark executable analog (reference reduction.cpp:84-204).
"""

import sys

from tpu_reductions.bench.driver import main

if __name__ == "__main__":
    sys.exit(main())
