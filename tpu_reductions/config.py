"""L0: one typed flag/config system.

Replaces the reference's three config tiers (SURVEY.md §5 "Config / flag
system"): cutil `CmdArgReader` CLI flags (reference reduction.cpp:31-40,
91-94,672-682), compile-time constants (mpi/constants.h:1-5), and
launcher environment (mpi/ccni_vn.sh:3,6; mpi/submit_all.sh:3).

Flag-name parity with the reference CLI (reduction.cpp:31-40):

  --method={SUM|MIN|MAX}      required, exits if absent (reduction.cpp:124-128)
  --type={int|float|double}   dtype, default int (reduction.cpp:96-109);
                              also accepts int32/float32/float64
  --n=<int>                   elements, default 1<<24 (reduction.cpp:665)
  --threads=<int>             tile rows per grid step — the threads-per-block
                              analog, default 256 (reduction.cpp:666)
  --kernel=<int>              kernel id; 6 (single-pass accumulator),
                              7 (two-pass partials), 8 (elementwise
                              accumulator), 9 (MXU matmul SUM, float
                              dtypes) and 10 (streaming deep-DMA
                              accumulator) are live; 0-5 are WAIVED,
                              mirroring the intentionally-emptied dispatch
                              cases (reduction_kernel.cu:278-289)
  --maxblocks=<int>           grid clamp, default 64 (reduction.cpp:668)
  --cpufinal                  finish partial reduction on host
                              (reduction.cpp:328-340)
  --cputhresh=<int>           partial count below which host finishes,
                              default 1 (reduction.cpp:667)
  --shmoo                     size sweep — IMPLEMENTED here, unlike the
                              reference's stub (reduction.cpp:577-580)
  --backend={pallas|xla|auto} TPU kernel selection (no reference analog:
                              xla is the always-correct comparator)
  --stat={mean|median}        per-iteration time statistic; mean matches
                              cutGetAverageTimerValue, median shrugs off
                              interconnect sync stalls

MPI-side constants (mpi/constants.h) become flags of the collective driver:
  --n / --iterations / --retries  (NUM_INTS, RETRY_COUNT analogs; the
  hard-coded CLOCK_RATE has no analog — we use real wall clocks, SURVEY §5).
"""

from __future__ import annotations

import argparse
import dataclasses
from typing import Optional

# dtype aliases: reference spells them int/float/double (reduction.cpp:96-109)
DTYPE_ALIASES = {
    "int": "int32",
    "float": "float32",
    "double": "float64",
    "int32": "int32",
    "float32": "float32",
    "float64": "float64",
    "bfloat16": "bfloat16",  # TPU-native extension beyond the reference set
}

METHODS = ("SUM", "MIN", "MAX")
# the reduction family (ISSUE 20; docs/FAMILY.md): prefix scan,
# segmented reductions, and index-carrying extremes. These are served
# methods (serve/request.py validates against SERVED_METHODS) and
# family-spot cells, NOT classic single-chip bench methods —
# ReduceConfig stays METHODS-only.
FAMILY_METHODS = ("SCAN", "SEGSUM", "SEGMIN", "SEGMAX",
                  "ARGMIN", "ARGMAX")
SERVED_METHODS = METHODS + FAMILY_METHODS
BACKENDS = ("auto", "pallas", "xla")

# ---------------------------------------------------------------------------
# Host->device transfer bounds — ONE home for the chunk-size doctrine.
#
# 2 GiB single messages survived the tunnel relay, 4 GiB killed it twice
# (round 2; utils/staging.py module docstring has the history). These
# were two hardcoded constants in utils/staging.py; they now live here so
# the env knob (TPU_REDUCTIONS_STAGE_CHUNK_BYTES), the CLI flag
# (--chunk-bytes) and the defaults agree by construction
# (docs/RESILIENCE.md env-knob table).
# ---------------------------------------------------------------------------

# Per-message bound: 256 MiB keeps a wide margin under the 4 GiB killer
# while adding only ~16 messages per surviving GiB.
DEFAULT_STAGE_CHUNK_BYTES = 256 << 20
# Payloads at or under this stage in ONE message (no reason to multiply
# round-trips for the common case). Default: 2x the chunk bound.
DEFAULT_STAGE_THRESHOLD_BYTES = 512 << 20
# Serving shard threshold: a request above this goes device-parallel
# (split across local devices, combined through the collectives
# registry — serve/executor.run_sharded) instead of streaming through
# one device. Same line as the per-request byte cap by default: the
# payloads the cap used to reject are exactly the ones worth sharding.
DEFAULT_SHARD_THRESHOLD_BYTES = 512 << 20


def _env_bytes(name: str) -> Optional[int]:
    import os
    try:
        v = int(os.environ[name])
        return v if v > 0 else None
    except (KeyError, ValueError):
        return None


def _env_float(name: str) -> Optional[float]:
    import os
    try:
        v = float(os.environ[name])
        return v if v >= 0 else None
    except (KeyError, ValueError):
        return None


def stage_chunk_bytes(override: Optional[int] = None) -> int:
    """The effective per-message host->device chunk bound: explicit
    argument (the --chunk-bytes flag), else the
    TPU_REDUCTIONS_STAGE_CHUNK_BYTES env override, else the 256 MiB
    default. The single source every staging/streaming path reads."""
    if override is not None and override > 0:
        return int(override)
    return _env_bytes("TPU_REDUCTIONS_STAGE_CHUNK_BYTES") \
        or DEFAULT_STAGE_CHUNK_BYTES


def stage_threshold_bytes(override: Optional[int] = None) -> int:
    """The single-message staging threshold: payloads above it must
    chunk. Explicit argument, else TPU_REDUCTIONS_STAGE_THRESHOLD_BYTES,
    else 2x the effective chunk bound (which preserves the historical
    256/512 MiB pair at defaults and keeps the pair coherent when only
    the chunk knob moves)."""
    if override is not None and override > 0:
        return int(override)
    return _env_bytes("TPU_REDUCTIONS_STAGE_THRESHOLD_BYTES") \
        or 2 * stage_chunk_bytes()


def shard_threshold_bytes(override: Optional[int] = None) -> int:
    """The device-parallel shard threshold of the serving tier: a
    request whose payload exceeds it splits across local devices
    (bounded per-device chunks, collective combine —
    serve/executor.run_sharded) when the backend has more than one
    device; at or under it, the single-device batch/stream paths
    apply. Explicit argument (the engine's shard_threshold_bytes
    knob), else TPU_REDUCTIONS_SHARD_THRESHOLD_BYTES, else 512 MiB
    (docs/RESILIENCE.md knob table; docs/SERVING.md scaling tier)."""
    if override is not None and override > 0:
        return int(override)
    return _env_bytes("TPU_REDUCTIONS_SHARD_THRESHOLD_BYTES") \
        or DEFAULT_SHARD_THRESHOLD_BYTES


# ---------------------------------------------------------------------------
# Elastic serving fleet bounds (serve/autoscale.py; docs/SERVING.md
# "elastic fleet"). Same knob discipline as the staging bounds above:
# explicit argument > env override > default, ONE home for all three
# (docs/RESILIENCE.md env-knob table).
# ---------------------------------------------------------------------------

DEFAULT_AUTOSCALE_MIN = 1
DEFAULT_AUTOSCALE_MAX = 8
DEFAULT_AUTOSCALE_COOLDOWN_S = 5.0


def autoscale_min(override: Optional[int] = None) -> int:
    """Floor on the elastic fleet's replica count: explicit argument,
    else TPU_REDUCTIONS_AUTOSCALE_MIN, else 1 (the autoscaler never
    drains the last replica below this)."""
    if override is not None and override > 0:
        return int(override)
    return _env_bytes("TPU_REDUCTIONS_AUTOSCALE_MIN") \
        or DEFAULT_AUTOSCALE_MIN


def autoscale_max(override: Optional[int] = None) -> int:
    """Ceiling on the elastic fleet's replica count: explicit argument,
    else TPU_REDUCTIONS_AUTOSCALE_MAX, else 8 (a burst can never spawn
    replicas past this, however far p99 drifts)."""
    if override is not None and override > 0:
        return int(override)
    return _env_bytes("TPU_REDUCTIONS_AUTOSCALE_MAX") \
        or DEFAULT_AUTOSCALE_MAX


def autoscale_cooldown_s(override: Optional[float] = None) -> float:
    """Minimum seconds between scaling actions: explicit argument, else
    TPU_REDUCTIONS_AUTOSCALE_COOLDOWN_S, else 5 s — one half of the
    oscillation damping (the other is the consecutive-calm-tick
    hysteresis; serve/autoscale.Autoscaler)."""
    if override is not None and override >= 0:
        return float(override)
    env = _env_float("TPU_REDUCTIONS_AUTOSCALE_COOLDOWN_S")
    return env if env is not None else DEFAULT_AUTOSCALE_COOLDOWN_S


# ---------------------------------------------------------------------------
# Crash-consistent control plane knobs (serve/journal.py, serve/engine.py
# dedup cache; docs/SERVING.md "crash-consistent control plane"). Same
# discipline: explicit argument > env override > default.
# ---------------------------------------------------------------------------

DEFAULT_DEDUP_CACHE_SIZE = 1024


def fleet_journal_path(override: Optional[str] = None) -> Optional[str]:
    """Where the fleet journal persists: explicit argument (the
    router's --journal flag), else TPU_REDUCTIONS_FLEET_JOURNAL, else
    None (journaling off — an in-process test fleet does not need a
    file). All writes route through utils/jsonio (RED010)."""
    if override:
        return str(override)
    import os
    return os.environ.get("TPU_REDUCTIONS_FLEET_JOURNAL") or None


def dedup_cache_size(override: Optional[int] = None) -> int:
    """Bound on each engine's settled-response dedup cache (entries):
    explicit argument, else TPU_REDUCTIONS_DEDUP_CACHE_SIZE, else 1024.
    Eviction is LRU; an evicted idempotency key degrades to the
    documented at-least-once fallback (retry re-executes) — never a
    hang (docs/SERVING.md)."""
    if override is not None and override > 0:
        return int(override)
    return _env_bytes("TPU_REDUCTIONS_DEDUP_CACHE_SIZE") \
        or DEFAULT_DEDUP_CACHE_SIZE

# Kernel ids: the reference kept only kernel 6 live and emptied 0-5
# (reduction_kernel.cu:278-289). We map 6 -> single-pass fold-accumulator
# Pallas kernel, 7 -> two-pass partials Pallas kernel, 8-10 ->
# extensions (elementwise / MXU / streaming accumulators), and WAIVE 0-5.
LIVE_KERNELS = (6, 7, 8, 9, 10)
KERNEL_SINGLE_PASS = 6
KERNEL_TWO_PASS = 7
KERNEL_ELEMENTWISE = 8
KERNEL_MXU = 9          # SUM over float dtypes: ones-row matmul on the
                        # MXU (arXiv:1811.09736 / 2001.05585 technique)
KERNEL_STREAM = 10      # manual deep DMA pipeline (default depth 4 vs
                        # Mosaic's automatic double-buffering) — the
                        # HBM-regime candidate (docs/PERF_NOTES.md)


@dataclasses.dataclass
class ReduceConfig:
    """Single-chip reduction benchmark configuration (L3 driver input)."""

    method: str = "SUM"
    dtype: str = "int32"
    n: int = 1 << 24                 # default n=1<<24 (reduction.cpp:665)
    threads: int = 256               # tile rows / grid step (reduction.cpp:666)
    kernel: int = KERNEL_SINGLE_PASS
    max_blocks: int = 64             # grid clamp (reduction.cpp:668)
    cpu_final: bool = False          # --cpufinal (reduction.cpp:328-340)
    cpu_thresh: int = 1              # --cputhresh (reduction.cpp:667)
    stream_buffers: int = 4          # kernel-10 DMA pipeline depth (the
                                     # one streaming knob Mosaic's
                                     # automatic depth-2 pipeline does
                                     # not expose; other kernels ignore)
    backend: str = "auto"
    iterations: int = 100            # timed iters (reduction.cpp:731)
    warmup: int = 1                  # warm-up launches (reduction.cpp:729)
    seed: int = 0                    # data seed (rank analog: reduce.c:38-41)
    device: Optional[int] = None     # --device analog (reduction.cpp:36)
    log_file: Optional[str] = "reduction.txt"   # shrSetLogFileName analog
    master_log: Optional[str] = None # MASTERLOGFILE analog (shrUtils.cpp)
    qatest: bool = False             # --qatest batch mode (shrQATest.h:90-97)
    verify: bool = True
    trace_dir: Optional[str] = None  # jax.profiler trace capture dir
    check: bool = False              # compiled/interpret/XLA consistency
    timing: str = "periter"          # periter|bulk|fetch|chained
    chain_reps: int = 5              # slope repetitions for timing=chained
    stat: str = "mean"               # mean (reference parity) | median
                                     # (robust to tunnel sync stalls)
    iterations_explicit: bool = False   # user set --iterations (chained
                                        # shmoo: treat as a span bound)
    stream: bool = False             # --stream: double-buffered chunked
                                     # streaming pipeline (ops/stream.py)
                                     # instead of stage-then-reduce
    chunk_bytes: Optional[int] = None   # --chunk-bytes override of the
                                        # staging/streaming chunk bound
                                        # (stage_chunk_bytes above)

    def __post_init__(self) -> None:
        self.method = self.method.upper()
        if self.method not in METHODS:
            raise ValueError(f"method must be one of {METHODS}, got {self.method!r}")
        if self.dtype not in DTYPE_ALIASES:
            raise ValueError(f"unknown dtype {self.dtype!r}")
        self.dtype = DTYPE_ALIASES[self.dtype]
        if self.backend not in BACKENDS:
            raise ValueError(f"backend must be one of {BACKENDS}, got {self.backend!r}")
        if self.n <= 0:
            raise ValueError("n must be positive")
        if self.threads <= 0 or self.max_blocks <= 0:
            raise ValueError("threads/max_blocks must be positive")
        if self.stream_buffers <= 0:
            raise ValueError("stream_buffers must be positive")
        if self.timing not in ("periter", "bulk", "fetch", "chained"):
            raise ValueError(f"timing must be periter|bulk|fetch|chained, "
                             f"got {self.timing!r}")
        if self.chain_reps <= 0:
            raise ValueError("chain_reps must be positive")
        if self.stat not in ("mean", "median"):
            raise ValueError(f"stat must be mean|median, got {self.stat!r}")
        if self.chunk_bytes is not None and self.chunk_bytes <= 0:
            raise ValueError("chunk_bytes must be positive")

    @property
    def nbytes(self) -> int:
        import numpy as np
        return self.n * np.dtype(self.dtype).itemsize


@dataclasses.dataclass
class CollectiveConfig:
    """Cross-chip collective reduction configuration (MPI_Reduce analog).

    Mirrors mpi/reduce.c + mpi/constants.h + launcher scripts:
      n            total elements across all shards (NUM_INTS/NUM_DOUBLES
                   analog, constants.h:1-2 — but as a flag, not a constant)
      retries      timed repetitions (RETRY_COUNT=5, constants.h:5)
      num_devices  rank count (sbatch --nodes sweep, submit_all.sh:3-4)
      mesh_shape   optional multi-axis mesh (torus analog)
      mapping      mesh axis-order / device permutation — the
                   BGLMPI_MAPPING=TXYZ analog (ccni_vn.sh:3)
      mode         'vn' uses every addressable device, 'co' uses one device
                   per host/chip — the BG/L virtual-node vs coprocessor mode
                   analog (ccni_vn.sh:6)
      rooted       'none' = all-reduce (psum everywhere); 'scatter' =
                   reduce-scatter (rooted wire cost, each rank keeps L/k);
                   'root' = true reduce-to-root like MPI_Reduce(root=0)
                   (reduce.c:76,90) — root holds the full reduced array.
                   Bools accepted: False -> 'none', True -> 'scatter'.
    """

    method: str = "SUM"
    dtype: str = "int32"
    n: int = 1 << 24
    retries: int = 5
    warmup: int = 1                  # reduce.c:61-64 warm-up reduce
    num_devices: Optional[int] = None
    mesh_shape: Optional[tuple] = None
    mapping: str = "default"
    mode: str = "vn"
    rooted: str = "none"             # none|scatter|root (bools accepted)
    quantized: bool = False          # block-quantized wire (EQuARX-style
                                     # compression; SUM f32/bf16/f64-dd,
                                     # exact coarse-key MIN/MAX f32/f64 —
                                     # collectives/quant.quant_supported)
    quant_bits: int = 8              # wire width for --quantized
                                     # (SUM: 4|8|16; MIN/MAX keys: 8|16)
    backend: str = "xla"
    seed: int = 0
    verify: bool = True
    qatest: bool = False             # batch mode: QA markers only
    timing: str = "periter"          # periter (reduce.c structure) |
                                     # chained (honest slope mode)
    chain_span: int = 16             # in-program iterations per slope
    # multi-host launch (the mpirun/SLURM tier, ccni_vn.sh:6-8): every
    # participating process runs the same CLI with its own --process-id;
    # see docs/MULTIHOST.md
    coordinator: Optional[str] = None   # host0 address, e.g. "10.0.0.1:8476"
    num_processes: Optional[int] = None
    process_id: Optional[int] = None
    out: Optional[str] = None        # --out artifact (bench/resume
    #                                  Checkpoint: per-repeat rows,
    #                                  persist-per-row + resume)

    def __post_init__(self) -> None:
        self.method = self.method.upper()
        if self.method not in METHODS:
            raise ValueError(f"method must be one of {METHODS}, got {self.method!r}")
        self.dtype = DTYPE_ALIASES[self.dtype]
        from tpu_reductions.parallel.collectives import normalize_rooted
        self.rooted = normalize_rooted(self.rooted)
        if self.mode not in ("vn", "co"):
            raise ValueError("mode must be 'vn' or 'co'")
        if self.timing not in ("periter", "chained"):
            raise ValueError(f"timing must be periter|chained, "
                             f"got {self.timing!r}")
        if self.chain_span <= 0:
            raise ValueError("chain_span must be positive")
        if self.quantized:
            from tpu_reductions.collectives.quant import (
                quant_support_error, quant_supported)
            if not quant_supported(self.method, self.dtype,
                                   self.quant_bits):
                # actionable fail-fast: the error names the supported
                # (op, dtype, bits) space instead of silently narrowing
                raise ValueError(quant_support_error(
                    self.method, self.dtype, self.quant_bits))


def _add_common_flags(p: argparse.ArgumentParser) -> None:
    p.add_argument("--method", type=str, default=None,
                   help="Reduction to benchmark: SUM|MIN|MAX (required, "
                        "mirroring reduction.cpp:124-128)")
    p.add_argument("--type", dest="dtype", type=str, default="int",
                   help="int|float|double (or int32/float32/float64/bfloat16)")
    p.add_argument("--n", type=int, default=1 << 24,
                   help="Number of elements to reduce (default 2^24)")
    p.add_argument("--seed", type=int, default=0, help="Data seed")
    p.add_argument("--qatest", action="store_true",
                   help="QA batch mode (shrQATest --qatest analog)")
    p.add_argument("--no-verify", dest="verify", action="store_false",
                   help="Skip host-oracle verification")
    p.add_argument("--platform", type=str, default=None,
                   choices=("cpu", "tpu"),
                   help="Force the JAX platform (e.g. cpu to run on a "
                        "machine without a TPU)")


def build_single_chip_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="tpu_reductions",
        description="Self-verifying single-chip TPU reduction benchmark "
                    "(reference: cuda/C/src/reduction)",
    )
    _add_common_flags(p)
    p.add_argument("--threads", type=int, default=256,
                   help="Tile rows per grid step (threads-per-block analog)")
    p.add_argument("--kernel", type=int, default=KERNEL_SINGLE_PASS,
                   help="6=single-pass fold accumulator, 7=two-pass "
                        "partials, 8=single-pass elementwise accumulator, "
                        "9=MXU matmul SUM (float dtypes; other combos "
                        "WAIVE), 10=streaming deep-DMA accumulator; "
                        "0-5 WAIVED (reference emptied them)")
    p.add_argument("--maxblocks", dest="max_blocks", type=int, default=64,
                   help="Grid clamp (maxblocks analog)")
    p.add_argument("--streambuffers", dest="stream_buffers", type=int,
                   default=4,
                   help="Kernel-10 async-DMA pipeline depth (default 4; "
                        "Mosaic's automatic BlockSpec pipeline is depth "
                        "2). Other kernels ignore this knob")
    p.add_argument("--cpufinal", dest="cpu_final", action="store_true",
                   help="Finish partial reduction on host")
    p.add_argument("--cputhresh", dest="cpu_thresh", type=int, default=1,
                   help="Host-finish threshold on partial count")
    p.add_argument("--backend", type=str, default="auto",
                   choices=list(BACKENDS))
    p.add_argument("--iterations", type=int, default=None,
                   help="Timed iterations (default 100, reduction.cpp:731)")
    p.add_argument("--warmup", type=int, default=1)
    p.add_argument("--device", type=int, default=None,
                   help="Device index (--device analog)")
    p.add_argument("--shmoo", action="store_true",
                   help="Run the size sweep 2^shmoo-min..2^shmoo-max "
                        "(implemented, unlike the reference's stub at "
                        "reduction.cpp:577-580)")
    p.add_argument("--shmoo-min", dest="shmoo_min", type=int, default=10,
                   help="Smallest shmoo size as a power of two (default 10)")
    p.add_argument("--shmoo-max", dest="shmoo_max", type=int, default=24,
                   help="Largest shmoo size as a power of two (default 24; "
                        "BASELINE config #5 sweeps to 30)")
    p.add_argument("--logfile", dest="log_file", type=str,
                   default="reduction.txt")
    p.add_argument("--masterlog", dest="master_log", type=str, default=None)
    p.add_argument("--trace", dest="trace_dir", type=str, default=None,
                   help="Capture a jax.profiler trace of the hot loop into "
                        "this directory (cutil-timer observability analog)")
    p.add_argument("--check", action="store_true",
                   help="Run the compiled/interpret/XLA consistency check "
                        "before benchmarking (bank-checker analog)")
    p.add_argument("--timing", type=str, default="periter",
                   choices=("periter", "bulk", "fetch", "chained"),
                   help="Sync discipline: periter=reference structure; "
                        "bulk=one span, amortized dispatch; fetch=host "
                        "round-trip each iteration; chained=K data-"
                        "dependent in-program iterations, slope-timed to "
                        "host materialization — the honest mode on "
                        "tunneled/async backends (ops/chain.py)")
    p.add_argument("--chainreps", dest="chain_reps", type=int, default=5,
                   help="Slope repetitions for --timing=chained")
    p.add_argument("--stat", type=str, default="mean",
                   choices=("mean", "median"),
                   help="Per-iteration statistic feeding GB/s: mean = "
                        "cutGetAverageTimerValue parity; median = robust "
                        "to interconnect/tunnel sync stalls")
    p.add_argument("--stream", action="store_true",
                   help="Streaming pipeline mode (ops/stream.py): chunked "
                        "host->device staging double-buffered against "
                        "on-device accumulation — bounded device memory, "
                        "no single-message relay hazard, sustained-GB/s + "
                        "chunks/s metrics (docs/STREAMING.md)")
    p.add_argument("--chunk-bytes", dest="chunk_bytes", type=int,
                   default=None,
                   help="Per-message host->device chunk bound override "
                        "(default: TPU_REDUCTIONS_STAGE_CHUNK_BYTES env, "
                        "else 256 MiB — config.stage_chunk_bytes)")
    return p


def parse_single_chip(argv=None):
    """Parse CLI args -> (ReduceConfig, shmoo).

    shmoo is None unless --shmoo was given, in which case it is the
    (min_pow, max_pow) size range — truthy, so `if shmoo:` keeps working.
    Exits with an error if --method is missing, mirroring the reference's
    required-flag behavior (reduction.cpp:124-128).
    """
    p = build_single_chip_parser()
    ns = p.parse_args(argv)
    if ns.method is None:
        p.error("--method={SUM|MIN|MAX} is required "
                "(reference exits too: reduction.cpp:124-128)")
    if ns.dtype not in DTYPE_ALIASES:
        p.error(f"unknown --type {ns.dtype!r}; expected one of "
                f"{sorted(set(DTYPE_ALIASES))}")
    if ns.method.upper() not in METHODS:
        p.error(f"--method must be one of {METHODS}, got {ns.method!r}")
    cfg = ReduceConfig(
        method=ns.method, dtype=ns.dtype, n=ns.n, threads=ns.threads,
        kernel=ns.kernel, max_blocks=ns.max_blocks, cpu_final=ns.cpu_final,
        cpu_thresh=ns.cpu_thresh, stream_buffers=ns.stream_buffers,
        backend=ns.backend,
        iterations=(ns.iterations if ns.iterations is not None else 100),
        iterations_explicit=ns.iterations is not None,
        warmup=ns.warmup, seed=ns.seed,
        device=ns.device, log_file=ns.log_file, master_log=ns.master_log,
        qatest=ns.qatest, verify=ns.verify, trace_dir=ns.trace_dir,
        check=ns.check, timing=ns.timing, chain_reps=ns.chain_reps,
        stat=ns.stat, stream=ns.stream, chunk_bytes=ns.chunk_bytes,
    )
    _apply_platform(ns)
    if ns.shmoo and not 0 < ns.shmoo_min <= ns.shmoo_max:
        p.error(f"--shmoo-min/--shmoo-max must satisfy 0 < min <= max, "
                f"got {ns.shmoo_min}/{ns.shmoo_max}")
    # iterations_explicit: whether the user set --iterations (chained
    # shmoo treats an explicit value as a span bound; the default is
    # auto-sized per payload — bench/sweep.run_shmoo)
    return cfg, ((ns.shmoo_min, ns.shmoo_max) if ns.shmoo else None)


def enable_compile_cache(path: str | None = None) -> None:
    """Point JAX's persistent compilation cache at a repo-local dir
    (untracked). The wiring lives in utils/compile_cache.py now — ONE
    home for the cache-dir plumbing AND the fingerprint introspection
    the compile observatory reads (obs/compile.py; ISSUE 8) — and this
    historical entry keeps every `_apply_platform` caller on it.
    TPU_REDUCTIONS_NO_COMPILE_CACHE=1 disables."""
    from tpu_reductions.utils.compile_cache import enable
    enable(path)


def _apply_platform(ns) -> None:
    enable_compile_cache()
    if getattr(ns, "platform", None):
        # must happen before the first jax backend touch; the axon plugin
        # ignores JAX_PLATFORMS, so this goes through jax.config.
        import jax
        jax.config.update("jax_platforms", ns.platform)
        if ns.platform == "cpu" and getattr(ns, "num_devices", None):
            # provision enough virtual CPU devices for the requested rank
            # count (the host-platform analog of a pod slice); 'co' mode
            # addresses every other device, so it needs twice as many.
            # Only when --devices is explicit — otherwise leave any
            # environment-provided device count (XLA_FLAGS) alone.
            want = ns.num_devices * (2 if getattr(ns, "mode", "vn") == "co"
                                     else 1)
            nproc = getattr(ns, "num_processes", None) or 1
            if nproc > 1:
                # multi-host: --devices is the GLOBAL rank count; each
                # process provisions only its local share
                if want % nproc != 0:
                    co = (" (mode=co provisions 2x that in virtual "
                          "devices)" if want != ns.num_devices else "")
                    # redlint: disable=RED007 -- flag-validation exit before any device dispatch; nothing is in flight
                    raise SystemExit(
                        f"--devices={ns.num_devices}{co} must divide "
                        f"evenly among --num-processes={nproc}: every "
                        "process provisions an equal local share "
                        "(docs/MULTIHOST.md)")
                want //= nproc
            try:
                jax.config.update("jax_num_cpu_devices", want)
            except AttributeError:
                # pre-0.4.38 jax: provision via XLA_FLAGS instead. This
                # function's contract is "called before the first
                # backend touch", so the env route is still effective.
                import os
                os.environ["XLA_FLAGS"] = (
                    os.environ.get("XLA_FLAGS", "")
                    + f" --xla_force_host_platform_device_count={want}")


def build_collective_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="tpu_reductions.collective",
        description="Cross-chip collective reduction benchmark "
                    "(reference: mpi/reduce.c over the BG/L torus)",
        # no prefix abbreviation: an abbreviated --hel would reach the
        # parser as --help AFTER the QA RUNNING marker printed, forcing
        # a marker for what is really a usage request; exact -h/--help
        # are intercepted before any marker (collective_driver.main)
        allow_abbrev=False,
    )
    _add_common_flags(p)
    p.add_argument("--retries", type=int, default=5,
                   help="Timed repetitions (RETRY_COUNT analog)")
    p.add_argument("--warmup", type=int, default=1)
    p.add_argument("--devices", dest="num_devices", type=int, default=None,
                   help="Device count (rank-count analog)")
    p.add_argument("--mapping", type=str, default="default",
                   help="Mesh axis ordering (BGLMPI_MAPPING analog)")
    p.add_argument("--mode", type=str, default="vn", choices=("vn", "co"),
                   help="vn=all devices, co=one per chip (BG/L VN/CO analog)")
    p.add_argument("--quantized", action="store_true",
                   help="block-quantized wire (EQuARX-style compression, "
                        "collectives/quant.py): SUM over float32/"
                        "bfloat16/float64 rides a --quant-bits ring with "
                        "error-feedback residuals (approximate — "
                        "verified within the declared quant_error_bound);"
                        " MIN/MAX over float32/float64 use coarse "
                        "order-preserving keys and stay EXACT. "
                        "Unsupported combos fail fast with the "
                        "supported table (docs/COLLECTIVES.md)")
    p.add_argument("--quant-bits", dest="quant_bits", type=int, default=8,
                   help="wire width for --quantized: 4|8|16 for SUM "
                        "block scaling, 8|16 for MIN/MAX coarse keys "
                        "(default 8)")
    p.add_argument("--rooted", nargs="?", const="scatter", default="none",
                   choices=("none", "scatter", "root"),
                   help="Rooted reduce semantics: bare --rooted = "
                        "'scatter' (reduce-scatter, the rooted wire "
                        "cost); 'root' = true reduce-to-root like "
                        "MPI_Reduce(root=0) — the root rank holds the "
                        "full reduced array (reduce.c:76,90)")
    p.add_argument("--timing", type=str, default="periter",
                   choices=("periter", "chained"),
                   help="periter = reduce.c's sync-per-collective "
                        "structure; chained = data-dependent in-program "
                        "iterations, slope-timed (the honest mode on "
                        "tunneled/async backends)")
    p.add_argument("--chainspan", dest="chain_span", type=int, default=16,
                   help="In-program iterations per slope for "
                        "--timing=chained")
    p.add_argument("--coordinator", type=str, default=None,
                   help="Multi-host: coordinator address host:port "
                        "(process 0's host); see docs/MULTIHOST.md")
    p.add_argument("--num-processes", dest="num_processes", type=int,
                   default=None,
                   help="Multi-host: total participating processes")
    p.add_argument("--process-id", dest="process_id", type=int,
                   default=None,
                   help="Multi-host: this process's id in [0, "
                        "num_processes)")
    p.add_argument("--out", type=str, default=None,
                   help="JSON artifact path (bench/resume.Checkpoint "
                        "shape: rows persisted the moment they land; "
                        "an interrupted run resumes them on "
                        "re-invocation under the same contract)")
    return p


def parse_collective(argv=None) -> CollectiveConfig:
    p = build_collective_parser()
    ns = p.parse_args(argv)
    if ns.method is None:
        p.error("--method={SUM|MIN|MAX} is required")
    _apply_platform(ns)
    return CollectiveConfig(
        method=ns.method, dtype=ns.dtype, n=ns.n, retries=ns.retries,
        warmup=ns.warmup, num_devices=ns.num_devices, mapping=ns.mapping,
        mode=ns.mode, rooted=ns.rooted, seed=ns.seed, verify=ns.verify,
        qatest=ns.qatest, timing=ns.timing, chain_span=ns.chain_span,
        quantized=ns.quantized, quant_bits=ns.quant_bits,
        coordinator=ns.coordinator, num_processes=ns.num_processes,
        process_id=ns.process_id, out=ns.out,
    )
