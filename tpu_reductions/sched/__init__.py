"""sched — value-per-second planning for flappy chip windows.

Four rounds of live evidence died to the same structural fact: the
session plan was a FIXED, hand-ordered step list with static budgets
(scripts/chip_session.sh), while the resource it spends — the tunnel
relay's live window — lasts minutes and dies without warning
(CLAUDE.md; round 4's flap was ~6 min). A window that opens mid-list
replayed the same prefix every time; a flap mid-step wasted whatever
the static ordering put first. The reference faced the same scarce-
allocation problem — a sweep harness extracting a full bandwidth
surface from rationed Blue Gene/L cluster slots (SURVEY.md §0.3, the
mpi/submit_all.sh SLURM scripts) — and answered it with a harness, not
a hand list; "memory-efficient array redistribution" (PAPERS.md, Zhang
et al. 2021) makes the same move explicit: plan data movement against
a cost model exactly when the resource is the bottleneck.

This package converts the last three PRs' death-proofing (resume,
watchdog, heartbeat, preflight, flight recorder) into evidence-per-
minute:

  * `sched.tasks`    — the registry of measurement units (firstrow,
    scoreboards, races, smoke, ladder, flagship/hazard cells), each
    with a value score, a completion predicate over the existing
    bench/resume artifacts, a hazard flag and a static budget. The ONE
    sanctioned home of wall-clock budgets and step orderings (redlint
    RED013).
  * `sched.priors`   — duration priors learned from committed flight-
    recorder ledgers (step/sched events) + a window-length quantile
    model from recorded flap history, updated online as tasks finish.
  * `sched.planner`  — the greedy value/expected-second knapsack
    against the remaining-window estimate.
  * `sched.state`    — the crash-safe plan state (utils/jsonio atomic
    persists under a Checkpoint-style meta contract): an exit-3/exit-4
    re-invocation resumes the PLAN, not the script.
  * `sched.executor` — plan-and-execute loop; each task runs as a
    subprocess under the existing heartbeat/watchdog/preflight
    machinery, re-planned after every task.

CLI: `python -m tpu_reductions.sched` (docs/SCHEDULER.md).
scripts/chip_session.sh drives its step sequence through `--next` /
`--record` so its relay gate, per-step commits and exit trap stay in
charge of the shell side.

EVERY module in this package is jax-free by construction: planning
must keep working — and stay instant — while the relay is dead.
"""
