"""CLI: plan-and-execute a live window, or serve one decision at a
time to scripts/chip_session.sh.

Modes (docs/SCHEDULER.md):

    python -m tpu_reductions.sched                  # full executor run
    python -m tpu_reductions.sched --plan-only      # print the table
    python -m tpu_reductions.sched --next --emit=shell   # one pick
    python -m tpu_reductions.sched --record TASK --rc N --elapsed S

The full run is the rehearsal/acceptance surface (`--platform=cpu`
completes a whole plan off-chip; a SIGKILL mid-plan resumes). The
`--next`/`--record` pair is how chip_session.sh drives the SAME
planner while keeping its relay gate, per-step commits and exit trap:
`--next` prints eval-able SCHED_TASK_* assignments (exit 10 = plan
complete), the shell runs the task through its step() machinery, then
`--record` feeds the outcome back. Online duration updates flow
between one-shot invocations through the flight-recorder ledger
itself: every `sched.done` lands in TPU_REDUCTIONS_LEDGER, and the
next invocation's priors scan re-reads it.

Exit codes: 0 ok/plan-complete (full run), 3/4 window death
(propagated from the task — utils/watchdog.py vocabulary), 10 plan
complete (--next only), 2 usage.

jax-free (package docstring): safe to invoke while the relay is dead.
"""

from __future__ import annotations

import argparse
import os
import shlex
import sys
from typing import List

from tpu_reductions.obs import ledger
from tpu_reductions.sched import executor, planner, tasks as tasks_mod
from tpu_reductions.sched.priors import Priors
from tpu_reductions.sched.state import STATE_VERSION, PlanState

PLAN_COMPLETE_EXIT = 10


def _build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="tpu_reductions.sched",
        description="Value-per-expected-second window scheduler "
                    "(docs/SCHEDULER.md)")
    p.add_argument("--plan-only", action="store_true",
                   help="print the plan table and exit (no device, no "
                        "state writes)")
    p.add_argument("--next", dest="next_", action="store_true",
                   help="replan, record ONE pick, print it for the "
                        "shell loop (exit 10 when the plan is done)")
    p.add_argument("--emit", choices=("shell", "text"), default="text",
                   help="--next output format (shell = eval-able "
                        "SCHED_TASK_* assignments)")
    p.add_argument("--record", metavar="TASK", default=None,
                   help="record a finished task (shell loop feedback)")
    p.add_argument("--rc", type=int, default=0,
                   help="exit code for --record")
    p.add_argument("--elapsed", type=float, default=0.0,
                   help="wall-clock seconds for --record")
    p.add_argument("--state", default="sched_state.json",
                   help="plan state artifact (sched/state.py)")
    p.add_argument("--tasks", dest="tasks_file", default=None,
                   help="JSON task registry override (tests, chaos)")
    p.add_argument("--platform", choices=("cpu", "tpu"), default=None,
                   help="cpu = rehearsal profile (chip-only tasks "
                        "recorded skipped, rehearsal-scale commands)")
    p.add_argument("--only", default=None,
                   help="comma-separated task slugs to restrict to")
    p.add_argument("--history", action="append", default=None,
                   help="extra ledger file(s) for duration/window "
                        "priors (default: the active ledger)")
    p.add_argument("--compile-ledger", dest="compile_ledger",
                   default=None,
                   help="compile observatory artifact feeding the "
                        "cold/warm duration priors (default: "
                        "TPU_REDUCTIONS_COMPILE_LEDGER, else "
                        "compile_ledger.json)")
    p.add_argument("--window-quantile", type=float, default=0.5,
                   help="window-length quantile the knapsack plans "
                        "against")
    return p


def _active(ns) -> tuple:
    """(tasks, excluded, meta, priors) for the invocation."""
    only = ([s.strip() for s in ns.only.split(",") if s.strip()]
            if ns.only else None)
    if ns.tasks_file:
        active = tasks_mod.load_tasks_file(ns.tasks_file)
        if only is not None:
            active = [t for t in active if t.name in only]
        excluded: List = []
        if ns.platform == "cpu":
            excluded = [t for t in active if t.chip_only]
            active = [t for t in active if not t.chip_only]
    else:
        active = tasks_mod.registry(platform=ns.platform, only=only)
        excluded = tasks_mod.rehearsal_excluded(platform=ns.platform,
                                                only=only)
    tasks_mod.by_name(active)    # duplicate slugs fail loudly
    meta = {"version": STATE_VERSION,
            "registry": tasks_mod.registry_hash(active),
            "platform": ns.platform or "default"}
    history = list(ns.history or [])
    env_ledger = ledger.resolved_path()
    if env_ledger:
        history.append(env_ledger)
    elif not history:
        history.append("obs_ledger.jsonl")
    # the compile observatory's cold/warm axis (ISSUE 8): rows filtered
    # to the planning platform — a cpu-warm surface says nothing about
    # the tunnel cache (obs/compile.CompileModel)
    from tpu_reductions.obs.compile import DEFAULT_LEDGER, ENV_PATH
    compile_ledger = ns.compile_ledger \
        or os.environ.get(ENV_PATH) or DEFAULT_LEDGER
    priors = Priors.from_ledgers(
        history, compile_ledger=compile_ledger,
        platform=("cpu" if ns.platform == "cpu" else "tpu"))
    return active, excluded, meta, priors


def _emit_next(entry, emit: str) -> None:
    t = entry.task
    if emit == "shell":
        print(f"SCHED_TASK_SLUG={shlex.quote(t.name)}")
        print(f"SCHED_TASK_NAME={shlex.quote(t.title)}")
        print(f"SCHED_TASK_BUDGET={int(t.budget_s)}")
        print(f"SCHED_TASK_ARTIFACTS={shlex.quote(' '.join(t.artifacts))}")
        print(f"SCHED_TASK_CMD={shlex.quote(t.command)}")
    else:
        print(f"{t.name} (budget {int(t.budget_s)}s, est "
              f"{entry.est_s:.1f}s): {t.command}")


def main(argv=None) -> int:
    ns = _build_parser().parse_args(argv)
    modes = sum((ns.plan_only, ns.next_, ns.record is not None))
    if modes > 1:
        print("sched: --plan-only / --next / --record are exclusive",
              file=sys.stderr)
        return 2
    active, excluded, meta, priors = _active(ns)

    if ns.plan_only:
        state = PlanState(ns.state, meta, readonly=True)
        p = planner.plan(active, state, priors)
        print(planner.render_table(p))
        for t in excluded:
            print(f"   {t.name:<18} -- skipped: chip-only "
                  "(rehearsal profile)")
        return 0

    if ns.next_ or ns.record is not None:
        ledger.arm()   # one-shot modes append to the session's ledger
    else:
        # full run: the session must open BEFORE the plan state's
        # first persist so the timeline attributes it correctly
        ledger.arm_session("sched",
                           argv=list(argv) if argv else sys.argv[1:])
    state = PlanState(ns.state, meta)

    if ns.record is not None:
        status = executor._status_for(ns.rc)
        priors.observe(ns.record, ns.elapsed)
        state.record_done(ns.record, ns.rc, ns.elapsed, status)
        ledger.emit("sched.done", task=ns.record, rc=ns.rc,
                    actual_s=round(ns.elapsed, 3), status=status)
        return 0

    if ns.next_:
        # captured BEFORE this invocation's own skip records: only a
        # plan that follows earlier picks/outcomes is a re-plan
        prior_activity = bool(state.tasks)
        for t in excluded:
            if not state.attempted(t.name):
                ledger.emit("sched.skip", task=t.name,
                            reason="chip-only")
                state.record_skip(t.name, "chip-only")
        state.reconcile(active)
        p = planner.plan(active, state, priors)
        executor.record_skips(p, state)
        executor.emit_plan(p, replan=prior_activity)
        entry = p.next_entry
        if entry is None:
            state.finalize()
            print("sched: plan complete", file=sys.stderr)
            return PLAN_COMPLETE_EXIT
        ledger.emit("sched.pick", task=entry.task.name,
                    est_s=round(entry.est_s, 1),
                    value=entry.task.value, fits=entry.fits)
        state.record_pick(entry.task, entry.est_s)
        _emit_next(entry, ns.emit)
        return 0

    # full plan-and-execute run (rehearsal + standalone windows)
    return executor.run_plan(active, state, priors, excluded=excluded)


if __name__ == "__main__":
    sys.exit(main())
