"""Greedy value/expected-second knapsack against the remaining window.

The planning rule, in value order (module sched docstring has the why):

  1. a task already settled this window (state), or whose completion
     artifact is fresh-complete (tasks.artifact_complete), leaves the
     plan — re-measuring costs live minutes and buys nothing;
  2. `requires` gates eligibility on the prerequisite having been
     ATTEMPTED this window (smoke vets lowering surfaces before the
     races that depend on them — a FAILED smoke still vetted);
  3. hazard tasks (4 GiB staging cells, the relay's proven killer) are
     eligible only once every non-hazard task is settled or planned —
     "hazard cells stay last" is an invariant, not a weight;
  4. everything else orders by value / expected-duration (sched/
     priors.py), the greedy knapsack: each entry is marked `fits`
     against the cumulative remaining-window estimate, but the TOP
     pick is always runnable — a pessimistic window prior must never
     idle an alive window (the estimate is a model; the relay
     answering right now is a fact).

Replanning is just calling plan() again: it is a pure function of
(registry, state, priors, now). The ranking itself is the shared
greedy knapsack core (sched/knapsack.py — ISSUE 6 generalized it out
of this module so the serving engine's batch scheduler and this
planner import ONE implementation).
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from typing import List, Optional, Sequence

from tpu_reductions.sched.knapsack import greedy_plan
from tpu_reductions.sched.priors import Priors
from tpu_reductions.sched.state import PlanState
from tpu_reductions.sched.tasks import Task, artifact_complete


@dataclass(frozen=True)
class PlanEntry:
    """One planned pick: the task plus the estimates that ranked it."""
    task: Task
    est_s: float
    ratio: float          # value / est_s — the greedy key
    fits: bool            # inside the cumulative remaining estimate
    cumulative_s: float
    compile: str = "-"    # cold/warm standing of the task's surfaces
    #                       (priors.compile_status — the compile
    #                       observatory's column, ISSUE 8)


@dataclass(frozen=True)
class Plan:
    """The ordered plan + the artifact-skips discovered while planning
    (the caller records them: planning is pure, recording is not)."""
    entries: List[PlanEntry]
    remaining_s: float
    skips: List[tuple]    # (task_name, reason)

    @property
    def next_entry(self) -> Optional[PlanEntry]:
        return self.entries[0] if self.entries else None


def plan(tasks: Sequence[Task], state: PlanState, priors: Priors,
         now: Optional[float] = None) -> Plan:
    """Build the current plan (module docstring has the rules)."""
    now = time.time() if now is None else now
    remaining = priors.remaining_s(state.window_t0, now)
    skips: List[tuple] = []
    open_tasks: List[Task] = []
    for t in tasks:
        if state.settled(t.name):
            continue
        if t.done_artifact and artifact_complete(t.done_artifact,
                                                 state.window_t0):
            skips.append((t.name, "artifact-complete"))
            continue
        open_tasks.append(t)
    attempted_or_skipped = {t.name for t in tasks
                            if state.attempted(t.name)}
    attempted_or_skipped.update(name for name, _ in skips)
    in_registry = {t.name for t in tasks}

    def eligible(t: Task) -> bool:
        # a prerequisite absent from the active registry (--only
        # filter, rehearsal profile) can never be attempted — it must
        # not deadlock the tasks behind it
        return all(r in attempted_or_skipped or r not in in_registry
                   for r in t.requires)

    normal = [t for t in open_tasks if not t.hazard and eligible(t)]
    # requires-blocked tasks still belong in the printed plan (after
    # their prerequisites); order the pools separately then concatenate
    blocked = [t for t in open_tasks if not t.hazard and not eligible(t)]
    hazard = [t for t in open_tasks if t.hazard]

    ranked = greedy_plan([normal, blocked, hazard],
                         value=lambda t: t.value,
                         cost=priors.estimate,
                         budget_s=remaining,
                         tie_key=lambda t: t.name)
    entries = [PlanEntry(task=r.item, est_s=r.cost, ratio=r.ratio,
                         fits=r.fits, cumulative_s=r.cumulative,
                         compile=priors.compile_status(r.item))
               for r in ranked]
    return Plan(entries=entries, remaining_s=remaining, skips=skips)


def render_table(p: Plan) -> str:
    """The --plan-only table: stable for a given (registry, priors,
    state) — the acceptance contract prints it twice and diffs."""
    lines = [f"{'#':>2} {'task':<18} {'value':>7} {'est s':>8} "
             f"{'val/s':>8} {'cum s':>8} {'compile':>7} fits"]
    for i, e in enumerate(p.entries):
        flag = "yes" if e.fits else "no"
        if e.task.hazard:
            flag += " [hazard:last]"
        lines.append(f"{i:>2} {e.task.name:<18} {e.task.value:>7.0f} "
                     f"{e.est_s:>8.1f} {e.ratio:>8.3f} "
                     f"{e.cumulative_s:>8.1f} {e.compile:>7} {flag}")
    for name, reason in p.skips:
        lines.append(f"   {name:<18} -- skipped: {reason}")
    lines.append(f"remaining-window estimate: {p.remaining_s:.1f} s "
                 f"({len(p.entries)} task(s) planned)")
    return "\n".join(lines)
