"""Plan-and-execute loop: spend the window, persist every decision.

Each picked task runs as a SUBPROCESS (`bash -c <task.command>`) so the
existing protection stack applies unchanged: the task's own entry point
arms the flight recorder + watchdog (`maybe_arm_for_tpu` — socket gate,
preflight wedge gate, heartbeat hang trigger) and persists its rows per
the bench/resume discipline. The executor itself NEVER imports jax: a
dead relay can hang the axon plugin, and the planner must keep working
exactly then.

Budget enforcement mirrors scripts/chip_session.sh's
`timeout --signal=INT --kill-after=120`: SIGINT first to the task's
process group (python raises KeyboardInterrupt; per-row persistence and
the drivers' queue drains run — killing mid-device-queue can wedge the
chip, CLAUDE.md), escalating to SIGTERM and only then a hard kill after
the grace (TPU_REDUCTIONS_SCHED_KILL_GRACE_S compresses it for tests).

Window-death contract: a task exiting 3 (dead relay) or 4 (hang — both
from utils/watchdog.py) ends the window: the plan state persists the
abort and the executor exits with the SAME code, so the watcher layer
(scripts/await_window.sh) re-arms exactly as it does for a died
session — and the next invocation RESUMES the plan (sched/state.py).
Between tasks the executor re-probes the relay (pure sockets,
utils/watchdog.relay_alive) the way chip_session's per-step gate does.

Every decision is a typed ledger event — `sched.plan`, `sched.pick`,
`sched.skip`, `sched.done`, `sched.replan` (registered in
lint/grammar.py, attributed by obs/timeline.py) — so every window
commits a plan-vs-actual record.
"""

from __future__ import annotations

import os
import signal
import subprocess
import sys
import time
from typing import List, Optional, Sequence

from tpu_reductions.faults.inject import fault_point
from tpu_reductions.obs import ledger, trace
from tpu_reductions.sched import planner
from tpu_reductions.sched.priors import Priors
from tpu_reductions.sched.state import PlanState
from tpu_reductions.sched.tasks import Task
from tpu_reductions.utils.watchdog import (HANG_EXIT_CODE,
                                           WATCHDOG_EXIT_CODE,
                                           relay_alive,
                                           tunneled_environment)

WINDOW_DEATH_CODES = (WATCHDOG_EXIT_CODE, HANG_EXIT_CODE)
PLAN_COMPLETE_RC = 0


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ[name])
    except (KeyError, ValueError):
        return default


def _log(msg: str) -> None:
    print(f"sched: {msg}", file=sys.stderr, flush=True)


def run_task(task: Task, budget_s: Optional[float] = None,
             env: Optional[dict] = None) -> int:
    """One task subprocess under the INT-first budget discipline
    (module docstring); returns its exit code (124 = budget cut, the
    `timeout` convention chip_session's step() already maps)."""
    budget = float(budget_s if budget_s is not None else task.budget_s)
    grace = _env_float("TPU_REDUCTIONS_SCHED_KILL_GRACE_S", 120.0)
    proc = subprocess.Popen(["bash", "-c", task.command],
                            env=env, start_new_session=True)
    try:
        return proc.wait(timeout=budget)
    except subprocess.TimeoutExpired:
        pass
    _log(f"task {task.name} hit its {budget:.0f}s budget: SIGINT "
         "(drain-first discipline)")
    for sig, wait_s in ((signal.SIGINT, grace),
                        (signal.SIGTERM, grace / 4 + 1)):
        try:
            os.killpg(proc.pid, sig)
        except (ProcessLookupError, PermissionError):
            break
        try:
            proc.wait(timeout=wait_s)
            break
        except subprocess.TimeoutExpired:
            continue
    if proc.poll() is None:
        # the backstop for a process too wedged to honor the interrupt
        # (chip_session's --kill-after analog); nothing on the chip can
        # still be in flight through a relay this dead
        proc.kill()
        proc.wait()
    return 124


def _status_for(rc: int) -> str:
    if rc == 0:
        return "done"
    if rc in WINDOW_DEATH_CODES:
        return "aborted"
    if rc == 124:
        return "budget-cut"
    return "failed"


def record_skips(p: planner.Plan, state: PlanState) -> None:
    """Persist + emit the artifact-skips a planning pass discovered
    (planning is pure; recording happens here, once per skip)."""
    for name, reason in p.skips:
        ledger.emit("sched.skip", task=name, reason=reason)
        state.record_skip(name, reason)


def emit_plan(p: planner.Plan, replan: bool) -> None:
    ledger.emit("sched.replan" if replan else "sched.plan",
                tasks=[e.task.name for e in p.entries],
                est_s=[round(e.est_s, 1) for e in p.entries],
                remaining_s=round(p.remaining_s, 1))


def run_plan(tasks: Sequence[Task], state: PlanState, priors: Priors,
             excluded: Sequence[Task] = (),
             env: Optional[dict] = None,
             _run=run_task) -> int:
    """The loop: reconcile -> plan -> pick -> run -> record -> replan,
    until the plan runs dry (finalize, exit 0) or the window dies
    (exit 3/4, plan state resumable). `_run` is injectable for
    tests."""
    # trace continuity (ISSUE 12): a resumed plan whose prior
    # invocation died mid-task (an "aborted" record, or a "picked" one
    # the death left unsettled) marks the seam with an explicit
    # trace.cut — the export closes the torn spans there, and the work
    # below continues under the SAME trace when the re-invocation
    # inherited TPU_REDUCTIONS_TRACE_CTX
    torn = sorted(n for n, rec in state.tasks.items()
                  if rec.get("status") in ("aborted", "picked"))
    if torn:
        trace.cut("window-death-resume", tasks=torn)
    for t in excluded:
        if not state.attempted(t.name):
            ledger.emit("sched.skip", task=t.name, reason="chip-only")
            state.record_skip(t.name, "chip-only")
    reconciled = state.reconcile(tasks)
    for name in reconciled:
        _log(f"task {name} reconciled: its artifact completed before "
             "the last death; not re-measured")
    env = dict(env if env is not None else os.environ)
    # the window epoch doubles as FIRSTROW_T0 for task commands that
    # reference it (headline_bench's doubles-suppression mtime check)
    env.setdefault("FIRSTROW_T0", f"{state.window_t0:.2f}")
    # cross-process propagation: every task subprocess parents its
    # events under the executor's span via TPU_REDUCTIONS_TRACE_CTX
    # (obs/trace.py adopts it at arm time) — one trace per session
    env.update(trace.propagation_env())
    replan = False
    while True:
        p = planner.plan(tasks, state, priors)
        record_skips(p, state)
        emit_plan(p, replan)
        replan = True
        entry = p.next_entry
        if entry is None:
            state.finalize()
            _log("plan complete: every task settled or skipped")
            return PLAN_COMPLETE_RC
        if tunneled_environment() and not relay_alive():
            # chip_session's between-steps gate, executor edition: the
            # relay died between tasks — stop with the plan resumable
            _log("relay dead between tasks; plan state persisted for "
                 "the next window")
            ledger.emit("sched.done", task=entry.task.name,
                        status="not-started", reason="relay-dead")
            return WATCHDOG_EXIT_CODE
        # chaos seam (faults/inject.py): the `sched.task` point fires
        # between pick and launch — a scripted raise/stall/exit here is
        # the deterministic spelling of "the executor died mid-plan"
        fault_point("sched.task")
        ledger.emit("sched.pick", task=entry.task.name,
                    est_s=round(entry.est_s, 1),
                    value=entry.task.value,
                    fits=entry.fits)
        state.record_pick(entry.task, entry.est_s)
        if not entry.fits:
            _log(f"pick {entry.task.name} does not fit the remaining-"
                 f"window estimate ({p.remaining_s:.0f}s) — running "
                 "anyway: the relay answering is a fact, the estimate "
                 "is a model")
        t0 = time.monotonic()
        rc = _run(entry.task, env=env)
        actual = time.monotonic() - t0
        status = _status_for(rc)
        if rc not in WINDOW_DEATH_CODES:
            # an aborted task's duration is the WINDOW's length, not
            # the task's — feeding it to the priors would teach the
            # planner that dying is fast
            priors.observe(entry.task.name, actual)
        state.record_done(entry.task.name, rc, actual, status)
        ledger.emit("sched.done", task=entry.task.name, rc=rc,
                    actual_s=round(actual, 3),
                    planned_s=round(entry.est_s, 1), status=status)
        _log(f"task {entry.task.name}: {status} rc={rc} "
             f"({actual:.1f}s vs {entry.est_s:.1f}s planned)")
        if rc in WINDOW_DEATH_CODES:
            _log(f"window death (rc={rc}); plan state persisted — "
                 "re-invocation resumes the remaining tasks")
            return rc
