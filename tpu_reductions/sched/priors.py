"""Duration priors + window-length model, learned from ledger history.

The static step budgets of scripts/chip_session.sh encode what a step
is ALLOWED to take; planning needs what it WILL take. Both answers are
already on disk: every window since PR 4 commits a flight-recorder
ledger (obs/ledger.py) whose `step.start`/`step.end` pairs time each
step and whose `sched.done` events (this PR) time each planned task,
and the ledger's own event-time clusters record how long the relay's
live windows actually lasted (round 4: ~6 min — CLAUDE.md). This
module turns that history into:

  * `estimate(task)` — median observed duration for the task (keyed by
    slug, falling back to the chip_session step title the pre-scheduler
    ledgers used), else the registry's static budget_s — the cold-start
    fallback the ISSUE requires. Durations observed THIS window
    (`observe`, fed by the executor as tasks finish) take precedence:
    the online update. The static fallback additionally consults the
    compile observatory (obs/compile.CompileModel over the committed
    compile_ledger.json, ISSUE 8): a task whose declared surfaces are
    ALL cache-warm sheds the cold-compile seconds the cache banked —
    the budget_s priors were written for cold windows, and charging a
    warm surface 20-40 s of tunnel compile mis-ranks it in a
    minutes-long window. History medians are left alone: they already
    embed whatever compile cost their windows actually paid.
  * `window_quantile(q)` / `remaining_s(window_t0)` — a quantile model
    over recorded window lengths (event clusters split at
    WINDOW_GAP_S); with no history the prior is the observed round-4
    flap (DEFAULT_WINDOW_S). remaining_s never goes below zero — the
    planner treats an outlived estimate as "every further second is a
    bonus" and keeps picking by ratio (sched/planner.py).

Purely offline: reads JSONL files, touches no device, imports no jax.
"""

from __future__ import annotations

import os
from typing import Dict, Iterable, List, Optional, Sequence

from tpu_reductions.obs.timeline import read_ledger
from tpu_reductions.sched.tasks import Task

# the one observed full flap length (round 4, 2026-07-31: relay up
# 03:43Z, dead ~03:49Z) — the cold-start window prior
DEFAULT_WINDOW_S = 360.0
# event-time gap that splits ledger history into distinct windows: the
# watcher polls every ~20 s while idle, so anything past 30 min of
# silence is a new window, not a slow step
WINDOW_GAP_S = 1800.0


def scan_history(paths: Iterable[str]) -> dict:
    """Parse ledger files into {'durations': {name: [s, ...]},
    'windows': [s, ...], 'spans': {name: [s, ...]}}. Unreadable/empty
    files are skipped — history is an optimization, never a failure.
    The 'spans' pool is the causal-trace evidence (ISSUE 12;
    obs/critical_path.span_medians): per-span-name durations from the
    reconstructed tree, the sub-task axis `Priors.span_median` serves
    back to any consumer that wants finer grain than whole tasks.
    read_ledger stitches a rotated `<path>.1` segment automatically."""
    durations: Dict[str, List[float]] = {}
    windows: List[float] = []
    spans: Dict[str, List[float]] = {}
    for path in paths:
        if not path or not os.path.exists(path):
            continue
        try:
            events, _torn = read_ledger(path)
        except OSError:
            continue
        if not events:
            continue
        _scan_durations(events, durations)
        windows.extend(_cluster_windows(events))
        _scan_spans(events, spans)
    return {"durations": durations, "windows": windows, "spans": spans}


def _scan_durations(events: Sequence[dict],
                    durations: Dict[str, List[float]]) -> None:
    """step.start/step.end pairs (pre-scheduler sessions, keyed by the
    step title) and sched.done events (which carry their own actual_s)
    both feed the same sample pool."""
    pending: Dict[str, float] = {}
    for e in events:
        ev = e.get("ev")
        if ev == "step.start" and isinstance(e.get("name"), str):
            pending[e["name"]] = e["t"]
        elif ev == "step.end" and isinstance(e.get("name"), str):
            t0 = pending.pop(e["name"], None)
            if t0 is not None and e["t"] > t0:
                durations.setdefault(e["name"], []).append(e["t"] - t0)
        elif ev == "sched.done" and isinstance(e.get("task"), str):
            a = e.get("actual_s")
            if isinstance(a, (int, float)) and a > 0:
                durations.setdefault(e["task"], []).append(float(a))


def _scan_spans(events: Sequence[dict],
                spans: Dict[str, List[float]]) -> None:
    """Fold one ledger's reconstructed span durations into the pool
    (cut/synthetic closes excluded — a span the death clipped is not a
    duration sample)."""
    try:
        from tpu_reductions.obs.critical_path import span_medians
        for name, med in span_medians(events).items():
            spans.setdefault(name, []).append(med)
    except Exception:
        # span evidence is gravy: a malformed ledger must not stop the
        # planner from estimating with the coarser pools
        pass


def _cluster_windows(events: Sequence[dict]) -> List[float]:
    """Window lengths from event-time clusters: consecutive events more
    than WINDOW_GAP_S apart start a new window. Zero-length clusters
    (a lone probe event) are dropped — they are watcher heartbeats,
    not windows."""
    out: List[float] = []
    start = prev = events[0]["t"]
    for e in events[1:]:
        if e["t"] - prev > WINDOW_GAP_S:
            if prev > start:
                out.append(prev - start)
            start = e["t"]
        prev = e["t"]
    if prev > start:
        out.append(prev - start)
    return out


def _median(vals: Sequence[float]) -> float:
    s = sorted(vals)
    mid = len(s) // 2
    return s[mid] if len(s) % 2 else (s[mid - 1] + s[mid]) / 2.0


def _quantile(vals: Sequence[float], q: float) -> float:
    s = sorted(vals)
    if not s:
        raise ValueError("quantile of empty history")
    idx = min(len(s) - 1, max(0, int(round(q * (len(s) - 1)))))
    return s[idx]


class Priors:
    """The planner's cost model: per-task duration estimates + the
    remaining-window estimate, updated online as tasks finish, with
    the compile observatory's cold/warm axis folded into the static
    fallback (module docstring)."""

    def __init__(self, history: Optional[dict] = None,
                 compile_model=None) -> None:
        history = history or {"durations": {}, "windows": []}
        self._durations: Dict[str, List[float]] = {
            k: list(v) for k, v in history.get("durations", {}).items()}
        self._windows: List[float] = list(history.get("windows", []))
        self._spans: Dict[str, List[float]] = {
            k: list(v) for k, v in history.get("spans", {}).items()}
        self._online: Dict[str, float] = {}
        self._compile = compile_model   # obs/compile.CompileModel

    @classmethod
    def from_ledgers(cls, paths: Iterable[str],
                     compile_ledger: Optional[str] = None,
                     platform: Optional[str] = None) -> "Priors":
        """Build from committed ledger histories (CLI default:
        obs_ledger.jsonl in the cwd; --history adds more) plus, when
        `compile_ledger` names a committed compile_ledger.json, the
        observatory's cold/warm model filtered to `platform`'s rows."""
        model = None
        if compile_ledger:
            from tpu_reductions.obs.compile import CompileModel
            model = CompileModel.from_file(compile_ledger,
                                           platform=platform)
        return cls(scan_history(paths), compile_model=model)

    def observe(self, name: str, seconds: float) -> None:
        """Online update: a task finished this window — its actual
        duration becomes the sharpest estimate for a re-pick (retries
        after a budget cut) and joins the sample pool for any ledger
        scan a LATER window performs."""
        if seconds > 0:
            self._online[name] = seconds
            self._durations.setdefault(name, []).append(seconds)

    def estimate(self, task: Task) -> float:
        """Expected duration: this window's observation, else the
        history median (slug first, then the chip_session step title
        the pre-scheduler ledgers keyed on), else the static budget —
        discounted by the cache-banked compile seconds when every
        surface the task declares is warm (module docstring; the floor
        keeps a mis-declared surface list from zeroing an estimate)."""
        if task.name in self._online:
            return self._online[task.name]
        for key in (task.name, task.title):
            samples = self._durations.get(key)
            if samples:
                return _median(samples)
        base = float(task.budget_s)
        if self._compile is not None and task.surfaces and \
                self._compile.status(task.surfaces) == "warm":
            saved = self._compile.saved_s(task.surfaces)
            if saved > 0:
                base = max(base - saved, 0.25 * float(task.budget_s))
        return base

    def compile_status(self, task: Task) -> str:
        """The task's cold/warm standing for the plan table
        (sched/planner.render_table): 'warm'/'cold'/'mixed' from the
        compile observatory, '-' when the task declares no surfaces or
        no model is loaded."""
        if self._compile is None or not task.surfaces:
            return "-"
        return self._compile.status(task.surfaces)

    def span_median(self, name: str) -> Optional[float]:
        """Median duration for one span name across the scanned ledger
        history (ISSUE 12: the sub-task evidence the causal trace adds
        — e.g. the 'compile' span median prices a cold surface with
        MEASURED tunnel-compile seconds instead of the static 20-40 s
        folklore). None when the history never saw the span."""
        samples = self._spans.get(name)
        return _median(samples) if samples else None

    def window_quantile(self, q: float = 0.5) -> float:
        """The window-length model: quantile of recorded flap history,
        DEFAULT_WINDOW_S when no history exists."""
        if not self._windows:
            return DEFAULT_WINDOW_S
        return _quantile(self._windows, q)

    def remaining_s(self, window_t0: float, now: float,
                    q: float = 0.5) -> float:
        """Expected seconds left in THIS window (never negative: an
        outlived window keeps planning — every further second is a
        bonus, see module docstring)."""
        return max(0.0, self.window_quantile(q) - max(0.0, now - window_t0))
