"""Greedy value/expected-cost ranking — the ONE knapsack core.

PR 5's window planner (sched/planner.py) ranks measurement tasks by
value per expected second against the remaining-window estimate; the
serving engine (tpu_reductions/serve/, ISSUE 6) ranks coalesced
request batches by value per expected device-second against a
per-round device-time window. Same algorithm, different nouns — so it
lives here ONCE, parameterized by (value, expected-cost, budget), and
both schedulers import it instead of forking it (the ISSUE 6
satellite contract).

Properties both callers rely on:

  * **Pure.** No clocks, no I/O, no globals: `greedy_plan` is a
    function of its arguments, so replanning is just calling it again
    (the sched/planner.py doctrine) and the serve batcher can plan
    every round without synchronization.
  * **jax-free.** sched/ plans with the relay dead; serve/ admits
    while the device is busy. Neither may pay a jax import
    (redlint RED014 additionally bans device work in serve/ outside
    its executor module).
  * **Top pick always runnable.** `fits` is advisory: a pessimistic
    cost model must never idle an alive window / an idle device — the
    caller launches the top entry regardless (sched/planner.py rule 4;
    serve/coalesce.plan_round applies the same rule to batches).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable, Iterable, List, Sequence

# guard against a zero/negative cost estimate blowing up the ratio:
# the same floor sched/planner.py has always used
_MIN_COST = 1e-9


@dataclass(frozen=True)
class Ranked:
    """One planned pick: the item plus the estimates that ranked it."""
    item: object
    cost: float           # expected cost (seconds, for both callers)
    ratio: float          # value / cost — the greedy key
    fits: bool            # inside the cumulative budget
    cumulative: float     # running cost total up to and including this


def rank_order(items: Iterable, *, value: Callable[[object], float],
               cost: Callable[[object], float],
               tie_key: Callable[[object], object] = str) -> List:
    """Order one pool by descending value/cost ratio (value, then
    tie_key break ties deterministically — the planner's stable-table
    contract)."""
    return sorted(items,
                  key=lambda it: (-value(it) / max(cost(it), _MIN_COST),
                                  -value(it), tie_key(it)))


def mark_fits(ordered: Sequence, *, value: Callable[[object], float],
              cost: Callable[[object], float],
              budget_s: float) -> List[Ranked]:
    """Annotate an already-ordered sequence with cumulative cost and
    the fits flag against `budget_s` (one shared budget line across the
    whole sequence, however many pools it was ordered from)."""
    out: List[Ranked] = []
    cum = 0.0
    for it in ordered:
        c = cost(it)
        cum += c
        out.append(Ranked(item=it, cost=c,
                          ratio=value(it) / max(c, _MIN_COST),
                          fits=cum <= budget_s, cumulative=cum))
    return out


def greedy_plan(pools: Sequence[Iterable], *,
                value: Callable[[object], float],
                cost: Callable[[object], float],
                budget_s: float,
                tie_key: Callable[[object], object] = str
                ) -> List[Ranked]:
    """The full greedy knapsack: rank each pool independently by
    value/cost, concatenate pools in the order given (the planner's
    normal -> requires-blocked -> hazard tiers; serve passes a single
    pool), and mark fits against one cumulative budget."""
    ordered = [it for pool in pools
               for it in rank_order(pool, value=value, cost=cost,
                                    tie_key=tie_key)]
    return mark_fits(ordered, value=value, cost=cost, budget_s=budget_s)
