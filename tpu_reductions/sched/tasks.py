"""Task registry — the measurement units a live window can spend on.

Each Task is one committed-artifact unit of the round-5 session
(scripts/chip_session.sh kept the same commands; THIS module owns their
budgets, values and ordering inputs). The registry is the single
sanctioned home of hardcoded wall-clock budgets and step orderings —
redlint RED013 (docs/LINT.md) keeps stray copies out of the rest of
the tree, with reason-waivers only on chip_session.sh's no-scheduler
fallback path.

Value scores encode the round verdicts, not wall-clock: the firstrow
headline (round-4 do-this #3) dominates everything; the DOUBLE
scoreboard (three rounds the #1 gap) outranks the races; hazard cells
(the 4 GiB staging payloads that killed both round-2 windows) are
eligible strictly last regardless of ratio. The planner
(sched/planner.py) divides value by the duration PRIOR (sched/
priors.py — learned from ledger history, this registry's budget_s as
the cold-start fallback), so the actual pick order adapts per window.

Completion predicates read the bench/resume artifact contract: an
artifact marked `complete: true` whose mtime falls inside THIS window
(>= the plan state's window_t0) means the unit's evidence already
landed — re-measuring it would spend live minutes on redundant rows
(the per-window freshness rule of scripts/chip_session.sh's
BENCH_DOUBLES suppression, generalized).
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple


@dataclass(frozen=True)
class Task:
    """One schedulable measurement unit (module docstring has the field
    semantics)."""
    name: str                       # slug: plan-state / ledger identity
    title: str                      # chip_session step display name
    value: float                    # window-value score (verdict-derived)
    budget_s: float                 # wall-clock cap AND cold-start prior
    command: str                    # bash -c body (the session command)
    artifacts: Tuple[str, ...]      # per-step commit set
    done_artifact: Optional[str] = None   # complete:true here => skip
    hazard: bool = False            # 4 GiB cells: strictly last
    chip_only: bool = False         # excluded from cpu rehearsal plans
    requires: Tuple[str, ...] = ()  # must be attempted first
    rehearsal_command: Optional[str] = None   # cpu-scale variant
    surfaces: Tuple[str, ...] = ()  # compile-observatory surface ids
    #                                 this task's executables hit
    #                                 (obs/compile.py): all cache-warm
    #                                 => the cheap duration prior
    #                                 (sched/priors.py, ISSUE 8)


def artifact_complete(path: str, window_t0: float) -> bool:
    """The completion predicate: `path` parses, carries
    `complete: true`, and was written this window (mtime >= window_t0;
    a complete artifact committed by a PREVIOUS window must not
    suppress this window's fresh rows — the chip_session BENCH_DOUBLES
    rule)."""
    try:
        if os.path.getmtime(path) < window_t0:
            return False
        data = json.loads(open(path).read())
    except (OSError, ValueError):
        return False
    return isinstance(data, dict) and data.get("complete") is True


# ---------------------------------------------------------------------------
# The session registry. Commands are the round-5 session's, verbatim
# (scripts/chip_session.sh history); budgets are the former static step
# budgets, now demoted to cold-start priors + hard caps.
# ---------------------------------------------------------------------------

_HEADLINE_CMD = (
    "set -o pipefail; d=1; "
    'if grep -q "\\"complete\\": true" BENCH_doubles.json 2>/dev/null '
    '&& grep -q "\\"status\\": \\"PASSED\\"" BENCH_doubles.json 2>/dev/null '
    '&& [ "$(stat -c %Y BENCH_doubles.json)" -ge "${FIRSTROW_T0%.*}" ]; '
    "then d=0; fi; "
    "BENCH_SKIP_PROBE=1 BENCH_DOUBLES=$d python bench.py | tee BENCH_live.json")

_INT_OP_CMD = (
    "rc=0; "
    "python -m tpu_reductions.bench.spot --type=int "
    "--methods=SUM,MIN,MAX --n=16777216 --kernel=7 --threads=384 "
    "--iterations=256 --chainreps=5 --out=int_op_spot_k7.json || rc=$?; "
    "python -m tpu_reductions.bench.spot --type=int "
    "--methods=SUM,MIN,MAX --n=16777216 --kernel=6 --threads=512 "
    "--iterations=256 --chainreps=5 --out=int_op_spot_k6.json || rc=$?; "
    "python -m tpu_reductions.bench.spot --type=int "
    "--methods=SUM,MIN,MAX --n=16777216 --backend=xla "
    "--iterations=256 --chainreps=5 --out=int_op_spot_xla.json || rc=$?; "
    "exit $rc")

_MXU_F32_CMD = (
    "rc=0; "
    "python -m tpu_reductions.bench.autotune --method=SUM --type=float "
    "--n=16777216 --iterations=256 --grid=mxu --comparator "
    "--out=tune_mxu_f32.json || rc=$?; "
    "python -m tpu_reductions.bench.autotune --method=SUM --type=float "
    "--n=67108864 --grid=mxu --comparator "
    "--out=tune_mxu_f32_hbm.json || rc=$?; "
    "exit $rc")

# cpu rehearsal scale: tiny n / few reps so a full-plan DRYRUN finishes
# in ~a minute on the 8-device virtual platform (tests/conftest.py)
_R = "--platform=cpu --n=65536 --iterations=16 --chainreps=2"

SESSION_TASKS: Tuple[Task, ...] = (
    Task("firstrow", "first row", value=1000.0, budget_s=300,
         command="python -m tpu_reductions.bench.firstrow",
         rehearsal_command=("python -m tpu_reductions.bench.firstrow "
                            f"{_R} --skip-doubles"),
         artifacts=("FIRSTROW.json", "BENCH_snapshot.json",
                    "BENCH_doubles.json"),
         done_artifact="FIRSTROW.json", surfaces=("k7", "dd")),
    Task("headline_bench", "headline bench", value=400.0, budget_s=240,
         command=_HEADLINE_CMD,
         artifacts=("BENCH_live.json", "BENCH_snapshot.json",
                    "BENCH_doubles.json"),
         chip_only=True,   # bench.py is the real-chip round metric
         requires=("firstrow",), surfaces=("k7", "dd")),
    Task("double_spot", "double scoreboard", value=360.0, budget_s=300,
         command=("python -m tpu_reductions.bench.spot --type=double "
                  "--methods=SUM,MIN,MAX --n=16777216 --iterations=256 "
                  "--chainreps=5 --out=double_spot.json"),
         rehearsal_command=("python -m tpu_reductions.bench.spot "
                            f"--type=double --methods=SUM,MIN,MAX {_R} "
                            "--out=double_spot.json"),
         artifacts=("double_spot.json",),
         done_artifact="double_spot.json", surfaces=("dd",)),
    Task("calibrate_ladder", "calibration ladder", value=260.0,
         budget_s=240,
         command=("python -m tpu_reductions.utils.calibrate --ladder "
                  "--chainspan 256 --reps 7 --out=calibration_live.json"),
         rehearsal_command=("python -m tpu_reductions.utils.calibrate "
                            "--ladder --platform=cpu --n=65536 "
                            "--chainspan 16 --reps 2 "
                            "--out=calibration_live.json"),
         artifacts=("calibration_live.json",),
         done_artifact="calibration_live.json", surfaces=("xla",)),
    Task("smoke", "lowering smoke", value=240.0, budget_s=420,
         command="python -m tpu_reductions.bench.smoke --out=smoke.json",
         rehearsal_command=("python -m tpu_reductions.bench.smoke "
                            "--platform=cpu --out=smoke.json"),
         artifacts=("smoke.json",),
         done_artifact="smoke.json",
         surfaces=("k8", "k9", "k10@2", "k10@4", "k10@8", "dd")),
    Task("hbm26", "hbm regime race 2^26", value=200.0, budget_s=420,
         command=("python -m tpu_reductions.bench.autotune --method=SUM "
                  "--type=int --n=67108864 --grid=hbm --comparator "
                  "--out=tune_hbm.json"),
         artifacts=("tune_hbm.json",), done_artifact="tune_hbm.json",
         chip_only=True, requires=("smoke",),
         surfaces=("k8", "k10@2", "k10@4", "k10@8")),
    Task("hbm27", "hbm regime race 2^27", value=180.0, budget_s=420,
         command=("python -m tpu_reductions.bench.autotune --method=SUM "
                  "--type=int --n=134217728 --grid=hbm --comparator "
                  "--out=tune_hbm27.json"),
         artifacts=("tune_hbm27.json",), done_artifact="tune_hbm27.json",
         chip_only=True, requires=("smoke",),
         surfaces=("k8", "k10@2", "k10@4", "k10@8")),
    Task("int_op_parity", "int op parity probe", value=160.0,
         budget_s=420, command=_INT_OP_CMD,
         artifacts=("int_op_spot_k7.json", "int_op_spot_k6.json",
                    "int_op_spot_xla.json"),
         done_artifact="int_op_spot_xla.json",
         chip_only=True, requires=("smoke",),
         surfaces=("k6", "k7", "xla")),
    Task("stream_probe", "streaming pipeline probe", value=170.0,
         budget_s=300,
         # 1 GiB int32 through 64 MiB chunks: 16 chunks of double-
         # buffered transfer/fold overlap, partial fetched every 4 —
         # the first on-chip evidence for the pipeline that erases the
         # 4 GiB staging hazard (ISSUE 7; docs/STREAMING.md). The
         # serial comparator stays off on chip (its per-chunk forced
         # fetch pays a tunnel RTT each; overlap efficiency is the
         # off-chip rehearsal's number). The ONE committed probe lives
         # in the experiment dir (the PR-6 serving_curve dedup rule —
         # bench/regen.py folds it from there); the rehearsal writes to
         # its sandbox cwd, which has no examples/ tree
         command=("python -m tpu_reductions.bench.stream --method=SUM "
                  "--type=int --n=268435456 --chunk-bytes=67108864 "
                  "--sync-every=4 "
                  "--out=examples/tpu_run/stream_probe.json"),
         rehearsal_command=("python -m tpu_reductions.bench.stream "
                            "--method=SUM --type=int --platform=cpu "
                            "--n=1048576 --chunk-bytes=65536 "
                            "--sync-every=4 --serial-baseline "
                            "--out=stream_probe.json"),
         artifacts=("examples/tpu_run/stream_probe.json",),
         done_artifact="examples/tpu_run/stream_probe.json",
         surfaces=("stream",)),
    Task("bf16_spot", "bf16 existence spot", value=150.0, budget_s=180,
         command=("python -m tpu_reductions.bench.spot --type=bfloat16 "
                  "--methods=SUM,MIN,MAX --n=16777216 --iterations=256 "
                  "--chainreps=5 --out=bf16_spot.json"),
         rehearsal_command=("python -m tpu_reductions.bench.spot "
                            f"--type=bfloat16 --methods=SUM,MIN,MAX {_R} "
                            "--out=bf16_spot.json"),
         artifacts=("bf16_spot.json",), done_artifact="bf16_spot.json",
         surfaces=("k6",)),
    Task("mxu_f32", "mxu race f32", value=120.0, budget_s=420,
         command=_MXU_F32_CMD,
         artifacts=("tune_mxu_f32.json", "tune_mxu_f32_hbm.json"),
         done_artifact="tune_mxu_f32_hbm.json",
         chip_only=True, requires=("smoke",), surfaces=("k9",)),
    Task("mxu_bf16", "mxu race bf16", value=100.0, budget_s=300,
         command=("python -m tpu_reductions.bench.autotune --method=SUM "
                  "--type=bfloat16 --n=16777216 --iterations=256 "
                  "--grid=mxu --comparator --out=tune_mxu_bf16.json"),
         artifacts=("tune_mxu_bf16.json",),
         done_artifact="tune_mxu_bf16.json",
         chip_only=True, requires=("smoke",), surfaces=("k9",)),
    Task("fine_race", "fine tile race", value=90.0, budget_s=420,
         command=("python -m tpu_reductions.bench.autotune --method=SUM "
                  "--type=int --n=16777216 --iterations=256 "
                  "--chainreps=7 --grid=fine --out=tune_fine.json"),
         rehearsal_command=("python -m tpu_reductions.bench.autotune "
                            "--method=SUM --type=int --platform=cpu "
                            "--n=65536 --iterations=16 --chainreps=2 "
                            "--grid=fine --out=tune_fine.json"),
         artifacts=("tune_fine.json",), done_artifact="tune_fine.json",
         requires=("smoke",), surfaces=("k6", "k7", "k8")),
    Task("quant_curve", "accuracy-vs-bandwidth curve", value=140.0,
         budget_s=300,
         # off-chip by design (virtual CPU mesh up to 64 ranks —
         # bench/quant_curve.py): safe with the relay dead, so it is
         # ideal flap-time filler; the committed artifact lives with
         # the rank-scaling evidence and bench/regen folds it into
         # report.md from there
         command=("python -m tpu_reductions.bench.quant_curve "
                  "--platform=cpu "
                  "--out=examples/rank_scaling/quant_curve.json"),
         rehearsal_command=("python -m tpu_reductions.bench.quant_curve "
                            "--platform=cpu --ranks=2,4,8 --n=262144 "
                            "--out=quant_curve.json"),
         artifacts=("examples/rank_scaling/quant_curve.json",),
         done_artifact="examples/rank_scaling/quant_curve.json"),
    Task("reshard_curve", "redistribution curve", value=120.0,
         budget_s=420,
         # off-chip by design (ISSUE 15; docs/RESHARD.md): the planner's
         # primitive programs run on the virtual CPU mesh up to 64 ranks
         # (bench/reshard_curve.py) — safe with the relay dead, so it is
         # flap-time filler like quant_curve; the committed artifact
         # lives with the rank-scaling evidence and bench/regen folds
         # reshard_curve_markdown into report.md from there
         command=("python -m tpu_reductions.bench.reshard_curve "
                  "--platform=cpu "
                  "--out=examples/rank_scaling/reshard_curve.json"),
         rehearsal_command=("python -m tpu_reductions.bench.reshard_curve "
                            "--platform=cpu --ranks=2,4 --n=262144 "
                            "--out=reshard_curve.json"),
         artifacts=("examples/rank_scaling/reshard_curve.json",),
         done_artifact="examples/rank_scaling/reshard_curve.json"),
    Task("serving_scale", "open-loop serving scale curve", value=110.0,
         budget_s=600,
         # off-chip by design (ISSUE 13; docs/SERVING.md scaling tier):
         # the open-loop grid drives in-process engines and the replica
         # router on --platform=cpu with the per-launch tunnel RTT
         # modeled through a local slow relay — safe with the relay
         # dead, so it is flap-time filler like quant_curve; the ONE
         # committed artifact lives in the experiment dir and
         # bench/regen folds scale_markdown into report.md from there
         command="bash scripts/run_serving_scale.sh",
         rehearsal_command=("python -m tpu_reductions.serve.loadgen "
                            "--platform=cpu --devices=8 --scale "
                            "--scale-clients=16,64 --replicas=2 "
                            "--n=8192 --skip-sharded "
                            "--out=serving_scale.json"),
         artifacts=("examples/tpu_run/serving_scale.json",),
         done_artifact="examples/tpu_run/serving_scale.json"),
    Task("serving_elastic", "elastic autoscaler curve", value=105.0,
         budget_s=600,
         # off-chip by design (ISSUE 17; docs/SERVING.md elastic
         # fleet): the diurnal open-loop plan drives in-process
         # engines behind the autoscaler on --platform=cpu with the
         # tunnel RTT modeled through a local slow relay, and the
         # drain's redistribution program runs on the virtual CPU
         # mesh — safe with the relay dead, flap-time filler like
         # serving_scale; the ONE committed artifact lives in the
         # experiment dir and bench/regen folds elastic_markdown into
         # report.md from there
         command="bash scripts/run_serving_elastic.sh",
         rehearsal_command=("python -m tpu_reductions.serve.loadgen "
                            "--platform=cpu --devices=8 --elastic "
                            "--scale-clients=64 --elastic-seconds=4 "
                            "--n=8192 "
                            "--out=serving_elastic.json"),
         artifacts=("examples/tpu_run/serving_elastic.json",),
         done_artifact="examples/tpu_run/serving_elastic.json"),
    Task("family_spot", "reduction-family spot", value=130.0,
         budget_s=300,
         # the family grid (ISSUE 20; docs/FAMILY.md): SCAN racing the
         # MXU matmul trick against the XLA cumsum, segmented reduce,
         # argmin/argmax — every cell chained-timed and oracle-verified,
         # plus the end-to-end serving proof rows. The committed
         # artifact is what exec/cost.pick_scan prices from, and
         # bench/regen folds family_spot_markdown into report.md; the
         # smoke gate must have lowered mxu-scan first
         command=("python -m tpu_reductions.bench.family_spot "
                  "--n=16777216 "
                  "--out=examples/tpu_run/family_spot.json"),
         rehearsal_command=("python -m tpu_reductions.bench.family_spot "
                            "--platform=cpu --n=131072 --serve-n=8192 "
                            "--reps=2 --out=family_spot.json"),
         artifacts=("examples/tpu_run/family_spot.json",),
         done_artifact="examples/tpu_run/family_spot.json",
         requires=("smoke",),
         surfaces=("mxu-scan", "xla-cumsum", "seg/segsum",
                   "argk/argmin")),
    Task("serving_recovery", "crash-recovery instrument", value=100.0,
         budget_s=420,
         # off-chip by design (ISSUE 18; docs/SERVING.md
         # crash-consistent control plane): a REAL journaled router
         # subprocess over ProcessReplica children dies via the
         # scripted router.crash os._exit and restarts against its
         # journal, then the in-process kill-replica / drain contrast
         # pair runs on the same seeded idem-keyed workload — all on
         # --platform=cpu, safe with the relay dead, flap-time filler
         # like the other serving curves; the ONE committed artifact
         # lives in the experiment dir and bench/regen folds
         # recovery_markdown into report.md from there
         command="bash scripts/run_serving_recovery.sh",
         rehearsal_command=("python -m tpu_reductions.serve.loadgen "
                            "--platform=cpu --recovery "
                            "--recovery-requests=24 --crash-after=8 "
                            "--n=8192 "
                            "--out=serving_recovery.json"),
         artifacts=("examples/tpu_run/serving_recovery.json",),
         done_artifact="examples/tpu_run/serving_recovery.json"),
    Task("flagship", "flagship experiment", value=300.0, budget_s=10800,
         command="bash scripts/run_tpu_experiment.sh examples/tpu_run",
         artifacts=("examples/tpu_run",),
         hazard=True,       # its tail is the 4 GiB HAZARD_CELLS
         chip_only=True, requires=("smoke", "calibrate_ladder"),
         surfaces=("k6", "k7", "dd", "xla")),
)


def registry(platform: Optional[str] = None,
             only: Optional[Sequence[str]] = None) -> List[Task]:
    """The active task list. `platform='cpu'` selects the rehearsal
    profile: chip-only tasks drop out (the executor records them
    skipped) and tasks with a rehearsal_command swap it in. `only`
    filters by slug — the focused-rehearsal seam."""
    out: List[Task] = []
    for t in SESSION_TASKS:
        if only is not None and t.name not in only:
            continue
        if platform == "cpu":
            if t.chip_only:
                continue
            if t.rehearsal_command:
                t = dataclasses.replace(t, command=t.rehearsal_command)
        out.append(t)
    return out


def rehearsal_excluded(platform: Optional[str] = None,
                       only: Optional[Sequence[str]] = None) -> List[Task]:
    """Chip-only tasks a cpu-rehearsal plan must record as SKIPPED
    (sched.skip reason='chip-only') instead of silently dropping —
    the no-silent-caps rule of the plan-vs-actual record."""
    if platform != "cpu":
        return []
    return [t for t in SESSION_TASKS if t.chip_only
            and (only is None or t.name in only)]


def load_tasks_file(path: str) -> List[Task]:
    """An explicit JSON registry (`--tasks=FILE`): a list of objects
    with the Task field names (value/budget_s/command/artifacts
    required). The chaos harness and the chip_session rehearsal tests
    drive toy registries through the REAL planner/executor this way."""
    data = json.loads(open(path).read())
    if not isinstance(data, list):
        raise ValueError(f"{path}: tasks file must be a JSON list")
    out = []
    for i, spec in enumerate(data):
        if not isinstance(spec, dict) or "name" not in spec:
            raise ValueError(f"{path}[{i}]: each task needs a 'name'")
        out.append(Task(
            name=spec["name"], title=spec.get("title", spec["name"]),
            value=float(spec.get("value", 1.0)),
            budget_s=float(spec.get("budget_s", 60.0)),
            command=spec.get("command", "true"),
            artifacts=tuple(spec.get("artifacts", ())),
            done_artifact=spec.get("done_artifact"),
            hazard=bool(spec.get("hazard", False)),
            chip_only=bool(spec.get("chip_only", False)),
            requires=tuple(spec.get("requires", ())),
            surfaces=tuple(spec.get("surfaces", ()))))
    return out


def registry_hash(tasks: Sequence[Task]) -> str:
    """Stable digest of the active registry — part of the plan state's
    meta contract (sched/state.py): a state persisted against a
    different task set must re-plan fresh, never resume."""
    blob = json.dumps([dataclasses.asdict(t) for t in tasks],
                      sort_keys=True)
    return hashlib.sha256(blob.encode()).hexdigest()[:16]


def by_name(tasks: Sequence[Task]) -> Dict[str, Task]:
    """Slug -> Task index (duplicate slugs are a registry bug: loud)."""
    out: Dict[str, Task] = {}
    for t in tasks:
        if t.name in out:
            raise ValueError(f"duplicate task slug {t.name!r}")
        out[t.name] = t
    return out
