"""Crash-safe plan state — resume the PLAN, not the script.

The same discipline bench/resume.Checkpoint gives measurement rows,
applied to the session plan itself: one artifact file of shape
`{**meta, "complete": bool, "window_t0": t, "tasks": {...}}`, written
atomically (utils/jsonio) after every state transition, with the
Checkpoint meta-contract rule — a prior state resumes only when every
meta key (registry hash, platform, version) round-trips identically;
a state left `complete: false` by a watchdog exit 3/4 or a SIGKILL
resumes its window (same window_t0, completed tasks stay completed,
zero re-measurement), while a `complete: true` state is a finished
window and a re-invocation plans FRESH (per-window freshness, exactly
like Checkpoint).

Pick/death reconciliation: `--next`/the executor record a task as
`picked` BEFORE running it. A re-invocation that finds a picked-but-
never-recorded task consults the task's completion artifact: complete
and fresh => the task finished and only the record died with the
process (counted done, status 'reconciled'); otherwise the pick is
dropped and the task is eligible again — the window died mid-task and
whatever rows the task persisted resume at the TASK's own grain
(bench/resume.py), not ours.

jax-free by construction (package docstring).
"""

from __future__ import annotations

import json
import os
import time
from typing import Dict, List, Optional, Sequence

from tpu_reductions.obs import ledger
from tpu_reductions.sched.tasks import Task, artifact_complete
from tpu_reductions.utils.jsonio import atomic_json_dump

STATE_VERSION = 1
# terminal statuses: the task consumed its window opportunity.
# "aborted" (the window died mid-task, rc 3/4) is deliberately NOT
# settled: the task never got its chance — a resume re-plans it, and
# whatever rows it persisted before the death resume at the task's own
# grain (bench/resume.py).
_SETTLED = ("done", "reconciled", "failed", "budget-cut", "skipped")


class PlanState:
    """One window's plan ledger (module docstring has the contract)."""

    def __init__(self, path: Optional[str], meta: dict,
                 now: Optional[float] = None,
                 readonly: bool = False) -> None:
        """`readonly=True` (the --plan-only contract: print the plan,
        touch nothing) still LOADS a resumable prior state but never
        writes one."""
        self.path = os.fspath(path) if path is not None else None
        self.meta = json.loads(json.dumps(meta))
        self.readonly = readonly
        self.tasks: Dict[str, dict] = {}
        now = time.time() if now is None else now
        self.window_t0 = now
        prior = self._load_prior()
        if prior is not None:
            self.window_t0 = float(prior.get("window_t0", now))
            for name, rec in prior.get("tasks", {}).items():
                if isinstance(rec, dict):
                    self.tasks[name] = rec
            if not readonly:
                ledger.emit("resume.decision", mode="resume-plan",
                            path=self.path, prior_tasks=len(self.tasks),
                            window_t0=self.window_t0)
        self._persist(complete=False)

    def _load_prior(self) -> Optional[dict]:
        if self.path is None or not os.path.exists(self.path):
            return None
        try:
            data = json.loads(open(self.path).read())
        except (OSError, ValueError):
            return None   # truncated by a pre-atomic interrupt: fresh
        if not isinstance(data, dict) or data.get("complete") is True:
            return None   # finished window: plan fresh
        if not all(data.get(k) == v for k, v in self.meta.items()):
            return None   # different registry/platform: never resume
        return data

    # -- transitions (each persists atomically; a death between a
    #    transition and its persist loses at most that transition,
    #    which reconcile() re-derives from the task artifacts) --------

    def record_pick(self, task: Task, est_s: float) -> None:
        self.tasks[task.name] = {"status": "picked",
                                 "planned_s": round(est_s, 3),
                                 "value": task.value,
                                 "picked_at": round(time.time(), 3)}
        self._persist(complete=False)

    def record_done(self, name: str, rc: int, actual_s: float,
                    status: str) -> None:
        rec = self.tasks.setdefault(name, {})
        rec.update({"status": status, "rc": rc,
                    "actual_s": round(actual_s, 3)})
        self._persist(complete=False)

    def record_skip(self, name: str, reason: str) -> None:
        self.tasks[name] = {"status": "skipped", "reason": reason}
        self._persist(complete=False)

    def finalize(self) -> None:
        """The plan ran dry: mark the window's record complete (the
        next invocation plans fresh)."""
        self._persist(complete=True)

    def _persist(self, complete: bool) -> None:
        if self.path is None or self.readonly:
            return
        atomic_json_dump(self.path, {
            **self.meta, "complete": complete,
            "window_t0": round(self.window_t0, 3),
            "tasks": self.tasks})
        ledger.emit("artifact.persist", path=self.path,
                    rows=len(self.tasks), complete=complete,
                    grain="plan")

    # -- queries ------------------------------------------------------

    def reconcile(self, tasks: Sequence[Task]) -> List[str]:
        """Settle stale 'picked' entries after a death (module
        docstring); returns the reconciled slugs."""
        index = {t.name: t for t in tasks}
        fixed = []
        for name, rec in list(self.tasks.items()):
            if rec.get("status") != "picked":
                continue
            t = index.get(name)
            if t is not None and t.done_artifact and artifact_complete(
                    t.done_artifact, self.window_t0):
                rec.update({"status": "reconciled", "rc": 0})
                fixed.append(name)
            else:
                del self.tasks[name]   # eligible again
        self._persist(complete=False)
        return fixed

    def settled(self, name: str) -> bool:
        return self.tasks.get(name, {}).get("status") in _SETTLED

    def attempted(self, name: str) -> bool:
        """Whether the task consumed its opportunity this window (any
        recorded status at all counts — `requires` gates on attempted,
        not on success: a smoke that FAILED still vetted lowering)."""
        return name in self.tasks


def plan_vs_actual_markdown(state: dict) -> str:
    """The committed plan-vs-actual record, rendered for report.md /
    WINDOW_SUMMARY.md (bench/regen.py folds it in — ISSUE 5
    satellite). Pure formatting over a persisted state dict."""
    tasks = state.get("tasks") or {}
    lines = ["## plan vs actual (scheduler)", "",
             "| task | planned s | actual s | status |",
             "|---|---|---|---|"]
    for name in sorted(tasks, key=lambda n: tasks[n].get("picked_at",
                                                         float("inf"))):
        rec = tasks[name]
        planned = rec.get("planned_s")
        actual = rec.get("actual_s")
        status = rec.get("status", "?")
        if status == "skipped" and rec.get("reason"):
            status = f"skipped ({rec['reason']})"
        lines.append(
            f"| {name} "
            f"| {planned if planned is not None else '-'} "
            f"| {actual if actual is not None else '-'} "
            f"| {status} |")
    if not tasks:
        lines.append("| (no tasks planned) | - | - | - |")
    state_done = "complete" if state.get("complete") else "interrupted"
    lines.append("")
    lines.append(f"plan state: {state_done}; "
                 f"window_t0={state.get('window_t0', '-')}")
    return "\n".join(lines)
