"""tpu_reductions — a TPU-native reduction-benchmark framework.

Rebuilds the capability surface of szabodabo/CUDA-MPI-Reductions (see
/root/repo/SURVEY.md) as an idiomatic JAX/XLA/Pallas framework:

- Reduction ops SUM / MIN / MAX over int32 / float32 / float64
  (reference: cuda/C/src/reduction/reduction_kernel.cu, mpi/reduce.c:21-28).
- Single-chip hierarchical Pallas reduction kernels — the TPU analog of the
  tree + warp-synchronous CUDA "kernel 6" (reduction_kernel.cu:74-253).
- Cross-chip collective reductions over a `jax.sharding.Mesh` — the analog of
  `MPI_Reduce` over the Blue Gene/L torus (mpi/reduce.c:76,90).
- Self-verifying benchmark drivers (accelerator vs Kahan host oracle,
  PASSED/FAILED/WAIVED protocol — reduction.cpp:206-249, shrQATest.h).
- A sweep -> collect -> average -> plot pipeline (mpi/submit_all.sh,
  getAvgs.sh, makePlots.gp analogs).

Layer map (SURVEY.md §7):
  L0 config/CLI      tpu_reductions.config
  L1 runtime utils   tpu_reductions.utils.{timing,logging,qa,rng}
  L2 ops             tpu_reductions.ops.{registry,xla_reduce,pallas_reduce,oracle}
  L3 collectives     tpu_reductions.parallel.{mesh,collectives}
  L4 drivers         tpu_reductions.bench.{driver,collective_driver}
  L5 sweep/analysis  tpu_reductions.bench.{sweep,aggregate,plot}
"""

__version__ = "0.1.0"
