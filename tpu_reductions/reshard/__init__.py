"""Reshard engine — portable array redistribution on the collectives
registry (ISSUE 15; Zhang et al. 2112.01075, EQuARX 2506.17615):

  spec.py        ShardingSpec — validated, byte-identical JSON round
                 trip (specs live inside committed artifacts)
  primitives.py  the four redistribution moves as shard_map programs
                 (the ONE RED016-whitelisted home outside collectives/
                 for on-device redistribution spellings) + the plan
                 executor with instrumented buffer accounting
  planner.py     cheapest primitive program under a peak-memory bound,
                 priced by collectives/algorithms.algorithm_cost
  oracle.py      pure-numpy reference every executed plan is verified
                 against, element-wise per rank

Instrument: bench/reshard_curve.py (committed artifact
examples/rank_scaling/reshard_curve.json); runbook: docs/RESHARD.md.
"""

from tpu_reductions.reshard.oracle import (local_block, logical_global,
                                           reshard_reference,
                                           verify_placement)
from tpu_reductions.reshard.planner import (Plan, PlanStep,
                                            ReshardPlanError,
                                            naive_plan, plan_reshard)
from tpu_reductions.reshard.primitives import (PRIMITIVES, Primitive,
                                               collect_shards,
                                               declared_buffers,
                                               declared_mem_factor,
                                               execute_plan, make_mesh,
                                               partition_spec,
                                               place_spec,
                                               quant_compression,
                                               reshard_error_bound,
                                               step_label)
from tpu_reductions.reshard.spec import ShardingSpec, ShardingSpecError

__all__ = [
    "Plan", "PlanStep", "PRIMITIVES", "Primitive", "ReshardPlanError",
    "ShardingSpec", "ShardingSpecError", "collect_shards",
    "declared_buffers", "declared_mem_factor", "execute_plan",
    "local_block", "logical_global", "make_mesh", "naive_plan",
    "partition_spec", "place_spec", "plan_reshard",
    "quant_compression", "reshard_error_bound", "reshard_reference",
    "step_label", "verify_placement",
]
