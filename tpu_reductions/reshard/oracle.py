"""Pure-numpy reshard reference — every executed plan is verified
against this, element-wise, per rank (ISSUE 15 tentpole (d)).

The discipline is the single-chip bench's elementwise host oracle
(reduction.cpp:232-239) lifted to placements: instead of "is the
reduced value right", the question is "does rank r hold EXACTLY the
block of the logical global array its target spec assigns it". Nothing
here imports jax — the reference must not share code (or bugs) with
the device path it checks; the executor hands it plain numpy shards
(reshard/primitives.execute_plan collects them per device).

Value convention (reshard/spec.py): a non-partial spec's carried value
is the global array itself; a `partial` spec's carried value is a
stack of per-rank addends with shape (k, *global_shape) whose
elementwise sum is the logical global value.
"""

from __future__ import annotations

import numpy as np

from tpu_reductions.reshard.spec import ShardingSpec, ShardingSpecError


def logical_global(carried: np.ndarray, spec: ShardingSpec
                   ) -> np.ndarray:
    """The logical global array a carried value denotes: itself, or the
    sum over the leading stacked rank axis when the spec is partial
    (module docstring). Mirrors reduction.cpp:232-239's oracle role for
    placements."""
    x = np.asarray(carried)
    if not spec.partial:
        return x
    k = spec.num_ranks
    if x.ndim != spec.ndim + 1 or x.shape[0] != k:
        raise ShardingSpecError(
            f"partial value must be a (k={k}, *shape) addend stack, "
            f"got shape {x.shape}")
    # accumulate wide so the reference is at least as accurate as the
    # device sum it judges
    return x.astype(np.float64, copy=False).sum(axis=0).astype(x.dtype) \
        if np.issubdtype(x.dtype, np.floating) else x.sum(axis=0)


def local_block(global_np: np.ndarray, spec: ShardingSpec, rank: int
                ) -> np.ndarray:
    """What rank `rank` of a 1-D mesh holds under `spec` (non-partial):
    the full array when replicated, else block `rank` of the single
    sharded dimension. This is the entire reshard semantics in four
    lines of numpy — the reference every device program must match."""
    if spec.partial:
        raise ShardingSpecError(
            "local_block describes settled placements; a partial "
            "spec's per-rank value is addend `rank` of the stack")
    if len(spec.mesh_axes) != 1:
        raise ShardingSpecError(
            f"oracle handles 1-D meshes, got {spec.mesh_axes}")
    d = spec.sharded_dim()
    if d is None:
        return np.asarray(global_np)
    k = spec.num_ranks
    size = global_np.shape[d] // k
    idx = [slice(None)] * global_np.ndim
    idx[d] = slice(rank * size, (rank + 1) * size)
    return np.asarray(global_np)[tuple(idx)]


def reshard_reference(carried: np.ndarray, src: ShardingSpec,
                      dst: ShardingSpec, rank: int) -> np.ndarray:
    """The numpy answer for rank `rank` after resharding `carried`
    (placed per `src`) into `dst` — logical_global then local_block."""
    return local_block(logical_global(carried, src), dst, rank)


def verify_placement(carried: np.ndarray, src: ShardingSpec,
                     dst: ShardingSpec, shards: list,
                     atol: float = 0.0) -> dict:
    """Element-wise verification of an executed plan: `shards[r]` is
    the numpy block rank r actually holds; every rank must match the
    reference within `atol` (0.0 = bit-exact; quantized wire passes
    the composed declared bound). Returns {ok, max_err, ranks}."""
    k = dst.num_ranks
    if len(shards) != k:
        raise ShardingSpecError(
            f"expected {k} rank shards, got {len(shards)}")
    max_err = 0.0
    ok = True
    for r in range(k):
        want = reshard_reference(carried, src, dst, r)
        got = np.asarray(shards[r])
        if got.shape != want.shape:
            return {"ok": False, "max_err": float("inf"), "ranks": k,
                    "detail": f"rank {r} shape {got.shape} != "
                              f"{want.shape}"}
        if atol == 0.0:
            ok = ok and bool(np.array_equal(got, want))
            if not ok:
                max_err = max(max_err, float(
                    np.abs(got.astype(np.float64)
                           - want.astype(np.float64)).max()))
        else:
            err = float(np.abs(got.astype(np.float64)
                               - want.astype(np.float64)).max())
            max_err = max(max_err, err)
            ok = ok and err <= atol
    return {"ok": bool(ok), "max_err": max_err, "ranks": k}
