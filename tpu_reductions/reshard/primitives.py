"""Redistribution primitives + the plan executor (ISSUE 15 tentpole (b)).

The four primitive moves of Zhang et al.'s reshard decomposition
(PAPERS.md 2112.01075 §3) as runnable shard_map programs over the
collectives package's machinery — ppermute ring construction stays in
collectives/rings.py (ring_all_to_all) per redlint RED016, and THIS
file is the only place outside `collectives/` allowed to spell the
on-device redistribution calls (all_gather / psum_scatter /
dynamic-slice-on-device); the extended RED016 fence pins that.

Each primitive declares, next to its implementation:
  * its wire-cost label in the collectives registry
    (collectives/algorithms.py `reshard_*` entries — the α-β cost the
    planner prices), and
  * its peak-memory factor — per-rank live bytes ÷ GLOBAL array bytes,
    the paper's headline constraint — via `declared_buffers`, an
    explicit enumeration of every buffer the builder allocates. The
    executor instruments the REAL per-device shard sizes against this
    declaration (`execute_plan` reports `measured_mem_factor`; the
    property tests hold measured <= declared).

Quantized wire (EQuARX, PAPERS.md 2506.17615): the wire-crossing
primitives optionally ship block-scaled b-bit carriers
(collectives/quant.block_encode) — each element crosses a lossy hop at
most once per step, so a plan's composed error bound is
steps_quantized * max|x| / levels(bits) (a 2x margin over the
half-step rounding of each crossing; reshard/planner.plan_error_bound).

The reference has no analog: MPI arrays lived whole on every rank
(reduce.c:30-36); redistribution is the part the library hid.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tpu_reductions.collectives.quant import (QUANT_BLOCK, block_decode,
                                              block_encode, levels)
from tpu_reductions.collectives.rings import ring_all_to_all, shard_map
from tpu_reductions.reshard.spec import ShardingSpec, ShardingSpecError


@dataclasses.dataclass(frozen=True)
class Primitive:
    """One redistribution move: its registry label (quantized variants
    append _q{bits}) and a one-line memory story (the full buffer
    enumeration is `declared_buffers`). No reference analog
    (TPU-native)."""

    name: str
    label: str
    mem_note: str


PRIMITIVES: Dict[str, Primitive] = {
    "identity": Primitive(
        "identity", "reshard_dynamic_slice",
        "in only (nothing moves)"),
    "all_gather": Primitive(
        "all_gather", "reshard_all_gather",
        "in 1/k + out 1 (quant: + encoded copies, (in+out)*(2+c))"),
    "dynamic_slice": Primitive(
        "dynamic_slice", "reshard_dynamic_slice",
        "in + out slice; zero wire"),
    "collective_permute": Primitive(
        "collective_permute", "reshard_collective_permute",
        "in + pieces stack + out (3/k) + two in-flight 1/k**2 pieces"),
    "reduce_scatter": Primitive(
        "reduce_scatter", "reshard_reduce_scatter",
        "full addend 1 + out 1/k"),
}


def quant_compression(bits: int, itemsize: int) -> float:
    """Wire bytes per payload byte of the block-scaled encoding: b-bit
    carrier + one f32 scale per QUANT_BLOCK elements (the same constant
    the registry's reshard_*_q{bits} factors derive from)."""
    return (bits / 8 + 4 / QUANT_BLOCK) / itemsize


def step_label(primitive: str, quant_bits: Optional[int]) -> str:
    """Registry label of a primitive under the chosen wire form."""
    base = PRIMITIVES[primitive].label
    if quant_bits is None or primitive in ("identity", "dynamic_slice",
                                           "reduce_scatter"):
        return base
    return f"{base}_q{quant_bits}"


def declared_buffers(primitive: str, k: int, in_f: float, out_f: float,
                     quant_bits: Optional[int] = None,
                     itemsize: int = 4) -> Tuple[Tuple[str, float], ...]:
    """The declared buffer enumeration of one step: (name, fraction of
    GLOBAL array bytes) for every per-rank buffer the builder
    allocates. The step's declared peak-memory factor is the sum; the
    executor's instrumented accounting must never exceed it
    (tests/test_reshard.py). Fractions follow the builders below
    line-for-line — change an allocation THERE and this table (or the
    property test screams)."""
    c = (quant_compression(quant_bits, itemsize)
         if quant_bits is not None else 0.0)
    if primitive == "identity":
        return (("in", in_f),)
    if primitive == "dynamic_slice":
        return (("in", in_f), ("out", out_f))
    if primitive == "all_gather":
        if quant_bits is None:
            return (("in", in_f), ("out", out_f))
        return (("in", in_f), ("flat", in_f),
                ("enc_local", c * in_f), ("enc_gathered", c * out_f),
                ("decoded", out_f), ("out", out_f))
    if primitive == "collective_permute":
        piece = in_f / k
        base = [("in", in_f), ("pieces", in_f), ("out", out_f),
                ("send_piece", piece), ("rx_piece", piece)]
        if quant_bits is not None:
            base += [("send_enc", c * piece), ("rx_enc", c * piece)]
        return tuple(base)
    if primitive == "reduce_scatter":
        return (("in", in_f), ("out", out_f))
    raise ShardingSpecError(f"unknown primitive {primitive!r}")


def declared_mem_factor(primitive: str, k: int, in_f: float,
                        out_f: float, quant_bits: Optional[int] = None,
                        itemsize: int = 4) -> float:
    """Sum of `declared_buffers` — the factor every emitted plan step
    carries and the planner's --mem-bound filters on."""
    return sum(f for _, f in declared_buffers(primitive, k, in_f, out_f,
                                              quant_bits, itemsize))


# ---------------------------------------------------------------------------
# placement
# ---------------------------------------------------------------------------


def make_mesh(k: int, axis: str = "ranks") -> Mesh:
    """A 1-D mesh over the first k local devices (the virtual-device
    ladder of tests/conftest.py and the rank-scaling sweep)."""
    devs = jax.devices()
    if len(devs) < k:
        raise ShardingSpecError(f"need {k} devices, have {len(devs)}")
    return Mesh(np.array(devs[:k]), (axis,))


def partition_spec(spec: ShardingSpec, axis: str = "ranks") -> P:
    """The jax PartitionSpec of a carried value under `spec`: a partial
    value's leading stacked addend axis is sharded; otherwise the one
    sharded dim carries the mesh axis."""
    if spec.partial:
        return P(axis, *([None] * spec.ndim))
    d = spec.sharded_dim()
    if d is None:
        return P(*([None] * spec.ndim))
    return P(*[axis if i == d else None for i in range(spec.ndim)])


def place_spec(carried: np.ndarray, spec: ShardingSpec, mesh: Mesh,
               axis: str = "ranks"):
    """Place a host value per its spec (the reshard engine's ingest;
    partial values are (k, *shape) addend stacks — reshard/spec.py)."""
    x = np.asarray(carried)
    if spec.partial:
        if x.ndim != spec.ndim + 1 or x.shape[0] != spec.num_ranks:
            raise ShardingSpecError(
                f"partial value must be (k={spec.num_ranks}, *shape), "
                f"got {x.shape}")
    else:
        spec.local_shape(x.shape)   # divisibility check
    # redlint: disable=RED003 -- sharded per-device placement (1/k of the value per device), not single-device bulk staging
    return jax.device_put(x, NamedSharding(mesh, partition_spec(spec,
                                                                axis)))


def collect_shards(y, mesh: Mesh, axis: str = "ranks") -> list:
    """Per-rank numpy blocks of a device array, ordered by mesh
    position — what oracle.verify_placement consumes."""
    order = {d: i for i, d in enumerate(mesh.devices.reshape(-1))}
    shards = [None] * len(order)
    for s in y.addressable_shards:
        shards[order[s.device]] = np.asarray(s.data)
    return shards


# ---------------------------------------------------------------------------
# step builders (the RED016-fenced device spellings live HERE only)
# ---------------------------------------------------------------------------


def _quant_ok(count: int) -> bool:
    return count % QUANT_BLOCK == 0


def build_step(step, mesh: Mesh, global_shape: Tuple[int, ...],
               dtype, axis: str = "ranks"):
    """Compile one plan step into a jitted shard_map program. Returns
    (fn, aux_buffers) where aux_buffers lists the modeled intermediate
    allocations as (name, per-rank bytes) — the executor combines them
    with the REAL in/out shard sizes for the instrumented accounting
    (module docstring)."""
    k = mesh.shape[axis]
    itemsize = np.dtype(dtype).itemsize
    g_bytes = int(np.prod(global_shape)) * itemsize
    in_spec = partition_spec(step.src, axis)
    out_spec = partition_spec(step.dst, axis)
    qb = step.quant_bits
    aux = []

    if step.primitive == "identity":
        def local(x):
            return x
        fn = local, in_spec, out_spec

    elif step.primitive == "all_gather":
        d = step.dims[0]
        local_shape = step.src.local_shape(global_shape)
        if qb is None:
            def local(x):
                return jax.lax.all_gather(x, axis, axis=d, tiled=True)
        else:
            n_local = int(np.prod(local_shape))
            if not _quant_ok(n_local):
                raise ShardingSpecError(
                    f"quantized all-gather needs local count "
                    f"{n_local} % {QUANT_BLOCK} == 0")
            c = quant_compression(qb, itemsize)
            aux += [("flat", n_local * itemsize),
                    ("enc_local", int(c * n_local * itemsize)),
                    ("enc_gathered", int(c * g_bytes)),
                    ("decoded", g_bytes)]

            def local(x, _d=d, _ls=local_shape, _qb=qb):
                flat = x.reshape(-1)
                carrier, scales = block_encode(flat, _qb)
                gc = jax.lax.all_gather(carrier, axis, axis=0,
                                        tiled=True)
                gs = jax.lax.all_gather(scales, axis, axis=0,
                                        tiled=True)
                parts = block_decode(gc, gs, _qb).reshape((k,) + _ls)
                return jnp.concatenate([parts[i] for i in range(k)],
                                       axis=_d)
        fn = local, in_spec, out_spec

    elif step.primitive == "dynamic_slice":
        d = step.dims[0]
        size = global_shape[d] // step.dst.partitions(d)

        def local(x, _d=d, _s=size):
            r = jax.lax.axis_index(axis)
            return jax.lax.dynamic_slice_in_dim(x, r * _s, _s, axis=_d)
        fn = local, in_spec, out_spec

    elif step.primitive == "collective_permute":
        src_d, dst_d = step.dims
        local_shape = step.src.local_shape(global_shape)
        piece_shape = list(local_shape)
        piece_shape[dst_d] //= k
        piece_count = int(np.prod(piece_shape))
        piece_bytes = piece_count * itemsize
        aux += [("pieces", int(np.prod(local_shape)) * itemsize),
                ("send_piece", piece_bytes), ("rx_piece", piece_bytes)]
        to_wire = from_wire = None
        if qb is not None:
            if not _quant_ok(piece_count):
                raise ShardingSpecError(
                    f"quantized permute needs piece count "
                    f"{piece_count} % {QUANT_BLOCK} == 0")
            c = quant_compression(qb, itemsize)
            aux += [("send_enc", int(c * piece_bytes)),
                    ("rx_enc", int(c * piece_bytes))]
            _ps = tuple(piece_shape)

            def to_wire(p, _qb=qb):
                return block_encode(p.reshape(-1), _qb)

            def from_wire(rx, _qb=qb, _shape=_ps):
                return block_decode(rx[0], rx[1], _qb).reshape(_shape)

        def local(x, _sd=src_d, _dd=dst_d, _tw=to_wire, _fw=from_wire):
            return ring_all_to_all(axis, k, x, split_axis=_dd,
                                   concat_axis=_sd, to_wire=_tw,
                                   from_wire=_fw)
        fn = local, in_spec, out_spec

    elif step.primitive == "reduce_scatter":
        d = step.dims[0]

        def local(x, _d=d):
            # (1, *shape) addend -> shape, then scatter the sum
            x = x.reshape(x.shape[1:])
            return jax.lax.psum_scatter(x, axis,
                                        scatter_dimension=_d,
                                        tiled=True)
        fn = local, in_spec, out_spec

    else:
        raise ShardingSpecError(f"unknown primitive {step.primitive!r}")

    local_fn, in_s, out_s = fn
    return jax.jit(shard_map(local_fn, mesh=mesh, in_specs=in_s,
                             out_specs=out_s, check_vma=False)), aux


# ---------------------------------------------------------------------------
# executor
# ---------------------------------------------------------------------------


def execute_plan(plan, carried: np.ndarray, mesh: Mesh, *,
                 axis: str = "ranks") -> dict:
    """Run a planner program step by step with per-primitive timing and
    instrumented buffer accounting; returns

        {shards, wall_s, steps: [{primitive, algorithm, wall_s,
         buffer_bytes, mem_factor}], measured_mem_factor}

    Each step times to HOST MATERIALIZATION (jax.device_get) — never
    block_until_ready, whose ack-only return this platform's timing
    doctrine bans (CLAUDE.md) — and emits a `reshard.step` ledger event
    so obs/timeline attributes per-primitive wall clock; the run is
    bracketed by `reshard.plan`/`reshard.done`. Buffer accounting: the
    REAL largest per-device shard bytes of the step's input and output
    plus the builder's modeled intermediates (`build_step` aux), as a
    fraction of global bytes — held against every step's declared
    factor.

    No reference analog (TPU-native)."""
    from tpu_reductions.exec import core as exec_core
    from tpu_reductions.exec.plan import launch_plan
    from tpu_reductions.obs import ledger, trace
    from tpu_reductions.utils.timing import Stopwatch

    x_np = np.asarray(carried)
    dtype = x_np.dtype
    global_shape = (x_np.shape[1:] if plan.source.partial
                    else x_np.shape)
    g_bytes = int(np.prod(global_shape)) * dtype.itemsize

    with trace.child():
        ledger.emit("reshard.plan", src=plan.source.describe(),
                    dst=plan.target.describe(),
                    program=[s.primitive for s in plan.steps],
                    wire_bytes=int(plan.wire_bytes),
                    mem_factor=round(plan.mem_factor, 6),
                    ranks=mesh.shape[axis])
        step_rows = []
        total = 0.0

        def program(ctx):
            # the whole redistribution program is ONE plan: the
            # contract declares no whole-plan phase, and every step's
            # blocking device region — dispatch + host materialization
            # — runs under its own ctx.guard so a mid-plan relay stall
            # trips exit 4 instead of hanging (RED019)
            nonlocal total
            x = place_spec(x_np, plan.source, mesh, axis)
            measured = _shard_fraction(x, g_bytes)
            for step in plan.steps:
                fn, aux = build_step(step, mesh, global_shape, dtype,
                                     axis)
                watch = Stopwatch()
                watch.start()
                with ctx.guard("reshard.step"):
                    y = fn(x)
                    jax.device_get(y)
                wall_s = watch.stop()
                total += wall_s
                in_b = _max_shard_bytes(x)
                out_b = _max_shard_bytes(y)
                aux_b = sum(b for _, b in aux)
                step_bytes = in_b + out_b + aux_b
                step_frac = step_bytes / g_bytes
                measured = max(measured, step_frac)
                step_rows.append({"primitive": step.primitive,
                                  "algorithm": step.algorithm,
                                  "wall_s": round(wall_s, 6),
                                  "buffer_bytes": int(step_bytes),
                                  "mem_factor": round(step_frac, 6)})
                ledger.emit("reshard.step", primitive=step.primitive,
                            algorithm=step.algorithm,
                            wall_s=round(wall_s, 6),
                            mem_factor=round(step_frac, 6),
                            ranks=mesh.shape[axis])
                x = y
            return collect_shards(x, mesh, axis), measured

        shards, measured = exec_core.run(launch_plan(
            "reshard", "reshard", program, timing="steps",
            heartbeat_phase=None, ranks=int(mesh.shape[axis]),
            steps=len(plan.steps)))
        ledger.emit("reshard.done", src=plan.source.describe(),
                    dst=plan.target.describe(), steps=len(plan.steps),
                    wall_s=round(total, 6),
                    measured_mem_factor=round(measured, 6))
    return {"shards": shards, "wall_s": total, "steps": step_rows,
            "measured_mem_factor": measured}


def _max_shard_bytes(y) -> int:
    return max((s.data.nbytes for s in y.addressable_shards), default=0)


def _shard_fraction(y, g_bytes: int) -> float:
    return _max_shard_bytes(y) / g_bytes


def reshard_error_bound(n_quant_steps: int, bits: Optional[int],
                        max_abs: float) -> float:
    """Composed declared bound of a plan's quantized crossings: each
    element crosses each lossy step at most once, each crossing rounds
    at most half a quantization step of a block whose max is <=
    max|x| — declared with the suite's 2x margin
    (collectives/quant.quant_error_bound's convention)."""
    if not n_quant_steps or bits is None:
        return 0.0
    return n_quant_steps * float(max_abs) / levels(bits)
