"""The reshard planner — cheapest primitive program under a
peak-memory bound (ISSUE 15 tentpole (c); Zhang et al. 2112.01075).

Given a (source, target) ShardingSpec pair on one mesh axis, the
planner enumerates the candidate primitive programs (reshard/
primitives.py), prices each with the SAME α-β cost machinery every
collective in this repo is priced with (collectives/algorithms.
algorithm_cost over the reshard_* registry entries — no cost literal
lives here), attaches each plan's declared peak-memory factor (max
over its steps' `declared_buffers` sums), and picks the cheapest plan
whose factor fits `mem_bound`. A bound no candidate fits REFUSES with
every candidate's factor in the message — the paper's headline
constraint is a hard gate, not advice.

Candidate programs (k ranks, global payload G):

  src == dst                identity            0 wire
  partial -> sharded d      [reduce_scatter d]  (k-1)/k G
  partial -> replicated     [reduce_scatter 0, all_gather 0]
  replicated -> sharded d   [dynamic_slice d]   0 wire
  sharded d -> replicated   [all_gather d]      (k-1)/k G
  sharded a -> sharded b    [collective_permute a->b]    (k-1)/k**2 G
                         vs [all_gather a, dynamic_slice b]  "naive"

The permute beats the naive program by a factor k on wire but holds
the pieces stack alongside input and output (3/k + 2/k**2 vs the
naive's 1 + 1/k peak at the gathered intermediate) — at small k a
tight --mem-bound really does flip the choice, which is the planner's
reason to exist. `naive_plan` stays exported so the committed curve
can show the margin (ISSUE 15 acceptance).

The reference has no analog: its arrays lived whole on every rank
(reduce.c:30-36).
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

from tpu_reductions.collectives.algorithms import REGISTRY, algorithm_cost
from tpu_reductions.reshard import primitives as prims
from tpu_reductions.reshard.spec import ShardingSpec, ShardingSpecError

# choose_topology's tunnel-regime defaults (collectives/algorithms.py):
# tens of microseconds per hop, ~100 GB/s-class links
DEFAULT_ALPHA_S = 20e-6
DEFAULT_BETA_S_PER_BYTE = 1 / 100e9


class ReshardPlanError(ValueError):
    """No candidate program fits (unsupported spec pair, or every
    candidate exceeds the memory bound). No reference analog
    (TPU-native)."""


@dataclasses.dataclass(frozen=True)
class PlanStep:
    """One primitive application: which move, under which registry
    label (quantized wire changes the label, never the primitive),
    between which intermediate specs, with its declared costs."""

    primitive: str
    algorithm: str
    src: ShardingSpec
    dst: ShardingSpec
    dims: Tuple[int, ...]
    quant_bits: Optional[int]
    wire_bytes: float
    mem_factor: float

    def to_obj(self) -> dict:
        return {"primitive": self.primitive, "algorithm": self.algorithm,
                "dims": list(self.dims), "quant_bits": self.quant_bits,
                "wire_bytes": self.wire_bytes,
                "mem_factor": round(self.mem_factor, 6)}


@dataclasses.dataclass(frozen=True)
class Plan:
    """A priced primitive program. `mem_factor` is the max over steps
    (one step runs at a time; its declared buffers are the live set),
    `wire_bytes`/`cost_s` sum the registry-priced steps."""

    source: ShardingSpec
    target: ShardingSpec
    steps: Tuple[PlanStep, ...]
    cost_s: float
    wire_bytes: float
    mem_factor: float
    quant_steps: int = 0
    note: str = ""

    def to_obj(self) -> dict:
        return {"src": self.source.to_obj(), "dst": self.target.to_obj(),
                "steps": [s.to_obj() for s in self.steps],
                "cost_s": self.cost_s, "wire_bytes": self.wire_bytes,
                "mem_factor": round(self.mem_factor, 6),
                "quant_steps": self.quant_steps, "note": self.note}


def _check_pair(src: ShardingSpec, dst: ShardingSpec) -> int:
    if len(src.mesh_axes) != 1 or len(dst.mesh_axes) != 1:
        raise ReshardPlanError(
            f"planner handles 1-D meshes (the paper's per-mesh-axis "
            f"sub-problem); got {src.mesh_axes} -> {dst.mesh_axes}")
    if src.mesh_axes != dst.mesh_axes:
        raise ReshardPlanError(
            f"source and target meshes differ: {src.mesh_axes} vs "
            f"{dst.mesh_axes}")
    if src.ndim != dst.ndim:
        raise ReshardPlanError(
            f"rank mismatch: {src.ndim} vs {dst.ndim} dims")
    if dst.partial:
        raise ReshardPlanError("a partial TARGET is not a placement")
    return src.num_ranks


def _step(primitive: str, src: ShardingSpec, dst: ShardingSpec,
          dims: Tuple[int, ...], k: int, g_bytes: int, itemsize: int,
          quant_bits: Optional[int], n_for_quant: int) -> PlanStep:
    """Build one priced step; quantized wire applies only when the
    step's wire chunks block-align (collectives/quant.QUANT_BLOCK),
    else the step stays exact (the quantized ring's own fallback
    discipline, quant_ring_applies)."""
    qb = quant_bits
    if qb is not None and (primitive in ("identity", "dynamic_slice",
                                         "reduce_scatter")
                           or n_for_quant % prims.QUANT_BLOCK != 0):
        qb = None
    label = prims.step_label(primitive, qb)
    in_f = src.local_fraction()
    out_f = dst.local_fraction()
    return PlanStep(
        primitive, label, src, dst, dims, qb,
        wire_bytes=REGISTRY[label].wire_factor(k) * g_bytes,
        mem_factor=prims.declared_mem_factor(primitive, k, in_f, out_f,
                                             qb, itemsize))


def _price(src, dst, steps, k, alpha_s, beta, g_bytes, note=""):
    cost = sum(algorithm_cost(s.algorithm, k, g_bytes, alpha_s, beta)
               for s in steps)
    mem = max([s.mem_factor for s in steps],
              default=src.local_fraction())
    return Plan(src, dst, tuple(steps), cost,
                sum(s.wire_bytes for s in steps), mem,
                quant_steps=sum(1 for s in steps
                                if s.quant_bits is not None),
                note=note)


def _candidates(src: ShardingSpec, dst: ShardingSpec,
                global_shape: Tuple[int, ...], itemsize: int,
                quant_bits: Optional[int], alpha_s: float,
                beta: float) -> list:
    k = _check_pair(src, dst)
    import numpy as np
    n = int(np.prod(global_shape))
    g_bytes = n * itemsize
    dst.local_shape(global_shape)   # divisibility gates
    if not src.partial:
        src.local_shape(global_shape)
    sd = None if src.partial else src.sharded_dim()
    dd = dst.sharded_dim()

    def step(primitive, s, d, dims, n_q):
        return _step(primitive, s, d, dims, k, g_bytes, itemsize,
                     quant_bits, n_q)

    out = []
    if src.partial:
        if dd is not None:
            out.append(_price(src, dst,
                              [step("reduce_scatter", src, dst, (dd,),
                                    n)],
                              k, alpha_s, beta, g_bytes))
        else:
            d0 = 0 if src.ndim else None
            if d0 is None or global_shape[0] % k:
                raise ReshardPlanError(
                    f"partial -> replicated needs dim 0 extent "
                    f"divisible by k={k} for the scatter+gather "
                    f"program (shape {global_shape})")
            mid = ShardingSpec.sharded(k, src.ndim, 0)
            out.append(_price(src, dst,
                              [step("reduce_scatter", src, mid, (0,),
                                    n),
                               step("all_gather", mid, dst, (0,),
                                    n // k)],
                              k, alpha_s, beta, g_bytes))
        return out
    if sd == dd:
        out.append(_price(src, dst, [], k, alpha_s, beta, g_bytes,
                          note="identity: source already matches"))
        return out
    if sd is None:
        out.append(_price(src, dst,
                          [step("dynamic_slice", src, dst, (dd,), n)],
                          k, alpha_s, beta, g_bytes))
        return out
    if dd is None:
        out.append(_price(src, dst,
                          [step("all_gather", src, dst, (sd,), n // k)],
                          k, alpha_s, beta, g_bytes))
        return out
    # sharded -> sharded on a different dim: permute vs naive
    out.append(_price(src, dst,
                      [step("collective_permute", src, dst, (sd, dd),
                            n // (k * k))],
                      k, alpha_s, beta, g_bytes))
    out.append(_naive(src, dst, k, g_bytes, n, itemsize, quant_bits,
                      alpha_s, beta))
    return out


def _naive(src, dst, k, g_bytes, n, itemsize, quant_bits, alpha_s,
           beta):
    sd, dd = src.sharded_dim(), dst.sharded_dim()
    rep = ShardingSpec.replicated(k, src.ndim)
    steps = [_step("all_gather", src, rep, (sd,), k, g_bytes, itemsize,
                   quant_bits, n // k),
             _step("dynamic_slice", rep, dst, (dd,), k, g_bytes,
                   itemsize, quant_bits, n)]
    return _price(src, dst, steps, k, alpha_s, beta, g_bytes,
                  note="naive all-gather-then-slice")


def plan_reshard(src: ShardingSpec, dst: ShardingSpec,
                 global_shape: Tuple[int, ...], itemsize: int = 4, *,
                 mem_bound: Optional[float] = None,
                 quant_bits: Optional[int] = None,
                 alpha_s: float = DEFAULT_ALPHA_S,
                 beta_s_per_byte: float = DEFAULT_BETA_S_PER_BYTE
                 ) -> Plan:
    """THE planner entry point (module docstring): cheapest candidate
    under `mem_bound`, ties broken toward fewer steps. Refuses — with
    every candidate's declared factor — when nothing fits."""
    cands = _candidates(src, dst, global_shape, itemsize, quant_bits,
                        alpha_s, beta_s_per_byte)
    fits = [p for p in cands
            if mem_bound is None or p.mem_factor <= mem_bound]
    if not fits:
        detail = "; ".join(
            f"[{' + '.join(s.primitive for s in p.steps) or 'identity'}]"
            f" needs {p.mem_factor:.3f}" for p in cands)
        raise ReshardPlanError(
            f"no {src.describe()} -> {dst.describe()} program fits "
            f"mem-bound {mem_bound}: {detail}")
    return min(fits, key=lambda p: (p.cost_s, len(p.steps)))


def naive_plan(src: ShardingSpec, dst: ShardingSpec,
               global_shape: Tuple[int, ...], itemsize: int = 4, *,
               quant_bits: Optional[int] = None,
               alpha_s: float = DEFAULT_ALPHA_S,
               beta_s_per_byte: float = DEFAULT_BETA_S_PER_BYTE
               ) -> Optional[Plan]:
    """The all-gather-then-slice baseline for a sharded->sharded pair
    (None for pairs with no naive alternative) — the committed curve's
    beats-naive margin reads its wire_bytes (ISSUE 15 acceptance)."""
    k = _check_pair(src, dst)
    if src.partial or src.sharded_dim() is None \
            or dst.sharded_dim() is None \
            or src.sharded_dim() == dst.sharded_dim():
        return None
    import numpy as np
    n = int(np.prod(global_shape))
    return _naive(src, dst, k, n * itemsize, n, itemsize, quant_bits,
                  alpha_s, beta_s_per_byte)
