"""ShardingSpec — the value type of the reshard engine (ISSUE 15).

A spec names a device mesh (ordered (axis_name, size) pairs) and, per
array dimension, which mesh axes partition it — the portable sharding
description of Zhang et al.'s array-redistribution framework (PAPERS.md
2112.01075 §2, where every transfer is a (source, target) pair of
exactly these). Specs are validated at construction, immutable, and
JSON-round-trippable BYTE-identically (canonical form), because they
live inside committed artifacts (examples/rank_scaling/
reshard_curve.json) and a spec that drifts on re-serialization would
defeat the resume contract's meta comparison (bench/resume.Checkpoint).

A `partial=True` spec carries pending-reduction state: each rank holds
one full-size ADDEND and the logical global value is their elementwise
sum — the input shape reduce_scatter consumes (the carried array gains
a leading stacked rank axis; reshard/oracle.py spells the semantics in
numpy). The reference has no analog: its MPI arrays lived whole on
every rank (reduce.c:30-36), sharding is the part MPI hid.
"""

from __future__ import annotations

import dataclasses
import json
from typing import Tuple


class ShardingSpecError(ValueError):
    """A spec that does not describe a placement (bad mesh axis, reused
    axis, unknown name...). No reference analog (TPU-native)."""


@dataclasses.dataclass(frozen=True)
class ShardingSpec:
    """mesh_axes: ordered ((name, size), ...) — the device mesh.
    dim_specs: per array dimension, the tuple of mesh axis names that
    partition it (() = replicated along that dim). partial: the value
    is a per-rank sum addend, not yet reduced (module docstring).

    No reference analog (TPU-native)."""

    mesh_axes: Tuple[Tuple[str, int], ...]
    dim_specs: Tuple[Tuple[str, ...], ...]
    partial: bool = False

    def __post_init__(self):
        mesh = tuple((str(n), int(s)) for n, s in self.mesh_axes)
        dims = tuple(tuple(str(a) for a in d) for d in self.dim_specs)
        object.__setattr__(self, "mesh_axes", mesh)
        object.__setattr__(self, "dim_specs", dims)
        object.__setattr__(self, "partial", bool(self.partial))
        names = [n for n, _ in mesh]
        if len(set(names)) != len(names):
            raise ShardingSpecError(f"duplicate mesh axis in {names}")
        for n, s in mesh:
            if not n.isidentifier():
                raise ShardingSpecError(f"mesh axis name {n!r} is not "
                                        f"an identifier")
            if s < 1:
                raise ShardingSpecError(f"mesh axis {n!r} has size {s}")
        used = []
        for d in dims:
            for a in d:
                if a not in names:
                    raise ShardingSpecError(
                        f"dim spec references unknown mesh axis {a!r} "
                        f"(mesh has {names})")
                used.append(a)
        if len(set(used)) != len(used):
            raise ShardingSpecError(
                f"mesh axis used on more than one array position: "
                f"{sorted(a for a in set(used) if used.count(a) > 1)}")

    # -- derived geometry --------------------------------------------------

    @property
    def ndim(self) -> int:
        return len(self.dim_specs)

    @property
    def num_ranks(self) -> int:
        """Total device count of the mesh."""
        out = 1
        for _, s in self.mesh_axes:
            out *= s
        return out

    def axis_size(self, name: str) -> int:
        for n, s in self.mesh_axes:
            if n == name:
                return s
        raise ShardingSpecError(f"no mesh axis {name!r}")

    def partitions(self, dim: int) -> int:
        """How many ways array dimension `dim` is split."""
        out = 1
        for a in self.dim_specs[dim]:
            out *= self.axis_size(a)
        return out

    def sharded_dim(self):
        """The single partitioned array dimension, or None when fully
        replicated. Raises when more than one dim is partitioned (the
        single-axis planner's precondition; multi-dim specs are valid
        values but have no plan yet — docs/RESHARD.md)."""
        dims = [i for i, d in enumerate(self.dim_specs)
                if d and self.partitions(i) > 1]
        if not dims:
            return None
        if len(dims) > 1:
            raise ShardingSpecError(
                f"spec partitions {len(dims)} dims; the planner handles "
                f"one per spec (dims {dims})")
        return dims[0]

    def local_shape(self, global_shape: Tuple[int, ...]
                    ) -> Tuple[int, ...]:
        """Per-rank block shape for a given global shape; validates
        divisibility (partition counts must divide their extents)."""
        if len(global_shape) != self.ndim:
            raise ShardingSpecError(
                f"spec has {self.ndim} dims, array has "
                f"{len(global_shape)}")
        out = []
        for i, n in enumerate(global_shape):
            p = self.partitions(i)
            if n % p:
                raise ShardingSpecError(
                    f"dim {i} extent {n} does not divide into {p} "
                    f"partitions")
            out.append(n // p)
        return tuple(out)

    def local_fraction(self) -> float:
        """Per-rank resident fraction of the GLOBAL array bytes — the
        unit of the planner's peak-memory factors. Replication costs
        full copies; a partial spec's addend is full-size by
        definition."""
        f = 1.0
        for i in range(self.ndim):
            f /= self.partitions(i)
        return f

    # -- canonical JSON ----------------------------------------------------

    def to_obj(self) -> dict:
        return {"mesh": [[n, s] for n, s in self.mesh_axes],
                "dims": [list(d) for d in self.dim_specs],
                "partial": self.partial}

    def to_json(self) -> str:
        """Canonical compact encoding: sorted keys, no whitespace — the
        byte-identical round-trip contract
        (tests/test_reshard.py::test_spec_json_roundtrip)."""
        return json.dumps(self.to_obj(), sort_keys=True,
                          separators=(",", ":"))

    @classmethod
    def from_obj(cls, obj: dict) -> "ShardingSpec":
        try:
            mesh = tuple((str(n), int(s)) for n, s in obj["mesh"])
            dims = tuple(tuple(str(a) for a in d) for d in obj["dims"])
            partial = bool(obj.get("partial", False))
        except (KeyError, TypeError, ValueError) as e:
            raise ShardingSpecError(f"malformed spec object: {e}")
        return cls(mesh, dims, partial)

    @classmethod
    def from_json(cls, text: str) -> "ShardingSpec":
        try:
            obj = json.loads(text)
        except ValueError as e:
            raise ShardingSpecError(f"spec is not JSON: {e}")
        if not isinstance(obj, dict):
            raise ShardingSpecError(f"spec must be a JSON object, got "
                                    f"{type(obj).__name__}")
        return cls.from_obj(obj)

    # -- constructors ------------------------------------------------------

    @classmethod
    def replicated(cls, k: int, ndim: int, *, axis: str = "ranks",
                   partial: bool = False) -> "ShardingSpec":
        """Fully replicated (or partial) spec on a 1-D k-device mesh."""
        return cls(((axis, k),), tuple(() for _ in range(ndim)),
                   partial)

    @classmethod
    def sharded(cls, k: int, ndim: int, dim: int, *,
                axis: str = "ranks") -> "ShardingSpec":
        """1-D mesh spec partitioning exactly array dimension `dim`."""
        return cls(((axis, k),),
                   tuple((axis,) if i == dim else ()
                         for i in range(ndim)))

    def describe(self) -> str:
        """Short human label ('S0@8', 'R@8', 'P@8') for logs/notes."""
        k = self.num_ranks
        if self.partial:
            return f"P@{k}"
        d = self.sharded_dim()
        return f"R@{k}" if d is None else f"S{d}@{k}"
