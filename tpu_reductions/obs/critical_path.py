"""Critical-path attribution over the reconstructed span tree.

Given a ledger's events, rebuild the spans (obs/trace_export.
build_spans) and sweep the global timeline asking, at every instant,
"which is the DEEPEST span active right now?" — the innermost open
span is the thing actually holding the session's wall clock (its
ancestors are just waiting on it). Merging adjacent instants with the
same answer yields the longest dependent chain, and bucketing each
segment by its span's leading dotted token (compile.*, staging.*,
chain.*, collective.*, serve.*, stream.*, ...) gives the per-phase
shares the window summary prints:

    window bounded by: compile 38% -> staging 22% -> chain 31%

This is deliberately a SEQUENTIAL-chain model, not a DAG scheduler
critique: on this platform one process owns the device lease at a
time (CLAUDE.md "Hard-won environment facts"), so the deepest active
span IS the bottleneck. Gaps where no span is open are attributed to
`idle` — on a flapping relay that is usually await-window time.

Offline by construction: stdlib only, no device, safe after an exit
3/4 (run it right after obs/timeline). sched/priors.py reads the
per-span medians (`span_medians`) to sharpen duration priors with
sub-task evidence. No reference analog (the cutil timer registry,
cutil.cpp:1567-1692, kept averages but never causality).
"""

from __future__ import annotations

from typing import Dict, List, Optional

from tpu_reductions.obs.trace_export import build_spans

# spans too coarse to be a bottleneck label — their CHILDREN hold the
# clock; only when nothing finer is open do they win a segment
_ENVELOPES = ("session", "request")


def _label(name: str) -> str:
    """Bucket a span name by its leading dotted token ("compile.start"
    sliced to "compile"); synthesized request spans -> "serve"."""
    tok = name.split(".")[0].split(" ")[0]
    return "serve" if tok == "request" else tok


def compute(events: List[dict], min_share: float = 0.01) -> Optional[dict]:
    """Critical path for one parsed ledger. Returns None when there is
    nothing to attribute, else {"wall_s", "segments": [{label, dur_s,
    share}], "shares": {label: share}, "chain": "a NN% -> b NN%"}
    with one segment per label (total seconds that label held the
    path), ordered by first appearance, shares over the whole wall
    clock, sub-`min_share` labels folded away."""
    spans = [s for s in build_spans(events) if s["t1"] > s["t0"]]
    if not spans:
        return None
    t0 = min(s["t0"] for s in spans)
    t1 = max(s["t1"] for s in spans)
    wall = t1 - t0
    if wall <= 0:
        return None
    # depth = how nested the span is at each instant; deepest wins,
    # envelope spans lose ties to anything more specific
    cuts = sorted({s["t0"] for s in spans} | {s["t1"] for s in spans})
    timeline: List[List] = []   # [label, dur]
    for a, b in zip(cuts, cuts[1:]):
        if b <= a:
            continue
        active = [s for s in spans if s["t0"] <= a and s["t1"] >= b]
        if not active:
            label = "idle"
        else:
            def _depth(s):
                return (sum(1 for o in active
                            if o["t0"] <= s["t0"] and o["t1"] >= s["t1"]
                            and o is not s),
                        _label(s["name"]) not in _ENVELOPES,
                        -(s["t1"] - s["t0"]))
            label = _label(max(active, key=_depth)["name"])
            if label in _ENVELOPES:
                label = "idle"
        if timeline and timeline[-1][0] == label:
            timeline[-1][1] += b - a
        else:
            timeline.append([label, b - a])
    # aggregate per label, ordered by FIRST appearance on the critical
    # path: a window with 200 alternating chain-trip/idle slivers reads
    # as "idle NN% -> chain NN%", not a 200-link chain — the headline
    # is where the wall clock went, in the order the window spent it
    shares: Dict[str, float] = {}
    order: List[str] = []
    for label, dur in timeline:
        if label not in shares:
            order.append(label)
        shares[label] = shares.get(label, 0.0) + dur / wall
    segments = [{"label": label, "dur_s": round(shares[label] * wall, 6),
                 "share": round(shares[label], 4)}
                for label in order if shares[label] >= min_share]
    if not segments:
        return None
    chain = " -> ".join(f"{s['label']} {s['share'] * 100:.0f}%"
                        for s in segments)
    return {"wall_s": round(wall, 6), "segments": segments,
            "shares": {k: round(v, 4) for k, v in sorted(
                shares.items(), key=lambda kv: -kv[1])},
            "chain": chain}


def span_medians(events: List[dict]) -> Dict[str, float]:
    """Median duration per span name across one ledger — the sub-task
    evidence sched/priors.py folds into its duration model (a task
    whose compile span is warm-cached shrinks by the compile
    median)."""
    import statistics
    by_name: Dict[str, List[float]] = {}
    for s in build_spans(events):
        if s["dur_s"] > 0 and not s["cut"]:
            by_name.setdefault(s["name"], []).append(s["dur_s"])
    return {name: round(statistics.median(v), 6)
            for name, v in sorted(by_name.items())}


def markdown(cp: Optional[dict]) -> List[str]:
    """The WINDOW_SUMMARY.md critical-path section (timeline
    --summary-md appends it; the session exit trap commits it)."""
    if not cp:
        return []
    lines = ["### critical path", "",
             f"window bounded by: {cp['chain']}", "",
             "| segment | wall s | share |", "| --- | --- | --- |"]
    for s in cp["segments"]:
        lines.append(f"| {s['label']} | {s['dur_s']:.3f} | "
                     f"{s['share'] * 100:.1f}% |")
    lines.append("")
    return lines
