"""Compile observatory: every XLA/Pallas compile as a typed, persisted
observation (ISSUE 8 tentpole).

The 20-40 s first Pallas tunnel compile is the single largest consumer
of a flap window (CLAUDE.md; ROADMAP item 5), yet until this module
nothing measured it: the flight recorder saw only a coarse `hb.phase
compile` interval, the scheduler folded cold-vs-warm into one static
budget prior, and `.jax_cache/` amortized compiles invisibly. Three
pieces fix that:

  * `compile_span(surface, ...)` — bracket one compile seam. Emits
    `compile.start`/`compile.end` ledger events (lint/grammar.py
    COMPILE_EVENTS) carrying the surface id (k8 / k9 / k10@depth / dd /
    stream / serve-bucket / chain / collective), platform, payload
    geometry, wall-clock duration, and the cache verdict — cold/warm,
    decided by fingerprinting `.jax_cache/` before and after
    (utils/compile_cache.py): new entries appeared => the compile was
    COLD; a populated cache gained nothing => WARM.
  * `probe_lower_compile(fn, *args, surface=...)` — the split probe for
    surfaces that permit AOT staging: `jax.jit(fn).lower(*args)` then
    `.compile()`, each half timed, both landing in one compile.end
    event (`lower_s` / `compile_s`). Surfaces that only compile lazily
    (the chained fori_loop entry, a bucket's first launch) use the
    plain wall-clock span instead.
  * `CompileLedger` — per-surface observations persisted into a
    committed `compile_ledger.json` on the bench/resume.Checkpoint
    artifact contract ({**meta, "complete": bool, "surfaces": [...]},
    atomic writes, `artifact.persist` events), with ONE deliberate
    deviation, documented here: prior rows merge in even from a
    `complete: true` artifact, because the observatory describes the
    persistent compile cache — which also survives across windows — so
    its knowledge is cumulative, not per-campaign. Keyed by (surface,
    platform, verdict): the artifact holds at most one cold and one
    warm row per surface per platform — exactly the cold/warm table
    the scheduler's priors and the report fold read.

`CompileModel` is the read side: the scheduler (sched/priors.py) asks
it whether a task's surfaces are cache-warm and how many cold-compile
seconds the cache already banked — the compile axis of the
value/expected-second cost model.

Import discipline: NO jax import at module load (the obs package stays
jax-free — the scheduler reads compile models while the relay is dead).
The span reads jax lazily and only when the process already imported
it; when the ledger is unarmed and no persistent path is configured, a
span costs two fingerprint stats and nothing else.

Arming: `TPU_REDUCTIONS_COMPILE_LEDGER` names the persistent artifact
(scripts/chip_session.sh exports `compile_ledger.json` and commits it
per step); unset = events only. `TPU_REDUCTIONS_OBS_DISABLE=1` turns
the whole observatory off with the rest of the recorder.
"""

from __future__ import annotations

import contextlib
import json
import os
import sys
import time
from typing import Dict, Iterable, List, Optional, Tuple

from tpu_reductions.obs import ledger
from tpu_reductions.utils import compile_cache

ENV_PATH = "TPU_REDUCTIONS_COMPILE_LEDGER"
DEFAULT_LEDGER = "compile_ledger.json"

_META = {"kind": "compile-observatory", "version": 1}


def _platform() -> Optional[str]:
    """The active jax backend, WITHOUT triggering backend init: a
    process that never imported jax (the scheduler with the relay dead)
    gets None, never a hang."""
    mod = sys.modules.get("jax")
    if mod is None:
        return None
    try:
        return mod.default_backend()
    except Exception:
        return None


def _row_key(row: dict) -> Tuple:
    return (row.get("surface"), row.get("platform"), row.get("verdict"))


class CompileLedger:
    """The persisted per-surface observation store (module docstring
    has the contract and its one documented deviation)."""

    def __init__(self, path: str) -> None:
        self.path = os.fspath(path)
        self._rows: Dict[Tuple, dict] = {}
        prior = self._load_prior()
        if prior is not None:
            for row in prior.get("surfaces", []):
                if isinstance(row, dict) and row.get("surface"):
                    self._rows[_row_key(row)] = row

    def _load_prior(self) -> Optional[dict]:
        try:
            data = json.loads(open(self.path).read())
        except (OSError, ValueError):
            return None   # absent or truncated pre-atomic: start empty
        if not isinstance(data, dict):
            return None
        if not all(data.get(k) == v for k, v in _META.items()):
            return None   # different contract version: never merge
        return data

    @property
    def rows(self) -> List[dict]:
        return sorted(self._rows.values(),
                      key=lambda r: (str(r.get("surface")),
                                     str(r.get("platform")),
                                     str(r.get("verdict"))))

    def record(self, row: dict) -> None:
        """Replace-or-insert one observation and persist atomically —
        the persist-per-row live-window discipline (a flap loses
        nothing already observed)."""
        key = _row_key(row)
        prev = self._rows.get(key)
        row = dict(row)
        row["count"] = (prev.get("count", 1) + 1) if prev else 1
        self._rows[key] = row
        self._persist(complete=False)

    def finalize(self) -> None:
        """Mark the artifact complete (the warm CLI's end-of-pass
        stamp; seam processes leave it incomplete by design — the
        observatory is always open for more observations)."""
        self._persist(complete=True)

    def _persist(self, complete: bool) -> None:
        from tpu_reductions.utils.jsonio import atomic_json_dump
        rows = self.rows
        atomic_json_dump(self.path, {**_META, "complete": complete,
                                     "surfaces": rows})
        ledger.emit("artifact.persist", path=self.path, rows=len(rows),
                    complete=complete, grain="compile")


_armed: Optional[CompileLedger] = None
_last: Optional[dict] = None


def last_observation() -> Optional[dict]:
    """The most recent compile_span's full observation row (the warm
    CLI reads it back right after each probe; None before any span)."""
    return _last


def arm(path: Optional[str] = None) -> Optional[CompileLedger]:
    """Open (or reuse) the persistent observation store: explicit path,
    else TPU_REDUCTIONS_COMPILE_LEDGER, else whatever an entry point
    already armed this process (the span seams call `arm()` bare), else
    off (events only)."""
    global _armed
    if ledger.disabled():
        return None
    if path is None:
        path = os.environ.get(ENV_PATH) or None
        if path is None:
            return _armed
    path = os.fspath(path)
    if _armed is None or _armed.path != path:
        _armed = CompileLedger(path)
    return _armed


def disarm() -> None:
    """Drop the armed store (tests)."""
    global _armed
    _armed = None


@contextlib.contextmanager
def compile_span(surface: str, **fields):
    """Bracket one compile seam (module docstring). Yields a mutable
    dict the caller may extend with split timings (`lower_s`,
    `compile_s` — probe_lower_compile does); everything in it rides the
    compile.end event and the persisted row. Never raises on its own:
    the observed compile's exceptions pass through untouched, recorded
    as `error` on the end event."""
    from tpu_reductions.obs import trace
    before = compile_cache.fingerprint()
    # one child trace context for the whole seam (ISSUE 12): the
    # start/end pair share a span id and nested emits parent under it,
    # so compile spans gain causal parentage in the trace tree for free
    with trace.child():
        ledger.emit("compile.start", surface=surface, **fields)
        obs: dict = {}
        t0 = time.monotonic()
        err = None
        try:
            yield obs
        except BaseException as e:
            err = f"{type(e).__name__}: {e}"[:200]
            raise
        finally:
            dur = round(time.monotonic() - t0, 6)
            after = compile_cache.fingerprint()
            verdict = compile_cache.verdict(before, after)
            row = {"surface": surface, "platform": _platform(),
                   "verdict": verdict, "dur_s": dur,
                   "cache_new": len(after - before), **fields, **obs}
            if err is not None:
                row["error"] = err
            global _last
            _last = row
            ledger.emit("compile.end", **row)
            store = arm()
            if store is not None and err is None:
                store.record({k: v for k, v in row.items()
                              if k != "cache_new"})


def probe_lower_compile(fn, *args, surface: str, **fields):
    """The lower/compile split probe: stage `fn` AOT —
    `jit(fn).lower(*args)` then `.compile()` — inside one compile_span,
    with each half's wall-clock on the compile.end event. `fn` may
    already be a jit-wrapped callable (its own `.lower` is used, so the
    probed executable is EXACTLY the one later calls hit — warming a
    re-wrapped copy would populate a different cache key). Returns the
    compiled executable (callable with the same args). Use where the
    surface permits AOT staging; lazy-compiling seams use compile_span
    alone."""
    import jax
    staged = fn if hasattr(fn, "lower") else jax.jit(fn)
    with compile_span(surface, **fields) as obs:
        t0 = time.monotonic()
        lowered = staged.lower(*args)
        obs["lower_s"] = round(time.monotonic() - t0, 6)
        t1 = time.monotonic()
        compiled = lowered.compile()
        obs["compile_s"] = round(time.monotonic() - t1, 6)
    return compiled


def load(path: str = DEFAULT_LEDGER) -> Optional[dict]:
    """The committed artifact, parsed (None when absent/foreign) — the
    read primitive CompileModel and bench/regen share."""
    try:
        data = json.loads(open(path).read())
    except (OSError, ValueError):
        return None
    if not isinstance(data, dict) or not all(
            data.get(k) == v for k, v in _META.items()):
        return None
    return data


class CompileModel:
    """The scheduler-facing read model over a committed
    compile_ledger.json: which surfaces are cache-warm right now, and
    how many cold-compile seconds the cache banked (sched/priors.py
    folds this into the per-task duration estimate)."""

    def __init__(self, rows: Iterable[dict] = ()) -> None:
        self._by_surface: Dict[str, Dict[str, dict]] = {}
        for row in rows:
            if not isinstance(row, dict):
                continue
            s, v = row.get("surface"), row.get("verdict")
            if isinstance(s, str) and isinstance(v, str):
                self._by_surface.setdefault(s, {})[v] = row

    @classmethod
    def from_file(cls, path: str = DEFAULT_LEDGER,
                  platform: Optional[str] = None) -> "CompileModel":
        """Load from the committed artifact; `platform` keeps only
        rows observed on that backend (a cpu-warm surface says nothing
        about the tunnel cache) — rows without a platform stamp pass
        either way."""
        data = load(path)
        rows = (data or {}).get("surfaces", [])
        if platform is not None:
            rows = [r for r in rows if isinstance(r, dict)
                    and r.get("platform") in (platform, None)]
        return cls(rows)

    def known(self, surface: str) -> bool:
        return surface in self._by_surface

    def is_warm(self, surface: str) -> bool:
        """Warm = a warm observation exists, or a cold one does and the
        persistent cache it populated is still on disk (the cold
        compile's entries make the NEXT one warm by construction)."""
        obs = self._by_surface.get(surface)
        if not obs:
            return False
        if "warm" in obs:
            return True
        return "cold" in obs and bool(compile_cache.fingerprint())

    def _dur(self, surface: str, verdict: str) -> Optional[float]:
        row = self._by_surface.get(surface, {}).get(verdict)
        d = (row or {}).get("dur_s")
        return float(d) if isinstance(d, (int, float)) else None

    def saved_s(self, surfaces: Iterable[str]) -> float:
        """Cold-minus-warm seconds the cache banks across `surfaces`
        that are warm right now — what a task's estimate may shed."""
        total = 0.0
        for s in surfaces:
            if not self.is_warm(s):
                continue
            cold = self._dur(s, "cold")
            if cold is None:
                continue
            total += max(cold - (self._dur(s, "warm") or 0.0), 0.0)
        return total

    def status(self, surfaces: Iterable[str]) -> str:
        """One word for the plan table's cold/warm column: 'warm'
        (every known surface warm), 'cold' (none warm), 'mixed', or
        '-' (no surfaces declared / nothing observed)."""
        surfaces = list(surfaces)
        if not surfaces:
            return "-"
        known = [s for s in surfaces if self.known(s)]
        if not known:
            return "-"
        warm = [self.is_warm(s) for s in known]
        if all(warm) and len(known) == len(surfaces):
            return "warm"
        return "mixed" if any(warm) else "cold"


def compile_markdown(data: dict) -> str:
    """The per-surface cold/warm compile-latency table for report.md
    (bench/regen.py folds it next to the GB/s tables) — pure formatting
    over the committed artifact."""
    rows = [r for r in data.get("surfaces", []) if isinstance(r, dict)]
    lines = ["## compile observatory (per-surface cold/warm)", "",
             "| surface | platform | verdict | lower s | compile s "
             "| total s | obs |",
             "|---|---|---|---|---|---|---|"]
    if not rows:
        lines.append("| (no observations) | - | - | - | - | - | - |")
    for r in rows:
        def _f(key):
            v = r.get(key)
            return f"{v:.3f}" if isinstance(v, (int, float)) else "-"
        lines.append(
            f"| {r.get('surface', '?')} | {r.get('platform') or '-'} "
            f"| {r.get('verdict', '?')} | {_f('lower_s')} "
            f"| {_f('compile_s')} | {_f('dur_s')} "
            f"| {r.get('count', 1)} |")
    state = "complete" if data.get("complete") else "open"
    lines.append("")
    lines.append(f"observatory: {state}; cold surfaces re-pay their "
                 "compile next window, warm ones serve from "
                 ".jax_cache/")
    return "\n".join(lines)
