"""Post-mortem timeline: reconstruct chip sessions from a flight
recorder ledger and attribute wall-clock per phase.

The reference audited runs by re-reading accumulated logs offline
(getAvgs.sh over stdout-*; the shrLog master log). This is that
analysis layer for the event ledger (obs/ledger.py): purely offline,
never touches a device, safe to run the moment a watchdog exit 3/4
hands control back — docs/RESILIENCE.md's runbook says to run it
FIRST.

What it computes, per session (one `session.start`..end/exit stream
per pid) and for the window as a whole:

  * a chronological narrative (every event, T+offset from the ledger's
    first event — the firstrow timeline generalized to every entry
    point);
  * per-phase wall-clock attribution from the heartbeat phase
    transitions (`hb.phase` events, utils/heartbeat.py): measure /
    compile / staging / host, with retry backoff carved out of host
    time (retry.attempt events) and exit-4 stall age carved out of the
    stalled guard's bucket (watchdog.exit events) — so "where did the
    minutes go" has a machine answer;
  * window-utilization metrics: the fraction of recorded seconds spent
    measuring vs compiling vs staging vs retrying vs stalled;
  * critical-path attribution over the causal span tree (ISSUE 12;
    obs/critical_path.py): the longest dependent chain and its
    per-segment shares — "window bounded by: compile 38% -> staging
    22% -> chain 31%" — rendered into the --summary-md output.

A ledger whose size cap rotated it mid-session is read WHOLE: the
`<ledger>.1` segment is stitched back in front of the live file by
read_ledger, for every consumer (timeline, obs/trace_export,
sched/priors).

Outputs: a text report (default), `--json OUT` (summary JSON written
atomically via utils/jsonio — bench/regen collates it into report.md),
and `--summary-md` (the WINDOW_SUMMARY.md per-window utilization
table, so the next live round's summary is computed, not hand-written).

Torn/unparseable lines are COUNTED and reported, never fatal: the
ledger's single-write append contract makes them impossible in normal
operation, so a nonzero count is itself a finding.

CLI:
    python -m tpu_reductions.obs.timeline <ledger.jsonl> \
        [--json OUT] [--summary-md] [--max-events N]
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import List, Optional, Tuple

BUCKETS = ("measure", "compile", "staging", "retrying", "stalled",
           "host")


def _bucket(phase: Optional[str]) -> str:
    """Map a heartbeat phase label to an attribution bucket. Unknown
    guarded phases (chained/fetch/bulk/periter/device/steady/...) are
    measurement by construction — only guarded device regions carry a
    phase at all (utils/heartbeat.py)."""
    if phase is None:
        return "host"
    if phase == "compile":
        return "compile"
    if phase == "staging":
        return "staging"
    return "measure"


def read_ledger(path) -> Tuple[List[dict], int]:
    """Parse a JSONL ledger -> (events sorted by t, torn_line_count).
    A line that fails to parse, or parses to something that is not an
    event row, counts as torn. A rotated predecessor segment
    `<path>.1` (obs/ledger.py's size-cap rotation renames the full
    file there) is stitched back IN FRONT of the live file, so a
    session whose ledger rolled over mid-run reads whole — every
    consumer of this reader (timeline, trace_export, sched/priors)
    gets the stitch for free. OSError only when no segment exists."""
    events: List[dict] = []
    torn = 0

    def _parse(fobj) -> None:
        nonlocal torn
        for line in fobj:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                torn += 1
                continue
            if isinstance(rec, dict) and isinstance(rec.get("t"),
                                                    (int, float)) \
                    and isinstance(rec.get("ev"), str):
                events.append(rec)
            else:
                torn += 1

    rotated = f"{path}.1"
    stitched = False
    try:
        with open(rotated, errors="replace") as f:
            _parse(f)
        stitched = True
    except OSError:
        pass
    try:
        with open(path, errors="replace") as f:
            _parse(f)
    except OSError:
        if not stitched:
            raise
    events.sort(key=lambda e: e["t"])
    return events, torn


def split_sessions(events: List[dict]) -> List[dict]:
    """Group events into sessions: per pid, a new session opens at each
    `session.start` (events before one — e.g. shell supervisor events —
    form their own leading pseudo-session). Sessions order by first
    event time."""
    by_pid: dict = {}
    for e in events:
        by_pid.setdefault(e.get("pid"), []).append(e)
    sessions = []
    for pid, evs in by_pid.items():
        cur = None
        for e in evs:
            if e["ev"] == "session.start" or cur is None:
                cur = {"pid": pid, "events": []}
                sessions.append(cur)
            cur["events"].append(e)
    sessions.sort(key=lambda s: s["events"][0]["t"])
    return sessions


def analyze_session(sess: dict) -> dict:
    """Per-phase wall-clock attribution for one session (module
    docstring has the carving rules)."""
    evs = sess["events"]
    t0, t1 = evs[0]["t"], evs[-1]["t"]
    buckets = dict.fromkeys(BUCKETS, 0.0)
    phase: Optional[str] = None
    retry_s = 0.0
    exit_event = None
    prog = next((e.get("prog") for e in evs
                 if e["ev"] == "session.start"), None)
    for i, e in enumerate(evs):
        if e["ev"] == "hb.phase":
            phase = e.get("phase")
        if e["ev"] == "retry.attempt":
            d = e.get("delay_s")
            retry_s += float(d) if isinstance(d, (int, float)) else 0.0
        if e["ev"] == "watchdog.exit" and exit_event is None:
            exit_event = e
        nxt = evs[i + 1]["t"] if i + 1 < len(evs) else t1
        buckets[_bucket(phase)] += max(0.0, nxt - e["t"])
    # retry backoff sleeps run between guards (phase None -> host):
    # carve them into their own bucket, bounded so clock skew between
    # events can never drive host time negative
    carve = min(retry_s, buckets["host"])
    buckets["host"] -= carve
    buckets["retrying"] += carve
    # an exit-4 hang accrued its no-progress age inside the stalled
    # guard's phase bucket — reattribute it as stalled time
    if exit_event is not None and exit_event.get("code") == 4:
        age = exit_event.get("age_s")
        age = float(age) if isinstance(age, (int, float)) else 0.0
        b = _bucket(exit_event.get("phase"))
        carve = min(age, buckets[b])
        buckets[b] -= carve
        buckets["stalled"] += carve
    wall = max(t1 - t0, 0.0)
    ended = any(e["ev"] == "session.end" for e in evs)
    if exit_event is not None:
        end = f"exit {exit_event.get('code')}"
    elif ended:
        end = "end"
    else:
        end = "cut"       # no terminal event: SIGKILL-class death
    return {
        "pid": sess["pid"],
        "prog": prog,
        "t0": t0, "t1": t1,
        "wall_s": round(wall, 6),
        "end": end,
        "events": len(evs),
        "phases_s": {k: round(v, 6) for k, v in buckets.items()},
        "utilization": {k: (round(v / wall, 4) if wall > 0 else 0.0)
                        for k, v in buckets.items()},
        "persists": sum(1 for e in evs if e["ev"] == "artifact.persist"),
        "reused_rows": sum(1 for e in evs if e["ev"] == "resume.reuse"),
        "retries": sum(1 for e in evs if e["ev"] == "retry.attempt"),
        "faults": sum(1 for e in evs if e["ev"] == "fault.fire"),
    }


def sched_summary(events: List[dict]) -> Optional[dict]:
    """Plan-vs-actual attribution from the scheduler's typed events
    (sched.plan/pick/skip/done/replan — lint/grammar.py SCHED_EVENTS;
    tpu_reductions/sched/). One record per task in first-pick order:
    planned vs actual seconds and the settled status, plus the replan
    count — the committed answer to 'what did the planner promise and
    what did the window deliver'. None when no scheduler ran."""
    tasks: dict = {}
    order: List[str] = []
    replans = 0
    for e in events:
        ev = e["ev"]
        if ev not in ("sched.pick", "sched.done", "sched.skip",
                      "sched.replan", "sched.plan"):
            continue
        if ev == "sched.replan":
            replans += 1
            continue
        if ev == "sched.plan":
            continue
        name = e.get("task")
        if not isinstance(name, str):
            continue
        if name not in tasks:
            tasks[name] = {"task": name, "planned_s": None,
                           "actual_s": None, "status": None}
            order.append(name)
        rec = tasks[name]
        if ev == "sched.pick":
            rec["planned_s"] = e.get("est_s")
            rec["status"] = rec["status"] or "picked"
        elif ev == "sched.done":
            rec["actual_s"] = e.get("actual_s")
            rec["status"] = e.get("status") or "done"
        elif ev == "sched.skip":
            rec["status"] = "skipped"
            rec["reason"] = e.get("reason")
    if not tasks:
        return None
    return {"tasks": [tasks[n] for n in order], "replans": replans}


def _percentile(sorted_vals: List[float], q: float) -> float:
    idx = min(len(sorted_vals) - 1,
              max(0, int(round(q * (len(sorted_vals) - 1)))))
    return sorted_vals[idx]


def serve_summary(events: List[dict]) -> Optional[dict]:
    """Per-request latency attribution from the serving engine's typed
    events (serve.enqueue/coalesce/launch/verify/respond —
    lint/grammar.py SERVE_EVENTS; tpu_reductions/serve/). The post-hoc
    answer ISSUE 6 requires: how many requests, how they resolved,
    where their milliseconds went (queued vs in-launch — the engine
    stamps queue_s/latency_s on every respond event), and how hard
    coalescing worked (batches, mean size). None when no engine ran.

    Requests JOIN BY ID (ISSUE 12 satellite): the `req` field — the
    request's trace id, obs/trace.request_context — keys every
    enqueue→respond pair, so the latency split never misaligns under
    reordered completion, and the mismatches are FLAGGED (`orphans`):
    an admitted request that never got a respond (a torn session), or
    a non-rejected respond with no enqueue (rejected responds are
    legitimately enqueue-less — admission control sheds before the
    queue)."""
    enq = [e for e in events if e["ev"] == "serve.enqueue"]
    responds = [e for e in events if e["ev"] == "serve.respond"]
    launches = [e for e in events if e["ev"] == "serve.launch"]
    sheds = [e for e in events if e["ev"] == "serve.shed"]
    routes = [e for e in events if e["ev"] == "route.done"]
    if not enq and not responds and not routes:
        return None
    by_status: dict = {}
    for e in responds:
        s = e.get("status") or "?"
        by_status[s] = by_status.get(s, 0) + 1
    out = {"requests": len(enq), "responses": len(responds),
           "by_status": by_status, "batches": len(launches),
           "shed_episodes": len(sheds)}
    shards = sum(1 for e in events if e["ev"] == "serve.shard")
    if shards:
        out["sharded_launches"] = shards
    router = _router_summary(events, routes)
    if router:
        out["router"] = router
    sizes = [e["size"] for e in launches
             if isinstance(e.get("size"), int)]
    if sizes:
        out["mean_batch"] = round(sum(sizes) / len(sizes), 2)
    pending = {e["req"] for e in enq if isinstance(e.get("req"), str)}
    joined: List[dict] = []
    orphan_responses = 0
    for e in responds:
        rid = e.get("req")
        if isinstance(rid, str) and rid in pending:
            pending.discard(rid)
            joined.append(e)
        elif e.get("status") != "rejected":
            orphan_responses += 1
    if pending or orphan_responses:
        out["orphans"] = {"requests": len(pending),
                          "responses": orphan_responses}
    ok_lat = sorted(e["latency_s"] for e in joined
                    if e.get("status") == "ok"
                    and isinstance(e.get("latency_s"), (int, float)))
    if ok_lat:
        out["latency_s"] = {"p50": round(_percentile(ok_lat, 0.5), 6),
                            "p99": round(_percentile(ok_lat, 0.99), 6)}
    queued = sorted(e["queue_s"] for e in joined
                    if isinstance(e.get("queue_s"), (int, float)))
    if queued:
        out["queue_s"] = {"p50": round(_percentile(queued, 0.5), 6),
                          "p99": round(_percentile(queued, 0.99), 6)}
    return out


def _router_summary(events: List[dict],
                    routes: List[dict]) -> Optional[dict]:
    """Per-replica attribution from the router's typed events
    (lint/grammar.py ROUTE_EVENTS/REPLICA_EVENTS; serve/router.py —
    the ISSUE 13 satellite): per replica, how many terminal outcomes
    it served with what latency tail and how much of the shed/error
    weight it carried; plus the re-route and replica-death record
    (how much work moved because a replica failed). None when no
    router ran."""
    reroutes = [e for e in events if e["ev"] == "route.reroute"]
    downs = [e for e in events if e["ev"] == "replica.down"]
    if not routes and not reroutes and not downs:
        return None
    per: dict = {}
    for e in routes:
        rep = e.get("replica") or "(none)"
        d = per.setdefault(rep, {"requests": 0, "ok": 0, "shed": 0,
                                 "error": 0, "_lat": []})
        d["requests"] += 1
        s = e.get("status")
        if s in d:
            d[s] += 1
        if s == "ok" and isinstance(e.get("latency_s"), (int, float)):
            d["_lat"].append(e["latency_s"])
    for e in reroutes:
        rep = e.get("replica") or "(none)"
        d = per.setdefault(rep, {"requests": 0, "ok": 0, "shed": 0,
                                 "error": 0, "_lat": []})
        d["rerouted_away"] = d.get("rerouted_away", 0) + 1
    for rep, d in per.items():
        lat = sorted(d.pop("_lat"))
        if lat:
            d["latency_s"] = {"p50": round(_percentile(lat, 0.5), 6),
                              "p99": round(_percentile(lat, 0.99), 6)}
    return {"routed": len(routes), "reroutes": len(reroutes),
            "replica_downs": [{"replica": e.get("replica"),
                               "reason": e.get("reason")}
                              for e in downs],
            "replicas": per}


def stream_summary(events: List[dict]) -> Optional[dict]:
    """Streaming-pipeline attribution from the stream.* typed events
    (lint/grammar.py STREAM_EVENTS; ops/stream.py + bench/stream.py).
    The committed answer to the ISSUE-7 acceptance question: how many
    chunks streamed at what sustained rate, how often the honest
    partial materialized, whether any stream resumed mid-payload, and
    — when the serial comparator ran — the overlap efficiency
    (serial stage-then-reduce wall-clock over streamed wall-clock;
    > 1 means transfer/compute overlap paid off). None when no stream
    ran."""
    starts = [e for e in events if e["ev"] == "stream.start"]
    ends = [e for e in events if e["ev"] == "stream.end"]
    if not starts and not ends:
        return None
    out = {
        "streams": len(starts),
        "chunks": sum(1 for e in events if e["ev"] == "stream.chunk"),
        "syncs": sum(1 for e in events if e["ev"] == "stream.sync"),
        "resumed": sum(1 for e in starts
                       if isinstance(e.get("start_chunk"), int)
                       and e["start_chunk"] > 0),
    }
    rates = [e["gbps"] for e in ends
             if isinstance(e.get("gbps"), (int, float))]
    if rates:
        out["gbps_sustained"] = round(max(rates), 4)
    cps = [e["chunks_per_s"] for e in ends
           if isinstance(e.get("chunks_per_s"), (int, float))]
    if cps:
        out["chunks_per_s"] = round(max(cps), 4)
    overlaps = [e for e in events if e["ev"] == "stream.overlap"]
    if overlaps:
        last = overlaps[-1]
        for key in ("stream_wall_s", "serial_wall_s"):
            if isinstance(last.get(key), (int, float)):
                out[key] = last[key]
        if isinstance(last.get("efficiency"), (int, float)):
            out["overlap_efficiency"] = last["efficiency"]
    return out


def collective_summary(events: List[dict]) -> Optional[dict]:
    """Collective-phase attribution from the collective.* typed events
    (lint/grammar.py COLLECTIVE_EVENTS; bench/collective_driver.py +
    bench/quant_curve.py). The ISSUE-10 answer to "where did the
    collective minutes go": per selected algorithm, how many launches
    ran and how much wall-clock their device phases took (the
    launch/done brackets), plus how often the selector fell back off
    its first choice (a select whose note marks a degrade). None when
    no collective ran."""
    selects = [e for e in events if e["ev"] == "collective.select"]
    dones = [e for e in events if e["ev"] == "collective.done"]
    launches = sum(1 for e in events if e["ev"] == "collective.launch")
    if not selects and not dones and not launches:
        return None
    algos: dict = {}
    order: List[str] = []
    total_s = 0.0
    for e in dones:
        a = e.get("algorithm")
        if not isinstance(a, str):
            continue
        if a not in algos:
            algos[a] = {"algorithm": a, "launches": 0, "wall_s": 0.0}
            order.append(a)
        algos[a]["launches"] += 1
        d = e.get("wall_s")
        if isinstance(d, (int, float)):
            algos[a]["wall_s"] += float(d)
            total_s += float(d)
    for rec in algos.values():
        rec["wall_s"] = round(rec["wall_s"], 6)
    return {"selects": len(selects), "launches": launches,
            "collective_s": round(total_s, 6),
            "algorithms": [algos[a] for a in order]}


def reshard_summary(events: List[dict]) -> Optional[dict]:
    """Per-primitive redistribution attribution from the reshard.*
    typed events (lint/grammar.py RESHARD_EVENTS; reshard/
    primitives.execute_plan). The ISSUE-15 answer to "where did the
    reshard minutes go": per primitive (all_gather / dynamic_slice /
    collective_permute / reduce_scatter), how many steps ran and how
    much wall-clock they took to host materialization, plus how many
    whole programs executed. None when no reshard ran."""
    plans = sum(1 for e in events if e["ev"] == "reshard.plan")
    steps = [e for e in events if e["ev"] == "reshard.step"]
    dones = [e for e in events if e["ev"] == "reshard.done"]
    if not plans and not steps and not dones:
        return None
    prims: dict = {}
    order: List[str] = []
    total_s = 0.0
    for e in steps:
        p = e.get("primitive")
        if not isinstance(p, str):
            continue
        if p not in prims:
            prims[p] = {"primitive": p, "steps": 0, "wall_s": 0.0}
            order.append(p)
        prims[p]["steps"] += 1
        d = e.get("wall_s")
        if isinstance(d, (int, float)):
            prims[p]["wall_s"] += float(d)
            total_s += float(d)
    for rec in prims.values():
        rec["wall_s"] = round(rec["wall_s"], 6)
    return {"plans": plans, "programs": len(dones),
            "reshard_s": round(total_s, 6),
            "primitives": [prims[p] for p in order]}


def autoscale_summary(events: List[dict]) -> Optional[dict]:
    """Replica-count-vs-load attribution from the elastic fleet's
    typed events (lint/grammar.py AUTOSCALE_EVENTS/DRAIN_EVENTS;
    serve/autoscale.py — ISSUE 17). Per tick: how many replicas were
    active against what per-replica load; per action: scale-ups with
    their prewarm counts and scale-downs with the drain protocol's
    evidence (wait wall-clock, handed-off keys, shed count, the
    oracle verdict on the redistribution program). None when no
    autoscaler ran."""
    ticks = [e for e in events if e["ev"] == "autoscale.tick"]
    ups = [e for e in events if e["ev"] == "autoscale.up"]
    downs = [e for e in events if e["ev"] == "autoscale.down"]
    dones = [e for e in events if e["ev"] == "drain.done"]
    if not ticks and not ups and not downs and not dones:
        return None
    counts = [e["replicas"] for e in ticks
              if isinstance(e.get("replicas"), int)]
    loads = [float(e["load_per_replica"]) for e in ticks
             if isinstance(e.get("load_per_replica"), (int, float))]
    resh_by_replica = {e.get("replica"): e for e in events
                       if e["ev"] == "drain.reshard"}
    drains = []
    for e in dones:
        rec = {"replica": e.get("replica"),
               "waited_s": e.get("waited_s"),
               "keys": e.get("keys"),
               "shed": e.get("shed"), "expired": e.get("expired"),
               "reshard_ok": e.get("reshard_ok")}
        resh = resh_by_replica.get(e.get("replica"))
        if resh is not None:
            rec["program"] = resh.get("program")
            rec["reshard_s"] = resh.get("wall_s")
            rec["measured_mem_factor"] = resh.get("measured_mem_factor")
        drains.append(rec)
    out = {"ticks": len(ticks), "ups": len(ups), "downs": len(downs),
           "prewarmed": sum(int(e.get("prewarmed", 0)) for e in ups),
           "drains": drains}
    if counts:
        out["replicas_min"] = min(counts)
        out["replicas_max"] = max(counts)
    if loads:
        out["load_max"] = round(max(loads), 4)
    return out


def recovery_summary(events: List[dict]) -> Optional[dict]:
    """Crash-recovery attribution from the control plane's typed
    events (lint/grammar.py JOURNAL_EVENTS/ADOPT_EVENTS + serve.dedup;
    serve/journal.py, serve/router.adopt_fleet — ISSUE 18). Per
    recovery: the adopt.begin -> adopt.done wall clock IS the MTTR
    evidence, with the per-child verdicts (adopted vs INT-first
    reaped vs already gone) and the exactly-once record (dedup cache
    hits that answered retried keys without re-touching the device).
    None when no journal was in play."""
    begins = [e for e in events if e["ev"] == "adopt.begin"]
    dones = [e for e in events if e["ev"] == "adopt.done"]
    reps = [e for e in events if e["ev"] == "adopt.replica"]
    journal_records = sum(1 for e in events
                          if e["ev"] == "journal.record")
    replays = [e for e in events if e["ev"] == "journal.replay"]
    dedup_hits = sum(1 for e in events if e["ev"] == "serve.dedup")
    if not begins and not dones and not journal_records \
            and not replays and not dedup_hits:
        return None
    verdicts: dict = {}
    for e in reps:
        v = e.get("verdict")
        if isinstance(v, str):
            verdicts[v] = verdicts.get(v, 0) + 1
    recoveries = []
    for e in dones:
        recoveries.append({"adopted": e.get("adopted"),
                           "reaped": e.get("reaped"),
                           "mttr_s": e.get("wall_s")})
    out = {"recoveries": len(dones),
           "adopted": sum(int(e.get("adopted", 0)) for e in dones),
           "reaped": sum(int(e.get("reaped", 0)) for e in dones),
           "verdicts": verdicts,
           "journal_records": journal_records,
           "journal_replays": len(replays),
           "dedup_hits": dedup_hits}
    mttrs = [r["mttr_s"] for r in recoveries
             if isinstance(r["mttr_s"], (int, float))]
    if mttrs:
        out["mttr_max_s"] = round(max(float(m) for m in mttrs), 6)
    if recoveries:
        out["per_recovery"] = recoveries
    return out


def compile_summary(events: List[dict]) -> Optional[dict]:
    """Per-surface compile attribution from the compile observatory's
    typed events (compile.start/end, warm.* — lint/grammar.py
    COMPILE_EVENTS; obs/compile.py). The committed answer to the
    ISSUE-8 acceptance question: which surfaces compiled this window,
    cold or warm (the .jax_cache verdict), at what cost, and how much
    of the recorded window went to compiling at all. None when no
    instrumented compile ran."""
    ends = [e for e in events if e["ev"] == "compile.end"]
    if not ends:
        return None
    surfaces: dict = {}
    order: List[str] = []
    total_s = 0.0
    for e in ends:
        s = e.get("surface")
        if not isinstance(s, str):
            continue
        if s not in surfaces:
            surfaces[s] = {"surface": s, "count": 0, "cold_s": None,
                           "warm_s": None, "last_verdict": None,
                           "errors": 0}
        rec = surfaces[s]
        rec["count"] += 1
        d = e.get("dur_s")
        d = float(d) if isinstance(d, (int, float)) else 0.0
        total_s += d
        v = e.get("verdict")
        if v in ("cold", "warm"):
            rec[f"{v}_s"] = d
        rec["last_verdict"] = v
        if e.get("error"):
            rec["errors"] += 1
        if s not in order:
            order.append(s)
    out = {"compiles": len(ends), "compile_s": round(total_s, 6),
           "surfaces": [surfaces[s] for s in order]}
    warm_runs = sum(1 for e in events if e["ev"] == "warm.end")
    if warm_runs:
        out["warm_runs"] = warm_runs
    return out


def exec_summary(events: List[dict]) -> Optional[dict]:
    """Execution-core attribution from the exec.* typed events
    (lint/grammar.py EXEC_EVENTS; exec/core.run + exec/cost.py —
    ISSUE 19). Per surface: how many LaunchPlans were declared, how
    many completed (the exec.plan vs exec.done join IS the
    duplicate-launch audit the chaos suite runs), how many failed, and
    the wall-clock the core attributed to each. Plus every cost-oracle
    decision (exec.select) with its static baseline, so a regime flip
    is visible in the window record, not just in exec_decisions.json.
    None when no plan executed."""
    plans = [e for e in events if e["ev"] == "exec.plan"]
    selects = [e for e in events if e["ev"] == "exec.select"]
    launches = sum(1 for e in events if e["ev"] == "exec.launch")
    dones = [e for e in events if e["ev"] == "exec.done"]
    if not plans and not selects and not launches and not dones:
        return None
    surfaces: dict = {}
    order: List[str] = []
    total_s = 0.0
    failures = 0

    def rec_for(e: dict) -> Optional[dict]:
        s = e.get("surface")
        if not isinstance(s, str):
            return None
        if s not in surfaces:
            surfaces[s] = {"surface": s, "kind": e.get("kind"),
                           "plans": 0, "done": 0, "failed": 0,
                           "wall_s": 0.0}
            order.append(s)
        return surfaces[s]

    for e in plans:
        rec = rec_for(e)
        if rec is not None:
            rec["plans"] += 1
            if rec["kind"] is None and isinstance(e.get("kind"), str):
                rec["kind"] = e["kind"]
    for e in dones:
        rec = rec_for(e)
        if rec is None:
            continue
        rec["done"] += 1
        if e.get("ok") is False:
            rec["failed"] += 1
            failures += 1
        d = e.get("wall_s")
        if isinstance(d, (int, float)):
            rec["wall_s"] += float(d)
            total_s += float(d)
    for rec in surfaces.values():
        rec["wall_s"] = round(rec["wall_s"], 6)
    sel_rows = [{"axis": e.get("axis"), "choice": e.get("choice"),
                 "static_choice": e.get("static"),
                 "flipped": bool(e.get("flipped",
                                       e.get("choice") != e.get("static"))),
                 "reason": e.get("reason")} for e in selects]
    return {"plans": len(plans), "launches": launches,
            "done": len(dones), "failures": failures,
            "exec_s": round(total_s, 6),
            "surfaces": [surfaces[s] for s in order],
            "selects": sel_rows}


def summarize(path, events: List[dict], torn: int) -> dict:
    """The machine-readable summary JSON (bench/regen collates it into
    report.md; chip_session.sh persists it as obs_timeline.json)."""
    sessions = [analyze_session(s) for s in split_sessions(events)]
    out = {"ledger": str(path), "events": len(events),
           "torn_lines": torn, "sessions": sessions}
    sched = sched_summary(events)
    if sched is not None:
        out["sched"] = sched
    serve = serve_summary(events)
    if serve is not None:
        out["serve"] = serve
    stream = stream_summary(events)
    if stream is not None:
        out["stream"] = stream
    coll = collective_summary(events)
    if coll is not None:
        out["collective"] = coll
    resh = reshard_summary(events)
    if resh is not None:
        out["reshard"] = resh
    auto = autoscale_summary(events)
    if auto is not None:
        out["autoscale"] = auto
    rec = recovery_summary(events)
    if rec is not None:
        out["recovery"] = rec
    comp = compile_summary(events)
    if comp is not None:
        out["compile"] = comp
    execu = exec_summary(events)
    if execu is not None:
        out["exec"] = execu
    from tpu_reductions.obs import critical_path as _cp
    cp = _cp.compute(events)
    if cp is not None:
        out["critical_path"] = cp
    if events:
        t0, t1 = events[0]["t"], events[-1]["t"]
        wall = max(t1 - t0, 0.0)
        totals = dict.fromkeys(BUCKETS, 0.0)
        for s in sessions:
            for k, v in s["phases_s"].items():
                totals[k] += v
        recorded = sum(totals.values())
        out["window"] = {
            "t0": t0, "t1": t1, "wall_s": round(wall, 6),
            "recorded_s": round(recorded, 6),
            "phases_s": {k: round(v, 6) for k, v in totals.items()},
            "utilization": {k: (round(v / recorded, 4)
                                if recorded > 0 else 0.0)
                            for k, v in totals.items()},
        }
    return out


def _fmt_event(e: dict, t0: float) -> str:
    skip = {"t", "ev", "pid"}
    detail = " ".join(f"{k}={e[k]}" for k in e if k not in skip)
    return f"  T+{e['t'] - t0:9.3f}s [{e.get('pid')}] {e['ev']:<18} " \
           f"{detail}".rstrip()


def narrative(events: List[dict], torn: int, summary: dict,
              max_events: int = 400) -> str:
    """The human text report: chronological event narrative + the
    per-session attribution block."""
    lines = []
    if not events:
        return "empty ledger (no parseable events)"
    t0 = events[0]["t"]
    lines.append(f"{summary['events']} event(s), {torn} torn line(s), "
                 f"{len(summary['sessions'])} session(s), "
                 f"{summary.get('window', {}).get('wall_s', 0.0):.1f} s "
                 "recorded")
    shown = events[:max_events]
    for e in shown:
        lines.append(_fmt_event(e, t0))
    if len(events) > len(shown):
        lines.append(f"  ... {len(events) - len(shown)} more event(s) "
                     "(raise --max-events)")
    for s in summary["sessions"]:
        ph = s["phases_s"]
        util = " | ".join(f"{k} {ph[k]:.2f}s ({s['utilization'][k]:.0%})"
                          for k in BUCKETS if ph[k] > 0)
        lines.append(f"session {s['prog'] or '(shell)'} pid={s['pid']} "
                     f"T+{s['t0'] - t0:.3f}s..T+{s['t1'] - t0:.3f}s "
                     f"-> {s['end']}: {util or 'no attributed time'}; "
                     f"{s['persists']} persist(s), "
                     f"{s['reused_rows']} reused row(s), "
                     f"{s['retries']} retry(ies)")
    return "\n".join(lines)


def summary_markdown(summary: dict) -> str:
    """The per-window utilization table for WINDOW_SUMMARY.md — the
    satellite contract: the next round's summary is computed from the
    ledger, never hand-written."""
    lines = ["## window utilization (flight recorder)", ""]
    lines.append("| session | wall s | measure | compile | staging "
                 "| retry | stalled | host | end |")
    lines.append("|---|---|---|---|---|---|---|---|---|")
    for s in summary.get("sessions", []):
        u = s["utilization"]
        lines.append(
            f"| {s['prog'] or '(shell)'} (pid {s['pid']}) "
            f"| {s['wall_s']:.1f} "
            f"| {u['measure']:.0%} | {u['compile']:.0%} "
            f"| {u['staging']:.0%} | {u['retrying']:.0%} "
            f"| {u['stalled']:.0%} | {u['host']:.0%} | {s['end']} |")
    win = summary.get("window")
    if win:
        u = win["utilization"]
        lines.append("")
        lines.append(
            f"window: {win['recorded_s']:.1f} s recorded — "
            f"measure {u['measure']:.0%}, compile {u['compile']:.0%}, "
            f"staging {u['staging']:.0%}, retrying {u['retrying']:.0%}, "
            f"stalled {u['stalled']:.0%}, host {u['host']:.0%}"
            + (f"; {summary['torn_lines']} torn line(s)"
               if summary.get("torn_lines") else ""))
    cp = summary.get("critical_path")
    if cp:
        # the span tree's longest dependent chain (ISSUE 12): at every
        # instant, the DEEPEST open span holds the wall clock —
        # obs/critical_path.py has the model and the markdown
        from tpu_reductions.obs.critical_path import markdown as _cp_md
        lines.append("")
        lines.extend(_cp_md(cp))
        lines.pop()     # the section's trailing blank: joined below
    sched = summary.get("sched")
    if sched:
        # the scheduler's plan-vs-actual record (ISSUE 5 satellite):
        # per task, what the planner promised vs what the window
        # delivered — skipped tasks carry their reason
        lines.append("")
        lines.append("### plan vs actual (scheduler)")
        lines.append("")
        lines.append("| task | planned s | actual s | status |")
        lines.append("|---|---|---|---|")
        for rec in sched["tasks"]:
            status = rec.get("status") or "?"
            if status == "skipped" and rec.get("reason"):
                status = f"skipped ({rec['reason']})"
            planned = rec.get("planned_s")
            actual = rec.get("actual_s")
            lines.append(
                f"| {rec['task']} "
                f"| {planned if planned is not None else '-'} "
                f"| {actual if actual is not None else '-'} "
                f"| {status} |")
        lines.append("")
        lines.append(f"{sched['replans']} replan(s)")
    serve = summary.get("serve")
    if serve:
        # the serving engine's per-request record (ISSUE 6): request
        # counts by terminal status + the latency split the respond
        # events carry
        lines.append("")
        lines.append("### serving (per-request attribution)")
        lines.append("")
        statuses = ", ".join(f"{k}: {v}" for k, v
                             in sorted(serve["by_status"].items())) \
            or "-"
        lines.append(f"{serve['requests']} request(s), "
                     f"{serve['responses']} response(s) ({statuses}); "
                     f"{serve['batches']} launch(es)"
                     + (f", mean batch {serve['mean_batch']}"
                        if serve.get("mean_batch") else "")
                     + (f", {serve['shed_episodes']} shed episode(s)"
                        if serve.get("shed_episodes") else ""))
        lat, q = serve.get("latency_s"), serve.get("queue_s")
        if lat:
            lines.append(
                f"ok latency p50 {lat['p50'] * 1e3:.2f} ms / "
                f"p99 {lat['p99'] * 1e3:.2f} ms"
                + (f"; queued p50 {q['p50'] * 1e3:.2f} ms / "
                   f"p99 {q['p99'] * 1e3:.2f} ms" if q else ""))
        if serve.get("sharded_launches"):
            lines.append(f"{serve['sharded_launches']} device-parallel "
                         "sharded launch(es)")
        router = serve.get("router")
        if router:
            # the scaling tier's record (ISSUE 13): per replica, the
            # terminal outcomes it served and the shed/error weight it
            # carried, plus what moved because a replica failed
            lines.append("")
            lines.append("### router (per-replica attribution)")
            lines.append("")
            lines.append("| replica | requests | ok | shed | error "
                         "| rerouted away | p50 ms | p99 ms |")
            lines.append("|---|---|---|---|---|---|---|---|")
            for rep in sorted(router["replicas"]):
                d = router["replicas"][rep]
                lat = d.get("latency_s")
                lines.append(
                    f"| {rep} | {d['requests']} | {d['ok']} "
                    f"| {d['shed']} | {d['error']} "
                    f"| {d.get('rerouted_away', 0)} "
                    f"| {lat['p50'] * 1e3:.2f} | {lat['p99'] * 1e3:.2f} |"
                    if lat else
                    f"| {rep} | {d['requests']} | {d['ok']} "
                    f"| {d['shed']} | {d['error']} "
                    f"| {d.get('rerouted_away', 0)} | - | - |")
            downs = router.get("replica_downs") or []
            lines.append("")
            lines.append(
                f"{router['routed']} routed, {router['reroutes']} "
                f"re-route(s), {len(downs)} replica death(s)"
                + (": " + ", ".join(
                    f"{d.get('replica')} ({d.get('reason')})"
                    for d in downs) if downs else ""))
    stream = summary.get("stream")
    if stream:
        # the streaming pipeline's record (ISSUE 7): chunk throughput,
        # honest-sync cadence, resume count, and — when the serial
        # comparator ran — the overlap-efficiency verdict
        lines.append("")
        lines.append("### streaming pipeline")
        lines.append("")
        lines.append(
            f"{stream['streams']} stream(s), {stream['chunks']} "
            f"chunk(s), {stream['syncs']} honest sync(s)"
            + (f", {stream['resumed']} resumed mid-payload"
               if stream.get("resumed") else "")
            + (f"; sustained {stream['gbps_sustained']} GB/s"
               if stream.get("gbps_sustained") is not None else "")
            + (f", {stream['chunks_per_s']} chunks/s"
               if stream.get("chunks_per_s") is not None else ""))
        if stream.get("overlap_efficiency") is not None:
            lines.append(
                f"overlap efficiency x{stream['overlap_efficiency']} "
                f"(serial {stream.get('serial_wall_s', '?')} s vs "
                f"streamed {stream.get('stream_wall_s', '?')} s)")
    coll = summary.get("collective")
    if coll:
        # the collective suite's record (ISSUE 10): per selected
        # algorithm, launches and device-phase wall-clock — the
        # collective share of the window, attributed by the registry
        # label the ONE selector picked
        lines.append("")
        lines.append("### collective (per-algorithm attribution)")
        lines.append("")
        lines.append("| algorithm | launches | wall s |")
        lines.append("|---|---|---|")
        for rec in coll["algorithms"]:
            lines.append(f"| {rec['algorithm']} | {rec['launches']} "
                         f"| {rec['wall_s']:.3f} |")
        lines.append("")
        lines.append(f"{coll['selects']} selection(s), "
                     f"{coll['launches']} launch(es), "
                     f"{coll['collective_s']:.2f} s in collective "
                     "device phases")
    resh = summary.get("reshard")
    if resh:
        # the reshard engine's record (ISSUE 15): per-primitive step
        # counts and device-phase wall-clock — which redistribution
        # move the window actually paid for
        lines.append("")
        lines.append("### reshard (per-primitive attribution)")
        lines.append("")
        lines.append("| primitive | steps | wall s |")
        lines.append("|---|---|---|")
        for rec in resh["primitives"]:
            lines.append(f"| {rec['primitive']} | {rec['steps']} "
                         f"| {rec['wall_s']:.3f} |")
        lines.append("")
        lines.append(f"{resh['plans']} plan(s), "
                     f"{resh['programs']} program(s) executed, "
                     f"{resh['reshard_s']:.2f} s in reshard device "
                     "phases")
    auto = summary.get("autoscale")
    if auto:
        # the elastic fleet's record (ISSUE 17): replica count vs
        # load across the window + per-drain protocol evidence — the
        # committed proof that planned scale-down sheds nothing
        lines.append("")
        lines.append("### elastic fleet (replica count vs load)")
        lines.append("")
        span = (f"replicas {auto['replicas_min']}.."
                f"{auto['replicas_max']}"
                if auto.get("replicas_max") is not None else "replicas ?")
        lines.append(
            f"{auto['ticks']} control tick(s), {span}, "
            f"{auto['ups']} scale-up(s) "
            f"({auto['prewarmed']} key(s) prewarmed), "
            f"{auto['downs']} planned drain(s)"
            + (f"; peak load/replica {auto['load_max']}"
               if auto.get("load_max") is not None else ""))
        if auto["drains"]:
            lines.append("")
            lines.append("| drained replica | waited s | keys handed "
                         "| shed | expired | reshard |")
            lines.append("|---|---|---|---|---|---|")
            for d in auto["drains"]:
                waited = d.get("waited_s")
                resh_cell = "-"
                if d.get("program"):
                    ok = "ok" if d.get("reshard_ok") else "FAILED"
                    resh_cell = (f"{d['program']} ({ok}, mem x"
                                 f"{d.get('measured_mem_factor')})")
                elif d.get("reshard_ok"):
                    resh_cell = "ok"
                lines.append(
                    f"| {d['replica']} "
                    f"| {waited if waited is not None else '-'} "
                    f"| {d.get('keys', '-')} | {d.get('shed', '-')} "
                    f"| {d.get('expired', '-')} | {resh_cell} |")
    rec = summary.get("recovery")
    if rec:
        # the crash-consistent control plane's record (ISSUE 18):
        # per-recovery MTTR from the adopt.begin -> adopt.done wall
        # clock, the per-child adoption verdicts, and the dedup-cache
        # hits that made router retries exactly-once
        lines.append("")
        lines.append("### crash recovery (journal / adoption / dedup)")
        lines.append("")
        verdicts = ", ".join(f"{k}: {v}" for k, v
                             in sorted(rec["verdicts"].items())) or "-"
        lines.append(
            f"{rec['recoveries']} recovery(ies), "
            f"{rec['adopted']} replica(s) adopted, "
            f"{rec['reaped']} reaped ({verdicts}); "
            f"{rec['journal_records']} journal record(s), "
            f"{rec['journal_replays']} replay(s), "
            f"{rec['dedup_hits']} dedup hit(s)"
            + (f"; MTTR <= {rec['mttr_max_s']:.3f} s"
               if rec.get("mttr_max_s") is not None else ""))
        if rec.get("per_recovery"):
            lines.append("")
            lines.append("| recovery | adopted | reaped | MTTR s |")
            lines.append("|---|---|---|---|")
            for i, r in enumerate(rec["per_recovery"]):
                mttr = r.get("mttr_s")
                lines.append(
                    f"| {i} | {r.get('adopted', '-')} "
                    f"| {r.get('reaped', '-')} "
                    f"| {f'{mttr:.3f}' if isinstance(mttr, (int, float)) else '-'} |")
    comp = summary.get("compile")
    if comp:
        # the compile observatory's record (ISSUE 8): per-surface
        # cold/warm compile latency + the compile share of the window —
        # the axis the window planner was blind on
        lines.append("")
        lines.append("### compile observatory (per-surface cold/warm)")
        lines.append("")
        lines.append("| surface | cold s | warm s | last verdict "
                     "| compiles |")
        lines.append("|---|---|---|---|---|")
        for rec in comp["surfaces"]:
            cold = rec.get("cold_s")
            warm_v = rec.get("warm_s")
            lines.append(
                f"| {rec['surface']} "
                f"| {f'{cold:.3f}' if cold is not None else '-'} "
                f"| {f'{warm_v:.3f}' if warm_v is not None else '-'} "
                f"| {rec.get('last_verdict') or '?'} "
                f"| {rec['count']}"
                + (f" ({rec['errors']} error(s))" if rec["errors"]
                   else "") + " |")
        recorded = summary.get("window", {}).get("recorded_s") or 0.0
        share = (f", {comp['compile_s'] / recorded:.0%} of the "
                 "recorded window" if recorded > 0 else "")
        lines.append("")
        lines.append(f"{comp['compiles']} instrumented compile(s), "
                     f"{comp['compile_s']:.2f} s total{share}"
                     + (f"; {comp['warm_runs']} warming pass(es)"
                        if comp.get("warm_runs") else ""))
    execu = summary.get("exec")
    if execu:
        # the execution core's record (ISSUE 19): per-surface plan/done
        # counts (the duplicate-launch audit is this join) + the
        # cost-oracle decisions with their static baselines
        lines.append("")
        lines.append("### execution core (per-surface LaunchPlan "
                     "attribution)")
        lines.append("")
        lines.append("| surface | kind | plans | done | failed "
                     "| wall s |")
        lines.append("|---|---|---|---|---|---|")
        for rec in execu["surfaces"]:
            lines.append(
                f"| {rec['surface']} | {rec.get('kind') or '?'} "
                f"| {rec['plans']} | {rec['done']} | {rec['failed']} "
                f"| {rec['wall_s']:.3f} |")
        lines.append("")
        lines.append(f"{execu['plans']} plan(s), {execu['launches']} "
                     f"launch(es), {execu['done']} completed, "
                     f"{execu['failures']} failure(s), "
                     f"{execu['exec_s']:.2f} s in planned device work")
        if execu["selects"]:
            lines.append("")
            lines.append("| decision axis | chosen | static pick "
                         "| flipped | why |")
            lines.append("|---|---|---|---|---|")
            for sel in execu["selects"]:
                lines.append(
                    f"| {sel.get('axis') or '?'} "
                    f"| {sel.get('choice') or '?'} "
                    f"| {sel.get('static_choice') or '?'} "
                    f"| {'YES' if sel.get('flipped') else 'no'} "
                    f"| {sel.get('reason') or '-'} |")
    return "\n".join(lines)


def main(argv=None) -> int:
    """CLI: reconstruct the session timeline from a ledger (module
    docstring). Exit 0 with events, 1 on an empty/absent ledger."""
    p = argparse.ArgumentParser(
        prog="tpu_reductions.obs.timeline",
        description="Post-mortem timeline + window-utilization metrics "
                    "from a flight-recorder ledger")
    p.add_argument("ledger", help="JSONL event ledger (obs/ledger.py)")
    p.add_argument("--json", dest="json_out", default=None,
                   help="write the machine-readable summary here "
                        "(atomic; bench/regen collates it)")
    p.add_argument("--summary-md", action="store_true",
                   help="print ONLY the WINDOW_SUMMARY.md utilization "
                        "table")
    p.add_argument("--quiet", action="store_true",
                   help="no stdout (use with --json from scripts)")
    p.add_argument("--max-events", type=int, default=400,
                   help="narrative event cap (default 400)")
    ns = p.parse_args(argv)
    try:
        events, torn = read_ledger(ns.ledger)
    except OSError as e:
        print(f"timeline: cannot read {ns.ledger}: {e}",
              file=sys.stderr)
        return 1
    summary = summarize(ns.ledger, events, torn)
    if ns.json_out:
        from tpu_reductions.utils.jsonio import atomic_json_dump
        atomic_json_dump(ns.json_out, summary)
    if ns.quiet:
        pass
    elif ns.summary_md:
        print(summary_markdown(summary))
    else:
        print(narrative(events, torn, summary,
                        max_events=ns.max_events))
        print()
        print(summary_markdown(summary))
    return 0 if events else 1


if __name__ == "__main__":
    sys.exit(main())
