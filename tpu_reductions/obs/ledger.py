"""Crash-safe append-only JSONL event ledger — the flight recorder core.

Design constraints, in order:

  1. **No torn lines.** A watchdog `os._exit` (utils/watchdog.py) or a
     SIGKILL-class death (faults/inject.py action "exit") can land at
     ANY instant; a postmortem that cannot parse its own ledger is
     worse than none. Every event is therefore ONE `os.write` of one
     complete line to an O_APPEND fd — appends of line-sized writes are
     atomic at the fd layer, so concurrent writers (the session, the
     watchdog thread, the shell supervisors via scripts/obs_event.sh)
     interleave at line granularity and a kill can only lose the line
     in flight, never tear a previous one. The write is fsync'd — the
     same durability contract as utils/jsonio (an event that claimed a
     row persisted must itself survive the power cut).
  2. **Never the failure.** `emit` never raises and never blocks on
     anything but the local filesystem: observability must not take
     down the measurement it observes. Internal errors disarm the
     ledger after one stderr warning.
  3. **Free when off.** Unarmed (TPU_REDUCTIONS_LEDGER unset) or
     disabled (TPU_REDUCTIONS_OBS_DISABLE=1), `emit` is one attribute
     test. No entry point changes behavior when the recorder is off.
  4. **Host-side only.** No jax import, no device call, no sync — and
     callers only emit OUTSIDE timed regions (docs/OBSERVABILITY.md
     "overhead guarantees"; the timing seams in utils/timing.py emit
     after their perf_counter windows close).

Row grammar: `{"t": <epoch>, "ev": "<type>", "pid": <pid>, ...}` — the
leading keys are fixed and the schema lives in lint/grammar.py
(EVENT_ROW_RE / EVENT_NAME_RE) like every other machine-parsed row this
suite emits; redlint RED012 bans ad-hoc emission outside this module
and scripts/obs_event.sh. Events carry the current heartbeat phase
(utils/heartbeat.py) when one is active, so ack-vs-materialization
attribution stays honest per docs/TIMING.md — and, when a trace
context is active (obs/trace.py), the causal identity fields
`trace`/`span`/`parent` (lint/grammar.py TRACE_FIELDS), so the
offline analyzers rebuild the span tree from the rows alone.

This is the shrLog/shrLogEx master-log multiplex of the reference
(cuda/shared/src/shrUtils.cpp:157,173-280) rebuilt as a typed,
crash-ordered event stream instead of prose lines.

CLI (used by tests and hand-driven postmortems; the shell supervisors
use scripts/obs_event.sh instead to stay python-free):

    python -m tpu_reductions.obs.ledger <event> [key=value ...] \
        [--ledger PATH]
"""

from __future__ import annotations

import atexit
import json
import os
import sys
import threading
import time
from typing import Optional

from tpu_reductions.lint.grammar import EVENT_NAME_RE

ENV_PATH = "TPU_REDUCTIONS_LEDGER"
ENV_DISABLE = "TPU_REDUCTIONS_OBS_DISABLE"
# Optional size cap (bytes) with rotate-to-`.1` (docs/RESILIENCE.md):
# round-5 watch logs showed multi-hour armed sessions appending
# unboundedly. Rotation is one atomic rename of the full file to
# `<path>.1` (replacing any previous rollover) followed by a fresh
# O_APPEND open — the active file stays crash-safe (no truncation, no
# partial copy), and by-path producers (scripts/obs_event.sh) land in
# the new file on their next append. A concurrent python writer
# holding the old fd keeps appending to the rotated file until its own
# next size check — lines are never lost, only filed under `.1`.
ENV_MAX_BYTES = "TPU_REDUCTIONS_LEDGER_MAX_BYTES"

_fd: Optional[int] = None
_path: Optional[str] = None
_max_bytes: Optional[int] = None
_session_open = False
# Serializes every mutation of the module state above (arm/disarm/
# rotation/session open), which races between the process main thread
# and emitters on the serving/replica threads (redlint RED021). The
# emit hot path stays lock-free: it READS _fd once and issues one
# line-atomic O_APPEND os.write — a concurrent rotation at worst files
# that line under `<path>.1` (the ENV_MAX_BYTES contract above).
_state_lock = threading.Lock()


def disabled() -> bool:
    """TPU_REDUCTIONS_OBS_DISABLE=1: hard off, even when armed."""
    return os.environ.get(ENV_DISABLE) == "1"


def resolved_path(path: Optional[str | os.PathLike] = None
                  ) -> Optional[str]:
    """The ledger file: explicit argument, else TPU_REDUCTIONS_LEDGER,
    else None (recorder off — the default for bare CLI invocations;
    scripts/chip_session.sh exports the env for live windows)."""
    if path is not None:
        return os.fspath(path)
    return os.environ.get(ENV_PATH) or None


def armed() -> bool:
    """Whether emits currently reach a ledger file."""
    return _fd is not None and not disabled()


def _warn(msg: str) -> None:
    print(f"obs.ledger: {msg} (recorder disarmed; the run continues "
          "unobserved)", file=sys.stderr, flush=True)


def arm(path: Optional[str | os.PathLike] = None) -> Optional[str]:
    """Open (create) the ledger for appending; returns the path or None
    when the recorder stays off. Idempotent for the same path; arming a
    different path closes the previous fd."""
    global _fd, _path, _max_bytes
    if disabled():
        return None
    path = resolved_path(path)
    if path is None:
        return None
    with _state_lock:
        if _fd is not None and _path == path:
            return path
        try:
            fd = os.open(path,
                         os.O_WRONLY | os.O_CREAT | os.O_APPEND, 0o644)
        except OSError as e:
            _warn(f"cannot open ledger {path!r}: {e}")
            return None
        if _fd is not None:
            try:
                os.close(_fd)
            except OSError:
                pass
        _fd, _path = fd, path
        try:
            _max_bytes = int(os.environ.get(ENV_MAX_BYTES, ""))
            if _max_bytes <= 0:
                _max_bytes = None
        except ValueError:
            _max_bytes = None
    return path


def disarm() -> None:
    """Close the ledger (tests; subprocesses end via session.end)."""
    global _fd, _path, _session_open, _max_bytes
    with _state_lock:
        if _fd is not None:
            try:
                os.close(_fd)
            except OSError:
                pass
        _fd, _path, _session_open, _max_bytes = None, None, False, None
    # trace.reset acquires the trace lock — deliberately OUTSIDE
    # _state_lock so the two module locks never nest (redlint RED022)
    try:
        # a disarmed recorder sheds its trace identity too (tests
        # re-arm fresh sessions; a stale root would chain them)
        from tpu_reductions.obs import trace
        trace.reset()
    except Exception:
        pass


def _current_trace():
    """The active trace context, lazily (same cycle discipline as the
    heartbeat read below: obs/trace.py never imports this module's
    emit path at import time)."""
    try:
        from tpu_reductions.obs import trace
        return trace.active()
    except Exception:
        return None


def _current_phase() -> Optional[str]:
    """The active heartbeat phase, lazily (no import cycle: heartbeat
    emits through this module and this module only READS heartbeat)."""
    try:
        from tpu_reductions.utils import heartbeat
        snap = heartbeat.snapshot()
        return snap["phase"] if snap["in_flight"] else None
    except Exception:
        return None


def _clean(v):
    """JSON-safe field value: non-finite floats become null (the
    RFC-8259 discipline of BenchResult.to_dict), unknown types
    stringify."""
    if isinstance(v, float) and (v != v or v in (float("inf"),
                                                 float("-inf"))):
        return None
    if isinstance(v, (str, int, float, bool)) or v is None:
        return v
    if isinstance(v, (list, tuple)):
        return [_clean(x) for x in v]
    if isinstance(v, dict):
        return {str(k): _clean(x) for k, x in v.items()}
    return str(v)


def emit(ev: str, **fields) -> bool:
    """Append one event; returns True iff a line landed. NEVER raises.

    Fields pass through as JSON (None stays null — an explicit
    `phase=None` records a cleared phase); the current heartbeat phase
    is attached automatically when the caller does not pass one."""
    global _fd
    if _fd is None or disabled():
        return False
    try:
        if not EVENT_NAME_RE.match(ev):
            _warn_once_bad_name(ev)
            return False
        rec = {"t": round(time.time(), 6), "ev": ev, "pid": os.getpid()}
        if "phase" not in fields:
            phase = _current_phase()
            if phase is not None:
                rec["phase"] = phase
        if "trace" not in fields:
            # causal identity (obs/trace.py): stamped from the ambient
            # context unless the caller carries an explicit one (the
            # serving engine's per-request traces)
            tr = _current_trace()
            if tr is not None:
                rec["trace"] = tr.trace_id
                rec["span"] = tr.span_id
                if tr.parent_id is not None:
                    rec["parent"] = tr.parent_id
        for k, v in fields.items():
            rec[str(k)] = _clean(v)
        line = (json.dumps(rec) + "\n").encode("utf-8", "replace")
        if _max_bytes is not None:
            _maybe_rotate(len(line))
        os.write(_fd, line)          # ONE write: line-atomic append
        os.fsync(_fd)                # jsonio durability contract
        return True
    except Exception as e:           # constraint 2: never the failure
        try:
            _warn(f"append failed: {type(e).__name__}: {e}")
            disarm()
        except Exception:
            pass
        return False


def _maybe_rotate(incoming: int) -> None:
    """Size-capped rotation (ENV_MAX_BYTES header comment): when the
    next line would push the active file past the cap, rename it whole
    to `<path>.1` and reopen fresh. Raises nothing the emit wrapper
    does not already contain; a failed rename just keeps appending to
    the oversized file (hygiene is best-effort, durability is not)."""
    global _fd
    with _state_lock:
        if _fd is None or _path is None or _max_bytes is None:
            return
        try:
            if os.fstat(_fd).st_size + incoming <= _max_bytes:
                return
            os.replace(_path, _path + ".1")
            fd = os.open(_path, os.O_WRONLY | os.O_CREAT | os.O_APPEND,
                         0o644)
        except OSError:
            return
        try:
            os.close(_fd)
        except OSError:
            pass
        _fd = fd


_bad_names: set = set()


def _warn_once_bad_name(ev: str) -> None:
    with _state_lock:
        if ev in _bad_names:
            return
        _bad_names.add(ev)
    print(f"obs.ledger: dropped event with non-grammar name {ev!r} "
          "(lint/grammar.py EVENT_NAME_RE)", file=sys.stderr,
          flush=True)


def arm_session(prog: str, argv=None, **fields) -> Optional[str]:
    """The entry-point hook: arm from the environment and record
    `session.start` (+ a best-effort `session.end` at interpreter exit
    — watchdog exits bypass atexit by design and are recorded by their
    own `watchdog.exit` event instead). Call it next to
    `maybe_arm_for_tpu` in every main; a no-op when no ledger is
    configured."""
    global _session_open
    path = arm()
    if path is None:
        return None
    try:
        # root the process span tree BEFORE the first emit: session.*
        # and everything after carry the trace — adopted from
        # TPU_REDUCTIONS_TRACE_CTX when a parent propagated one
        from tpu_reductions.obs import trace
        trace.ensure_root()
    except Exception:
        pass
    emit("session.start", prog=prog,
         argv=list(argv) if argv is not None else None, **fields)
    with _state_lock:
        register = not _session_open
        _session_open = True
    if register:
        atexit.register(_end_session)
    return path


def _end_session() -> None:
    emit("session.end")


def main(argv=None) -> int:
    """CLI append: one event from the command line (tests, hand-driven
    postmortem annotations). key=value fields parse numerics; the
    shell supervisors use scripts/obs_event.sh instead (no python
    import on their hot paths)."""
    import argparse
    p = argparse.ArgumentParser(
        prog="tpu_reductions.obs.ledger",
        description="Append one event to the flight-recorder ledger")
    p.add_argument("event", help="dotted event name (lint/grammar.py "
                                 "EVENT_NAME_RE)")
    p.add_argument("fields", nargs="*", help="key=value event fields")
    p.add_argument("--ledger", default=None,
                   help="ledger path (default TPU_REDUCTIONS_LEDGER)")
    ns = p.parse_args(argv)
    if arm(ns.ledger) is None:
        print("obs.ledger: no ledger configured "
              f"(--ledger or {ENV_PATH})", file=sys.stderr)
        return 1
    fields = {}
    for kv in ns.fields:
        k, _, v = kv.partition("=")
        try:
            fields[k] = int(v)
        except ValueError:
            try:
                fields[k] = float(v)
            except ValueError:
                fields[k] = v
    return 0 if emit(ns.event, **fields) else 1


if __name__ == "__main__":
    sys.exit(main())
