"""Span API over the flight-recorder ledger (obs/ledger.py).

A span is one host-side region worth postmortem attribution: it emits
`<name>.start` on entry and `<name>.end` (with `dur_s`, and `error`
when the region raised) on exit. This is the named-stopwatch idea of
the reference's cutil timer registry (cutCreateTimer/cutStartTimer,
cutil.cpp:1567-1692) re-pointed at the event ledger instead of an
in-memory average — the duration lands in the crash-ordered record, so
it survives the process.

Since ISSUE 12 every span is also a node of the causal trace tree
(obs/trace.py): entering a span pushes a child trace context, so the
`.start`/`.end` pair share one span id, nested spans parent under it,
and every point event emitted inside carries the span's identity —
obs/trace_export.py rebuilds the tree offline.

Spans are strictly host-side instrumentation: they never sync a
device, and the instrumented seams only open spans OUTSIDE timed
regions (utils/timing.py emits after its perf_counter windows close;
docs/OBSERVABILITY.md has the full overhead contract). When the ledger
is unarmed a span is two attribute tests — safe to leave in hot-ish
host paths.
"""

from __future__ import annotations

import contextlib
import time

from tpu_reductions.obs import ledger, trace

event = ledger.emit     # alias: seams import one module for both


@contextlib.contextmanager
def span(name: str, **fields):
    """Bracket one host-side region with `<name>.start` / `<name>.end`
    events; `dur_s` is monotonic wall-clock, `error` records a raising
    region (the exception is re-raised untouched — spans observe,
    never contain). The pair share a child trace context so the region
    is one node of the span tree."""
    if not ledger.armed():
        yield
        return
    with trace.child():
        ledger.emit(name + ".start", **fields)
        t0 = time.monotonic()
        try:
            yield
        except BaseException as e:
            ledger.emit(name + ".end",
                        dur_s=round(time.monotonic() - t0, 6),
                        error=f"{type(e).__name__}: {e}"[:200], **fields)
            raise
        ledger.emit(name + ".end", dur_s=round(time.monotonic() - t0, 6),
                    **fields)
