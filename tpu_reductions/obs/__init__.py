"""obs — the flight recorder: structured telemetry for chip sessions.

The reference multiplexed every benchmark line into per-app + master
logs precisely so runs could be audited after the fact (shrLog/shrLogEx,
cuda/shared/src/shrUtils.cpp:157,173-280; SURVEY.md §5 — the row schema
IS the metrics API). On this platform the audit question is harsher:
live relay windows die in minutes (CLAUDE.md), sessions end in watchdog
exit 3/4, and the story of *where the minutes went* — compile vs
staging vs measuring vs retrying vs stalled — used to be scattered
across watch logs, heartbeat stderr and per-artifact JSON. This package
is the machine-readable record:

  * `obs.ledger`  — crash-safe append-only JSONL event ledger (atomic
    single-line appends, fsync policy shared with utils/jsonio; no
    torn lines under SIGKILL). Armed by every entry point alongside
    the watchdog; a no-op unless TPU_REDUCTIONS_LEDGER names a file.
  * `obs.spans`   — span/event helpers over the ledger for the
    instrumented seams (utils/retry, utils/staging, utils/timing,
    utils/heartbeat phase transitions, utils/watchdog exits,
    bench/resume checkpoints, faults/inject firings).
  * `obs.timeline` — the post-mortem CLI: reconstructs a session
    timeline from a ledger, attributes wall-clock per phase, and
    computes window-utilization metrics (text report, summary JSON,
    and the WINDOW_SUMMARY.md markdown table).
  * `obs.compile` — the compile observatory (ISSUE 8): every
    XLA/Pallas compile bracketed with its surface id and `.jax_cache`
    cold/warm verdict (utils/compile_cache fingerprints), persisted
    per-surface into compile_ledger.json; the scheduler's cold/warm
    duration priors and the report's compile-latency table read it.
  * `obs.trace` — causal identity (ISSUE 12): contextvar-scoped
    trace/span/parent ids stamped onto every emitted event, propagated
    across process boundaries via TPU_REDUCTIONS_TRACE_CTX (sched task
    subprocesses, shell steps, chaos relays all parent under one
    session trace; exit-3/4 re-invocations continue the trace past an
    explicit `trace.cut` marker).
  * `obs.trace_export` — offline Chrome-trace/Perfetto JSON export of
    the reconstructed span tree (pid/tid = process/trace lanes).
  * `obs.critical_path` — the longest dependent chain per session/
    request: "window bounded by: compile 38% -> staging 22% -> chain
    31%", folded into timeline --summary-md and report.md.

Strictly host-side by contract: instrumentation adds no device work, no
sync, and never emits inside a timed region (docs/OBSERVABILITY.md has
the overhead guarantees; docs/TIMING.md the ack-vs-materialization
attribution rules the phase labels preserve).
"""

from tpu_reductions.obs.ledger import arm, arm_session, armed, emit

__all__ = ["arm", "arm_session", "armed", "emit"]
