"""Causal trace context for the flight recorder — every event gets an
identity in ONE span tree.

The ledger (obs/ledger.py) records *what* happened; this module records
*why it took that long*: a (trace_id, span_id, parent_id) context,
contextvar-scoped so threads and async tasks each see their own span
stack, that `ledger.emit` stamps onto every event and `obs/spans.span`
/ `compile_span` / the instrumented seams push children onto. The
analysis layer (obs/trace_export.py Perfetto export,
obs/critical_path.py longest-chain attribution) rebuilds the tree
offline from nothing but the stamped events.

Cross-process propagation: `TPU_REDUCTIONS_TRACE_CTX` carries
`<trace_id>:<span_id>` into subprocesses (sched/executor.py task
launches, scripts/chip_session.sh steps, scripts/obs_event.sh shell
events, faults/relay.py chaos runs). A process that finds the env var
adopts the trace id and parents its root span under the propagated
span — so one live window is ONE trace across every pid that served
it. A re-invocation after a watchdog exit 3/4 continues the same
trace and marks the discontinuity with an explicit `trace.cut` event
(registered in lint/grammar.py); the analysis layer closes spans the
death tore open at the cut, never leaving a torn tree.

Overhead contract (docs/OBSERVABILITY.md): pure host-side id
bookkeeping — no jax import, no device call, no syscall beyond
os.urandom at id mint. When the ledger is unarmed nothing here runs at
all (the span helpers bail before touching this module).

This is the reference's named-stopwatch registry (cutCreateTimer,
cutil.cpp:1567-1692) grown the way serving stacks grew it: the name
became a span, the registry became a tree, the tree became portable
across processes.
"""

from __future__ import annotations

import contextlib
import contextvars
import os
import re
import threading
from dataclasses import dataclass
from typing import Optional

ENV_CTX = "TPU_REDUCTIONS_TRACE_CTX"

# ids propagated through env/shell: keep them shell-quoting-proof
_ID_RE = re.compile(r"^[A-Za-z0-9][A-Za-z0-9_.-]{0,63}$")


@dataclass(frozen=True)
class TraceContext:
    """One node of the span tree: the trace it belongs to, its own span
    id, and the span it nests under (None for a trace root)."""

    trace_id: str
    span_id: str
    parent_id: Optional[str] = None

    def encode(self) -> str:
        """The TPU_REDUCTIONS_TRACE_CTX wire form: `trace:span`."""
        return f"{self.trace_id}:{self.span_id}"


def new_id(nbytes: int = 6) -> str:
    """A fresh hex id (os.urandom — no Math.random/clock coupling)."""
    return os.urandom(nbytes).hex()


def decode(value: Optional[str]) -> Optional[TraceContext]:
    """Parse the `trace:span` wire form; malformed input is None (a
    corrupt env var must never take down the session it describes)."""
    if not value:
        return None
    trace_id, sep, span_id = value.partition(":")
    if not sep or not _ID_RE.match(trace_id) or not _ID_RE.match(span_id):
        return None
    return TraceContext(trace_id=trace_id, span_id=span_id)


_cv: contextvars.ContextVar = contextvars.ContextVar(
    "tpu_reductions_trace", default=None)
_root: Optional[TraceContext] = None
_root_adopted = False
_lock = threading.Lock()


def active() -> Optional[TraceContext]:
    """The context events stamp right now: the innermost open span in
    this thread/task, else the process root, else a root adopted from
    TPU_REDUCTIONS_TRACE_CTX on first use, else None (untraced)."""
    # redlint: disable=RED023 -- contextvar isolation; get() never blocks
    ctx = _cv.get()
    if ctx is not None:
        return ctx
    if _root is not None:
        return _root
    if os.environ.get(ENV_CTX):
        return ensure_root()
    return None


def ensure_root() -> TraceContext:
    """Create (once) the process root span: adopt the trace id from
    TPU_REDUCTIONS_TRACE_CTX and parent under its span when propagated,
    else mint a fresh trace. Idempotent; thread-safe."""
    global _root, _root_adopted
    with _lock:
        if _root is None:
            inherited = decode(os.environ.get(ENV_CTX))
            if inherited is not None:
                _root = TraceContext(trace_id=inherited.trace_id,
                                     span_id=new_id(),
                                     parent_id=inherited.span_id)
                _root_adopted = True
            else:
                _root = TraceContext(trace_id=new_id(8), span_id=new_id())
                _root_adopted = False
        return _root


def adopted() -> bool:
    """Whether the root came from a propagated context (the marker the
    trace.cut sites key on: only a continued trace has a cut)."""
    return _root is not None and _root_adopted


def reset() -> None:
    """Drop the process root (tests; ledger.disarm calls this so a
    disarmed recorder also sheds its trace identity)."""
    global _root, _root_adopted
    with _lock:
        _root = None
        _root_adopted = False


@contextlib.contextmanager
def child():
    """Open a child span context: a fresh span id parented under the
    innermost active span (a process root is created on demand).
    Events emitted inside carry the child's identity; the contextvar
    token discipline makes nesting thread- and async-safe. With the
    ledger unarmed this is a no-op yielding None — identity without a
    recorder is pure overhead (the contract in the module
    docstring)."""
    from tpu_reductions.obs import ledger
    if not ledger.armed():
        yield None
        return
    parent = active() or ensure_root()
    ctx = TraceContext(trace_id=parent.trace_id, span_id=new_id(),
                       parent_id=parent.span_id)
    token = _cv.set(ctx)
    try:
        yield ctx
    finally:
        _cv.reset(token)


@contextlib.contextmanager
def activate(ctx: TraceContext):
    """Run a block under an explicit context (the serving engine's
    per-request traces re-enter their request context this way)."""
    token = _cv.set(ctx)
    try:
        yield ctx
    finally:
        _cv.reset(token)


def request_context(request_id: str) -> TraceContext:
    """One trace PER serving request: the request id IS the trace id
    (and the root span id), so loadgen/timeline join latencies by id
    instead of positionally and a p99 outlier decomposes into its own
    span tree (docs/SERVING.md; ISSUE 12)."""
    rid = str(request_id)
    return TraceContext(trace_id=rid, span_id=rid)


def request_fields(request_id: str) -> dict:
    """The explicit stamp for per-request events (serve/engine.py):
    `{"trace": rid, "span": rid}` — passed as **fields so ledger.emit
    skips ambient stamping for them. The ONE sanctioned way to mint
    trace identity outside this module (redlint RED012)."""
    rid = str(request_id)
    return {"trace": rid, "span": rid}


def propagation_env() -> dict:
    """The env fragment that parents a subprocess under the current
    span: `{TPU_REDUCTIONS_TRACE_CTX: "trace:span"}` (sched/executor.py
    merges it into every task launch; chip_session.sh exports the same
    variable for its shell steps)."""
    ctx = active() or ensure_root()
    return {ENV_CTX: ctx.encode()}


def cut(reason: str, **fields) -> bool:
    """Record a trace discontinuity: the previous process serving this
    trace died (watchdog exit 3/4, SIGKILL) and this invocation
    continues the same trace. The analysis layer closes orphaned spans
    at the cut instead of leaving the tree torn."""
    from tpu_reductions.obs import ledger
    return ledger.emit("trace.cut", reason=reason, **fields)
