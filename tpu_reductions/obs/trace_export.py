"""Chrome-trace / Perfetto export of the flight recorder's span tree.

The ledger (obs/ledger.py) is a flat crash-ordered JSONL stream whose
events carry causal identity (obs/trace.py: trace/span/parent —
lint/grammar.py TRACE_FIELDS). This module rebuilds the span tree
offline and emits the Chrome trace-event JSON Perfetto and
chrome://tracing load directly:

    python -m tpu_reductions.obs.trace_export ledger.jsonl \
        --out trace.json

Lanes: `pid` = the emitting process (named after its session.start
prog), `tid` = one lane per trace within the process — so a chip
session renders as session → task subprocess → launch → compile/
staging/collective child slices, and every serving request gets its
own lane (one trace per request, obs/trace.request_context). Flow
arrows connect a child process's root span to the parent span that
propagated TPU_REDUCTIONS_TRACE_CTX to it.

Span reconstruction rules (shared with obs/critical_path.py):

  * bracket pairs — `X.start`/`X.end` (and the legacy-named pairs
    `collective.launch`/`collective.done`, `serve.start`/`serve.stop`)
    matched by span id when stamped, by (pid, name) stack otherwise;
  * orphaned opens — a watchdog exit 3/4 or SIGKILL tears the close
    away — are closed synthetically at the trace's `trace.cut` event
    (the re-invocation's continuity marker, obs/trace.py) or at the
    pid's last recorded instant, flagged `cut`: the tree is never
    torn;
  * point events carrying `dur_s`/`exec_s` (chain.trip, timing.loop,
    serve.verify) become completed slices ending at their emit time —
    the seams emit AFTER their perf_counter windows close
    (docs/OBSERVABILITY.md), so [t - dur, t] is the honest interval;
  * serving requests synthesize a per-request span from their
    enqueue→respond bracket, with queued/exec child slices from the
    queue_s split the respond event carries.

Rotation stitch (ISSUE 12 satellite): reads through
obs/timeline.read_ledger, which re-heads the rotated `<ledger>.1`
segment — a session whose ledger rolled over mid-run exports whole.

Offline by construction: stdlib only, no device, safe after exit 3/4.
No reference analog (TPU-native; the cutil stopwatch registry never
had an export story).
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Dict, List, Optional, Tuple

# legacy-named bracket pairs that predate the `.start`/`.end` span
# convention — registered event names (lint/grammar.py), paired here
OPENER_CLOSERS = {"collective.launch": "collective.done",
                  "serve.start": "serve.stop",
                  "route.start": "route.stop"}
CLOSER_SUFFIX = ".end"
OPENER_SUFFIX = ".start"
# point-event duration fields, in precedence order (the emitters close
# their perf_counter windows before emitting — docs/OBSERVABILITY.md)
DUR_FIELDS = ("dur_s", "exec_s")


def _split_bracket(ev: str) -> Tuple[Optional[str], Optional[str]]:
    """(base, kind) where kind is 'open'/'close'/None for a point."""
    if ev in OPENER_CLOSERS:
        return ev, "open"
    for base, closer in OPENER_CLOSERS.items():
        if ev == closer:
            return base, "close"
    if ev.endswith(OPENER_SUFFIX):
        return ev[:-len(OPENER_SUFFIX)], "open"
    if ev.endswith(CLOSER_SUFFIX):
        return ev[:-len(CLOSER_SUFFIX)], "close"
    return None, None


def _cut_time(e: dict, cuts: List[dict], pid_last: Dict) -> float:
    """Synthetic close time for an orphaned open: the first trace.cut
    of the same trace after it, else the pid's last recorded instant
    (which is >= the open by construction)."""
    tr = e.get("trace")
    for c in cuts:
        if c["t"] >= e["t"] and (tr is None or c.get("trace") == tr):
            return c["t"]
    return pid_last.get(e.get("pid"), e["t"])


def build_spans(events: List[dict]) -> List[dict]:
    """Reconstruct span records from a flat event list (module
    docstring has the rules). Each record: {name, pid, t0, t1, dur_s,
    trace, span, parent, cut, fields}."""
    spans: List[dict] = []
    cuts = [e for e in events if e["ev"] == "trace.cut"]
    pid_last: Dict = {}
    for e in events:
        pid_last[e.get("pid")] = max(pid_last.get(e.get("pid"), e["t"]),
                                     e["t"])
    by_span: Dict = {}      # (pid, span_id) -> open event
    by_name: Dict = {}      # (pid, base) -> [open events] (legacy stack)
    skip = {"t", "ev", "pid", "trace", "span", "parent"}

    def _close(open_e: dict, base: str, t1: float, cut: bool,
               close_fields: Optional[dict] = None) -> None:
        fields = {k: v for k, v in open_e.items() if k not in skip}
        for k, v in (close_fields or {}).items():
            if k not in skip and k not in DUR_FIELDS:
                fields.setdefault(k, v)
        spans.append({"name": base, "pid": open_e.get("pid"),
                      "t0": open_e["t"], "t1": max(t1, open_e["t"]),
                      "dur_s": round(max(t1 - open_e["t"], 0.0), 6),
                      "trace": open_e.get("trace"),
                      "span": open_e.get("span"),
                      "parent": open_e.get("parent"),
                      "cut": cut, "fields": fields})

    for e in events:
        base, kind = _split_bracket(e["ev"])
        if kind == "open":
            key = (e.get("pid"), e.get("span"))
            if e.get("span") is not None:
                by_span[key] = e
            else:
                by_name.setdefault((e.get("pid"), base), []).append(e)
        elif kind == "close":
            key = (e.get("pid"), e.get("span"))
            open_e = by_span.pop(key, None) if e.get("span") is not None \
                else None
            if open_e is None:
                stack = by_name.get((e.get("pid"), base))
                open_e = stack.pop() if stack else None
            if open_e is not None:
                _close(open_e, base, e["t"], cut=False, close_fields=e)
        else:
            for df in DUR_FIELDS:
                d = e.get(df)
                if isinstance(d, (int, float)) and d > 0:
                    fields = {k: v for k, v in e.items() if k not in skip}
                    spans.append({"name": e["ev"], "pid": e.get("pid"),
                                  "t0": e["t"] - float(d), "t1": e["t"],
                                  "dur_s": round(float(d), 6),
                                  "trace": e.get("trace"),
                                  "span": e.get("span"),
                                  "parent": e.get("parent"),
                                  "cut": False, "fields": fields})
                    break
    # orphaned opens: the close died with the process — synthesize it
    # at the trace.cut (or the pid's last instant), never leave a torn
    # tree (ISSUE 12 satellite 3's acceptance shape)
    for open_e in list(by_span.values()) + \
            [e for stack in by_name.values() for e in stack]:
        base, _ = _split_bracket(open_e["ev"])
        _close(open_e, base or open_e["ev"],
               _cut_time(open_e, cuts, pid_last), cut=True)
    spans.extend(_request_spans(events))
    spans.sort(key=lambda s: (s["t0"], s["t1"]))
    return spans


def _request_spans(events: List[dict]) -> List[dict]:
    """Per-request span synthesis: one trace per serving request (the
    request id is the trace id — obs/trace.request_context), bracketed
    enqueue→respond with queued/exec child slices from the queue_s
    split the respond event stamps."""
    enq: Dict[str, dict] = {}
    out: List[dict] = []
    for e in events:
        rid = e.get("req")
        if not isinstance(rid, str):
            continue
        if e["ev"] == "serve.enqueue":
            enq[rid] = e
        elif e["ev"] == "serve.respond" and rid in enq:
            e0 = enq.pop(rid)
            t0, t1 = e0["t"], e["t"]
            base = {"pid": e0.get("pid"), "trace": rid, "span": rid,
                    "parent": None, "cut": False}
            out.append({**base, "name": f"request {rid}",
                        "t0": t0, "t1": t1,
                        "dur_s": round(t1 - t0, 6),
                        "fields": {"status": e.get("status"),
                                   "method": e0.get("method"),
                                   "n": e0.get("n"),
                                   "batch_size": e.get("batch_size")}})
            q = e.get("queue_s")
            if isinstance(q, (int, float)) and 0 < q <= t1 - t0:
                out.append({**base, "name": "queued", "parent": rid,
                            "span": f"{rid}.q", "t0": t0, "t1": t0 + q,
                            "dur_s": round(q, 6), "fields": {}})
                out.append({**base, "name": "exec", "parent": rid,
                            "span": f"{rid}.x", "t0": t0 + q, "t1": t1,
                            "dur_s": round(t1 - t0 - q, 6), "fields": {}})
    return out


def chrome_trace(events: List[dict]) -> dict:
    """The Chrome trace-event JSON ({"traceEvents": [...]}) for a
    parsed ledger: X slices for spans, i instants for point events,
    M metadata naming the process/trace lanes, s/f flow arrows for
    cross-process parentage (module docstring)."""
    spans = build_spans(events)
    if not events:
        return {"traceEvents": [], "displayTimeUnit": "ms"}
    t_base = events[0]["t"]
    for s in spans:
        t_base = min(t_base, s["t0"])

    def _us(t: float) -> float:
        return round((t - t_base) * 1e6, 1)

    # tid lanes: per (pid, trace), stable in first-appearance order
    lanes: Dict[Tuple, int] = {}
    lane_label: Dict[Tuple, str] = {}

    def _tid(pid, trace_id) -> int:
        key = (pid, trace_id)
        if key not in lanes:
            lanes[key] = len([k for k in lanes if k[0] == pid]) + 1
            if trace_id is None:
                lane_label[key] = "untraced"
            elif trace_id.startswith("r") and trace_id[1:].isdigit():
                lane_label[key] = f"request {trace_id}"
            else:
                lane_label[key] = f"trace {trace_id}"
        return lanes[key]

    prog_by_pid: Dict = {}
    for e in events:
        if e["ev"] == "session.start" and e.get("pid") is not None:
            prog_by_pid.setdefault(e["pid"], e.get("prog")
                                   or e.get("src") or "session")
    out: List[dict] = []
    span_ids: Dict[str, dict] = {}
    for s in spans:
        tid = _tid(s["pid"], s["trace"])
        args = {k: v for k, v in s["fields"].items() if v is not None}
        if s["cut"]:
            args["cut"] = True
        out.append({"ph": "X", "name": s["name"],
                    "cat": s["name"].split(".")[0],
                    "ts": _us(s["t0"]),
                    "dur": max(round(s["dur_s"] * 1e6, 1), 1.0),
                    "pid": s["pid"] if s["pid"] is not None else 0,
                    "tid": tid, "args": args})
        if s["span"] is not None:
            span_ids[s["span"]] = s
    # flow arrows: a span whose parent lives in ANOTHER pid was
    # propagated there via TPU_REDUCTIONS_TRACE_CTX — draw the arrow
    flow_n = 0
    for s in spans:
        p = s.get("parent")
        if p is None or p not in span_ids:
            continue
        parent = span_ids[p]
        if parent["pid"] == s["pid"]:
            continue
        flow_n += 1
        common = {"cat": "propagation", "name": "trace-ctx",
                  "id": flow_n}
        out.append({**common, "ph": "s", "pid": parent["pid"],
                    "tid": _tid(parent["pid"], parent["trace"]),
                    "ts": _us(min(max(parent["t0"], s["t0"]),
                                  parent["t1"]))})
        out.append({**common, "ph": "f", "bp": "e", "pid": s["pid"],
                    "tid": _tid(s["pid"], s["trace"]),
                    "ts": _us(s["t0"])})
    # instants for point events that did not become slices
    sliced = {(s["pid"], s["t1"], s["name"]) for s in spans}
    for e in events:
        base, kind = _split_bracket(e["ev"])
        if kind is not None:
            continue
        if any(isinstance(e.get(df), (int, float)) and e[df] > 0
               for df in DUR_FIELDS):
            continue
        if (e.get("pid"), e["t"], e["ev"]) in sliced:
            continue
        args = {k: v for k, v in e.items()
                if k not in ("t", "ev", "pid", "trace", "span", "parent")
                and v is not None}
        out.append({"ph": "i", "s": "t", "name": e["ev"],
                    "cat": e["ev"].split(".")[0], "ts": _us(e["t"]),
                    "pid": e.get("pid") if e.get("pid") is not None
                    else 0,
                    "tid": _tid(e.get("pid"), e.get("trace")),
                    "args": args})
    # lane metadata last (ph M sorts anywhere; keep deterministic)
    for pid, prog in sorted(prog_by_pid.items(), key=lambda kv: str(kv)):
        out.append({"ph": "M", "name": "process_name", "pid": pid,
                    "args": {"name": f"{prog} (pid {pid})"}})
    for (pid, _tr), tid in sorted(lanes.items(),
                                  key=lambda kv: (str(kv[0][0]), kv[1])):
        out.append({"ph": "M", "name": "thread_name",
                    "pid": pid if pid is not None else 0, "tid": tid,
                    "args": {"name": lane_label[(pid, _tr)]}})
    return {"traceEvents": out, "displayTimeUnit": "ms"}


def main(argv=None) -> int:
    """CLI: ledger.jsonl -> trace.json (module docstring; the runbook
    step is "open trace.json in https://ui.perfetto.dev")."""
    from tpu_reductions.obs.timeline import read_ledger
    p = argparse.ArgumentParser(
        prog="tpu_reductions.obs.trace_export",
        description="Export a flight-recorder ledger as Chrome-trace/"
                    "Perfetto JSON (span tree, process/trace lanes)")
    p.add_argument("ledger", help="JSONL event ledger (obs/ledger.py; "
                                  "a rotated <ledger>.1 is stitched in)")
    p.add_argument("--out", default="trace.json",
                   help="Chrome trace JSON output (default trace.json)")
    ns = p.parse_args(argv)
    try:
        events, torn = read_ledger(ns.ledger)
    except OSError as e:
        print(f"trace_export: cannot read {ns.ledger}: {e}",
              file=sys.stderr)
        return 1
    doc = chrome_trace(events)
    from tpu_reductions.utils.jsonio import atomic_json_dump
    atomic_json_dump(ns.out, doc)
    slices = sum(1 for e in doc["traceEvents"] if e["ph"] == "X")
    lanes = len({(e.get("pid"), e.get("tid"))
                 for e in doc["traceEvents"] if e["ph"] == "X"})
    print(f"trace_export: {len(events)} event(s) ({torn} torn) -> "
          f"{slices} slice(s) on {lanes} lane(s): {ns.out} "
          "(open in https://ui.perfetto.dev)", file=sys.stderr)
    return 0 if events else 1


if __name__ == "__main__":
    sys.exit(main())
