"""L3: distributed communication backend — mesh building + collective
reductions over ICI/DCN.

TPU-native equivalent of the reference's MPI backend (SURVEY.md §2.6):
MPI_Reduce over the Blue Gene/L torus becomes jax.lax.psum/pmin/pmax under
shard_map on a jax.sharding.Mesh; VN/CO node modes and the BGLMPI_MAPPING
task-placement variable become device-granularity and mesh-axis-order
options; SLURM + mpirun multi-node launch becomes the JAX distributed
runtime (jax.distributed.initialize) over DCN.
"""

from tpu_reductions.parallel.mesh import (build_mesh, device_inventory,
                                          initialize_distributed)
from tpu_reductions.parallel.collectives import (bandwidth_report,
                                                 make_collective_reduce,
                                                 shard_payload)

__all__ = ["build_mesh", "device_inventory", "initialize_distributed",
           "make_collective_reduce", "shard_payload", "bandwidth_report"]
