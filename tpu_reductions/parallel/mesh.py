"""Mesh construction over ICI/DCN — the rank/placement layer.

Reference mapping (SURVEY.md §2.6):
- `MPI_Init/Comm_rank/Comm_size` (reduce.c:32-34) ≙ jax device discovery +
  `build_mesh`; the mesh axis size is the comm size.
- SLURM `--nodes` sweep (submit_all.sh:3-4) ≙ the `num_devices` argument.
- Blue Gene VN vs CO mode — 2 ranks/node vs 1 (ccni_vn.sh:6, `-mode VN`)
  ≙ `mode`: "vn" addresses every device, "co" one device per chip/host
  pair (coarser granularity, fewer-but-fatter ranks).
- `BGLMPI_MAPPING=TXYZ` task placement (ccni_vn.sh:3) ≙ `mapping`:
  device-order permutations controlling which physical neighbors become
  mesh neighbors (axis order determines which collectives ride which ICI
  axis).
- Multi-node launch (`mpirun` under sbatch) ≙ `initialize_distributed`
  wrapping jax.distributed.initialize over DCN.
"""

from __future__ import annotations

import math
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

DEFAULT_AXIS = "ranks"

MAPPINGS = ("default", "reversed", "interleaved")


def device_inventory() -> dict:
    """Discoverable topology facts (the deviceQuery analog, and the
    `MPI_Comm_size` source of truth)."""
    devs = jax.devices()
    return {
        "platform": jax.default_backend(),
        "num_devices": len(devs),
        "num_processes": jax.process_count(),
        "process_index": jax.process_index(),
        "device_kinds": sorted({d.device_kind for d in devs}),
    }


def _order_devices(devs: list, mapping: str) -> list:
    """Permute device order — the BGLMPI_MAPPING analog. On a real torus
    the order decides which logical neighbors are physical ICI neighbors;
    'reversed' and 'interleaved' exist to expose placement sensitivity the
    way TXYZ-vs-XYZT did on the Blue Gene."""
    if mapping == "default":
        return devs
    if mapping == "reversed":
        return devs[::-1]
    if mapping == "interleaved":
        return devs[0::2] + devs[1::2]
    raise ValueError(f"unknown mapping {mapping!r}; one of {MAPPINGS}")


def coarsen_to_chips(devs: Sequence) -> list:
    """One device per physical chip — the real CO-mode granularity.

    Blue Gene CO mode ran 1 rank per node where VN ran one per core
    (ccni_vn.sh:6). The TPU twin: on generations whose JAX devices are
    per-TensorCore (v2/v3/v5p expose `coords` shared by a chip's cores
    and a distinguishing `core_on_chip`), CO keeps the first core of
    every chip. On single-device-per-chip generations (v4/v5e megacore)
    every device already IS a chip and CO == VN — exactly as CO == VN on
    a single-core Blue Gene node would have been.

    Devices without chip topology (the virtual CPU test mesh) SIMULATE
    the VN->CO halving by keeping every other device — that branch
    exists so the CO code path is exercisable off-TPU, and is labeled a
    simulation here and in PARITY.md, not claimed as a granularity
    semantic.
    """
    if not all(hasattr(d, "coords") for d in devs):
        return list(devs[0::2]) if len(devs) > 1 else list(devs)
    seen: dict = {}
    for d in devs:
        chip = (d.process_index, getattr(d, "slice_index", 0),
                tuple(d.coords))
        if chip not in seen or getattr(d, "core_on_chip", 0) < \
                getattr(seen[chip], "core_on_chip", 0):
            seen[chip] = d
    return list(seen.values())


def build_mesh(num_devices: Optional[int] = None,
               mesh_shape: Optional[Sequence[int]] = None,
               axis_names: Optional[Sequence[str]] = None,
               mapping: str = "default",
               mode: str = "vn") -> Mesh:
    """Build the reduction mesh.

    num_devices: rank count (defaults to all available after `mode`
    filtering) — the sbatch --nodes analog. mesh_shape/axis_names allow a
    multi-axis (torus-like) mesh; default is 1-D ("ranks",).
    """
    devs = jax.devices()
    if mode == "co":
        devs = coarsen_to_chips(devs)
    elif mode != "vn":
        raise ValueError("mode must be 'vn' or 'co'")
    devs = _order_devices(devs, mapping)
    if num_devices is not None:
        if num_devices > len(devs):
            raise ValueError(f"requested {num_devices} devices, "
                             f"only {len(devs)} available in mode={mode!r}")
        devs = devs[:num_devices]
    if mesh_shape is None:
        mesh_shape = (len(devs),)
        axis_names = tuple(axis_names or (DEFAULT_AXIS,))
    else:
        mesh_shape = tuple(mesh_shape)
        if math.prod(mesh_shape) != len(devs):
            raise ValueError(f"mesh_shape {mesh_shape} != {len(devs)} devices")
        if axis_names is None:
            axis_names = ((DEFAULT_AXIS,) if len(mesh_shape) == 1
                          else tuple(f"ax{i}"
                                     for i in range(len(mesh_shape))))
        axis_names = tuple(axis_names)
        if len(axis_names) != len(mesh_shape):
            raise ValueError(f"{len(axis_names)} axis names for "
                             f"{len(mesh_shape)}-d mesh")
    dev_array = np.array(devs).reshape(mesh_shape)
    return Mesh(dev_array, axis_names)


def _distributed_client_active() -> bool:
    """Whether jax.distributed.initialize has already run in this
    process (calling it twice raises)."""
    try:
        from jax._src import distributed as _dist
        return _dist.global_state.client is not None
    except Exception:       # private-API drift: assume not initialized
        return False


def initialize_distributed(coordinator_address: Optional[str] = None,
                           num_processes: Optional[int] = None,
                           process_id: Optional[int] = None) -> bool:
    """Multi-host bring-up over DCN — the mpirun/SLURM launch analog
    (ccni_vn.sh:6-8). Every participating process calls this before
    build_mesh; the mesh then spans all processes' devices and the
    collectives ride the cross-host transport (ICI within a slice, DCN/
    gloo across hosts). Returns True when it initialized the runtime,
    False when it no-opped (single-process, or already initialized —
    jax.distributed.initialize raises if called twice, so the guard is
    load-bearing, not cosmetic).

    Launch recipe: docs/MULTIHOST.md (pod slice: one process per host,
    same binary, coordinator = host 0; localhost demo: two CPU processes
    over gloo — exercised by tests/test_mesh_distributed.py and
    `python __graft_entry__.py`)."""
    if num_processes in (None, 1):
        return False
    if _distributed_client_active():
        return False
    jax.distributed.initialize(coordinator_address=coordinator_address,
                               num_processes=num_processes,
                               process_id=process_id)
    return True
