"""Compat shim: the collective suite moved to `tpu_reductions.collectives`.

This module was the 650-line monolith holding every cross-chip reduction
(the MPI_Reduce analog, reduce.c:76,90); it is now a package —
collectives/rings.py (ring machinery), collectives/quant.py (quantized
wire forms), collectives/algorithms.py (registry + selector),
collectives/core.py (builders + host plumbing). Every pre-package name
is re-exported here so existing imports keep working; new code should
import from `tpu_reductions.collectives` directly.
"""

from tpu_reductions.collectives.algorithms import (  # noqa: F401
    ROOTED_MODES, WIRE_FACTORS, _halving_applies, bandwidth_report,
    collective_algorithm, dd_ring_algorithm, normalize_rooted,
    q8_ring_algorithm)
from tpu_reductions.collectives.core import (  # noqa: F401
    _COLLECTIVES, host_collective_oracle, local_view,
    local_view_and_selection, make_chained_collective,
    make_chained_pair_collective, make_collective_reduce,
    make_dd_sum_all_reduce, make_key_minmax_all_reduce,
    mesh_spans_processes, shard_payload)
from tpu_reductions.collectives.quant import (  # noqa: F401
    Q8_BLOCK, make_q8_sum_all_reduce)
from tpu_reductions.collectives.rings import (  # noqa: F401
    ring_rs_ag as _ring_rs_ag, shard_map)
