"""Streaming reduction pipeline: double-buffered host->device staging
overlapped with on-device accumulation.

The reference stages its whole payload in ONE untimed H2D copy before
the timed loop (reduction.cpp:721-726) and our port inherited that
shape — which is exactly why the 4 GiB shmoo cell killed both round-2
relay windows (utils/staging.py module docstring has the history) and
why the serving engine capped admissions at 512 MiB. This module
replaces stage-then-reduce with a pipeline over bounded chunks
(config.stage_chunk_bytes doctrine), following Zhang et al.
(arXiv:2112.01075, PAPERS.md): when transport is the bottleneck,
chunked pipelining that overlaps transfer with compute is the win —
our tunnel relay IS that bottleneck.

Shape of the pipeline (ROADMAP item 2; docs/STREAMING.md):

  acc  = identity (SUBLANES, LANES) block, resident on device
  d[0] = put_chunk_async(chunk 0)              # transfer in flight
  for i in chunks:
      d[i+1] = put_chunk_async(chunk i+1)      # next transfer launches
      acc    = fold(acc, d[i])                 # while this fold runs
      every `sync_every` chunks:
          partial = device_get(acc)            # ~4 KiB: the honest
                                               # materialization point

Because jax dispatch is asynchronous, both the put and the fold return
on dispatch: chunk i+1's host slicing + transfer genuinely overlap
chunk i's device fold, and at most TWO chunk buffers (plus the tiny
accumulator block) are resident on device at any instant — an
arbitrarily large (multi-TB or unbounded) input reduces in O(2 chunks)
of device memory, and no single message can ever reconstruct the 4 GiB
relay killer. The periodic `partial` fetch is at once the honest
timing boundary (CLAUDE.md: per-launch synced timings are bogus on
this platform; only host materialization is real), the liveness tick
the heartbeat watchdog keys on, and the resume checkpoint a mid-stream
relay flap restarts from (bench/stream.py persists it under the
bench/resume contract).

float64 never touches the device (CLAUDE.md): SUM streams as
(hi, lo) float32 double-double planes folded with error-free
transformations, MIN/MAX as order-preserving int32 key pairs — the
ops/dd_reduce.py encodings, chunk-grain. The streaming SUM split is
UNscaled (host_split, not host_split_scaled: a per-chunk scale could
not be combined across chunks), so the f64 SUM range contract is
|x| < ~3.4e38 — far beyond every benchmark payload (byte/RAND_MAX
values, reduction.cpp:698-705); MIN/MAX keys are full-range and exact.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Sequence

import numpy as np

from tpu_reductions.config import stage_chunk_bytes
from tpu_reductions.ops.registry import ReduceOpSpec, accum_dtype, get_op

# (SUBLANES, LANES) = the 32-bit VPU tile (pallas_guide.md): the
# accumulator block shape, and the alignment quantum of every chunk
_SUBLANES = 8
_LANES = 128
_BLOCK = _SUBLANES * _LANES

_I32_MAX = np.int32(2**31 - 1)
_I32_MIN = np.int32(-2**31)


@dataclasses.dataclass(frozen=True)
class ChunkPlan:
    """The static chunk geometry of one streamed reduction — part of
    the resume meta contract (bench/resume.Checkpoint): a partial
    accumulator checkpointed under one plan must never be resumed
    under another.

    No reference analog (TPU-native).
    """

    n: int                 # total payload elements
    dtype: str
    chunk_elems: int       # full-chunk element count (BLOCK-aligned,
    #                        power-of-two block count)
    num_chunks: int        # ceil(n / chunk_elems)
    chunk_bytes: int       # the bound chunk_elems was fit under

    @property
    def chunk_rows(self) -> int:
        """Staged (rows, LANES) height of one full chunk.
        No reference analog (TPU-native)."""
        return self.chunk_elems // _LANES

    def chunk_span(self, index: int) -> tuple[int, int]:
        """[start, end) element range of chunk `index` (the last chunk
        is ragged; its staged tail pads with the op identity).

        No reference analog (TPU-native).
        """
        if not 0 <= index < self.num_chunks:
            raise IndexError(f"chunk {index} outside 0..{self.num_chunks}")
        start = index * self.chunk_elems
        return start, min(start + self.chunk_elems, self.n)


def plan_chunks(n: int, dtype: str, chunk_bytes: Optional[int] = None
                ) -> ChunkPlan:
    """Fit the largest power-of-two count of (SUBLANES, LANES) blocks
    under the per-message bound (config.stage_chunk_bytes — the
    round-2 relay-hazard doctrine). A power-of-two block count keeps
    the in-chunk fold a static halving tree on the dd pair path and
    one retrace-free executable shape everywhere.

    No reference analog (TPU-native).
    """
    if n <= 0:
        raise ValueError("n must be positive")
    bound = stage_chunk_bytes(chunk_bytes)
    itemsize = np.dtype(dtype).itemsize
    if str(dtype) == "float64":
        # f64 streams as TWO 32-bit planes per chunk (dd pair
        # encoding): the wire cost per element is unchanged (8 B), but
        # each plane message must respect the bound on its own
        itemsize = 4
    blocks = max(1, bound // (itemsize * _BLOCK))
    blocks = 1 << (blocks.bit_length() - 1)          # floor to pow2
    chunk_elems = blocks * _BLOCK
    num_chunks = -(-n // chunk_elems)
    return ChunkPlan(n=n, dtype=str(dtype), chunk_elems=chunk_elems,
                     num_chunks=num_chunks, chunk_bytes=bound)


def _jit_fold(method: str, dtype: str, donate: bool):
    """Jitted (acc, chunk2d) -> acc fold, built once per (method,
    dtype, donate): the chunk collapses to one (SUBLANES, LANES) block
    along the leading axis and combines elementwise into the resident
    accumulator — the grid-stride accumulate of the reference kernel
    (reduction_kernel.cu:88-98) at chunk grain, with donation so the
    device never holds two accumulator generations."""
    import jax
    import jax.numpy as jnp

    op = get_op(method)

    def fold(acc, chunk2d):
        folded = op.jnp_reduce(
            chunk2d.reshape(-1, _SUBLANES, _LANES), axis=0)
        return op.jnp_combine(acc, folded.astype(acc.dtype))

    return jax.jit(fold, donate_argnums=(0,) if donate else ())


def _jit_dd_fold(method: str, donate: bool):
    """Jitted pair fold for streamed f64: (acc_hi, acc_lo, hi2d, lo2d)
    -> (acc_hi, acc_lo). In-chunk: a static halving tree of error-free
    transformations (dd add for SUM, lexicographic key selection for
    MIN/MAX — ops/dd_reduce.py's kernel arithmetic); cross-chunk: one
    elementwise pair combine into the resident accumulator blocks. All
    32-bit, TPU-safe (no f64 anywhere, CLAUDE.md)."""
    import jax

    from tpu_reductions.ops.dd_reduce import _dd_add, _dd_select

    method = method.upper()

    def fold(acc_hi, acc_lo, hi2d, lo2d):
        hi = hi2d.reshape(-1, _SUBLANES, _LANES)
        lo = lo2d.reshape(-1, _SUBLANES, _LANES)
        while hi.shape[0] > 1:                 # pow2 by plan_chunks
            h = hi.shape[0] // 2
            if method == "SUM":
                hi, lo = _dd_add(hi[:h], lo[:h], hi[h:], lo[h:])
            else:
                hi, lo = _dd_select(hi[:h], lo[:h], hi[h:], lo[h:],
                                    minimum=(method == "MIN"))
        hi, lo = hi[0], lo[0]
        if method == "SUM":
            return _dd_add(acc_hi, acc_lo, hi, lo)
        return _dd_select(acc_hi, acc_lo, hi, lo,
                          minimum=(method == "MIN"))

    return jax.jit(fold, donate_argnums=(0, 1) if donate else ())


class StreamReducer:
    """The device half of the streaming pipeline: a persistent
    (SUBLANES, LANES) on-device partial accumulator (pair of blocks on
    the f64 dd path) that bounded chunks fold into, with checkpoint/
    restore at the fetched-partial grain.

    The reference has no analog — its whole payload is device-resident
    before the first kernel (reduction.cpp:721-726); this class is what
    removes that requirement. Drive it through `run_stream` (which owns
    the double-buffer loop, heartbeat, fault points and ledger events)
    rather than directly.
    """

    def __init__(self, method: str, dtype: str, n: int, *,
                 chunk_bytes: Optional[int] = None) -> None:
        import jax

        self.method = method.upper()
        self.dtype = str(dtype)
        self.op: ReduceOpSpec = get_op(self.method)
        self.plan = plan_chunks(n, self.dtype, chunk_bytes)
        self.is_dd = self.dtype == "float64"
        donate = jax.default_backend() == "tpu"
        if self.is_dd:
            self._fold = _jit_dd_fold(self.method, donate)
        else:
            self._fold = _jit_fold(self.method, self.dtype, donate)
        self._acc = None       # device block, or (hi, lo) pair
        self._compile_observed = False   # first fold = compile span

    # -- accumulator lifecycle -----------------------------------------

    def _identity_partial(self) -> "np.ndarray | tuple":
        if self.is_dd:
            if self.method == "SUM":
                z = np.zeros((_SUBLANES, _LANES), np.float32)
                return z, z.copy()
            ident = _I32_MAX if self.method == "MIN" else _I32_MIN
            k = np.full((_SUBLANES, _LANES), ident, np.int32)
            return k, k.copy()
        if self.method == "SUM":
            dt = np.dtype(accum_dtype(self.dtype))
        else:
            dt = np.dtype(self.dtype)
        return np.full((_SUBLANES, _LANES), self.op.identity(dt), dt)

    def restore(self, partial=None) -> None:
        """Install a partial accumulator on device: None = the op's
        identity (a fresh stream); otherwise the host-side partial a
        previous `partial()` fetch produced — the resume-from-last-
        verified-chunk primitive (bench/stream.py checkpoint rows).

        No reference analog (TPU-native).
        """
        from tpu_reductions.utils.staging import put_chunk_async
        if partial is None:
            partial = self._identity_partial()
        if self.is_dd:
            hi, lo = partial
            self._acc = (put_chunk_async(np.asarray(hi)),
                         put_chunk_async(np.asarray(lo)))
        else:
            self._acc = put_chunk_async(np.asarray(partial))

    def stage(self, flat: np.ndarray, index: int):
        """Cut + pad chunk `index` out of the flat host payload and
        start its (dispatch-async) transfer — the double-buffered half
        of the reference's one-shot H2D staging (reduction.cpp:721-726).
        Ragged tails pad with the op's monoid identity (registry.py:
        identity lanes cannot perturb any result); every chunk ships at
        the same full-chunk shape so the fold executable never
        retraces. f64 splits to its two 32-bit planes here (module
        docstring)."""
        from tpu_reductions.utils.staging import put_chunk_async
        start, end = self.plan.chunk_span(index)
        rows = self.plan.chunk_rows
        piece = np.ravel(flat)[start:end]
        if self.is_dd:
            from tpu_reductions.ops.dd_reduce import (host_key_encode,
                                                      host_split)
            piece = np.asarray(piece, np.float64)
            if self.method == "SUM":
                hi, lo = host_split(piece)
                pads = (np.float32(0.0), np.float32(0.0))
            else:
                hi, lo = host_key_encode(piece)
                pads = ((_I32_MAX, _I32_MAX) if self.method == "MIN"
                        else (_I32_MIN, _I32_MIN))
            pad = self.plan.chunk_elems - piece.size
            hi = np.pad(hi, (0, pad), constant_values=pads[0])
            lo = np.pad(lo, (0, pad), constant_values=pads[1])
            return (put_chunk_async(hi.reshape(rows, _LANES)),
                    put_chunk_async(lo.reshape(rows, _LANES)))
        piece = np.asarray(piece)
        pad = self.plan.chunk_elems - piece.size
        if pad:
            piece = np.pad(piece, (0, pad),
                           constant_values=self.op.identity(piece.dtype))
        return put_chunk_async(piece.reshape(rows, _LANES))

    def fold(self, staged) -> None:
        """Fold one staged chunk into the resident accumulator
        (dispatch-async; the periodic `partial()` fetch is the
        completion point) — the grid-stride accumulate
        (reduction_kernel.cu:88-98) at chunk grain. The FIRST fold is
        the chunk executable's compile point: it is bracketed in a
        compile observatory span (obs/compile.py, surface `stream`) so
        the pipeline's one compile lands in the ledger with its
        cold/warm cache verdict — later folds pay nothing."""
        assert self._acc is not None, "restore() before fold()"
        if not self._compile_observed:
            self._compile_observed = True
            from tpu_reductions.exec import core as exec_core
            with exec_core.observe_compile(
                    "stream", op=self.method, dtype=self.plan.dtype,
                    chunk_elems=self.plan.chunk_elems, pair=self.is_dd):
                self._fold_one(staged)
            return
        self._fold_one(staged)

    def _fold_one(self, staged) -> None:
        if self.is_dd:
            hi, lo = staged
            self._acc = self._fold(self._acc[0], self._acc[1], hi, lo)
        else:
            self._acc = self._fold(self._acc, staged)

    def partial(self):
        """Materialize the running partial on host (~4 KiB) — the
        honest timing boundary, the heartbeat's forward-progress proof,
        and the resume checkpoint payload, in one fetch (module
        docstring).

        No reference analog (TPU-native).
        """
        import jax
        assert self._acc is not None, "restore() before partial()"
        if self.is_dd:
            hi = np.asarray(jax.device_get(self._acc[0]))
            lo = np.asarray(jax.device_get(self._acc[1]))
            return hi, lo
        return np.asarray(jax.device_get(self._acc))

    def finish(self, partial=None):
        """Collapse a fetched partial block to the final scalar on
        host — the D2H + final-fold tail of the reference flow
        (reduction.cpp:328-340,377-381), block-sized here because the
        streamed accumulator IS the partials array. int32 SUM wraps
        mod 2^32 (np int32 accumulate) to match the device accumulator;
        f64 decodes through the dd pair finish (bit-exact for MIN/MAX
        keys)."""
        if partial is None:
            partial = self.partial()
        if self.is_dd:
            from tpu_reductions.ops.dd_reduce import host_finish_pairs
            hi, lo = partial
            return host_finish_pairs(hi, lo, self.method)
        block = np.asarray(partial)
        if self.method == "SUM":
            if block.dtype == np.int32:
                # exact int64 fold wrapped to int32 == the device's
                # wrapping int32 accumulator (reduction.cpp:748,776-777)
                return np.int64(block.sum(dtype=np.int64)
                                ).astype(np.int32)[()]
            return np.float64(block.astype(np.float64).sum())
        return self.op.np_reduce(block)


def partial_to_jsonable(partial) -> dict:
    """Serialize a fetched partial for the resume checkpoint artifact
    (bench/resume rows are JSON): {'planes': [...], 'dtype': ...} —
    float planes round-trip exactly (repr-precision floats; i32 keys
    as ints).

    No reference analog (TPU-native).
    """
    planes = list(partial) if isinstance(partial, tuple) \
        else [np.asarray(partial)]
    return {"dtype": str(np.asarray(planes[0]).dtype),
            "planes": [np.asarray(p).ravel().tolist() for p in planes]}


def partial_from_jsonable(spec: dict):
    """Invert partial_to_jsonable back into restore()'s input shape.

    No reference analog (TPU-native).
    """
    dt = np.dtype(spec["dtype"])
    planes = [np.asarray(p, dtype=dt).reshape(_SUBLANES, _LANES)
              for p in spec["planes"]]
    return tuple(planes) if len(planes) == 2 else planes[0]


@dataclasses.dataclass
class StreamResult:
    """Outcome of one streamed reduction (run_stream): the final
    scalar plus the sustained-rate metrics that replace the per-launch
    GB/s of the staged benchmark (reduction.cpp:743-745) — wall-clock
    here runs first-stage to final partial materialization, so the
    number is honest by construction (module docstring)."""

    value: object                 # np scalar (np.float64 on dd path)
    chunks_done: int
    num_chunks: int
    nbytes: int
    wall_s: float
    syncs: int
    resumed_from: int = 0         # first chunk this run folded

    @property
    def gbps(self) -> float:
        """Sustained GB/s over the streamed span (transfer + fold,
        overlapped — NOT a kernel-only rate). No reference analog
        (TPU-native)."""
        return (self.nbytes / self.wall_s) / 1e9 if self.wall_s > 0 \
            else float("inf")

    @property
    def chunks_per_s(self) -> float:
        """Pipeline cadence: chunks folded per second this run.
        No reference analog (TPU-native)."""
        done = self.chunks_done - self.resumed_from
        return done / self.wall_s if self.wall_s > 0 else float("inf")


def run_stream(flat: np.ndarray, method: str, *,
               chunk_bytes: Optional[int] = None,
               sync_every: int = 8,
               start_chunk: int = 0,
               init_partial=None,
               on_sync=None,
               reducer: Optional[StreamReducer] = None) -> StreamResult:
    """Drive the full double-buffered streaming pipeline over a flat
    host payload (module docstring has the loop shape). This is the
    ONE sanctioned loop: it owns the `stream.chunk` fault point
    (faults/inject.py), the heartbeat guard/ticks (a stalled relay
    mid-stream draws watchdog exit 4, not a hang), and the stream.*
    flight-recorder events (docs/OBSERVABILITY.md).

    `on_sync(chunks_done, partial, oracle_ready)` fires at every
    periodic materialization with the fetched partial — bench/stream.py
    persists it as the resume checkpoint; `start_chunk`/`init_partial`
    resume a stream from a prior checkpoint (chunks before start_chunk
    are never re-staged or re-folded). Every fold is sequential over
    the same chunk boundaries regardless of where a run started, so a
    resumed stream's final value is byte-identical to an uninterrupted
    one's.

    The reference's analog is the untimed one-shot stage + timed loop
    (reduction.cpp:721-745); here staging IS the timed loop, overlapped.
    """
    import time

    from tpu_reductions.exec import core as exec_core
    from tpu_reductions.exec.plan import launch_plan
    from tpu_reductions.faults.inject import fault_point
    from tpu_reductions.obs import ledger, trace

    flat = np.ravel(flat)
    r = reducer or StreamReducer(method, str(flat.dtype), flat.size,
                                 chunk_bytes=chunk_bytes)
    plan = r.plan
    if not 0 <= start_chunk <= plan.num_chunks:
        raise ValueError(f"start_chunk {start_chunk} outside plan "
                         f"(0..{plan.num_chunks})")
    sync_every = max(1, int(sync_every))
    # one span per stream (ISSUE 12): the start/end bracket shares a
    # child trace context, and every chunk/sync event inside carries
    # it — trace_export renders the pipeline as one slice with the
    # per-chunk stage-vs-fold overlap split in its events
    with trace.child():
        ledger.emit("stream.start", method=r.method, dtype=r.dtype,
                    n=plan.n, nbytes=int(flat.nbytes),
                    chunk_elems=plan.chunk_elems,
                    num_chunks=plan.num_chunks, start_chunk=start_chunk,
                    sync_every=sync_every)
        t0 = time.monotonic()
        partial = None
        syncs = 0

        def pipeline(ctx):
            # the whole double-buffered loop is ONE plan: the executor
            # holds the "stream" heartbeat phase around it (contract),
            # the per-chunk forward-progress marks are ctx.tick()
            nonlocal partial, syncs
            r.restore(init_partial)
            if start_chunk < plan.num_chunks:
                inflight = r.stage(flat, start_chunk)
            for i in range(start_chunk, plan.num_chunks):
                # chaos hook: the relay dying mid-chunk IS the round-2
                # death shape this pipeline exists to survive
                # (tests/test_stream_chaos.py drives this point)
                fault_point("stream.chunk")
                t_stage = time.monotonic()
                nxt = r.stage(flat, i + 1) if i + 1 < plan.num_chunks \
                    else None
                t_fold = time.monotonic()
                r.fold(inflight)           # overlaps nxt's transfer
                t_done = time.monotonic()
                inflight = nxt
                ctx.tick()
                done = i + 1
                # stage_s/fold_s are DISPATCH-side wall clock (the
                # honest-timing doctrine: device completion is only
                # observable at the periodic materialization) — enough
                # to see the double-buffer overlap, not a device timing
                ledger.emit("stream.chunk", chunk=i, chunks_done=done,
                            total=plan.num_chunks,
                            stage_s=round(t_fold - t_stage, 6),
                            fold_s=round(t_done - t_fold, 6))
                if done % sync_every == 0 or done == plan.num_chunks:
                    partial = r.partial()  # honest materialization
                    syncs += 1
                    ctx.tick()
                    ledger.emit("stream.sync", chunks_done=done,
                                total=plan.num_chunks,
                                elapsed_s=round(
                                    time.monotonic() - t0, 6))
                    if on_sync is not None:
                        on_sync(done, partial)
            if partial is None:        # resumed-at-end degenerate case
                partial = r.partial()

        exec_core.run(launch_plan(
            "stream", "stream", pipeline, timing="stream",
            heartbeat_phase="stream",
            staging_bound=int(plan.chunk_bytes),
            method=r.method, dtype=r.dtype, n=plan.n,
            chunks=plan.num_chunks, start_chunk=start_chunk))
        wall = time.monotonic() - t0
        value = r.finish(partial)
        span = plan.chunk_span(start_chunk)[0] if start_chunk \
            < plan.num_chunks else plan.n
        nbytes = int(flat.nbytes) - span * flat.dtype.itemsize
        res = StreamResult(value=value, chunks_done=plan.num_chunks,
                           num_chunks=plan.num_chunks, nbytes=nbytes,
                           wall_s=wall, syncs=syncs,
                           resumed_from=start_chunk)
        ledger.emit("stream.end", chunks=plan.num_chunks,
                    resumed_from=start_chunk, wall_s=round(wall, 6),
                    gbps=round(res.gbps, 4),
                    chunks_per_s=round(res.chunks_per_s, 4))
    return res


def iter_chunks(flat: np.ndarray, plan: ChunkPlan,
                start: int = 0) -> Sequence[np.ndarray]:
    """Host-side chunk views under `plan` (the incremental oracle's
    input grain, ops/oracle.IncrementalOracle) — views, not copies.

    No reference analog (TPU-native).
    """
    flat = np.ravel(flat)
    for i in range(start, plan.num_chunks):
        s, e = plan.chunk_span(i)
        yield flat[s:e]
