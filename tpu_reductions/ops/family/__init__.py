"""The reduction family: SCAN, segmented reductions, ARGMIN/ARGMAX
(ISSUE 20; docs/FAMILY.md).

The reference benchmarks exactly three full reductions
({SUM,MIN,MAX} — reduction.h:15-25, reduce.c:21-28); real traffic has
the *family* around them. This package adds three method groups and
threads them through every layer (registry, oracle, exec core,
serving wire, spot/smoke/warm instruments):

  SCAN            inclusive prefix sum — the MXU matmul trick of
                  Carrasco et al. (arXiv:1811.09736: within-block
                  scan = row-block @ upper-triangular ones matrix)
                  next to the XLA `cumsum` baseline, with a
                  chunk-carry so the streaming pipeline's 2-chunk
                  bound (ops/stream.py) scans unbounded inputs
  SEGSUM/MIN/MAX  segmented reductions over a segment-offset vector —
                  the batched row-reduce shape serving traffic has;
                  serve/executor's ragged-batch path launches ONE
                  concatenated segment reduce instead of paying
                  identity-padding to the bucket's power of two
  ARGMIN/ARGMAX   index-carrying extremes via order-preserving
                  (key, index) planes reusing ops/dd_reduce.py's
                  key-encoding idiom — exact, lowest-index tie-break
                  on both device and oracle

Method vocabulary lives in config.FAMILY_METHODS / SERVED_METHODS;
registry entries in ops/registry.FAMILY_OPS. Every device launch built
here goes through the one executor (`exec.core.run` on a LaunchPlan —
RED025: no raw guard/retry spellings in this package).
"""

from __future__ import annotations

from tpu_reductions.config import FAMILY_METHODS, SERVED_METHODS
from tpu_reductions.ops.family.argreduce import (arg_reduce_fn,
                                                 arg_reduce_rows_fn,
                                                 host_arg_reduce,
                                                 order_key)
from tpu_reductions.ops.family.scan import (SCAN_IMPLS, StreamScanner,
                                            host_scan, scan_fn,
                                            scan_impls, scan_rows_fn)
from tpu_reductions.ops.family.segmented import (SEG_BASE,
                                                 host_segment_reduce,
                                                 random_offsets,
                                                 segment_ids_from_offsets,
                                                 segment_reduce_fn)

__all__ = [
    "FAMILY_METHODS", "SERVED_METHODS", "SCAN_IMPLS", "SEG_BASE",
    "is_family_method", "family_surface",
    "scan_fn", "scan_rows_fn", "scan_impls", "host_scan",
    "StreamScanner",
    "segment_reduce_fn", "host_segment_reduce",
    "segment_ids_from_offsets", "random_offsets",
    "arg_reduce_fn", "arg_reduce_rows_fn", "host_arg_reduce",
    "order_key",
]


def is_family_method(name: str) -> bool:
    """Whether `name` is a family method (SCAN/SEG*/ARG*) as opposed to
    a classic full reduction (config.METHODS). No reference analog
    (TPU-native)."""
    return name.upper() in FAMILY_METHODS


def family_surface(method: str, impl: str | None = None) -> str:
    """Compile-observatory surface id for a family launch — the warm/
    smoke manifest rows and the spot cells must agree on these
    spellings (bench/warm.py: mxu-scan, seg, argk).

    No reference analog (TPU-native).
    """
    m = method.upper()
    if m == "SCAN":
        return impl or "xla-cumsum"
    if m in SEG_BASE:
        return f"seg/{m.lower()}"
    if m in ("ARGMIN", "ARGMAX"):
        return f"argk/{m.lower()}"
    raise ValueError(f"not a family method: {method!r}")
