"""Segmented reductions — SEGSUM / SEGMIN / SEGMAX over an offset
vector (ISSUE 20; docs/FAMILY.md).

A segmented reduce is the batched row-reduce shape serving traffic
has: one flat payload, a vector of segment offsets (ragged — segments
may be empty), one result per segment. serve/executor.run_batch's
stacked bucket launch is this operation in disguise with every
segment forced to the bucket's power-of-two length; the ragged path
here launches ONE concatenated segment reduce and pays zero
identity-padding (serve/executor.run_family_batch).

Device side rides XLA's segment combiners (`jax.ops.segment_sum/
min/max` — scatter-combine, not a redistribution primitive, so no
RED016 fence applies); empty segments come back as the op's monoid
identity, exactly the padding contract the classic path uses
(ops/registry.ReduceOpSpec.identity — the guard the reference's
non-pow2 min/max kernels lacked, reduction_kernel.cu:140,157).
int32 SEGSUM wraps mod 2^32 per segment on both device and oracle
(the reference's accumulator-width contract, reduction.cpp:748,776-777).

No reference analog (the reference reduces whole arrays only).
"""

from __future__ import annotations

import functools

import numpy as np

from tpu_reductions.ops.registry import get_op

# family method -> the classic op whose combine/identity/tolerance
# rules each segment follows
SEG_BASE = {"SEGSUM": "SUM", "SEGMIN": "MIN", "SEGMAX": "MAX"}


@functools.lru_cache(maxsize=None)
def segment_reduce_fn(method: str, num_segments: int):
    """Jitted (x, segment_ids) -> per-segment results for one family
    method at a static segment count (retrace per count, like every
    other shape axis).

    No reference analog (TPU-native).
    """
    import jax

    m = method.upper()
    combiner = {"SEGSUM": jax.ops.segment_sum,
                "SEGMIN": jax.ops.segment_min,
                "SEGMAX": jax.ops.segment_max}[m]

    def seg(x, ids):
        return combiner(x, ids, num_segments=num_segments)

    return jax.jit(seg)


def segment_ids_from_offsets(offsets: np.ndarray) -> np.ndarray:
    """Expand an offset vector (length S+1, offsets[0]=0,
    offsets[-1]=n, monotone; equal neighbors = empty segment) into the
    per-element segment-id vector the device combiner consumes.

    No reference analog (TPU-native).
    """
    offsets = np.asarray(offsets, dtype=np.int64)
    lengths = np.diff(offsets)
    if offsets[0] != 0 or (lengths < 0).any():
        raise ValueError("offsets must start at 0 and be monotone")
    return np.repeat(np.arange(lengths.size, dtype=np.int32), lengths)


def random_offsets(n: int, num_segments: int, seed: int) -> np.ndarray:
    """Deterministic ragged offsets for `n` elements: `num_segments`
    segments with uniformly random cut points, duplicates included —
    so empty segments occur by construction and the ragged path is
    exercised, not just the uniform one.

    No reference analog (TPU-native).
    """
    rng = np.random.default_rng(seed)
    cuts = np.sort(rng.integers(0, n + 1, size=num_segments - 1))
    return np.concatenate(([0], cuts, [n])).astype(np.int64)


def host_segment_reduce(x: np.ndarray, offsets: np.ndarray,
                        method: str) -> np.ndarray:
    """Host oracle: per-segment numpy reduce in host_reduce's result
    conventions — int32 SEGSUM wraps mod 2^32 per segment, float sums
    accumulate in float64, MIN/MAX exact; an empty segment yields the
    base op's monoid identity (the device combiner's fill value).
    Returns float64 (every family digest comparison happens in the
    float64 value domain; int32 values embed exactly).

    No reference analog (TPU-native).
    """
    from tpu_reductions.ops.oracle import host_reduce

    m = method.upper()
    base = SEG_BASE[m]
    op = get_op(base)
    x = np.ravel(np.asarray(x))
    offsets = np.asarray(offsets, dtype=np.int64)
    out = np.empty(offsets.size - 1, dtype=np.float64)
    for i in range(offsets.size - 1):
        seg = x[offsets[i]:offsets[i + 1]]
        if seg.size == 0:
            out[i] = np.float64(op.identity(x.dtype))
        else:
            out[i] = np.float64(host_reduce(seg, base))
    return out
