"""ARGMIN / ARGMAX — index-carrying extremes via order-preserving
key planes (ISSUE 20; docs/FAMILY.md).

The encoding reuses ops/dd_reduce.py's idiom (host_key_encode: an
order-preserving bitcast makes float order equal signed-integer
order) at 32-bit width: for a float32 bit pattern b,

    key = b ^ ((b >> 31) & 0x7FFFFFFF)

keeps non-negatives fixed (sign bit clear -> XOR with 0) and flips the
magnitude bits of negatives (sign bit set -> XOR with 0x7FFFFFFF), so
signed int32 order of keys == float32 total order (NaN-free payloads,
the benchmark fill contract reduction.cpp:698-705). int32 values are
their own key. The reduction is then a lexicographic MIN over the
(key, index) planes — ARGMAX over key's order-reversing complement
~key — realized as key-extreme + masked index-min, which breaks every
tie to the LOWEST index by construction; the host oracle
(np.argmin/argmax, first occurrence) has the same tie rule, so parity
is exact (ops/registry.tolerance: 0.0).

No reference analog (the reference's min/max return values only,
reduction.cpp:228-249).
"""

from __future__ import annotations

import functools

import numpy as np


def order_key(x: np.ndarray) -> np.ndarray:
    """Host-side order-preserving int32 key of an int32/float32 array
    (module docstring) — the 32-bit sibling of
    ops/dd_reduce.host_key_encode's 64-bit pair.

    No reference analog (TPU-native).
    """
    x = np.ravel(np.asarray(x))
    if x.dtype == np.int32:
        return x
    if x.dtype != np.float32:
        raise ValueError(f"order_key supports int32/float32, got {x.dtype}")
    b = x.view(np.int32)
    return b ^ ((b >> np.int32(31)) & np.int32(0x7FFFFFFF))


@functools.lru_cache(maxsize=None)
def arg_reduce_fn(method: str, dtype: str):
    """Jitted x -> int32 index of the extreme, lowest index on ties.

    No reference analog (TPU-native).
    """
    import jax
    import jax.numpy as jnp

    m = method.upper()
    if m not in ("ARGMIN", "ARGMAX"):
        raise ValueError(f"not an arg method: {method!r}")
    floating = np.issubdtype(np.dtype(dtype), np.floating)

    def argk(x):
        n = x.shape[0]
        if floating:
            b = jax.lax.bitcast_convert_type(x, jnp.int32)
            key = b ^ ((b >> 31) & jnp.int32(0x7FFFFFFF))
        else:
            key = x
        if m == "ARGMAX":
            # bitwise complement reverses int32 order exactly (no
            # negation overflow at INT32_MIN), turning the lexicographic
            # MIN machinery into ARGMAX
            key = ~key
        kmin = jnp.min(key)
        idx = jnp.arange(n, dtype=jnp.int32)
        # lexicographic (key, index) MIN: among the extreme's ties the
        # smallest index wins; non-ties are masked to n (> any index)
        return jnp.min(jnp.where(key == kmin, idx, jnp.int32(n)))

    return jax.jit(argk)


@functools.lru_cache(maxsize=None)
def arg_reduce_rows_fn(method: str, dtype: str):
    """Jitted (k, n) -> (k,) per-row extreme indices — the coalesced
    serving shape (serve/executor.run_batch's family dispatch), same
    lexicographic (key, index) machinery as arg_reduce_fn per row.

    No reference analog (TPU-native).
    """
    import jax
    import jax.numpy as jnp

    m = method.upper()
    if m not in ("ARGMIN", "ARGMAX"):
        raise ValueError(f"not an arg method: {method!r}")
    floating = np.issubdtype(np.dtype(dtype), np.floating)

    def rows(x):
        n = x.shape[1]
        if floating:
            b = jax.lax.bitcast_convert_type(x, jnp.int32)
            key = b ^ ((b >> 31) & jnp.int32(0x7FFFFFFF))
        else:
            key = x
        if m == "ARGMAX":
            key = ~key
        kext = jnp.min(key, axis=1, keepdims=True)
        idx = jnp.arange(n, dtype=jnp.int32)[None, :]
        return jnp.min(jnp.where(key == kext, idx, jnp.int32(n)), axis=1)

    return jax.jit(rows)


def host_arg_reduce(x: np.ndarray, method: str) -> np.int64:
    """Host oracle: numpy's first-occurrence argmin/argmax — the same
    lowest-index tie rule the device lexicographic reduce has.

    No reference analog (TPU-native).
    """
    m = method.upper()
    x = np.ravel(np.asarray(x))
    if m == "ARGMIN":
        return np.int64(np.argmin(x))
    if m == "ARGMAX":
        return np.int64(np.argmax(x))
    raise ValueError(f"not an arg method: {method!r}")
