"""SCAN — inclusive prefix sum, MXU matmul trick + XLA baseline.

Carrasco et al. (arXiv:1811.09736) extend the tensor-core
matmul-as-reduction idiom (the one kernel 9 uses for full SUM,
following Navarro et al. arXiv:2001.05585) to *scan*: the inclusive
prefix sum of a row block x of width B is

    y = x @ U,   U[i, j] = 1  iff  i <= j     (upper triangular,
                                               diagonal included)

so within-block scans ride the MXU at matmul throughput. Blocks then
need their predecessors' totals added — the hierarchical carry level
of the paper's recursion; at our block counts that level is a single
vector cumsum, so it stays on the VPU rather than paying a quadratic
(nb x nb) ones matrix.

Two implementations behind one `scan_fn(impl, dtype)` cache:

  xla-cumsum   `jnp.cumsum` — the XLA baseline, every dtype; int32
               wraps mod 2^32 (same accumulator-width contract as SUM,
               reduction.cpp:748,776-777)
  mxu-scan     the blocked matmul trick above — float dtypes only (an
               integer matmul would not land on the MXU), highest
               precision so the ones-matrix products are exact sums

`StreamScanner` is the chunk-carry composition with the streaming
pipeline's chunk plan (ops/stream.plan_chunks): per bounded chunk,
y = scan(chunk) + carry and carry' = y[-1], so an arbitrarily large
input scans under the <= 2-chunk device-residency bound and no message
can exceed config.stage_chunk_bytes. For int32 the chunk-carry result
is bit-identical to the one-shot cumsum (associativity of modular
addition); floats reassociate across the chunk boundary within SUM's
declared tolerance (ops/registry.tolerance).

No reference analog (the reference has no scan at all).
"""

from __future__ import annotations

import functools
from typing import Optional

import numpy as np

from tpu_reductions.ops.stream import iter_chunks, plan_chunks
from tpu_reductions.utils import staging

# MXU tile width (pallas_guide.md): the within-block scan width
_MXU_B = 128

SCAN_IMPLS = ("xla-cumsum", "mxu-scan")


def scan_impls(dtype) -> tuple:
    """The implementations legal for `dtype` — the exec/cost.py
    candidate axis (pick_scan). mxu-scan is float-only: the trick is a
    matmul, and an int32 matmul would not ride the MXU.

    No reference analog (TPU-native).
    """
    if _is_float(dtype):
        return SCAN_IMPLS
    return ("xla-cumsum",)


def _is_float(dtype) -> bool:
    """bfloat16 is a float for the MXU's purposes but not a numpy
    floating subtype (it lives in ml_dtypes), so the gate names it."""
    return (str(np.dtype(dtype)) == "bfloat16"
            or np.issubdtype(np.dtype(dtype), np.floating))


def _core(impl: str, dtype: str):
    """Traceable 1-D inclusive-prefix core for one implementation
    (module docstring) — shared by the one-shot/carry jit (scan_fn)
    and the row-batched serving jit (scan_rows_fn).

    No reference analog (TPU-native).
    """
    import jax.numpy as jnp

    if impl == "xla-cumsum":
        def core(x):
            return jnp.cumsum(x, dtype=x.dtype)
    elif impl == "mxu-scan":
        if not _is_float(dtype):
            raise ValueError(f"mxu-scan is float-only, got {dtype}")

        def core(x):
            n = x.shape[0]
            nb = -(-n // _MXU_B)
            xp = jnp.pad(x, (0, nb * _MXU_B - n)).reshape(nb, _MXU_B)
            u = jnp.triu(jnp.ones((_MXU_B, _MXU_B), dtype=x.dtype))
            # within-block scan on the MXU (1811.09736); highest
            # precision so each ones-column product is an exact sum
            within = jnp.dot(xp, u, precision="highest")
            # hierarchical carry level: exclusive prefix of block totals
            totals = within[:, -1]
            excl = jnp.cumsum(totals, dtype=x.dtype) - totals
            return (within + excl[:, None]).reshape(-1)[:n]
    else:
        raise ValueError(f"unknown scan impl {impl!r}; one of {SCAN_IMPLS}")

    return core


@functools.lru_cache(maxsize=None)
def scan_fn(impl: str, dtype: str):
    """Jitted (chunk, carry) -> inclusive prefix array. `carry` is the
    running total of everything before this chunk (0 for a one-shot
    scan); adding it on device keeps the int32 wrap in the device's
    own accumulator width.

    No reference analog (TPU-native).
    """
    import jax

    core = _core(impl, dtype)
    return jax.jit(lambda x, carry: core(x) + carry)


@functools.lru_cache(maxsize=None)
def scan_rows_fn(impl: str, dtype: str):
    """Jitted (k, n) -> (k, n) per-row inclusive prefixes — the
    coalesced serving shape (serve/executor.run_batch's family
    dispatch): k stacked SCAN requests pay one dispatch.

    No reference analog (TPU-native).
    """
    import jax

    return jax.jit(jax.vmap(_core(impl, dtype)))


def host_scan(x: np.ndarray) -> np.ndarray:
    """Host oracle: the full inclusive prefix in the device's
    accumulator conventions — int32 wraps mod 2^32 (exact int64 cumsum
    then truncate, same result class as a wrapping int32 accumulator),
    floats accumulate in float64 (the Kahan-class reference precision,
    reduction.cpp:214-227) for tolerance comparison.

    No reference analog (TPU-native).
    """
    x = np.ravel(np.asarray(x))
    if x.dtype == np.int32:
        return np.cumsum(x.astype(np.int64)).astype(np.uint64).astype(
            np.uint32).view(np.int32)
    return np.cumsum(x.astype(np.float64))


class StreamScanner:
    """Chunk-carry prefix scan over the streaming chunk plan
    (module docstring has the recurrence). Drive each device launch
    through the executor: `scan(flat, call=ctx.call)` from inside a
    LaunchPlan builder keeps the package RED025-clean.

    No reference analog (TPU-native).
    """

    def __init__(self, dtype: str, n: int, *, impl: str = "xla-cumsum",
                 chunk_bytes: Optional[int] = None) -> None:
        self.dtype = str(dtype)
        self.impl = impl
        self.plan = plan_chunks(n, self.dtype, chunk_bytes)
        self._fn = scan_fn(impl, self.dtype)
        self._carry = np.dtype(self.dtype).type(0)

    @property
    def carry(self):
        """Running total of every element scanned so far (the next
        chunk's additive offset). No reference analog (TPU-native)."""
        return self._carry

    def scan(self, flat: np.ndarray, *, call=None) -> np.ndarray:
        """Full inclusive prefix of `flat`, one bounded chunk at a
        time (<= 2 chunks device-resident: the staged chunk plus its
        in-flight result). `call` wraps each device unit — pass
        `ctx.call` from a LaunchPlan builder.

        No reference analog (TPU-native).
        """
        import jax

        call = call or (lambda fn: fn())
        flat = np.ravel(np.asarray(flat, dtype=self.dtype))
        out = np.empty(flat.size, dtype=self.dtype)
        pos = 0
        for chunk in iter_chunks(flat, self.plan):
            def unit(chunk=chunk):
                d = staging.put_chunk_async(
                    chunk, chunk_bytes=self.plan.chunk_bytes)
                return np.asarray(jax.device_get(
                    self._fn(d, self._carry)))
            y = call(unit)
            out[pos:pos + y.size] = y
            self._carry = y[-1]
            pos += y.size
        return out
