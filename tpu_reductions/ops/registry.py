"""Reduction op registry: {SUM, MIN, MAX} over {int32, float32, float64}.

The reference expresses this table twice: as 27 explicit template
instantiations per op on the CUDA side (reduction_kernel.cu:527-564,
dispatched via reduction.h:15-25) and as a {MPI_MAX,MPI_MIN,MPI_SUM} op
struct table on the MPI side (reduce.c:21-28). Here it is one registry that
every layer (XLA baseline, Pallas kernel, collectives, oracle, drivers)
keys off — `jax.jit` retracing per (op, dtype, shape) plays the role of the
compile-time template fan-out (SURVEY.md §3.4).
"""

from __future__ import annotations

import dataclasses
from typing import Callable

import jax.numpy as jnp
import numpy as np


@dataclasses.dataclass(frozen=True)
class ReduceOpSpec:
    """One reduction operator, described for every backend that needs it. No reference analog (TPU-native)."""

    name: str                       # SUM | MIN | MAX
    jnp_reduce: Callable            # full-array reduce (XLA baseline)
    jnp_combine: Callable           # elementwise combine (Pallas tree step)
    np_reduce: Callable             # host fallback oracle
    lax_collective: str             # psum | pmin | pmax (MPI_Op analog)
    monoid_identity: Callable       # dtype -> identity scalar (for padding)

    def identity(self, dtype) -> np.ndarray:
        """Padding identity for `dtype` — what ragged tails are filled
        with so padded lanes cannot perturb the result (the guard the
        reference's non-pow2 min/max kernels lacked,
        reduction_kernel.cu:140,157)."""
        return self.monoid_identity(np.dtype(dtype))


def _sum_identity(dt: np.dtype):
    return dt.type(0)


def _jnp_sum_same_dtype(x, **kw):
    """SUM that accumulates in the input dtype (no int32->int64 / implicit
    promotion under x64). Matching the device accumulator's width is what
    makes int verification exact-match (reduction.cpp:748,776-777): both
    sides wrap mod 2^32. Exception: sub-32-bit floats accumulate in f32 —
    the TPU-native convention (bf16 data stream, f32 accumulator);
    accumulating in bf16 would swamp beyond ~1e3 elements."""
    acc = accum_dtype(x.dtype)
    return jnp.sum(x, dtype=acc, **kw)


def accum_dtype(dtype):
    """Accumulator dtype for SUM: f32 for sub-32-bit floats, else the
    input dtype.

    No reference analog (TPU-native).
    """
    dt = jnp.dtype(dtype)
    if jnp.issubdtype(dt, jnp.floating) and dt.itemsize < 4:
        return jnp.float32
    return dt


def _min_identity(dt: np.dtype):
    # Padding value must be the monoid identity so padded lanes never win:
    # max representable for MIN, min representable for MAX. The reference
    # instead guards loads with bounds checks (and gets the guard wrong for
    # min/max — reduction_kernel.cu:157,221; see SURVEY.md §2.2 bugs).
    if np.issubdtype(dt, np.integer):
        return dt.type(np.iinfo(dt).max)
    return dt.type(np.inf)


def _max_identity(dt: np.dtype):
    if np.issubdtype(dt, np.integer):
        return dt.type(np.iinfo(dt).min)
    return dt.type(-np.inf)


OPS = {
    "SUM": ReduceOpSpec(
        name="SUM",
        jnp_reduce=_jnp_sum_same_dtype,
        jnp_combine=jnp.add,
        np_reduce=np.sum,
        lax_collective="psum",
        monoid_identity=_sum_identity,
    ),
    "MIN": ReduceOpSpec(
        name="MIN",
        jnp_reduce=jnp.min,
        jnp_combine=jnp.minimum,
        np_reduce=np.min,
        lax_collective="pmin",
        monoid_identity=_min_identity,
    ),
    "MAX": ReduceOpSpec(
        name="MAX",
        jnp_reduce=jnp.max,
        jnp_combine=jnp.maximum,
        np_reduce=np.max,
        lax_collective="pmax",
        monoid_identity=_max_identity,
    ),
}


# --------------------------------------------------------------------------
# The reduction family (ISSUE 20; docs/FAMILY.md; config.FAMILY_METHODS):
# SCAN, segmented reductions, argmin/argmax as ReduceOpSpec-compatible
# entries. The spec fields describe the COMBINE monoid each method's
# partials obey — SCAN carries combine like SUM (a prefix's continuation
# adds the running total), SEG* segments each follow their base op, and
# ARG* combine in the order-preserving key domain (ops/family/argreduce)
# where the extreme is a MIN/MAX — so chained timing (ops/chain.py needs
# name + jnp_combine), padding (identity) and the collective spelling
# all fall out of the same table the classic ops use. The family device
# entry points live in ops/family/; these specs are the registry's view.
# --------------------------------------------------------------------------

def _scan_np_reduce(x, **kw):
    # digest convention: a scan's scalar digest is its last prefix
    # element == the full SUM (docs/FAMILY.md)
    return np.sum(x, **kw)


FAMILY_OPS = {
    "SCAN": ReduceOpSpec(
        name="SCAN",
        jnp_reduce=_jnp_sum_same_dtype,
        jnp_combine=jnp.add,
        np_reduce=_scan_np_reduce,
        lax_collective="psum",
        monoid_identity=_sum_identity,
    ),
    "SEGSUM": ReduceOpSpec(
        name="SEGSUM",
        jnp_reduce=_jnp_sum_same_dtype,
        jnp_combine=jnp.add,
        np_reduce=np.sum,
        lax_collective="psum",
        monoid_identity=_sum_identity,
    ),
    "SEGMIN": ReduceOpSpec(
        name="SEGMIN",
        jnp_reduce=jnp.min,
        jnp_combine=jnp.minimum,
        np_reduce=np.min,
        lax_collective="pmin",
        monoid_identity=_min_identity,
    ),
    "SEGMAX": ReduceOpSpec(
        name="SEGMAX",
        jnp_reduce=jnp.max,
        jnp_combine=jnp.maximum,
        np_reduce=np.max,
        lax_collective="pmax",
        monoid_identity=_max_identity,
    ),
    "ARGMIN": ReduceOpSpec(
        name="ARGMIN",
        jnp_reduce=jnp.min,
        jnp_combine=jnp.minimum,
        np_reduce=np.min,
        lax_collective="pmin",
        monoid_identity=_min_identity,
    ),
    "ARGMAX": ReduceOpSpec(
        name="ARGMAX",
        jnp_reduce=jnp.max,
        jnp_combine=jnp.maximum,
        np_reduce=np.max,
        lax_collective="pmax",
        monoid_identity=_max_identity,
    ),
}


def get_op(name: str) -> ReduceOpSpec:
    """Lookup by the CLI spelling: the reference's --method flag values
    (SUM/MIN/MAX, reduction.cpp:84-204) plus the family methods
    (config.FAMILY_METHODS; docs/FAMILY.md)."""
    key = name.upper()
    if key in OPS:
        return OPS[key]
    if key in FAMILY_OPS:
        return FAMILY_OPS[key]
    raise ValueError(f"unknown reduction {name!r}; expected one of "
                     f"{list(OPS) + list(FAMILY_OPS)}")


def tolerance(method: str, dtype: str, n: int) -> float:
    """Verification tolerance, matching the reference's acceptance rule
    (reduction.cpp:750,763-765,776-779): ints exact; float32 1e-8*n;
    float64 1e-12. MIN/MAX are exact selections for every dtype — only
    SUM accumulates rounding error.
    """
    if dtype in ("int32", "int64"):
        return 0.0
    if method.upper() in ("MIN", "MAX", "SEGMIN", "SEGMAX",
                          "ARGMIN", "ARGMAX"):
        # exact selections — the family extremes inherit the MIN/MAX
        # rule, and arg indices are integers whatever the data dtype
        return 0.0
    if dtype == "float64":
        return 1e-12
    if dtype == "bfloat16":
        return 1e-2 * n   # bf16 extension: ~3 decimal digits of mantissa
    return 1e-8 * n
