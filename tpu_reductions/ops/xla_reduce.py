"""XLA-baseline reduction — the always-correct comparator (SURVEY.md §7 L2b).

`jnp.sum/min/max` under `jit` lowers to a single fused XLA reduce that the
compiler already tiles across HBM optimally; it plays the role the CPU
reference played for the CUDA kernel (a second, independent implementation
to validate the hand-written kernel against) while ALSO being a competitive
performance baseline on TPU. The Pallas kernel (pallas_reduce.py) must match
it bit-for-bit on ints and within registry.tolerance on floats.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from tpu_reductions.ops.registry import get_op


@functools.partial(jax.jit, static_argnames=("method",))
def xla_reduce(x: jax.Array, method: str = "SUM") -> jax.Array:
    """Reduce `x` to a scalar with XLA's native reduction.

    int32 SUM accumulates in int32 (wrapping), matching the reference's
    int accumulator semantics (reduction.cpp:748,776-777) — the oracle
    wraps identically, so int verification is exact-match.
    """
    return get_op(method).jnp_reduce(x)


def make_xla_reduce(method: str):
    """A jitted closure over the op, for benchmarking without re-passing
    statics (each (method, dtype, shape) gets its own executable — the
    template-instantiation fan-out analog, SURVEY.md §3.4)."""
    op = get_op(method)

    @jax.jit
    def fn(x):
        return op.jnp_reduce(x)

    return fn
