"""L2a: single-chip hierarchical Pallas reduction kernels.

TPU-native redesign of the reference's "kernel 6" CUDA reduction
(reference cuda/C/src/reduction/reduction_kernel.cu:74-253 and its host-side
multi-pass finishing loop, reduction.cpp:297-384). The mapping is
architectural, not line-by-line (SURVEY.md §7):

  CUDA mechanism (reference)                TPU mechanism (here)
  ----------------------------------------  --------------------------------
  grid-stride loop, 2 elems/thread/step     sequential Pallas grid; each
  (Brent's theorem, kernel.cu:88-98)        step DMAs a (TM,128) HBM tile
                                            into VMEM (pipelined by Pallas)
  shared-memory tree 512->64 with           VPU lane/sublane reduction of
  __syncthreads (kernel.cu:106-108)         the tile to an (8,128) vector
  warp-synchronous final 32->1 on           (8,128)->scalar finish — a tiny
  volatile smem (kernel.cu:110-122)         XLA reduce (or host finish)
  block partials + kernel relaunch          per-block partial rows +
  until <= cpuFinalThreshold                repeated Pallas passes
  (reduction.cpp:343-357)                   (two-pass kernel)
  --cpufinal host finishing                 fetch partials, finish with the
  (reduction.cpp:328-340)                   host oracle combine
  threads-per-block / maxBlocks knobs       TM tile rows / P partial rows
  (getNumBlocksAndThreads,                  (choose_tiling below)
  reduction.cpp:272-291)

There is no warp-synchronous hazard class on TPU (SURVEY.md §5 "race
detection") — the VPU is a lockstep vector unit and Pallas grids are
sequential per core — so the reference's volatile-smem subtlety dissolves;
correctness instead rests on monoid-identity padding (registry.py), which
also fixes the reference's non-pow2 min/max OOB bugs by construction
(reduction_kernel.cu:140,157,204,221 — see SURVEY.md §2.2).

Kernel ids (config.KERNEL_*):
  6  single-pass: per step, fold the tile to a sublane block and combine
     into one VMEM accumulator block revisited across the whole grid.
  7  two-pass: P partial rows (maxblocks analog), finished by further
     passes / XLA / host according to cpu_final / cpu_thresh.
  8  single-pass elementwise: combine the whole (TM,128) tile into a
     (TM,128) VMEM accumulator — no in-step fold at all (pure VPU
     elementwise, no sublane relayout); larger final finish. An
     extension beyond the reference's numbering, kept to let the
     benchmark race the two accumulation structures.
  9  MXU matmul SUM (float dtypes): ones-row matmul turns the tile fold
     into a systolic-array op (arXiv:1811.09736 / 2001.05585 technique,
     rebuilt TPU-native); MIN/MAX and int combos WAIVE.
  10 streaming accumulator: input stays in HBM; the kernel runs its own
     STREAM_BUFFERS-deep async-DMA pipeline (vs Mosaic's automatic
     depth-2 BlockSpec pipeline) and folds chunks elementwise — the
     HBM-regime candidate (docs/PERF_NOTES.md hypotheses).

float64: XLA-on-TPU emulates f64 but Mosaic/Pallas does not support it;
pallas_reduce transparently uses a double-double (two-float32) kernel for
f64 SUM fidelity — see dd_reduce.py — or falls back to XLA (see
`f64_strategy`).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

if not hasattr(pltpu, "CompilerParams"):
    # pre-0.4.38 jax spells it TPUCompilerParams; same constructor
    pltpu.CompilerParams = pltpu.TPUCompilerParams

from tpu_reductions.ops.registry import ReduceOpSpec, get_op

LANES = 128      # TPU vector lane count (last-dim tile), pallas_guide.md
SUBLANES = 8     # 32-bit sublane tile (f32/i32)


def sublanes_for(dtype) -> int:
    """Minimum sublane count by element width (pallas_guide.md tiling
    table): 8 for 32-bit, 16 for bf16/f16, 32 for 8-bit. 64-bit types only
    exist on the interpret path (CPU hosts), where 8 is fine.

    No reference analog (TPU-native).
    """
    return {8: 8, 4: 8, 2: 16, 1: 32}[np.dtype(dtype).itemsize]


def _interpret_default() -> bool:
    """Pallas TPU lowering only runs on TPU; everywhere else (the CPU test
    mesh, SURVEY.md §4) use interpreter mode."""
    return jax.default_backend() != "tpu"


def choose_tiling(n: int, threads: int = 256, max_blocks: int = 64,
                  dtype="float32") -> tuple[int, int, int]:
    """Pick (TM tile rows, P partial blocks, T tiles per block) for `n`
    elements — the getNumBlocksAndThreads analog (reduction.cpp:272-291):
    threads -> tile rows per grid step, maxBlocks -> grid clamp with
    per-block striding over multiple tiles.

    Returns (tm, p, t) with p * t * tm * LANES >= n; tm is aligned to the
    dtype's minimum sublane tile.
    """
    sub = sublanes_for(dtype) if np.dtype(dtype).itemsize < 4 else SUBLANES
    rows = pl.cdiv(n, LANES)
    tm = max(sub, min(int(threads), 2048))
    tm -= tm % sub
    num_tiles = pl.cdiv(rows, tm)
    p = max(1, min(int(max_blocks), num_tiles))
    t = pl.cdiv(num_tiles, p)
    return tm, p, t


def padded_2d_shape(n: int, tm: int, p: int, t: int) -> tuple[int, int]:
    """(rows, LANES) device layout for n elements under the (tm, p, t)
    tiling — the grid-shape arithmetic of the CUDA launch config
    (reduction.cpp:665-668), relaid for the (sublane, lane) VPU tile."""
    return (p * t * tm, LANES)


def stage_padded(x: np.ndarray | jax.Array, tm: int, p: int, t: int,
                 op: ReduceOpSpec):
    """Pad a flat array to (P*T*TM, LANES) with the op's monoid identity and
    reshape — done once at data-staging time, outside the timed loop (the
    reference similarly fixes pow2/block geometry before timing).

    Multi-GiB host payloads stage through bounded per-message transfers
    (utils/staging.py — single bulk messages at 4 GiB killed the tunnel
    relay in both round-2 live windows); the result is identical.

    No reference analog (TPU-native).
    """
    if isinstance(x, np.ndarray):
        from tpu_reductions.utils.staging import maybe_chunked_stage
        flat = np.ravel(x)
        rows, lanes = padded_2d_shape(flat.size, tm, p, t)
        staged = maybe_chunked_stage(flat, rows, lanes,
                                     op.identity(flat.dtype))
        if staged is not None:
            return staged
    # redlint: disable=RED015 -- reached only when maybe_chunked_stage above judged the payload under the staging threshold (or x is already on device)
    x = jnp.ravel(jnp.asarray(x))
    rows, lanes = padded_2d_shape(x.size, tm, p, t)
    pad = rows * lanes - x.size
    ident = op.identity(x.dtype)
    x = jnp.pad(x, (0, pad), constant_values=ident)
    return x.reshape(rows, lanes)


# ---------------------------------------------------------------------------
# Kernels
# ---------------------------------------------------------------------------


MXU_ACC_ROWS = 8    # f32 sublane tile: the kernel-9 accumulator height


def _acc_dtype(in_dtype, op: ReduceOpSpec):
    """Accumulator dtype inside the kernel: f32 for bf16 SUM (bf16 stays
    in HBM at 2 B/element — the bandwidth win — but accumulates at f32 in
    VMEM, the TPU-native convention); input dtype otherwise."""
    if op.name == "SUM":
        from tpu_reductions.ops.registry import accum_dtype
        return accum_dtype(in_dtype)
    return jnp.dtype(in_dtype)


def _tile_to_sublane(tile: jax.Array, op: ReduceOpSpec, tm: int) -> jax.Array:
    """(TM, 128) -> (sublane_tile, 128): the shared-memory tree analog,
    done as a sublane-group reduction on the VPU."""
    sub = sublanes_for(tile.dtype)
    acc = _acc_dtype(tile.dtype, op)
    if tm == sub:
        return tile.astype(acc)
    t3 = tile.reshape(tm // sub, sub, LANES)
    if op.name == "SUM":
        return jnp.sum(t3, axis=0, dtype=acc)
    if op.name == "MIN":
        return jnp.min(t3, axis=0)
    return jnp.max(t3, axis=0)


def _accumulator_kernel(op: ReduceOpSpec, transform):
    """Shared single-pass structure: every grid step applies `transform`
    to its tile and combines it into one resident VMEM accumulator block
    (same out index every step — the grid-stride accumulate). Kernel 6
    folds the tile to a sublane block first; kernel 8's transform is just
    the accumulator-dtype cast."""

    def kernel(in_ref, acc_ref):
        step = pl.program_id(0)
        part = transform(in_ref[:], acc_ref.dtype)

        @pl.when(step == 0)
        def _():
            acc_ref[:] = part

        @pl.when(step > 0)
        def _():
            acc_ref[:] = op.jnp_combine(acc_ref[:], part)

    return kernel


def _accumulator_call(x2d: jax.Array, op: ReduceOpSpec, tm: int,
                      transform, acc_rows: int,
                      interpret: Optional[bool]) -> jax.Array:
    rows = x2d.shape[0]
    interpret = _interpret_default() if interpret is None else interpret
    return pl.pallas_call(
        _accumulator_kernel(op, transform),
        out_shape=jax.ShapeDtypeStruct((acc_rows, LANES),
                                       _acc_dtype(x2d.dtype, op)),
        grid=(rows // tm,),
        in_specs=[pl.BlockSpec((tm, LANES), lambda i: (i, 0),
                               memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec((acc_rows, LANES), lambda i: (0, 0),
                               memory_space=pltpu.VMEM),
        # every step revisits the one accumulator block: the grid is
        # inherently sequential — declare it so Mosaic never tries to
        # split it across cores
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("arbitrary",)),
        interpret=interpret,
    )(x2d)


def elementwise_call(x2d: jax.Array, op: ReduceOpSpec, tm: int,
                     interpret: Optional[bool] = None) -> jax.Array:
    """Kernel 8: whole-tile elementwise combine into a (TM,128) resident
    accumulator — maximal VPU regularity, zero relayout per step.
    Returns the (TM, 128) accumulator.

    No reference analog (TPU-native).
    """
    return _accumulator_call(x2d, op, tm,
                             lambda tile, acc_dt: tile.astype(acc_dt),
                             acc_rows=tm, interpret=interpret)


def mxu_call(x2d: jax.Array, op: ReduceOpSpec, tm: int,
             interpret: Optional[bool] = None) -> jax.Array:
    """Kernel 9: SUM on the MXU (float dtypes only). Each grid step
    reduces its (TM, 128) tile to per-lane column sums with a ones-row
    matmul — sum(tile, axis=0) == onehot_row0(8, TM) @ tile — so the
    adds ride the systolic array instead of the VPU (the tensor-core
    reduction technique of arXiv:1811.09736 / arXiv:2001.05585, re-done
    TPU-native). The (8, 128) resident accumulator's row 0 carries the
    running column sums; rows 1-7 stay zero (the lhs is zero there), so
    the standard whole-block `finish` is exact.

    MIN/MAX have no matmul form and integer matmul is not exact on the
    MXU — the driver WAIVEs those combos (the reference's incapable-
    hardware gate, reduction.cpp:148-155)."""
    if op.name != "SUM":
        raise ValueError("kernel 9 (MXU) implements SUM only")
    if not jnp.issubdtype(x2d.dtype, jnp.floating):
        raise ValueError("kernel 9 (MXU) needs a float dtype; integer "
                         "matmul is not exact on the MXU")

    def transform(tile, acc_dt):
        # one-hot row 0: row 0 of the product = column sums, rows 1-7
        # exactly zero. f32 operands at HIGHEST precision: on TPU the
        # dot still lowers to the MXU (bf16x3 passes), on the CPU
        # interpret path it is a plain exact f32 matmul.
        lhs = (jax.lax.broadcasted_iota(
            jnp.int32, (MXU_ACC_ROWS, tile.shape[0]), 0) == 0
        ).astype(acc_dt)
        return jax.lax.dot_general(
            lhs, tile.astype(acc_dt),
            dimension_numbers=(((1,), (0,)), ((), ())),
            precision=jax.lax.Precision.HIGHEST,
            preferred_element_type=acc_dt)

    return _accumulator_call(x2d, op, tm, transform,
                             acc_rows=MXU_ACC_ROWS, interpret=interpret)


STREAM_BUFFERS = 4   # kernel-10 DMA pipeline depth (Mosaic's automatic
                     # BlockSpec pipeline is depth 2; deeper lookahead
                     # is the one streaming knob it does not expose)


def _stream_kernel(op: ReduceOpSpec, tm: int, n_buffers: int,
                   num_chunks: int):
    """Kernel 10: hand-rolled DMA pipeline. The input stays in HBM
    (memory_space=ANY); the kernel runs its own `n_buffers`-deep
    async-copy pipeline — start the DMA for chunk i+depth-1, wait on
    chunk i, fold it elementwise into a resident (TM, 128) accumulator.

    Same grid-stride-accumulate semantics as kernels 6/8
    (reduction_kernel.cu:88-98), but the HBM->VMEM traffic is scheduled
    explicitly instead of by Mosaic's automatic double-buffered
    BlockSpec pipeline: at HBM-bound sizes the only thing that matters
    is keeping the DMA engine saturated, and a deeper pipeline rides
    out per-chunk scheduling jitter the depth-2 auto-pipeline cannot
    (the docs/PERF_NOTES.md hypothesis that k6 gives up 5-8% to XLA in
    the HBM regime for exactly this reason)."""

    def kernel(x_hbm_ref, acc_ref):
        def body(scratch, sems):
            def dma(slot, idx):
                return pltpu.make_async_copy(
                    x_hbm_ref.at[pl.ds(idx * tm, tm)],
                    scratch.at[slot], sems.at[slot])

            # warm-up: fill the lookahead window (static bounds —
            # unrolled at trace time)
            for s in range(min(n_buffers - 1, num_chunks)):
                dma(s, s).start()

            acc_ref[:] = jnp.full_like(
                acc_ref, op.identity(acc_ref.dtype))

            def loop_body(i, _):
                slot = i % n_buffers

                @pl.when(i + n_buffers - 1 < num_chunks)
                def _():
                    dma((i + n_buffers - 1) % n_buffers,
                        i + n_buffers - 1).start()

                dma(slot, i).wait()
                acc_ref[:] = op.jnp_combine(
                    acc_ref[:], scratch[slot].astype(acc_ref.dtype))
                return 0

            jax.lax.fori_loop(0, num_chunks, loop_body, 0)

        pl.run_scoped(
            body,
            scratch=pltpu.VMEM((n_buffers, tm, LANES),
                               x_hbm_ref.dtype),
            sems=pltpu.SemaphoreType.DMA((n_buffers,)))

    return kernel


def stream_call(x2d: jax.Array, op: ReduceOpSpec, tm: int,
                interpret: Optional[bool] = None,
                n_buffers: int = STREAM_BUFFERS) -> jax.Array:
    """Kernel 10 entry: the grid-stride accumulate
    (reduction_kernel.cu:88-98) with an explicit deep DMA pipeline
    (_stream_kernel). Returns the (TM, 128) accumulator (the standard
    `finish` folds it, exactly as for kernel 8)."""
    rows = x2d.shape[0]
    if rows % tm:
        # staged inputs (stage_padded: p*t*tm rows) are always aligned;
        # anything else would silently drop the ragged tail from the
        # chunk count below — refuse instead of reducing wrongly
        raise ValueError(f"stream_call needs rows % tm == 0, got "
                         f"{rows} rows with tm={tm}")
    interpret = _interpret_default() if interpret is None else interpret
    num_chunks = rows // tm
    return pl.pallas_call(
        _stream_kernel(op, tm, n_buffers, num_chunks),
        out_shape=jax.ShapeDtypeStruct((tm, LANES),
                                       _acc_dtype(x2d.dtype, op)),
        in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        interpret=interpret,
    )(x2d)


def _two_pass_kernel(op: ReduceOpSpec, tm: int):
    """Kernel 7: grid (P, T); block i accumulates T tiles into partial
    sublane block i — the numBlocks-partials structure (reduction.cpp:323
    producing blocks partials), with the maxblocks clamp expressed as
    per-block striding.

    Each partial is a full (sublane, 128) block, not a single row: TPU
    lowering requires output blocks whose second-to-last dim is a multiple
    of the sublane tile (pallas_guide.md tiling table), so a (1, 128)
    partial row — the literal numBlocks analog — cannot be lowered."""

    def kernel(in_ref, out_ref):
        j = pl.program_id(1)
        part = _tile_to_sublane(in_ref[:], op, tm)

        @pl.when(j == 0)
        def _():
            out_ref[:] = part

        @pl.when(j > 0)
        def _():
            out_ref[:] = op.jnp_combine(out_ref[:], part)

    return kernel


def single_pass_call(x2d: jax.Array, op: ReduceOpSpec, tm: int,
                     interpret: Optional[bool] = None) -> jax.Array:
    """Kernel 6: per step, fold the tile to its sublane block, then
    combine into the resident accumulator. Returns the (sublane_tile, 128)
    accumulator.

    No reference analog (TPU-native).
    """
    return _accumulator_call(
        x2d, op, tm,
        lambda tile, _acc_dt: _tile_to_sublane(tile, op, tm),
        acc_rows=sublanes_for(x2d.dtype), interpret=interpret)


def two_pass_call(x2d: jax.Array, op: ReduceOpSpec, tm: int, p: int, t: int,
                  interpret: Optional[bool] = None) -> jax.Array:
    """Run the partials kernel over a staged (P*T*TM, 128) array.
    Returns (P*sublane, 128) partials — sublane block i is block i's
    partial (see _two_pass_kernel on why a block, not a row).

    No reference analog (TPU-native).
    """
    interpret = _interpret_default() if interpret is None else interpret
    sub = sublanes_for(x2d.dtype)
    return pl.pallas_call(
        _two_pass_kernel(op, tm),
        out_shape=jax.ShapeDtypeStruct((p * sub, LANES),
                                       _acc_dtype(x2d.dtype, op)),
        grid=(p, t),
        in_specs=[pl.BlockSpec((tm, LANES), lambda i, j: (i * t + j, 0),
                               memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec((sub, LANES), lambda i, j: (i, 0),
                               memory_space=pltpu.VMEM),
        # block i owns partial block i exclusively: the P axis is
        # embarrassingly parallel (Mosaic may split it across cores on
        # multi-core TPUs — the numBlocks concurrency the CUDA grid had);
        # the T axis revisits block i's accumulator, so it stays serial
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "arbitrary")),
        interpret=interpret,
    )(x2d)


def _multipass_finish(partials: jax.Array, op: ReduceOpSpec, threads: int,
                      max_blocks: int, cpu_thresh: int,
                      interpret: Optional[bool]) -> jax.Array:
    """Multi-pass finishing: keep relaunching the two-pass kernel on the
    partials while more than cpu_thresh rows remain and a further pass is
    worthwhile (reduction.cpp:343-357). Sizes are static, so this Python
    loop unrolls at trace time into a fixed pass chain.

    Two termination guards:
      * floor — the partials' OWN sublane tile (16 rows for bf16 min/max,
        8 for 32-bit); one block is as small as a pass can get, so
        comparing against the 32-bit constant would spin forever on bf16;
      * halving clamp — a pass emits p2 * sublane rows; clamp p2 so each
        pass at least halves the partials. Without this, tm == sublane
        tile with max_blocks >= num_tiles maps every tile to its own
        partial block — zero shrinkage, and this trace-time loop never
        terminates (the reference's relaunch loop halves by construction).
    """
    while (partials.shape[0] > max(cpu_thresh, 1)
           and partials.shape[0] > sublanes_for(partials.dtype)):
        sub2 = sublanes_for(partials.dtype)
        mb2 = max(1, min(max_blocks, partials.shape[0] // (2 * sub2)))
        tm2, p2, t2 = choose_tiling(partials.size, threads, mb2,
                                    partials.dtype)
        x2 = stage_padded(partials, tm2, p2, t2, op)
        partials = two_pass_call(x2, op, tm2, p2, t2, interpret=interpret)
    return partials


# ---------------------------------------------------------------------------
# Public entry points
# ---------------------------------------------------------------------------


def finish(partials: jax.Array, op: ReduceOpSpec) -> jax.Array:
    """Final (small) reduction of an accumulator/partials block to a scalar
    — the warp-final analog. The block is at most a few KB, so a plain XLA
    reduce is the right tool (fused, on-chip).

    No reference analog (TPU-native).
    """
    return op.jnp_reduce(partials)


def host_finish(partials: jax.Array, op: ReduceOpSpec) -> np.ndarray:
    """--cpufinal analog (reduction.cpp:328-340): fetch partials and finish
    with the host combine. Uses the correct op (the reference's min/max
    host-finish wrongly used `+=` — reduction.cpp:426-429,516-521)."""
    return op.np_reduce(np.asarray(jax.device_get(partials)))


def f64_strategy() -> str:
    """How f64 is handled by the Pallas path on this backend:
    'native' (interpret / CPU), 'dd' (double-double kernel on TPU), or
    'xla' fallback. SURVEY.md §7 "hard parts"."""
    return "native" if jax.default_backend() != "tpu" else "dd"


# one pallas_call per reduce, dispatched by kernel id; kernel 7 (the
# multi-pass partials chain) is the only structure outside this map.
# Membership here IS the "is it a single-invocation kernel" question —
# one registry for both entry points (pallas_reduce/_make_staged_parts)
SINGLE_INVOCATION_CALLS = {6: single_pass_call,
                           8: elementwise_call,
                           9: mxu_call,
                           10: stream_call}


def _single_invocation_call(kernel: int, stream_buffers: int):
    """Registry lookup with the kernel-10 depth knob bound — the ONE
    place the knob meets the dispatch, shared by both entry points so
    they can never diverge on depth."""
    call = SINGLE_INVOCATION_CALLS[kernel]
    if kernel == 10:
        import functools
        call = functools.partial(call, n_buffers=stream_buffers)
    return call


def pallas_reduce(x: jax.Array, method: str, *, threads: int = 256,
                  max_blocks: int = 64, kernel: int = 6,
                  cpu_final: bool = False, cpu_thresh: int = 1,
                  stream_buffers: int = STREAM_BUFFERS,
                  interpret: Optional[bool] = None):
    """Reduce a flat array to a scalar with the Pallas kernels.

    Self-contained (pads/stages internally) — use `stage_padded` +
    `make_staged_reduce` to keep staging out of a timed loop.
    `cpu_final`/`cpu_thresh` mirror reduction.cpp:328-357: extra Pallas
    passes run while more than `cpu_thresh` partial rows remain, then the
    remainder is finished on host (cpu_final) or by XLA.
    """
    op = get_op(method)
    # Inspect the dtype BEFORE any jnp conversion: on TPU x64 is never
    # enabled, so jnp.ravel would silently downcast an f64 payload to f32
    # and lose the double-double route.
    if str(np.asarray(x).dtype if not isinstance(x, jax.Array) else x.dtype
           ) == "float64" and jax.default_backend() == "tpu":
        # No f64 on the TPU device at all — route through the
        # double-double path (host split -> f32 kernel -> host finish).
        from tpu_reductions.ops.dd_reduce import dd_pallas_reduce_f64
        return dd_pallas_reduce_f64(x, method, threads=threads,
                                    max_blocks=max_blocks)
    x = jnp.ravel(x)

    tm, p, t = choose_tiling(x.size, threads, max_blocks, x.dtype)
    x2d = stage_padded(x, tm, p, t, op)

    if kernel in SINGLE_INVOCATION_CALLS:
        acc = _single_invocation_call(kernel, stream_buffers)(
            x2d, op, tm, interpret=interpret)
        if cpu_final:
            return host_finish(acc, op)
        return finish(acc, op)

    if kernel == 7:
        partials = two_pass_call(x2d, op, tm, p, t, interpret=interpret)
        partials = _multipass_finish(partials, op, threads, max_blocks,
                                     cpu_thresh, interpret)
        if cpu_final:
            return host_finish(partials, op)
        return finish(partials, op)

    raise ValueError(f"kernel {kernel} is not live; only 6-10 "
                     "(0-5 are WAIVED, mirroring reduction_kernel.cu:278-289)")


def _make_staged_parts(method: str, n: int, dtype, *, threads: int = 256,
                       max_blocks: int = 64, kernel: int = 6,
                       cpu_thresh: int = 1,
                       stream_buffers: int = STREAM_BUFFERS,
                       interpret: Optional[bool] = None):
    """(op, stage_fn, device_fn): the staging closure and the un-jitted
    device-only partials function shared by make_staged_reduce (which
    adds the finish) and make_staged_core (which must stay chainable —
    ops/chain.py traces it inside a fori_loop)."""
    op = get_op(method)
    tm, p, t = choose_tiling(n, threads, max_blocks, dtype)

    def stage_fn(x):
        return stage_padded(x, tm, p, t, op)

    if kernel in SINGLE_INVOCATION_CALLS:
        call = _single_invocation_call(kernel, stream_buffers)

        def device_fn(x2d):
            return call(x2d, op, tm, interpret=interpret)
    else:
        def device_fn(x2d):
            partials = two_pass_call(x2d, op, tm, p, t, interpret=interpret)
            return _multipass_finish(partials, op, threads, max_blocks,
                                     cpu_thresh, interpret)

    return op, stage_fn, device_fn


def make_staged_reduce(method: str, n: int, dtype, *, threads: int = 256,
                       max_blocks: int = 64, kernel: int = 6,
                       cpu_final: bool = False, cpu_thresh: int = 1,
                       stream_buffers: int = STREAM_BUFFERS,
                       interpret: Optional[bool] = None):
    """Build (stage_fn, reduce_fn) for benchmarking: `stage_fn` pads/
    reshapes host data once (outside the timed loop); `reduce_fn` takes
    the staged (R,128) array and returns the scalar.

    cpu_final/cpu_thresh mirror the reference's finishing knobs
    (reduction.cpp:328-357): kernel 7 chains extra Pallas passes while
    more than cpu_thresh partial rows remain; cpu_final fetches the
    remaining partials and finishes them on host inside the timed region
    (as --cpufinal does)."""
    op, stage_fn, device_fn = _make_staged_parts(
        method, n, dtype, threads=threads, max_blocks=max_blocks,
        kernel=kernel, cpu_thresh=cpu_thresh,
        stream_buffers=stream_buffers, interpret=interpret)

    if cpu_final:
        jit_device = jax.jit(device_fn)

        def reduce_fn(x2d):
            return host_finish(jit_device(x2d), op)
    else:
        reduce_fn = jax.jit(lambda x2d: finish(device_fn(x2d), op))

    return stage_fn, reduce_fn


def make_staged_core(method: str, n: int, dtype, *, threads: int = 256,
                     max_blocks: int = 64, kernel: int = 6,
                     cpu_thresh: int = 1,
                     stream_buffers: int = STREAM_BUFFERS,
                     interpret: Optional[bool] = None):
    """Build (op, stage_fn, core) with `core(x2d) -> scalar` entirely
    on-device (no host finish) — the chainable form consumed by
    ops/chain.make_chained_reduce for honest slope timing.

    No reference analog (TPU-native).
    """
    op, stage_fn, device_fn = _make_staged_parts(
        method, n, dtype, threads=threads, max_blocks=max_blocks,
        kernel=kernel, cpu_thresh=cpu_thresh,
        stream_buffers=stream_buffers, interpret=interpret)

    def core(x2d):
        return finish(device_fn(x2d), op)

    return op, stage_fn, core
