"""Host oracle: the CPU reference every accelerator result is checked against.

The reference verifies every run against a host reduction computed on the
same data — Kahan-compensated sum for reals (reduction.cpp:214-227), linear
scans for min/max (reduction.cpp:228-249) — with exact matching for ints and
scaled tolerances for floats (reduction.cpp:750,763-765,776-779). That
self-verifying-benchmark pattern is the whole test strategy (SURVEY.md §4).

Two backends:
- native: csrc/oracle.cpp via ctypes (true Kahan at C speed) — the
  framework's native runtime component, auto-built with g++ on first use.
- numpy fallback: math.fsum (exactly-rounded) for f64, float64-accumulated
  np.sum for f32, np.min/max scans — used when the toolchain is missing.
"""

from __future__ import annotations

import ctypes
import math
import os
import subprocess
from pathlib import Path
from typing import Optional

import numpy as np

from tpu_reductions.ops.registry import get_op, tolerance

_CSRC = Path(__file__).resolve().parents[2] / "csrc"
_LIB_PATH = _CSRC / "liboracle.so"
_lib: Optional[ctypes.CDLL] = None
_lib_tried = False


def _build_native() -> bool:
    try:
        subprocess.run(["make", "-C", str(_CSRC)], check=True,
                       capture_output=True, timeout=120)
        return _LIB_PATH.exists()
    except Exception:
        return False


def _load() -> Optional[ctypes.CDLL]:
    """Load (building if needed) the native oracle; None on any failure."""
    global _lib, _lib_tried
    if _lib is not None or _lib_tried:
        return _lib
    _lib_tried = True
    if os.environ.get("TPU_REDUCTIONS_NO_NATIVE"):
        return None
    if not _LIB_PATH.exists() and not _build_native():
        return None
    try:
        lib = ctypes.CDLL(str(_LIB_PATH))
    except OSError:
        return None
    i64 = ctypes.c_int64
    u32 = ctypes.c_uint32
    f32p = np.ctypeslib.ndpointer(np.float32, flags="C_CONTIGUOUS")
    f64p = np.ctypeslib.ndpointer(np.float64, flags="C_CONTIGUOUS")
    i32p = np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS")
    sigs = {
        "oracle_kahan_sum_f32": (ctypes.c_double, [f32p, i64]),
        "oracle_kahan_sum_f64": (ctypes.c_double, [f64p, i64]),
        "oracle_kahan_sum_f32_mt": (ctypes.c_double,
                                    [f32p, i64, ctypes.c_int]),
        "oracle_kahan_sum_f64_mt": (ctypes.c_double,
                                    [f64p, i64, ctypes.c_int]),
        "oracle_hw_threads": (ctypes.c_int, []),
        "oracle_sum_i32": (ctypes.c_int32, [i32p, i64]),
        "oracle_min_i32": (ctypes.c_int32, [i32p, i64]),
        "oracle_max_i32": (ctypes.c_int32, [i32p, i64]),
        "oracle_min_f32": (ctypes.c_float, [f32p, i64]),
        "oracle_max_f32": (ctypes.c_float, [f32p, i64]),
        "oracle_min_f64": (ctypes.c_double, [f64p, i64]),
        "oracle_max_f64": (ctypes.c_double, [f64p, i64]),
        "oracle_fill_i32": (None, [i32p, i64, u32, u32]),
        "oracle_fill_f32": (None, [f32p, i64, u32, u32]),
        "oracle_fill_f64": (None, [f64p, i64, u32, u32]),
        "oracle_now_ns": (i64, []),
    }
    try:
        for name, (res, args) in sigs.items():
            fn = getattr(lib, name)
            fn.restype = res
            fn.argtypes = args
    except AttributeError:
        return None
    _lib = lib
    return _lib


def native_available() -> bool:
    """Whether the C oracle library built (csrc/, auto-compiled on
    first use) — the host-reference availability check the reference
    never needed (its CPU oracle was inline, reduction.cpp:748-780)."""
    return _load() is not None


_SUFFIX = {"int32": "i32", "float32": "f32", "float64": "f64"}


def host_reduce(x: np.ndarray, method: str) -> np.ndarray:
    """Compute the oracle reduction of `x` on the host.

    Kahan sum for reals (reduction.cpp:214-227), linear scans for
    min/max (reduction.cpp:228-249). SUM of reals returns float64
    regardless of input dtype (the Kahan accumulator's precision); SUM
    of int32 wraps mod 2^32 to match the device's int32 accumulator;
    MIN/MAX return the input dtype.
    """
    method = method.upper()
    x = np.ascontiguousarray(x)
    dtype = str(x.dtype)
    lib = _load()

    if method == "SUM":
        if dtype == "int32":
            if lib is not None:
                return np.int32(lib.oracle_sum_i32(x, x.size))
            # int64 exact sum, then wrap to int32 — same result as a
            # wrapping int32 accumulator.
            return np.int64(x.sum(dtype=np.int64)).astype(np.int32)
        # threaded Kahan for large payloads (cutil-multithreading analog,
        # actually used): identical result class, ~cores x faster
        mt_threshold = 1 << 22
        if dtype == "float32":
            if lib is not None:
                if x.size >= mt_threshold:
                    return np.float64(lib.oracle_kahan_sum_f32_mt(
                        x, x.size, min(8, lib.oracle_hw_threads())))
                return np.float64(lib.oracle_kahan_sum_f32(x, x.size))
            return np.float64(x.sum(dtype=np.float64))
        if dtype == "float64":
            if lib is not None:
                if x.size >= mt_threshold:
                    return np.float64(lib.oracle_kahan_sum_f64_mt(
                        x, x.size, min(8, lib.oracle_hw_threads())))
                return np.float64(lib.oracle_kahan_sum_f64(x, x.size))
            return np.float64(math.fsum(x.tolist()) if x.size < (1 << 22)
                              else x.sum(dtype=np.float64))
        # bf16 etc: accumulate in f64
        return np.float64(x.astype(np.float64).sum())

    if method in ("MIN", "MAX"):
        if lib is not None and dtype in _SUFFIX:
            fn = getattr(lib, f"oracle_{method.lower()}_{_SUFFIX[dtype]}")
            return x.dtype.type(fn(x, x.size))
        return get_op(method).np_reduce(x)

    if method == "SCAN":
        # a scan's scalar digest is its last prefix element == the full
        # SUM (docs/FAMILY.md); the full-prefix oracle is
        # ops/family/scan.host_scan
        return host_reduce(x, "SUM")

    if method in ("ARGMIN", "ARGMAX"):
        from tpu_reductions.ops.family.argreduce import host_arg_reduce
        return host_arg_reduce(x, method)

    raise ValueError(f"unknown method {method!r}")


def native_fill(n: int, dtype: str, rank: int = 0, seed: int = 0
                ) -> Optional[np.ndarray]:
    """Generate a payload with the native MT19937 filler; None if the
    native library is unavailable (callers fall back to utils.rng).

    No reference analog (TPU-native).
    """
    lib = _load()
    if lib is None or dtype not in _SUFFIX:
        return None
    out = np.empty(n, dtype=dtype)
    getattr(lib, f"oracle_fill_{_SUFFIX[dtype]}")(out, n, rank, seed)
    return out


class IncrementalOracle:
    """Chunk-wise host oracle for streamed reductions (ops/stream.py):
    the same acceptance reference as `host_reduce` (Kahan sum for
    reals, reduction.cpp:214-227; linear scans for min/max,
    reduction.cpp:228-249), fed one bounded chunk at a time so a
    multi-TB streamed payload never needs a second host-resident copy
    to verify against.

    Per chunk, `update` runs the one-shot oracle (native Kahan at C
    speed when built) and combines its result into the running state:
    int32 SUM wraps mod 2^32 exactly like the device accumulator;
    float SUM carries a Kahan-compensated (total, comp) pair across
    chunk boundaries so the cross-chunk combine adds no error class the
    one-shot oracle doesn't have; MIN/MAX keep the running extreme
    (exact). `state()`/`from_state()` round-trip through JSON — the
    resume checkpoint carries the oracle alongside the device partial
    (bench/stream.py), so a resumed stream verifies without re-reading
    chunks it already consumed. Parity with the one-shot oracle, chunk
    boundaries included, is proven in tests/test_stream.py.
    """

    def __init__(self, method: str, dtype: str) -> None:
        self.method = method.upper()
        if self.method not in ("SUM", "MIN", "MAX", "SCAN",
                               "ARGMIN", "ARGMAX"):
            raise ValueError(f"unknown method {method!r}")
        self.dtype = str(dtype)
        self.count = 0
        self._int_total = 0          # int32 SUM: wrapped running total
        self._sum = 0.0              # float SUM: Kahan pair
        self._comp = 0.0
        self._extreme: Optional[float] = None   # MIN/MAX running value
        # ARGMIN/ARGMAX: global index of the running extreme — `count`
        # at each update is the chunk's global offset, so indices stay
        # global across chunk boundaries; a tie keeps the OLD index
        # (earlier chunk == lower index, docs/FAMILY.md tie rule)
        self._extreme_idx: Optional[int] = None

    def update(self, chunk: np.ndarray) -> None:
        """Fold one host chunk into the running oracle state (module
        class docstring has the per-class combine rules).

        No reference analog (TPU-native).
        """
        if chunk.size == 0:
            return
        chunk = np.asarray(chunk)
        if self.method in ("ARGMIN", "ARGMAX"):
            li = int(np.argmin(chunk) if self.method == "ARGMIN"
                     else np.argmax(chunk))
            v = float(chunk[li])
            better = (self._extreme is None
                      or (v < self._extreme if self.method == "ARGMIN"
                          else v > self._extreme))
            if better:    # strict: a tie keeps the earlier (lower) index
                self._extreme = v
                self._extreme_idx = self.count + li
            self.count += int(chunk.size)
            return
        h = host_reduce(chunk, self.method)
        self.count += int(chunk.size)
        if self.method in ("SUM", "SCAN"):
            if self.dtype == "int32":
                # both addends already wrap mod 2^32; their wrapped sum
                # equals the one-shot wrapped total (associativity of
                # modular addition — reduction.cpp:748,776-777)
                self._int_total = int(np.int64(self._int_total)
                                      + np.int64(np.int32(h))
                                      & np.int64(0xFFFFFFFF))
            else:
                # Knuth two-sum across the chunk boundary: the chunk's
                # Kahan total joins a Kahan-compensated running pair
                y = float(h) - self._comp
                t = self._sum + y
                self._comp = (t - self._sum) - y
                self._sum = t
        else:
            v = float(h)
            if self._extreme is None:
                self._extreme = v
            elif self.method == "MIN":
                self._extreme = min(self._extreme, v)
            else:
                self._extreme = max(self._extreme, v)

    def value(self):
        """The oracle value so far, in host_reduce's result conventions
        (int32 SUM -> np.int32; real SUM -> np.float64; MIN/MAX -> the
        input dtype) — reduction.cpp:748-780's comparison operand.

        No reference analog (TPU-native).
        """
        if self.method in ("SUM", "SCAN"):
            if self.dtype == "int32":
                return np.int64(self._int_total).astype(np.int32)[()]
            return np.float64(self._sum)
        if self.method in ("ARGMIN", "ARGMAX"):
            if self._extreme_idx is None:
                raise ValueError("oracle saw no data")
            return np.int64(self._extreme_idx)
        if self._extreme is None:
            raise ValueError("oracle saw no data")
        return np.dtype(self.dtype).type(self._extreme)

    def state(self) -> dict:
        """JSON-able snapshot for the stream resume checkpoint
        (bench/resume rows). No reference analog (TPU-native)."""
        return {"method": self.method, "dtype": self.dtype,
                "count": self.count, "int_total": self._int_total,
                "sum": self._sum, "comp": self._comp,
                "extreme": self._extreme,
                "extreme_idx": self._extreme_idx}

    @classmethod
    def from_state(cls, state: dict) -> "IncrementalOracle":
        """Rebuild the oracle a prior (interrupted) stream persisted.
        No reference analog (TPU-native)."""
        o = cls(state["method"], state["dtype"])
        o.count = int(state.get("count", 0))
        o._int_total = int(state.get("int_total", 0))
        o._sum = float(state.get("sum", 0.0))
        o._comp = float(state.get("comp", 0.0))
        o._extreme = state.get("extreme")
        idx = state.get("extreme_idx")
        o._extreme_idx = None if idx is None else int(idx)
        return o


def verify(device_result, host_result, method: str, dtype: str, n: int
           ) -> tuple[bool, float]:
    """Acceptance check, mirroring reduction.cpp:750-780.

    Returns (passed, abs_diff). Ints and MIN/MAX: exact. float32 SUM:
    |diff| <= 1e-8*n. float64 SUM: |diff| <= 1e-12.
    """
    tol = tolerance(method, dtype, n)
    diff = abs(float(np.asarray(device_result, dtype=np.float64))
               - float(np.asarray(host_result, dtype=np.float64)))
    if tol == 0.0:
        # exact-match classes: compare in the value domain, not float
        passed = np.asarray(device_result).astype(np.float64) == \
            np.asarray(host_result).astype(np.float64)
        return bool(passed), diff
    return diff <= tol, diff
