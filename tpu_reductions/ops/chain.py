"""Data-dependent chained reduction — honest timing on async backends.

The reference times its hot loop by bracketing every launch with a device
sync (reduction.cpp:319-320,373-374 around the 100-iteration loop at
reduction.cpp:731). That discipline assumes the sync primitive actually
waits for device execution. On a tunneled/async PJRT backend that
assumption can FAIL: `jax.block_until_ready` may return once the launch
is acknowledged, long before the kernel runs, so a per-iteration timed
loop measures dispatch-acknowledgement latency (a flat ~20-30 us floor
regardless of N — measured on this image's tunneled TPU; a 1 GiB reduce
"completed" in 26 us, 40x over the chip's HBM roof).

The fix is structural, not statistical: run K iterations *inside one
compiled program*, each iteration's input data-dependent on the previous
iteration's result so XLA can neither hoist the loop-invariant reduction
out of the loop nor elide any iteration, and force completion by
materializing the final dependent scalar on the host. Timing two trip
counts K_lo < K_hi and taking the slope
    (t(K_hi) - t(K_lo)) / (K_hi - K_lo)
cancels every constant cost — dispatch, tunnel round-trip, compile-cache
lookup, host sync — leaving the true per-iteration device time. The
slope estimator is valid on honest platforms too (it is just amortized
timing), so it is the portable default for bandwidth numbers.

Liveness: a chained trip's host-visible boundary is the materialization
that bounds it — the fori_loop body is traced once and its iterations
never re-enter Python, so the forward-progress heartbeat for chained
execution ticks at `utils/timing.time_chained`'s per-trip fetch (one
heartbeat guard per trip, 'compile' phase for the first). A trip
stranded by a stalled relay therefore goes heartbeat-stale and draws
the watchdog's exit 4 (utils/heartbeat.py) instead of hanging forever.

Mechanism: the staged (rows, 128) array is the `lax.fori_loop` carry;
each step reduces it, then folds the step's scalar into element [0, 0]
with the op's own combine (a one-element dynamic-update on a loop-carried
buffer — updated in place by XLA, not copied). The perturbation makes
iteration i+1's input depend on iteration i's output; it deliberately
changes the reduced value, so correctness is verified on a separate
unchained call (bench/driver.py) and the chained scalar is used for
timing only.
"""

from __future__ import annotations

import math
from typing import Callable

import jax
import jax.numpy as jnp

from tpu_reductions.ops.registry import ReduceOpSpec

# Span-sizing rate model per device kind: (vmem_resident_bytes,
# vmem_rate, hbm_rate). Working sets at or under the residency bound can
# stay VMEM-resident across chained iterations (measured on v5e: a
# 64 MiB carry reduced at ~2.8 TB/s, 3.4x the HBM roof —
# calibration_r02.json), so the estimate must assume the FAST regime
# there or the slope signal comes up short. Erring fast (bigger span)
# costs seconds; erring slow risks the negative-slope failure mode, so
# the unknown-TPU default reuses the fastest measured rates.
_TPU_RATE_MODEL = {
    # device_kind prefix: (resident_bytes, vmem_B/s, hbm_B/s)
    "TPU v5 lite": (112 << 20, 3.5e12, 819e9),    # v5e, measured here
    "TPU v5p": (80 << 20, 1.2e13, 2765e9),
    "TPU v4": (100 << 20, 8e12, 1228e9),
}
_TPU_DEFAULT_RATES = (112 << 20, 1.2e13, 2765e9)
_CPU_BYTES_PER_S = 10e9


def auto_chain_span(n: int, dtype: str, *, target_signal_s: float = 6e-3,
                    lo: int = 8, hi: int = 4096) -> int:
    """Pick the in-program iteration count (the slope span) for chained
    timing at payload size n.

    The slope (t(k_hi) - t(k_lo)) needs enough in-program signal to
    clear the tunnel's multi-ms materialization jitter: span 16 at
    n=2^24 measured a NEGATIVE median slope, span 256 a stable one
    (calibration_r02.json) — but at n=2^30 one iteration already takes
    ~5 ms and a fixed span 256 would burn minutes per sample. Estimate
    the per-iteration time from the platform roofline (the VMEM-resident
    rate for working sets that fit, since overestimating per-iter time
    undersizes the span) and size the span to ~target_signal_s of real
    device work, clamped to [lo, hi].

    No reference analog (TPU-native).
    """
    import numpy as np
    bytes_per_iter = n * np.dtype(jnp.bfloat16 if dtype == "bfloat16"
                                  else dtype).itemsize
    if jax.default_backend() == "tpu":
        kind = jax.devices()[0].device_kind
        resident, vmem_rate, hbm_rate = next(
            (v for k, v in _TPU_RATE_MODEL.items() if kind.startswith(k)),
            _TPU_DEFAULT_RATES)
        rate = vmem_rate if bytes_per_iter <= resident else hbm_rate
    else:
        rate = _CPU_BYTES_PER_S
    est_iter_s = bytes_per_iter / rate
    return max(lo, min(hi, math.ceil(target_signal_s / max(est_iter_s,
                                                           1e-9))))


def make_chained_reduce(core: Callable, op: ReduceOpSpec,
                        surface: str | None = None):
    """Wrap a device-only scalar reduction into `chained(x2d, k) ->
    scalar` running k data-dependent iterations inside one jitted
    program.

    `core` is either `core(x2d) -> scalar` (single-plane paths) or
    `core(hi2d, lo2d) -> (s_hi, s_lo)` with `x2d` passed as a 2-tuple of
    planes (the f64 dd SUM / order-key MIN/MAX pair paths — the same
    two spellings parallel.collectives' chain builder covers). For the
    pair form, the first plane's scalar perturbs the first plane's
    [0, 0] element: the dependency chain is what matters, the chained
    value is for timing only (module docstring).

    `k` is a traced argument (the fori_loop lowers to a while loop), so
    one executable serves every trip count — one tunnel compile, many
    timings. The returned scalar transitively depends on every
    iteration's reduction, so materializing it on the host bounds the
    completion of all k kernel executions.

    `surface` names this executable for the compile observatory
    (obs/compile.py; default `chain/<op>`): the FIRST call — the one
    that traces and compiles — is bracketed in a compile_span, so the
    20-40 s tunnel compile lands in the ledger with its .jax_cache
    cold/warm verdict. Later calls pay two attribute tests. The span
    sits entirely inside the warm-up trip (utils/timing.time_chained
    never uses the first two trips for slopes), so timing doctrine is
    untouched.

    No reference analog (TPU-native).
    """
    def chained(x2d, k) -> jax.Array:
        pair = isinstance(x2d, tuple)

        def call(x):
            return core(*x) if pair else core(x)

        def first(y):
            return y[0] if isinstance(y, tuple) else y

        out = first(jax.eval_shape(call, x2d))
        init = jnp.zeros(out.shape, out.dtype)

        def body(_, carry):
            x, _last = carry
            s = first(call(x))
            # fold the step scalar into one element: in-place one-element
            # update on the loop-carried buffer; breaks loop-invariance
            if pair:
                x0 = x[0].at[0, 0].set(
                    op.jnp_combine(x[0][0, 0], s.astype(x[0].dtype)))
                x = (x0,) + x[1:]
            else:
                x = x.at[0, 0].set(op.jnp_combine(x[0, 0],
                                                  s.astype(x.dtype)))
            return x, s

        _, last = jax.lax.fori_loop(0, k, body, (x2d, init))
        return last

    jitted = jax.jit(chained)
    sid = surface or f"chain/{op.name.lower()}"
    state = {"first": True}

    def chained_observed(x2d, k):
        if state["first"]:
            state["first"] = False
            from tpu_reductions.exec import core as exec_core
            plane = x2d[0] if isinstance(x2d, tuple) else x2d
            shape = tuple(getattr(plane, "shape", ()) or ())
            with exec_core.observe_compile(sid, op=op.name,
                                           rows=(shape[0] if shape
                                                 else None),
                                           pair=isinstance(x2d, tuple)):
                return jitted(x2d, k)
        return jitted(x2d, k)

    # the warming pass (bench/warm.py) AOT-compiles EXACTLY this
    # executable — re-jitting the wrapper would warm a different cache
    # key, so the underlying jit stays reachable (and the one-compile
    # contract stays testable through the wrapper)
    chained_observed.jitted = jitted
    chained_observed.surface = sid
    chained_observed._cache_size = jitted._cache_size
    return chained_observed
