"""Data-dependent chained reduction — honest timing on async backends.

The reference times its hot loop by bracketing every launch with a device
sync (reduction.cpp:319-320,373-374 around the 100-iteration loop at
reduction.cpp:731). That discipline assumes the sync primitive actually
waits for device execution. On a tunneled/async PJRT backend that
assumption can FAIL: `jax.block_until_ready` may return once the launch
is acknowledged, long before the kernel runs, so a per-iteration timed
loop measures dispatch-acknowledgement latency (a flat ~20-30 us floor
regardless of N — measured on this image's tunneled TPU; a 1 GiB reduce
"completed" in 26 us, 40x over the chip's HBM roof).

The fix is structural, not statistical: run K iterations *inside one
compiled program*, each iteration's input data-dependent on the previous
iteration's result so XLA can neither hoist the loop-invariant reduction
out of the loop nor elide any iteration, and force completion by
materializing the final dependent scalar on the host. Timing two trip
counts K_lo < K_hi and taking the slope
    (t(K_hi) - t(K_lo)) / (K_hi - K_lo)
cancels every constant cost — dispatch, tunnel round-trip, compile-cache
lookup, host sync — leaving the true per-iteration device time. The
slope estimator is valid on honest platforms too (it is just amortized
timing), so it is the portable default for bandwidth numbers.

Mechanism: the staged (rows, 128) array is the `lax.fori_loop` carry;
each step reduces it, then folds the step's scalar into element [0, 0]
with the op's own combine (a one-element dynamic-update on a loop-carried
buffer — updated in place by XLA, not copied). The perturbation makes
iteration i+1's input depend on iteration i's output; it deliberately
changes the reduced value, so correctness is verified on a separate
unchained call (bench/driver.py) and the chained scalar is used for
timing only.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp

from tpu_reductions.ops.registry import ReduceOpSpec


def make_chained_reduce(core: Callable[[jax.Array], jax.Array],
                        op: ReduceOpSpec):
    """Wrap a device-only scalar reduction `core(x2d) -> scalar` into
    `chained(x2d, k) -> scalar` running k data-dependent iterations inside
    one jitted program.

    `k` is a traced argument (the fori_loop lowers to a while loop), so
    one executable serves every trip count — one tunnel compile, many
    timings. The returned scalar transitively depends on every
    iteration's reduction, so materializing it on the host bounds the
    completion of all k kernel executions.
    """
    def chained(x2d: jax.Array, k) -> jax.Array:
        out = jax.eval_shape(core, x2d)
        init = jnp.zeros(out.shape, out.dtype)

        def body(_, carry):
            x, _last = carry
            s = core(x)
            # fold the step scalar into one element: in-place one-element
            # update on the loop-carried buffer; breaks loop-invariance
            x = x.at[0, 0].set(op.jnp_combine(x[0, 0], s.astype(x.dtype)))
            return x, s

        _, last = jax.lax.fori_loop(0, k, body, (x2d, init))
        return last

    return jax.jit(chained)
