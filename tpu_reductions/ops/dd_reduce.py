"""Double-double (two-float32) float64 reductions for the Pallas path.

SURVEY.md §7 flags f64 as a hard part: Mosaic/Pallas has no 64-bit types,
and on this image even XLA's emulated f64 cannot be used on the TPU (the
axon tunnel rejects it) — which maps neatly onto the reference's own
capability gate: a device without double support gets QA_WAIVED
(reduction.cpp:116-120,148-155). Instead of waiving, this module provides a
native-f64-free f64 path:

  host: split each f64 value x into f32 pair (hi, lo), hi = fl32(x),
        lo = fl32(x - hi)            [exact to ~48 mantissa bits]
  TPU:  pure-32-bit Pallas kernels accumulate the pairs —
        SUM on (hi, lo) f32 pairs with error-free transformations (Knuth
        two-sum + Dekker renormalization, the standard double-double
        recipe); MIN/MAX on order-preserving int32 KEY pairs: each f64 is
        bijectively mapped to a (k_hi, k_lo) int32 pair whose
        lexicographic order equals f64 order (sign-flip bitcast trick),
        so the selection is EXACT — no precision is lost at all
  host: promote the small accumulator lattice back to f64 and finish
        (SUM: compensated combine; MIN/MAX: invert the key bijection).

No f64 value ever touches the device, and jax x64 mode is never required
on the TPU.

Error budget vs the reference's f64 acceptance threshold of 1e-12 absolute
(reduction.cpp:764): the split is exact to 2^-48 ≈ 3.6e-15 relative per
element; compensated accumulation keeps the running error at the same
order. For the benchmark payload (byte/RAND_MAX values, sums O(1) at
n=2^24 — reduction.cpp:698-705) total error is ~1e-15, comfortably inside
1e-12. Verified against the exactly-rounded host sum in
tests/test_dd_reduce.py.

Range: the SUM path is full f64 range. A bare f32 split would overflow
for |x| >= ~3.4e38, so the staged path pre-scales the payload by an exact
power of two (host_split_scaled: ldexp by the max element's exponent, so
the largest magnitude sits near 2^20) and the host finish undoes it —
power-of-two scaling is exact in binary floating point, so the error
budget is unchanged. Elements more than ~2^-169 smaller than the max
underflow to zero in the scaled planes; their total possible contribution
(n * max * 2^-169) is ~2^-145 relative, far inside the 1e-12 acceptance
band. MIN/MAX via keys are full-range and bit-exact (including -0.0 vs
+0.0 ordering; NaNs are excluded by the payload contract, as in the
reference).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from tpu_reductions.ops.pallas_reduce import (LANES,
                                              _interpret_default,
                                              choose_tiling)


# ---------------------------------------------------------------------------
# Splitting / staging (host side, numpy — no device f64)
# ---------------------------------------------------------------------------


def host_split(x: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """f64 -> (hi, lo) float32 pair with hi + lo == x to ~48 bits. Pure
    numpy so the split can run before any device transfer. Overflows for
    |x| >= f32 max — use host_split_scaled for full-range payloads.

    No reference analog (TPU-native).
    """
    x = np.asarray(x, dtype=np.float64)
    hi = x.astype(np.float32)
    lo = (x - hi.astype(np.float64)).astype(np.float32)
    return hi, lo


def host_split_scaled(x: np.ndarray
                      ) -> tuple[np.ndarray, np.ndarray, int]:
    """Full-range f64 -> (hi, lo, s): split ldexp(x, -s) where the integer
    exponent shift s places the largest magnitude near 2^20 — far from
    both f32 overflow (2^128) and the denormal floor for the lo plane.
    Reconstruct with ldexp(hi + lo, s). Power-of-two rescaling is exact,
    so precision matches host_split; payloads containing inf/nan are
    rejected (the reference's payload contract excludes them).

    No reference analog (TPU-native).
    """
    x = np.asarray(x, dtype=np.float64)
    m = float(np.max(np.abs(x))) if x.size else 0.0
    if not np.isfinite(m):
        raise ValueError("payload contains non-finite values; the dd "
                         "split (like the reference payload contract) "
                         "requires finite f64")
    s = int(np.floor(np.log2(m))) - 20 if m > 0.0 else 0
    hi, lo = host_split(np.ldexp(x, -s))
    return hi, lo, s


def split_hi_lo(x: jax.Array) -> tuple[jax.Array, jax.Array]:
    """In-graph split (needs x64; used on CPU hosts/tests only). No reference analog (TPU-native)."""
    hi = x.astype(jnp.float32)
    # redlint: disable=RED001 -- in-graph split runs on x64 CPU hosts/tests only (docstring contract); the TPU path uses host_split
    lo = (x - hi.astype(jnp.float64)).astype(jnp.float32)
    return hi, lo


def host_key_encode(x: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """Bijectively map f64 values to (k_hi, k_lo) int32 pairs whose
    lexicographic signed order equals the f64 total order.

    Standard order-preserving float bitcast: for the uint64 bit pattern b,
      key = b ^ 0x8000000000000000   if the sign bit is clear (x >= +0.0)
      key = ~b                       if the sign bit is set
    makes unsigned-integer order match float order. Splitting into 32-bit
    halves and flipping each half's top bit converts unsigned lexicographic
    order into *signed* int32 lexicographic order (TPU integers are
    signed). Exactly invertible — see host_key_decode.

    No reference analog (TPU-native).
    """
    b = np.ravel(np.asarray(x, dtype=np.float64)).view(np.uint64)
    sign = (b >> np.uint64(63)).astype(bool)
    key = np.where(sign, ~b, b ^ np.uint64(0x8000000000000000))
    k_hi = ((key >> np.uint64(32)) ^ np.uint64(0x80000000)).astype(
        np.uint32).view(np.int32)
    k_lo = ((key & np.uint64(0xFFFFFFFF)) ^ np.uint64(0x80000000)).astype(
        np.uint32).view(np.int32)
    return k_hi, k_lo


def host_key_decode(k_hi: np.ndarray, k_lo: np.ndarray) -> np.ndarray:
    """Invert host_key_encode: (k_hi, k_lo) int32 -> f64, bit-exact. No reference analog (TPU-native)."""
    hi_u = (np.asarray(k_hi).view(np.uint32).astype(np.uint64)
            ^ np.uint64(0x80000000))
    lo_u = (np.asarray(k_lo).view(np.uint32).astype(np.uint64)
            ^ np.uint64(0x80000000))
    key = (hi_u << np.uint64(32)) | lo_u
    sign = (key >> np.uint64(63)).astype(bool)  # post-map: top bit set <=> x>=0
    b = np.where(sign, key ^ np.uint64(0x8000000000000000), ~key)
    return b.view(np.float64)


_I32_MAX = np.int32(2**31 - 1)
_I32_MIN = np.int32(-2**31)


def stage_split_padded(x: np.ndarray, method: str, threads: int = 256,
                       max_blocks: int = 64
                       ) -> tuple[np.ndarray, np.ndarray,
                                  tuple[int, int, int], int]:
    """Host-side staging: encode the f64 payload as two 32-bit planes and
    pad/reshape both to (P*T*TM, LANES).

    SUM -> (hi, lo) float32 double-double planes (exact power-of-two
    pre-scaled by 2^-s for full f64 range — host_split_scaled),
    zero-padded. MIN/MAX -> (k_hi, k_lo) int32 order-key planes (always
    full-range; s == 0), padded with the largest/smallest key pair (the
    monoid identity in key space).
    Returns (plane_hi, plane_lo, (tm, p, t), s) — finish with
    host_finish_pairs(..., scale_exp=s).

    No reference analog (TPU-native).
    """
    method = method.upper()
    flat = np.ravel(np.asarray(x, dtype=np.float64))
    tm, p, t = choose_tiling(flat.size, threads, max_blocks)
    rows = p * t * tm
    pad = rows * LANES - flat.size
    s = 0
    if method == "SUM":
        hi, lo, s = host_split_scaled(flat)
        pads = (np.float32(0.0), np.float32(0.0))
    else:
        hi, lo = host_key_encode(flat)
        pads = ((_I32_MAX, _I32_MAX) if method == "MIN"
                else (_I32_MIN, _I32_MIN))
    hi = np.pad(hi, (0, pad), constant_values=pads[0]).reshape(rows, LANES)
    lo = np.pad(lo, (0, pad), constant_values=pads[1]).reshape(rows, LANES)
    return hi, lo, (tm, p, t), s


# ---------------------------------------------------------------------------
# Error-free transformations
# ---------------------------------------------------------------------------


def _two_sum(a, b):
    """Error-free transformation: a + b = s + err exactly (Knuth)."""
    s = a + b
    bb = s - a
    err = (a - (s - bb)) + (b - bb)
    return s, err


def _dd_add(hi1, lo1, hi2, lo2):
    """(hi1,lo1) + (hi2,lo2) -> renormalized (hi,lo)."""
    s, e = _two_sum(hi1, hi2)
    e = e + (lo1 + lo2)
    hi = s + e
    lo = e - (hi - s)
    return hi, lo


def _dd_select(hi1, lo1, hi2, lo2, minimum: bool):
    """Elementwise lexicographic min/max over (hi, lo) pairs."""
    if minimum:
        take2 = (hi2 < hi1) | ((hi2 == hi1) & (lo2 < lo1))
    else:
        take2 = (hi2 > hi1) | ((hi2 == hi1) & (lo2 > lo1))
    return jnp.where(take2, hi2, hi1), jnp.where(take2, lo2, lo1)


# ---------------------------------------------------------------------------
# Kernels
# ---------------------------------------------------------------------------


def _dd_kernel(method: str):
    """Grid-sequential elementwise pair accumulation: each step folds its
    (TM,128) hi/lo tiles into resident (TM,128) accumulator blocks — the
    grid-stride accumulate of the reference kernel
    (reduction_kernel.cu:88-98), carried in compensated f32-pair
    arithmetic."""

    def kernel(hi_ref, lo_ref, acc_hi_ref, acc_lo_ref):
        step = pl.program_id(0)

        @pl.when(step == 0)
        def _():
            acc_hi_ref[:] = hi_ref[:]
            acc_lo_ref[:] = lo_ref[:]

        @pl.when(step > 0)
        def _():
            if method == "SUM":
                hi, lo = _dd_add(acc_hi_ref[:], acc_lo_ref[:],
                                 hi_ref[:], lo_ref[:])
            else:
                hi, lo = _dd_select(acc_hi_ref[:], acc_lo_ref[:],
                                    hi_ref[:], lo_ref[:],
                                    minimum=(method == "MIN"))
            acc_hi_ref[:] = hi
            acc_lo_ref[:] = lo

    return kernel


def dd_pallas_call(hi2d: jax.Array, lo2d: jax.Array, method: str, tm: int,
                   interpret: Optional[bool] = None
                   ) -> tuple[jax.Array, jax.Array]:
    """Run the pair-accumulator kernel over staged (R,128) f32 planes.
    Returns the (TM,128) hi/lo accumulators (jittable, f32-only).

    No reference analog (TPU-native).
    """
    rows = hi2d.shape[0]
    interpret = _interpret_default() if interpret is None else interpret
    dt = hi2d.dtype  # f32 planes for SUM, i32 key planes for MIN/MAX
    return pl.pallas_call(
        _dd_kernel(method.upper()),
        out_shape=[jax.ShapeDtypeStruct((tm, LANES), dt),
                   jax.ShapeDtypeStruct((tm, LANES), dt)],
        grid=(rows // tm,),
        in_specs=[pl.BlockSpec((tm, LANES), lambda i: (i, 0),
                               memory_space=pltpu.VMEM),
                  pl.BlockSpec((tm, LANES), lambda i: (i, 0),
                               memory_space=pltpu.VMEM)],
        out_specs=[pl.BlockSpec((tm, LANES), lambda i: (0, 0),
                                memory_space=pltpu.VMEM),
                   pl.BlockSpec((tm, LANES), lambda i: (0, 0),
                                memory_space=pltpu.VMEM)],
        # sequential accumulator grid (same structure as pallas_reduce's
        # single-pass kernels): declare it so Mosaic never parallelizes
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("arbitrary",)),
        interpret=interpret,
    )(hi2d, lo2d)


# ---------------------------------------------------------------------------
# Device finish (all-device path) + host finish + public entry points
# ---------------------------------------------------------------------------


def device_finish_pairs(acc_hi: jax.Array, acc_lo: jax.Array,
                        method: str) -> tuple[jax.Array, jax.Array]:
    """Fold the (TM, LANES) pair accumulator down to ONE scalar pair on
    device — the pair-arithmetic analog of the reference's on-device
    final fold (the warp-synchronous 32->1 tail, reduction_kernel.cu:
    110-122, and the multi-pass partials finish, reduction.cpp:343-357)
    — so the f64 path stays all-device, only 8 bytes ever cross to the
    host, and chained slope timing applies exactly as on the int/float
    paths.

    jnp.sum/min/max cannot be used: the fold must preserve pair
    semantics (compensated dd addition for SUM, lexicographic selection
    for the MIN/MAX key pairs). Instead: a static log2 halving tree of
    the same error-free transformations the kernel uses — pad the
    flattened planes to a power of two with the op's identity, then
    repeatedly combine the two halves elementwise. All 32-bit, jittable,
    TPU-safe (no f64 anywhere).

    Error budget (SUM): each _dd_add is an error-free transformation
    renormalized to ~2^-48 relative accuracy, and the tree adds only
    log2(TM*128) ~ 10-13 levels on top of the kernel's accumulation, so
    the finish stays inside the same ~1e-15 budget as the host
    promote-and-sum it replaces (module docstring error analysis);
    MIN/MAX key selection is exact. Verified against the host finish in
    tests/test_dd_reduce.py."""
    method = method.upper()
    hi, lo = jnp.ravel(acc_hi), jnp.ravel(acc_lo)
    size = hi.shape[0]
    pow2 = 1 << max(size - 1, 0).bit_length()
    if pow2 != size:
        if method == "SUM":
            pad = jnp.zeros((pow2 - size,), hi.dtype)
            hi, lo = (jnp.concatenate([hi, pad]),
                      jnp.concatenate([lo, pad]))
        else:
            ident = _I32_MAX if method == "MIN" else _I32_MIN
            pad = jnp.full((pow2 - size,), ident, hi.dtype)
            hi, lo = (jnp.concatenate([hi, pad]),
                      jnp.concatenate([lo, pad]))
    while hi.shape[0] > 1:
        h = hi.shape[0] // 2
        if method == "SUM":
            hi, lo = _dd_add(hi[:h], lo[:h], hi[h:], lo[h:])
        else:
            hi, lo = _dd_select(hi[:h], lo[:h], hi[h:], lo[h:],
                                minimum=(method == "MIN"))
    return hi[0], lo[0]


def decode_pair_scalar(s_hi, s_lo, method: str,
                       scale_exp: int = 0) -> np.float64:
    """Convert the device's final scalar pair (8 bytes) to np.float64 on
    host — the D2H of the final result scalar (reduction.cpp:377-381),
    pair-encoded: SUM promotes and undoes the staging pre-scale exactly
    (ldexp); MIN/MAX inverts the order-key bijection — bit-exact."""
    if method.upper() == "SUM":
        z = float(s_hi) + float(s_lo)
        return np.float64(np.ldexp(z, scale_exp))
    return np.float64(host_key_decode(np.asarray(s_hi, dtype=np.int32),
                                      np.asarray(s_lo, dtype=np.int32)))


def _make_stage_fn(method: str, tm: int, threads: int, max_blocks: int):
    """One staging closure shared by the device- and host-finish
    builders: np f64 payload -> (hi2d, lo2d) device planes + the
    ride-along scale int (untimed staging metadata, like the padding
    geometry)."""

    def put(plane2d):
        # already identity-padded on host; bound per-message transfer
        # size for multi-GiB planes (utils/staging.py relay hazard)
        from tpu_reductions.utils.staging import maybe_chunked_stage
        staged = maybe_chunked_stage(plane2d.ravel(), plane2d.shape[0],
                                     plane2d.shape[1],
                                     plane2d.dtype.type(0))
        # redlint: disable=RED015 -- single-message path only when maybe_chunked_stage judged the plane under the staging threshold
        return jnp.asarray(plane2d) if staged is None else staged

    def stage_fn(x_np):
        hi2d, lo2d, (tm2, _, _), s = stage_split_padded(
            np.asarray(x_np, dtype=np.float64), method, threads,
            max_blocks)
        assert tm2 == tm
        return put(hi2d), put(lo2d), s

    return stage_fn


def make_dd_device_reduce(method: str, n: int, *, threads: int = 256,
                          max_blocks: int = 64,
                          interpret: Optional[bool] = None):
    """Memoizing wrapper over _build_dd_device_reduce: the benchmark
    driver builds this triple twice per f64 config — once for the
    verification reduce (_make_device_fn) and once for the chained
    timing fn (_make_chained_fn) — and each dd core costs a full Pallas
    compile through the tunnel (~20-40 s first time). One cache entry
    per (args, backend) shares the jitted core between them; the
    backend key guards against a platform switch mid-process (tests
    flip cpu/interpret).

    No reference analog (TPU-native).
    """
    return _dd_device_reduce_cached(method.upper(), n, threads,
                                    max_blocks, interpret,
                                    jax.default_backend())


def _dd_device_reduce_cached(method, n, threads, max_blocks, interpret,
                             _backend):
    key = (method, n, threads, max_blocks, interpret, _backend)
    hit = _DD_DEVICE_CACHE.get(key)
    if hit is None:
        if len(_DD_DEVICE_CACHE) >= 32:   # bound: a long shmoo sweeps
            _DD_DEVICE_CACHE.clear()      # many n values; drop the lot
        hit = _DD_DEVICE_CACHE[key] = _build_dd_device_reduce(
            method, n, threads=threads, max_blocks=max_blocks,
            interpret=interpret)
    return hit


_DD_DEVICE_CACHE: dict = {}


def _build_dd_device_reduce(method: str, n: int, *, threads: int = 256,
                            max_blocks: int = 64,
                            interpret: Optional[bool] = None):
    """Build (stage_fn, core, finish) for the ALL-DEVICE f64 path:

      stage_fn(np f64) -> (hi2d, lo2d, s) device planes + host scale int
      core(hi2d, lo2d) -> (s_hi, s_lo) device scalar pair  [jittable —
          kernel + device tree finish; this is the chainable reduce]
      finish(s_hi, s_lo, scale_exp) -> np.float64  [8-byte host decode]

    This is the f64 twin of pallas_reduce.make_staged_core: the timed
    region is pure device work, so chained slope timing applies and the
    f64 benchmark stops being bound by host-link transfer (the old
    host_finish_pairs path remains as the --cpufinal spelling,
    reduction.cpp:328-340)."""
    tm, _, _ = choose_tiling(n, threads, max_blocks)
    method = method.upper()
    stage_fn = _make_stage_fn(method, tm, threads, max_blocks)

    @jax.jit
    def core(hi2d, lo2d):
        acc_hi, acc_lo = dd_pallas_call(hi2d, lo2d, method, tm,
                                        interpret=interpret)
        return device_finish_pairs(acc_hi, acc_lo, method)

    def finish(s_hi, s_lo, scale_exp=0):
        return decode_pair_scalar(s_hi, s_lo, method,
                                  scale_exp=scale_exp)

    return stage_fn, core, finish


def host_finish_pairs(acc_hi, acc_lo, method: str,
                      scale_exp: int = 0) -> np.float64:
    """Finish the small (TM*128-pair) accumulator lattice on host — the
    warp-final analog at --cpufinal semantics (reduction.cpp:328-340).

    SUM: promote f32 (hi, lo) planes to f64, combine (pairwise np.sum
    keeps error ~1e-16 relative at this size), and undo the staging
    pre-scale exactly with ldexp(., scale_exp). MIN/MAX: rebuild the
    uint64 order keys, select (unsigned key order == f64 order), and
    decode — bit-exact."""
    hi = np.asarray(jax.device_get(acc_hi))
    lo = np.asarray(jax.device_get(acc_lo))
    method = method.upper()
    if method == "SUM":
        z = hi.astype(np.float64) + lo.astype(np.float64)
        return np.float64(np.ldexp(z.sum(), scale_exp))
    vals = host_key_decode(hi, lo)
    # Accumulator slots that only ever saw the padding identity decode to
    # NaN (the pad key is not a real float's image); the payload contract
    # excludes NaNs (as in the reference), so nan-ignoring selection is
    # exactly "ignore pure-padding slots".
    return np.float64(np.nanmin(vals) if method == "MIN"
                      else np.nanmax(vals))


def make_dd_staged_reduce(method: str, n: int, *, threads: int = 256,
                          max_blocks: int = 64,
                          interpret: Optional[bool] = None):
    """Build (stage_fn, reduce_fn) for f64 benchmarking with no device f64:
    stage_fn(np f64) -> (hi2d, lo2d) device f32 planes (untimed);
    reduce_fn(hi2d, lo2d) -> np.float64 scalar (timed: kernel + host
    finish, the --cpufinal structure).

    No reference analog (TPU-native).
    """
    tm, _, _ = choose_tiling(n, threads, max_blocks)
    stage_fn = _make_stage_fn(method.upper(), tm, threads, max_blocks)

    kernel_fn = jax.jit(lambda h, l: dd_pallas_call(h, l, method, tm,
                                                    interpret=interpret))

    def reduce_fn(hi2d, lo2d, scale_exp=0):
        acc_hi, acc_lo = kernel_fn(hi2d, lo2d)
        return host_finish_pairs(acc_hi, acc_lo, method,
                                 scale_exp=scale_exp)

    return stage_fn, reduce_fn


def dd_pallas_reduce_f64(x, method: str = "SUM", *, threads: int = 256,
                         max_blocks: int = 64,
                         interpret: Optional[bool] = None) -> np.float64:
    """One-shot f64 reduce via the double-double path (host split ->
    f32 Pallas -> host finish). Accepts numpy or jax input.

    No reference analog (TPU-native).
    """
    x_np = np.asarray(jax.device_get(x) if isinstance(x, jax.Array) else x,
                      dtype=np.float64)
    hi2d, lo2d, (tm, _, _), s = stage_split_padded(x_np, method, threads,
                                                   max_blocks)
    # redlint: disable=RED015 -- one-shot convenience entry (tests/CPU hosts, docstring contract); the benchmark path stages through _make_stage_fn's bounded put
    acc_hi, acc_lo = dd_pallas_call(jnp.asarray(hi2d), jnp.asarray(lo2d),
                                    method, tm, interpret=interpret)
    return host_finish_pairs(acc_hi, acc_lo, method, scale_exp=s)


def dd_pallas_sum_f64(x: jax.Array, *, threads: int = 256,
                      max_blocks: int = 64,
                      interpret: Optional[bool] = None) -> jax.Array:
    """Fully in-graph f64 SUM (requires x64; CPU hosts/tests — on the
    axon TPU use dd_pallas_reduce_f64, which never puts f64 on device).

    No reference analog (TPU-native).
    """
    assert x.dtype == jnp.float64, x.dtype  # redlint: disable=RED001 -- CPU-hosts/tests-only entry point (docstring contract); never reached on the axon TPU
    x = jnp.ravel(x)
    tm, p, t = choose_tiling(x.size, threads, max_blocks)
    rows = p * t * tm
    x = jnp.pad(x, (0, rows * LANES - x.size))  # SUM identity: 0.0
    hi, lo = split_hi_lo(x.reshape(rows, LANES))
    acc_hi, acc_lo = dd_pallas_call(hi, lo, "SUM", tm, interpret=interpret)
    # redlint: disable=RED001 -- same CPU-only contract as the assert above
    return jnp.sum(acc_hi.astype(jnp.float64) + acc_lo.astype(jnp.float64))
