"""L2: reduction op registry + kernels + host oracle.

- registry: {SUM,MIN,MAX} x {int32,float32,float64(,bfloat16)} op table —
  the analog of the reference's templated kernel fan-out
  (reduction_kernel.cu:527-564) and MPI op table (reduce.c:21-28).
- xla_reduce: jnp baseline — the always-correct comparator.
- pallas_reduce: single-chip hierarchical Pallas kernels — the tree +
  warp-synchronous "kernel 6" analog (reduction_kernel.cu:74-253).
- oracle: host reference (Kahan) — reduction.cpp:206-249 analog, with a
  native C++ backend in csrc/.
- chain: data-dependent chained reduction for honest slope timing on
  async/tunneled backends (no reference analog — its local CUDA sync
  could be trusted).
"""

from tpu_reductions.ops.chain import make_chained_reduce
from tpu_reductions.ops.oracle import host_reduce, verify
from tpu_reductions.ops.registry import OPS, ReduceOpSpec, get_op, tolerance
from tpu_reductions.ops.xla_reduce import xla_reduce

__all__ = ["OPS", "ReduceOpSpec", "get_op", "tolerance",
           "xla_reduce", "host_reduce", "verify", "make_chained_reduce"]
