"""Replica router: the horizontal scale-out tier over N serving
engines (ROADMAP item 2; docs/SERVING.md "scaling tier").

One `ReplicaRouter` fronts N replicas — in-process `LocalReplica`
engines (the loadgen's fast path) or process-per-replica
`ProcessReplica` children (`python -m tpu_reductions.serve` over the
TCP JSON-lines wire, the production shape) — and routes each request:

  * **bucket affinity**: small requests (<= affinity_bytes) hash-route
    on (method, dtype, n), so one replica's jit bucket cache
    (serve/executor.py `_bucket`) serves every recurrence of a key
    instead of every replica paying the same trace+compile
    (the .jax_cache doctrine, horizontally);
  * **load balance**: everything else goes to the alive replica with
    the fewest outstanding requests;
  * **death re-routing**: a terminal response that indicates replica
    failure (dead process, dead relay, stopped engine) re-submits the
    request to another alive replica (`route.reroute`) up to
    max_retries times — chaos-tested against faults/relay.FakeRelay —
    so every routed request still resolves to exactly one of the five
    terminal statuses (serve/request.STATUSES, the no-hang contract).

The router is jax-free BY CONSTRUCTION (redlint RED014 fences every
serve/ module except serve/executor.py): it moves requests, never
payloads — device work happens inside the replicas.

CLI (the process-per-replica tier in one command):

    python -m tpu_reductions.serve.router --replicas 2 \
        [--port 0 --port-file PATH] [--platform cpu] [--relay-port P]
"""

from __future__ import annotations

import dataclasses
import itertools
import os
import queue
import signal
import socket
import subprocess
import sys
import threading
import time
import zlib
from typing import Dict, List, Optional, Sequence

from tpu_reductions.faults.inject import fault_point
from tpu_reductions.obs import ledger, trace
from tpu_reductions.serve.journal import FleetJournal
from tpu_reductions.serve.request import (PendingResponse, ReduceRequest,
                                          ReduceResponse)

# substrings of a terminal response's error that mean "this REPLICA
# failed", not "this REQUEST failed" — the re-route predicate. A
# verification failure or an expired deadline would fail identically
# anywhere; these would not.
_REPLICA_FAILURE_MARKS = ("replica-dead", "replica-timeout",
                          "relay dead", "relay-dead", "engine-stopped")

# the planned scale-down terminal (docs/SERVING.md elastic fleet),
# deliberately NOT in the failure vocabulary: a draining replica is
# healthy, its admission is closed by policy, so landing on one
# re-routes WITHOUT burning a max_retries attempt (the retry budget
# exists for failures, and a planned drain is not one)
_REPLICA_DRAINING_MARK = "replica-draining"


def replica_failure(resp: ReduceResponse) -> bool:
    """Whether this terminal response blames the replica rather than
    the request (module docstring) — the router's re-route predicate,
    exported so the chaos tests pin exactly the statuses that re-route."""
    if resp.status not in ("error", "shed", "rejected"):
        return False
    return any(m in (resp.error or "") for m in _REPLICA_FAILURE_MARKS)


def replica_draining(resp: ReduceResponse) -> bool:
    """Whether this terminal response is a draining replica declining
    NEW work (serve/engine.begin_drain's rejection mark) — distinct
    from replica_failure: the router re-submits without consuming a
    retry attempt, so max_retries=0 fleets still drain losslessly."""
    if resp.status not in ("error", "shed", "rejected"):
        return False
    return _REPLICA_DRAINING_MARK in (resp.error or "")


def _is_draining(replica) -> bool:
    """Duck-typed draining probe: replicas without the drain protocol
    (any pre-elastic replica shape) never report draining."""
    probe = getattr(replica, "draining", None)
    return bool(probe()) if callable(probe) else False


class LocalReplica:
    """One in-process engine behind the router — the loadgen's replica
    flavor (no subprocess spawn / TCP hop, so the scaling series
    measures routing + engine behavior, not fork latency)."""

    def __init__(self, replica_id: str, engine) -> None:
        self.replica_id = replica_id
        self._engine = engine

    def start(self) -> "LocalReplica":
        self._engine.start()
        ledger.emit("replica.up", replica=self.replica_id, kind="local")
        return self

    def alive(self) -> bool:
        e = self._engine
        return (e._thread is not None and e._thread.is_alive()
                and not e._stopping)

    def submit(self, request: ReduceRequest) -> PendingResponse:
        return self._engine.submit(request)

    def prewarm(self, method: str, dtype: str, n: int, *,
                up_to_batch: int = 1) -> None:
        """Delegate to the engine's jit-bucket warmer (the loadgen's
        measure-serving-not-compilation discipline)."""
        self._engine.prewarm(method, dtype, n, up_to_batch=up_to_batch)

    # -- drain protocol (serve/autoscale.drain_replica) ---------------

    def drain_begin(self) -> None:
        """Close admission for planned scale-down; in-flight and queued
        work keeps serving (serve/engine.begin_drain)."""
        self._engine.begin_drain()

    def draining(self) -> bool:
        return bool(self._engine.draining)

    def queued_depth(self) -> int:
        return self._engine.queued_depth()

    def warm_bucket_keys(self) -> list:
        return self._engine.warm_bucket_keys()

    def slo_p99(self, slo: str):
        return self._engine.slo_p99(slo)

    def stats(self) -> dict:
        """Engine terminal counters (the drain-vs-kill evidence:
        a drained victim retires with shed == 0)."""
        return dict(self._engine.stats)

    def stop(self) -> None:
        self._engine.stop(drain=True)

    def kill(self) -> None:
        """Chaos seam: hard-stop without drain (queued work sheds) —
        the in-process stand-in for a replica process dying."""
        ledger.emit("replica.down", replica=self.replica_id,
                    reason="killed")
        self._engine.stop(drain=False)


class ProcessReplica:
    """One `python -m tpu_reductions.serve` child behind the router —
    process-per-replica (the tentpole's production shape): its own
    interpreter, its own jax runtime, its own engine; the router talks
    to it over the TCP JSON-lines wire through a small worker pool, so
    `submit` never blocks the caller. A dead child (or a dead
    connection) resolves every affected request with a
    `replica-dead` error — which the router's re-route predicate
    catches."""

    def __init__(self, replica_id: str, *, platform: str = "cpu",
                 relay_port: Optional[int] = None, workers: int = 4,
                 request_timeout_s: float = 600.0,
                 spawn_timeout_s: float = 90.0,
                 reap_grace_s: float = 5.0,
                 extra_args: Sequence[str] = ()) -> None:
        self.replica_id = replica_id
        self._platform = platform
        self._relay_port = relay_port
        self._workers = workers
        self._request_timeout_s = request_timeout_s
        self._spawn_timeout_s = spawn_timeout_s
        self._reap_grace_s = reap_grace_s
        self._extra_args = list(extra_args)
        self._proc: Optional[subprocess.Popen] = None
        self._pid: Optional[int] = None    # adopted orphans: no Popen
        self._port: Optional[int] = None
        self._jobs: "queue.Queue" = queue.Queue()
        self._threads: List[threading.Thread] = []
        self._down_emitted = False
        self._lock = threading.Lock()

    @classmethod
    def adopt(cls, replica_id: str, *, port: int, pid: int,
              platform: str = "cpu",
              relay_port: Optional[int] = None, workers: int = 4,
              request_timeout_s: float = 600.0,
              reap_grace_s: float = 5.0) -> "ProcessReplica":
        """Re-attach to a still-running child a DEAD controller left
        behind (the fleet journal's port+pid record): no Popen handle —
        the orphan was reparented to init when the old router died —
        so liveness falls back to signal-0 probes and reaping to raw
        os.kill escalation. `start()` on an adopted replica only
        spins up the worker pool; the process already runs."""
        rep = cls(replica_id, platform=platform, relay_port=relay_port,
                  workers=workers, request_timeout_s=request_timeout_s,
                  reap_grace_s=reap_grace_s)
        rep._pid = int(pid)
        rep._port = int(port)
        return rep

    @property
    def pid(self) -> Optional[int]:
        return self._proc.pid if self._proc is not None else self._pid

    @property
    def port(self) -> Optional[int]:
        return self._port

    @property
    def adopted(self) -> bool:
        return self._proc is None and self._pid is not None

    def start(self) -> "ProcessReplica":
        if self.adopted:
            # the child already runs; only the router-side worker pool
            # needs (re)building
            self._start_workers()
            ledger.emit("replica.up", replica=self.replica_id,
                        kind="adopted", port=self._port, pid=self._pid)
            return self
        import shutil
        import tempfile
        port_dir = tempfile.mkdtemp(prefix="replica-")
        port_file = os.path.join(port_dir, "port")
        cmd = [sys.executable, "-m", "tpu_reductions.serve",
               "--port", "0", "--port-file", port_file]
        if self._platform:
            cmd += ["--platform", self._platform]
        if self._relay_port is not None:
            cmd += ["--relay-port", str(self._relay_port)]
        cmd += self._extra_args
        self._proc = subprocess.Popen(cmd, stdout=subprocess.DEVNULL,
                                      stderr=subprocess.DEVNULL)
        ledger.emit("replica.spawn", replica=self.replica_id,
                    pid=self._proc.pid)
        try:
            deadline = time.monotonic() + self._spawn_timeout_s
            while time.monotonic() < deadline:
                if self._proc.poll() is not None:
                    raise RuntimeError(
                        f"replica {self.replica_id} died during spawn "
                        f"(exit {self._proc.returncode})")
                try:
                    with open(port_file) as f:
                        self._port = int(f.read().strip())
                    break
                except (OSError, ValueError):
                    time.sleep(0.05)
            if self._port is None:
                self._proc.kill()
                raise TimeoutError(
                    f"replica {self.replica_id} never published its "
                    f"port within {self._spawn_timeout_s}s")
        finally:
            # the port is read (or the spawn failed): the tempdir has
            # served its purpose — one leaked dir per spawn otherwise
            shutil.rmtree(port_dir, ignore_errors=True)
        self._start_workers()
        ledger.emit("replica.up", replica=self.replica_id,
                    kind="process", port=self._port)
        return self

    def _start_workers(self) -> None:
        for i in range(self._workers):
            t = threading.Thread(target=self._worker, daemon=True,
                                 name=f"{self.replica_id}-w{i}")
            t.start()
            self._threads.append(t)

    def alive(self) -> bool:
        if self._proc is not None:
            return self._proc.poll() is None
        if self._pid is None:
            return False
        # adopted orphan: no waitable handle — signal-0 probes the pid
        # (reparented to init, still signalable by us)
        try:
            os.kill(self._pid, 0)
            return True
        except (ProcessLookupError, PermissionError):
            return False

    def ping(self) -> bool:
        """Liveness probe over the existing TCP wire (the adoption
        check): a pid can be alive while the engine inside is wedged —
        only a control round-trip proves the replica SERVES."""
        return self._control({"op": "ping"}).get("ok") is True

    def submit(self, request: ReduceRequest) -> PendingResponse:
        pending = PendingResponse(f"{self.replica_id}-pending")
        if not self.alive():
            self._mark_down("process-exited")
            pending.resolve(ReduceResponse(
                pending.request_id, "error", request.method,
                request.dtype, request.n,
                error=f"replica-dead: {self.replica_id} not running"))
            return pending
        self._jobs.put((request, pending))
        return pending

    def _worker(self) -> None:
        """One connection, one blocking round-trip at a time. Every
        failure mode — dead process, refused/broken connection, read
        timeout — resolves the in-flight request with a replica-dead
        error; the job queue itself never drops a request."""
        import json
        conn = None
        rfile = None
        while True:
            item = self._jobs.get()
            if item is None:
                break
            request, pending = item
            try:
                if conn is None:
                    conn = socket.create_connection(
                        ("127.0.0.1", self._port), timeout=5.0)
                    conn.settimeout(self._request_timeout_s)
                    rfile = conn.makefile("rb")
                spec = {"method": request.method, "type": request.dtype,
                        "n": request.n, "seed": request.seed,
                        "deadline_s": request.deadline_s,
                        "value": request.value,
                        "tenant": request.tenant,
                        "priority": request.priority,
                        "slo": request.slo,
                        "idem_key": request.idem_key}
                conn.sendall((json.dumps(spec) + "\n").encode())
                raw = rfile.readline()
                if not raw:
                    raise ConnectionError("connection closed mid-request")
                d = json.loads(raw)
                pending.resolve(ReduceResponse(
                    d.get("request_id", pending.request_id),
                    d.get("status", "error"), request.method,
                    request.dtype, request.n,
                    result=d.get("result"), error=d.get("error"),
                    latency_s=d.get("latency_s"),
                    queue_s=d.get("queue_s"),
                    batch_size=d.get("batch_size")))
            except socket.timeout:
                self._drop_conn(conn)
                conn = rfile = None
                pending.resolve(ReduceResponse(
                    pending.request_id, "error", request.method,
                    request.dtype, request.n,
                    error=(f"replica-timeout: {self.replica_id} gave "
                           f"no response in {self._request_timeout_s}s")))
            except (OSError, ValueError, ConnectionError) as e:
                self._drop_conn(conn)
                conn = rfile = None
                self._mark_down(f"{type(e).__name__}: {e}")
                pending.resolve(ReduceResponse(
                    pending.request_id, "error", request.method,
                    request.dtype, request.n,
                    error=(f"replica-dead: {self.replica_id} "
                           f"({type(e).__name__}: {e})")))
        self._drop_conn(conn)

    @staticmethod
    def _drop_conn(conn) -> None:
        if conn is not None:
            try:
                conn.close()
            except OSError:
                pass

    def _mark_down(self, reason: str) -> None:
        with self._lock:
            if self._down_emitted:
                return
            self._down_emitted = True
        ledger.emit("replica.down", replica=self.replica_id,
                    reason=reason[:120])

    # -- drain protocol (serve/autoscale.drain_replica) ---------------

    def _control(self, spec: dict) -> dict:
        """One {"op": ...} control round-trip on a dedicated short
        connection (serve/__main__ handles ops before request parsing);
        failures report instead of raising — a dead child mid-drain is
        the kill case, not a crash."""
        import json
        try:
            with socket.create_connection(("127.0.0.1", self._port),
                                          timeout=10.0) as conn:
                conn.sendall((json.dumps(spec) + "\n").encode())
                raw = conn.makefile("rb").readline()
            return json.loads(raw) if raw else {"error": "no response"}
        except (OSError, ValueError, ConnectionError) as e:
            return {"error": f"{type(e).__name__}: {e}"}

    def drain_begin(self) -> None:
        resp = self._control({"op": "drain"})
        with self._lock:
            self._draining_flag = not resp.get("error")

    def draining(self) -> bool:
        with self._lock:
            return bool(getattr(self, "_draining_flag", False))

    def queued_depth(self) -> int:
        return int(self._control({"op": "drain_status"}
                                 ).get("queued") or 0)

    def warm_bucket_keys(self) -> list:
        keys = self._control({"op": "drain_status"}).get("warm_keys")
        return [tuple(k) for k in keys] if keys else []

    def slo_p99(self, slo: str):
        return None      # per-class tails stay in the child process

    def stats(self) -> dict:
        return self._control({"op": "drain_status"}).get("stats") or {}

    def prewarm(self, method: str, dtype: str, n: int, *,
                up_to_batch: int = 1) -> None:
        self._control({"op": "prewarm", "method": method, "type": dtype,
                       "n": int(n), "up_to_batch": int(up_to_batch)})

    def stop(self) -> None:
        for _ in self._threads:
            self._jobs.put(None)
        self.reap()

    def reap(self) -> Optional[str]:
        """INT-first teardown with bounded grace before escalation:
        SIGINT lets the child's KeyboardInterrupt path drain its
        engine (a SIGKILL to a child with a nonempty device queue is
        the machine-wedge hazard — CLAUDE.md), SIGTERM after
        `reap_grace_s`, SIGKILL only as the last resort another grace
        later. Returns the signal that ended it (or None if it was
        already gone) — the adoption probe's reap evidence."""
        if not self.alive():
            return None
        for sig_name, sig_no in (("int", signal.SIGINT),
                                 ("term", signal.SIGTERM),
                                 ("kill", signal.SIGKILL)):
            try:
                if self._proc is not None:
                    self._proc.send_signal(sig_no)
                else:
                    os.kill(self._pid, sig_no)
            except (ProcessLookupError, PermissionError, OSError):
                return None
            deadline = time.monotonic() + self._reap_grace_s
            while time.monotonic() < deadline:
                if not self.alive():
                    return sig_name
                time.sleep(0.05)
        return "kill"

    def kill(self) -> None:
        """Chaos seam: SIGKILL the child mid-traffic. In-flight
        round-trips fail to replica-dead errors and the router
        re-routes them."""
        self._mark_down("killed")
        if self._proc is not None:
            if self._proc.poll() is None:
                self._proc.kill()
        elif self._pid is not None:
            try:
                os.kill(self._pid, signal.SIGKILL)
            except (ProcessLookupError, PermissionError):
                pass


@dataclasses.dataclass
class _Routed:
    """Router-internal record of one in-flight routed request."""

    request: ReduceRequest
    router_id: str
    pending: PendingResponse          # the router's own slot
    t_submit: float
    attempts: int = 0
    tried: tuple = ()


class ReplicaRouter:
    """The scale-out front end (module docstring). Interface-compatible
    with ServeEngine where the front ends care: `submit(request) ->
    PendingResponse`, `start()`, `stop()`, `stats`."""

    def __init__(self, replicas: Sequence, *,
                 affinity_bytes: int = 1 << 20,
                 max_retries: int = 2,
                 journal: Optional[FleetJournal] = None) -> None:
        if not replicas:
            raise ValueError("need at least one replica")
        self._replicas = list(replicas)
        self._affinity_bytes = affinity_bytes
        self._max_retries = max_retries
        # the write-ahead fleet journal (serve/journal.py): with no
        # path it is a pure in-memory record, so every fleet mutation
        # below journals unconditionally — crash consistency is not an
        # opt-in code path that only the CLI exercises
        self._journal = journal if journal is not None \
            else FleetJournal(None)
        self._outstanding: Dict[str, int] = {
            r.replica_id: 0 for r in self._replicas}
        self._lock = threading.Lock()
        self._ids = itertools.count()
        self.stats: Dict[str, int] = {
            "routed": 0, "rerouted": 0, "drain_rerouted": 0,
            "affinity": 0, "balanced": 0, "no_replica": 0}

    @property
    def journal(self) -> FleetJournal:
        return self._journal

    def _journal_replica(self, replica, state: str) -> None:
        """Journal one replica transition, with whatever identity the
        replica shape exposes (ProcessReplica: port+pid; LocalReplica:
        name only — an in-process replica dies with the controller, so
        there is nothing to re-adopt and the record is for the
        narrative)."""
        self._journal.record_replica(
            replica.replica_id, state=state,
            port=getattr(replica, "port", None),
            pid=getattr(replica, "pid", None),
            platform=getattr(replica, "_platform", None),
            relay_port=getattr(replica, "_relay_port", None))

    # -- lifecycle ----------------------------------------------------

    def start(self) -> "ReplicaRouter":
        for r in self._replicas:
            already_up = bool(getattr(r, "adopted", False))
            if not already_up:
                # write-ahead: the journal knows about the child
                # BEFORE it exists, so a crash mid-spawn leaves a
                # "starting" record recovery probes and reaps
                self._journal_replica(r, "starting")
            r.start()
            self._journal_replica(r, "up")
        ledger.emit("route.start", replicas=len(self._replicas),
                    affinity_bytes=self._affinity_bytes,
                    max_retries=self._max_retries)
        return self

    def stop(self) -> None:
        for r in self._replicas:
            self._journal_replica(r, "down")
            r.stop()
            self._journal.forget_replica(r.replica_id)
        ledger.emit("route.stop", **{k: int(v)
                                     for k, v in self.stats.items()})

    @property
    def replicas(self) -> List:
        return list(self._replicas)

    # -- elastic fleet (serve/autoscale.py; docs/SERVING.md) ----------

    def add_replica(self, replica) -> None:
        """Scale-up seam: start the replica and admit it to routing —
        affinity hashes immediately include it (the autoscaler prewarms
        the hot keys first so recurrences don't pay a cold compile).
        Journals write-ahead: "starting" before the spawn, "up" once
        the port/pid exist."""
        if not getattr(replica, "adopted", False):
            self._journal_replica(replica, "starting")
        replica.start()
        with self._lock:
            self._replicas.append(replica)
            self._outstanding.setdefault(replica.replica_id, 0)
        self._journal_replica(replica, "up")

    def remove_replica(self, replica_id: str) -> None:
        """Scale-down seam: forget a replica AFTER its drain completed
        (serve/autoscale.drain_replica) — late `_on_result` callbacks
        from the removed replica tolerate the missing outstanding row."""
        self._journal.record_replica(replica_id, state="down")
        with self._lock:
            self._replicas = [r for r in self._replicas
                              if r.replica_id != replica_id]
            self._outstanding.pop(replica_id, None)
        self._journal.forget_replica(replica_id)

    def load_snapshot(self) -> dict:
        """The autoscaler's per-tick observable: per-replica
        outstanding + alive/draining flags + routing stats — the same
        signals route.* ledger events carry, read in-process."""
        with self._lock:
            outstanding = dict(self._outstanding)
            stats = dict(self.stats)
            replicas = [{"replica": r.replica_id, "alive": r.alive(),
                         "draining": _is_draining(r)}
                        for r in self._replicas]
        return {"outstanding": outstanding, "stats": stats,
                "replicas": replicas}

    def affinity_target(self, method: str, dtype: str, n: int,
                        exclude: tuple = ()):
        """The replica a warm bucket key would hash to once `exclude`
        (the drain victim) is gone — the handoff placement oracle: the
        drain prewarms each key exactly where future affinity routing
        will land it (same crc32 hash as `_pick`)."""
        with self._lock:
            alive = [r for r in self._replicas
                     if r.replica_id not in exclude and r.alive()
                     and not _is_draining(r)]
        if not alive:
            return None
        key = f"{method}:{dtype}:{n}"
        return alive[zlib.crc32(key.encode()) % len(alive)]

    # -- routing ------------------------------------------------------

    def submit(self, request: ReduceRequest) -> PendingResponse:
        """Route one request; always returns a PendingResponse that
        WILL resolve (the replicas' no-hang contract plus the
        no-alive-replica terminal error here)."""
        # chaos seam (faults/inject.py): a scripted `exit` here is the
        # deterministic SIGKILL-class controller death mid-burst the
        # recovery suite restarts from — os._exit, no atexit, no
        # drain; the children orphan alive with the journal as the
        # only record of them
        fault_point("router.crash")
        rid = f"g{next(self._ids):06d}"
        pending = PendingResponse(rid)
        routed = _Routed(request=request, router_id=rid,
                         pending=pending, t_submit=time.monotonic())
        self._dispatch(routed)
        return pending

    def _pick(self, request: ReduceRequest, tried: tuple):
        """(replica, policy) among alive replicas not yet tried for
        this request; (None, None) when none qualify. Small requests
        hash on the jit-bucket key for cache affinity; large ones go
        least-outstanding."""
        with self._lock:
            alive = [r for r in self._replicas
                     if r.replica_id not in tried and r.alive()
                     and not _is_draining(r)]
            if not alive:
                return None, None
            if request.nbytes <= self._affinity_bytes:
                key = f"{request.method}:{request.dtype}:{request.n}"
                idx = zlib.crc32(key.encode()) % len(alive)
                return alive[idx], "affinity"
            return min(alive, key=lambda r: self._outstanding[
                r.replica_id]), "balanced"

    def _dispatch(self, routed: _Routed) -> None:
        replica, policy = self._pick(routed.request, routed.tried)
        if replica is None:
            self.stats["no_replica"] += 1
            self._finish(routed, None, ReduceResponse(
                routed.router_id, "error", routed.request.method,
                routed.request.dtype, routed.request.n,
                error=("no-replica-alive: all replicas dead or "
                       "already tried for this request")))
            return
        routed.attempts += 1
        routed.tried += (replica.replica_id,)
        self.stats["routed"] += 1
        self.stats[policy] += 1
        if policy == "affinity":
            # journal the bucket placement (deduped inside): recovery
            # re-prewarms exactly the keys traffic has made hot, onto
            # the replicas the post-adoption hash will route them to
            r = routed.request
            self._journal.record_placement(r.method, r.dtype, r.n)
        with self._lock:
            self._outstanding[replica.replica_id] += 1
        ledger.emit("route.request", req=routed.router_id,
                    replica=replica.replica_id, policy=policy,
                    attempt=routed.attempts,
                    **trace.request_fields(routed.router_id))
        inner = replica.submit(routed.request)
        inner.add_done_callback(
            lambda resp, rep=replica: self._on_result(routed, rep, resp))

    def _on_result(self, routed: _Routed, replica,
                   resp: ReduceResponse) -> None:
        with self._lock:
            if replica.replica_id in self._outstanding:
                self._outstanding[replica.replica_id] = max(
                    0, self._outstanding[replica.replica_id] - 1)
        if replica_draining(resp):
            # planned scale-down is not a failure: re-route WITHOUT
            # consuming a max_retries attempt (ISSUE 17 satellite 1 —
            # a max_retries=0 fleet still drains losslessly); `tried`
            # keeps the victim so an all-draining fleet terminates at
            # the no-replica-alive error instead of looping
            routed.attempts -= 1
            self.stats["drain_rerouted"] += 1
            ledger.emit("route.reroute", req=routed.router_id,
                        replica=replica.replica_id,
                        attempt=routed.attempts,
                        reason=(resp.error or "")[:120],
                        **trace.request_fields(routed.router_id))
            self._dispatch(routed)
            return
        if replica_failure(resp) \
                and routed.attempts <= self._max_retries:
            self.stats["rerouted"] += 1
            ledger.emit("route.reroute", req=routed.router_id,
                        replica=replica.replica_id,
                        attempt=routed.attempts,
                        reason=(resp.error or "")[:120],
                        **trace.request_fields(routed.router_id))
            self._dispatch(routed)
            return
        self._finish(routed, replica, resp)

    def _finish(self, routed: _Routed, replica,
                resp: ReduceResponse) -> None:
        out = dataclasses.replace(
            resp, request_id=routed.router_id,
            latency_s=round(time.monotonic() - routed.t_submit, 6))
        ledger.emit("route.done", req=routed.router_id,
                    replica=(replica.replica_id if replica else None),
                    status=out.status, latency_s=out.latency_s,
                    attempts=routed.attempts,
                    **trace.request_fields(routed.router_id))
        routed.pending.resolve(out)


def adopt_fleet(journal: FleetJournal, *,
                request_timeout_s: float = 600.0,
                reap_grace_s: float = 5.0):
    """Recover a dead controller's fleet from its journal
    (docs/SERVING.md "crash-consistent control plane"): probe every
    journaled replica over the existing TCP wire and split the fleet
    into (adopted, reaped) — still-serving children come back as
    `ProcessReplica.adopt` handles ready for a new router; everything
    else (never came up, pid gone, wedged engine) is reaped INT-first
    with bounded grace (never SIGKILL-first: a child mid-device-queue
    is the machine-wedge hazard) and forgotten from the journal.
    `adopt.done`'s wall_s IS the controller-MTTR evidence the recovery
    artifact commits."""
    entries = journal.replicas()
    t0 = time.monotonic()
    ledger.emit("adopt.begin", candidates=len(entries))
    adopted: List[ProcessReplica] = []
    reaped: List[str] = []
    for name in sorted(entries):
        entry = entries[name]
        port, pid = entry.get("port"), entry.get("pid")
        if port is None or pid is None or entry.get("state") == "down":
            # never came up (write-ahead "starting" with no port) or
            # already retired: nothing to probe, nothing to adopt
            verdict = "stale"
            journal.forget_replica(name)
        else:
            rep = ProcessReplica.adopt(
                name, port=int(port), pid=int(pid),
                platform=entry.get("platform") or "cpu",
                relay_port=entry.get("relay_port"),
                request_timeout_s=request_timeout_s,
                reap_grace_s=reap_grace_s)
            if rep.alive() and rep.ping():
                verdict = "adopted"
                adopted.append(rep)
            else:
                sig = rep.reap()
                verdict = f"reaped-{sig}" if sig else "gone"
                reaped.append(name)
                journal.forget_replica(name)
        ledger.emit("adopt.replica", replica=name, verdict=verdict,
                    port=port, pid=pid)
    ledger.emit("adopt.done", adopted=len(adopted), reaped=len(reaped),
                wall_s=round(time.monotonic() - t0, 6))
    return adopted, reaped


def reprewarm_placements(router: ReplicaRouter) -> int:
    """Re-prewarm every journaled bucket-affinity placement onto the
    replica the CURRENT alive set hashes it to — the recovery twin of
    the drain handoff: the adopted fleet's compile caches end up where
    post-recovery affinity routing will actually land the keys."""
    warmed = 0
    for method, dtype, n in router.journal.placements():
        target = router.affinity_target(method, dtype, int(n))
        if target is None:
            continue
        try:
            target.prewarm(method, dtype, int(n))
            warmed += 1
        except (OSError, ValueError, RuntimeError):
            continue
    return warmed


def local_router(n_replicas: int, *, engine_kwargs: Optional[dict] = None,
                 affinity_bytes: int = 1 << 20,
                 max_retries: int = 2) -> ReplicaRouter:
    """N in-process engine replicas behind one router — the loadgen's
    scaling-series construction (and the chaos tests': each engine can
    be handed its own transport through engine_kwargs['transports'])."""
    from tpu_reductions.serve.engine import ServeEngine
    kwargs = dict(engine_kwargs or {})
    transports = kwargs.pop("transports", None)
    replicas = []
    for i in range(n_replicas):
        kw = dict(kwargs)
        if transports is not None:
            kw["transport"] = transports[i]
        replicas.append(LocalReplica(f"replica-{i}", ServeEngine(**kw)))
    return ReplicaRouter(replicas, affinity_bytes=affinity_bytes,
                         max_retries=max_retries)


def main(argv=None) -> int:
    """CLI: the process-per-replica tier in one command — spawn N
    `python -m tpu_reductions.serve` children, route over them, serve
    the same TCP JSON-lines wire the single engine speaks (so every
    existing client just points at the router port instead)."""
    import argparse

    from tpu_reductions.config import _apply_platform

    p = argparse.ArgumentParser(
        prog="tpu_reductions.serve.router",
        description="Replica router over process-per-replica serving "
                    "engines (docs/SERVING.md scaling tier)")
    p.add_argument("--replicas", type=int, default=2)
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=0,
                   help="0 = ephemeral (printed + --port-file)")
    p.add_argument("--port-file", default=None)
    p.add_argument("--affinity-bytes", type=int, default=1 << 20,
                   help="requests at or under this hash-route for jit "
                        "bucket affinity; larger ones load-balance")
    p.add_argument("--max-retries", type=int, default=2,
                   help="re-route attempts after a replica failure")
    p.add_argument("--request-timeout-s", type=float, default=600.0)
    p.add_argument("--max-seconds", type=float, default=None)
    p.add_argument("--platform", default=None, choices=("cpu", "tpu"))
    p.add_argument("--relay-port", type=int, default=None,
                   help="every replica gates launches on this relay "
                        "port (chaos rehearsals: faults/relay.py)")
    p.add_argument("--journal", default=None,
                   help="fleet journal path (default: "
                        "TPU_REDUCTIONS_FLEET_JOURNAL env, else "
                        "journaling off). A restart against a journal "
                        "a dead controller left behind re-adopts its "
                        "still-live replica children, reaps the rest "
                        "INT-first, resumes the autoscaler "
                        "mid-cooldown, and re-prewarms journaled "
                        "placements (docs/SERVING.md crash-consistent "
                        "control plane)")
    p.add_argument("--autoscale", action="store_true",
                   help="run the elastic autoscaler over the fleet "
                        "(serve/autoscale.py), its control state "
                        "journaled per tick and resumed on restart")
    ns = p.parse_args(argv)
    _apply_platform(ns)

    from tpu_reductions.obs.ledger import arm_session
    arm_session("serve.router", argv=list(argv) if argv
                else sys.argv[1:])
    from tpu_reductions.exec.core import maybe_arm_for_tpu
    maybe_arm_for_tpu()   # the autoscaler's drain path touches devices

    if ns.replicas <= 0:
        p.error("--replicas must be positive")

    from tpu_reductions.config import fleet_journal_path
    journal = FleetJournal(fleet_journal_path(ns.journal))
    adopted, _ = adopt_fleet(
        journal, request_timeout_s=ns.request_timeout_s) \
        if journal.replicas() else ([], [])

    def spawn(i: int) -> ProcessReplica:
        return ProcessReplica(f"replica-{i}", platform=ns.platform,
                              relay_port=ns.relay_port,
                              request_timeout_s=ns.request_timeout_s)

    taken = {r.replica_id for r in adopted}
    replicas: List = list(adopted)
    i = 0
    while len(replicas) < ns.replicas:
        if f"replica-{i}" not in taken:
            replicas.append(spawn(i))
        i += 1
    router = ReplicaRouter(replicas,
                           affinity_bytes=ns.affinity_bytes,
                           max_retries=ns.max_retries,
                           journal=journal).start()
    if adopted:
        reprewarm_placements(router)

    autoscaler = None
    if ns.autoscale:
        from tpu_reductions.serve.autoscale import Autoscaler
        autoscaler = Autoscaler(router, spawn, journal=journal)
        autoscaler.restore_state(journal.autoscaler_state())
        autoscaler.start()

    import socketserver

    from tpu_reductions.serve.__main__ import _Server, _make_handler
    server = _Server((ns.host, ns.port),
                     _make_handler(router, ns.request_timeout_s))
    port = server.server_address[1]
    print(f"routing {ns.replicas} replicas on {ns.host}:{port}",
          flush=True)
    if ns.port_file:
        from tpu_reductions.utils.jsonio import atomic_text_dump
        atomic_text_dump(ns.port_file, f"{port}\n")
    t = threading.Thread(target=server.serve_forever, daemon=True)
    t.start()
    try:
        if ns.max_seconds is None:
            while True:
                time.sleep(0.5)
        else:
            time.sleep(ns.max_seconds)
    except KeyboardInterrupt:
        pass
    finally:
        server.shutdown()
        if autoscaler is not None:
            autoscaler.stop()
        router.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
