"""The ONE device-touching module of the serving layer (RED014).

A coalesced batch of k compatible requests (same method/dtype/n)
executes as ONE stacked device call: payloads stack into a (k, n)
array, rows pad to the next power of two with the op's monoid
identity (ops/registry.py — identity rows cannot perturb any result),
and a single jitted row-reduce produces all k scalars. This is
run_benchmark_batch's machinery (bench/driver.py: many configs, one
process, dispatch amortized) reduced to its serving essence — the
whole point of coalescing is that k requests pay one dispatch, one
trace-cache lookup and one transfer instead of k.

Bucketed padding keeps the jit cache small: every batch size k serves
from one of log2(max_batch)+1 executables per (method, dtype, n)
instead of one per k — the serving analog of the compile-budget
doctrine (a recompile through the tunnel costs 20-40 s; CLAUDE.md).

Device failures flow through the same classification as the bench:
`utils/retry.py` retries transient flaps under a heartbeat guard and
re-raises dead-relay/deterministic errors to the engine's
shed/containment path. Verification is the bench's own oracle
(ops/oracle.py), per request, against each request's deterministic
payload.

All jax imports are local to the methods: constructing a
BatchExecutor is free and jax-free (the engine builds one eagerly;
only the first capability query or launch pays backend init — after
the entry point's watchdog/preflight gates have run).
"""

from __future__ import annotations

import functools
from typing import Dict, List, Optional

import numpy as np

from tpu_reductions.faults.inject import fault_point


def _bucket(k: int) -> int:
    """Next power of two >= k (the jit-cache bucketing contract)."""
    b = 1
    while b < k:
        b <<= 1
    return b


# (method, dtype, n, padded-k) keys whose first launch was already
# bracketed in a compile observatory span (run_batch below)
_observed_buckets: set = set()


@functools.lru_cache(maxsize=8)
def _jit_row_reduce(method: str):
    """One jitted stacked row-reduce per op; jax's own trace cache
    fans it out per (dtype, padded-k, n) shape — the template fan-out
    role of ops/registry.py's jit retracing, bucketed by _bucket."""
    import jax

    from tpu_reductions.ops.registry import get_op
    op = get_op(method)
    return jax.jit(lambda x: op.jnp_reduce(x, axis=1))


class BatchExecutor:
    """Fused stacked launches for the serving engine (module
    docstring). The engine calls exactly two things: `capabilities()`
    (admission's dtype gate) and `run_batch(...)`."""

    def __init__(self) -> None:
        self._caps: Optional[dict] = None

    def capabilities(self) -> dict:
        """{'backend': str, 'supports_f64': bool}, resolved lazily and
        cached — admission only pays backend discovery when a request
        actually needs the answer (float64), and only after the entry
        point's pre-JAX gates have run (utils/watchdog.py RED011
        doctrine)."""
        if self._caps is None:
            import jax
            backend = jax.default_backend()
            # float64 on the TPU device wedges the axon tunnel
            # machine-wide (CLAUDE.md); off-TPU it additionally needs
            # x64 already enabled — the serving engine never toggles
            # global jax state mid-traffic (utils/x64.py is the bench's
            # scoped exception, unusable under concurrent tenants)
            supports_f64 = backend != "tpu" and \
                bool(jax.config.jax_enable_x64)
            self._caps = {"backend": backend,
                          "supports_f64": supports_f64}
        return self._caps

    def run_batch(self, method: str, dtype: str, n: int,
                  seeds: List[int]) -> List[Dict]:
        """Execute one coalesced batch; returns one dict per request
        (in seed order): {'result', 'ok', 'host', 'diff'}. Raises on
        device failure after the retry wrapper's classification — the
        engine contains the crash to the batch (the crash_result
        discipline of bench/driver.py, response-shaped)."""
        from tpu_reductions.ops import oracle as oracle_mod
        from tpu_reductions.ops.registry import get_op
        from tpu_reductions.utils.retry import retry_device_call
        from tpu_reductions.utils.rng import host_data

        # chaos hook: one coalesced launch = one interruptible unit,
        # the serving analog of bench.run (faults/inject.py;
        # docs/RESILIENCE.md fault-point table)
        fault_point("serve.batch")

        op = get_op(method)
        payloads = []
        for seed in seeds:
            x = oracle_mod.native_fill(n, dtype, rank=0, seed=seed)
            if x is None:
                x = host_data(n, dtype, rank=0, seed=seed)
            payloads.append(x)
        k = len(payloads)
        kb = _bucket(k)
        stacked = np.stack(payloads)
        if kb > k:
            pad = np.full((kb - k, n), op.identity(stacked.dtype),
                          dtype=stacked.dtype)
            stacked = np.concatenate([stacked, pad])

        fn = _jit_row_reduce(method)

        def launch():
            import jax
            # jit ingests the host array directly (one bounded
            # transfer: admission + the batcher's byte cap keep every
            # stacked payload under the 512 MiB single-message bound)
            return np.asarray(jax.device_get(fn(stacked)))

        # compile observatory (obs/compile.py): the first launch of a
        # (method, dtype, n, bucket) key is the bucket's trace+compile
        # point — engine.prewarm drives exactly these — so it runs
        # inside a compile_span and lands in the ledger with its
        # cold/warm cache verdict; steady-state launches pay one set
        # lookup
        bucket_key = (method, dtype, n, kb)
        if bucket_key not in _observed_buckets:
            _observed_buckets.add(bucket_key)
            from tpu_reductions.obs.compile import compile_span
            with compile_span(f"serve-bucket/{method.lower()}",
                              dtype=dtype, n=n, batch=kb):
                vals = retry_device_call(launch, phase="serve")[:k]
        else:
            vals = retry_device_call(launch, phase="serve")[:k]

        out: List[Dict] = []
        for i, seed in enumerate(seeds):
            host = oracle_mod.host_reduce(payloads[i], method)
            ok, diff = oracle_mod.verify(vals[i], host, method, dtype, n)
            out.append({
                "result": float(np.asarray(vals[i], dtype=np.float64)),
                "ok": bool(ok),
                "host": float(np.asarray(host, dtype=np.float64)),
                "diff": float(diff),
            })
        return out

    def run_stream(self, method: str, dtype: str, n: int, seed: int,
                   *, chunk_bytes: Optional[int] = None,
                   sync_every: int = 8) -> Dict:
        """Execute ONE oversized request through the streaming
        pipeline (ops/stream.py; docs/STREAMING.md): bounded chunks
        double-buffered against on-device accumulation, so the payload
        that the per-request byte cap used to reject outright — it
        could reconstruct the 4 GiB single-message relay killer — now
        serves in O(2 chunks) of device memory with no message ever
        exceeding the staging bound. Verification is the incremental
        chunk-wise oracle (ops/oracle.IncrementalOracle), so the host
        side never needs a second full-payload pass either. Same retry
        classification and response shape as run_batch."""
        from tpu_reductions.ops import oracle as oracle_mod
        from tpu_reductions.ops.stream import (iter_chunks, plan_chunks,
                                               run_stream)
        from tpu_reductions.utils.retry import retry_device_call
        from tpu_reductions.utils.rng import host_data

        fault_point("serve.batch")   # same interruptible-unit hook as
        #                              a coalesced launch

        x = oracle_mod.native_fill(n, dtype, rank=0, seed=seed)
        if x is None:
            x = host_data(n, dtype, rank=0, seed=seed)

        res = retry_device_call(
            lambda: run_stream(x, method, chunk_bytes=chunk_bytes,
                               sync_every=sync_every),
            phase="serve")

        oracle = oracle_mod.IncrementalOracle(method, dtype)
        for chunk in iter_chunks(x, plan_chunks(n, dtype, chunk_bytes)):
            oracle.update(chunk)
        ok, diff = oracle_mod.verify(res.value, oracle.value(),
                                     method, dtype, n)
        return {
            "result": float(np.asarray(res.value, dtype=np.float64)),
            "ok": bool(ok),
            "host": float(np.asarray(oracle.value(), dtype=np.float64)),
            "diff": float(diff),
            "chunks": res.num_chunks,
            "gbps": round(res.gbps, 4),
        }
