"""The ONE device-touching module of the serving layer (RED014).

A coalesced batch of k compatible requests (same method/dtype/n)
executes as ONE stacked device call: payloads stack into a (k, n)
array, rows pad to the next power of two with the op's monoid
identity (ops/registry.py — identity rows cannot perturb any result),
and a single jitted row-reduce produces all k scalars. This is
run_benchmark_batch's machinery (bench/driver.py: many configs, one
process, dispatch amortized) reduced to its serving essence — the
whole point of coalescing is that k requests pay one dispatch, one
trace-cache lookup and one transfer instead of k.

Bucketed padding keeps the jit cache small: every batch size k serves
from one of log2(max_batch)+1 executables per (method, dtype, n)
instead of one per k — the serving analog of the compile-budget
doctrine (a recompile through the tunnel costs 20-40 s; CLAUDE.md).

Device failures flow through the same classification as the bench:
`utils/retry.py` retries transient flaps under a heartbeat guard and
re-raises dead-relay/deterministic errors to the engine's
shed/containment path. Verification is the bench's own oracle
(ops/oracle.py), per request, against each request's deterministic
payload.

All jax imports are local to the methods: constructing a
BatchExecutor is free and jax-free (the engine builds one eagerly;
only the first capability query or launch pays backend init — after
the entry point's watchdog/preflight gates have run).
"""

from __future__ import annotations

import functools
from typing import Dict, List, Optional

import numpy as np

from tpu_reductions.faults.inject import fault_point


def _bucket(k: int) -> int:
    """Next power of two >= k (the jit-cache bucketing contract)."""
    b = 1
    while b < k:
        b <<= 1
    return b


# (method, dtype, n, padded-k) keys whose first launch was already
# bracketed in a compile observatory span (run_batch below)
_observed_buckets: set = set()


@functools.lru_cache(maxsize=16)
def _jit_shard_fold(method: str, acc_dtype: str, width: int):
    """Jitted (acc, chunk2d) -> acc fold for the device-parallel path:
    ops/stream._jit_fold widened to a `width`-block accumulator so the
    per-device partial is long enough for the quantized collective
    ring's block alignment (collectives/quant.quant_ring_applies). One
    executable per (method, acc dtype, width); jax dispatches it on
    whichever device the arguments are committed to, so all shards
    share it."""
    import jax

    from tpu_reductions.ops.registry import get_op
    from tpu_reductions.ops.stream import _LANES, _SUBLANES
    op = get_op(method)

    def fold(acc, chunk2d):
        folded = op.jnp_reduce(
            chunk2d.reshape(-1, width * _SUBLANES, _LANES), axis=0)
        return op.jnp_combine(acc, folded.astype(acc.dtype))

    return jax.jit(fold)


@functools.lru_cache(maxsize=1)
def _jit_flatten():
    """Jitted on-device reshape (W*SUBLANES, LANES) -> (W*BLOCK,): the
    per-device accumulator becomes one shard of the collective's
    global array without a host round-trip."""
    import jax
    return jax.jit(lambda a: a.reshape(-1))


@functools.lru_cache(maxsize=8)
def _jit_row_reduce(method: str):
    """One jitted stacked row-reduce per op; jax's own trace cache
    fans it out per (dtype, padded-k, n) shape — the template fan-out
    role of ops/registry.py's jit retracing, bucketed by _bucket."""
    import jax

    from tpu_reductions.ops.registry import get_op
    op = get_op(method)
    return jax.jit(lambda x: op.jnp_reduce(x, axis=1))


class BatchExecutor:
    """Fused stacked launches for the serving engine (module
    docstring). The engine calls exactly two things: `capabilities()`
    (admission's dtype gate) and `run_batch(...)`."""

    def __init__(self) -> None:
        self._caps: Optional[dict] = None

    def capabilities(self) -> dict:
        """{'backend': str, 'supports_f64': bool}, resolved lazily and
        cached — admission only pays backend discovery when a request
        actually needs the answer (float64), and only after the entry
        point's pre-JAX gates have run (utils/watchdog.py RED011
        doctrine)."""
        if self._caps is None:
            import jax
            backend = jax.default_backend()
            # float64 on the TPU device wedges the axon tunnel
            # machine-wide (CLAUDE.md); off-TPU it additionally needs
            # x64 already enabled — the serving engine never toggles
            # global jax state mid-traffic (utils/x64.py is the bench's
            # scoped exception, unusable under concurrent tenants)
            supports_f64 = backend != "tpu" and \
                bool(jax.config.jax_enable_x64)
            self._caps = {"backend": backend,
                          "supports_f64": supports_f64,
                          # the engine's shard gate: device-parallel
                          # oversized requests need >1 local device
                          "device_count": len(jax.local_devices())}
        return self._caps

    def run_batch(self, method: str, dtype: str, n: int,
                  seeds: List[int]) -> List[Dict]:
        """Execute one coalesced batch; returns one dict per request
        (in seed order): {'result', 'ok', 'host', 'diff'}. Raises on
        device failure after the retry wrapper's classification — the
        engine contains the crash to the batch (the crash_result
        discipline of bench/driver.py, response-shaped)."""
        from tpu_reductions.config import FAMILY_METHODS
        from tpu_reductions.exec import core as exec_core
        from tpu_reductions.exec.plan import launch_plan
        from tpu_reductions.ops import oracle as oracle_mod
        from tpu_reductions.ops.registry import get_op
        from tpu_reductions.utils.rng import host_data

        method = method.upper()
        # the reduction family (SCAN/SEG*/ARG* — ISSUE 20,
        # docs/FAMILY.md) coalesces through the same engine but
        # launches per method group, not as a padded row-reduce
        if method in FAMILY_METHODS:
            return self._run_family_batch(method, dtype, n, seeds)

        # chaos hook: one coalesced launch = one interruptible unit,
        # the serving analog of bench.run (faults/inject.py;
        # docs/RESILIENCE.md fault-point table)
        fault_point("serve.batch")

        op = get_op(method)
        payloads = []
        for seed in seeds:
            x = oracle_mod.native_fill(n, dtype, rank=0, seed=seed)
            if x is None:
                x = host_data(n, dtype, rank=0, seed=seed)
            payloads.append(x)
        k = len(payloads)
        kb = _bucket(k)
        stacked = np.stack(payloads)
        if kb > k:
            pad = np.full((kb - k, n), op.identity(stacked.dtype),
                          dtype=stacked.dtype)
            stacked = np.concatenate([stacked, pad])

        fn = _jit_row_reduce(method)

        def launch():
            import jax
            # jit ingests the host array directly (one bounded
            # transfer: admission + the batcher's byte cap keep every
            # stacked payload under the 512 MiB single-message bound)
            return np.asarray(jax.device_get(fn(stacked)))

        # the bucket launch is ONE LaunchPlan (exec/core.py): the
        # executor owns the retry classification + "serve" heartbeat
        # guard the old inline retry_device_call spelled here
        plan = launch_plan(f"serve-bucket/{method.lower()}", "serve",
                           lambda ctx: launch(), timing="serve",
                           heartbeat_phase="serve", retry=True,
                           drain=True, method=method, dtype=dtype,
                           n=n, batch=kb)
        # compile observatory (exec_core.observe_compile): the first
        # launch of a (method, dtype, n, bucket) key is the bucket's
        # trace+compile point — engine.prewarm drives exactly these —
        # so it runs inside a compile span and lands in the ledger with
        # its cold/warm cache verdict; steady-state launches pay one
        # set lookup
        bucket_key = (method, dtype, n, kb)
        if bucket_key not in _observed_buckets:
            _observed_buckets.add(bucket_key)
            with exec_core.observe_compile(plan.surface, dtype=dtype,
                                           n=n, batch=kb):
                vals = exec_core.run(plan)[:k]
        else:
            vals = exec_core.run(plan)[:k]

        out: List[Dict] = []
        for i, seed in enumerate(seeds):
            host = oracle_mod.host_reduce(payloads[i], method)
            ok, diff = oracle_mod.verify(vals[i], host, method, dtype, n)
            out.append({
                "result": float(np.asarray(vals[i], dtype=np.float64)),
                "ok": bool(ok),
                "host": float(np.asarray(host, dtype=np.float64)),
                "diff": float(diff),
            })
        return out

    # segments per served segmented request: small enough that the
    # offset vector is wire-trivial, large enough to exercise ragged
    # and (by the random-cut construction) occasionally empty segments
    _SERVE_SEGMENTS = 8

    def _run_family_batch(self, method: str, dtype: str, n: int,
                          seeds: List[int]) -> List[Dict]:
        """Coalesced launch for one family method group (ISSUE 20;
        docs/FAMILY.md), same response shape as run_batch:

          SCAN    k requests stack to (k, n); the impl (mxu-scan vs
                  xla-cumsum) is a cost-oracle decision
                  (exec/cost.pick_scan, exec.select-audited); the
                  served scalar is the scan digest (last prefix =
                  full SUM).
          SEG*    the RAGGED path: k offset-vector payloads
                  concatenate into ONE flat array with globally
                  renumbered segment ids and launch a single
                  segment reduce — no identity padding to the
                  bucket's power of two, the whole point of
                  segmented serving.
          ARG*    k requests stack to (k, n); one lexicographic
                  (key, index) row reduce returns all k extreme
                  indices, exact with lowest-index ties
                  (ops/family/argreduce.py).

        Each launch is one LaunchPlan through exec.core.run (RED025)
        and lands a `family.serve` ledger event."""
        from tpu_reductions.exec import core as exec_core
        from tpu_reductions.exec.cost import CostOracle, emit_select
        from tpu_reductions.exec.plan import launch_plan
        from tpu_reductions.obs import ledger
        from tpu_reductions.ops import oracle as oracle_mod
        from tpu_reductions.ops.family import (SEG_BASE,
                                               arg_reduce_rows_fn,
                                               host_segment_reduce,
                                               random_offsets,
                                               scan_rows_fn,
                                               segment_ids_from_offsets,
                                               segment_reduce_fn)
        from tpu_reductions.utils.rng import host_data

        fault_point("serve.batch")   # same interruptible-unit hook as
        #                              a classic coalesced launch

        payloads = []
        for seed in seeds:
            x = oracle_mod.native_fill(n, dtype, rank=0, seed=seed)
            if x is None:
                x = host_data(n, dtype, rank=0, seed=seed)
            payloads.append(np.ravel(x))
        k = len(payloads)

        if method == "SCAN":
            decision = CostOracle().pick_scan(dtype, n)
            emit_select(decision, method=method, dtype=dtype, n=n,
                        batch=k)
            fn = scan_rows_fn(decision.choice, dtype)
            stacked = np.stack(payloads)
            surface = f"family-scan/{decision.choice}"

            def launch():
                import jax
                # jit ingests the host stack directly — the same
                # bounded-transfer argument as run_batch's launch
                return np.asarray(jax.device_get(fn(stacked)))[:, -1]
        elif method in SEG_BASE:
            offsets = [random_offsets(n, self._SERVE_SEGMENTS, seed)
                       for seed in seeds]
            s = self._SERVE_SEGMENTS
            flat = np.concatenate(payloads)
            ids = np.concatenate(
                [np.int32(i * s) + segment_ids_from_offsets(off)
                 for i, off in enumerate(offsets)]).astype(np.int32)
            # (k, s) mask of non-empty segments: empty segments come
            # back as the op's monoid identity (+-inf for float
            # MIN/MAX), which must not poison the digest sum — both
            # sides drop them identically
            nonempty = np.stack([np.diff(off) > 0 for off in offsets])
            fn = segment_reduce_fn(method, k * s)
            surface = f"family-seg/{method.lower()}"

            def launch():
                import jax
                segs = np.asarray(jax.device_get(fn(flat, ids)))
                # per-request digest: float64 sum of its non-empty
                # per-segment results (per-segment values are the real
                # payload; the digest is only the scalar the wire
                # carries back)
                segs = segs.astype(np.float64).reshape(k, s)
                return np.where(nonempty, segs, 0.0).sum(axis=1)
        else:   # ARGMIN / ARGMAX
            fn = arg_reduce_rows_fn(method, dtype)
            stacked = np.stack(payloads)
            surface = f"family-argk/{method.lower()}"

            def launch():
                import jax
                return np.asarray(jax.device_get(fn(stacked)))

        plan = launch_plan(surface, "serve", lambda ctx: launch(),
                           timing="serve", heartbeat_phase="serve",
                           retry=True, drain=True, method=method,
                           dtype=dtype, n=n, batch=k)
        # first launch per (surface, dtype, n) is the group's
        # trace+compile point — same observatory discipline as the
        # classic bucket launch above
        bucket_key = (surface, dtype, n, _bucket(k))
        if bucket_key not in _observed_buckets:
            _observed_buckets.add(bucket_key)
            with exec_core.observe_compile(plan.surface, dtype=dtype,
                                           n=n, batch=k):
                vals = exec_core.run(plan)
        else:
            vals = exec_core.run(plan)

        out: List[Dict] = []
        ok_count = 0
        for i in range(k):
            if method in SEG_BASE:
                segs_h = host_segment_reduce(payloads[i], offsets[i],
                                             method)
                host = float(segs_h[nonempty[i]].sum())
                # the digest is a SUM of per-segment results, so it
                # verifies under SUM's tolerance class (SEGMIN/SEGMAX
                # per-segment values are exact, making the digest
                # exact too)
                ok, diff = oracle_mod.verify(vals[i], host, "SUM",
                                             dtype, n)
            else:
                host = oracle_mod.host_reduce(payloads[i], method)
                ok, diff = oracle_mod.verify(vals[i], host, method,
                                             dtype, n)
            ok_count += bool(ok)
            out.append({
                "result": float(np.asarray(vals[i], dtype=np.float64)),
                "ok": bool(ok),
                "host": float(np.asarray(host, dtype=np.float64)),
                "diff": float(diff),
            })
        ledger.emit("family.serve", method=method, dtype=dtype, n=n,
                    batch=k, surface=surface, ok=ok_count,
                    failed=k - ok_count)
        return out

    def run_stream(self, method: str, dtype: str, n: int, seed: int,
                   *, chunk_bytes: Optional[int] = None,
                   sync_every: int = 8) -> Dict:
        """Execute ONE oversized request through the streaming
        pipeline (ops/stream.py; docs/STREAMING.md): bounded chunks
        double-buffered against on-device accumulation, so the payload
        that the per-request byte cap used to reject outright — it
        could reconstruct the 4 GiB single-message relay killer — now
        serves in O(2 chunks) of device memory with no message ever
        exceeding the staging bound. Verification is the incremental
        chunk-wise oracle (ops/oracle.IncrementalOracle), so the host
        side never needs a second full-payload pass either. Same retry
        classification and response shape as run_batch."""
        from tpu_reductions.config import FAMILY_METHODS
        from tpu_reductions.exec import core as exec_core
        from tpu_reductions.exec.plan import launch_plan
        from tpu_reductions.ops import oracle as oracle_mod
        from tpu_reductions.ops.stream import (iter_chunks, plan_chunks,
                                               run_stream)
        from tpu_reductions.utils.rng import host_data

        method = method.upper()
        if method in FAMILY_METHODS and method != "SCAN":
            # segmented/arg requests carry whole-payload structure the
            # chunk fold cannot carry across a boundary yet — they stay
            # under the coalesced-batch size cap (docs/FAMILY.md)
            raise ValueError(f"{method} has no streaming path; only "
                             "SCAN chunk-carries (ops/family/scan.py)")
        if method == "SCAN":
            return self._run_stream_scan(dtype, n, seed,
                                         chunk_bytes=chunk_bytes)

        fault_point("serve.batch")   # same interruptible-unit hook as
        #                              a coalesced launch

        x = oracle_mod.native_fill(n, dtype, rank=0, seed=seed)
        if x is None:
            x = host_data(n, dtype, rank=0, seed=seed)

        res = exec_core.run(launch_plan(
            f"serve-stream/{method.lower()}", "serve",
            lambda ctx: run_stream(x, method, chunk_bytes=chunk_bytes,
                                   sync_every=sync_every),
            timing="stream", heartbeat_phase="serve", retry=True,
            drain=True, method=method, dtype=dtype, n=n))

        oracle = oracle_mod.IncrementalOracle(method, dtype)
        for chunk in iter_chunks(x, plan_chunks(n, dtype, chunk_bytes)):
            oracle.update(chunk)
        ok, diff = oracle_mod.verify(res.value, oracle.value(),
                                     method, dtype, n)
        return {
            "result": float(np.asarray(res.value, dtype=np.float64)),
            "ok": bool(ok),
            "host": float(np.asarray(oracle.value(), dtype=np.float64)),
            "diff": float(diff),
            "chunks": res.num_chunks,
            "gbps": round(res.gbps, 4),
        }

    def _run_stream_scan(self, dtype: str, n: int, seed: int, *,
                         chunk_bytes: Optional[int] = None) -> Dict:
        """Oversized SCAN through the chunk-carry scanner
        (ops/family/scan.StreamScanner; docs/FAMILY.md): per bounded
        chunk y = scan(chunk) + carry, carry' = y[-1], so an
        arbitrarily large prefix sum serves under the <= 2-chunk
        device-residency bound. The served scalar is the scan digest
        (final carry = full SUM), verified against the incremental
        oracle — same response shape as run_stream."""
        import time as _time

        from tpu_reductions.exec import core as exec_core
        from tpu_reductions.exec.plan import launch_plan
        from tpu_reductions.ops import oracle as oracle_mod
        from tpu_reductions.ops.family.scan import StreamScanner
        from tpu_reductions.ops.stream import iter_chunks, plan_chunks
        from tpu_reductions.utils.rng import host_data

        fault_point("serve.batch")

        x = oracle_mod.native_fill(n, dtype, rank=0, seed=seed)
        if x is None:
            x = host_data(n, dtype, rank=0, seed=seed)
        x = np.ravel(x)

        sc = StreamScanner(dtype, n, chunk_bytes=chunk_bytes)
        t0 = _time.perf_counter()
        exec_core.run(launch_plan(
            "serve-stream/scan", "serve",
            lambda ctx: sc.scan(x, call=lambda fn: ctx.call(
                fn, phase="serve")),
            timing="stream", heartbeat_phase=None, retry=False,
            drain=True, staging_bound=int(sc.plan.chunk_bytes),
            method="SCAN", dtype=dtype, n=n))
        wall = _time.perf_counter() - t0
        digest = sc.carry

        oracle = oracle_mod.IncrementalOracle("SCAN", dtype)
        for chunk in iter_chunks(x, plan_chunks(n, dtype, chunk_bytes)):
            oracle.update(chunk)
        ok, diff = oracle_mod.verify(digest, oracle.value(),
                                     "SCAN", dtype, n)
        return {
            "result": float(np.asarray(digest, dtype=np.float64)),
            "ok": bool(ok),
            "host": float(np.asarray(oracle.value(), dtype=np.float64)),
            "diff": float(diff),
            "chunks": sc.plan.num_chunks,
            "gbps": round(x.nbytes / max(wall, 1e-9) / 1e9, 4),
        }

    def run_sharded(self, method: str, dtype: str, n: int, seed: int,
                    *, chunk_bytes: Optional[int] = None,
                    quantized: bool = False, quant_bits: int = 8,
                    devices=None) -> Dict:
        """Execute ONE oversized request device-parallel (the serving
        tier's vertical scale path, docs/SERVING.md): the payload
        splits into contiguous per-device shards, each shard folds
        chunk-by-chunk — every host->device message bounded by the
        staging doctrine (config.stage_chunk_bytes) — into a resident
        per-device partial block, and the k partials finish with ONE
        collective combine whose algorithm comes from
        collectives/algorithms.select_algorithm (recorded in a
        `collective.select` ledger event, launch/done bracketed). With
        `quantized`, the combine rides the EQuARX-style block-scaled
        wire (collectives/quant.py) when the geometry supports it;
        verification then accepts the declared error bound instead of
        the exact tolerance. Same response shape as run_batch."""
        import jax
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

        from tpu_reductions.collectives.algorithms import select_algorithm
        from tpu_reductions.collectives.core import make_collective_reduce
        from tpu_reductions.collectives.quant import (
            make_quant_sum_all_reduce, quant_error_bound, quant_supported)
        from tpu_reductions.exec import core as exec_core
        from tpu_reductions.exec.plan import launch_plan
        from tpu_reductions.obs import ledger, trace
        from tpu_reductions.ops import oracle as oracle_mod
        from tpu_reductions.ops.registry import accum_dtype, get_op
        from tpu_reductions.ops.stream import (_BLOCK, _LANES, _SUBLANES,
                                               iter_chunks, plan_chunks)
        from tpu_reductions.utils.rng import host_data

        fault_point("serve.batch")

        from tpu_reductions.config import FAMILY_METHODS

        method = method.upper()
        if method in FAMILY_METHODS:
            if method == "SCAN":
                # an oversized SCAN chunk-carries; the digest is the
                # same scalar the sharded fold would produce
                return self.run_stream(method, dtype, n, seed,
                                       chunk_bytes=chunk_bytes)
            raise ValueError(f"{method} has no device-parallel path; "
                             "family methods serve via the coalesced "
                             "batch (docs/FAMILY.md)")
        if dtype == "float64":
            raise ValueError("float64 shards through the dd stream "
                             "path, not run_sharded (serve/engine.py "
                             "_should_shard)")
        devs = list(devices) if devices is not None \
            else list(jax.local_devices())
        k = min(len(devs), n)
        if k <= 1:
            # degenerate geometry: the streaming path IS the sharded
            # path at k=1 (same bounded messages, no wire)
            return self.run_stream(method, dtype, n, seed,
                                   chunk_bytes=chunk_bytes)
        devs = devs[:k]

        x = oracle_mod.native_fill(n, dtype, rank=0, seed=seed)
        if x is None:
            x = host_data(n, dtype, rank=0, seed=seed)
        x = np.ravel(x)

        op = get_op(method)
        acc_dt = np.dtype(accum_dtype(dtype)) if method == "SUM" \
            else np.dtype(dtype)
        base = -(-n // k)                       # per-shard length
        plan = plan_chunks(base, dtype, chunk_bytes)
        # accumulator width: wide enough (16 blocks when the chunk
        # allows) that per_rank divides by k*QUANT_BLOCK at k=8, so the
        # quantized ring genuinely applies to the combine instead of
        # always falling back to the exact psum
        width = min(16, plan.chunk_elems // _BLOCK)
        per_rank = width * _BLOCK
        fold = _jit_shard_fold(method, str(acc_dt), width)

        def fold_shard(rank: int, dev):
            lo_i = rank * base
            shard = x[lo_i:min(n, lo_i + base)]
            acc = jax.device_put(  # redlint: disable=RED003 -- identity accumulator, width*8*128 elements, orders of magnitude under the chunk bound
                np.full((width * _SUBLANES, _LANES),
                        op.identity(acc_dt), acc_dt), dev)
            chunks = -(-shard.size // plan.chunk_elems)
            for c in range(chunks):
                piece = shard[c * plan.chunk_elems:
                              (c + 1) * plan.chunk_elems]
                pad = plan.chunk_elems - piece.size
                if pad:
                    piece = np.pad(
                        piece, (0, pad),
                        constant_values=op.identity(piece.dtype))
                # one bounded message per chunk (plan_chunks fits the
                # chunk under config.stage_chunk_bytes — the per-device
                # spelling of the utils/staging relay-hazard doctrine)
                staged = jax.device_put(  # redlint: disable=RED003 -- one plan_chunks-bounded chunk (<= config.stage_chunk_bytes) per message, per-device sharded staging
                    piece.reshape(-1, _LANES), dev)
                acc = fold(acc, staged)
            return acc

        # per-shard folds: one plan, k retried device units — the
        # contract sets no whole-plan phase; each ctx.call carries the
        # "serve" guard exactly where the old inline retries did
        accs = exec_core.run(launch_plan(
            f"serve-shard/{method.lower()}", "serve",
            lambda ctx: [ctx.call(lambda r=r, d=d: fold_shard(r, d),
                                  phase="serve")
                         for r, d in enumerate(devs)],
            timing="serve", heartbeat_phase=None, drain=True,
            staging_bound=int(plan.chunk_bytes), method=method,
            dtype=dtype, n=n, devices=k))

        # combine dtype: what the partials actually hold (bf16 SUM
        # accumulates f32 — ops/registry.accum_dtype)
        combine_dtype = str(acc_dt)
        use_quant = bool(quantized) and method == "SUM" \
            and quant_supported(method, combine_dtype, quant_bits)
        selection = select_algorithm(method, combine_dtype, k, per_rank,
                                     quantized=use_quant,
                                     bits=quant_bits)
        ledger.emit("collective.select", algorithm=selection.algorithm,
                    method=method, dtype=combine_dtype, ranks=k,
                    wire_factor=round(selection.wire_factor, 6),
                    quantized=use_quant,
                    bits=(quant_bits if use_quant else None))

        mesh = Mesh(np.array(devs), ("ranks",))
        flats = [_jit_flatten()(a) for a in accs]
        garr = jax.make_array_from_single_device_arrays(
            (k * per_rank,), NamedSharding(mesh, P("ranks")), flats)
        if use_quant:
            coll = make_quant_sum_all_reduce(mesh, bits=quant_bits,
                                             dtype=combine_dtype)
        else:
            coll = make_collective_reduce(method, mesh, "ranks",
                                          rooted="none")
        with trace.child():
            ledger.emit("collective.launch",
                        algorithm=selection.algorithm, method=method,
                        dtype=combine_dtype, ranks=k, n=int(per_rank))
            import time as _time
            t0 = _time.perf_counter()
            block = np.asarray(jax.device_get(exec_core.run(launch_plan(
                f"serve-combine/{selection.algorithm}", "collective",
                lambda ctx: ctx.call(lambda: coll(garr), phase="serve"),
                timing="serve", heartbeat_phase=None, drain=True,
                method=method, dtype=combine_dtype, ranks=k,
                quantized=use_quant))))
            ledger.emit("collective.done",
                        algorithm=selection.algorithm, method=method,
                        dtype=combine_dtype, ranks=k,
                        wall_s=round(_time.perf_counter() - t0, 6),
                        rows=1)

        # host collapse of the replicated combined block — the
        # StreamReducer.finish discipline (int32 SUM wraps mod 2^32)
        if method == "SUM" and block.dtype == np.int32:
            value = np.int64(block.sum(dtype=np.int64)
                             ).astype(np.int32)[()]
        elif method == "SUM":
            value = np.float64(block.astype(np.float64).sum())
        else:
            value = op.np_reduce(block)

        oracle = oracle_mod.IncrementalOracle(method, dtype)
        for chunk in iter_chunks(x, plan_chunks(n, dtype, chunk_bytes)):
            oracle.update(chunk)
        ok, diff = oracle_mod.verify(value, oracle.value(),
                                     method, dtype, n)
        bound = None
        if not ok and use_quant:
            # the quantized wire is approximate BY CONTRACT: accept the
            # declared per-element bound summed over the combined block
            # (collectives/quant.quant_error_bound; docs/COLLECTIVES.md)
            max_abs = max(float(np.abs(np.asarray(
                jax.device_get(a), dtype=np.float64)).max())
                for a in accs)
            bound = quant_error_bound(method, combine_dtype, quant_bits,
                                      k, max_abs) * per_rank
            ok = float(diff) <= bound
        return {
            "result": float(np.asarray(value, dtype=np.float64)),
            "ok": bool(ok),
            "host": float(np.asarray(oracle.value(), dtype=np.float64)),
            "diff": float(diff),
            "algorithm": selection.algorithm,
            "wire_factor": round(selection.wire_factor, 6),
            "quantized": use_quant,
            "quant_bound": bound,
            "devices": k,
            "per_device_chunks": plan.num_chunks,
            "chunk_bytes": plan.chunk_bytes,
        }

    def run_reshard(self, plan, carried: np.ndarray) -> Dict:
        """Execute ONE planner-emitted redistribution program
        (reshard/planner.plan_reshard) on the local mesh — the drain
        protocol's device seam (serve/autoscale.drain_replica): the
        autoscaler plans and oracle-verifies jax-free, and every
        device touch funnels through here so the rest of serve/ stays
        inside the RED014 fence. Returns execute_plan's result dict
        ({'shards', 'wall_s', 'steps', 'measured_mem_factor'})."""
        from tpu_reductions.exec import core as exec_core
        from tpu_reductions.exec.plan import launch_plan
        from tpu_reductions.reshard.primitives import (execute_plan,
                                                       make_mesh)

        fault_point("serve.batch")

        mesh = make_mesh(plan.source.num_ranks)
        return exec_core.run(launch_plan(
            "serve-reshard", "reshard",
            lambda ctx: execute_plan(plan, carried, mesh),
            timing="steps", heartbeat_phase="serve", retry=True,
            drain=True, ranks=plan.source.num_ranks,
            steps=len(plan.steps)))
