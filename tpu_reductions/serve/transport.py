"""Per-launch transport gate: dead-relay detection + latency injection.

On the tunneled box every device call crosses the relay, and the
relay's three failure modes (dead / slow / stalled — docs/RESILIENCE.md
fault model) all land AHEAD of the launch from the engine's point of
view. The gate makes that explicit: before each coalesced launch the
engine performs one bounded relay round-trip —

  * connection refused on every probe port -> `TransportDead`: the
    engine sheds instead of dispatching work that can only hang
    (the serving spelling of watchdog exit 3);
  * the chaos relay's `slow` behavior (faults/relay.py) holds the
    accepted connection for `delay_s` before closing — draining to EOF
    makes that latency land HERE, deterministically, which is how load
    tests exercise deadline expiry and shedding without wall-clock
    races (the ISSUE 6 latency-injection satellite);
  * a stalled relay (accepts, never closes) is bounded by `read_cap_s`
    — the gate returns and the heartbeat/watchdog machinery owns any
    longer stall (exit-4 territory), so the gate itself can never be
    the hang.

Untunneled hosts (no relay marker) skip the gate entirely: a plain
`--platform=cpu` run pays nothing. Chaos tests opt in by pointing
`TPU_REDUCTIONS_RELAY_MARKER` / `TPU_REDUCTIONS_RELAY_PORTS` at a
FakeRelay, like every other relay consumer.

Drain-to-EOF is only performed when `TPU_REDUCTIONS_RELAY_PORTS` is
overridden (i.e. the stack is pointed at a scriptable relay): the real
relay's protocol does not promise to close probe connections, so
against the default ports the gate degrades to the same cheap
connect-probe `utils/watchdog.probe_relay` uses.

jax-free (redlint RED014): the gate is pure sockets.
"""

from __future__ import annotations

import os
import socket
import time
from typing import Optional

from tpu_reductions.utils import heartbeat
from tpu_reductions.utils.watchdog import resolved_ports, tunneled_environment

from tpu_reductions.serve.request import TransportDead


class RelayTransport:
    """The engine's default transport gate (module docstring)."""

    def __init__(self, *, connect_timeout_s: float = 2.0,
                 read_cap_s: float = 5.0,
                 drain: Optional[bool] = None,
                 ports: Optional[tuple] = None,
                 assume_tunneled: bool = False) -> None:
        """`drain=None` (default) drains to EOF only when the relay
        ports are env-overridden (a scriptable relay is in play);
        True/False force it either way — tests pass True. `ports` +
        `assume_tunneled` bind the gate to an explicit relay (the
        loadgen's modeled-RTT mode) without touching the process
        environment."""
        self._connect_timeout_s = connect_timeout_s
        self._read_cap_s = read_cap_s
        self._drain = drain
        self._ports = tuple(ports) if ports is not None else None
        self._assume_tunneled = assume_tunneled

    def _should_drain(self) -> bool:
        if self._drain is not None:
            return self._drain
        if self._ports is not None:
            return True
        return bool(os.environ.get("TPU_REDUCTIONS_RELAY_PORTS"))

    def _gated(self) -> bool:
        return self._assume_tunneled or tunneled_environment()

    def _resolved_ports(self):
        return self._ports if self._ports is not None \
            else resolved_ports()

    def gate(self) -> float:
        """One bounded relay round-trip; returns the seconds it cost
        (the injected latency, when a `slow` relay is in play). Raises
        TransportDead when every probe port refuses. Untunneled: free.

        Runs under a heartbeat guard so a stall here is watched like
        any other transport wait (utils/heartbeat.py)."""
        if not self._gated():
            return 0.0
        t0 = time.monotonic()
        inconclusive = False
        with heartbeat.guard("serve"):  # redlint: disable=RED025 -- guards a raw TCP relay-port probe (no device work, pre-jax); there is no launch to plan, only a socket wait to watch
            for port in self._resolved_ports():
                try:
                    with socket.create_connection(
                            ("127.0.0.1", port),
                            timeout=self._connect_timeout_s) as s:
                        if self._should_drain():
                            s.settimeout(self._read_cap_s)
                            try:
                                while s.recv(1024):
                                    heartbeat.tick()
                            except (socket.timeout, TimeoutError):
                                # stalled relay: bounded here; longer
                                # stalls are exit-4 territory
                                pass
                            except OSError:
                                pass
                    return time.monotonic() - t0
                except (ConnectionRefusedError, ConnectionResetError,
                        socket.timeout, TimeoutError):
                    continue
                except OSError:
                    # EMFILE-class local degradation says nothing about
                    # the relay (the probe_relay asymmetry): treat as
                    # passable, never as dead
                    inconclusive = True
        if inconclusive:
            return time.monotonic() - t0
        raise TransportDead(
            "relay refuses on every probe port "
            f"({','.join(map(str, self._resolved_ports()))})")


class NullTransport:
    """A gate that never gates — the explicit opt-out for in-process
    tests that want the engine without any relay semantics."""

    def gate(self) -> float:
        return 0.0
