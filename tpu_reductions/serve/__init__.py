"""Reduction-as-a-service: the async multi-tenant serving layer.

bench/driver.py's measure-verify-report loop is one-shot and
single-tenant; this package is the persistent serving form the ROADMAP
north star asks for (docs/SERVING.md): an engine that accepts
reduction requests (op x dtype x payload), coalesces compatible
concurrent requests into fused stacked device launches, schedules
mixed traffic with the shared value/expected-cost knapsack
(sched/knapsack.py) against a per-round device-time window, and
executes through an admission-controlled path with bounded queue
depth, per-request deadlines, and graceful load shedding — rejecting
or shedding instead of wedging, the serving-shaped spelling of the
relay doctrine every bench entry point already follows.

Module map (redlint RED014 enforces the device boundary):

  request.py   typed request/response surface + the future-like slot
               (jax-free)
  transport.py per-launch relay gate: dead-relay detection + the
               chaos relay's `slow` latency injection (jax-free)
  coalesce.py  batch formation + knapsack round planning + the online
               duration cost model (jax-free)
  engine.py    the serving core: admission -> queue -> coalesce ->
               plan -> launch -> verify -> respond (jax-free)
  executor.py  the ONLY device-touching module: fused stacked
               launches with retry/heartbeat, oracle verification
  loadgen.py   closed-loop load generator + the committed
               requests/s + p50/p99 serving curve
  __main__.py  `python -m tpu_reductions.serve` — the TCP JSON-lines
               front end

Every request transition lands in the flight recorder as a `serve.*`
event (lint/grammar.py SERVE_EVENTS); `python -m
tpu_reductions.obs.timeline` attributes per-request latency post-hoc
(docs/OBSERVABILITY.md).
"""

from tpu_reductions.serve.request import (ReduceRequest, ReduceResponse,
                                          TransportDead)

__all__ = ["ReduceRequest", "ReduceResponse", "TransportDead"]
