"""Typed request/response surface of the serving engine.

One `ReduceRequest` is one tenant's ask: reduce an `n`-element payload
of `dtype` with `method`, optionally within `deadline_s`. The payload
itself is generated engine-side from the request's seed (the same
deterministic host fillers the bench uses, utils/rng.py /
ops/oracle.native_fill) so a request is a few bytes on the wire while
the serving path still moves and verifies real data.

jax-free by construction: admission control, queueing and scheduling
must all work with the relay dead (redlint RED014 bans device work in
serve/ outside serve/executor.py).
"""

from __future__ import annotations

import dataclasses
import threading
from typing import Optional

import numpy as np

from tpu_reductions.config import DTYPE_ALIASES, SERVED_METHODS

# terminal response statuses — the engine's whole vocabulary. Every
# submitted request resolves to exactly one of these (the no-hang
# contract of docs/SERVING.md):
#   ok        executed, verified, result attached
#   error     executed path failed (device error, verification failure,
#             dead relay mid-launch) — the reason is in .error
#   rejected  refused at admission (queue full, oversize, unservable
#             dtype, engine stopped) — never entered the queue
#   expired   the per-request deadline passed before a result existed
#   shed      dropped by load shedding (relay death, engine drain)
STATUSES = ("ok", "error", "rejected", "expired", "shed")


class TransportDead(RuntimeError):
    """The relay refuses on every probe port at launch time: the
    serving analog of the watchdog's exit-3 verdict. The engine
    responds to the doomed batch, sheds the queue with explicit
    per-request responses, and keeps running — a later window's
    traffic finds the transport gate green again (faults/relay.py's
    flap model)."""


@dataclasses.dataclass
class ReduceRequest:
    """One reduction request (validated at construction — a malformed
    request never reaches the queue)."""

    method: str
    dtype: str
    n: int
    seed: int = 0
    deadline_s: Optional[float] = None   # relative to submission
    value: float = 1.0                   # scheduling weight (knapsack)
    tenant: str = "default"              # per-tenant quota bucket
    priority: int = 1                    # higher preempts lower on a
    #                                      full queue (docs/SERVING.md)
    slo: Optional[str] = None            # SLO class name — resolved to
    #                                      a deadline by the engine's
    #                                      slo_classes table
    idem_key: Optional[str] = None       # client-supplied idempotency
    #                                      key: retries/re-routes that
    #                                      carry the same key settle to
    #                                      ONE terminal response — a
    #                                      duplicate of a settled key
    #                                      returns the cached response
    #                                      without re-touching the
    #                                      device (exactly-once;
    #                                      docs/SERVING.md
    #                                      "crash-consistent control
    #                                      plane")

    def __post_init__(self) -> None:
        self.method = self.method.upper()
        # the served vocabulary is the classic ops PLUS the reduction
        # family (SCAN/SEG*/ARG* — ISSUE 20, docs/FAMILY.md); admission,
        # coalescing and SLO handling are method-agnostic, only the
        # executor dispatches per group
        if self.method not in SERVED_METHODS:
            raise ValueError(f"method must be one of {SERVED_METHODS}, "
                             f"got {self.method!r}")
        if self.dtype not in DTYPE_ALIASES:
            raise ValueError(f"unknown dtype {self.dtype!r}")
        self.dtype = DTYPE_ALIASES[self.dtype]
        if self.n <= 0:
            raise ValueError("n must be positive")
        if self.deadline_s is not None and self.deadline_s <= 0:
            raise ValueError("deadline_s must be positive (or None)")
        if self.value <= 0:
            raise ValueError("value must be positive")
        if not isinstance(self.tenant, str) or not self.tenant:
            raise ValueError("tenant must be a non-empty string")
        if not isinstance(self.priority, int) or self.priority < 0:
            raise ValueError("priority must be a non-negative int")
        if self.slo is not None and (not isinstance(self.slo, str)
                                     or not self.slo):
            raise ValueError("slo must be a non-empty string (or None)")
        if self.idem_key is not None and (
                not isinstance(self.idem_key, str) or not self.idem_key):
            raise ValueError("idem_key must be a non-empty string "
                             "(or None)")

    @property
    def nbytes(self) -> int:
        """Payload size — what admission's byte cap and the batcher's
        per-launch byte bound meter (the 512 MiB relay-hazard doctrine
        of utils/staging.py, applied at the front door)."""
        return self.n * np.dtype(self.dtype).itemsize


@dataclasses.dataclass
class ReduceResponse:
    """One terminal outcome. `latency_s` is submit-to-response wall
    clock; `queue_s` is the admission-to-launch share of it (the
    split obs/timeline.py also reconstructs from serve.* events)."""

    request_id: str
    status: str
    method: str
    dtype: str
    n: int
    result: Optional[float] = None
    error: Optional[str] = None
    latency_s: Optional[float] = None
    queue_s: Optional[float] = None
    batch_size: Optional[int] = None

    @property
    def ok(self) -> bool:
        return self.status == "ok"

    def to_dict(self) -> dict:
        """JSON-ready (the TCP front end's response line)."""
        return dataclasses.asdict(self)


class PendingResponse:
    """The future-like slot `ServeEngine.submit` returns: resolved
    exactly once, waitable with a timeout. Thread-safe — the engine
    worker resolves, any client thread waits."""

    def __init__(self, request_id: str) -> None:
        self.request_id = request_id
        self._event = threading.Event()
        self._response: Optional[ReduceResponse] = None
        self._lock = threading.Lock()
        self._callbacks: list = []

    def resolve(self, response: ReduceResponse) -> None:
        """Engine-side: attach the terminal response (first resolution
        wins; a second is a bug upstream and is ignored rather than
        clobbering what a client may already have read)."""
        with self._lock:
            if self._response is not None:
                return
            self._response = response
            callbacks, self._callbacks = self._callbacks, []
        self._event.set()
        for fn in callbacks:
            fn(response)

    def add_done_callback(self, fn) -> None:
        """Run `fn(response)` when this slot resolves — on the
        resolving thread, or immediately on the calling thread if
        already resolved. The open-loop loadgen and the replica
        router's re-route path hang off this instead of burning a
        waiter thread per in-flight request."""
        with self._lock:
            if self._response is None:
                self._callbacks.append(fn)
                return
            response = self._response
        fn(response)

    def done(self) -> bool:
        return self._event.is_set()

    def result(self, timeout: Optional[float] = None) -> ReduceResponse:
        """Block until resolved. Raises TimeoutError instead of
        returning None — a caller that forgets the timeout sees a loud
        failure, never a silent null response."""
        if not self._event.wait(timeout):
            raise TimeoutError(f"request {self.request_id} unresolved "
                               f"after {timeout}s")
        assert self._response is not None
        return self._response
