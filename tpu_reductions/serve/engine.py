"""The serving core: admission -> queue -> coalesce -> plan -> launch
-> verify -> respond.

One `ServeEngine` is a persistent multi-tenant service over the
single-chip reduction machinery (docs/SERVING.md has the architecture;
this docstring has the invariants):

  * **Admission control.** `submit` resolves instantly with
    status `rejected` when the request is unservable (bounded queue
    full, payload over the per-request byte cap — the relay-hazard
    bound, float64 on a backend that cannot carry it, engine
    stopped). An admitted request WILL resolve: every code path ends
    in exactly one terminal response (the no-hang contract).
  * **Coalescing.** Per round, queued requests group by
    (method, dtype, n) into fused stacked launches
    (serve/coalesce.py); mixed traffic ranks by the shared knapsack
    against `device_window_s` of expected device time, deferred
    batches re-queue ahead of newer arrivals.
  * **Deadlines.** `deadline_s` is relative to submission; it is
    checked at gather, immediately before launch, and at response
    time — a result that arrives late is `expired`, not silently
    stale (the serving spelling of "a WAIVED row is not a PASSED
    row").
  * **Shedding, not wedging.** A dead relay at the transport gate
    (serve/transport.py) fails the doomed batch with explicit
    `error` responses and sheds the entire queue with explicit `shed`
    responses; the engine keeps running, so a relay that flaps back
    finds it serving (the round-4 flap model). `stop(drain=True)`
    finishes in-flight work and sheds the rest the same way.
  * **Every transition is traced.** serve.* events
    (lint/grammar.py SERVE_EVENTS) land in the flight recorder;
    obs/timeline.py reconstructs per-request latency post-hoc.

The engine itself is jax-free (redlint RED014): all device work flows
through serve/executor.py, constructed lazily on first use.
"""

from __future__ import annotations

import itertools
import sys
import threading
import time
from collections import OrderedDict, deque
from dataclasses import dataclass
from typing import Deque, Dict, List, Optional

from tpu_reductions import config
from tpu_reductions.obs import ledger, trace
from tpu_reductions.serve.coalesce import (Batch, CostModel, coalesce,
                                           plan_round)
from tpu_reductions.serve.request import (PendingResponse, ReduceRequest,
                                          ReduceResponse, TransportDead)
from tpu_reductions.serve.transport import RelayTransport

# per-request payload cap: one coalesced launch must never be able to
# reconstruct the 4 GiB single-message relay killer (round 2, twice;
# utils/staging.py's chunk threshold is the same 512 MiB line)
DEFAULT_MAX_REQUEST_BYTES = 512 << 20

# dtypes the quantized collective wire can carry for SUM (static
# knowledge mirrored from collectives/quant.SUM_DTYPES — spelled here
# so the jax-free engine can test eligibility without importing the
# collectives stack; executor.run_sharded re-checks quant_supported
# and falls back to the exact wire on disagreement)
_QUANT_SUM_DTYPES = ("float32", "bfloat16")


class _SLOTracker:
    """Rolling per-SLO-class p99 over recent ok latencies. Nearest-rank
    p99 over a bounded window (newest 64): the admission-time signal
    for p99-aware shedding — when a class's observed tail already
    misses its deadline, admitting more of that class just converts
    future `ok`s into `expired`s after the device did the work."""

    def __init__(self, window: int = 64, min_samples: int = 8) -> None:
        self._window = window
        self.min_samples = min_samples
        self._samples: Dict[str, Deque[float]] = {}
        self._lock = threading.Lock()

    def observe(self, slo: str, latency_s: float) -> None:
        with self._lock:
            dq = self._samples.get(slo)
            if dq is None:
                dq = self._samples[slo] = deque(maxlen=self._window)
            dq.append(latency_s)

    def p99(self, slo: str) -> Optional[float]:
        """Nearest-rank p99 of the class window, or None below
        min_samples (a cold class is never shed on tail evidence it
        does not have)."""
        with self._lock:
            dq = self._samples.get(slo)
            if dq is None or len(dq) < self.min_samples:
                return None
            vals = sorted(dq)
        rank = max(0, -(-99 * len(vals) // 100) - 1)
        return vals[rank]


@dataclass
class _Admitted:
    """Engine-internal record of one admitted request."""

    request: ReduceRequest
    request_id: str
    pending: PendingResponse
    t_enqueue: float                     # monotonic
    t_deadline: Optional[float]          # monotonic absolute, or None
    t_launch: Optional[float] = None
    batch_size: Optional[int] = None
    streamed: bool = False               # oversized: routed through the
    #                                      streaming pipeline, never
    #                                      coalesced (ops/stream.py)

    def expired(self, now: float) -> bool:
        return self.t_deadline is not None and now > self.t_deadline

    @property
    def priority(self) -> int:
        return self.request.priority


class ServeEngine:
    """The multi-tenant serving engine (module docstring)."""

    def __init__(self, *, max_queue: int = 64, max_batch: int = 32,
                 coalesce_window_s: float = 0.005,
                 device_window_s: float = 0.25,
                 max_request_bytes: int = DEFAULT_MAX_REQUEST_BYTES,
                 stream_oversized: bool = True,
                 stream_chunk_bytes: Optional[int] = None,
                 shard_oversized: bool = True,
                 shard_threshold_bytes: Optional[int] = None,
                 tenant_quota: Optional[int] = None,
                 slo_classes: Optional[Dict[str, float]] = None,
                 slo_min_samples: int = 8,
                 quant_slack_factor: float = 2.0,
                 dedup_cache_size: Optional[int] = None,
                 executor=None, transport=None,
                 cost_model: Optional[CostModel] = None) -> None:
        if max_queue <= 0 or max_batch <= 0:
            raise ValueError("max_queue/max_batch must be positive")
        if tenant_quota is not None and tenant_quota <= 0:
            raise ValueError("tenant_quota must be positive (or None)")
        self._max_queue = max_queue
        self._max_batch = max_batch
        self._coalesce_window_s = coalesce_window_s
        self._device_window_s = device_window_s
        self._max_request_bytes = max_request_bytes
        # oversized requests used to be REJECTED at the byte cap (the
        # cap exists because one coalesced launch must never rebuild
        # the 4 GiB single-message relay killer); the streaming
        # pipeline serves them instead in O(2 chunks) of device memory
        # with every message bounded (ops/stream.py, docs/STREAMING.md)
        self._stream_oversized = stream_oversized
        self._stream_chunk_bytes = stream_chunk_bytes
        # ...and above the shard threshold they go device-PARALLEL when
        # the backend has >1 device: staging-bounded per-device chunk
        # folds finished by a collective combine picked through
        # collectives/algorithms.select_algorithm (executor.run_sharded;
        # docs/SERVING.md scaling tier). f64 stays on the stream/dd
        # path — the collective registry's dd planes are a different
        # launch shape than the per-device fold accumulators.
        self._shard_oversized = shard_oversized
        self._shard_threshold = config.shard_threshold_bytes(
            shard_threshold_bytes)
        # multi-tenancy: per-tenant queued-depth quota, priority
        # preemption on a full queue, SLO classes (name -> deadline_s
        # applied when the request names no deadline of its own) with
        # p99-aware admission shedding
        self._tenant_quota = tenant_quota
        self._slo_classes = dict(slo_classes or {})
        self._slo = _SLOTracker(min_samples=slo_min_samples)
        self._quant_slack_factor = quant_slack_factor
        self._executor = executor          # lazy BatchExecutor when None
        self._transport = transport if transport is not None \
            else RelayTransport()
        self._cost_model = cost_model or CostModel()
        self._queue: Deque[_Admitted] = deque()
        self._cond = threading.Condition()
        self._thread: Optional[threading.Thread] = None
        self._stopping = False
        self._stopped = False
        self._draining = False
        self._ids = itertools.count()
        # stats counters are bumped from submitter threads (admission)
        # AND the worker loop; every write funnels through _bump under
        # this lock (redlint RED021). _exec_lock serializes the lazy
        # BatchExecutor construction for the same reason — construction
        # is jax-free (serve/executor.py header), so holding the lock
        # never wraps a device sync (RED023).
        self._stats_lock = threading.Lock()
        self._exec_lock = threading.Lock()
        self.stats: Dict[str, float] = {
            "submitted": 0, "ok": 0, "error": 0, "rejected": 0,
            "expired": 0, "shed": 0, "batches": 0, "batched_requests": 0,
            "preempted": 0, "sharded": 0, "dedup_hits": 0}
        # exactly-once settlement (docs/SERVING.md "crash-consistent
        # control plane"): bounded LRU of settled terminal responses
        # keyed on the client-supplied idempotency key. A duplicate of
        # a settled key — a router re-route after a timeout, a client
        # retry across a controller crash — returns the cached response
        # WITHOUT re-touching the device. Only settled outcomes cache
        # (ok, and errors that are not transport/lifecycle failures);
        # rejected/shed/expired stay retryable by design. Eviction at
        # the bound degrades the evicted key to at-least-once (retry
        # re-executes) — documented fallback, never a hang.
        self._dedup_max = config.dedup_cache_size(dedup_cache_size)
        self._dedup: "OrderedDict[str, ReduceResponse]" = OrderedDict()
        self._dedup_lock = threading.Lock()
        # jit-bucket keys this engine has warmed or launched — the warm
        # state a planned drain hands to the surviving replicas
        # (serve/autoscale.drain_replica; docs/SERVING.md elastic fleet)
        self._warm_keys: set = set()

    def _bump(self, key: str, delta: int = 1) -> None:
        with self._stats_lock:
            self.stats[key] = self.stats.get(key, 0) + delta

    # -- lifecycle ----------------------------------------------------

    def start(self) -> "ServeEngine":
        """Start the worker; requests submitted before start() queue up
        and are served once it runs (the test seam for deterministic
        coalescing)."""
        if self._thread is not None:
            return self
        ledger.emit("serve.start", max_queue=self._max_queue,
                    max_batch=self._max_batch,
                    coalesce_window_s=self._coalesce_window_s,
                    device_window_s=self._device_window_s)
        self._thread = threading.Thread(target=self._run,
                                        name="serve-engine", daemon=True)
        self._thread.start()
        return self

    def stop(self, drain: bool = True, timeout: float = 30.0) -> None:
        """Shut down: with drain, the worker finishes the batch in
        flight and sheds everything still queued with explicit `shed`
        responses; without, shedding happens immediately. Idempotent."""
        with self._cond:
            if self._stopped and self._thread is None:
                return
            self._stopping = True
            if not drain:
                self._shed_locked("engine-stopped")
            self._cond.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=timeout)
            self._thread = None
        with self._cond:
            self._shed_locked("engine-stopped")
            self._stopped = True
        ledger.emit("serve.stop", **{k: int(v)
                                     for k, v in self.stats.items()})

    def begin_drain(self) -> None:
        """Enter the draining admission mode (docs/SERVING.md elastic
        fleet): every NEW submit resolves `rejected` with the
        `replica-draining` mark — which the router re-routes for free
        (serve/router.replica_draining) — while queued and in-flight
        work keeps serving to completion. Distinct from stop(): the
        worker stays up, nothing sheds. The drain protocol
        (serve/autoscale.drain_replica) calls stop() only once the
        queue and the router's outstanding count hit zero, so a
        planned drain sheds ZERO requests where a kill sheds the
        queue."""
        with self._cond:
            self._draining = True

    @property
    def draining(self) -> bool:
        return self._draining

    def queued_depth(self) -> int:
        """Current admission-queue depth — one of the autoscaler's
        control signals (serve/autoscale.py) and the drain protocol's
        emptiness probe."""
        with self._cond:
            return len(self._queue)

    def slo_p99(self, slo: str) -> Optional[float]:
        """Rolling p99 of an SLO class (the _SLOTracker the p99-aware
        shed consults), exported as an autoscaler control signal."""
        return self._slo.p99(slo)

    def warm_bucket_keys(self) -> List[tuple]:
        """The (method, dtype, n) jit-bucket keys this engine has
        warmed or served — the cache state a planned drain prewarms
        onto survivors so retiring the replica does not re-cold-start
        its traffic (serve/autoscale.drain_replica)."""
        with self._stats_lock:
            return sorted(self._warm_keys)

    def prewarm(self, method: str, dtype: str, n: int,
                up_to_batch: int = 1) -> None:
        """Compile-cache warming through the sanctioned executor path:
        run one tiny launch per jit bucket (1, 2, 4, ... up_to_batch)
        for the key, so serving traffic never pays a trace/compile
        inside a measured or deadline-bound window (the .jax_cache
        doctrine, serving-shaped; ROADMAP item 5's cold-start story).
        Call before start() or while the engine is idle."""
        with self._stats_lock:
            self._warm_keys.add((method, dtype, n))
        k = 1
        while True:
            self._ensure_executor().run_batch(method, dtype, n,
                                              list(range(k)))
            if k >= up_to_batch:
                return
            k <<= 1

    # -- admission ----------------------------------------------------

    def submit(self, request: ReduceRequest) -> PendingResponse:
        """Admit, reject, or shed one request; always returns a
        PendingResponse (rejections and admission-time sheds come back
        already resolved). Admission order: static servability ->
        SLO-class resolution -> p99-aware shed -> tenant quota ->
        queue bound (with priority preemption)."""
        rid = f"r{next(self._ids):06d}"
        pending = PendingResponse(rid)
        self._bump("submitted")
        # exactly-once short-circuit BEFORE admission: a settled
        # idempotency key answers from the dedup cache even on a
        # draining or stopping engine — the work already happened;
        # re-running it (or bouncing the retry) would break the
        # one-terminal-status-per-key contract
        if request.idem_key is not None:
            cached = self._dedup_get(request.idem_key)
            if cached is not None:
                self._bump("dedup_hits")
                ledger.emit("serve.dedup", req=rid,
                            idem=request.idem_key,
                            orig=cached.request_id,
                            status=cached.status,
                            **trace.request_fields(rid))
                pending.resolve(cached)
                return pending
        reason = self._admission_reason(request)
        if reason is not None:
            return self._resolve_at_admission(request, rid, pending,
                                              "rejected", reason)
        deadline_s = self._effective_deadline(request)
        # p99-aware shedding (docs/SERVING.md scaling tier): when the
        # class's observed tail already blows its deadline, the honest
        # terminal status is `shed` (load), not `rejected` (malformed/
        # unservable) — the device work the request would trigger is
        # predicted to expire anyway
        if request.slo is not None and deadline_s is not None:
            p99 = self._slo.p99(request.slo)
            if p99 is not None and p99 > deadline_s:
                return self._resolve_at_admission(
                    request, rid, pending, "shed",
                    f"p99-over-slo: class {request.slo!r} p99 "
                    f"{p99:.3f}s > deadline {deadline_s:.3f}s")
        now = time.monotonic()
        adm = _Admitted(request=request, request_id=rid, pending=pending,
                        t_enqueue=now,
                        t_deadline=(now + deadline_s
                                    if deadline_s else None),
                        streamed=(request.nbytes
                                  > self._max_request_bytes
                                  # above the shard threshold the
                                  # request leaves the coalesced path
                                  # even when it fits the byte cap:
                                  # the stream fork then picks
                                  # device-parallel vs chunked-serial
                                  # (_should_shard)
                                  or (self._shard_oversized
                                      and request.dtype != "float64"
                                      and request.nbytes
                                      > self._shard_threshold)))
        with self._cond:
            reason = self._enqueue_locked(adm)
            depth = len(self._queue)
            if reason is None:
                self._cond.notify_all()
        if reason is not None:
            return self._resolve_at_admission(request, rid, pending,
                                              "rejected", reason)
        # one trace per request (ISSUE 12): the request id IS the
        # trace id, so every event of its lifecycle shares identity
        # and trace_export renders one lane per request
        ledger.emit("serve.enqueue", req=rid, method=request.method,
                    dtype=request.dtype, n=request.n, depth=depth,
                    streamed=adm.streamed, tenant=request.tenant,
                    priority=request.priority,
                    # the idem key on the enqueue row is what makes
                    # "zero duplicate device executions" LEDGER-
                    # verifiable: loadgen --recovery joins enqueue
                    # rows to coalesce/launch rows per key
                    **({"idem": request.idem_key}
                       if request.idem_key else {}),
                    **trace.request_fields(rid))
        return pending

    # -- exactly-once dedup cache -------------------------------------

    def _dedup_get(self,
                   idem_key: str) -> Optional[ReduceResponse]:
        """Cached terminal response for a settled key (LRU touch), or
        None — the miss path costs one dict lookup under a lock."""
        with self._dedup_lock:
            resp = self._dedup.get(idem_key)
            if resp is not None:
                self._dedup.move_to_end(idem_key)
            return resp

    @staticmethod
    def _dedup_settled(status: str, error: Optional[str]) -> bool:
        """Whether an outcome is a SETTLEMENT worth caching. ok always
        is; an error is only when the device genuinely executed and
        failed (verification mismatch, contained batch crash) — a
        transport/lifecycle failure (dead relay, stopping engine,
        draining replica) must stay retryable, or a cached failure
        would poison every later retry of the key."""
        if status == "ok":
            return True
        if status != "error":
            return False
        e = error or ""
        return not any(mark in e for mark in (
            "relay dead", "relay-dead", "engine-stopped",
            "replica-draining"))

    def _dedup_put(self, idem_key: str, resp: ReduceResponse) -> None:
        """Record a settlement (first settle wins — a racing duplicate
        never clobbers what a client may already hold) and evict LRU
        past the bound (config.dedup_cache_size)."""
        with self._dedup_lock:
            if idem_key in self._dedup:
                return
            self._dedup[idem_key] = resp
            while len(self._dedup) > self._dedup_max:
                self._dedup.popitem(last=False)

    def _resolve_at_admission(self, request: ReduceRequest, rid: str,
                              pending: PendingResponse, status: str,
                              reason: str) -> PendingResponse:
        """Terminal verdict before the queue: resolve the slot now
        (never entered the queue, so no latency split to report)."""
        self._bump(status)
        resp = ReduceResponse(rid, status, request.method,
                              request.dtype, request.n, error=reason)
        ledger.emit("serve.respond", req=rid, status=status,
                    reason=reason[:120], **trace.request_fields(rid))
        pending.resolve(resp)
        return pending

    def _effective_deadline(self,
                            request: ReduceRequest) -> Optional[float]:
        """The request's own deadline wins; else its SLO class's
        (validated in _admission_reason, so the lookup here hits)."""
        if request.deadline_s is not None:
            return request.deadline_s
        if request.slo is not None:
            return self._slo_classes.get(request.slo)
        return None

    def _enqueue_locked(self, adm: _Admitted) -> Optional[str]:
        """Append under the lock, enforcing the per-tenant quota and
        the queue bound. A full queue admits a higher-priority arrival
        by preempting (shedding) the newest lowest-priority queued
        request — deterministic under any relay behavior because no
        device state is consulted. Returns a rejection reason or
        None."""
        request = adm.request
        if self._tenant_quota is not None:
            depth_t = sum(1 for a in self._queue
                          if a.request.tenant == request.tenant)
            if depth_t >= self._tenant_quota:
                return (f"tenant quota: {request.tenant!r} already has "
                        f"{depth_t} queued (quota {self._tenant_quota})")
        if len(self._queue) >= self._max_queue:
            victim = self._preempt_victim_locked(request.priority)
            if victim is None:
                return f"queue full (depth {len(self._queue)})"
            self._queue.remove(victim)
            self._bump("preempted")
            self._respond(victim, "shed",
                          error=(f"priority-preempted: displaced by "
                                 f"priority {request.priority} arrival"))
        self._queue.append(adm)
        return None

    def _preempt_victim_locked(self,
                               priority: int) -> Optional[_Admitted]:
        """The newest queued request of the lowest priority class,
        when that class is strictly below the arrival's (never shed
        an equal-priority peer: FIFO fairness within a class)."""
        if not self._queue:
            return None
        lowest = min(a.priority for a in self._queue)
        if lowest >= priority:
            return None
        for a in reversed(self._queue):
            if a.priority == lowest:
                return a
        return None

    def _admission_reason(self, request: ReduceRequest) -> Optional[str]:
        if self._stopping or self._stopped:
            return "engine-stopped"
        if self._draining:
            # the planned scale-down vocabulary, distinct from
            # engine-stopped BY DESIGN: the router re-routes this
            # without burning a max_retries attempt
            # (serve/router.replica_draining) because the replica is
            # healthy — admission is closed by policy, not failure
            return ("replica-draining: admission closed for planned "
                    "scale-down (in-flight work finishing)")
        if request.slo is not None \
                and request.slo not in self._slo_classes:
            return (f"unknown slo class {request.slo!r} (configured: "
                    f"{sorted(self._slo_classes) or 'none'})")
        oversized = request.nbytes > self._max_request_bytes
        if oversized and not self._stream_oversized:
            return (f"payload {request.nbytes} B exceeds the "
                    f"{self._max_request_bytes} B per-request cap "
                    "(single-message relay hazard; utils/staging.py) "
                    "and streaming is disabled")
        if request.dtype == "float64" and not oversized:
            # the coalesced stacked launch has no f64 story off-x64;
            # the streaming pipeline always does (dd pair chunks,
            # ops/stream.py) — so only the batch path gates here
            caps = self._capabilities()
            if not caps.get("supports_f64", False):
                return ("float64 unservable on this backend "
                        f"({caps.get('backend', '?')}): device f64 is "
                        "the dd pair path's job (ops/dd_reduce.py)")
        return None

    def _capabilities(self) -> dict:
        try:
            return self._ensure_executor().capabilities()
        except Exception as e:                    # capability probe
            return {"backend": f"error: {e}",     # failure: reject f64,
                    "supports_f64": False}        # keep serving 32-bit

    def _ensure_executor(self):
        # reached from both submitter threads (capability probes at
        # admission) and the worker loop — without the lock two racing
        # first calls build two executors with separate jit caches
        with self._exec_lock:
            if self._executor is None:
                from tpu_reductions.serve.executor import BatchExecutor
                self._executor = BatchExecutor()
            return self._executor

    # -- responses ----------------------------------------------------

    def _respond(self, adm: _Admitted, status: str, *,
                 result: Optional[float] = None,
                 error: Optional[str] = None) -> None:
        now = time.monotonic()
        latency = now - adm.t_enqueue
        queue_s = (adm.t_launch - adm.t_enqueue) if adm.t_launch else None
        self._bump(status)
        r = adm.request
        resp = ReduceResponse(adm.request_id, status, r.method, r.dtype,
                              r.n, result=result,
                              error=error[:200] if error else None,
                              latency_s=round(latency, 6),
                              queue_s=(round(queue_s, 6)
                                       if queue_s is not None else None),
                              batch_size=adm.batch_size)
        fields = {"req": adm.request_id, "status": status,
                  "latency_s": resp.latency_s, "queue_s": resp.queue_s,
                  "batch_size": adm.batch_size,
                  **trace.request_fields(adm.request_id)}
        if error:
            fields["reason"] = error[:120]
        if status == "ok" and r.slo is not None:
            # feed the class tail estimate that p99-aware admission
            # shedding consults (only ok latencies: a shed/rejected
            # request's instant resolution says nothing about service)
            self._slo.observe(r.slo, latency)
        # exactly-once: record the settlement BEFORE resolving, so a
        # duplicate racing the resolution finds the cache populated
        if r.idem_key is not None and self._dedup_settled(status, error):
            self._dedup_put(r.idem_key, resp)
        ledger.emit("serve.respond", **fields)
        adm.pending.resolve(resp)

    def _shed_locked(self, reason: str) -> None:
        """Shed every queued request with an explicit response (caller
        holds the lock for the queue swap; responses resolve outside
        any device path so this can never block)."""
        if not self._queue:
            return
        doomed = list(self._queue)
        self._queue.clear()
        ledger.emit("serve.shed", count=len(doomed), reason=reason)
        for adm in doomed:
            self._respond(adm, "shed", error=reason)

    # -- the worker ---------------------------------------------------

    def _run(self) -> None:
        while True:
            with self._cond:
                while not self._queue and not self._stopping:
                    self._cond.wait(timeout=0.05)
                if self._stopping and not self._queue:
                    return
            # bounded gather window: let a concurrent burst coalesce
            if self._coalesce_window_s > 0:
                time.sleep(self._coalesce_window_s)
            with self._cond:
                taken = list(self._queue)
                self._queue.clear()
            try:
                self._serve_round(taken)
            except Exception as e:
                # the worker must never die silently: contain, respond,
                # keep serving
                print(f"serve.engine: round failed "
                      f"({type(e).__name__}: {e}); requests get "
                      "error responses", file=sys.stderr, flush=True)
                for adm in taken:
                    if not adm.pending.done():
                        self._respond(adm, "error",
                                      error=f"{type(e).__name__}: {e}")
            with self._cond:
                if self._stopping and not self._queue:
                    return

    def _serve_round(self, taken: List[_Admitted]) -> None:
        now = time.monotonic()
        live: List[_Admitted] = []
        streams: List[_Admitted] = []
        for adm in taken:
            if adm.expired(now):
                self._respond(adm, "expired",
                              error="deadline passed in queue")
            elif adm.streamed:
                streams.append(adm)
            else:
                live.append(adm)
        for adm in streams:
            # oversized requests never coalesce (one stream already
            # saturates the transfer pipeline); they launch singly —
            # device-parallel above the shard threshold when the
            # backend has devices to split across, else streaming
            if self._should_shard(adm):
                self._launch_sharded(adm)
            else:
                self._launch_stream(adm)
        if not live:
            return
        batches = coalesce(live, max_batch=self._max_batch,
                           max_batch_bytes=self._max_request_bytes)
        launch, defer = plan_round(batches, cost_model=self._cost_model,
                                   device_window_s=self._device_window_s)
        for b in launch:
            # request ids are per-engine (r000000 collides across
            # replicas), so the exactly-once audit joins on the
            # client-supplied idempotency keys stamped HERE — the
            # launch-membership event IS the device-execution record
            # (serve/loadgen._recovery_evidence)
            idems = [a.request.idem_key for a in b.admitted]
            ledger.emit("serve.coalesce", batch=b.batch_id,
                        method=b.key[0], dtype=b.key[1], n=b.key[2],
                        size=b.size,
                        reqs=[a.request_id for a in b.admitted],
                        **({"idems": idems} if any(idems) else {}))
        if defer:
            # deferred batches keep their place ahead of new arrivals
            with self._cond:
                self._queue.extendleft(reversed(
                    [a for b in defer for a in b.admitted]))
        for b in launch:
            self._launch(b)

    def _launch(self, batch: Batch) -> None:
        now = time.monotonic()
        live = []
        for adm in batch.admitted:
            if adm.expired(now):
                self._respond(adm, "expired",
                              error="deadline passed before launch")
            else:
                live.append(adm)
        if not live:
            return
        method, dtype, n = batch.key
        with self._stats_lock:
            self._warm_keys.add(batch.key)
        est = self._cost_model.estimate(batch.key)
        ledger.emit("serve.launch", batch=batch.batch_id, size=len(live),
                    method=method, dtype=dtype, n=n,
                    est_s=round(est, 6))
        t0 = time.monotonic()
        for adm in live:
            adm.t_launch = t0
            adm.batch_size = len(live)
        try:
            self._transport.gate()
            results = self._ensure_executor().run_batch(
                method, dtype, n, [a.request.seed for a in live])
        except TransportDead as e:
            # the serving exit-3: fail the doomed batch loudly, shed
            # the queue, keep running for the next flap window
            for adm in live:
                self._respond(adm, "error", error=f"relay dead: {e}")
            with self._cond:
                self._shed_locked("relay-dead")
            return
        except Exception as e:
            # crash contained to the batch (bench/driver.crash_result
            # discipline): one broken key must not take the service
            for adm in live:
                self._respond(adm, "error",
                              error=f"{type(e).__name__}: {e}")
            return
        dt = time.monotonic() - t0
        self._cost_model.observe(batch.key, dt)
        self._bump("batches")
        self._bump("batched_requests", len(live))
        ok_count = sum(1 for r in results if r["ok"])
        ledger.emit("serve.verify", batch=batch.batch_id,
                    ok=ok_count, failed=len(live) - ok_count,
                    exec_s=round(dt, 6))
        now = time.monotonic()
        for adm, res in zip(live, results):
            if adm.expired(now):
                self._respond(adm, "expired",
                              error="deadline passed before response")
            elif res["ok"]:
                self._respond(adm, "ok", result=res["result"])
            else:
                self._respond(adm, "error",
                              error=(f"verification failed: device "
                                     f"{res['result']!r} vs oracle "
                                     f"{res['host']!r} "
                                     f"(diff {res['diff']:g})"))

    def _should_shard(self, adm: _Admitted) -> bool:
        """Device-parallel eligibility for one oversized request:
        above the shard threshold (config.shard_threshold_bytes /
        TPU_REDUCTIONS_SHARD_THRESHOLD_BYTES), more than one local
        device, and not f64 (dd pair planes stay on the streaming
        path — their plane encoding is not the per-device fold's
        accumulator shape)."""
        r = adm.request
        if not self._shard_oversized or r.dtype == "float64":
            return False
        if r.nbytes <= self._shard_threshold:
            return False
        return self._capabilities().get("device_count", 1) > 1

    def _quant_wire(self, adm: _Admitted, est_s: float) -> bool:
        """Quantized collective wire eligibility (EQuARX-style,
        docs/COLLECTIVES.md): opt in only when the request carries a
        deadline whose remaining slack is tight against the cost
        model's estimate (slack < quant_slack_factor x estimate) — the
        loaded-tier regime where wire bytes buy latency — and the
        (method, dtype) is statically quantizable for SUM. The
        executor re-checks quant_supported and falls back to the
        exact wire, so a stale static table degrades accuracy of the
        CHOICE, never correctness."""
        if adm.t_deadline is None:
            return False
        r = adm.request
        if r.method != "SUM" or r.dtype not in _QUANT_SUM_DTYPES:
            return False
        slack = adm.t_deadline - time.monotonic()
        return slack < self._quant_slack_factor * max(est_s, 1e-6)

    def _launch_sharded(self, adm: _Admitted) -> None:
        """Serve one oversized request device-parallel: split across
        local devices in utils/staging-bounded per-device chunks,
        per-device fold, then a collective combine whose algorithm
        comes from collectives/algorithms.select_algorithm
        (executor.run_sharded — all device work stays behind RED014's
        whitelist). Same transport gate, deadline checks, crash
        containment and response vocabulary as every other launch."""
        now = time.monotonic()
        if adm.expired(now):
            self._respond(adm, "expired",
                          error="deadline passed before launch")
            return
        r = adm.request
        est = self._cost_model.estimate((r.method, r.dtype, r.n))
        quantized = self._quant_wire(adm, est)
        ledger.emit("serve.shard", req=adm.request_id, method=r.method,
                    dtype=r.dtype, n=r.n, nbytes=r.nbytes,
                    quantized=quantized,
                    **trace.request_fields(adm.request_id))
        t0 = time.monotonic()
        adm.t_launch = t0
        adm.batch_size = 1
        try:
            self._transport.gate()
            res = self._ensure_executor().run_sharded(
                r.method, r.dtype, r.n, r.seed,
                chunk_bytes=self._stream_chunk_bytes,
                quantized=quantized)
        except TransportDead as e:
            self._respond(adm, "error", error=f"relay dead: {e}")
            with self._cond:
                self._shed_locked("relay-dead")
            return
        except Exception as e:
            self._respond(adm, "error",
                          error=f"{type(e).__name__}: {e}")
            return
        dt = time.monotonic() - t0
        self._cost_model.observe((r.method, r.dtype, r.n), dt)
        self._bump("batches")
        self._bump("batched_requests")
        self._bump("sharded")
        ledger.emit("serve.verify", batch=f"p-{adm.request_id}",
                    ok=int(res["ok"]), failed=int(not res["ok"]),
                    exec_s=round(dt, 6),
                    algorithm=res.get("algorithm"),
                    devices=res.get("devices"),
                    **trace.request_fields(adm.request_id))
        if adm.expired(time.monotonic()):
            self._respond(adm, "expired",
                          error="deadline passed before response")
        elif res["ok"]:
            self._respond(adm, "ok", result=res["result"])
        else:
            self._respond(adm, "error",
                          error=(f"verification failed: device "
                                 f"{res['result']!r} vs oracle "
                                 f"{res['host']!r} "
                                 f"(diff {res['diff']:g})"))

    def _launch_stream(self, adm: _Admitted) -> None:
        """Serve one oversized request through the streaming pipeline
        (executor.run_stream): same transport gate, deadline checks,
        crash containment and response vocabulary as a coalesced
        launch — the request that used to bounce off the byte cap now
        resolves `ok` while the device never holds more than two
        chunks of it (docs/STREAMING.md; docs/SERVING.md)."""
        now = time.monotonic()
        if adm.expired(now):
            self._respond(adm, "expired",
                          error="deadline passed before launch")
            return
        r = adm.request
        ledger.emit("serve.stream", req=adm.request_id, method=r.method,
                    dtype=r.dtype, n=r.n, nbytes=r.nbytes,
                    **trace.request_fields(adm.request_id))
        t0 = time.monotonic()
        adm.t_launch = t0
        adm.batch_size = 1
        try:
            self._transport.gate()
            res = self._ensure_executor().run_stream(
                r.method, r.dtype, r.n, r.seed,
                chunk_bytes=self._stream_chunk_bytes)
        except TransportDead as e:
            self._respond(adm, "error", error=f"relay dead: {e}")
            with self._cond:
                self._shed_locked("relay-dead")
            return
        except Exception as e:
            self._respond(adm, "error",
                          error=f"{type(e).__name__}: {e}")
            return
        dt = time.monotonic() - t0
        self._cost_model.observe((r.method, r.dtype, r.n), dt)
        self._bump("batches")
        self._bump("batched_requests")
        ledger.emit("serve.verify", batch=f"s-{adm.request_id}",
                    ok=int(res["ok"]), failed=int(not res["ok"]),
                    exec_s=round(dt, 6),
                    **trace.request_fields(adm.request_id))
        if adm.expired(time.monotonic()):
            self._respond(adm, "expired",
                          error="deadline passed before response")
        elif res["ok"]:
            self._respond(adm, "ok", result=res["result"])
        else:
            self._respond(adm, "error",
                          error=(f"verification failed: device "
                                 f"{res['result']!r} vs oracle "
                                 f"{res['host']!r} "
                                 f"(diff {res['diff']:g})"))
