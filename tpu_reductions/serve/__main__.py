"""`python -m tpu_reductions.serve` — the TCP JSON-lines front end.

One request object per line, one response line back, over a local TCP
socket (the transport is deliberately minimal: the engine is the
product, the socket is a demo-grade front door the loadgen's
--connect mode and shell rehearsals drive):

    {"method": "SUM", "type": "int", "n": 65536, "seed": 1,
     "deadline_s": 2.0}
 ->
    {"request_id": "r000000", "status": "ok", "result": 8355840.0,
     "latency_s": 0.0021, ...}

Entry-point doctrine, same as every bench CLI: the flight recorder and
the watchdog arm together before any backend touch
(docs/OBSERVABILITY.md; utils/watchdog.py), so a relay death under
live traffic resolves to watchdog vocabulary (exit 3/4) with every
already-answered request's trace in the ledger — and the engine itself
sheds, never hangs (serve/engine.py).

CLI:
    python -m tpu_reductions.serve [--port 0] [--port-file PATH] \
        [--platform cpu] [--max-seconds S] [engine knobs]
"""

from __future__ import annotations

import argparse
import json
import socketserver
import sys
import threading
import time

from tpu_reductions.config import _apply_platform


def _control_response(engine, spec: dict) -> dict:
    """The {"op": ...} control plane a ProcessReplica parent drives
    for planned scale-down (serve/router.ProcessReplica._control;
    docs/SERVING.md "elastic fleet"): drain closes admission,
    drain_status reports the drain-protocol observables, prewarm
    warms a handed-off bucket key. Unknown ops (or a front end
    without the protocol, e.g. the router CLI) report instead of
    raising — the parent treats an error as the kill case."""
    op = spec.get("op")
    try:
        if op == "ping":
            # the adoption liveness probe (serve/router.adopt_fleet):
            # a pid can outlive a wedged engine, so recovery trusts
            # only a served control round-trip
            return {"op": op, "ok": True}
        if op == "drain":
            engine.begin_drain()
            return {"op": op, "ok": True}
        if op == "drain_status":
            return {"op": op, "ok": True,
                    "draining": bool(getattr(engine, "draining", False)),
                    "queued": engine.queued_depth(),
                    "warm_keys": [list(k)
                                  for k in engine.warm_bucket_keys()],
                    "stats": {k: v for k, v in engine.stats.items()}}
        if op == "prewarm":
            engine.prewarm(spec["method"],
                           spec.get("type", spec.get("dtype", "int")),
                           int(spec["n"]),
                           up_to_batch=int(spec.get("up_to_batch", 1)))
            return {"op": op, "ok": True}
        return {"op": op, "error": f"unknown control op: {op!r}"}
    except (AttributeError, KeyError, TypeError, ValueError) as e:
        return {"op": op, "error": f"{type(e).__name__}: {e}"}


def _make_handler(engine, request_timeout_s: float):
    from tpu_reductions.serve.request import ReduceRequest

    class Handler(socketserver.StreamRequestHandler):
        def handle(self):
            for raw in self.rfile:
                raw = raw.strip()
                if not raw:
                    continue
                try:
                    spec = json.loads(raw)
                    if isinstance(spec, dict) and "op" in spec:
                        resp = _control_response(engine, spec)
                        self.wfile.write(
                            (json.dumps(resp) + "\n").encode())
                        self.wfile.flush()
                        continue
                    req = ReduceRequest(
                        method=spec["method"],
                        dtype=spec.get("type", spec.get("dtype", "int")),
                        n=int(spec.get("n", 1 << 16)),
                        seed=int(spec.get("seed", 0)),
                        deadline_s=spec.get("deadline_s"),
                        value=float(spec.get("value", 1.0)),
                        tenant=spec.get("tenant", "default"),
                        priority=int(spec.get("priority", 1)),
                        slo=spec.get("slo"),
                        idem_key=spec.get("idem_key"))
                except (KeyError, TypeError, ValueError) as e:
                    resp = {"status": "rejected",
                            "error": f"malformed request: {e}"}
                else:
                    try:
                        resp = engine.submit(req).result(
                            timeout=request_timeout_s).to_dict()
                    except TimeoutError as e:
                        resp = {"status": "error", "error": str(e)}
                self.wfile.write((json.dumps(resp) + "\n").encode())
                self.wfile.flush()

    return Handler


class _Server(socketserver.ThreadingTCPServer):
    allow_reuse_address = True
    daemon_threads = True


def main(argv=None) -> int:
    """CLI entry (module docstring): start the engine, serve JSON
    lines until --max-seconds (or interrupt), drain on the way out."""
    p = argparse.ArgumentParser(
        prog="tpu_reductions.serve",
        description="Reduction-as-a-service: TCP JSON-lines front end "
                    "over the async serving engine (docs/SERVING.md)")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=0,
                   help="0 = ephemeral (printed + --port-file)")
    p.add_argument("--port-file", default=None,
                   help="write the bound port here once listening")
    p.add_argument("--max-queue", type=int, default=64)
    p.add_argument("--max-batch", type=int, default=32)
    p.add_argument("--coalesce-window-ms", type=float, default=5.0)
    p.add_argument("--device-window-ms", type=float, default=250.0)
    p.add_argument("--request-timeout-s", type=float, default=600.0,
                   help="per-connection wait bound on one response")
    p.add_argument("--max-seconds", type=float, default=None,
                   help="total runtime bound (default: until killed)")
    p.add_argument("--platform", default=None, choices=("cpu", "tpu"))
    p.add_argument("--devices", dest="num_devices", type=int,
                   default=None,
                   help="virtual CPU device count (--platform=cpu; the "
                        "sharded path needs >1)")
    p.add_argument("--relay-port", type=int, default=None,
                   help="gate launches against this relay port (a "
                        "router parent's chaos relay — every replica "
                        "pays the same modeled transport RTT)")
    ns = p.parse_args(argv)
    _apply_platform(ns)

    from tpu_reductions.obs.ledger import arm_session
    arm_session("serve", argv=list(argv) if argv else sys.argv[1:])
    from tpu_reductions.exec.core import maybe_arm_for_tpu
    maybe_arm_for_tpu()   # a server hung on a dead relay serves nothing

    from tpu_reductions.serve.engine import ServeEngine
    transport = None
    if ns.relay_port is not None:
        from tpu_reductions.serve.transport import RelayTransport
        transport = RelayTransport(ports=(ns.relay_port,),
                                   assume_tunneled=True, drain=True)
    engine = ServeEngine(
        max_queue=ns.max_queue, max_batch=ns.max_batch,
        coalesce_window_s=ns.coalesce_window_ms / 1e3,
        device_window_s=ns.device_window_ms / 1e3,
        transport=transport).start()

    server = _Server((ns.host, ns.port),
                     _make_handler(engine, ns.request_timeout_s))
    port = server.server_address[1]
    print(f"serving on {ns.host}:{port}", flush=True)
    if ns.port_file:
        from tpu_reductions.utils.jsonio import atomic_text_dump
        atomic_text_dump(ns.port_file, f"{port}\n")
    t = threading.Thread(target=server.serve_forever, daemon=True)
    t.start()
    try:
        if ns.max_seconds is None:
            while True:
                time.sleep(0.5)
        else:
            time.sleep(ns.max_seconds)
    except KeyboardInterrupt:
        pass
    finally:
        server.shutdown()
        engine.stop(drain=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
