"""Batch formation + round planning + the online duration cost model.

Coalescing rule (docs/SERVING.md): concurrent requests are compatible
iff they share (method, dtype, n) — exactly the key under which one
stacked (k, n) device call computes all k results in a single launch
(serve/executor.py). A batch is bounded twice: by `max_batch` rows
(the executor's jit-bucket ceiling) and by `max_batch_bytes` of
stacked payload (the 512 MiB single-message relay-hazard bound of
utils/staging.py, applied at batch-formation time so a coalesced
launch can never reconstruct the round-2 killer).

Mixed traffic is scheduled by the shared greedy knapsack
(sched/knapsack.py — the ISSUE 6 generalization): each batch's value
is the sum of its requests' values, its cost is the `CostModel`'s
expected device-seconds for its key, and the budget is the engine's
per-round device-time window. Batches that don't fit defer to the
next round (where new arrivals may coalesce into them); the top pick
always launches — an idle device must never wait on a pessimistic
estimate (the planner's always-runnable rule).

`CostModel` is the serving-grain analog of sched/priors.py: an
exponentially-weighted moving average of observed launch durations per
batch key, updated online as batches finish — the Zhang-et-al
cost-model role (PAPERS.md 2112.01075) at request granularity.

jax-free (redlint RED014): planning never touches the device.
"""

from __future__ import annotations

import itertools
from dataclasses import dataclass, field
from typing import Dict, List, Sequence, Tuple

from tpu_reductions.sched.knapsack import greedy_plan

BatchKey = Tuple[str, str, int]          # (method, dtype, n)

_batch_ids = itertools.count()


@dataclass
class Batch:
    """One fused launch unit: compatible admitted requests in arrival
    order. `admitted` items are the engine's internal records (each
    carries .request, .request_id, deadlines — serve/engine.py)."""

    key: BatchKey
    admitted: List = field(default_factory=list)
    batch_id: str = field(
        default_factory=lambda: f"b{next(_batch_ids):05d}")

    @property
    def size(self) -> int:
        return len(self.admitted)

    @property
    def value(self) -> float:
        return sum(a.request.value for a in self.admitted)

    @property
    def nbytes(self) -> int:
        return sum(a.request.nbytes for a in self.admitted)


def coalesce(admitted: Sequence, *, max_batch: int,
             max_batch_bytes: int) -> List[Batch]:
    """Group admitted requests into batches by key, preserving arrival
    order within a key, splitting at the row and byte bounds."""
    by_key: Dict[BatchKey, List] = {}
    order: List[BatchKey] = []
    for a in admitted:
        r = a.request
        key = (r.method, r.dtype, r.n)
        if key not in by_key:
            by_key[key] = []
            order.append(key)
        by_key[key].append(a)
    batches: List[Batch] = []
    for key in order:
        cur = Batch(key=key)
        for a in by_key[key]:
            if cur.size >= max_batch or \
                    (cur.size and cur.nbytes + a.request.nbytes
                     > max_batch_bytes):
                batches.append(cur)
                cur = Batch(key=key)
            cur.admitted.append(a)
        if cur.size:
            batches.append(cur)
    return batches


class CostModel:
    """EWMA expected device-seconds per batch key (module docstring).
    `default_s` is the cold-start prior — deliberately modest, so an
    unobserved key neither hogs nor starves the round window."""

    def __init__(self, *, alpha: float = 0.3,
                 default_s: float = 0.02) -> None:
        if not 0 < alpha <= 1:
            raise ValueError("alpha must be in (0, 1]")
        self._alpha = alpha
        self._default_s = default_s
        self._est: Dict[BatchKey, float] = {}

    def estimate(self, key: BatchKey) -> float:
        return self._est.get(key, self._default_s)

    def observe(self, key: BatchKey, seconds: float) -> None:
        if seconds <= 0:
            return
        prev = self._est.get(key)
        self._est[key] = seconds if prev is None else \
            (1 - self._alpha) * prev + self._alpha * seconds


def plan_round(batches: Sequence[Batch], *, cost_model: CostModel,
               device_window_s: float
               ) -> Tuple[List[Batch], List[Batch]]:
    """One scheduling round: (launch_now, defer). Ranking is the
    shared knapsack (sched/knapsack.greedy_plan); the top pick always
    launches even when nothing 'fits' the window."""
    if not batches:
        return [], []
    ranked = greedy_plan([batches],
                         value=lambda b: b.value,
                         cost=lambda b: cost_model.estimate(b.key),
                         budget_s=device_window_s,
                         tie_key=lambda b: b.batch_id)
    launch = [r.item for r in ranked if r.fits]
    if not launch:
        launch = [ranked[0].item]
    chosen = {id(b) for b in launch}
    defer = [r.item for r in ranked if id(r.item) not in chosen]
    return launch, defer
