"""Load generator: the closed-loop serving curve AND the open-loop
scaling curve (docs/SERVING.md).

Closed loop (ISSUE 6, `serving_curve.json`): N client threads each
drive submit-wait-submit — concurrency == clients — and the run
distills into requests/s + p50/p99 at N concurrent clients, coalesced
vs `sequential` (max_batch=1) on the SAME workload and executor.

Open loop (ISSUE 13, `serving_scale.json`, `--scale`): arrivals come
from a seeded arrival PROCESS (Poisson exponential gaps, or bursty —
Poisson burst epochs of `--burst` back-to-back arrivals), dispatched
at their planned offsets regardless of completions, so 1000+ clients
cost one dispatcher thread plus completion callbacks
(PendingResponse.add_done_callback), never 1000 waiter threads. The
scaling grid runs `sequential` / `coalesced` / `routerN`
(serve/router.py, N in-process replicas) over the same seeded
workload at each client count, every series gating launches through
ONE shared chaos relay in `slow` mode (faults/relay.py holds each
connection in its own thread, so N replicas genuinely overlap their
modeled per-launch RTTs) — the 1-vs-N-replica series the ISSUE 13
acceptance reads. `--scale` also lands one `sharded` row: an
oversized (> shard threshold) request through the engine's
device-parallel path, with the `collective.select` algorithm choice
parsed back out of the armed ledger.

Everything is seeded (`--seed`): same seed -> byte-identical workload
plan (arrival offsets AND request specs), closed loop included.

Artifacts: bench/resume.Checkpoint shape ({meta, complete, rows}),
one row per mode / per (series, clients, process) cell, persisted the
moment each lands; `bench/regen.py` folds them into report.md via
`curve_markdown` / `scale_markdown`.

Elastic (ISSUE 17, `serving_elastic.json`, `--elastic`): an
autoscaled LocalReplica fleet (serve/autoscale.py) tracks the seeded
`--plan=diurnal` arrival shape (ramp/burst/ebb/peak/tail composed
from the same poisson/bursty primitives) per client count — the
committed row carries the replica-count-vs-load trajectory and the
p99-inside-SLO verdict — then the drain-vs-kill pair retires a
replica mid-burst both ways on one seeded workload (planned drain:
zero victim shed, warm keys handed off, partials resharded under the
declared peak-memory bound; SIGKILL control: in-flight losses).

CLI:
    python -m tpu_reductions.serve.loadgen --platform=cpu --clients=8 \
        [--requests=32 --n=65536 --methods=SUM,MIN,MAX --type=int] \
        [--connect HOST:PORT] --out=serving_curve.json
    python -m tpu_reductions.serve.loadgen --platform=cpu --scale \
        [--scale-clients=64,256,1024 --replicas=4 --seed=0] \
        --out=examples/tpu_run/serving_scale.json
    python -m tpu_reductions.serve.loadgen --platform=cpu --elastic \
        [--plan=diurnal --scale-clients=64,256,1024 --slo-s=5] \
        --out=examples/tpu_run/serving_elastic.json
"""

from __future__ import annotations

import argparse
import json
import os
import random
import socket
import sys
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

from tpu_reductions.config import DTYPE_ALIASES, METHODS, _apply_platform


def percentile(sorted_vals: List[float], q: float) -> float:
    """Nearest-rank percentile over an already-sorted list (the same
    estimator sched/priors.py uses for window quantiles)."""
    if not sorted_vals:
        raise ValueError("percentile of empty sample")
    idx = min(len(sorted_vals) - 1,
              max(0, int(round(q * (len(sorted_vals) - 1)))))
    return sorted_vals[idx]


def _client_loop(submit, client: int, requests: int, methods: List[str],
                 dtype: str, n: int, deadline_s: Optional[float],
                 out: List[dict], barrier: threading.Barrier,
                 seed: int) -> None:
    from tpu_reductions.serve.request import ReduceRequest
    barrier.wait()
    for i in range(requests):
        # wave-aligned mix: in a closed loop the clients advance in
        # rough lockstep, so indexing by i alone gives each wave ONE
        # method — the concurrency shape coalescing exists for (a
        # per-client offset would guarantee mixed keys every wave and
        # measure the scheduler instead of the batcher)
        req = ReduceRequest(method=methods[i % len(methods)],
                            dtype=dtype, n=n,
                            seed=seed * 1000003 + client * 100003 + i,
                            deadline_s=deadline_s)
        t0 = time.monotonic()
        try:
            resp = submit(req)
        except Exception as e:              # a client error is a row,
            out.append({"status": "client-error",   # never a crash
                        "latency_s": time.monotonic() - t0,
                        "error": f"{type(e).__name__}: {e}"})
            continue
        # the request id is the request's trace id (ISSUE 12): stamped
        # through the response path so rows join the ledger's
        # serve.enqueue/respond events BY ID, never positionally
        # (obs/timeline.serve_summary flags the orphans)
        out.append({"req": resp.request_id,
                    "status": resp.status,
                    "latency_s": (resp.latency_s
                                  if resp.latency_s is not None
                                  else time.monotonic() - t0),
                    "batch_size": resp.batch_size})


def run_load(submit, *, clients: int, requests: int, methods: List[str],
             dtype: str, n: int, deadline_s: Optional[float] = None,
             seed: int = 0) -> dict:
    """Drive the closed loop; `submit(req) -> ReduceResponse` is either
    the in-process engine (resolved PendingResponse) or the TCP client.
    Returns the raw per-mode measurement (one curve row, mode-less)."""
    per_client: List[List[dict]] = [[] for _ in range(clients)]
    barrier = threading.Barrier(clients + 1)
    threads = [threading.Thread(
        target=_client_loop,
        args=(submit, c, requests, methods, dtype, n, deadline_s,
              per_client[c], barrier, seed), daemon=True)
        for c in range(clients)]
    for t in threads:
        t.start()
    barrier.wait()
    t0 = time.monotonic()
    for t in threads:
        t.join()
    wall = max(time.monotonic() - t0, 1e-9)
    rows = [r for recs in per_client for r in recs]
    return {"clients": clients, **_distill(rows, wall)}


def _distill(rows: List[dict], wall: float) -> dict:
    """One curve/scale row from per-request records (shared by the
    closed and open loops so the two artifacts' columns line up)."""
    by_status: Dict[str, int] = {}
    for r in rows:
        by_status[r["status"]] = by_status.get(r["status"], 0) + 1
    ok_lat = sorted(r["latency_s"] for r in rows
                    if r["status"] == "ok"
                    and isinstance(r.get("latency_s"), (int, float)))
    sizes = [r["batch_size"] for r in rows
             if isinstance(r.get("batch_size"), int)]
    row = {
        "requests": len(rows),
        "wall_s": round(wall, 6),
        "rps": round(len(rows) / wall, 2),
        "ok": by_status.get("ok", 0),
        "by_status": by_status,
        "mean_batch": (round(sum(sizes) / len(sizes), 2)
                       if sizes else None),
    }
    if ok_lat:
        row["p50_ms"] = round(percentile(ok_lat, 0.50) * 1e3, 3)
        row["p99_ms"] = round(percentile(ok_lat, 0.99) * 1e3, 3)
    return row


# --------------------------------------------------------------------------
# Open loop (ISSUE 13): seeded arrival processes + callback completion
# --------------------------------------------------------------------------

# the seeded time-varying arrival plan (ISSUE 17; --plan=diurnal):
# ramp + burst epochs composed from the poisson/bursty processes —
# (name, fraction of count, rate factor vs the base rate, process).
# The elastic curve drives THIS shape so the autoscaler has real
# scale-up (burst, peak) and scale-down (ebb, tail) signals to track.
DIURNAL_EPOCHS = (
    ("ramp", 0.20, 0.25, "poisson"),
    ("burst", 0.20, 2.00, "bursty"),
    ("ebb", 0.20, 0.25, "poisson"),
    ("peak", 0.20, 1.50, "bursty"),
    ("tail", 0.20, 0.25, "poisson"),
)
# total plan duration in units of count/base_rate: sum(frac / factor)
# over the epochs — the elastic mode sizes base_rate from this so a
# cell spans --elastic-seconds of wall clock
DIURNAL_TIME_FACTOR = sum(f / r for _, f, r, _ in DIURNAL_EPOCHS)


def diurnal_epoch_counts(count: int) -> List[int]:
    """Per-epoch arrival counts for a `count`-arrival diurnal plan:
    floor(frac * count) each, remainder into the last epoch — so the
    composition is exact and deterministic for any count."""
    counts = [int(frac * count) for _, frac, _, _ in DIURNAL_EPOCHS]
    counts[-1] += count - sum(counts)
    return counts


def open_arrivals(rng: random.Random, *, count: int, rate_rps: float,
                  process: str = "poisson",
                  burst: int = 32) -> List[float]:
    """`count` arrival offsets (seconds from t0) drawn from the named
    process at aggregate `rate_rps`:

      * poisson — i.i.d. exponential gaps (the memoryless open-loop
        default);
      * bursty  — Poisson BURST epochs, `burst` back-to-back arrivals
        each (same long-run rate, pathological short-run concurrency —
        the coalescing window's stress shape);
      * diurnal — the DIURNAL_EPOCHS composition (ramp -> burst ->
        ebb -> peak -> tail), each epoch its own poisson/bursty
        process at `rate_rps` x the epoch's factor, time offsets
        accumulated across epochs — deterministic per rng state like
        the primitives it composes.
    """
    if count <= 0 or rate_rps <= 0:
        raise ValueError("count and rate_rps must be positive")
    offsets: List[float] = []
    t = 0.0
    if process == "poisson":
        for _ in range(count):
            t += rng.expovariate(rate_rps)
            offsets.append(t)
    elif process == "bursty":
        while len(offsets) < count:
            t += rng.expovariate(rate_rps / burst)
            offsets.extend([t] * min(burst, count - len(offsets)))
    elif process == "diurnal":
        for (_, _, factor, proc), k in zip(DIURNAL_EPOCHS,
                                           diurnal_epoch_counts(count)):
            if k <= 0:
                continue
            sub = open_arrivals(rng, count=k,
                                rate_rps=rate_rps * factor,
                                process=proc, burst=burst)
            offsets.extend(t + o for o in sub)
            t = offsets[-1]
    else:
        raise ValueError(f"unknown arrival process {process!r} "
                         "(poisson|bursty|diurnal)")
    return offsets


def plan_workload(seed: int, *, count: int, methods: Sequence[str],
                  dtype: str, n_choices: Sequence[int],
                  rate_rps: float, process: str = "poisson",
                  burst: int = 32, deadline_s: Optional[float] = None,
                  slo: Optional[str] = None) -> List[Tuple]:
    """The seeded open-loop plan: `count` (offset_s, ReduceRequest)
    pairs, fully determined by `seed` (same seed -> identical offsets
    AND request specs — tests/test_loadgen pins this), so every series
    of a scaling run replays the SAME workload. `slo` stamps every
    request with that SLO class (the elastic mode's p99 contract)."""
    from tpu_reductions.serve.request import ReduceRequest
    rng = random.Random(seed)
    offsets = open_arrivals(rng, count=count, rate_rps=rate_rps,
                            process=process, burst=burst)
    plan = []
    for off in offsets:
        plan.append((off, ReduceRequest(
            method=rng.choice(list(methods)), dtype=dtype,
            n=rng.choice(list(n_choices)),
            seed=rng.randrange(1 << 30), deadline_s=deadline_s,
            slo=slo)))
    return plan


def run_open_load(submit_async, plan: List[Tuple], *,
                  timeout_s: float = 600.0) -> dict:
    """Dispatch the planned arrivals at their offsets regardless of
    completions (open loop) and collect terminal outcomes via
    `PendingResponse.add_done_callback` — one dispatcher thread total,
    so 1000+ clients are cheap. `submit_async(req)` must return a
    PendingResponse (ServeEngine.submit or ReplicaRouter.submit).
    Latency per request = dispatch-to-resolution wall clock."""
    rows: List[dict] = []
    lock = threading.Lock()
    done = threading.Event()
    remaining = [len(plan)]
    t_last = [0.0]

    def _record(resp, t_sub):
        now = time.monotonic()
        with lock:
            rows.append({"req": resp.request_id, "status": resp.status,
                         "latency_s": now - t_sub,
                         "batch_size": resp.batch_size})
            t_last[0] = max(t_last[0], now)
            remaining[0] -= 1
            if remaining[0] == 0:
                done.set()

    t0 = time.monotonic()
    for off, req in plan:
        delay = t0 + off - time.monotonic()
        if delay > 0:
            time.sleep(delay)
        t_sub = time.monotonic()
        try:
            pending = submit_async(req)
        except Exception as e:
            _record(type("R", (), {"request_id": "?",
                                   "status": "client-error",
                                   "batch_size": None,
                                   "error": str(e)})(), t_sub)
            continue
        pending.add_done_callback(
            lambda resp, ts=t_sub: _record(resp, ts))
    if not done.wait(timeout_s):
        raise TimeoutError(f"open loop: {remaining[0]} of {len(plan)} "
                           f"requests unresolved after {timeout_s}s — "
                           "the no-hang contract is broken upstream")
    wall = max(t_last[0] - t0, 1e-9)
    with lock:
        return _distill(list(rows), wall)


def curve_markdown(artifact: dict) -> str:
    """The report.md section bench/regen.py appends: the serving curve
    next to the GB/s tables."""
    lines = ["## serving under concurrent load (requests/s, latency)",
             ""]
    meta = ", ".join(f"{k}={artifact[k]}"
                     for k in ("dtype", "n", "methods", "platform",
                               "launch_latency_ms")
                     if artifact.get(k) is not None)
    if meta:
        lines += [f"workload: {meta}", ""]
    lines.append("| mode | clients | requests | req/s | p50 ms "
                 "| p99 ms | mean batch | ok | other |")
    lines.append("|---|---|---|---|---|---|---|---|---|")
    rows = {r.get("mode"): r for r in artifact.get("rows", [])
            if isinstance(r, dict)}
    for mode, r in rows.items():
        other = ", ".join(f"{k}:{v}"
                          for k, v in sorted(r.get("by_status",
                                                   {}).items())
                          if k != "ok") or "-"
        lines.append(
            f"| {mode} | {r.get('clients', '-')} "
            f"| {r.get('requests', '-')} | {r.get('rps', '-')} "
            f"| {r.get('p50_ms', '-')} | {r.get('p99_ms', '-')} "
            f"| {r.get('mean_batch', '-')} | {r.get('ok', '-')} "
            f"| {other} |")
    co, seq = rows.get("coalesced"), rows.get("sequential")
    if co and seq and seq.get("rps"):
        lines += ["", f"coalescing speedup: "
                      f"{co['rps'] / seq['rps']:.2f}x requests/s "
                      "(same workload, same executor, batch size 1 vs "
                      "coalesced)"]
    return "\n".join(lines)


def scale_markdown(artifact: dict) -> str:
    """The report.md section for the open-loop scaling curve
    (bench/regen.py folds it next to the closed-loop serving curve)."""
    lines = ["## serving scale-out (open loop: requests/s and latency "
             "vs clients)", ""]
    meta = ", ".join(f"{k}={artifact[k]}"
                     for k in ("dtype", "methods", "n_choices",
                               "replicas", "seed", "launch_latency_ms",
                               "platform")
                     if artifact.get(k) is not None)
    if meta:
        lines += [f"workload: {meta}", ""]
    rows = [r for r in artifact.get("rows", []) if isinstance(r, dict)]
    grid = [r for r in rows if r.get("series") != "sharded"]
    if grid:
        lines.append("| series | clients | process | req/s | p50 ms "
                     "| p99 ms | ok | other |")
        lines.append("|---|---|---|---|---|---|---|---|")
        for r in sorted(grid, key=lambda r: (r.get("process", ""),
                                             r.get("clients", 0),
                                             r.get("series", ""))):
            other = ", ".join(
                f"{k}:{v}" for k, v in sorted(r.get("by_status",
                                                    {}).items())
                if k != "ok") or "-"
            lines.append(
                f"| {r.get('series', '-')} | {r.get('clients', '-')} "
                f"| {r.get('process', '-')} | {r.get('rps', '-')} "
                f"| {r.get('p50_ms', '-')} | {r.get('p99_ms', '-')} "
                f"| {r.get('ok', '-')} | {other} |")
    by_key = {r.get("key"): r for r in grid}
    router_series = sorted({r["series"] for r in grid
                            if str(r.get("series", "")).startswith(
                                "router")})
    for rs in router_series:
        # the 1-vs-N record at every client count both series ran (one
        # line per count: the scaling story, not a cherry-picked point)
        for clients in sorted({r.get("clients") for r in grid
                               if isinstance(r.get("clients"), int)}):
            ro = by_key.get(f"{rs}@{clients}@poisson")
            co = by_key.get(f"coalesced@{clients}@poisson")
            if ro and co and co.get("rps"):
                lines += ["", f"replica scale-out at {clients} "
                              f"open-loop clients: {rs} serves "
                              f"{ro['rps'] / co['rps']:.2f}x the "
                              "single coalesced engine's requests/s "
                              "(same seeded workload, same shared "
                              "slow relay)"]
    sh = next((r for r in rows if r.get("series") == "sharded"), None)
    if sh:
        mib = (sh.get("nbytes") or 0) / (1 << 20)
        lines += ["", f"device-parallel sharded row: n={sh.get('n')} "
                      f"({mib:.0f} MiB, over the "
                      f"{sh.get('shard_threshold_mib', 512):.0f} MiB "
                      f"shard threshold) -> status={sh.get('status')} "
                      f"via algorithm={sh.get('algorithm')} on "
                      f"{sh.get('devices')} devices "
                      f"(collective.select in the armed ledger; "
                      f"latency {sh.get('latency_s')}s)"]
    return "\n".join(lines)


def _run_scale(ns, methods: List[str]) -> int:
    """`--scale`: the ISSUE 13 open-loop scaling grid + sharded row
    (module docstring). One shared slow relay gates every series."""
    from tpu_reductions.bench.resume import Checkpoint
    from tpu_reductions.obs import ledger
    from tpu_reductions.serve.engine import ServeEngine
    from tpu_reductions.serve.request import ReduceRequest
    from tpu_reductions.serve.router import local_router

    n_choices = (max(1024, ns.n // 2), ns.n, ns.n * 2)
    counts = sorted({int(c) for c in ns.scale_clients.split(",")
                     if c.strip()})
    series_router = f"router{ns.replicas}"
    meta = {"instrument": "serving_scale",
            "dtype": DTYPE_ALIASES[ns.dtype], "methods": ",".join(methods),
            "n_choices": list(n_choices), "replicas": ns.replicas,
            "seed": ns.seed, "rate_factor": ns.rate_factor,
            "burst": ns.burst,
            "launch_latency_ms": ns.launch_latency_ms,
            "platform": ns.platform or "default"}
    ck = Checkpoint(ns.out, meta, key_fn=lambda r: r.get("key"))

    relay = None
    if ns.launch_latency_ms > 0:
        from tpu_reductions.faults.relay import FakeRelay
        from tpu_reductions.faults.schedule import Phase
        relay = FakeRelay([Phase("slow",
                                 delay_s=ns.launch_latency_ms / 1e3)])
        relay.start()

    def _transport():
        if relay is None:
            return None
        from tpu_reductions.serve.transport import RelayTransport
        return RelayTransport(ports=(relay.port,), assume_tunneled=True,
                              drain=True)

    def _prewarm(engines, up_to_batch):
        for e in engines:
            for m in methods:
                for n in n_choices:
                    e.prewarm(m, ns.dtype, n, up_to_batch=up_to_batch)

    # grid: every series at every client count (poisson), plus the
    # bursty stress rows at the middle count for the batched series
    cells = [(s, c, "poisson") for c in counts
             for s in ("sequential", "coalesced", series_router)]
    mid = counts[len(counts) // 2] if counts else 0
    cells += [(s, mid, "bursty") for s in ("coalesced", series_router)]
    try:
        for series, clients, process in cells:
            key = f"{series}@{clients}@{process}"
            prior = ck.resume(key,
                              reusable=lambda r: bool(r.get("requests")))
            if prior is not None:
                print(f"scale {key}: resumed from prior artifact",
                      file=sys.stderr)
                ck.add(prior)
                continue
            # same (seed, clients, process) -> same plan for EVERY
            # series: the 1-vs-N comparison replays one workload
            plan_seed = (ns.seed * 1_000_003 + clients * 31
                         + (1 if process == "bursty" else 0))
            plan = plan_workload(
                plan_seed, count=clients, methods=methods,
                dtype=ns.dtype, n_choices=n_choices,
                rate_rps=ns.rate_factor * clients, process=process,
                burst=ns.burst)
            common = dict(max_queue=max(2048, 2 * clients),
                          device_window_s=ns.device_window_ms / 1e3)
            if series == "sequential":
                target = ServeEngine(max_batch=1, coalesce_window_s=0.0,
                                     transport=_transport(),
                                     **common).start()
                submit_async, engines = target.submit, [target]
                batch = 1
            elif series == "coalesced":
                target = ServeEngine(max_batch=ns.max_batch,
                                     coalesce_window_s=0.0,
                                     transport=_transport(),
                                     **common).start()
                submit_async, engines = target.submit, [target]
                batch = ns.max_batch
            else:
                target = local_router(
                    ns.replicas,
                    engine_kwargs=dict(max_batch=ns.max_batch,
                                       coalesce_window_s=0.0,
                                       transports=[_transport()
                                                   for _ in
                                                   range(ns.replicas)],
                                       **common)).start()
                submit_async = target.submit
                engines = target.replicas
                batch = ns.max_batch
            _prewarm(engines, min(batch, 8))
            row = run_open_load(submit_async, plan, timeout_s=900)
            target.stop()
            ck.add({"key": key, "series": series, "clients": clients,
                    "process": process, **row})
            print(f"scale {key}: rps={row.get('rps')} "
                  f"p99_ms={row.get('p99_ms')}", file=sys.stderr)

        # the device-parallel sharded row: one oversized request
        # through the engine's shard path, algorithm choice read back
        # from the armed ledger's collective.select event
        prior = ck.resume("sharded",
                          reusable=lambda r: r.get("status") == "ok")
        if prior is not None:
            ck.add(prior)
        elif not ns.skip_sharded:
            ledger_path = ledger.arm(None)
            if ledger_path is None and ns.out:
                ledger_path = ledger.arm(ns.out + ".ledger.jsonl")
            req = ReduceRequest("SUM", "int", ns.sharded_n,
                                seed=ns.seed)
            engine = ServeEngine(max_queue=8, max_batch=4,
                                 transport=_transport()).start()
            resp = engine.submit(req).result(timeout=900)
            engine.stop()
            row = {"key": "sharded", "series": "sharded",
                   "status": resp.status, "n": req.n,
                   "nbytes": req.nbytes,
                   "shard_threshold_mib":
                       engine._shard_threshold / (1 << 20),
                   "result": resp.result, "error": resp.error,
                   "latency_s": resp.latency_s}
            row.update(_sharded_evidence(ledger_path))
            ck.add(row)
    finally:
        if relay is not None:
            relay.stop()
    if ns.out:
        ck.finalize()
    artifact = {**meta, "rows": ck.rows}
    print(scale_markdown(artifact))
    if ns.out:
        print(f"wrote {ns.out}")
    return 0


def _sharded_evidence(ledger_path: Optional[str]) -> dict:
    """Pull the sharded launch's algorithm choice back out of the
    armed ledger (collective.select / serve.verify events) so the
    committed artifact row carries the evidence pointer inline."""
    out: dict = {"ledger": ledger_path}
    if not ledger_path or not os.path.exists(ledger_path):
        return out
    try:
        with open(ledger_path) as f:
            for line in f:
                try:
                    ev = json.loads(line)
                except ValueError:
                    continue
                if ev.get("ev") == "collective.select":
                    out["algorithm"] = ev.get("algorithm")
                    out["wire_factor"] = ev.get("wire_factor")
                    out["quantized"] = ev.get("quantized")
                    out["ranks"] = ev.get("ranks")
                elif ev.get("ev") == "serve.verify":
                    if ev.get("devices") is not None:
                        out["devices"] = ev.get("devices")
    except OSError:
        pass
    return out


def elastic_markdown(artifact: dict) -> str:
    """The report.md section for the elastic fleet (bench/regen.py
    folds it after the scaling curve): replica trajectory per cell +
    the drain-vs-kill contract line."""
    lines = ["## elastic serving fleet (autoscaler tracking the "
             "diurnal plan)", ""]
    meta = ", ".join(f"{k}={artifact[k]}"
                     for k in ("plan", "slo_s", "autoscale_min",
                               "autoscale_max", "cooldown_s", "seed",
                               "platform")
                     if artifact.get(k) is not None)
    if meta:
        lines += [f"config: {meta}", ""]
    rows = [r for r in artifact.get("rows", []) if isinstance(r, dict)]
    cells = [r for r in rows if str(r.get("key", "")).startswith(
        "elastic@")]
    if cells:
        lines.append("| clients | req/s | p99 ms | in SLO | replicas "
                     "min..max | ups | downs | ok | other |")
        lines.append("|---|---|---|---|---|---|---|---|---|")
        for r in sorted(cells, key=lambda r: r.get("clients", 0)):
            other = ", ".join(
                f"{k}:{v}" for k, v in sorted(r.get("by_status",
                                                    {}).items())
                if k != "ok") or "-"
            lines.append(
                f"| {r.get('clients', '-')} | {r.get('rps', '-')} "
                f"| {r.get('p99_ms', '-')} "
                f"| {'yes' if r.get('p99_in_slo') else 'NO'} "
                f"| {r.get('replicas_min', '-')}.."
                f"{r.get('replicas_max', '-')} "
                f"| {r.get('scale_ups', '-')} "
                f"| {r.get('scale_downs', '-')} "
                f"| {r.get('ok', '-')} | {other} |")
    dr = next((r for r in rows if r.get("key") == "drain"), None)
    kl = next((r for r in rows if r.get("key") == "kill"), None)
    if dr and kl:
        rs = dr.get("reshard") or {}
        lines += ["", "drain-vs-kill on the same seeded mid-burst "
                      "workload: planned drain shed "
                      f"{dr.get('victim_shed')} requests (redistribution "
                      f"program {rs.get('program')} oracle-verified="
                      f"{rs.get('ok')}, measured peak-memory factor "
                      f"{rs.get('measured_mem_factor')} <= declared "
                      f"{rs.get('mem_factor')}); SIGKILL shed "
                      f"{kl.get('victim_shed')} in-flight requests the "
                      "router had to re-route"]
    return "\n".join(lines)


def _compress_trajectory(history: List[dict],
                         keep_every: int = 10) -> List[dict]:
    """The committed replica-count-vs-load trajectory: every tick that
    acted (or changed the replica count) plus every `keep_every`-th
    hold tick — bounded, but the scale-up/down story stays intact."""
    if not history:
        return []
    t0 = history[0].get("t", 0.0)
    out = []
    last_n = None
    for i, rec in enumerate(history):
        act = rec.get("action") != "hold"
        changed = rec.get("replicas") != last_n
        if act or changed or i % keep_every == 0 \
                or i == len(history) - 1:
            out.append({"t": round(rec.get("t", t0) - t0, 3),
                        "replicas": rec.get("replicas"),
                        "load": rec.get("load_per_replica"),
                        "queued": rec.get("queued"),
                        "action": rec.get("action")})
        last_n = rec.get("replicas")
    return out


def _run_elastic(ns, methods: List[str]) -> int:
    """`--elastic`: the ISSUE 17 elastic-fleet curve. Per client
    count, an autoscaled LocalReplica fleet (serve/autoscale.py)
    tracks the seeded --plan arrival shape — replica count must
    follow load while p99 stays inside the declared SLO — then the
    drain-vs-kill pair retires a replica mid-burst both ways on one
    seeded workload: the planned drain's victim sheds ZERO requests
    (warm keys handed off, partials resharded under the declared
    peak-memory bound, oracle-verified), the SIGKILL control's victim
    sheds its queue."""
    from tpu_reductions.bench.resume import Checkpoint
    from tpu_reductions.serve.autoscale import Autoscaler, drain_replica
    from tpu_reductions.serve.engine import ServeEngine
    from tpu_reductions.serve.executor import BatchExecutor
    from tpu_reductions.serve.router import LocalReplica, local_router
    from tpu_reductions import config as cfg

    n_choices = (max(1024, ns.n // 2), ns.n, ns.n * 2)
    counts = sorted({int(c) for c in ns.scale_clients.split(",")
                     if c.strip()})
    amin = cfg.autoscale_min(ns.autoscale_min)
    amax = cfg.autoscale_max(ns.autoscale_max)
    # flag > env > the CELL-scale default: an 8-second plan needs a
    # sub-second cooldown, not config.py's live-fleet 5 s
    cooldown = (ns.autoscale_cooldown_s
                if ns.autoscale_cooldown_s is not None
                else cfg._env_float("TPU_REDUCTIONS_AUTOSCALE_COOLDOWN_S"))
    if cooldown is None:
        cooldown = 0.75
    meta = {"instrument": "serving_elastic", "plan": ns.plan,
            "dtype": DTYPE_ALIASES[ns.dtype],
            "methods": ",".join(methods),
            "n_choices": list(n_choices), "seed": ns.seed,
            "slo_s": ns.slo_s, "autoscale_min": amin,
            "autoscale_max": amax, "cooldown_s": cooldown,
            "elastic_seconds": ns.elastic_seconds,
            "launch_latency_ms": ns.launch_latency_ms,
            "platform": ns.platform or "default"}
    ck = Checkpoint(ns.out, meta, key_fn=lambda r: r.get("key"))

    relay = None
    if ns.launch_latency_ms > 0:
        from tpu_reductions.faults.relay import FakeRelay
        from tpu_reductions.faults.schedule import Phase
        relay = FakeRelay([Phase("slow",
                                 delay_s=ns.launch_latency_ms / 1e3)])
        relay.start()

    def _transport():
        if relay is None:
            return None
        from tpu_reductions.serve.transport import RelayTransport
        return RelayTransport(ports=(relay.port,), assume_tunneled=True,
                              drain=True)

    executor = BatchExecutor()
    slo_classes = {"std": ns.slo_s}
    dk_relay = None

    def _engine_kwargs(clients):
        return dict(max_batch=ns.max_batch, coalesce_window_s=0.0,
                    device_window_s=ns.device_window_ms / 1e3,
                    max_queue=max(2048, 2 * clients),
                    slo_classes=dict(slo_classes))

    def _prewarm(replicas):
        for rep in replicas:
            for m in methods:
                for n in n_choices:
                    rep.prewarm(m, ns.dtype, n)

    def _epoch_table(plan):
        bounds, i = [], 0
        for (name, _, factor, proc), k in zip(
                DIURNAL_EPOCHS, diurnal_epoch_counts(len(plan))):
            if k <= 0:
                continue
            bounds.append({"epoch": name, "t0": round(plan[i][0], 3),
                           "arrivals": k, "rate_factor": factor,
                           "process": proc})
            i += k
        return bounds

    try:
        # -- the autoscaled cells: replica count tracks the plan ------
        for clients in counts:
            key = f"elastic@{clients}@{ns.plan}"
            prior = ck.resume(key,
                              reusable=lambda r: bool(r.get("requests")))
            if prior is not None:
                print(f"elastic {key}: resumed from prior artifact",
                      file=sys.stderr)
                ck.add(prior)
                continue
            base_rate = (clients * DIURNAL_TIME_FACTOR
                         / max(ns.elastic_seconds, 0.5)
                         if ns.plan == "diurnal"
                         else clients / max(ns.elastic_seconds, 0.5))
            plan_seed = ns.seed * 1_000_003 + clients * 31 + 7
            plan = plan_workload(
                plan_seed, count=clients, methods=methods,
                dtype=ns.dtype, n_choices=n_choices,
                rate_rps=base_rate, process=ns.plan, burst=ns.burst,
                slo="std")
            ekw = _engine_kwargs(clients)
            router = local_router(
                amin, engine_kwargs=dict(
                    transports=[_transport() for _ in range(amin)],
                    **ekw)).start()
            _prewarm(router.replicas)
            spawned = []

            def spawn(i, _ekw=ekw, _spawned=spawned):
                rep = LocalReplica(
                    f"replica-e{i}",
                    ServeEngine(transport=_transport(), **_ekw))
                _spawned.append(rep)
                return rep

            scaler = Autoscaler(
                router, spawn, min_replicas=amin, max_replicas=amax,
                cooldown_s=cooldown, slo_classes=dict(slo_classes),
                executor=executor, down_ticks=ns.down_ticks
            ).start(interval_s=ns.tick_s)
            row = run_open_load(router.submit, plan, timeout_s=900)
            # let the loop observe the post-plan calm so the ebb-side
            # story (scale-down back toward min) lands in-trajectory
            settle = time.monotonic() + max(
                4 * (cooldown + ns.down_ticks * ns.tick_s), 1.0)
            while time.monotonic() < settle:
                snap = router.load_snapshot()
                if sum(1 for r in snap["replicas"]
                       if r["alive"] and not r["draining"]) <= amin:
                    break
                time.sleep(ns.tick_s)
            scaler.stop()
            router.stop()
            hist = scaler.history
            ups = sum(1 for r in hist if r["action"] == "up")
            downs = sum(1 for r in hist if r["action"] == "down")
            p99_in_slo = (row.get("p99_ms") is not None
                          and row["p99_ms"] / 1e3 <= ns.slo_s)
            ck.add({"key": key, "clients": clients, "plan": ns.plan,
                    **row, "p99_in_slo": bool(p99_in_slo),
                    "slo_s": ns.slo_s,
                    "replicas_min": min(r["replicas"] for r in hist),
                    "replicas_max": max(r["replicas"] for r in hist),
                    "scale_ups": ups, "scale_downs": downs,
                    "ticks": len(hist),
                    "epochs": _epoch_table(plan),
                    "trajectory": _compress_trajectory(hist),
                    "drains": [d["reshard"] for d in scaler.drains
                               if d.get("reshard")]})
            print(f"elastic {key}: rps={row.get('rps')} "
                  f"p99_ms={row.get('p99_ms')} ups={ups} downs={downs}",
                  file=sys.stderr)

        # -- drain-vs-kill: one seeded mid-burst workload, two exits --
        dk_clients = counts[len(counts) // 2] if counts else 64
        dk_seed = ns.seed * 1_000_003 + dk_clients * 31 + 13
        # the pair runs behind a deliberately slow relay (>= 25 ms per
        # launch): a burst then genuinely QUEUES behind the in-flight
        # batch, so the SIGKILL's victim dies with work on its queue —
        # the loss the planned drain exists to avoid
        dk_latency_ms = max(ns.launch_latency_ms, 25.0)
        if dk_latency_ms > 0:
            from tpu_reductions.faults.relay import FakeRelay
            from tpu_reductions.faults.schedule import Phase
            dk_relay = FakeRelay([Phase("slow",
                                        delay_s=dk_latency_ms / 1e3)])
            dk_relay.start()

        def _dk_transport():
            if dk_relay is None:
                return None
            from tpu_reductions.serve.transport import RelayTransport
            return RelayTransport(ports=(dk_relay.port,),
                                  assume_tunneled=True, drain=True)

        for mode in ("drain", "kill"):
            prior = ck.resume(
                mode, reusable=lambda r: r.get("victim_shed") is not None)
            if prior is not None:
                ck.add(prior)
                continue
            plan = plan_workload(
                dk_seed, count=dk_clients, methods=methods,
                dtype=ns.dtype, n_choices=n_choices,
                rate_rps=4.0 * dk_clients, process="bursty",
                burst=ns.burst, slo="std")
            router = local_router(
                3, engine_kwargs=dict(
                    transports=[_dk_transport() for _ in range(3)],
                    **_engine_kwargs(dk_clients))).start()
            _prewarm(router.replicas)
            victim = router.replicas[-1]
            # trigger at the END of a burst run (a maximal run of
            # equal offsets past the 1/3 mark): the whole burst has
            # dispatched, the worker is inside a slow launch, and the
            # victim's share of the burst sits QUEUED — the contract's
            # hard case for both exits
            offsets = [off for off, _ in plan]
            s = len(offsets) // 3
            while s + 1 < len(offsets) \
                    and offsets[s + 1] != offsets[s]:
                s += 1
            trig = s
            while trig + 1 < len(offsets) \
                    and offsets[trig + 1] == offsets[s]:
                trig += 1
            fired = threading.Event()
            evidence: dict = {}

            def act(_router=router, _victim=victim, _mode=mode,
                    _evidence=evidence, _fired=fired):
                _fired.wait(timeout=60)
                if _mode == "drain":
                    _evidence.update(drain_replica(
                        _router, _victim, executor=executor))
                else:
                    # catch the victim with work ON ITS QUEUE — the
                    # work SIGKILL sheds and a planned drain serves:
                    # behind the slow relay the worker is inside a
                    # 25 ms+ launch round while later burst arrivals
                    # queue behind it
                    deadline = time.monotonic() + 30.0
                    while time.monotonic() < deadline \
                            and _victim.queued_depth() <= 0:
                        time.sleep(0.001)
                    _victim.kill()
                    _evidence["victim_stats"] = _victim.stats()

            actor = threading.Thread(target=act, daemon=True)
            actor.start()
            dispatched = [0]

            def submit(req, _router=router, _d=dispatched,
                       _fired=fired, _trig=trig):
                _d[0] += 1
                if _d[0] == _trig + 1:
                    _fired.set()
                return _router.submit(req)

            row = run_open_load(submit, plan, timeout_s=900)
            actor.join(timeout=120)
            # kill's shed counter lands when the engine stops; read
            # the victim's terminals AFTER the actor finished
            stats = evidence.get("victim_stats") or {}
            router.stop()
            ck.add({"key": mode, "clients": dk_clients,
                    "process": "bursty", **row,
                    "victim": victim.replica_id,
                    "victim_shed": int(stats.get("shed", 0)),
                    "victim_expired": int(stats.get("expired", 0)),
                    "reshard": evidence.get("reshard"),
                    "handoff_keys": len(evidence.get("handoff") or []),
                    "drain_rerouted":
                        router.stats.get("drain_rerouted", 0),
                    "rerouted": router.stats.get("rerouted", 0)})
            print(f"elastic {mode}: victim_shed={stats.get('shed', 0)} "
                  f"ok={row.get('ok')}", file=sys.stderr)
    finally:
        if relay is not None:
            relay.stop()
        if dk_relay is not None:
            dk_relay.stop()
    if ns.out:
        ck.finalize()
    artifact = {**meta, "rows": ck.rows}
    print(elastic_markdown(artifact))
    if ns.out:
        print(f"wrote {ns.out}")
    return 0


def recovery_markdown(artifact: dict) -> str:
    """The report.md section for the crash-recovery instrument
    (bench/regen.py folds it after the elastic fleet): per disruption
    scenario on ONE seeded idem-keyed workload, the MTTR / shed /
    duplicate-execution record the ISSUE 18 acceptance reads."""
    lines = ["## crash-consistent control plane (kill-router vs "
             "kill-replica vs drain)", ""]
    meta = ", ".join(f"{k}={artifact[k]}"
                     for k in ("dtype", "methods", "requests",
                               "crash_after", "seed", "platform")
                     if artifact.get(k) is not None)
    if meta:
        lines += [f"config: {meta}", ""]
    rows = [r for r in artifact.get("rows", []) if isinstance(r, dict)]
    if rows:
        lines.append("| scenario | requests | ok | shed | duplicate "
                     "device execs | dedup hits | MTTR s | adopted "
                     "| reaped | other |")
        lines.append("|---|---|---|---|---|---|---|---|---|---|")
        order = {"kill_router": 0, "kill_replica": 1, "drain": 2}
        for r in sorted(rows, key=lambda r: order.get(r.get("key"), 9)):
            other = ", ".join(
                f"{k}:{v}" for k, v in sorted(r.get("by_status",
                                                    {}).items())
                if k != "ok") or "-"
            mttr = r.get("mttr_s")
            lines.append(
                f"| {r.get('key', '-')} | {r.get('requests', '-')} "
                f"| {r.get('ok', '-')} | {r.get('shed', '-')} "
                f"| {r.get('duplicates', '-')} "
                f"| {r.get('dedup_hits', '-')} "
                f"| {f'{mttr:.3f}' if isinstance(mttr, (int, float)) else '-'} "
                f"| {r.get('adopted', '-')} | {r.get('reaped', '-')} "
                f"| {other} |")
    kr = next((r for r in rows if r.get("key") == "kill_router"), None)
    if kr:
        lines += ["", "controller SIGKILL mid-burst: the restarted "
                      "router re-adopted "
                      f"{kr.get('adopted')} journaled replica(s) in "
                      f"{kr.get('adopt_wall_s')} s, every retried "
                      "request carried its idempotency key, and the "
                      "ledger shows "
                      f"{kr.get('duplicates')} duplicate device "
                      f"execution(s) ({kr.get('dedup_hits')} retried "
                      "key(s) answered from the dedup cache without "
                      "re-touching the device)"]
    return "\n".join(lines)


def _stamp_idem(plan: List[Tuple], prefix: str) -> List[Tuple]:
    """Stamp every planned request with a client-supplied idempotency
    key (the exactly-once contract's join key): scenario-prefixed so
    one shared ledger separates the three scenarios' executions."""
    import dataclasses
    return [(off, dataclasses.replace(req, idem_key=f"{prefix}{i}"))
            for i, (off, req) in enumerate(plan)]


def _recovery_evidence(ledger_path: Optional[str], prefix: str) -> dict:
    """The ledger-verified exactly-once record for one scenario's key
    prefix: serve.coalesce launch-membership rows carry the
    idempotency keys of every request they put on the device (request
    ids are per-engine and collide across replicas, so the audit
    counts keys, never rids) — per-key launches beyond the first are
    the duplicate device executions, serve.dedup rows are the retries
    the cache answered WITHOUT a launch, and adopt.done is the
    adoption/MTTR record when a recovery ran."""
    out: dict = {"duplicates": 0, "dedup_hits": 0, "executed_keys": 0}
    if not ledger_path or not os.path.exists(ledger_path):
        return out
    execs: Dict[str, int] = {}
    paths = [p for p in (ledger_path + ".1", ledger_path)
             if os.path.exists(p)]      # rotation-aware, oldest first
    for path in paths:
        try:
            with open(path) as f:
                for line in f:
                    try:
                        ev = json.loads(line)
                    except ValueError:
                        continue
                    name = ev.get("ev")
                    if name == "serve.coalesce":
                        for idem in ev.get("idems") or []:
                            if isinstance(idem, str) \
                                    and idem.startswith(prefix):
                                execs[idem] = execs.get(idem, 0) + 1
                    elif name == "serve.dedup":
                        idem = ev.get("idem")
                        if isinstance(idem, str) \
                                and idem.startswith(prefix):
                            out["dedup_hits"] += 1
                    elif name == "adopt.done":
                        out["adopted"] = ev.get("adopted")
                        out["reaped"] = ev.get("reaped")
                        out["adopt_wall_s"] = ev.get("wall_s")
        except OSError:
            continue
    out["executed_keys"] = len(execs)
    out["duplicates"] = sum(max(0, c - 1) for c in execs.values())
    return out


def _recovery_client(port_file: str, plan: List[Tuple], *,
                     clients: int = 4,
                     retry_window_s: float = 90.0) -> List[dict]:
    """The kill-router scenario's TCP clients: `clients` threads split
    the idem-keyed plan; a broken connection (the controller died
    mid-burst) re-reads --port-file and RETRIES the same spec with the
    SAME idempotency key against whichever router is listening —
    at-least-once transport under the engine-side exactly-once cache.
    Returns one record per request: key, terminal status, attempts,
    and the completion wall clock (monotonic)."""
    rows: List[dict] = []
    lock = threading.Lock()

    def _port() -> Optional[int]:
        try:
            with open(port_file) as f:
                return int(f.read().strip())
        except (OSError, ValueError):
            return None

    def _one(req) -> dict:
        spec = {"method": req.method, "type": req.dtype, "n": req.n,
                "seed": req.seed, "idem_key": req.idem_key}
        deadline = time.monotonic() + retry_window_s
        attempts = 0
        err = "no attempt"
        while time.monotonic() < deadline:
            port = _port()
            if port is None:
                time.sleep(0.05)
                continue
            attempts += 1
            try:
                with socket.create_connection(("127.0.0.1", port),
                                              timeout=30) as sock:
                    sock.sendall((json.dumps(spec) + "\n").encode())
                    raw = sock.makefile("r").readline()
                if not raw:
                    raise ConnectionError("connection closed mid-request")
                d = json.loads(raw)
            except (OSError, ValueError) as e:
                err = f"{type(e).__name__}: {e}"
                time.sleep(0.05)
                continue
            return {"key": req.idem_key, "status": d.get("status"),
                    "attempts": attempts, "t_done": time.monotonic(),
                    "latency_s": d.get("latency_s")}
        return {"key": req.idem_key, "status": "client-error",
                "attempts": attempts, "t_done": time.monotonic(),
                "error": err}

    def _worker(slice_):
        for _, req in slice_:
            rec = _one(req)
            with lock:
                rows.append(rec)

    threads = [threading.Thread(target=_worker, args=(plan[c::clients],),
                                daemon=True)
               for c in range(clients)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    return rows


def _run_recovery(ns, methods: List[str]) -> int:
    """`--recovery`: the ISSUE 18 crash-recovery instrument. Three
    disruptions on ONE seeded idem-keyed workload shape:

      * kill_router — a REAL `serve.router --journal` subprocess over
        ProcessReplica children dies via the scripted `router.crash`
        os._exit mid-burst; the driver restarts it against the same
        journal; TCP clients retry broken requests with their original
        idempotency keys. The committed claim: zero duplicate device
        executions (ledger-joined), replicas re-adopted not respawned,
        MTTR in seconds.
      * kill_replica — SIGKILL-equivalent on one in-process replica
        mid-burst: the router re-routes carrying the keys, but a
        victim that already executed and shed its response re-executes
        on a survivor (separate dedup cache) — the honest at-least-once
        contrast the journal/dedup pair exists to beat.
      * drain — the planned exit (ISSUE 17): zero shed, zero
        duplicates, on the same workload.
    """
    import subprocess

    from tpu_reductions.bench.resume import Checkpoint
    from tpu_reductions.obs import ledger
    from tpu_reductions.serve.autoscale import drain_replica
    from tpu_reductions.serve.executor import BatchExecutor
    from tpu_reductions.serve.router import local_router

    meta = {"instrument": "serving_recovery",
            "dtype": DTYPE_ALIASES[ns.dtype],
            "methods": ",".join(methods), "n": ns.n,
            "requests": ns.recovery_requests,
            "crash_after": ns.crash_after, "seed": ns.seed,
            "platform": ns.platform or "default"}
    ck = Checkpoint(ns.out, meta, key_fn=lambda r: r.get("key"))
    ledger_path = None
    if ns.out:
        ledger_path = ledger.arm(ns.out + ".ledger.jsonl")
    n_choices = (max(1024, ns.n // 2), ns.n)

    def _plan(prefix: str):
        # same seed for every scenario: the three rows contrast the
        # EXIT, not the workload
        plan = plan_workload(
            ns.seed * 1_000_003 + 17, count=ns.recovery_requests,
            methods=methods, dtype=ns.dtype, n_choices=n_choices,
            rate_rps=8.0 * ns.recovery_requests, process="bursty",
            burst=ns.burst)
        return _stamp_idem(plan, prefix)

    def _reusable(r):
        return r.get("duplicates") is not None

    # -- kill_router: real subprocess controller, journaled fleet -----
    prior = ck.resume("kill_router", reusable=_reusable)
    if prior is not None:
        ck.add(prior)
    else:
        import shutil
        import tempfile
        workdir = tempfile.mkdtemp(prefix="recovery-")
        jpath = os.path.join(workdir, "fleet_journal.json")
        port_file = os.path.join(workdir, "port")
        env = dict(os.environ)
        if ledger_path:
            env["TPU_REDUCTIONS_LEDGER"] = ledger_path
        argv = [sys.executable, "-m", "tpu_reductions.serve.router",
                "--replicas", "2", "--journal", jpath,
                "--port-file", port_file, "--max-seconds", "300"]
        if ns.platform:
            argv += ["--platform", ns.platform]
        # the scripted controller death: os._exit on the
        # (crash_after+1)-th routed submit — no drain, no atexit,
        # children orphaned with the journal as their only record
        crash_env = dict(env)
        crash_env["TPU_REDUCTIONS_FAULTS"] = json.dumps(
            {"router.crash": {"after": ns.crash_after,
                              "action": "exit", "code": 86}})
        plan = _plan("kr-")
        procs: List = []
        t_death = [None]

        def _spawn(e):
            if os.path.exists(port_file):
                os.unlink(port_file)
            proc = subprocess.Popen(argv, env=e,
                                    stdout=subprocess.DEVNULL,
                                    stderr=subprocess.DEVNULL)
            procs.append(proc)
            deadline = time.monotonic() + 60
            while time.monotonic() < deadline:
                if os.path.exists(port_file):
                    return proc
                if proc.poll() is not None:
                    break
                time.sleep(0.05)
            raise RuntimeError("router subprocess never published "
                               f"its port (exit {proc.poll()})")

        rows: List[dict] = []
        try:
            proc1 = _spawn(crash_env)
            client = threading.Thread(
                target=lambda: rows.extend(
                    _recovery_client(port_file, plan)), daemon=True)
            client.start()
            # the driver IS the supervisor here: watch for the scripted
            # death, restart against the same journal (fault disarmed)
            while client.is_alive():
                if t_death[0] is None and proc1.poll() is not None:
                    t_death[0] = time.monotonic()
                    _spawn(env)
                client.join(timeout=0.05)
            client.join()
            mttr = None
            if t_death[0] is not None:
                after = [r["t_done"] for r in rows
                         if r.get("status") == "ok"
                         and r["t_done"] > t_death[0]]
                if after:
                    mttr = round(min(after) - t_death[0], 6)
            lat = sorted(r["latency_s"] for r in rows
                         if r.get("status") == "ok"
                         and isinstance(r.get("latency_s"),
                                        (int, float)))
            by_status: Dict[str, int] = {}
            for r in rows:
                s = r.get("status") or "?"
                by_status[s] = by_status.get(s, 0) + 1
            row = {"key": "kill_router", "requests": len(rows),
                   "ok": by_status.get("ok", 0),
                   "by_status": by_status,
                   "retried": sum(1 for r in rows
                                  if r.get("attempts", 1) > 1),
                   "router_exit": 86, "shed": 0, "mttr_s": mttr}
            if lat:
                row["p50_ms"] = round(percentile(lat, 0.50) * 1e3, 3)
                row["p99_ms"] = round(percentile(lat, 0.99) * 1e3, 3)
        finally:
            for proc in procs:
                if proc.poll() is None:
                    proc.send_signal(2)     # SIGINT: drain, never wedge
            for proc in procs:
                if proc.poll() is None:
                    try:
                        proc.wait(timeout=30)
                    except subprocess.TimeoutExpired:
                        proc.kill()
            shutil.rmtree(workdir, ignore_errors=True)
        row.update(_recovery_evidence(ledger_path, "kr-"))
        ck.add(row)
        print(f"recovery kill_router: ok={row.get('ok')} "
              f"duplicates={row.get('duplicates')} "
              f"mttr_s={row.get('mttr_s')}", file=sys.stderr)

    # -- kill_replica / drain: in-process contrast pair ---------------
    executor = BatchExecutor()
    for mode, prefix in (("kill_replica", "krep-"), ("drain", "dr-")):
        prior = ck.resume(mode, reusable=_reusable)
        if prior is not None:
            ck.add(prior)
            continue
        plan = _plan(prefix)
        router = local_router(3, engine_kwargs=dict(
            max_batch=ns.max_batch, coalesce_window_s=0.0,
            max_queue=max(2048, 2 * len(plan)))).start()
        victim = router.replicas[-1]
        trig = max(1, len(plan) // 3)
        fired = threading.Event()
        t_disrupt = [None]

        def act(_mode=mode, _victim=victim, _fired=fired,
                _t=t_disrupt):
            _fired.wait(timeout=60)
            _t[0] = time.monotonic()
            if _mode == "drain":
                drain_replica(router, _victim, executor=executor)
            else:
                _victim.kill()

        actor = threading.Thread(target=act, daemon=True)
        actor.start()
        dispatched = [0]

        def submit(req, _router=router, _d=dispatched, _fired=fired,
                   _trig=trig):
            _d[0] += 1
            if _d[0] == _trig + 1:
                _fired.set()
            return _router.submit(req)

        row = run_open_load(submit, plan, timeout_s=300)
        actor.join(timeout=60)
        stats = victim.stats()
        router.stop()
        out_row = {"key": mode, **row,
                   "victim": victim.replica_id,
                   "shed": int(stats.get("shed", 0)),
                   "rerouted": router.stats.get("rerouted", 0),
                   "drain_rerouted":
                       router.stats.get("drain_rerouted", 0)}
        if t_disrupt[0] is not None:
            out_row["mttr_s"] = 0.0     # in-process re-route: no gap
        evidence = _recovery_evidence(ledger_path, prefix)
        for k in ("adopted", "reaped", "adopt_wall_s"):
            # the adoption record belongs to kill_router alone — the
            # shared ledger's adopt.done is not prefix-scoped
            evidence.pop(k, None)
        out_row.update(evidence)
        ck.add(out_row)
        print(f"recovery {mode}: ok={row.get('ok')} "
              f"shed={out_row['shed']} "
              f"duplicates={out_row.get('duplicates')}",
              file=sys.stderr)

    if ns.out:
        ck.finalize()
    artifact = {**meta, "rows": ck.rows}
    print(recovery_markdown(artifact))
    if ns.out:
        print(f"wrote {ns.out}")
    return 0


def _tcp_submit(addr: str):
    """A submit() against the TCP front end: one connection per client
    thread (thread-local), one JSON line per request/response."""
    host, _, port = addr.rpartition(":")
    local = threading.local()

    from tpu_reductions.serve.request import ReduceResponse

    def submit(req):
        if getattr(local, "sock", None) is None:
            local.sock = socket.create_connection((host or "127.0.0.1",
                                                   int(port)), timeout=60)
            local.rfile = local.sock.makefile("r")
        line = json.dumps({"method": req.method, "type": req.dtype,
                           "n": req.n, "seed": req.seed,
                           "deadline_s": req.deadline_s,
                           # retries carry the key: the engine-side
                           # dedup cache makes the retry exactly-once
                           **({"idem_key": req.idem_key}
                              if req.idem_key else {})}) + "\n"
        local.sock.sendall(line.encode())
        raw = local.rfile.readline()
        if not raw:
            raise ConnectionError("server closed the connection")
        d = json.loads(raw)
        return ReduceResponse(
            d.get("request_id", "?"), d.get("status", "error"),
            d.get("method", req.method), d.get("dtype", req.dtype),
            d.get("n", req.n), result=d.get("result"),
            error=d.get("error"), latency_s=d.get("latency_s"),
            queue_s=d.get("queue_s"), batch_size=d.get("batch_size"))

    return submit


def main(argv=None) -> int:
    """CLI (module docstring): measure the serving curve, persist it,
    print the table."""
    p = argparse.ArgumentParser(
        prog="tpu_reductions.serve.loadgen",
        description="Closed-loop load generator for the serving engine "
                    "(requests/s + p50/p99 at N concurrent clients)")
    p.add_argument("--clients", type=int, default=8)
    p.add_argument("--requests", type=int, default=32,
                   help="requests per client (closed loop)")
    p.add_argument("--n", type=int, default=65536)
    p.add_argument("--type", dest="dtype", default="int")
    p.add_argument("--methods", default="SUM,MIN,MAX",
                   help="comma-separated mix; clients interleave it")
    p.add_argument("--deadline-s", type=float, default=None,
                   help="per-request deadline (default: none)")
    p.add_argument("--coalesce-window-ms", type=float, default=0.0,
                   help="0 = continuous batching (batches form from "
                        "whatever queued while the previous launch "
                        "ran — the closed-loop measurement mode); a "
                        "positive window suits bursty open-loop "
                        "traffic at a latency cost")
    p.add_argument("--device-window-ms", type=float, default=250.0)
    p.add_argument("--max-batch", type=int, default=32)
    p.add_argument("--max-queue", type=int, default=1024,
                   help="admission bound (generous: the loadgen "
                        "measures latency, not rejection, by default)")
    p.add_argument("--launch-latency-ms", type=float, default=2.0,
                   help="modeled per-launch transport round-trip, "
                        "injected through a local chaos relay in "
                        "`slow` mode (faults/relay.py) and the "
                        "engine's transport gate — the off-chip "
                        "stand-in for the tunnel's per-launch "
                        "materialization RTT (docs/TIMING.md; both "
                        "modes pay it identically, coalescing "
                        "amortizes it per batch). 0 disables (raw "
                        "host-only measurement)")
    p.add_argument("--modes", default="coalesced,sequential",
                   help="which engine modes to measure")
    p.add_argument("--connect", default=None,
                   help="HOST:PORT of a running `python -m "
                        "tpu_reductions.serve` (one 'remote' row "
                        "instead of the in-process modes)")
    p.add_argument("--seed", type=int, default=0,
                   help="workload RNG seed — same seed, same plan "
                        "(arrival offsets and request specs)")
    p.add_argument("--scale", action="store_true",
                   help="ISSUE 13 mode: the open-loop scaling grid "
                        "(sequential/coalesced/routerN x "
                        "--scale-clients x poisson+bursty) plus the "
                        "device-parallel sharded row; writes "
                        "serving_scale.json-shaped artifact to --out")
    p.add_argument("--scale-clients", default="64,256,1024",
                   help="open-loop client counts for the scale grid")
    p.add_argument("--replicas", type=int, default=4,
                   help="router replica count for the routerN series")
    p.add_argument("--rate-factor", type=float, default=8.0,
                   help="open-loop aggregate arrival rate = factor x "
                        "clients req/s (past single-engine saturation "
                        "by construction, so rps measures capacity)")
    p.add_argument("--burst", type=int, default=32,
                   help="arrivals per burst epoch in the bursty process")
    p.add_argument("--sharded-n", type=int, default=160_000_000,
                   help="element count of the sharded row's oversized "
                        "request (default: 640 MiB of int32, over the "
                        "512 MiB shard threshold)")
    p.add_argument("--skip-sharded", action="store_true",
                   help="omit the sharded row from --scale")
    p.add_argument("--elastic", action="store_true",
                   help="ISSUE 17 mode: autoscaled fleet tracking the "
                        "--plan arrival shape per --scale-clients "
                        "count, plus the drain-vs-kill contract pair; "
                        "writes serving_elastic.json-shaped artifact "
                        "to --out")
    p.add_argument("--plan", default="diurnal",
                   choices=("diurnal", "poisson", "bursty"),
                   help="arrival plan for the --elastic cells (the "
                        "seeded ramp/burst/ebb/peak/tail composition "
                        "by default)")
    p.add_argument("--elastic-seconds", type=float, default=8.0,
                   help="target wall-clock span of one elastic cell's "
                        "plan (the base arrival rate derives from it)")
    p.add_argument("--slo-s", type=float, default=5.0,
                   help="declared SLO deadline (class 'std') the "
                        "elastic cells must hold p99 inside")
    p.add_argument("--tick-s", type=float, default=0.05,
                   help="autoscaler control-loop interval (--elastic)")
    p.add_argument("--down-ticks", type=int, default=3,
                   help="consecutive calm ticks before a scale-down "
                        "(the hysteresis depth; serve/autoscale.py)")
    p.add_argument("--autoscale-min", type=int, default=None,
                   help="fleet floor (default: "
                        "TPU_REDUCTIONS_AUTOSCALE_MIN or 1)")
    p.add_argument("--autoscale-max", type=int, default=None,
                   help="fleet ceiling (default: "
                        "TPU_REDUCTIONS_AUTOSCALE_MAX or 8)")
    p.add_argument("--autoscale-cooldown-s", type=float, default=None,
                   help="post-action cooldown (default: "
                        "TPU_REDUCTIONS_AUTOSCALE_COOLDOWN_S or 0.75 "
                        "— cell-scale; config.py's 5 s default suits "
                        "live fleets)")
    p.add_argument("--recovery", action="store_true",
                   help="ISSUE 18 mode: kill-router / kill-replica / "
                        "drain on one seeded idem-keyed workload — "
                        "MTTR, shed count, and ledger-verified "
                        "duplicate device executions per scenario; "
                        "writes serving_recovery.json-shaped artifact "
                        "to --out (docs/SERVING.md crash-consistent "
                        "control plane)")
    p.add_argument("--recovery-requests", type=int, default=48,
                   help="requests per --recovery scenario")
    p.add_argument("--crash-after", type=int, default=16,
                   help="routed submits before the scripted "
                        "router.crash os._exit (--recovery)")
    p.add_argument("--devices", dest="num_devices", type=int,
                   default=None,
                   help="virtual CPU device count (--platform=cpu; "
                        "the sharded row needs >1)")
    p.add_argument("--platform", default=None, choices=("cpu", "tpu"))
    p.add_argument("--out", default=None)
    ns = p.parse_args(argv)
    methods = [m.strip().upper() for m in ns.methods.split(",")
               if m.strip()]
    if not methods or any(m not in METHODS for m in methods):
        p.error(f"--methods must name only {METHODS}, got {ns.methods!r}")
    if ns.dtype not in DTYPE_ALIASES:
        p.error(f"unknown --type {ns.dtype!r}")
    _apply_platform(ns)

    from tpu_reductions.obs.ledger import arm_session
    arm_session("serve.loadgen",
                argv=list(argv) if argv else sys.argv[1:])
    from tpu_reductions.exec.core import maybe_arm_for_tpu
    maybe_arm_for_tpu()   # a loadgen hung on a dead relay reports nothing

    if ns.scale:
        if ns.connect:
            p.error("--scale drives in-process engines/routers; "
                    "--connect is the single-engine TCP mode")
        return _run_scale(ns, methods)
    if ns.elastic:
        if ns.connect:
            p.error("--elastic drives in-process autoscaled fleets; "
                    "--connect is the single-engine TCP mode")
        return _run_elastic(ns, methods)
    if ns.recovery:
        if ns.connect:
            p.error("--recovery drives its own router subprocess and "
                    "in-process fleets; --connect is the single-engine "
                    "TCP mode")
        return _run_recovery(ns, methods)

    meta = {"dtype": DTYPE_ALIASES[ns.dtype], "n": ns.n,
            "methods": ",".join(methods), "clients": ns.clients,
            "requests_per_client": ns.requests,
            "launch_latency_ms": ns.launch_latency_ms,
            "seed": ns.seed,
            "platform": ns.platform or "default"}
    from tpu_reductions.bench.resume import Checkpoint
    ck = Checkpoint(ns.out, meta, key_fn=lambda r: r.get("mode"))

    # the modeled transport: a local chaos relay in `slow` mode and
    # the engine's per-launch gate pointed straight at it (no env
    # mutation) — the latency-injection satellite doing double duty as
    # the off-chip tunnel model
    relay = None
    if ns.launch_latency_ms > 0 and not ns.connect:
        from tpu_reductions.faults.relay import FakeRelay
        from tpu_reductions.faults.schedule import Phase
        relay = FakeRelay([Phase("slow",
                                 delay_s=ns.launch_latency_ms / 1e3)])
        relay.start()

    def _transport():
        if relay is None:
            return None
        from tpu_reductions.serve.transport import RelayTransport
        return RelayTransport(ports=(relay.port,), assume_tunneled=True,
                              drain=True)

    modes = ([m.strip() for m in ns.modes.split(",") if m.strip()]
             if not ns.connect else ["remote"])
    for mode in modes:
        # curve rows carry no PASSED/ok verdict field — a prior row is
        # reusable iff it actually measured something
        prior = ck.resume(mode,
                          reusable=lambda r: bool(r.get("requests")))
        if prior is not None:
            print(f"loadgen {mode}: resumed from prior artifact",
                  file=sys.stderr)
            ck.add(prior)
            continue
        if ns.connect:
            submit = _tcp_submit(ns.connect)
            row = run_load(submit, clients=ns.clients,
                           requests=ns.requests, methods=methods,
                           dtype=ns.dtype, n=ns.n,
                           deadline_s=ns.deadline_s, seed=ns.seed)
        else:
            from tpu_reductions.serve.engine import ServeEngine
            engine = ServeEngine(
                max_queue=ns.max_queue,
                max_batch=(1 if mode == "sequential" else ns.max_batch),
                coalesce_window_s=(0.0 if mode == "sequential"
                                   else ns.coalesce_window_ms / 1e3),
                device_window_s=ns.device_window_ms / 1e3,
                transport=_transport())
            engine.start()

            def submit(req, _engine=engine):
                return _engine.submit(req).result(timeout=600)

            # warm every jit bucket OUTSIDE the measured window so both
            # modes pay compile once and the curve measures serving,
            # not compilation (the .jax_cache doctrine)
            for m in methods:
                engine.prewarm(m, ns.dtype, ns.n,
                               up_to_batch=(1 if mode == "sequential"
                                            else min(ns.clients,
                                                     ns.max_batch)))
            row = run_load(submit, clients=ns.clients,
                           requests=ns.requests, methods=methods,
                           dtype=ns.dtype, n=ns.n,
                           deadline_s=ns.deadline_s, seed=ns.seed)
            engine.stop()
        row = {"mode": mode, **row}
        ck.add(row)
    if relay is not None:
        relay.stop()
    if ns.out:
        ck.finalize()
    artifact = {**meta, "rows": ck.rows}
    print(curve_markdown(artifact))
    if ns.out:
        print(f"wrote {ns.out}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
