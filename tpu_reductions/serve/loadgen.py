"""Closed-loop load generator + the committed serving curve.

N client threads each drive a closed loop of reduction requests
against the engine (submit, wait, submit — concurrency == clients, the
classic closed-loop load model) and the run distills into the serving
curve next to GB/s: requests/s and p50/p99 latency at N concurrent
clients. Two modes run back to back on the SAME workload and executor:

  * `coalesced`  — the engine as shipped (compatible concurrent
    requests fuse into stacked launches);
  * `sequential` — max_batch=1: N single-request launches, the
    pre-engine baseline.

The ratio of their requests/s is the acceptance number of ISSUE 6
("coalesced batched launches demonstrably beat N sequential
single-request launches on the same off-chip workload"). Entirely
runnable on --platform=cpu with the relay dead.

Artifact: bench/resume.Checkpoint shape ({meta, complete, rows}), one
row per mode, persisted the moment each mode finishes;
`bench/regen.py` folds it into report.md via `curve_markdown`.

CLI:
    python -m tpu_reductions.serve.loadgen --platform=cpu --clients=8 \
        [--requests=32 --n=65536 --methods=SUM,MIN,MAX --type=int] \
        [--connect HOST:PORT] --out=serving_curve.json
"""

from __future__ import annotations

import argparse
import json
import socket
import sys
import threading
import time
from typing import Dict, List, Optional

from tpu_reductions.config import DTYPE_ALIASES, METHODS, _apply_platform


def percentile(sorted_vals: List[float], q: float) -> float:
    """Nearest-rank percentile over an already-sorted list (the same
    estimator sched/priors.py uses for window quantiles)."""
    if not sorted_vals:
        raise ValueError("percentile of empty sample")
    idx = min(len(sorted_vals) - 1,
              max(0, int(round(q * (len(sorted_vals) - 1)))))
    return sorted_vals[idx]


def _client_loop(submit, client: int, requests: int, methods: List[str],
                 dtype: str, n: int, deadline_s: Optional[float],
                 out: List[dict], barrier: threading.Barrier) -> None:
    from tpu_reductions.serve.request import ReduceRequest
    barrier.wait()
    for i in range(requests):
        # wave-aligned mix: in a closed loop the clients advance in
        # rough lockstep, so indexing by i alone gives each wave ONE
        # method — the concurrency shape coalescing exists for (a
        # per-client offset would guarantee mixed keys every wave and
        # measure the scheduler instead of the batcher)
        req = ReduceRequest(method=methods[i % len(methods)],
                            dtype=dtype, n=n,
                            seed=client * 100003 + i,
                            deadline_s=deadline_s)
        t0 = time.monotonic()
        try:
            resp = submit(req)
        except Exception as e:              # a client error is a row,
            out.append({"status": "client-error",   # never a crash
                        "latency_s": time.monotonic() - t0,
                        "error": f"{type(e).__name__}: {e}"})
            continue
        # the request id is the request's trace id (ISSUE 12): stamped
        # through the response path so rows join the ledger's
        # serve.enqueue/respond events BY ID, never positionally
        # (obs/timeline.serve_summary flags the orphans)
        out.append({"req": resp.request_id,
                    "status": resp.status,
                    "latency_s": (resp.latency_s
                                  if resp.latency_s is not None
                                  else time.monotonic() - t0),
                    "batch_size": resp.batch_size})


def run_load(submit, *, clients: int, requests: int, methods: List[str],
             dtype: str, n: int,
             deadline_s: Optional[float] = None) -> dict:
    """Drive the closed loop; `submit(req) -> ReduceResponse` is either
    the in-process engine (resolved PendingResponse) or the TCP client.
    Returns the raw per-mode measurement (one curve row, mode-less)."""
    per_client: List[List[dict]] = [[] for _ in range(clients)]
    barrier = threading.Barrier(clients + 1)
    threads = [threading.Thread(
        target=_client_loop,
        args=(submit, c, requests, methods, dtype, n, deadline_s,
              per_client[c], barrier), daemon=True)
        for c in range(clients)]
    for t in threads:
        t.start()
    barrier.wait()
    t0 = time.monotonic()
    for t in threads:
        t.join()
    wall = max(time.monotonic() - t0, 1e-9)
    rows = [r for recs in per_client for r in recs]
    by_status: Dict[str, int] = {}
    for r in rows:
        by_status[r["status"]] = by_status.get(r["status"], 0) + 1
    ok_lat = sorted(r["latency_s"] for r in rows
                    if r["status"] == "ok"
                    and isinstance(r.get("latency_s"), (int, float)))
    sizes = [r["batch_size"] for r in rows
             if isinstance(r.get("batch_size"), int)]
    row = {
        "clients": clients,
        "requests": len(rows),
        "wall_s": round(wall, 6),
        "rps": round(len(rows) / wall, 2),
        "ok": by_status.get("ok", 0),
        "by_status": by_status,
        "mean_batch": (round(sum(sizes) / len(sizes), 2)
                       if sizes else None),
    }
    if ok_lat:
        row["p50_ms"] = round(percentile(ok_lat, 0.50) * 1e3, 3)
        row["p99_ms"] = round(percentile(ok_lat, 0.99) * 1e3, 3)
    return row


def curve_markdown(artifact: dict) -> str:
    """The report.md section bench/regen.py appends: the serving curve
    next to the GB/s tables."""
    lines = ["## serving under concurrent load (requests/s, latency)",
             ""]
    meta = ", ".join(f"{k}={artifact[k]}"
                     for k in ("dtype", "n", "methods", "platform",
                               "launch_latency_ms")
                     if artifact.get(k) is not None)
    if meta:
        lines += [f"workload: {meta}", ""]
    lines.append("| mode | clients | requests | req/s | p50 ms "
                 "| p99 ms | mean batch | ok | other |")
    lines.append("|---|---|---|---|---|---|---|---|---|")
    rows = {r.get("mode"): r for r in artifact.get("rows", [])
            if isinstance(r, dict)}
    for mode, r in rows.items():
        other = ", ".join(f"{k}:{v}"
                          for k, v in sorted(r.get("by_status",
                                                   {}).items())
                          if k != "ok") or "-"
        lines.append(
            f"| {mode} | {r.get('clients', '-')} "
            f"| {r.get('requests', '-')} | {r.get('rps', '-')} "
            f"| {r.get('p50_ms', '-')} | {r.get('p99_ms', '-')} "
            f"| {r.get('mean_batch', '-')} | {r.get('ok', '-')} "
            f"| {other} |")
    co, seq = rows.get("coalesced"), rows.get("sequential")
    if co and seq and seq.get("rps"):
        lines += ["", f"coalescing speedup: "
                      f"{co['rps'] / seq['rps']:.2f}x requests/s "
                      "(same workload, same executor, batch size 1 vs "
                      "coalesced)"]
    return "\n".join(lines)


def _tcp_submit(addr: str):
    """A submit() against the TCP front end: one connection per client
    thread (thread-local), one JSON line per request/response."""
    host, _, port = addr.rpartition(":")
    local = threading.local()

    from tpu_reductions.serve.request import ReduceResponse

    def submit(req):
        if getattr(local, "sock", None) is None:
            local.sock = socket.create_connection((host or "127.0.0.1",
                                                   int(port)), timeout=60)
            local.rfile = local.sock.makefile("r")
        line = json.dumps({"method": req.method, "type": req.dtype,
                           "n": req.n, "seed": req.seed,
                           "deadline_s": req.deadline_s}) + "\n"
        local.sock.sendall(line.encode())
        raw = local.rfile.readline()
        if not raw:
            raise ConnectionError("server closed the connection")
        d = json.loads(raw)
        return ReduceResponse(
            d.get("request_id", "?"), d.get("status", "error"),
            d.get("method", req.method), d.get("dtype", req.dtype),
            d.get("n", req.n), result=d.get("result"),
            error=d.get("error"), latency_s=d.get("latency_s"),
            queue_s=d.get("queue_s"), batch_size=d.get("batch_size"))

    return submit


def main(argv=None) -> int:
    """CLI (module docstring): measure the serving curve, persist it,
    print the table."""
    p = argparse.ArgumentParser(
        prog="tpu_reductions.serve.loadgen",
        description="Closed-loop load generator for the serving engine "
                    "(requests/s + p50/p99 at N concurrent clients)")
    p.add_argument("--clients", type=int, default=8)
    p.add_argument("--requests", type=int, default=32,
                   help="requests per client (closed loop)")
    p.add_argument("--n", type=int, default=65536)
    p.add_argument("--type", dest="dtype", default="int")
    p.add_argument("--methods", default="SUM,MIN,MAX",
                   help="comma-separated mix; clients interleave it")
    p.add_argument("--deadline-s", type=float, default=None,
                   help="per-request deadline (default: none)")
    p.add_argument("--coalesce-window-ms", type=float, default=0.0,
                   help="0 = continuous batching (batches form from "
                        "whatever queued while the previous launch "
                        "ran — the closed-loop measurement mode); a "
                        "positive window suits bursty open-loop "
                        "traffic at a latency cost")
    p.add_argument("--device-window-ms", type=float, default=250.0)
    p.add_argument("--max-batch", type=int, default=32)
    p.add_argument("--max-queue", type=int, default=1024,
                   help="admission bound (generous: the loadgen "
                        "measures latency, not rejection, by default)")
    p.add_argument("--launch-latency-ms", type=float, default=2.0,
                   help="modeled per-launch transport round-trip, "
                        "injected through a local chaos relay in "
                        "`slow` mode (faults/relay.py) and the "
                        "engine's transport gate — the off-chip "
                        "stand-in for the tunnel's per-launch "
                        "materialization RTT (docs/TIMING.md; both "
                        "modes pay it identically, coalescing "
                        "amortizes it per batch). 0 disables (raw "
                        "host-only measurement)")
    p.add_argument("--modes", default="coalesced,sequential",
                   help="which engine modes to measure")
    p.add_argument("--connect", default=None,
                   help="HOST:PORT of a running `python -m "
                        "tpu_reductions.serve` (one 'remote' row "
                        "instead of the in-process modes)")
    p.add_argument("--platform", default=None, choices=("cpu", "tpu"))
    p.add_argument("--out", default=None)
    ns = p.parse_args(argv)
    methods = [m.strip().upper() for m in ns.methods.split(",")
               if m.strip()]
    if not methods or any(m not in METHODS for m in methods):
        p.error(f"--methods must name only {METHODS}, got {ns.methods!r}")
    if ns.dtype not in DTYPE_ALIASES:
        p.error(f"unknown --type {ns.dtype!r}")
    _apply_platform(ns)

    from tpu_reductions.obs.ledger import arm_session
    arm_session("serve.loadgen",
                argv=list(argv) if argv else sys.argv[1:])
    from tpu_reductions.utils.watchdog import maybe_arm_for_tpu
    maybe_arm_for_tpu()   # a loadgen hung on a dead relay reports nothing

    meta = {"dtype": DTYPE_ALIASES[ns.dtype], "n": ns.n,
            "methods": ",".join(methods), "clients": ns.clients,
            "requests_per_client": ns.requests,
            "launch_latency_ms": ns.launch_latency_ms,
            "platform": ns.platform or "default"}
    from tpu_reductions.bench.resume import Checkpoint
    ck = Checkpoint(ns.out, meta, key_fn=lambda r: r.get("mode"))

    # the modeled transport: a local chaos relay in `slow` mode and
    # the engine's per-launch gate pointed straight at it (no env
    # mutation) — the latency-injection satellite doing double duty as
    # the off-chip tunnel model
    relay = None
    if ns.launch_latency_ms > 0 and not ns.connect:
        from tpu_reductions.faults.relay import FakeRelay
        from tpu_reductions.faults.schedule import Phase
        relay = FakeRelay([Phase("slow",
                                 delay_s=ns.launch_latency_ms / 1e3)])
        relay.start()

    def _transport():
        if relay is None:
            return None
        from tpu_reductions.serve.transport import RelayTransport
        return RelayTransport(ports=(relay.port,), assume_tunneled=True,
                              drain=True)

    modes = ([m.strip() for m in ns.modes.split(",") if m.strip()]
             if not ns.connect else ["remote"])
    for mode in modes:
        # curve rows carry no PASSED/ok verdict field — a prior row is
        # reusable iff it actually measured something
        prior = ck.resume(mode,
                          reusable=lambda r: bool(r.get("requests")))
        if prior is not None:
            print(f"loadgen {mode}: resumed from prior artifact",
                  file=sys.stderr)
            ck.add(prior)
            continue
        if ns.connect:
            submit = _tcp_submit(ns.connect)
            row = run_load(submit, clients=ns.clients,
                           requests=ns.requests, methods=methods,
                           dtype=ns.dtype, n=ns.n,
                           deadline_s=ns.deadline_s)
        else:
            from tpu_reductions.serve.engine import ServeEngine
            engine = ServeEngine(
                max_queue=ns.max_queue,
                max_batch=(1 if mode == "sequential" else ns.max_batch),
                coalesce_window_s=(0.0 if mode == "sequential"
                                   else ns.coalesce_window_ms / 1e3),
                device_window_s=ns.device_window_ms / 1e3,
                transport=_transport())
            engine.start()

            def submit(req, _engine=engine):
                return _engine.submit(req).result(timeout=600)

            # warm every jit bucket OUTSIDE the measured window so both
            # modes pay compile once and the curve measures serving,
            # not compilation (the .jax_cache doctrine)
            for m in methods:
                engine.prewarm(m, ns.dtype, ns.n,
                               up_to_batch=(1 if mode == "sequential"
                                            else min(ns.clients,
                                                     ns.max_batch)))
            row = run_load(submit, clients=ns.clients,
                           requests=ns.requests, methods=methods,
                           dtype=ns.dtype, n=ns.n,
                           deadline_s=ns.deadline_s)
            engine.stop()
        row = {"mode": mode, **row}
        ck.add(row)
    if relay is not None:
        relay.stop()
    if ns.out:
        ck.finalize()
    artifact = {**meta, "rows": ck.rows}
    print(curve_markdown(artifact))
    if ns.out:
        print(f"wrote {ns.out}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
