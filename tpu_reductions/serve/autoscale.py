"""Elastic serving fleet: the autoscaler control loop and the
drain-as-reshard scale-down protocol (ISSUE 17; docs/SERVING.md
"elastic fleet").

The reference sweeps rank counts because bandwidth-per-rank is the
story (`mpi/reduce.c:64-97` runs the same reduce at 64..1024 ranks);
this module closes the serving-side analog: capacity that FOLLOWS
load instead of a fixed `--replicas N`. A jax-free control loop reads
signals the stack already emits — rolling p99 per SLO class
(serve/engine._SLOTracker), queued depth, per-replica outstanding
(the same numbers route.* ledger events carry) — and spawns or
retires replicas behind the ReplicaRouter under hysteresis + cooldown
bounds (TPU_REDUCTIONS_AUTOSCALE_MIN/MAX/COOLDOWN_S).

Planned scale-down is a DRAIN, not a kill (`drain_replica`):

  1. admission closes (engine.begin_drain -> the `replica-draining`
     rejection the router re-routes for free) and `_pick` stops
     hashing new bucket-affinity keys to the victim;
  2. in-flight and queued work finishes (`drain.wait`);
  3. the victim's warm jit-bucket keys are prewarmed on exactly the
     survivors future affinity routing will hash them to
     (`router.affinity_target` — the handoff placement oracle);
  4. sharded partial state moves to the survivors' devices via a
     planner-emitted redistribution program (reshard/planner.py)
     executed under the declared peak-memory bound and verified
     element-wise against the pure-numpy oracle
     (reshard/oracle.verify_placement);
  5. only then does the replica stop and leave the routing table.

So a planned drain sheds ZERO requests where a SIGKILL sheds every
in-flight one — tests/test_serve_elastic.py proves the difference on
the same seeded workload.

Everything here is jax-free BY CONSTRUCTION (redlint RED014): the
drain PLANS and VERIFIES on the host; the one device touch — running
the redistribution program — funnels through
serve/executor.BatchExecutor.run_reshard.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Optional

import numpy as np

from tpu_reductions import config
from tpu_reductions.faults.inject import fault_point
from tpu_reductions.obs import ledger

# handoff payload geometry: one (k, k, _HANDOFF_COLS) f32 partial per
# drain — small enough to move in milliseconds on the virtual-CPU
# mesh, shaped so the partial->sharded program has a real
# reduce-scatter to run (dim 0 divisible by k for every k <= 64)
_HANDOFF_COLS = 128


def drain_replica(router, victim, *, executor=None,
                  mem_bound: float = 2.0, seed: int = 0,
                  poll_s: float = 0.02, timeout_s: float = 30.0,
                  clock: Callable[[], float] = time.monotonic) -> dict:
    """Retire `victim` from `router` by the drain protocol (module
    docstring) and return the evidence dict the elastic artifact
    commits: wait wall-clock, warm-key handoff map, and the
    oracle-verified redistribution program with its measured
    peak-memory factor vs the declared bound.

    No reference analog (the reference tears ranks down with the job;
    docs/SERVING.md "elastic fleet").
    """
    vid = victim.replica_id
    ledger.emit("drain.begin", replica=vid,
                mem_bound=round(float(mem_bound), 6))
    # write-ahead (serve/journal.py): the journal shows "draining"
    # before admission closes, so a controller crash mid-drain leaves
    # recovery a record of the phase — a draining-but-alive child is
    # adopted like any other and the drain re-decided
    journal = getattr(router, "journal", None)
    if journal is not None:
        journal.record_replica(vid, state="draining")
    victim.drain_begin()

    # -- 2. let in-flight + queued work finish ------------------------
    t0 = clock()
    drained = False
    while clock() - t0 < timeout_s:
        outstanding = router.load_snapshot()["outstanding"].get(vid, 0)
        queued = victim.queued_depth()
        if outstanding <= 0 and queued <= 0:
            drained = True
            break
        time.sleep(poll_s)
    waited_s = round(clock() - t0, 6)
    ledger.emit("drain.wait", replica=vid, waited_s=waited_s,
                drained=drained)

    # chaos hook: the drain's interruptible unit — a fault here is the
    # kill case the chaos suite contrasts against
    # (faults/inject.py; docs/RESILIENCE.md fault-point table)
    fault_point("drain.step")

    # -- 3. warm bucket keys -> the survivors affinity will pick ------
    handoff: List[dict] = []
    for key in victim.warm_bucket_keys():
        method, dtype, n = key
        target = router.affinity_target(method, dtype, int(n),
                                        exclude=(vid,))
        if target is None:
            continue
        target.prewarm(method, dtype, int(n))
        handoff.append({"key": [method, dtype, int(n)],
                        "target": target.replica_id})
    ledger.emit("drain.handoff", replica=vid, keys=len(handoff),
                targets=len({h["target"] for h in handoff}))

    # -- 4. sharded partials -> survivors via a planned reshard -------
    reshard = _reshard_partials(vid, executor=executor,
                                mem_bound=mem_bound, seed=seed)

    # -- 5. only now does the replica leave ---------------------------
    victim.stop()
    router.remove_replica(vid)
    stats = _victim_stats(victim)
    ledger.emit("drain.done", replica=vid, waited_s=waited_s,
                keys=len(handoff),
                shed=int(stats.get("shed", 0)),
                expired=int(stats.get("expired", 0)),
                reshard_ok=bool(reshard and reshard.get("ok")))
    return {"replica": vid, "drained": drained, "waited_s": waited_s,
            "handoff": handoff, "reshard": reshard,
            "victim_stats": stats}


def _victim_stats(victim) -> Dict[str, float]:
    """Duck-typed terminal counters of a retired replica — the
    drain-vs-kill contract's evidence (engine.stats for LocalReplica;
    replicas without counters report empty)."""
    probe = getattr(victim, "stats", None)
    if callable(probe):
        try:
            return dict(probe())
        except (TypeError, OSError, ValueError):
            return {}
    engine = getattr(victim, "_engine", None)
    return dict(engine.stats) if engine is not None else {}


def _reshard_partials(vid: str, *, executor, mem_bound: float,
                      seed: int) -> Optional[dict]:
    """Move the victim's per-device partial state to the survivors'
    placement as ONE planner-emitted program: partial per-rank addends
    -> row-sharded (the drain's state handoff is exactly the
    reshard_curve `partial_to_row` pair), planned under the declared
    peak-memory bound, executed through the RED014-whitelisted seam
    (executor.run_reshard), verified element-wise against the
    pure-numpy oracle. Returns None when the backend has no mesh to
    redistribute over (single-device: nothing is sharded, nothing
    moves)."""
    from tpu_reductions.reshard import (ShardingSpec, plan_reshard,
                                        verify_placement)
    if executor is None:
        from tpu_reductions.serve.executor import BatchExecutor
        executor = BatchExecutor()
    k = int(executor.capabilities().get("device_count", 1))
    if k < 2:
        return None
    src = ShardingSpec.replicated(k, 2, partial=True)
    dst = ShardingSpec.sharded(k, 2, 0)
    shape = (k, _HANDOFF_COLS)
    plan = plan_reshard(src, dst, shape, 4, mem_bound=mem_bound)
    rng = np.random.default_rng([seed, k])
    carried = rng.standard_normal((k,) + shape).astype(np.float32)
    m_abs = float(np.abs(carried).max())
    # the partial pair's f32 psum tolerance (bench/reshard_curve.py):
    # k half-ulps at the summed magnitude
    bound = float(k) * m_abs * 2.0 ** -22
    res = executor.run_reshard(plan, carried)
    verdict = verify_placement(carried, src, dst, res["shards"],
                               atol=bound)
    mem_ok = res["measured_mem_factor"] <= plan.mem_factor + 1e-9
    ok = bool(verdict["ok"]) and mem_ok
    ledger.emit("drain.reshard", replica=vid,
                program=",".join(s.primitive for s in plan.steps),
                ranks=k, wall_s=round(res["wall_s"], 6),
                mem_factor=round(plan.mem_factor, 6),
                measured_mem_factor=round(res["measured_mem_factor"], 6),
                max_err=verdict["max_err"], bound=bound, ok=ok)
    return {"ok": ok, "ranks": k,
            "program": [s.primitive for s in plan.steps],
            "mem_factor": round(plan.mem_factor, 6),
            "measured_mem_factor": round(res["measured_mem_factor"], 6),
            "mem_ok": mem_ok,
            "max_err": verdict["max_err"], "bound": bound,
            "wall_s": round(res["wall_s"], 6)}


class Autoscaler:
    """The control loop (module docstring): one `tick()` reads the
    fleet's signals and makes at most one scaling action, under the
    hysteresis that keeps a steady fleet steady — scale-up and
    scale-down trigger on DIFFERENT thresholds (up_load > down_load),
    scale-down additionally needs `down_ticks` consecutive calm ticks,
    and every action starts a cooldown during which no further action
    fires. Deterministic by construction (injectable clock, no
    randomness): the oscillation test drives tick() directly.

    `spawn(index)` returns a NOT-yet-started replica; the autoscaler
    starts it via router.add_replica and prewarms onto it every warm
    bucket key that now hashes to it (the scale-up twin of the drain's
    handoff — a fresh replica never serves a hot key cold)."""

    def __init__(self, router, spawn: Callable[[int], object], *,
                 min_replicas: Optional[int] = None,
                 max_replicas: Optional[int] = None,
                 cooldown_s: Optional[float] = None,
                 slo_classes: Optional[Dict[str, float]] = None,
                 executor=None, up_load: float = 4.0,
                 down_load: float = 1.0, down_ticks: int = 3,
                 mem_bound: float = 2.0, journal=None,
                 clock: Callable[[], float] = time.monotonic) -> None:
        self._router = router
        self._spawn = spawn
        self._min = config.autoscale_min(min_replicas)
        self._max = config.autoscale_max(max_replicas)
        self._cooldown_s = config.autoscale_cooldown_s(cooldown_s)
        if self._min < 1 or self._max < self._min:
            raise ValueError(
                f"need 1 <= min <= max, got min={self._min} "
                f"max={self._max}")
        self._slo_classes = dict(slo_classes or {})
        self._executor = executor
        self._up_load = float(up_load)
        self._down_load = float(down_load)
        self._down_ticks = int(down_ticks)
        self._mem_bound = float(mem_bound)
        self._clock = clock
        # the fleet journal, when the fleet has one: every tick's
        # control state (cooldown anchor, calm counter, last decision)
        # is journaled write-ahead so a restarted controller resumes
        # the POLICY mid-cooldown instead of cold-starting it
        # (serve/journal.py; router.journal is the usual source)
        self._journal = journal if journal is not None \
            else getattr(router, "journal", None)
        self._last_action_t: Optional[float] = None
        self._last_action: Optional[str] = None
        self._calm = 0
        self._next_idx = len(router.replicas)
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self.history: List[dict] = []
        self.drains: List[dict] = []

    # -- crash-consistent control state (serve/journal.py) ------------

    def export_state(self) -> dict:
        """The journal-shaped control-loop state. The cooldown anchor
        crosses processes as a WALL clock (`time.time()`): the dead
        controller's monotonic clock means nothing to the successor,
        but wall-clock elapsed-since-last-action does."""
        if self._last_action_t is None:
            last_wall = None
        else:
            last_wall = time.time() - (self._clock()
                                       - self._last_action_t)
        return {"last_action_wall": last_wall,
                "last_action": self._last_action,
                "cooldown_s": self._cooldown_s,
                "calm": self._calm, "next_idx": self._next_idx}

    def restore_state(self, state: Optional[dict]) -> None:
        """Resume a journaled control loop mid-cooldown: the remaining
        cooldown carries over (converted back onto this process's
        clock), as do the calm-tick counter and the replica-name
        counter — the successor never re-fires a decision the
        predecessor's hysteresis had already damped."""
        if not state:
            return
        last_wall = state.get("last_action_wall")
        if last_wall is not None:
            elapsed = max(0.0, time.time() - float(last_wall))
            self._last_action_t = self._clock() - elapsed
        self._last_action = state.get("last_action")
        self._calm = int(state.get("calm") or 0)
        self._next_idx = max(self._next_idx,
                             int(state.get("next_idx") or 0))
        ledger.emit("autoscale.resume",
                    cooling=(self._last_action_t is not None
                             and self._clock() - self._last_action_t
                             < self._cooldown_s),
                    calm_ticks=self._calm, next_idx=self._next_idx)

    # -- signals ------------------------------------------------------

    def _signals(self) -> dict:
        snap = self._router.load_snapshot()
        active = [r["replica"] for r in snap["replicas"]
                  if r["alive"] and not r["draining"]]
        queued = 0
        worst_p99 = None
        breach = False
        for rep in self._router.replicas:
            if rep.replica_id not in active:
                continue
            probe = getattr(rep, "queued_depth", None)
            if callable(probe):
                queued += int(probe() or 0)
            for slo, deadline in self._slo_classes.items():
                p99_fn = getattr(rep, "slo_p99", None)
                p99 = p99_fn(slo) if callable(p99_fn) else None
                if p99 is None:
                    continue
                if worst_p99 is None or p99 > worst_p99:
                    worst_p99 = p99
                if deadline is not None and p99 > deadline:
                    breach = True
        outstanding = sum(snap["outstanding"].get(r, 0) for r in active)
        load = (outstanding + queued) / max(1, len(active))
        return {"replicas": len(active), "outstanding": outstanding,
                "queued": queued, "load_per_replica": round(load, 4),
                "p99_worst": worst_p99, "p99_breach": breach,
                "active": active}

    # -- the loop body ------------------------------------------------

    def tick(self) -> dict:
        """One control-loop step: observe -> (maybe) act -> record.
        Returns the tick record (also appended to `history` — the
        replica-count-vs-load trajectory the elastic artifact
        commits)."""
        now = self._clock()
        sig = self._signals()
        n = sig["replicas"]
        cooling = (self._last_action_t is not None
                   and now - self._last_action_t < self._cooldown_s)
        want_up = (sig["load_per_replica"] > self._up_load
                   or sig["p99_breach"])
        calm = (sig["load_per_replica"] < self._down_load
                and not sig["p99_breach"])
        self._calm = self._calm + 1 if calm else 0
        action = "hold"
        if want_up and n < self._max and not cooling:
            # write-ahead: the decision (and the cooldown it starts)
            # is on disk before the spawn, so a crash mid-action
            # resumes cooling instead of immediately re-deciding
            self._last_action_t = now
            self._last_action = "up"
            self._calm = 0
            self._journal_state()
            self._scale_up(sig)
            action = "up"
        elif (self._calm >= self._down_ticks and n > self._min
                and not cooling):
            self._last_action_t = now
            self._last_action = "down"
            self._calm = 0
            self._journal_state()
            self._scale_down(sig)
            action = "down"
        else:
            self._journal_state()
        record = dict(sig, action=action, cooling=cooling,
                      calm_ticks=self._calm, t=round(now, 4))
        record.pop("active")
        ledger.emit("autoscale.tick", **record)
        self.history.append(record)
        return record

    def _journal_state(self) -> None:
        if self._journal is not None:
            self._journal.record_autoscaler(self.export_state())

    def _scale_up(self, sig: dict) -> None:
        replica = self._spawn(self._next_idx)
        self._next_idx += 1
        self._router.add_replica(replica)
        # the scale-up handoff: every warm key that NOW hashes to the
        # newcomer gets prewarmed there before traffic finds it cold
        warmed = 0
        seen = set()
        for rep in self._router.replicas:
            if rep.replica_id == replica.replica_id:
                continue
            probe = getattr(rep, "warm_bucket_keys", None)
            if not callable(probe):
                continue
            for key in probe():
                if key in seen:
                    continue
                seen.add(key)
                method, dtype, kn = key
                target = self._router.affinity_target(
                    method, dtype, int(kn))
                if target is not None \
                        and target.replica_id == replica.replica_id:
                    replica.prewarm(method, dtype, int(kn))
                    warmed += 1
        ledger.emit("autoscale.up", replica=replica.replica_id,
                    replicas=sig["replicas"] + 1,
                    load_per_replica=sig["load_per_replica"],
                    p99_breach=sig["p99_breach"], prewarmed=warmed)

    def _scale_down(self, sig: dict) -> None:
        # deterministic victim: the newest active replica (LIFO) —
        # the oldest replicas hold the longest-lived affinity history
        victim = None
        for rep in reversed(self._router.replicas):
            if rep.replica_id in sig["active"]:
                victim = rep
                break
        if victim is None:
            return
        evidence = drain_replica(self._router, victim,
                                 executor=self._executor,
                                 mem_bound=self._mem_bound,
                                 clock=self._clock)
        self.drains.append(evidence)
        ledger.emit("autoscale.down", replica=victim.replica_id,
                    replicas=sig["replicas"] - 1,
                    load_per_replica=sig["load_per_replica"],
                    shed=int(evidence["victim_stats"].get("shed", 0)))

    # -- optional background loop (the CLI/loadgen harness) -----------

    def start(self, interval_s: float = 0.25) -> "Autoscaler":
        if self._thread is not None:
            return self
        self._stop.clear()

        def loop() -> None:
            while not self._stop.is_set():
                self.tick()
                self._stop.wait(interval_s)

        self._thread = threading.Thread(target=loop, daemon=True,
                                        name="autoscaler")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=30.0)
            self._thread = None
