"""Write-ahead fleet journal: the control plane's crash consistency.

The serving fleet's orchestration state — which ProcessReplica
children exist (name, port, pid, platform, relay port, lifecycle
state), which jit-bucket placements have been prewarmed where, and
where the autoscaler's control loop stands (cooldown clock, calm-tick
counter, last decision) — used to live only in router/autoscaler
memory, so a controller death orphaned live children (with
possibly-nonempty device queues: the machine-wedge hazard of
CLAUDE.md) and cold-started the scaling policy. The journal gives the
control plane the same crash-consistency contract the bench artifacts
have had since bench/resume.py: every fleet transition is persisted
atomically (utils/jsonio — RED010's fsync'd temp+rename discipline)
BEFORE the action it describes, under a Checkpoint-style meta
contract, so a restarted `python -m tpu_reductions.serve.router
--journal=PATH` can re-adopt still-live children, reap the rest
INT-first, and resume the autoscaler mid-cooldown
(docs/SERVING.md "crash-consistent control plane").

Write-ahead ordering: `record_replica(name, state="starting")` lands
on disk before the Popen; "up" (with port+pid) lands the moment the
port file resolves; drain phases land before each phase acts. A crash
between journal and action therefore leaves a conservative record —
the recovering router probes a "starting" entry and reaps it if it
never came up, instead of discovering an unrecorded orphan.

jax-free by construction (RED014): the journal must be writable and
replayable with the relay dead.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Dict, List, Optional

from tpu_reductions.obs import ledger
from tpu_reductions.utils.jsonio import atomic_json_dump

# the meta contract (bench/resume doctrine): a journal whose meta does
# not round-trip identically is some other instrument's file — refuse
# to replay it rather than adopt a fleet it does not describe
JOURNAL_META = {"instrument": "fleet_journal", "version": 1}

# replica lifecycle vocabulary — every journaled replica is in exactly
# one of these states:
#   starting   journaled ahead of the spawn; no port/pid yet
#   up         serving (port + pid recorded)
#   draining   planned scale-down in progress (admission closed)
#   down       removed from the fleet (kept as tombstone for one
#              journal generation so recovery can explain it)
REPLICA_STATES = ("starting", "up", "draining", "down")


class FleetJournal:
    """Atomically-persisted fleet state (module docstring). With
    `path=None` the journal is a pure in-memory record — the
    in-process test fleets keep the same call sites without touching
    disk. Thread-safe: the router's submit threads, the autoscaler
    loop, and drain workers all record through one lock."""

    def __init__(self, path: Optional[str] = None) -> None:
        self.path = os.fspath(path) if path else None
        self._lock = threading.Lock()
        self._replicas: Dict[str, dict] = {}
        self._placements: List[list] = []
        self._autoscaler: Optional[dict] = None
        replayed = self._load()
        if self.path:
            ledger.emit("journal.open", path=self.path,
                        replayed=replayed,
                        replicas=len(self._replicas))

    # -- load / persist ------------------------------------------------

    def _load(self) -> bool:
        """Replay an existing journal file (meta contract permitting).
        A truncated/foreign file is ignored — an empty fleet record is
        the conservative recovery posture; atomic writes make real
        truncation unreachable, so this guards foreign files."""
        if not self.path or not os.path.exists(self.path):
            return False
        try:
            with open(self.path) as f:
                data = json.load(f)
        except (OSError, ValueError):
            return False
        if not isinstance(data, dict):
            return False
        if any(data.get(k) != v for k, v in JOURNAL_META.items()):
            return False
        reps = data.get("replicas")
        self._replicas = {str(k): dict(v) for k, v in reps.items()} \
            if isinstance(reps, dict) else {}
        self._placements = [list(p) for p in data.get("placements", [])
                            if isinstance(p, (list, tuple))]
        auto = data.get("autoscaler")
        self._autoscaler = dict(auto) if isinstance(auto, dict) else None
        ledger.emit("journal.replay", path=self.path,
                    replicas=len(self._replicas),
                    placements=len(self._placements),
                    autoscaler=self._autoscaler is not None)
        return True

    def _persist_locked(self, kind: str, name: Optional[str]) -> None:
        if not self.path:
            return
        atomic_json_dump(self.path, {
            **JOURNAL_META,
            "wall": time.time(),
            "replicas": self._replicas,
            "placements": self._placements,
            "autoscaler": self._autoscaler,
        })
        ledger.emit("journal.record", kind=kind,
                    **({"name": name} if name else {}),
                    replicas=len(self._replicas))

    # -- replica transitions (write-ahead: call BEFORE acting) ---------

    def record_replica(self, name: str, *, state: str,
                       port: Optional[int] = None,
                       pid: Optional[int] = None,
                       platform: Optional[str] = None,
                       relay_port: Optional[int] = None) -> None:
        """Journal one replica transition. Fields given as None keep
        their previously-journaled value (a drain transition does not
        forget the port the adoption probe needs)."""
        if state not in REPLICA_STATES:
            raise ValueError(f"state must be one of {REPLICA_STATES}, "
                             f"got {state!r}")
        with self._lock:
            entry = dict(self._replicas.get(name) or {})
            entry["state"] = state
            for key, val in (("port", port), ("pid", pid),
                             ("platform", platform),
                             ("relay_port", relay_port)):
                if val is not None:
                    entry[key] = val
            self._replicas[name] = entry
            self._persist_locked(f"replica-{state}", name)

    def forget_replica(self, name: str) -> None:
        """Drop a tombstone entirely (after a recovery has explained
        it, or when a spawn failed before the child ever existed)."""
        with self._lock:
            if self._replicas.pop(name, None) is not None:
                self._persist_locked("replica-forget", name)

    # -- placements / autoscaler ---------------------------------------

    def record_placement(self, method: str, dtype: str, n: int) -> None:
        """Journal one prewarmed jit-bucket placement — what recovery
        re-prewarms onto the adopted fleet so the survivors' compile
        caches match the pre-crash fleet's."""
        key = [method, dtype, int(n)]
        with self._lock:
            if key in self._placements:
                return
            self._placements.append(key)
            self._persist_locked("placement", None)

    def record_autoscaler(self, state: Optional[dict]) -> None:
        """Journal the autoscaler's exported control-loop state
        (serve/autoscale.Autoscaler.export_state: wall-clock cooldown
        anchor, calm-tick counter, last decision, name counter)."""
        with self._lock:
            self._autoscaler = dict(state) if state else None
            self._persist_locked("autoscaler", None)

    # -- recovery-side accessors ---------------------------------------

    def replicas(self) -> Dict[str, dict]:
        with self._lock:
            return {k: dict(v) for k, v in self._replicas.items()}

    def placements(self) -> List[tuple]:
        with self._lock:
            return [tuple(p) for p in self._placements]

    def autoscaler_state(self) -> Optional[dict]:
        with self._lock:
            return dict(self._autoscaler) if self._autoscaler else None
