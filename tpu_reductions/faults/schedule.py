"""Fault-schedule parsing for the fake relay (faults/relay.py).

A schedule is a JSON list of phases the relay steps through in order:

    [{"behavior": "accept", "duration_s": 2},
     {"behavior": "refuse", "connections": 3},
     {"behavior": "accept"}]

* `behavior` (required):
    accept — connections complete and are closed immediately (a healthy
             relay as seen by watchdog.relay_alive);
    refuse — the listening socket is closed: connects get ECONNREFUSED
             (the dead-relay signature both round-2 windows hit);
    stall  — connections complete but are held open and never serviced
             (the wedged-but-ports-open tunnel chip_session.sh's budget
             discipline exists for: probes say alive, work hangs);
    slow   — latency injection (ISSUE 6): connections complete but are
             held for `delay_s` before closing — a relay that services
             everything, late. Port probes still say alive; a consumer
             that waits for service (the serving engine's transport
             gate, serve/transport.py) pays `delay_s` per round-trip,
             which is how load tests exercise deadline expiry and
             shedding deterministically.
* `delay_s` (slow only, default 0.25): per-connection hold before the
  relay closes the connection.
* phase advance (optional, at most one of):
    duration_s   — advance after this much wall time;
    connections  — advance after this many observed connection attempts
                   (refused connects are invisible to userspace, so a
                   `refuse` phase must use duration_s).
  A phase with neither holds forever (the schedule's terminal state).

The flap the watchdog was built against is simply
accept -> refuse(duration) -> accept.
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import List, Sequence, Union

BEHAVIORS = ("accept", "refuse", "stall", "slow")

# per-connection hold of a `slow` phase that names no delay_s
DEFAULT_SLOW_DELAY_S = 0.25


@dataclasses.dataclass(frozen=True)
class Phase:
    """One relay behavior interval of a fault schedule."""

    behavior: str
    duration_s: float | None = None
    connections: int | None = None
    delay_s: float | None = None

    def __post_init__(self):
        if self.behavior not in BEHAVIORS:
            raise ValueError(f"unknown behavior {self.behavior!r} "
                             f"(expected one of {BEHAVIORS})")
        if self.duration_s is not None and self.connections is not None:
            raise ValueError("a phase advances on duration_s OR "
                             "connections, not both")
        if self.behavior == "refuse" and self.connections is not None:
            raise ValueError("refused connects never reach userspace: a "
                             "'refuse' phase must advance on duration_s")
        if self.duration_s is not None and self.duration_s < 0:
            raise ValueError(f"duration_s must be >= 0, got "
                             f"{self.duration_s}")
        if self.connections is not None and self.connections <= 0:
            raise ValueError(f"connections must be > 0, got "
                             f"{self.connections}")
        if self.delay_s is not None and self.behavior != "slow":
            raise ValueError("delay_s is the 'slow' behavior's knob; a "
                             f"'{self.behavior}' phase must not set it")
        if self.delay_s is not None and self.delay_s <= 0:
            raise ValueError(f"delay_s must be > 0, got {self.delay_s}")

    @property
    def hold_s(self) -> float:
        """The effective per-connection hold of a slow phase."""
        return self.delay_s if self.delay_s is not None \
            else DEFAULT_SLOW_DELAY_S


def load_schedule(src: Union[str, os.PathLike, Sequence]) -> List[Phase]:
    """Parse a schedule from a JSON file path, a JSON string, or an
    already-decoded list of phase dicts/Phases. Raises ValueError on
    anything malformed — a chaos run with a silently-empty schedule
    would test nothing while looking green."""
    if isinstance(src, (str, os.PathLike)) and os.path.exists(src):
        with open(src) as f:
            src = json.load(f)
    elif isinstance(src, str):
        src = json.loads(src)
    if not isinstance(src, (list, tuple)) or not src:
        raise ValueError("a fault schedule is a non-empty JSON list of "
                         "phases")
    phases = []
    for i, p in enumerate(src):
        if isinstance(p, Phase):
            phases.append(p)
            continue
        if not isinstance(p, dict):
            raise ValueError(f"phase {i}: expected an object, got "
                             f"{type(p).__name__}")
        unknown = set(p) - {"behavior", "duration_s", "connections",
                            "delay_s"}
        if unknown:
            raise ValueError(f"phase {i}: unknown key(s) "
                             f"{sorted(unknown)}")
        try:
            phases.append(Phase(**p))
        except (TypeError, ValueError) as e:
            raise ValueError(f"phase {i}: {e}") from e
    return phases
