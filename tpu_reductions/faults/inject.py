"""Env-driven deterministic fault injection (`TPU_REDUCTIONS_FAULTS`).

The hazardous loops this repo grew around the flapping relay — the
watchdog probe loop (utils/watchdog.py), the staging chunk loop
(utils/staging.py), chained execution (utils/timing.time_chained),
benchmark dispatch (bench/driver.run_benchmark) — each call
`fault_point("<name>")` at their vulnerable step. With the env var
unset that call is one dict lookup of None; with it set to a JSON plan
(or `@/path/to/plan.json`), named points fire scripted faults:

    TPU_REDUCTIONS_FAULTS='{"bench.run": {"after": 1, "action": "stall",
                            "seconds": 120}}'

Plan entry fields:
    after    skip the first N hits of the point (default 0)
    times    fire at most N times, then go quiet (default: forever) —
             `times` bounded firing is how a transient flap (fails,
             then recovers) is scripted
    action   raise        raise InjectedFault (a flap-surfaced error)
             stall        sleep `seconds` (default 3600) — a process
                          stuck in a device wait; only the watchdog's
                          os._exit can end it, which is the point
             exit         os._exit(`code`, default 1) — a SIGKILL-class
                          death mid-persist (the jsonio atomicity test)
             dead / inconclusive / suppress / anything else — no side
                          effect; the spec dict is returned for the
                          caller to interpret (the watchdog probe loop
                          maps "dead"/"inconclusive" onto probe
                          verdicts; the heartbeat maps "suppress" onto
                          a frozen progress mark — utils/heartbeat.py)

Registered fault points: `watchdog.probe`, `staging.chunk`,
`chain.step`, `bench.run`, `heartbeat.tick` (every progress mark,
utils/heartbeat.py), `preflight.probe` (fired in the sacrificial
discovery subprocess BEFORE its jax import — a scripted `stall` there
is how a wedged device lease is rehearsed without a device,
utils/preflight.py), `sched.task` (between the window scheduler's pick
and its launch, sched/executor.py — a scripted `exit` is the
deterministic "executor died mid-plan" the plan-resume contract is
tested against), `serve.batch` (one coalesced serving launch,
serve/executor.py — a scripted `raise` proves the engine contains a
batch crash to explicit error responses, tests/test_serve_chaos.py),
`stream.chunk` (one chunk of the streaming pipeline,
ops/stream.run_stream — a scripted `stall` mid-stream rehearses the
round-2 relay-death-mid-payload shape against the partial-accumulator
checkpoint, tests/test_stream_chaos.py), and `collective.hop` (fired
once per collective benchmark launch just before the warmup dispatch,
bench/collective_driver.py — a scripted `stall` mid rank-scaling sweep
rehearses a relay death between ladder rungs, and the re-invoked sweep
must resume its persisted per-rank-count rows byte-identically,
tests/test_chaos_e2e.py), and `reshard.cell` (fired once per
reshard-curve cell just before its plan executes,
bench/reshard_curve.py — a scripted `stall` mid-curve rehearses a
relay death between redistribution cells, and the re-invoked curve
must resume its persisted cell rows byte-identically,
tests/test_reshard_chaos.py), and `drain.step` (fired once per
planned replica drain after the wait-for-quiesce and before the
warm-key handoff, serve/autoscale.drain_replica — a scripted `raise`
there is the "drain interrupted mid-protocol" case the drain-vs-kill
contract contrasts: the victim dies like a SIGKILL instead of
finishing the handoff, tests/test_serve_elastic.py), and
`router.crash` (fired on every ReplicaRouter.submit before routing —
a scripted `exit` after N hits is the deterministic SIGKILL-class
controller death mid-burst: os._exit, no drain, children orphaned
alive with the fleet journal as their only record; the recovery
suite restarts the router against that journal,
tests/test_serve_recovery.py), and `exec.launch` (fired in
exec/core.run between the exec.plan event and the exec.launch event —
i.e. after the plan is declared but before ANY device work — so a
scripted `exit` there is the deterministic relay-death-mid-plan: the
re-invoked entry point must re-enter through exec/core and the ledger
join of exec.plan/exec.launch/exec.done rows must show zero duplicate
launches, tests/test_exec_chaos.py), and `family.cell` (fired once
per family-spot cell just before its payload is generated,
bench/family_spot.py — a scripted `exit` mid-grid rehearses a relay
death between family cells, and the re-invoked spot must resume its
persisted method x dtype x impl rows byte-identically,
tests/test_family.py).
docs/RESILIENCE.md keeps the list.

Counters are process-global and monotonic; `reset()` re-arms them for
in-process tests (subprocesses start fresh by construction).
"""

from __future__ import annotations

import json
import os
import time
from typing import Dict, Optional

ENV_VAR = "TPU_REDUCTIONS_FAULTS"


class InjectedFault(RuntimeError):
    """A scripted failure from a fault point — the stand-in for the
    error surface a relay flap produces mid-device-call."""


_counters: Dict[str, int] = {}
_plan_cache: tuple = (None, {})   # (raw env string, parsed plan)


def reset() -> None:
    """Clear hit counters and the plan cache (in-process tests)."""
    global _plan_cache
    _counters.clear()
    _plan_cache = (None, {})


def _plan() -> dict:
    """Parse (and cache, keyed on the raw env value) the active plan.
    A malformed plan raises ValueError loudly: a chaos run that
    silently injects nothing would test nothing while looking green."""
    global _plan_cache
    raw = os.environ.get(ENV_VAR)
    if not raw:
        return {}
    cached_raw, cached = _plan_cache
    if raw == cached_raw:
        return cached
    src = raw
    if raw.startswith("@"):
        with open(raw[1:]) as f:
            src = f.read()
    try:
        plan = json.loads(src)
    except ValueError as e:
        raise ValueError(f"{ENV_VAR}: malformed fault plan: {e}") from e
    if not isinstance(plan, dict):
        raise ValueError(f"{ENV_VAR}: fault plan must be a JSON object "
                         "mapping fault-point names to specs")
    _plan_cache = (raw, plan)
    return plan


def active() -> bool:
    """Whether any fault plan is armed (cheap env check)."""
    return bool(os.environ.get(ENV_VAR))


def fault_point(name: str) -> Optional[dict]:
    """Declare a fault point. Returns None when the point does not fire
    (no plan / not this point / outside its after..times window).
    Side-effect actions (raise/stall/exit) fire here; passive specs are
    returned for the caller to interpret (module docstring)."""
    if not os.environ.get(ENV_VAR):
        return None
    spec = _plan().get(name)
    if spec is None:
        return None
    hit = _counters.get(name, 0)
    _counters[name] = hit + 1
    after = int(spec.get("after", 0))
    times = spec.get("times")
    if hit < after:
        return None
    if times is not None and hit >= after + int(times):
        return None
    action = spec.get("action", "raise")
    # flight-recorder: a chaos run is only a replayable narrative if
    # every scripted fault is IN the record — emitted before the
    # side-effect so a stall/exit death certificate has its cause on
    # the line above it (obs/ledger.py fsyncs per event)
    from tpu_reductions.obs import ledger
    ledger.emit("fault.fire", point=name, action=action, hit=hit)
    if action == "raise":
        raise InjectedFault(spec.get("message",
                                     f"injected fault at {name} "
                                     f"(hit {hit})"))
    if action == "stall":
        time.sleep(float(spec.get("seconds", 3600)))
        return spec
    if action == "exit":
        os._exit(int(spec.get("code", 1)))
    return spec
