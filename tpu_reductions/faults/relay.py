"""Scriptable fake tunnel relay — a real TCP listener with faults.

Stands in for the axon tunnel relay (`/root/.relay.py`, ports 8082..)
that utils/watchdog.py probes and scripts/await_window.sh polls: a real
socket on a real port whose accept/refuse/stall behavior follows a
fault schedule (faults/schedule.py), so the dead-relay and flapping-
relay scenarios that have only ever happened *live* (round-2 window
deaths, the round-4 ~6-minute flap) can be reproduced deterministically
in CI. Point the consumers at it with the standard env overrides:

    TPU_REDUCTIONS_RELAY_PORTS=<port>   (watchdog probes, shell probes)
    TPU_REDUCTIONS_RELAY_MARKER=<file>  (any existing file = "tunneled")

Python API:

    with FakeRelay([Phase("accept", connections=1),
                    Phase("refuse")]) as relay:
        ... relay.port ...

`force(behavior)` overrides the schedule from test code — the
deterministic way to flip a relay dead the moment an artifact lands,
without racing wall-clock phases.

CLI (for shell-level chaos rehearsals of await_window/chip_session):

    python -m tpu_reductions.faults.relay --schedule=flap.json \
        [--port=0] [--port-file=PATH] [--max-seconds=S]
"""

from __future__ import annotations

import argparse
import socket
import sys
import threading
import time
from typing import List, Optional, Sequence, Union

from tpu_reductions.faults.schedule import Phase, load_schedule

_TICK_S = 0.05


class FakeRelay:
    """A schedule-driven TCP listener on 127.0.0.1.

    Thread-backed; `start()` binds and returns the port, `stop()` tears
    everything down (held `stall` connections included). Context-manager
    friendly. `connections` counts observed connection attempts
    (refused connects never reach userspace and are not counted)."""

    def __init__(self, schedule: Union[str, Sequence, None] = None,
                 host: str = "127.0.0.1", port: int = 0):
        self._phases: List[Phase] = (load_schedule(schedule) if schedule
                                     else [Phase("accept")])
        self._host = host
        self._want_port = port
        self._forced: Optional[str] = None
        self._forced_delay: Optional[float] = None
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._listener: Optional[socket.socket] = None
        self._held: List[socket.socket] = []
        self._phase_i = 0
        self._phase_t0 = 0.0
        self._phase_conns = 0
        self.port: Optional[int] = None
        self.connections = 0

    # -- lifecycle ----------------------------------------------------

    def start(self) -> int:
        """Bind (reserving the port for the relay's whole life, so a
        refuse phase can re-listen on the same port) and start the
        behavior thread; returns the port."""
        # redlint: disable=RED021 -- precedes Thread.start: happens-before
        self._listener = self._bind()
        self.port = self._listener.getsockname()[1]
        # redlint: disable=RED021 -- precedes Thread.start: happens-before
        self._phase_t0 = time.monotonic()
        self._thread = threading.Thread(target=self._serve,
                                        name="fake-relay", daemon=True)
        self._thread.start()
        return self.port

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
        self._close_listener()
        for c in self._held:
            try:
                c.close()
            except OSError:
                pass
        # redlint: disable=RED021 -- reclaimed after _stop.set + join
        self._held.clear()

    def __enter__(self) -> "FakeRelay":
        self.start()
        return self

    def __exit__(self, *exc) -> None:
        self.stop()

    # -- test control -------------------------------------------------

    def force(self, behavior: str,
              delay_s: Optional[float] = None) -> None:
        """Override the schedule with a fixed behavior from now on —
        the deterministic flip tests use instead of racing wall-clock
        phases ('refuse' the moment the artifact under test lands).
        `delay_s` sets the per-connection hold of a forced 'slow'."""
        if behavior not in ("accept", "refuse", "stall", "slow"):
            raise ValueError(f"unknown behavior {behavior!r}")
        with self._lock:
            self._forced = behavior
            self._forced_delay = delay_s

    @property
    def behavior(self) -> str:
        """The behavior currently in force (forced override first)."""
        with self._lock:
            if self._forced is not None:
                return self._forced
            return self._phases[self._phase_i].behavior

    # -- internals ----------------------------------------------------

    def _current_delay(self) -> float:
        """The per-connection hold in force for `slow` (forced delay,
        else the current phase's, else the schedule default)."""
        from tpu_reductions.faults.schedule import DEFAULT_SLOW_DELAY_S
        with self._lock:
            if self._forced == "slow":
                return self._forced_delay if self._forced_delay \
                    is not None else DEFAULT_SLOW_DELAY_S
            ph = self._phases[self._phase_i]
        return ph.hold_s if ph.behavior == "slow" \
            else DEFAULT_SLOW_DELAY_S

    def _slow_close(self, conn: socket.socket, delay_s: float) -> None:
        """Hold one slow connection for delay_s (stop-aware), then
        close it — 'serviced, late'."""
        deadline = time.monotonic() + delay_s
        while time.monotonic() < deadline and not self._stop.is_set():
            time.sleep(min(_TICK_S, max(0.0,
                                        deadline - time.monotonic())))
        try:
            conn.close()
        except OSError:
            pass

    def _bind(self) -> socket.socket:
        s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        s.bind((self._host, self._want_port if self.port is None
                else self.port))
        s.listen(8)
        s.settimeout(_TICK_S)
        return s

    def _close_listener(self) -> None:
        if self._listener is not None:
            try:
                self._listener.close()
            except OSError:
                pass
            self._listener = None

    def _advance_if_due(self) -> None:
        with self._lock:
            if self._phase_i >= len(self._phases) - 1:
                return
            ph = self._phases[self._phase_i]
            due = ((ph.duration_s is not None
                    and time.monotonic() - self._phase_t0 >= ph.duration_s)
                   or (ph.connections is not None
                       and self._phase_conns >= ph.connections))
            if due:
                self._phase_i += 1
                self._phase_t0 = time.monotonic()
                self._phase_conns = 0

    def _serve(self) -> None:
        while not self._stop.is_set():
            self._advance_if_due()
            behavior = self.behavior
            if behavior == "refuse":
                # no listener = kernel answers ECONNREFUSED, exactly
                # what a dead relay process looks like from a probe
                self._close_listener()
                time.sleep(_TICK_S)
                continue
            if self._listener is None:
                try:
                    self._listener = self._bind()
                except OSError:
                    # port transiently unavailable (TIME_WAIT edge):
                    # retry next tick rather than dying silently
                    time.sleep(_TICK_S)
                    continue
            try:
                conn, _ = self._listener.accept()
            except socket.timeout:
                continue
            except OSError:
                continue
            self.connections += 1
            with self._lock:
                self._phase_conns += 1
            if behavior == "stall":
                self._held.append(conn)   # wedged-but-ports-open
            elif behavior == "slow":
                # latency injection: hold delay_s, then service (close)
                # — each connection gets its own timer thread so a slow
                # relay is slow per round-trip, not serialized across
                # concurrent probers
                self._held.append(conn)
                threading.Thread(target=self._slow_close,
                                 args=(conn, self._current_delay()),
                                 daemon=True).start()
            else:
                try:
                    conn.close()
                except OSError:
                    pass


def main(argv=None) -> int:
    """CLI: run a schedule-driven fake relay until the schedule's
    terminal phase has held for --max-seconds (or forever). Writes the
    bound port to --port-file (atomic) so shell chaos rehearsals can
    point TPU_REDUCTIONS_RELAY_PORTS at it."""
    p = argparse.ArgumentParser(
        prog="tpu_reductions.faults.relay",
        description="Scriptable fake tunnel relay (chaos harness)")
    p.add_argument("--schedule", required=True,
                   help="fault schedule: JSON file path or inline JSON")
    p.add_argument("--port", type=int, default=0,
                   help="port to bind (0 = ephemeral)")
    p.add_argument("--port-file", default=None,
                   help="write the bound port here once listening")
    p.add_argument("--max-seconds", type=float, default=None,
                   help="total runtime bound (default: run until killed)")
    ns = p.parse_args(argv)
    try:
        phases = load_schedule(ns.schedule)
    except ValueError as e:
        p.error(str(e))
    # flight recorder + trace adoption (ISSUE 12): a chaos relay run
    # under an armed session inherits TPU_REDUCTIONS_TRACE_CTX, so its
    # session/phase events parent under the rehearsal that spawned it
    from tpu_reductions.obs import ledger
    ledger.arm_session("faults.relay", argv=sys.argv[1:])
    relay = FakeRelay(phases, port=ns.port)
    relay.start()
    print(f"fake relay: listening on 127.0.0.1:{relay.port} "
          f"({len(phases)} phase(s))", flush=True)
    if ns.port_file:
        from tpu_reductions.utils.jsonio import atomic_text_dump
        atomic_text_dump(ns.port_file, f"{relay.port}\n")
    t0 = time.monotonic()
    try:
        while ns.max_seconds is None \
                or time.monotonic() - t0 < ns.max_seconds:
            time.sleep(0.2)
    except KeyboardInterrupt:
        pass
    finally:
        relay.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
