"""Chaos layer: scriptable relay faults + deterministic injection.

The reference's only fault handling is the per-call CUDA abort macro
(cutil_inline_runtime.h:34-44): every failure is loud, local and
immediate. This platform's dominant failure mode is none of those — a
flapping tunnel relay that hangs processes forever mid-device-wait
(CLAUDE.md "Hard-won environment facts"; both round-2 windows died this
way) — and the defenses that grew around it (utils/watchdog.py,
utils/staging.py chunking, the per-row persist discipline, sweep
resume) were point fixes that had never been exercised under an
*actual* injected failure. This package makes every one of those
failure paths testable off-chip:

  * `faults.relay.FakeRelay` — a real TCP listener whose accept/refuse/
    stall behavior follows a JSON fault schedule (`faults.schedule`),
    standing in for the tunnel relay the watchdog probes;
  * `faults.inject` — env-var driven (`TPU_REDUCTIONS_FAULTS`)
    deterministic fault points compiled into the hazardous loops (the
    watchdog probe loop, the staging chunk loop, chained execution,
    benchmark dispatch), near-zero cost when disabled;

so the full death -> watchdog exit-3 -> watcher re-arm -> resume
pipeline (docs/RESILIENCE.md) runs end-to-end in CI on --platform=cpu.
"""

from tpu_reductions.faults.inject import InjectedFault, fault_point
from tpu_reductions.faults.relay import FakeRelay
from tpu_reductions.faults.schedule import Phase, load_schedule

__all__ = ["FakeRelay", "InjectedFault", "Phase", "fault_point",
           "load_schedule"]
