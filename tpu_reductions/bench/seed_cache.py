"""Seed the flagship grid's resume cache from spot artifacts.

The chip session's value order puts the f64/int spot scoreboards
(bench/spot.py, session steps 2 and 7) long before the 3-hour flagship
experiment (step 11) — on a flapping relay the spots may be the ONLY
fresh measurements a window lands. But the report's INT/DOUBLE table
(examples/tpu_run/report.md) is fed by the flagship grid's raw cells
(sweep_all resume cache). This tool bridges them: a PASSED spot row
measured at EXACTLY the flagship grid contract (sweep.FLAGSHIP_GRID,
checked by the same cell_matches the sweep resume uses) is written
into an open rep slot of the grid cache, so the next regeneration
(bench/regen.py) — or the next window's sweep_all resume — counts it.

This extends the checkpoint/resume discipline (SURVEY.md §5; one step
beyond the reference, where only the offline analysis was resumable
via its accumulated files — mpi/getAvgs.sh reading stdout-*), it does
not relabel anything: only rows that already ARE flagship-grid
measurements move, their provenance is recorded, and a row never
seeds twice (re-running on the same artifacts is a no-op).

Offline by construction: never touches a device, safe after the relay
dies.
"""

from __future__ import annotations

import argparse
import json
import math
import os
import sys
from pathlib import Path
from typing import List, Optional

from tpu_reductions.bench.sweep import FLAGSHIP_GRID, cell_matches


def _same_measurement(a: dict, b: dict) -> bool:
    """The same physical measurement, wherever it sits: compare rows
    minus slot/provenance bookkeeping (the duplicate guard that makes
    re-seeding idempotent)."""
    strip = ("repeat", "seeded_from", "provenance")
    return ({k: v for k, v in a.items() if k not in strip}
            == {k: v for k, v in b.items() if k not in strip})


def seed(spot_path: str | Path, grid_dir: str | Path,
         grid: Optional[dict] = None, log=print) -> List[Path]:
    """Seed grid_dir/raw_output from one spot artifact; returns the
    cell files written. Rows that don't match the grid contract are
    skipped (a kernel-7 op-parity spot must never masquerade as a
    kernel-6 flagship cell); acceptable live cells are never
    overwritten (only empty slots and stale-config cells are fair
    game).

    No reference analog (TPU-native).
    """
    grid = dict(grid or FLAGSHIP_GRID)
    contract = {k: grid[k] for k in ("n", "backend", "kernel", "threads",
                                     "iterations", "timing",
                                     "chain_reps")}
    try:
        data = json.loads(Path(spot_path).read_text())
    except (OSError, ValueError) as e:
        log(f"seed_cache: {spot_path}: unreadable ({e}); skipped")
        return []
    raw = Path(grid_dir) / "raw_output"
    raw.mkdir(parents=True, exist_ok=True)
    seeded: List[Path] = []
    for row in data.get("rows", []):
        method, dtype = row.get("method"), row.get("dtype")
        if dtype not in grid["dtypes"] or method not in grid["methods"]:
            continue
        if not cell_matches(row, method=method, dtype=dtype, **contract):
            continue
        gbps = row.get("gbps")
        if not isinstance(gbps, (int, float)) or not math.isfinite(gbps):
            # a PASSED row whose gbps serialized as null (non-finite
            # rates nullify in to_dict) must not enter the cache: it
            # would crash this very log line and later sweep resume
            # logging, and it carries no averageable rate (round-4
            # ADVICE 3; mirrors collect_averages' guard)
            log(f"seed_cache: {dtype} {method}: non-finite gbps; skipped")
            continue
        slots = [raw / f"run-{dtype}-{method}-{rep}.json"
                 for rep in range(grid["repeats"])]
        from tpu_reductions.bench.resume import load_cell, store_cell
        current = {f: load_cell(f) for f in slots if f.exists()}
        if any(_same_measurement(row, cur) for cur in current.values()):
            continue   # this exact measurement is already in the cache
        for rep, f in enumerate(slots):
            cur = current.get(f)
            if cur is not None and cell_matches(
                    row=cur, method=method, dtype=dtype, **contract):
                continue   # a live grid cell: never overwrite
            out = dict(row)
            out["repeat"] = rep
            out["seeded_from"] = os.path.basename(str(spot_path))
            store_cell(f, out)   # atomic (utils/jsonio): a kill mid-
            #                      seed can't truncate a grid cell
            seeded.append(f)
            log(f"seed_cache: {dtype} {method} "
                f"{row.get('gbps', float('nan')):.4f} GB/s -> {f.name}")
            break
        else:
            log(f"seed_cache: {dtype} {method}: all {grid['repeats']} "
                "slots hold live cells; nothing to seed")
    return seeded


def main(argv=None) -> int:
    """CLI: move flagship-contract spot rows into the grid resume cache.
    No reference analog — resume plumbing for relay-flap windows; the
    contract itself is sweep.FLAGSHIP_GRID (reduction.cpp:665 geometry)."""
    p = argparse.ArgumentParser(
        prog="tpu_reductions.bench.seed_cache",
        description="Seed the flagship grid's resume cache from spot "
                    "artifacts (offline; missing artifacts are skipped)")
    p.add_argument("spots", nargs="+",
                   help="spot JSON artifacts (bench/spot.py --out files)")
    p.add_argument("--grid-dir", required=True,
                   help="flagship grid dir (e.g. "
                        "examples/tpu_run/single_chip)")
    ns = p.parse_args(argv)
    total = []
    for s in ns.spots:
        if not os.path.exists(s):
            print(f"seed_cache: {s}: absent; skipped", file=sys.stderr)
            continue
        total.extend(seed(s, ns.grid_dir))
    print(f"seed_cache: seeded {len(total)} cell(s)")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
