"""L5: roofline accounting for bandwidth results.

The reference's kernel was judged against its GPU's practical memory
bandwidth (~90% of it at n=2^24 — reduction_kernel.cu:74-127 vs
mpi/CUdata.txt); round-1 VERDICT item 2 asks the same of this
framework: "state the TPU roofline and the achieved fraction in the
report". This module derives both mechanically from shmoo rows so the
generated report can never ship curves without the analysis.

Two memory regimes (measured, calibration_r02.json / docs/TIMING.md):
working sets that fit VMEM stay resident across chained iterations and
run ABOVE the HBM roof (a feature of the chip, reported as such, never
as an HBM fraction); larger working sets are HBM-bound and their
fraction of the roof is the kernel-quality number.
"""

from __future__ import annotations

from typing import List, Optional, Sequence

import numpy as np

from tpu_reductions.bench.findings import pow2_label

# Per device-kind memory model: HBM roof (B/s) and the VMEM-residency
# bound for chained working sets. v5e values measured in this repo;
# others are public spec sheets (fractions against them are labeled
# with the kind so a misidentified chip is auditable).
MEMORY_MODEL = {
    "TPU v5 lite": {"hbm_bytes_per_s": 819e9, "vmem_bytes": 112 << 20},
    "TPU v5p": {"hbm_bytes_per_s": 2765e9, "vmem_bytes": 80 << 20},
    "TPU v4": {"hbm_bytes_per_s": 1228e9, "vmem_bytes": 100 << 20},
}
_DEFAULT_KIND = "TPU v5 lite"


def _bytes_per_element(dtype: str) -> int:
    return 2 if dtype == "bfloat16" else np.dtype(dtype).itemsize


def annotate(shmoo_rows: Sequence[dict],
             device_kind: Optional[str] = None) -> List[dict]:
    """Tag each shmoo row (BenchResult.to_dict()) with its memory
    regime and, in the HBM regime, the achieved fraction of the roof.

    No reference analog (TPU-native).
    """
    kind = device_kind or _DEFAULT_KIND
    model = next((m for k, m in MEMORY_MODEL.items()
                  if kind.startswith(k)), MEMORY_MODEL[_DEFAULT_KIND])
    out = []
    for r in shmoo_rows:
        bytes_ = r["n"] * _bytes_per_element(r["dtype"])
        regime = ("vmem_resident" if bytes_ <= model["vmem_bytes"]
                  else "hbm_bound")
        row = dict(r, working_set_bytes=bytes_, regime=regime,
                   device_kind=kind)
        if regime == "hbm_bound":
            row["hbm_fraction"] = (r["gbps"] * 1e9
                                   / model["hbm_bytes_per_s"])
        out.append(row)
    return out


def summarize(annotated: Sequence[dict]) -> List[str]:
    """Human-readable roofline lines for the generated report: per
    (dtype, method), the best HBM-bound fraction and the VMEM-regime
    peak.

    No reference analog (TPU-native).
    """
    lines: List[str] = []
    keys = sorted({(r["dtype"], r["method"]) for r in annotated})
    if annotated:
        kind = annotated[0]["device_kind"]
        model = next((m for k, m in MEMORY_MODEL.items()
                      if kind.startswith(k)),
                     MEMORY_MODEL[_DEFAULT_KIND])
        lines.append(f"Device: {kind}; HBM roof "
                     f"{model['hbm_bytes_per_s'] / 1e9:.0f} GB/s; "
                     f"VMEM-residency bound "
                     f"{model['vmem_bytes'] >> 20} MiB.")
    for dtype, method in keys:
        rows = [r for r in annotated
                if (r["dtype"], r["method"]) == (dtype, method)]
        hbm = [r for r in rows if r["regime"] == "hbm_bound"]
        vmem = [r for r in rows if r["regime"] == "vmem_resident"]
        if hbm:
            best = max(hbm, key=lambda r: r.get("hbm_fraction", 0.0))
            lines.append(
                f"{dtype} {method}: HBM-bound peak {best['gbps']:.1f} "
                f"GB/s = {100 * best['hbm_fraction']:.0f}% of the roof "
                f"(n={pow2_label(best['n'])})")
        if vmem:
            bestv = max(vmem, key=lambda r: r["gbps"])
            lines.append(
                f"{dtype} {method}: VMEM-resident peak "
                f"{bestv['gbps']:.1f} GB/s "
                f"(n={pow2_label(bestv['n'])}; above the "
                "HBM roof by design — the working set stays on-chip)")
    # rows whose oracle check never ran (e.g. timing recovered from a
    # session log after a relay death) must not be presented as
    # verified: carry the caveat into every generated report that
    # includes these lines
    unverified = [r for r in annotated
                  if r.get("verified") is False
                  or r.get("status") == "RECOVERED"]
    if unverified:
        lines.append(
            f"CAVEAT: {len(unverified)} of {len(annotated)} rows above "
            "are timing-only (status RECOVERED — the run died before "
            "the oracle-verification phase); verified rows carry "
            "status PASSED in the raw data.")
    return lines
