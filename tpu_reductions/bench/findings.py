"""L5: mechanical findings — the writeup narrative, derived not written.

The reference's writeup closes with hand-written observations
(writeup.tex:19: CUDA beats Blue Gene on doubles until ~1024 ranks, BG
overtakes CUDA on ints around 500-600 ranks, CUDA double > CUDA int,
BG double ~ half BG int). This module derives the same KINDS of
observation mechanically from the measured rows, so the generated
report can never ship curves without the analysis — and the analysis
can never drift from the data:

- per-curve half-power point N_1/2 (the classic latency/bandwidth
  crossover: the smallest N reaching half the curve's large-N
  asymptotic rate) — where the benchmark stops being dispatch-bound;
- the VMEM->HBM cliff (regime flip N and the bandwidth drop across
  it — TPU-specific structure the reference's GPU never had, its
  payload being DRAM-bound at every measured size);
- single-chip multiples vs the reference GPU per (dtype, op)
  (the CUDA-constant-overlay comparison of makePlots.gp:17-19,31-33);
- the collective-vs-single-chip crossover rank count (the
  BG-overtakes-CUDA observation, re-derived for mesh rank sweeps).

Every function takes plain row dicts and returns prose lines for the
report's Findings section; all are unit-tested offline.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple


def pow2_label(n: int) -> str:
    """'2^k' for exact powers of two, the literal value otherwise — the
    sweep's sizes are powers of two (bench.sweep.run_shmoo), but a
    floored label for anything else would name a size that never ran.

    No reference analog (TPU-native).
    """
    n = int(n)
    if n > 0 and n & (n - 1) == 0:
        return f"2^{n.bit_length() - 1}"
    return str(n)


def _curves(rows: Sequence[dict]) -> Dict[Tuple[str, str], List[dict]]:
    out: Dict[Tuple[str, str], List[dict]] = {}
    for r in rows:
        out.setdefault((r["dtype"], r["method"]), []).append(r)
    for pts in out.values():
        pts.sort(key=lambda r: r["n"])
    return out


def half_power_points(shmoo_rows: Sequence[dict]) -> List[str]:
    """Per curve: the smallest N whose rate reaches half the curve's
    ASYMPTOTIC (large-N) rate — the classic N_1/2 latency/bandwidth
    crossover; below it the benchmark measures launch/dispatch latency,
    not memory bandwidth.

    The reference rate is deliberately NOT the global peak: on TPU the
    peak sits in the VMEM-resident regime (bench.roofline), far above
    the HBM rate every large payload runs at, and half-of-peak would
    misclassify bandwidth-bound HBM rows as "dispatch-bound". With
    regime tags present, the asymptote is the median HBM-bound rate;
    without them, the largest-N row's rate.

    No reference analog (TPU-native).
    """
    import statistics

    lines = []
    for (dtype, method), pts in sorted(_curves(shmoo_rows).items()):
        if len(pts) < 3:
            continue
        hbm = [r["gbps"] for r in pts if r.get("regime") == "hbm_bound"]
        asym = statistics.median(hbm) if hbm else pts[-1]["gbps"]
        if asym <= 0:
            continue
        # guaranteed to match: every row at/above the asymptote's own
        # source rows satisfies the threshold
        n_half = next(r["n"] for r in pts if r["gbps"] >= asym / 2)
        lines.append(
            f"{dtype} {method}: half-power point N_1/2 = "
            f"{pow2_label(n_half)} (half the "
            f"{asym:.0f} GB/s large-N rate) — smaller payloads are "
            "dispatch-bound, not bandwidth-bound.")
    return lines


def vmem_cliff(annotated_rows: Sequence[dict]) -> List[str]:
    """The regime boundary from roofline-annotated rows (bench.roofline
    tags each row vmem_resident / hbm_bound): report the flip N and the
    rate drop across it — chip structure the reference's DRAM-bound GPU
    curves never showed.

    No reference analog (TPU-native).
    """
    lines = []
    for (dtype, method), pts in sorted(_curves(annotated_rows).items()):
        last_vmem: Optional[dict] = None
        first_hbm: Optional[dict] = None
        for r in pts:
            if r.get("regime") == "vmem_resident":
                last_vmem = r
            elif r.get("regime") == "hbm_bound" and first_hbm is None:
                first_hbm = r
        if last_vmem and first_hbm and first_hbm["gbps"] > 0:
            ratio = last_vmem["gbps"] / first_hbm["gbps"]
            lines.append(
                f"{dtype} {method}: VMEM->HBM cliff between "
                f"{pow2_label(last_vmem['n'])} and "
                f"{pow2_label(first_hbm['n'])} — "
                f"{last_vmem['gbps']:.0f} GB/s VMEM-resident vs "
                f"{first_hbm['gbps']:.0f} GB/s HBM-bound "
                f"({ratio:.1f}x drop at the residency boundary).")
    return lines


def reference_multiples(single_chip: Dict[tuple, float],
                        reference: Dict[tuple, float]) -> List[str]:
    """Single-chip averages vs the reference GPU's published numbers
    (mpi/CUdata.txt:2-8) — the writeup's central comparison, as
    multiples."""
    lines = []
    ratios = {}
    for key, gbps in sorted(single_chip.items()):
        ref = reference.get(key)
        if ref:
            ratios[key] = gbps / ref
    if not ratios:
        return lines
    lo, hi = min(ratios.values()), max(ratios.values())
    worst = min(ratios, key=ratios.get)
    best = max(ratios, key=ratios.get)
    # 2 significant figures: fixed .1f would collapse every CPU-demo /
    # fetch-mode ratio to an uninformative "0.0x"
    lines.append(
        f"Single-chip vs the reference GPU: {lo:.2g}x "
        f"({' '.join(worst)}) to {hi:.2g}x ({' '.join(best)}) across "
        f"the measured (dtype, op) grid.")
    under = [k for k, v in ratios.items() if v < 1.0]
    if under:
        lines.append(
            "BELOW the reference on: "
            + ", ".join(" ".join(k) for k in sorted(under))
            + " — check those rows' recorded timing discipline "
            "(BenchResult.timing in the raw data) before reading this "
            "as chip performance: fetch-mode rows time host transfer "
            "too.")
    return lines


def collective_crossover(coll_avgs: Dict[tuple, float],
                         single_chip: Dict[tuple, float]) -> List[str]:
    """The BG-overtakes-CUDA observation (writeup.tex:19), re-derived:
    for each (DTYPE, OP), the smallest rank count whose collective
    aggregate rate exceeds the single-chip rate — if any measured rank
    count does. `coll_avgs` keys are (DTYPE, OP, ranks)."""
    by_pair: Dict[tuple, List[tuple]] = {}
    for (dt, op, ranks), gbps in sorted(coll_avgs.items()):
        by_pair.setdefault((dt, op), []).append((int(ranks), gbps))
    crossings: Dict[tuple, Optional[int]] = {}
    no_cross: List[str] = []
    for (dt, op), pts in sorted(by_pair.items()):
        sc = single_chip.get((dt, op))
        if not sc:
            continue
        pts.sort()
        over = next((r for r, g in pts if g > sc), None)
        if over is not None:
            crossings[(dt, op)] = over
        else:
            top_r, top_g = pts[-1]
            no_cross.append(
                f"{dt} {op}: no crossover up to {top_r} ranks "
                f"({top_g:.2f} vs {sc:.2f} GB/s single-chip).")
    lines: List[str] = []
    if crossings:
        tail = (" (the reference saw Blue Gene overtake its GPU near "
                "500-600 ranks, writeup.tex:19).")
        ranks_seen = set(crossings.values())
        if len(ranks_seen) == 1 and len(crossings) > 1:
            # every pair crosses at the same rank count: one line, not
            # one per pair
            lines.append(
                f"The mesh overtakes one chip at {ranks_seen.pop()} "
                f"ranks for every measured (dtype, op) pair" + tail)
        else:
            for (dt, op), over in sorted(crossings.items()):
                lines.append(f"{dt} {op}: the mesh overtakes one chip "
                             f"at {over} ranks" + tail)
    return lines + no_cross


def derive_findings(rows: Optional[Sequence[dict]] = None,
                    single_chip: Optional[Dict[tuple, float]] = None,
                    coll_avgs: Optional[Dict[tuple, float]] = None,
                    reference: Optional[Dict[tuple, float]] = None
                    ) -> List[str]:
    """All applicable findings for the data at hand (any subset).
    `rows` are shmoo rows, ideally roofline-annotated (bench.roofline):
    the half-power points need only (n, gbps); the cliff detection
    additionally needs each row's `regime` tag and silently yields
    nothing without it.

    No reference analog (TPU-native).
    """
    lines: List[str] = []
    if rows:
        lines += half_power_points(rows)
        lines += vmem_cliff(rows)
        # Rows that never passed the oracle (recovered timing-only rows,
        # examples/tpu_run/RECOVERY.md) must not present as verified:
        # the caveat is emitted HERE so it travels with the findings —
        # a report built without the roofline section (whose summarize
        # also flags this) still carries it.
        unverified = [r for r in rows
                      if r.get("status") == "RECOVERED"
                      or r.get("verified") is False]
        if unverified:
            lines.append(
                f"CAVEAT: {len(unverified)} of {len(rows)} curve rows "
                "are timing-only recoveries (status RECOVERED — the "
                "oracle never ran on them); curve-derived findings "
                "above rest partly on unverified timings.")
    if single_chip and reference:
        lines += reference_multiples(single_chip, reference)
    if coll_avgs and single_chip:
        lines += collective_crossover(coll_avgs, single_chip)
    return lines
