"""Unified checkpoint/resume for every --out-writing entry point.

The reference's only resumability was offline: its analysis pipeline
(mpi/getAvgs.sh) re-read accumulated stdout-* files, but an interrupted
*measurement* run started over (SURVEY.md §5 "checkpoint/resume"). On
this platform interrupted measurement runs are the NORM — the tunnel
relay flaps in minutes (CLAUDE.md) and the watchdog (utils/watchdog.py)
hard-exits anything mid-batch — so every instrument grew its own
persist-per-row discipline, and sweep_all grew an ad-hoc per-cell
resume. This module is the shared spelling of both halves:

  * `Checkpoint` — one artifact file of shape
    `{**meta, "complete": bool, <rows_key>: [...]}` (the shape spot/
    autotune/smoke/calibrate/firstrow already commit), written
    atomically (utils/jsonio) after every row, with *resume*: a
    re-invocation against an artifact left `complete: false` by an
    interrupted run reuses its rows (meta contract permitting) instead
    of re-measuring them. A `complete: true` artifact is a finished
    campaign: re-invocation re-measures fresh by design — resume is
    interruption-proofing, not a measurement cache (the per-window
    freshness contract of scripts/chip_session.sh).
  * `load_cell` / `store_cell` — the sweep grid's per-cell cache files
    (run-<dtype>-<method>-<rep>.json), shared with the spot->cache
    seeder (seed_cache.py); sweep cells DO resume from completed runs,
    cell-grain, exactly as before (sweep_all docstring).

The chaos suite (faults/, tests/test_chaos_e2e.py) drives the whole
pipeline: scripted flap -> watchdog exit 3 -> re-invocation -> resumed
rows identical to an uninterrupted run's.
"""

from __future__ import annotations

import json
import os
from pathlib import Path
from typing import Callable, List, Optional

from tpu_reductions.obs import ledger
from tpu_reductions.utils.jsonio import atomic_json_dump


def default_reusable(row: dict) -> bool:
    """Whether a persisted row may satisfy a re-invocation without
    re-measuring: verified or by-design-waived rows only — FAILED rows
    re-run (the sweep cache's "failures are never cached" rule,
    bench/sweep.sweep_all), and rows carrying no verdict at all are
    not presumed good. Smoke manifests spell the verdict as `ok`.

    No reference analog (TPU-native).
    """
    if row.get("ok") is True:
        return True
    return row.get("status") in ("PASSED", "WAIVED")


def prior_artifact(path: Optional[str | os.PathLike],
                   meta: dict) -> Optional[dict]:
    """The artifact a prior INTERRUPTED run left at `path` (parsed, or
    None): exists, parses, is marked `complete: false`, and every meta
    key round-trips identically — the single-payload resume primitive
    (bench/firstrow.py's one-row artifact) under the same contract
    rules as Checkpoint.

    No reference analog (TPU-native).
    """
    if path is None or not os.path.exists(path):
        return None
    try:
        data = json.loads(Path(path).read_text())
    except (OSError, ValueError):
        return None   # truncated by a pre-atomic interrupt: re-run
    if not isinstance(data, dict) or data.get("complete") is True:
        return None
    meta = json.loads(json.dumps(meta))
    if not all(data.get(k) == v for k, v in meta.items()):
        return None
    ledger.emit("resume.decision", mode="resume-single",
                path=os.fspath(path))
    return data


class Checkpoint:
    """Atomic, idempotent row persistence behind one --out artifact —
    the shared resume discipline of SURVEY.md §5, extended from the
    reference's analysis-only file accumulation (mpi/getAvgs.sh over
    stdout-*) to the measurement layer itself."""

    def __init__(self, path: Optional[str | os.PathLike], meta: dict, *,
                 key_fn: Callable[[dict], object],
                 rows_key: str = "rows",
                 sort_key: Optional[Callable[[dict], object]] = None,
                 resume_from_complete: bool = False):
        """`path` None = in-memory only (no --out given). `meta` is the
        invocation contract: prior rows are reused only when every meta
        key round-trips identically through the prior artifact — a
        different geometry/discipline/n never resumes. `key_fn` maps a
        row to its identity within the artifact; `sort_key`, when
        given, orders rows at every persist (autotune's ranked-so-far
        snapshots). `resume_from_complete=True` also reuses rows from a
        finished artifact (module docstring has the default rationale).

        No reference analog (TPU-native).
        """
        self.path = os.fspath(path) if path is not None else None
        # json round-trip so tuple-valued meta compares equal to the
        # lists it becomes on disk
        self.meta = json.loads(json.dumps(meta))
        self.rows_key = rows_key
        self._key_fn = key_fn
        self._sort_key = sort_key
        self.rows: List[dict] = []
        self.reused: List[object] = []
        self._prior = {}
        prior = self._load_prior()
        if prior is not None and (resume_from_complete
                                  or prior.get("complete") is not True):
            if all(prior.get(k) == v for k, v in self.meta.items()):
                for row in prior.get(rows_key, []):
                    if isinstance(row, dict):
                        self._prior[key_fn(row)] = row
        if self.path is not None:
            # flight-recorder: the resume-vs-fresh decision is exactly
            # the fact the old postmortems had to infer from artifact
            # mtimes (obs/timeline.py surfaces it directly)
            ledger.emit("resume.decision",
                        mode="resume" if self._prior else "fresh",
                        path=self.path, prior_rows=len(self._prior))

    def _load_prior(self) -> Optional[dict]:
        if self.path is None or not os.path.exists(self.path):
            return None
        try:
            data = json.loads(Path(self.path).read_text())
        except (OSError, ValueError):
            return None   # truncated by a pre-jsonio interrupt: re-run
        return data if isinstance(data, dict) else None

    def resume(self, key: object,
               reusable: Callable[[dict], bool] = default_reusable
               ) -> Optional[dict]:
        """The prior run's row for `key`, iff one exists and `reusable`
        accepts it — the caller skips the measurement and must `add()`
        the returned row so it lands in the new artifact unchanged
        (rows are never mutated: a resumed row stays byte-identical so
        downstream dedup, e.g. seed_cache._same_measurement, still
        recognizes it).

        No reference analog (TPU-native).
        """
        row = self._prior.get(key)
        if row is not None and reusable(row):
            self.reused.append(key)
            ledger.emit("resume.reuse", key=str(key), path=self.path)
            return row
        return None

    def add(self, row: dict, extra: Optional[dict] = None) -> None:
        """Append one row and persist the artifact incomplete — the
        persist-per-row live-window discipline (every row is on disk
        the moment it exists; a flap loses nothing already measured).

        No reference analog (TPU-native).
        """
        self.rows.append(row)
        self._persist(complete=False, extra=extra)

    def finalize(self, extra: Optional[dict] = None) -> None:
        """Mark the artifact complete (the completeness key every
        consumer gates on — a partial file must never be mistaken for
        a decided one).

        No reference analog (TPU-native).
        """
        self._persist(complete=True, extra=extra)

    def _persist(self, complete: bool, extra: Optional[dict]) -> None:
        if self.path is None:
            return
        rows = (sorted(self.rows, key=self._sort_key)
                if self._sort_key else self.rows)
        atomic_json_dump(self.path, {**self.meta, **(extra or {}),
                                     "complete": complete,
                                     self.rows_key: rows})
        # flight-recorder: one event per persisted artifact state — the
        # "what was already safe on disk when it died" answer
        ledger.emit("artifact.persist", path=self.path, rows=len(rows),
                    complete=complete)


def run_checkpointed_cells(ck: "Checkpoint", cells, measure,
                           on_row=None) -> List[dict]:
    """The shared per-cell resume loop of the grid instruments
    (bench/quant_curve.py, bench/reshard_curve.py — ISSUE 15 satellite:
    one spelling of the boilerplate instead of two copies): for each
    cell key, reuse the prior run's row when the Checkpoint accepts it,
    else `measure(key)`; either way `add()` it so it lands in the new
    artifact (resumed rows byte-identical — Checkpoint.resume's
    contract), call `on_row(key, row)` for the caller's console line,
    and `finalize()` once the grid completes. Returns the rows in
    grid order.

    No reference analog (TPU-native).
    """
    rows: List[dict] = []
    for key in cells:
        row = ck.resume(key)
        if row is None:
            row = measure(key)
        ck.add(row)
        if on_row is not None:
            on_row(key, row)
        rows.append(row)
    ck.finalize()
    return rows


def load_cell(path: str | os.PathLike) -> dict:
    """One sweep-grid cell file as a dict; {} when absent/truncated (a
    pre-atomic interrupt) so the caller re-measures — the read half of
    sweep_all's resume (bench/sweep.py), shared with seed_cache.

    No reference analog (TPU-native).
    """
    try:
        data = json.loads(Path(path).read_text())
    except (OSError, ValueError):
        return {}
    return data if isinstance(data, dict) else {}


def store_cell(path: str | os.PathLike, row: dict) -> None:
    """Atomically persist one sweep-grid cell (compact one-line JSON,
    the stdout-<jobid> analog format) — the write half of sweep_all's
    resume and seed_cache's seeding, via utils/jsonio so a SIGKILL
    mid-persist can never truncate the cache.

    No reference analog (TPU-native).
    """
    atomic_json_dump(path, row, indent=None)
    ledger.emit("artifact.persist", path=os.fspath(path), rows=1,
                complete=True, grain="cell")


def result_from_row(cfg, row: dict):
    """Resurrect a BenchResult from a persisted artifact row so resumed
    candidates rank alongside fresh ones (bench/autotune.py). Only the
    fields ranking/reporting read (gbps, status, identity) are real;
    oracle fields are nan — the row was verified when measured, and
    re-deriving its oracle would be re-measurement by another name.

    No reference analog (TPU-native).
    """
    import math

    from tpu_reductions.bench.driver import BenchResult
    from tpu_reductions.utils.qa import QAStatus

    gbps = row.get("gbps")
    gbps = float(gbps) if isinstance(gbps, (int, float)) \
        and math.isfinite(gbps) else 0.0
    return BenchResult(cfg.method, cfg.dtype, cfg.n, cfg.backend,
                       cfg.kernel, gbps, 0.0, cfg.iterations,
                       QAStatus[row.get("status", "FAILED")],
                       float("nan"), float("nan"), float("nan"),
                       waived_reason=row.get("waived_reason"),
                       timing=row.get("timing"))
