"""L5: report generation — the writeup.tex analog.

The reference's terminal artifact is a LaTeX report embedding the two EPS
bandwidth figures with a findings narrative (writeup.tex:1-31, figures at
:21-28). Here the report is generated from the measured data: a Markdown
report (always) and a compilable LaTeX source (same content), embedding
the figures produced by bench.plot and the averaged tables from
bench.aggregate, plus the reference-baseline comparison the writeup drew
by hand.
"""

from __future__ import annotations

import datetime
from pathlib import Path
from typing import Dict, Optional, Sequence

from tpu_reductions.bench.aggregate import Key
# the golden row-schema spec (redlint RED005): the collective table's
# column set is COLLECTIVE_COLUMNS, so the report's section can never
# drift from the emitted `DATATYPE OP NODES GB/sec` grammar
from tpu_reductions.lint.grammar import COLLECTIVE_COLUMNS

# Reference headline numbers (BASELINE.md; mpi/CUdata.txt:2-8) for the
# comparison table the writeup's narrative was built around.
REFERENCE_SINGLE_GPU = {
    ("INT", "SUM"): 90.8413, ("INT", "MIN"): 90.7905, ("INT", "MAX"): 90.7969,
    ("DOUBLE", "SUM"): 92.7729, ("DOUBLE", "MIN"): 92.6014,
    ("DOUBLE", "MAX"): 92.7552,
}


def _calibration_note(cal: Optional[dict]) -> str:
    """One bullet documenting the timing methodology the numbers rest on
    (utils/calibrate.py — the reference needed no such note because a
    local CUDA sync really blocks; a tunneled backend's may not)."""
    if not cal:
        return ""
    if cal.get("block_awaits_execution"):
        how = ("the platform's sync primitive awaits execution; "
               "per-launch synced timing is valid")
    else:
        how = ("the platform's sync primitive does NOT await execution "
               "(blocked launch {:.0f} us vs {:.0f} us true per-iteration"
               " cost); bandwidths use the chained slope mode wherever "
               "the reduce is all-device (every dtype, including f64 "
               "via the device pair-tree finish) — only --cpufinal "
               "rows, host work by definition, fall back to per-launch "
               "timing and carry that caveat"
               .format(cal.get("single_blocked_s", 0) * 1e6,
                       cal.get("chained_per_iter_s", 0) * 1e6))
    return ("- Timing calibration ({} platform): {}.\n"
            .format(cal.get("platform", "?"), how))


def build_sc_rows(single_chip: Optional[Dict[tuple, float]]
                  ) -> list[tuple[str, str, float, Optional[float]]]:
    """(dtype, op, reference_gbps, ours_gbps|None) in the canonical
    order — the ONE single-chip row assembly shared by the md/tex
    renderer (generate_report) and the PDF compiler (bench.pdf), so the
    three artifacts can never disagree on rows, ordering, or missing
    cells. Re-creates the writeup's CUDA comparison rows
    (mpi/CUdata.txt:2-8; overlay constants makePlots.gp:17-19,31-33)."""
    return [(dt, op, ref, (single_chip or {}).get((dt, op)))
            for (dt, op), ref in sorted(REFERENCE_SINGLE_GPU.items())]


def build_coll_rows(avgs: Dict[Key, float]
                    ) -> list[tuple[str, str, int, float]]:
    """(dtype, op, ranks, gbps) in the canonical order — the shared
    collective row assembly (same contract as build_sc_rows).
    Re-creates the averaged `DATATYPE OP NODES GB/sec` rows of
    mpi/results/*.txt (getAvgs.sh:8-14)."""
    return [(dt, op, ranks, gbps)
            for (dt, op, ranks), gbps in sorted(avgs.items())]


def build_notes(calibration: Optional[dict]) -> list[str]:
    """The methodology notes, shared by report.md's Notes section and
    the PDF's Methodology block (same sharing contract as the row
    builders). Re-creates the verification story of the reference
    driver (oracle check reduction.cpp:748-780) plus this framework's
    f64-pair and timing-calibration notes."""
    notes = [
        "Verification: every single-chip number is oracle-checked "
        "(Kahan host reference); collective numbers are checked "
        "against an elementwise host oracle. Failed runs report 0 "
        "and are excluded.",
        "float64 on TPU uses the double-double / order-key 32-bit-"
        "pair paths; wire bytes per element are identical to native "
        "f64.",
    ]
    cal_note = _calibration_note(calibration).strip()
    if cal_note.startswith("- "):
        cal_note = cal_note[2:]
    if cal_note:
        notes.append(cal_note)
    return notes


def _table(rows: Sequence[Sequence[str]], header: Sequence[str]) -> str:
    out = ["| " + " | ".join(header) + " |",
           "|" + "|".join("---" for _ in header) + "|"]
    out += ["| " + " | ".join(str(c) for c in r) + " |" for r in rows]
    return "\n".join(out)


def generate_report(avgs: Dict[Key, float],
                    single_chip: Optional[Dict[tuple, float]] = None,
                    figures: Sequence[str | Path] = (),
                    out_dir: str | Path = ".",
                    platform: str = "tpu",
                    calibration: Optional[dict] = None,
                    roofline: Optional[Sequence[str]] = None,
                    annotated_rows: Optional[Sequence[dict]] = None,
                    findings: Optional[Sequence[str]] = None
                    ) -> Dict[str, Path]:
    """Render report.md + report.tex from averaged collective results
    (aggregate.average output) and optional single-chip numbers
    {(DATATYPE, OP): GB/s}. `calibration` (a
    utils.calibrate.TimingCalibration.to_dict()) documents whether the
    platform's sync primitive could be trusted and which timing
    discipline produced the numbers. Returns {"md": path, "tex": path}.

    The Findings section (bench.findings — writeup.tex:19's narrative,
    derived not written) is computed HERE from the data every caller
    already passes (avgs, single_chip, optional roofline-annotated
    shmoo rows), so no pipeline can ship curves without the analysis;
    `findings` overrides the derivation (tests)."""
    out = Path(out_dir)
    out.mkdir(parents=True, exist_ok=True)
    date = datetime.date.today().isoformat()

    if findings is None:
        from tpu_reductions.bench.findings import derive_findings
        findings = derive_findings(rows=annotated_rows,
                                   single_chip=single_chip,
                                   coll_avgs=avgs,
                                   reference=REFERENCE_SINGLE_GPU) or None

    # ---- tables (rows built by the shared builders) ----------------------
    coll_rows = [(dt, op, ranks, f"{gbps:.3f}")
                 for dt, op, ranks, gbps in build_coll_rows(avgs)]
    coll_tbl = _table(coll_rows, [c.lower() if c.isalpha() else c
                                  for c in COLLECTIVE_COLUMNS])

    sc_rows = []
    for dt, op, ref, ours in build_sc_rows(single_chip):
        ratio = f"{ours / ref:.2f}x" if ours else "—"
        sc_rows.append((dt, op, f"{ref:.4f}",
                        f"{ours:.4f}" if ours else "—", ratio))
    sc_tbl = _table(sc_rows, ["dtype", "op", "reference GPU GB/s",
                              f"this framework ({platform}) GB/s", "ratio"])

    fig_md = "\n\n".join(f"![{Path(f).stem}]({Path(f).name})"
                         for f in figures)

    # single-chip-only runs (one physical chip, e.g. examples/tpu_run)
    # have no rank sweep: omit the section rather than print a bare
    # header over an empty table
    coll_md = ("\n## Collective reductions vs rank count\n\n"
               "Averaged over repeats (reference convention: total "
               "payload bytes /\nwall time — reduce.c:79 analog with "
               "real clocks).\n\n" + coll_tbl + "\n") if coll_rows else ""

    roof_md = ("\n## Roofline\n\n"
               + "\n".join(f"- {ln}" for ln in roofline) + "\n"
               ) if roofline else ""

    # mechanical findings (bench.findings) — the writeup.tex:19
    # narrative derived from the data instead of written by hand
    find_md = ("\n## Findings\n\n"
               + "\n".join(f"- {ln}" for ln in findings) + "\n"
               ) if findings else ""

    md = f"""# TPU Reduction Benchmarks — generated report

*Generated {date} by tpu_reductions.bench.report (the writeup.tex analog).*

## Single-chip reductions vs the reference GPU

The reference measured a single CC≥1.3 GPU at n=2^24 elements
(mpi/CUdata.txt); this framework measures one TPU chip with the Pallas
kernel path at the same n.

{sc_tbl}
{coll_md}{roof_md}{find_md}
{fig_md}

## Notes

{chr(10).join("- " + n for n in build_notes(calibration))}
"""
    md_path = out / "report.md"
    md_path.write_text(md)

    tex = _to_tex(sc_rows, coll_rows, figures, date,
                  calibration=calibration, roofline=roofline,
                  findings=findings)
    tex_path = out / "report.tex"
    tex_path.write_text(tex)
    return {"md": md_path, "tex": tex_path}


def _to_tex(sc_rows, coll_rows, figures, date, calibration=None,
            roofline=None, findings=None) -> str:
    def tabular(rows, cols, header):
        lines = ["\\begin{tabular}{" + "l" * cols + "}",
                 " & ".join(header) + " \\\\ \\hline"]
        lines += [" & ".join(str(c) for c in r) + " \\\\" for r in rows]
        lines.append("\\end{tabular}")
        return "\n".join(lines)

    figs = "\n".join(
        "\\includegraphics[width=0.85\\textwidth]{%s}" % Path(f).name
        for f in figures if str(f).endswith(".eps"))
    # precomputed outside the f-string: backslashes are not allowed in
    # f-string expressions before Python 3.12
    coll_tex = ("\\section{Collective reductions}\n"
                + tabular(coll_rows, 4, ["dtype", "op", "ranks", "GB/s"])
                if coll_rows else "")
    roof_tex = ("\\section{Roofline}\n\\begin{itemize}\n"
                + "\n".join(f"\\item {_tex_escape(ln)}"
                             for ln in roofline)
                + "\n\\end{itemize}"
                if roofline else "")
    find_tex = ("\\section{Findings}\n\\begin{itemize}\n"
                + "\n".join(f"\\item {_tex_escape(ln)}"
                             for ln in findings)
                + "\n\\end{itemize}"
                if findings else "")
    return f"""\\documentclass{{article}}
\\usepackage{{graphicx}}
\\title{{TPU Reduction Benchmarks}}
\\date{{{date}}}
\\begin{{document}}
\\maketitle
\\section{{Single-chip reductions}}
{tabular(sc_rows, 5, ["dtype", "op", "ref GPU", "TPU", "ratio"])}
{coll_tex}
{roof_tex}
{find_tex}
\\section{{Figures}}
{figs}
\\section{{Methodology}}
{_tex_escape(_calibration_note(calibration)) or
 "Timing: per-launch device-synchronized iterations."}
\\end{{document}}
"""


def _tex_escape(s: str) -> str:
    # '^' appears in every power-of-two finding/roofline line (2^24);
    # bare it breaks compilation ('Missing $ inserted') — the module
    # promises a COMPILABLE LaTeX source
    return (s.replace("&", "\\&").replace("%", "\\%")
             .replace("#", "\\#").replace("_", "\\_")
             .replace("^", "\\textasciicircum{}")
             .replace("->", "$\\rightarrow$"))


def load_experiment(out_dir: str | Path,
                    calibration: Optional[str] = None) -> dict:
    """Reload everything a report needs from an experiment out_dir —
    the analysis-side resumability of the reference's file pipeline
    (raw_output -> collected.txt -> results/ -> writeup; SURVEY.md
    §3.3). Returns {avgs, single_chip, calibration, figures, roofline,
    annotated_rows}; shared by the md/tex regenerator (main) and the
    PDF compiler (bench.pdf). Raises FileNotFoundError when the out_dir
    holds no experiment at all."""
    import json

    from tpu_reductions.bench.aggregate import average, collect

    out = Path(out_dir)
    raw = out / "raw_output"
    sc_raw = out / "single_chip" / "raw_output"
    if raw.is_dir():
        avgs = average(collect(raw))
    elif sc_raw.is_dir():
        # single-chip-only out dirs (run_tpu_experiment.sh on one
        # physical chip) have no collective rank sweep — regenerate
        # with an empty collective section rather than refusing
        avgs = {}
    else:
        raise FileNotFoundError(
            f"neither {raw} nor {sc_raw} found — run the experiment "
            "pipeline first")

    # single-chip overlay numbers from the sweep's cached cells — the
    # same reconstruction run_experiment.sh does from live results
    sc: dict = {}
    if sc_raw.is_dir():
        for f in sorted(sc_raw.glob("*.json")):
            for line in f.read_text().splitlines():
                if not line.strip():
                    continue
                r = json.loads(line)
                if r.get("status") != "PASSED":
                    continue
                dt = {"int32": "INT", "float64": "DOUBLE"}.get(
                    r["dtype"], r["dtype"].upper())
                sc.setdefault((dt, r["method"]), []).append(r["gbps"])
        sc = {k: sum(v) / len(v) for k, v in sc.items()}

    cal_path = Path(calibration) if calibration \
        else out / "calibration.json"
    if calibration and not cal_path.exists():
        raise FileNotFoundError(f"{cal_path} not found")
    cal = json.loads(cal_path.read_text()) if cal_path.exists() else None

    roof_lines = None
    ann = None
    roof_path = out / "roofline.json"
    if roof_path.exists():
        from tpu_reductions.bench.roofline import summarize
        ann = json.loads(roof_path.read_text())
        roof_lines = summarize(ann)
    return {"avgs": avgs, "single_chip": sc or None, "calibration": cal,
            "figures": sorted(out.glob("*.eps")) + sorted(out.glob("*.png")),
            "roofline": roof_lines, "annotated_rows": ann}


def main(argv=None) -> int:
    """Regenerate the report offline from an experiment out_dir — no
    benchmarks are re-run.

        python -m tpu_reductions.bench.report out/ [--calibration cal.json]


    No reference analog (TPU-native).
    """
    import argparse

    p = argparse.ArgumentParser(
        prog="tpu_reductions.bench.report",
        description="Regenerate report.md/report.tex from an experiment "
                    "output directory (no benchmarks are re-run)")
    p.add_argument("out_dir", help="Directory holding raw_output/ from a "
                                   "previous run_experiment/sweep")
    p.add_argument("--calibration", type=str, default=None,
                   help="Path to a calibration JSON (utils.calibrate "
                        "output); defaults to <out_dir>/calibration.json "
                        "when present (run_experiment.sh writes it)")
    p.add_argument("--platform", type=str, default="tpu",
                   help="Platform label for the comparison table")
    ns = p.parse_args(argv)

    try:
        data = load_experiment(ns.out_dir, calibration=ns.calibration)
    except FileNotFoundError as e:
        p.error(str(e))
    paths = generate_report(data["avgs"], single_chip=data["single_chip"],
                            figures=data["figures"], out_dir=ns.out_dir,
                            platform=ns.platform,
                            calibration=data["calibration"],
                            roofline=data["roofline"],
                            annotated_rows=data["annotated_rows"])
    print(f"report: {paths['md']} {paths['tex']}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
