"""L5: tile-geometry autotuner for the single-chip Pallas kernels.

The reference exposes its kernel geometry as hand-set knobs
(`--threads`/`--maxblocks`, reference reduction.cpp:666-668) chosen by the
user per GPU; getNumBlocksAndThreads (reduction.cpp:272-291) merely clamps
them. On TPU the analogous knobs are the VMEM tile height (threads -> TM
rows) and the partial-block count (maxblocks -> P), and the right values
depend on the payload, dtype and accumulator structure — so this module
races a candidate grid and reports the fastest VERIFIED configuration
(SURVEY.md §7 step 3: "tile-shape autotuning replaces the
threads/maxblocks knobs").

Timing defaults to the chained slope mode (--timing=chained,
ops/chain.py): on the tunneled TPU, per-launch synced timing reads a
flat dispatch-ack floor regardless of tile geometry (utils/calibrate.py),
which would make every candidate score identically and the ranking pure
noise. A FAILED verify disqualifies a candidate so a wrong-but-fast
kernel can never win.

CLI:
    python -m tpu_reductions.bench.autotune --method=SUM --type=int \
        --n=16777216 [--platform=cpu] [--out=tune.json]
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import math
import sys
from typing import List, Optional, Sequence, Tuple

from tpu_reductions.bench.driver import BenchResult, run_benchmark_batch
from tpu_reductions.config import (DTYPE_ALIASES, KERNEL_ELEMENTWISE,
                                   KERNEL_MXU, KERNEL_SINGLE_PASS,
                                   KERNEL_STREAM, KERNEL_TWO_PASS, METHODS,
                                   ReduceConfig, _apply_platform)
from tpu_reductions.utils.logging import BenchLogger

# (kernel, threads, max_blocks[, stream_buffers]) candidate grid.
# Threads sweeps the VMEM tile height across its useful range (8 rows =
# one sublane tile, 2048 = the choose_tiling clamp); max_blocks only
# matters for the two-pass kernel's partial count, so the single-pass
# kernels pin it to the reference default of 64 (reduction.cpp:668). A
# 4th element, where present, is the kernel-10 DMA pipeline depth — the
# knob that actually matters for the streaming kernel (the maxblocks
# knob is structurally dead for single-pass kernels; racing the depth
# instead is the round-2 VERDICT's weak-#7 fix).
DEFAULT_GRID: Tuple[Tuple[int, ...], ...] = tuple(
    [(KERNEL_SINGLE_PASS, t, 64) for t in (64, 128, 256, 512, 1024, 2048)]
    + [(KERNEL_ELEMENTWISE, t, 64) for t in (64, 128, 256, 512, 1024, 2048)]
    + [(KERNEL_TWO_PASS, t, mb) for t in (256, 1024) for mb in (64, 256)]
    # MXU matmul SUM (kernel 9): participates in float races; int/MIN/
    # MAX configs WAIVE it (driver gate), ranking below every PASSED row
    + [(KERNEL_MXU, t, 64) for t in (256, 512, 1024)]
    # manual deep-DMA streaming accumulator (kernel 10)
    + [(KERNEL_STREAM, t, 64) for t in (256, 512)]
)

# Finer race around the round-2 winners (tune_r02.json: kernel 6
# threads=512 at 6238 GB/s, kernel 7 threads=256 at 5075) — the
# second-pass grid for squeezing past a coarse optimum.
FINE_GRID: Tuple[Tuple[int, ...], ...] = tuple(
    [(KERNEL_SINGLE_PASS, t, 64) for t in (320, 384, 448, 512, 640, 768)]
    + [(KERNEL_TWO_PASS, t, mb) for t in (128, 192, 256, 384, 512)
       for mb in (32, 64, 128)]
)

# HBM-regime race (run with --n >= 2^26 so the working set exceeds
# VMEM): big tiles for deep DMA on the single-pass kernels, and the
# fine race's two-pass winner geometry (k7 t=384, tune_fine.json)
# bracketed — the docs/PERF_NOTES.md next-window hypotheses 1 and 4.
# Kernel 10 races its pipeline depth (2 = Mosaic-equivalent baseline,
# 4 = default, 8 = deep lookahead) — the knob this kernel exists for.
# Use --comparator to append the XLA row (the 779 GB/s = 95%-of-roof
# rate calibration measured at 2^26; the gap to close).
# Value-ordered (round-4 flapping-relay discipline): chained races run
# — and persist — one candidate at a time in LIST order, and a budget
# cut keeps the measured prefix, so the hypothesis-bearing geometries
# lead: kernel 10's depth race (the knob the kernel exists for), then
# the two crowned VPU geometries, then the wider exploration tail.
HBM_GRID: Tuple[Tuple[int, ...], ...] = tuple(
    [(KERNEL_STREAM, 512, 64, d) for d in (4, 8, 2)]
    + [(KERNEL_TWO_PASS, 384, 64),       # fine-race winner (22.7 TB/s
                                         # VMEM; does it hold in HBM?)
       (KERNEL_SINGLE_PASS, 512, 64)]    # the committed HBM rows' cfg
    + [(KERNEL_SINGLE_PASS, t, 64) for t in (1024, 2048)]
    # kernel 8 skips the per-step sublane relayout entirely (pure
    # elementwise combine into a (TM,128) accumulator) — if k6's 5-8%
    # HBM deficit is fold latency between DMA waits, k8 shows it
    + [(KERNEL_ELEMENTWISE, t, 64) for t in (1024, 2048)]
    + [(KERNEL_TWO_PASS, 384, 128), (KERNEL_TWO_PASS, 512, 64)]
    + [(KERNEL_STREAM, 1024, 64, d) for d in (2, 4, 8)]
    + [(KERNEL_STREAM, 256, 64, 4)]
)

# Kernel-9 (MXU) race: float dtypes only (--type=float/bfloat16, SUM).
# k9 against the established VPU winners and the streaming kernel, so
# one race ranks the systolic-array reduction in both regimes
# (docs/PERF_NOTES.md hypothesis 5 — k9 has never lowered on-chip).
MXU_GRID: Tuple[Tuple[int, ...], ...] = tuple(
    [(KERNEL_MXU, t, 64) for t in (256, 512, 1024)]
    + [(KERNEL_SINGLE_PASS, 512, 64), (KERNEL_TWO_PASS, 384, 64),
       (KERNEL_STREAM, 512, 64, 4)]
)

GRIDS = {"default": DEFAULT_GRID, "fine": FINE_GRID, "hbm": HBM_GRID,
         "mxu": MXU_GRID}


def candidate_configs(base: ReduceConfig,
                      grid: Sequence[Tuple[int, ...]] = DEFAULT_GRID,
                      comparator: bool = False) -> List[ReduceConfig]:
    """Expand the (kernel, threads, max_blocks[, stream_buffers]) grid
    into benchmark configs sharing `base`'s op/dtype/n/timing discipline
    — the candidate space the reference leaves to hand-set
    --threads/--maxblocks knobs (reduction.cpp:666-668). The optional
    4th element sets the kernel-10 DMA pipeline depth (base's value
    otherwise). `comparator` PREPENDS one XLA-backend config so the
    race records the always-correct baseline it must beat (SURVEY.md
    §7 L2b) in the same run, same discipline — first, because chained
    races run in list order and persist per candidate: a budget-cut
    race must keep its yardstick row, not lose it behind the
    exploration tail."""
    cfgs = [dataclasses.replace(base, backend="pallas", kernel=g[0],
                                threads=g[1], max_blocks=g[2],
                                stream_buffers=(g[3] if len(g) > 3
                                                else base.stream_buffers))
            for g in grid]
    if comparator:
        cfgs.insert(0, dataclasses.replace(base, backend="xla",
                                           kernel=KERNEL_SINGLE_PASS,
                                           threads=256, max_blocks=64))
    return cfgs


def autotune(base: ReduceConfig,
             grid: Sequence[Tuple[int, int, int]] = DEFAULT_GRID,
             logger: Optional[BenchLogger] = None,
             comparator: bool = False,
             on_result=None,
             resume=None,
             ) -> List[Tuple[ReduceConfig, BenchResult]]:
    """Race the grid; return (config, result) pairs sorted fastest-first
    with verified (PASSED) candidates ranked strictly above the rest.

    Replaces getNumBlocksAndThreads' static clamping of user-picked knobs
    (reduction.cpp:272-291) with measurement (SURVEY.md §7 step 3).

    `on_result(cfg, result)` fires as each candidate completes. In
    chained mode candidates run (and therefore can PERSIST) one at a
    time — chained timing is regime-immune (driver.run_benchmark_batch
    docstring), so per-candidate runs measure identically to a batch,
    and a race that dies at candidate k keeps candidates 1..k-1 (the
    live-window lesson of examples/tpu_run/RECOVERY.md). Legacy timing
    modes keep the batch path: their comparability NEEDS the shared
    pre-fetch sync regime, so their on_result only fires at batch
    finalize.

    `resume(cfg)`, when given (chained mode only — per-candidate
    measurements are the only ones safely reusable across processes),
    returns a prior interrupted race's BenchResult for that candidate
    (bench/resume.result_from_row); the candidate is then not re-raced.
    A transient relay flap retries the candidate before the crash
    containment records it FAILED (utils/retry.py)."""
    logger = logger or BenchLogger(None, None)
    cfgs = candidate_configs(base, grid, comparator=comparator)
    if base.timing == "chained":
        from tpu_reductions.bench.driver import crash_result, run_benchmark
        from tpu_reductions.exec import core as exec_core
        from tpu_reductions.exec.plan import device_task
        results = []
        for cfg in cfgs:
            prior = resume(cfg) if resume is not None else None
            if prior is not None:
                logger.log(f"autotune kernel={cfg.kernel} "
                           f"threads={cfg.threads}: resumed from prior "
                           "race artifact")
                if on_result is not None:
                    on_result(cfg, prior)
                results.append(prior)
                continue
            try:
                res = exec_core.run(device_task(
                    f"autotune/k{cfg.kernel}",
                    lambda: run_benchmark(cfg, logger=logger),
                    retry_log=logger.log, method=cfg.method,
                    dtype=cfg.dtype, n=cfg.n, threads=cfg.threads,
                    max_blocks=cfg.max_blocks))
            except Exception as e:
                # one candidate that cannot even compile (e.g. a Mosaic
                # lowering gap on the real chip for a kernel the
                # interpret path accepts) must not kill a live race —
                # the batch path contains crashes the same way
                # (driver.crash_result)
                res = crash_result(cfg, e, logger)
            if on_result is not None:
                on_result(cfg, res)
            results.append(res)
    else:
        results = run_benchmark_batch(cfgs, logger=logger,
                                      on_result=on_result)
    pairs = list(zip(cfgs, results))
    pairs.sort(key=lambda cr: (not cr[1].passed, -cr[1].gbps))
    return pairs


def _row(cfg: ReduceConfig, res: BenchResult) -> dict:
    """One serialized ranking row. The XLA comparator ignores the
    geometry knobs entirely — a serialized kernel/threads value there
    would read as "the geometry XLA was measured at"; record null.
    Non-finite gbps (a fetch-mode avg_s <= 0 reports inf; crashed rows
    carry nan) serializes as null — json.dump's Infinity/NaN literals
    are not RFC-8259 JSON and break strict parsers."""
    xla = cfg.backend == "xla"
    row = {"backend": cfg.backend,
           "kernel": None if xla else cfg.kernel,
           "threads": None if xla else cfg.threads,
           "max_blocks": None if xla else cfg.max_blocks,
           "gbps": (round(res.gbps, 4) if math.isfinite(res.gbps)
                    else None),
           "status": res.status.name}
    if not xla and cfg.kernel == KERNEL_STREAM:
        row["stream_buffers"] = cfg.stream_buffers
    return row


def _row_key(row: dict) -> tuple:
    """A ranked row's identity inside the race artifact — the resume
    key (bench/resume.Checkpoint): the full geometry, with the XLA
    comparator's nulled knobs collapsing to one baseline slot."""
    return (row.get("backend"), row.get("kernel"), row.get("threads"),
            row.get("max_blocks"), row.get("stream_buffers"))


def _cfg_key(cfg: ReduceConfig) -> tuple:
    """The same resume key computed from a candidate config — must
    mirror _row exactly (null geometry for the XLA comparator; depth
    only for the streaming kernel) or resume would never match.

    No reference analog (TPU-native).
    """
    xla = cfg.backend == "xla"
    return (cfg.backend,
            None if xla else cfg.kernel,
            None if xla else cfg.threads,
            None if xla else cfg.max_blocks,
            cfg.stream_buffers if not xla and cfg.kernel == KERNEL_STREAM
            else None)


def main(argv=None) -> int:
    """CLI: race the tile grid and rank verified configs. No reference
    analog — the reference pinned exactly one geometry
    (reduction.cpp:665-668); this sweep exists because Pallas tiling is
    a free parameter there never was."""
    p = argparse.ArgumentParser(
        prog="tpu_reductions.autotune",
        description="Race the Pallas tile-geometry grid and report the "
                    "fastest verified configuration",
    )
    p.add_argument("--method", type=str, default="SUM")
    p.add_argument("--type", dest="dtype", type=str, default="int")
    p.add_argument("--n", type=int, default=1 << 24)
    p.add_argument("--iterations", type=int, default=50)
    p.add_argument("--warmup", type=int, default=2)
    p.add_argument("--stat", type=str, default="median",
                   choices=("mean", "median"))
    p.add_argument("--timing", type=str, default="chained",
                   choices=("periter", "bulk", "fetch", "chained"),
                   help="Sync discipline; chained is the only honest "
                        "mode on the tunneled TPU (ops/chain.py)")
    p.add_argument("--chainreps", dest="chain_reps", type=int, default=5)
    p.add_argument("--platform", type=str, default=None,
                   choices=("cpu", "tpu"))
    p.add_argument("--grid", type=str, default="default",
                   choices=sorted(GRIDS),
                   help="Candidate grid: 'default' spans the space, "
                        "'fine' races tightly around the round-2 "
                        "winners (tune_r02.json), 'hbm' targets the "
                        "HBM-bound regime (use with --n >= 2^26)")
    p.add_argument("--comparator", action="store_true",
                   help="Append one XLA-backend row to the race (the "
                        "baseline the Pallas winner must beat)")
    p.add_argument("--out", type=str, default=None,
                   help="Write the ranked results as JSON to this path")
    ns = p.parse_args(argv)
    if ns.dtype not in DTYPE_ALIASES:
        p.error(f"unknown --type {ns.dtype!r}")
    if ns.method.upper() not in METHODS:
        p.error(f"--method must be one of {METHODS}, got {ns.method!r}")
    if ns.n <= 0:
        p.error("--n must be positive")
    _apply_platform(ns)

    base = ReduceConfig(method=ns.method, dtype=ns.dtype, n=ns.n,
                        iterations=ns.iterations, warmup=ns.warmup,
                        stat=ns.stat, timing=ns.timing,
                        chain_reps=ns.chain_reps, log_file=None)
    # flight recorder + watchdog, armed together (docs/OBSERVABILITY.md)
    from tpu_reductions.obs.ledger import arm_session
    arm_session("bench.autotune",
                argv=list(argv) if argv else sys.argv[1:])
    from tpu_reductions.exec.core import maybe_arm_for_tpu
    maybe_arm_for_tpu()  # a race hung on a dead relay loses its ranking
    logger = BenchLogger(None, None, console=sys.stderr)

    # meta is the resume contract: a re-invocation after a mid-race
    # watchdog exit reuses only rows raced under the SAME op/dtype/n/
    # grid/discipline (bench/resume.Checkpoint)
    meta = {"method": ns.method.upper(),
            "dtype": DTYPE_ALIASES[ns.dtype], "n": ns.n,
            "grid": ns.grid, "timing": ns.timing,
            "iterations": ns.iterations, "chain_reps": ns.chain_reps,
            "stat": ns.stat}
    from tpu_reductions.bench.resume import Checkpoint, result_from_row
    ck = Checkpoint(ns.out, meta, rows_key="ranked", key_fn=_row_key,
                    # ranked-so-far order at every persist: a relay
                    # death mid-race keeps a sorted, readable artifact
                    sort_key=lambda r: (r["status"] != "PASSED",
                                        -(r["gbps"] or 0.0)))

    def persist(cfg, res):
        # after EVERY candidate, flagged incomplete: a relay death
        # mid-race keeps the measured candidates on disk
        ck.add(_row(cfg, res), extra={"best": None})

    def resume_candidate(cfg):
        row = ck.resume(_cfg_key(cfg))
        return result_from_row(cfg, row) if row is not None else None

    pairs = autotune(base, grid=GRIDS[ns.grid], logger=logger,
                     comparator=ns.comparator, on_result=persist,
                     resume=(resume_candidate
                             if ns.timing == "chained" else None))
    rows = []
    for cfg, res in pairs:
        row = _row(cfg, res)
        rows.append(row)
        # kernel-10 rows differ ONLY in depth in the hbm grid — the
        # console record (what survives a mid-race wedge in scrollback)
        # must state it, not just the JSON
        depth = (f" depth={cfg.stream_buffers}"
                 if row.get("stream_buffers") is not None else "")
        geom = ("(geometry n/a)          " if row["kernel"] is None else
                f"kernel={cfg.kernel} threads={cfg.threads:>5} "
                f"maxblocks={cfg.max_blocks:>4}{depth}")
        print(f"{cfg.backend:>6} {geom}  {res.gbps:10.2f} GB/s "
              f"[{res.status.name}]")
    # best = the fastest VERIFIED **tunable** (pallas) candidate: the
    # comparator row is a fixed baseline, not a geometry this tool can
    # recommend, and it must not mask "every Pallas candidate failed"
    # (exit 1) just because the baseline passed
    best = next((r for r, (cfg, res) in zip(rows, pairs)
                 if res.passed and cfg.backend == "pallas"), None)
    if best:
        bdepth = (f" depth={best['stream_buffers']}"
                  if best.get("stream_buffers") is not None else "")
        print(f"best: {best['backend']} kernel={best['kernel']} "
              f"threads={best['threads']} "
              f"maxblocks={best['max_blocks']}{bdepth} "
              f"-> {best['gbps']} GB/s")
    if ns.out:
        ck.finalize(extra={"best": best})
        print(f"wrote {ns.out}")
    return 0 if best else 1


if __name__ == "__main__":
    sys.exit(main())
