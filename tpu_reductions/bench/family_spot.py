"""L5: the reduction-family spot instrument — SCAN / SEG* / ARG*
measured, oracle-verified, and served (ISSUE 20; docs/FAMILY.md).

The reference benchmarks exactly three full reductions
(reduction.h:15-25); the family around them (prefix scan, segmented
reduce, argmin/argmax — ops/family/) lands here as one committed
artifact with the same discipline every other instrument follows:

  * every (method, dtype, impl) cell is CHAINED-timed (ops/chain.py —
    the only honest per-iteration clock on the tunneled TPU) and
    verified against the host oracle BEFORE its GB/s number counts:
    SCAN element-wise against the float64/int64 prefix
    (ops/family/scan.host_scan; int32 bit-exact under the mod-2^32
    wrap), SEG* per-segment against host_segment_reduce (ragged
    offsets with empty segments by construction), ARG* exact-index
    against numpy's first-occurrence argmin/argmax;
  * the SCAN cells race both implementations — the MXU matmul trick
    (arXiv:1811.09736) vs the XLA cumsum baseline — and the committed
    rates are exactly what `exec/cost.pick_scan` prices its candidate
    axis from;
  * three serving rows prove SCAN/SEGSUM/ARGMAX requests resolve `ok`
    END-TO-END through the coalescing engine (serve/engine.py ->
    serve/executor._run_family_batch) on the same platform — the wire
    support is measured, not asserted.

Every cell persists the moment it lands and resumes under the shared
contract (bench/resume.Checkpoint, keyed (kind, method, dtype, impl));
the `family.cell` fault point fires before each cell's payload exists,
so a scripted mid-grid exit-3 rehearses the relay-death resume
(tests/test_family.py). Rows print in the pinned
`DATATYPE OP IMPL N GBPS STATUS` schema (lint/grammar.py); bench/regen
folds the table into report.md.

CLI:
    python -m tpu_reductions.bench.family_spot [--platform=cpu] \
        [--n=1048576 --serve-n=16384 --segments=64 --seed=0 --reps=5] \
        --out=examples/tpu_run/family_spot.json
"""

from __future__ import annotations

import argparse
import sys
from typing import List, Optional

from tpu_reductions.faults.inject import fault_point
from tpu_reductions.lint.grammar import FAMILY_HEADER
from tpu_reductions.obs import ledger
from tpu_reductions.utils.logging import BenchLogger, family_row

# the committed grid: every family method x dtype the matrix supports
# (docs/FAMILY.md — no family f64; the dd pair planes stay with the
# classic methods), SCAN racing both implementations where legal
FAMILY_DTYPES = ("int32", "float32")
SEG_METHODS = ("SEGSUM", "SEGMIN", "SEGMAX")
ARG_METHODS = ("ARGMIN", "ARGMAX")
# the end-to-end serving rows: one method per family group, resolved
# `ok` through the real coalescing engine
SERVE_CELLS = (("SCAN", "float32"), ("SEGSUM", "int32"),
               ("ARGMAX", "float32"))


def family_cells() -> List[tuple]:
    """The (kind, method, dtype, impl) grid in artifact order — scan
    first (its rows carry the cost-oracle story), then the segmented
    group, then arg, then the serving proof rows.

    No reference analog (TPU-native).
    """
    from tpu_reductions.ops.family import scan_impls
    cells = []
    for dtype in FAMILY_DTYPES:
        for impl in scan_impls(dtype):
            cells.append(("cell", "SCAN", dtype, impl))
    for method in SEG_METHODS:
        for dtype in FAMILY_DTYPES:
            cells.append(("cell", method, dtype, "seg"))
    for method in ARG_METHODS:
        for dtype in FAMILY_DTYPES:
            cells.append(("cell", method, dtype, "argk"))
    for method, dtype in SERVE_CELLS:
        cells.append(("serve", method, dtype, "serve"))
    return cells


def _verify(method: str, dtype: str, impl: str, x, got, segments,
            offsets) -> tuple:
    """(ok, max_err): the per-method oracle comparison (module
    docstring). `got` is the full device result array/scalar from the
    verification launch — never the chained digest, which exists for
    timing only (ops/chain.py doctrine)."""
    import numpy as np

    from tpu_reductions.ops.family import (host_arg_reduce, host_scan,
                                           host_segment_reduce)
    from tpu_reductions.ops.registry import tolerance

    if method == "SCAN":
        want = host_scan(x)
        if dtype == "int32":
            return bool(np.array_equal(got, want)), float(
                np.abs(got.astype(np.int64) - want.astype(np.int64))
                .max())
        err = float(np.abs(got.astype(np.float64) - want).max())
        return err <= tolerance("SUM", dtype, x.size), err
    if method in SEG_METHODS:
        want = host_segment_reduce(x, offsets, method)
        got64 = got.astype(np.float64)
        if method == "SEGSUM" and dtype != "int32":
            finite = np.isfinite(want)
            err = float(np.abs(got64[finite] - want[finite]).max())
            return err <= tolerance("SUM", dtype, x.size), err
        # int32 (wrap-exact) and MIN/MAX (exact, +-inf identities on
        # empty segments compare equal) are exact-match classes
        eq = bool(np.array_equal(got64, want))
        with np.errstate(invalid="ignore"):
            err = float(np.nan_to_num(
                np.abs(got64 - want), nan=0.0, posinf=0.0).max())
        return eq, err
    want = host_arg_reduce(x, method)
    err = float(abs(int(got) - int(want)))
    return int(got) == int(want), err


def measure_cell(method: str, dtype: str, impl: str, n: int,
                 segments: int, seed: int, reps: int) -> dict:
    """One grid cell: a dedicated verification launch (full result
    array against the host oracle), then the chained-slope timing of a
    scalar digest core (make_chained_reduce — the digest's only job is
    the data dependence; verification never reads it).

    No reference analog (TPU-native).
    """
    import jax
    import jax.numpy as jnp
    import numpy as np

    from tpu_reductions.exec import core as exec_core
    from tpu_reductions.exec.plan import device_task
    from tpu_reductions.ops.chain import (auto_chain_span,
                                          make_chained_reduce)
    from tpu_reductions.ops.family import (arg_reduce_fn, family_surface,
                                           random_offsets, scan_fn,
                                           segment_ids_from_offsets,
                                           segment_reduce_fn)
    from tpu_reductions.ops.registry import get_op
    from tpu_reductions.utils.rng import host_data
    from tpu_reductions.utils.timing import Stopwatch, time_chained

    # chaos hook: one cell = one interruptible unit (docs/RESILIENCE.md
    # fault-point table; tests/test_family.py scripts an exit-3 here)
    fault_point("family.cell")

    x = host_data(n, dtype, rank=0, seed=seed)
    x2d = x.reshape(-1, 128)
    surface = family_surface(method, impl)
    offsets = None
    zero = np.dtype(dtype).type(0)

    if method == "SCAN":
        fn = scan_fn(impl, dtype)

        def full(x1d):
            return fn(x1d, zero)

        def core(xx):
            return fn(xx.reshape(-1), zero)[-1]
    elif method in SEG_METHODS:
        offsets = random_offsets(n, segments, seed)
        ids = segment_ids_from_offsets(offsets)
        mask = np.diff(offsets) > 0
        sfn = segment_reduce_fn(method, segments)

        def full(x1d):
            return sfn(x1d, ids)

        def core(xx):
            # timing digest only: empty-segment identities (+-inf for
            # float MIN/MAX) are masked so the scalar stays finite
            segs = sfn(xx.reshape(-1), ids)
            return jnp.where(mask, segs, zero).sum()
    else:
        fn = arg_reduce_fn(method, dtype)

        def full(x1d):
            return fn(x1d)

        def core(xx):
            return fn(xx.reshape(-1))

    # verification launch: one retried, flap-classified unit through
    # THE executor (exec/core.py) — full result materialized and
    # compared before any timing number exists
    got = np.asarray(exec_core.run(device_task(
        surface, lambda: jax.device_get(full(x)),
        method=method, dtype=dtype, n=n)))
    ok, max_err = _verify(method, dtype, impl, x, got, segments, offsets)

    chained = make_chained_reduce(core, get_op(method), surface=surface)
    span = auto_chain_span(n, dtype)
    watch = Stopwatch()
    time_chained(chained, x2d, 1, 1 + span, reps=reps, stopwatch=watch)
    per_iter = watch.median_s
    nbytes = n * np.dtype(dtype).itemsize
    gbps = (nbytes / per_iter / 1e9) if per_iter > 0 else 0.0

    row = {"kind": "cell", "method": method, "dtype": dtype,
           "impl": impl, "n": int(n), "segments": (segments if offsets
                                                   is not None else None),
           "span": span, "reps": reps, "per_iter_s": per_iter,
           "gbps": round(gbps, 4), "max_err": max_err,
           "status": "PASSED" if ok else "FAILED"}
    ledger.emit("family.cell", method=method, dtype=dtype, impl=impl,
                n=int(n), gbps=row["gbps"], status=row["status"])
    return row


def measure_serve(method: str, dtype: str, n: int, requests: int = 3
                  ) -> dict:
    """One serving proof row: `requests` real ReduceRequests submitted
    to an in-process ServeEngine and required to resolve `ok` through
    the coalescing path (serve/executor._run_family_batch emits the
    family.serve ledger evidence). This is the acceptance row — the
    family wire support measured end-to-end, not asserted.

    No reference analog (TPU-native).
    """
    import time as _time

    from tpu_reductions.serve.engine import ServeEngine
    from tpu_reductions.serve.request import ReduceRequest

    fault_point("family.cell")   # serving rows resume like any cell

    eng = ServeEngine(coalesce_window_s=0.0).start()
    try:
        t0 = _time.perf_counter()
        pends = [eng.submit(ReduceRequest(method=method, dtype=dtype,
                                          n=n, seed=s))
                 for s in range(requests)]
        resps = [p.result(timeout=120.0) for p in pends]
        wall = _time.perf_counter() - t0
    finally:
        eng.stop()
    ok_n = sum(1 for r in resps if r.status == "ok")
    row = {"kind": "serve", "method": method, "dtype": dtype,
           "impl": "serve", "n": int(n), "requests": requests,
           "ok_count": ok_n, "gbps": 0.0,
           "latency_s": round(wall, 6),
           "status": "PASSED" if ok_n == requests else "FAILED"}
    ledger.emit("family.cell", method=method, dtype=dtype, impl="serve",
                n=int(n), gbps=0.0, status=row["status"])
    return row


def run_family_spot(*, n: int, serve_n: int, segments: int, seed: int,
                    reps: int, out: Optional[str] = None,
                    logger: Optional[BenchLogger] = None) -> List[dict]:
    """The full grid with per-cell persist/resume (bench/resume
    .Checkpoint + run_checkpointed_cells — the shared loop of the
    quant/reshard curves), serving rows included.

    No reference analog (TPU-native).
    """
    from tpu_reductions.bench.resume import (Checkpoint,
                                             run_checkpointed_cells)
    logger = logger or BenchLogger(None, None)
    ck = Checkpoint(out, {"n": n, "serve_n": serve_n,
                          "segments": segments, "seed": seed,
                          "reps": reps, "timing": "chained",
                          "stat": "median"},
                    key_fn=lambda r: (r.get("kind", "cell"),
                                      r.get("method"), r.get("dtype"),
                                      r.get("impl")))
    logger.log(FAMILY_HEADER)

    def measure(key):
        kind, method, dtype, impl = key
        if kind == "serve":
            return measure_serve(method, dtype, serve_n)
        return measure_cell(method, dtype, impl, n, segments, seed, reps)

    def on_row(key, row):
        logger.log(family_row(row["dtype"], row["method"], row["impl"],
                              row["n"], row["gbps"], row["status"]))

    return run_checkpointed_cells(ck, family_cells(), measure, on_row)


def family_spot_markdown(data: dict) -> str:
    """The report fold (bench/regen.py): the committed family grid as
    one table — measured GB/s per (method, dtype, impl) with its
    verification verdict — plus the serving proof rows. Empty string
    when there are no rows (regen then skips the section).

    No reference analog (TPU-native).
    """
    rows = [r for r in data.get("rows", []) if isinstance(r, dict)]
    if not rows:
        return ""
    cells = [r for r in rows if r.get("kind") != "serve"]
    serves = [r for r in rows if r.get("kind") == "serve"]
    n_fail = sum(1 for r in rows if r.get("status") != "PASSED")
    lines = [
        "### Reduction family (SCAN / segmented / argmin-argmax)",
        "",
        f"{len(cells)} chained-verified cells at n={data.get('n')}"
        + (f" — **{n_fail} FAILED**" if n_fail
           else "; every cell oracle-verified")
        + " (docs/FAMILY.md; `python -m tpu_reductions.bench."
          "family_spot`). SCAN rates price `exec/cost.pick_scan`'s "
          "mxu-scan vs xla-cumsum axis.",
        "",
        "| method | dtype | impl | GB/s | max err | status |",
        "|---|---|---|---|---|---|",
    ]
    for r in cells:
        lines.append(
            f"| {r['method']} | {r['dtype']} | {r['impl']} "
            f"| {r['gbps']:.3f} | {r.get('max_err', 0.0):.3e} "
            f"| {r['status']} |")
    if serves:
        lines += ["",
                  "| served method | dtype | n | requests ok | status |",
                  "|---|---|---|---|---|"]
        for r in serves:
            lines.append(
                f"| {r['method']} | {r['dtype']} | {r['n']} "
                f"| {r['ok_count']}/{r['requests']} | {r['status']} |")
    return "\n".join(lines)


def main(argv=None) -> int:
    """CLI: the family grid + serving proof, one committed JSON
    artifact — the reference's per-op benchmark loop
    (reduction.cpp:161-200) extended to the method family it never
    had."""
    p = argparse.ArgumentParser(
        prog="tpu_reductions.bench.family_spot",
        description="Reduction-family spot: SCAN (mxu-scan vs "
                    "xla-cumsum), segmented reduce, argmin/argmax — "
                    "chained-timed, oracle-verified, served end-to-end",
    )
    p.add_argument("--n", type=int, default=1 << 20,
                   help="Cell payload elements (must divide by 128 for "
                        "the chained 2-D view)")
    p.add_argument("--serve-n", dest="serve_n", type=int,
                   default=1 << 14,
                   help="Per-request elements for the serving rows")
    p.add_argument("--segments", type=int, default=64,
                   help="Segment count for the SEG* cells (ragged "
                        "random offsets; empty segments occur by "
                        "construction)")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--reps", type=int, default=5,
                   help="Chained slope samples per cell (median wins)")
    p.add_argument("--platform", type=str, default=None,
                   choices=("cpu", "tpu"))
    p.add_argument("--out", type=str, default=None)
    ns = p.parse_args(argv)
    if ns.n <= 0 or ns.n % 128:
        p.error(f"--n must be a positive multiple of 128, got {ns.n}")
    if ns.segments < 2 or ns.serve_n <= 0 or ns.reps < 1:
        p.error("--segments >= 2, --serve-n > 0, --reps >= 1 required")
    from tpu_reductions.config import _apply_platform
    _apply_platform(ns)
    # flight recorder + watchdog BEFORE the first device touch
    # (docs/OBSERVABILITY.md; RED011)
    from tpu_reductions.obs.ledger import arm_session
    arm_session("bench.family_spot",
                argv=list(argv) if argv else sys.argv[1:])
    from tpu_reductions.exec.core import maybe_arm_for_tpu
    maybe_arm_for_tpu()
    logger = BenchLogger(None, None, console=sys.stdout)
    rows = run_family_spot(n=ns.n, serve_n=ns.serve_n,
                           segments=ns.segments, seed=ns.seed,
                           reps=ns.reps, out=ns.out, logger=logger)
    if ns.out:
        print(f"wrote {ns.out}")
    bad = [r for r in rows if r.get("status") != "PASSED"]
    for r in bad:
        print(f"FAILED: {r['method']} {r['dtype']} {r.get('impl')} "
              f"(max_err {r.get('max_err')})", file=sys.stderr)
    return 1 if bad or not rows else 0


if __name__ == "__main__":
    sys.exit(main())
