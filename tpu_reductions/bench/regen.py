"""Offline regeneration of a flagship experiment's report artifacts.

scripts/run_tpu_experiment.sh regenerates report.md/report.tex/
writeup.pdf only at its OWN end — a budget cut or relay death mid-
experiment leaves fresh raw cells and shmoo rows on disk with a stale
report on top of them. And the spot->cache seeder (seed_cache.py) can
land new flagship cells with no experiment run at all. This tool
re-collates everything FROM DISK: averages from the grid's raw cells,
curves from shmoo.json, roofline annotation, figures, report, pdf —
the analysis layer of run_tpu_experiment.sh with the benchmarking
stripped out (the same collected->averaged->plotted offline pipeline
the reference ran as getAvgs.sh + makePlots.gp over accumulated
stdout-* files).

Offline by construction: never touches a device, safe after the relay
dies. DOUBLE/INT averaging prefers rows measured under the current
flagship contract (sweep.FLAGSHIP_GRID); for a (dtype, op) with no
contract-matching rows it falls back to whatever PASSED rows exist
(legacy cells from an older discipline), so a half-migrated cache
still reports honestly rather than dropping the rows.


No reference analog (TPU-native).
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path
from typing import Dict, List, Tuple

from tpu_reductions.bench.sweep import FLAGSHIP_GRID, cell_matches

# the flagship plot constants (scripts/run_tpu_experiment.sh step 3)
PLOT_TITLE = "TPU v5e single-chip reduction bandwidth vs N"
PLOT_HLINES = {"reference CUDA int SUM (90.8)": 90.8413,
               "v5e HBM roof (819)": 819.0}
_DTYPE_LABEL = {"int32": "INT", "float64": "DOUBLE"}


def collect_averages(grid_dir: Path, grid: dict | None = None,
                     log=print) -> Dict[Tuple[str, str], float]:
    """{(DATATYPE, OP): mean GB/s} from the grid's raw cells, contract-
    matching rows first, legacy PASSED rows as the labeled fallback.

    No reference analog (TPU-native).
    """
    grid = dict(grid or FLAGSHIP_GRID)
    contract = {k: grid[k] for k in ("n", "backend", "kernel", "threads",
                                     "iterations", "timing",
                                     "chain_reps")}
    matching: Dict[Tuple[str, str], List[float]] = {}
    legacy: Dict[Tuple[str, str], List[float]] = {}
    for f in sorted((grid_dir / "raw_output").glob("run-*.json")):
        try:
            row = json.loads(f.read_text())
        except (OSError, ValueError):
            continue
        method, dtype = row.get("method"), row.get("dtype")
        gbps = row.get("gbps")
        if (row.get("status") != "PASSED" or not method or not dtype
                or not isinstance(gbps, (int, float))):
            continue
        key = (_DTYPE_LABEL.get(dtype, dtype.upper()), method)
        if cell_matches(row, method=method, dtype=dtype, **contract):
            matching.setdefault(key, []).append(float(gbps))
        elif (row.get("n") == contract["n"]
              and row.get("kernel") == contract["kernel"]
              and row.get("threads") == contract["threads"]
              and row.get("backend") == contract["backend"]):
            # legacy fallback is for older-DISCIPLINE cells at the FULL
            # flagship geometry (e.g. round-2 f64 fetch rows, measured
            # at threads=512/pallas) — a cell at a different n/kernel/
            # threads/backend (say a stray threads=1024 race row) must
            # never be averaged into the flagship table, however it got
            # into the cache (round-4 ADVICE 2)
            legacy.setdefault(key, []).append(float(gbps))
    out = {}
    for key in sorted(set(matching) | set(legacy)):
        vals = matching.get(key) or legacy.get(key)
        out[key] = sum(vals) / len(vals)
        if key not in matching:
            log(f"regen: {key[0]} {key[1]}: no contract-matching cells; "
                f"averaging {len(vals)} legacy row(s)")
    return out


def find_round_metrics(out_dir: Path) -> List[Path]:
    """Locate the committed per-round headline artifacts
    (BENCH_r01.json..) by walking up from the experiment dir to the
    repo root (they live at the top level, next to ROADMAP.md), falling
    back to the cwd. Snapshot side-files are excluded — they are a
    round's provenance, not a round.

    No reference analog (TPU-native).
    """
    for cand in (out_dir.resolve(), *out_dir.resolve().parents,
                 Path.cwd()):
        hits = sorted(f for f in cand.glob("BENCH_r[0-9]*.json")
                      if "snapshot" not in f.name)
        if hits:
            return hits
    return []


def trajectory_markdown(files: List[Path],
                        single_chip: Dict[Tuple[str, str], float]
                        | None = None) -> str:
    """The cross-round headline trajectory table (ISSUE 12 satellite):
    every committed round metric (bench.py's one JSON line, persisted
    as BENCH_rNN.json) in one table — int32 flagship GB/s, the
    vs-baseline multiple, and the measurement standing (measured /
    carried-stale / outage) — so a regression across windows is
    visible in one place instead of five files. The f64 column reads
    the round row when it carries one (`doubles_gbps`), else the
    current flagship DOUBLE SUM average stands underneath as context
    (the per-round files predate the DOUBLE scoreboard).

    No reference analog (TPU-native).
    """
    rows = []
    for f in files:
        try:
            d = json.loads(f.read_text())
        except (OSError, ValueError):
            continue
        p = d.get("parsed") or {}
        v = p.get("value")
        if not isinstance(v, (int, float)):
            continue
        rows.append({"round": d.get("n") or f.stem, "value": float(v),
                     "vs": p.get("vs_baseline"),
                     "doubles": p.get("doubles_gbps"),
                     "stale": bool(p.get("stale")),
                     "unit": p.get("unit") or "GB/s"})
    if not rows:
        return ""
    lines = ["## headline trajectory (cross-round)", "",
             "| round | int32 SUM GB/s | vs baseline | f64 GB/s "
             "| standing |", "|---|---|---|---|---|"]
    for r in rows:
        if r["value"] <= 0:
            standing = "outage (no measurement landed)"
        elif r["stale"]:
            standing = "carried (stale; accelerator unavailable)"
        else:
            standing = "measured live"
        vs = f"{r['vs']:.1f}x" if isinstance(r["vs"], (int, float)) \
            and r["vs"] > 0 else "-"
        dbl = f"{r['doubles']:.1f}" \
            if isinstance(r["doubles"], (int, float)) else "-"
        label = f"r{r['round']:02d}" if isinstance(r["round"], int) \
            else str(r["round"])
        lines.append(f"| {label} | {r['value']:.1f} | {vs} "
                     f"| {dbl} | {standing} |")
    if single_chip:
        dbl_now = single_chip.get(("DOUBLE", "SUM"))
        if isinstance(dbl_now, (int, float)):
            lines.append("")
            lines.append(f"current flagship DOUBLE SUM average: "
                         f"{dbl_now:.1f} GB/s (single_chip/"
                         "averages.json; the per-round files carry "
                         "only the int32 headline)")
    return "\n".join(lines)


def regenerate(out_dir: str | Path, device_kind: str | None = None,
               log=print) -> bool:
    """Re-collate out_dir's report artifacts from disk. Returns False
    (and does nothing) when out_dir has no experiment data.

    No reference analog (TPU-native).
    """
    out = Path(out_dir)
    grid_dir = out / "single_chip"
    shmoo_file = out / "shmoo.json"
    if not grid_dir.is_dir() and not shmoo_file.exists():
        log(f"regen: {out}: no experiment data (no single_chip/, no "
            "shmoo.json); nothing to do")
        return False

    from tpu_reductions.bench.pdf import generate_pdf
    from tpu_reductions.bench.plot import plot_vs_n
    from tpu_reductions.bench.report import generate_report
    from tpu_reductions.bench.roofline import annotate, summarize

    cal = None
    cal_file = out / "calibration.json"
    if cal_file.exists():
        try:
            cal = json.loads(cal_file.read_text())
        except (OSError, ValueError):
            cal = None
    platform = (cal or {}).get("platform", "tpu")

    from tpu_reductions.utils.jsonio import atomic_json_dump
    sc = collect_averages(grid_dir, log=log) if grid_dir.is_dir() else {}
    if sc:
        atomic_json_dump(
            grid_dir / "averages.json",
            {f"{d} {m}": g for (d, m), g in sorted(sc.items())})

    shmoo_rows: List[dict] = []
    if shmoo_file.exists():
        try:
            shmoo_rows = json.loads(shmoo_file.read_text())
        except (OSError, ValueError):
            shmoo_rows = []

    figures = ()
    if shmoo_rows:
        figures = plot_vs_n(shmoo_rows, out / "bandwidth_vs_n",
                            title=PLOT_TITLE, hlines=PLOT_HLINES)
    if device_kind is None:
        # reuse the kind the live run recorded (roofline.json) so an
        # offline regen never relabels the hardware
        try:
            ann_prior = json.loads((out / "roofline.json").read_text())
            device_kind = ann_prior[0]["device_kind"]
        except (OSError, ValueError, LookupError, TypeError, KeyError):
            device_kind = None
    ann = annotate(shmoo_rows, device_kind=device_kind)
    roof_lines = summarize(ann)
    if ann:
        atomic_json_dump(out / "roofline.json", ann)

    paths = generate_report({}, single_chip=sc, figures=figures,
                            out_dir=out, platform=platform,
                            calibration=cal, roofline=roof_lines,
                            annotated_rows=ann)
    log(f"regen: report: {paths['md']} {paths['tex']}")
    # flight-recorder collation: chip_session's exit trap drops the
    # timeline summary (obs/timeline.py --json) next to the flagship
    # evidence — fold its window-utilization table into the report so
    # "where did the window's minutes go" ships with the numbers
    tl_file = out / "obs_timeline.json"
    if tl_file.exists():
        try:
            from tpu_reductions.obs.timeline import summary_markdown
            tl = json.loads(tl_file.read_text())
            with open(paths["md"], "a") as f:
                f.write("\n" + summary_markdown(tl) + "\n")
            log("regen: appended window-utilization table "
                "(obs_timeline.json)")
        except (OSError, ValueError, KeyError, TypeError) as e:
            log(f"regen: obs_timeline.json unusable ({e}); skipped")
    # the scheduler's plan-vs-actual record (ISSUE 5 satellite): the
    # chip_session exit trap copies the plan state next to the
    # evidence; fold it in so every window's report says what the
    # planner promised vs what it delivered
    sched_file = out / "sched_state.json"
    if sched_file.exists():
        try:
            from tpu_reductions.sched.state import plan_vs_actual_markdown
            sched_state = json.loads(sched_file.read_text())
            with open(paths["md"], "a") as f:
                f.write("\n" + plan_vs_actual_markdown(sched_state)
                        + "\n")
            log("regen: appended plan-vs-actual table "
                "(sched_state.json)")
        except (OSError, ValueError, KeyError, TypeError) as e:
            log(f"regen: sched_state.json unusable ({e}); skipped")
    # the serving curve (ISSUE 6): requests/s + p50/p99 at N concurrent
    # clients, committed by serve/loadgen.py — the throughput-under-
    # load table next to GB/s
    sv_file = out / "serving_curve.json"
    if sv_file.exists():
        try:
            from tpu_reductions.serve.loadgen import curve_markdown
            sv = json.loads(sv_file.read_text())
            with open(paths["md"], "a") as f:
                f.write("\n" + curve_markdown(sv) + "\n")
            log("regen: appended serving-curve table "
                "(serving_curve.json)")
        except (OSError, ValueError, KeyError, TypeError) as e:
            log(f"regen: serving_curve.json unusable ({e}); skipped")
    # the open-loop scaling curve (ISSUE 13): requests/s + p50/p99 vs
    # clients across sequential/coalesced/routerN plus the
    # device-parallel sharded row, committed by serve/loadgen.py
    # --scale (scripts/run_serving_scale.sh)
    sc_file = out / "serving_scale.json"
    if sc_file.exists():
        try:
            from tpu_reductions.serve.loadgen import scale_markdown
            sc = json.loads(sc_file.read_text())
            with open(paths["md"], "a") as f:
                f.write("\n" + scale_markdown(sc) + "\n")
            log("regen: appended serving-scale table "
                "(serving_scale.json)")
        except (OSError, ValueError, KeyError, TypeError) as e:
            log(f"regen: serving_scale.json unusable ({e}); skipped")
    # the elastic autoscaler curve (ISSUE 17): replica count tracking
    # the diurnal load plan + the drain-vs-kill contract row,
    # committed by serve/loadgen.py --elastic
    # (scripts/run_serving_elastic.sh)
    el_file = out / "serving_elastic.json"
    if el_file.exists():
        try:
            from tpu_reductions.serve.loadgen import elastic_markdown
            el = json.loads(el_file.read_text())
            with open(paths["md"], "a") as f:
                f.write("\n" + elastic_markdown(el) + "\n")
            log("regen: appended elastic-fleet table "
                "(serving_elastic.json)")
        except (OSError, ValueError, KeyError, TypeError) as e:
            log(f"regen: serving_elastic.json unusable ({e}); skipped")
    # the crash-recovery instrument (ISSUE 18): MTTR / shed /
    # ledger-verified duplicate device executions for kill-router vs
    # kill-replica vs drain on one seeded idem-keyed workload,
    # committed by serve/loadgen.py --recovery
    # (scripts/run_serving_recovery.sh)
    rc_file = out / "serving_recovery.json"
    if rc_file.exists():
        try:
            from tpu_reductions.serve.loadgen import recovery_markdown
            rc = json.loads(rc_file.read_text())
            with open(paths["md"], "a") as f:
                f.write("\n" + recovery_markdown(rc) + "\n")
            log("regen: appended crash-recovery table "
                "(serving_recovery.json)")
        except (OSError, ValueError, KeyError, TypeError) as e:
            log(f"regen: serving_recovery.json unusable ({e}); skipped")
    # the streaming pipeline's committed probes (ISSUE 7 evidence,
    # ISSUE 8 relocation: the ONE copy lives in the experiment dir —
    # the PR-6 serving_curve dedup rule applied to stream artifacts)
    probes = {}
    for name in ("stream_probe", "stream_hazard"):
        pf = out / f"{name}.json"
        if pf.exists():
            try:
                probes[name] = json.loads(pf.read_text())
            except (OSError, ValueError):
                log(f"regen: {name}.json unusable; skipped")
    if probes:
        try:
            from tpu_reductions.bench.stream import stream_markdown
            with open(paths["md"], "a") as f:
                f.write("\n" + stream_markdown(probes) + "\n")
            log(f"regen: appended streaming-pipeline table "
                f"({', '.join(sorted(probes))})")
        except (OSError, ValueError, KeyError, TypeError) as e:
            log(f"regen: stream probes unusable ({e}); skipped")
    # the quantized suite's accuracy-vs-bandwidth curve (ISSUE 10):
    # the committed instrument lives with the rank-scaling evidence
    # (examples/rank_scaling/quant_curve.json — the sibling experiment
    # dir, same rank ladder); an out_dir-local copy wins if present
    qc_file = out / "quant_curve.json"
    if not qc_file.exists():
        qc_file = out.parent / "rank_scaling" / "quant_curve.json"
    if qc_file.exists():
        try:
            from tpu_reductions.bench.quant_curve import quant_curve_markdown
            qc = json.loads(qc_file.read_text())
            md = quant_curve_markdown(qc)
            if md:
                with open(paths["md"], "a") as f:
                    f.write("\n" + md + "\n")
                log(f"regen: appended accuracy-vs-bandwidth table "
                    f"({qc_file})")
        except (OSError, ValueError, KeyError, TypeError) as e:
            log(f"regen: quant_curve.json unusable ({e}); skipped")
    # the reshard engine's redistribution curve (ISSUE 15): committed
    # next to the rank-scaling evidence like quant_curve; same
    # out_dir-local override rule
    rc_file = out / "reshard_curve.json"
    if not rc_file.exists():
        rc_file = out.parent / "rank_scaling" / "reshard_curve.json"
    if rc_file.exists():
        try:
            from tpu_reductions.bench.reshard_curve import \
                reshard_curve_markdown
            rc = json.loads(rc_file.read_text())
            md = reshard_curve_markdown(rc)
            if md:
                with open(paths["md"], "a") as f:
                    f.write("\n" + md + "\n")
                log(f"regen: appended redistribution-curve table "
                    f"({rc_file})")
        except (OSError, ValueError, KeyError, TypeError) as e:
            log(f"regen: reshard_curve.json unusable ({e}); skipped")
    # the compile observatory's per-surface cold/warm table (ISSUE 8):
    # chip_session's exit trap copies compile_ledger.json next to the
    # evidence; the compile axis ships with the numbers it explains
    cl_file = out / "compile_ledger.json"
    if cl_file.exists():
        try:
            from tpu_reductions.obs.compile import (compile_markdown,
                                                    load as load_compile)
            cl = load_compile(cl_file)
            if cl is not None:
                with open(paths["md"], "a") as f:
                    f.write("\n" + compile_markdown(cl) + "\n")
                log("regen: appended compile-latency table "
                    "(compile_ledger.json)")
        except (OSError, ValueError, KeyError, TypeError) as e:
            log(f"regen: compile_ledger.json unusable ({e}); skipped")
    # the execution core's decision audit (ISSUE 19): the committed
    # cost-oracle grid (exec_decisions.json) vs the static defaults —
    # each regime flip ships with the numbers it steers
    xd_file = out / "exec_decisions.json"
    if xd_file.exists():
        try:
            from tpu_reductions.exec.cost import decisions_markdown
            xd = json.loads(xd_file.read_text())
            md = decisions_markdown(xd)
            if md:
                with open(paths["md"], "a") as f:
                    f.write("\n" + md + "\n")
                log("regen: appended exec-decision audit "
                    "(exec_decisions.json)")
        except (OSError, ValueError, KeyError, TypeError) as e:
            log(f"regen: exec_decisions.json unusable ({e}); skipped")
    # the reduction-family spot grid (ISSUE 20): SCAN / SEG* / ARG*
    # chained-verified rates + the end-to-end serving proof rows —
    # the same rows exec/cost.pick_scan prices its scan axis from
    fs_file = out / "family_spot.json"
    if fs_file.exists():
        try:
            from tpu_reductions.bench.family_spot import \
                family_spot_markdown
            fs = json.loads(fs_file.read_text())
            md = family_spot_markdown(fs)
            if md:
                with open(paths["md"], "a") as f:
                    f.write("\n" + md + "\n")
                log("regen: appended reduction-family table "
                    "(family_spot.json)")
        except (OSError, ValueError, KeyError, TypeError) as e:
            log(f"regen: family_spot.json unusable ({e}); skipped")
    # the cross-round headline trajectory (ISSUE 12 satellite): the
    # committed BENCH_rNN.json round metrics collated into one table
    # so regressions across windows are visible in one place
    traj = trajectory_markdown(find_round_metrics(out), single_chip=sc)
    if traj:
        with open(paths["md"], "a") as f:
            f.write("\n" + traj + "\n")
        log("regen: appended headline-trajectory table (BENCH_r*.json)")
    pdf = generate_pdf(out, platform=platform,
                       data={"avgs": {}, "single_chip": sc or None,
                             "calibration": cal,
                             "figures": list(figures),
                             "roofline": roof_lines,
                             "annotated_rows": ann})
    log(f"regen: writeup: {pdf}")
    return True


def main(argv=None) -> int:
    """CLI: offline re-collation of an experiment dir — the analysis
    half of the reference's file pipeline (raw_output -> collected.txt
    -> results/ -> writeup; SURVEY.md §3.3) without touching a device."""
    p = argparse.ArgumentParser(
        prog="tpu_reductions.bench.regen",
        description="Regenerate an experiment dir's report artifacts "
                    "from its on-disk data (offline; no device)")
    p.add_argument("out_dir")
    p.add_argument("--device-kind", default=None,
                   help="roofline hardware label override (default: "
                        "whatever the live run recorded)")
    ns = p.parse_args(argv)
    regenerate(ns.out_dir, device_kind=ns.device_kind,
               log=lambda m: print(m, file=sys.stderr))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
