"""Off-chip compile warming: AOT-lower every never-lowered surface and
report which remain cold (ISSUE 8; docs/OBSERVABILITY.md).

The lowering smoke (bench/smoke.py) proves surfaces CAN lower by
compiling and running them — it costs a device. This pass warms them
for free: each registered surface is staged ahead-of-time
(`jit(...).lower(args).compile()` through the compile observatory's
split probe, obs/compile.py) so the persistent `.jax_cache/` holds its
executable BEFORE any window opens, and the per-surface lower/compile
split plus the cold/warm cache verdict land in `compile_ledger.json`
(the committed artifact the scheduler's priors and the report fold
read). Nothing executes: on `--platform=cpu` this is the rehearsal's
cache-priming step, and a second invocation is the acceptance probe —
every surface should come back `warm` with a measurably smaller
compile half.

The registry mirrors smoke's case table (the canonical race
geometries) plus the surfaces smoke cannot see: the XLA comparator
chain, the streaming chunk fold, and the serving engine's batch=1
bucket. Surfaces are probed in isolation — one that fails to lower is
reported and the pass continues (the report IS the product, exactly
like smoke's manifest).

The reference never needed a warming pass — its kernels compiled at
build time (no reference analog; the closest shape is the smoke
gate's front-loaded discovery, bench/smoke.py).

CLI:
    python -m tpu_reductions.bench.warm [--platform=cpu] \
        [--n=1048576] [--out=compile_ledger.json]
"""

from __future__ import annotations

import argparse
import sys
from typing import Callable, List, Optional, Tuple

from tpu_reductions.config import (KERNEL_ELEMENTWISE, KERNEL_MXU,
                                   KERNEL_STREAM, _apply_platform)
from tpu_reductions.obs import ledger
from tpu_reductions.obs import compile as obs_compile


def _kernel_surface(surface: str, kernel: int, dtype: str, threads: int,
                    depth: int, method: str = "SUM") -> Tuple[str,
                                                              Callable]:
    """One chained kernel executable, staged exactly as the races run
    it: the builder returns (jitted_chain, args) for the AOT probe —
    the SAME jit object the driver's chain seam dispatches, so the
    cache key warmed here is the one a live race hits.

    No reference analog (TPU-native).
    """
    def build(n: int):
        from tpu_reductions.ops.chain import make_chained_reduce
        from tpu_reductions.ops.pallas_reduce import make_staged_core
        from tpu_reductions.utils.rng import host_data
        op, stage_fn, core = make_staged_core(
            method, n, dtype, threads=threads, kernel=kernel,
            stream_buffers=depth)
        chained = make_chained_reduce(core, op, surface=surface)
        x2d = stage_fn(host_data(n, dtype, rank=0, seed=0))
        return chained.jitted, (x2d, 2)

    return surface, build


def _xla_surface() -> Tuple[str, Callable]:
    """The XLA-comparator chain (the `--backend=xla` rows).

    No reference analog (TPU-native).
    """
    def build(n: int):
        from tpu_reductions.ops.chain import make_chained_reduce
        from tpu_reductions.ops.registry import get_op
        from tpu_reductions.utils.rng import host_data
        op = get_op("SUM")
        chained = make_chained_reduce(op.jnp_reduce, op, surface="xla")
        x2d = host_data(n, "int32", rank=0, seed=0).reshape(-1, 128)
        return chained.jitted, (x2d, 2)

    return "xla", build


def _dd_surface() -> Tuple[str, Callable]:
    """The f64 pair-path chain (ops/dd_reduce.py SUM two_sum tree).

    No reference analog (TPU-native).
    """
    def build(n: int):
        from tpu_reductions.ops.chain import make_chained_reduce
        from tpu_reductions.ops.dd_reduce import make_dd_device_reduce
        from tpu_reductions.ops.registry import get_op
        from tpu_reductions.utils.rng import host_data
        stage, dd_core, _finish = make_dd_device_reduce("SUM", n)
        chained = make_chained_reduce(dd_core, get_op("SUM"),
                                      surface="dd")
        hi2d, lo2d, _scale = stage(host_data(n, "float64", rank=0,
                                             seed=0))
        return chained.jitted, ((hi2d, lo2d), 2)

    return "dd", build


def _stream_surface() -> Tuple[str, Callable]:
    """The streaming pipeline's chunk-fold executable (ops/stream.py).
    Lowered from shape specs alone — no payload, no device memory.

    No reference analog (TPU-native).
    """
    def build(n: int):
        import jax
        import numpy as np

        from tpu_reductions.ops.stream import (StreamReducer,
                                               plan_chunks)
        plan = plan_chunks(n, "int32", 128 * 128 * 4)
        r = StreamReducer("SUM", "int32", n,
                          chunk_bytes=plan.chunk_bytes)
        acc = jax.ShapeDtypeStruct((8, 128), np.int32)
        chunk = jax.ShapeDtypeStruct((plan.chunk_rows, 128), np.int32)
        return r._fold, (acc, chunk)

    return "stream", build


def _serve_surface() -> Tuple[str, Callable]:
    """The serving engine's batch=1 bucket row-reduce
    (serve/executor.py — what engine.prewarm compiles first).

    No reference analog (TPU-native).
    """
    def build(n: int):
        import jax
        import numpy as np

        from tpu_reductions.serve.executor import _jit_row_reduce
        fn = _jit_row_reduce("SUM")
        return fn, (jax.ShapeDtypeStruct((1, n), np.int32),)

    return "serve-bucket/sum", build


def _family_scan_surface(impl: str, dtype: str) -> Tuple[str, Callable]:
    """One family SCAN executable (ops/family/scan.py — the MXU matmul
    trick or the cumsum baseline), staged from shape specs alone.
    Surface id == impl, shared with bench/smoke.py FAMILY_CASES and
    ops/family.family_surface.

    No reference analog (TPU-native).
    """
    def build(n: int):
        import jax
        import numpy as np

        from tpu_reductions.ops.family import scan_fn
        fn = scan_fn(impl, dtype)
        return fn, (jax.ShapeDtypeStruct((n,), np.dtype(dtype)),
                    jax.ShapeDtypeStruct((), np.dtype(dtype)))

    return impl, build


def _family_seg_surface() -> Tuple[str, Callable]:
    """The segmented-reduce executable (ops/family/segmented.py).

    No reference analog (TPU-native).
    """
    def build(n: int):
        import jax
        import numpy as np

        from tpu_reductions.ops.family import segment_reduce_fn
        fn = segment_reduce_fn("SEGSUM", 64)
        return fn, (jax.ShapeDtypeStruct((n,), np.int32),
                    jax.ShapeDtypeStruct((n,), np.int32))

    return "seg/segsum", build


def _family_arg_surface() -> Tuple[str, Callable]:
    """The (key, index) arg-reduce executable (ops/family/argreduce.py).

    No reference analog (TPU-native).
    """
    def build(n: int):
        import jax
        import numpy as np

        from tpu_reductions.ops.family import arg_reduce_fn
        fn = arg_reduce_fn("ARGMIN", "float32")
        return fn, (jax.ShapeDtypeStruct((n,), np.float32),)

    return "argk/argmin", build


def surfaces() -> List[Tuple[str, Callable]]:
    """The warm registry: every surface the next window would
    otherwise compile cold, in smoke's canonical geometries
    (bench/smoke.py CASES) plus the chain/stream/serve executables
    smoke never builds.

    No reference analog (TPU-native).
    """
    return [
        _kernel_surface("k6", 6, "int32", 256, 4),
        _kernel_surface("k7", 7, "int32", 384, 4),
        _kernel_surface("k8", KERNEL_ELEMENTWISE, "int32", 2048, 4),
        _kernel_surface("k9", KERNEL_MXU, "float32", 256, 4),
        _kernel_surface("k10@2", KERNEL_STREAM, "int32", 512, 2),
        _kernel_surface("k10@4", KERNEL_STREAM, "int32", 512, 4),
        _kernel_surface("k10@8", KERNEL_STREAM, "int32", 512, 8),
        _dd_surface(),
        _xla_surface(),
        _stream_surface(),
        _serve_surface(),
        # the reduction family (ISSUE 20): mxu-scan is the one family
        # surface with a genuinely novel lowering; the baselines ride
        # along so a live window compiles none of them twice
        _family_scan_surface("mxu-scan", "float32"),
        _family_scan_surface("xla-cumsum", "int32"),
        _family_seg_surface(),
        _family_arg_surface(),
    ]


def run_warm(n: int = 1 << 20, skip: Optional[set] = None,
             only: Optional[set] = None, log=print) -> List[dict]:
    """Probe every registered surface (module docstring); returns one
    report row per surface. `skip` names surfaces an interrupted prior
    pass already banked (the resume path of main()); `only` restricts
    the registry (the focused-rehearsal seam, --only).

    No reference analog (TPU-native).
    """
    active = [(s, b) for s, b in surfaces()
              if only is None or s in only]
    rows: List[dict] = []
    ledger.emit("warm.start", surfaces=len(active))
    for surface, build in active:
        if skip and surface in skip:
            rows.append({"surface": surface, "verdict": "resumed",
                         "error": None})
            log(f"  warm {surface:<16} resumed (banked by the "
                "interrupted pass)")
            continue
        try:
            fn, args = build(n)
            obs_compile.probe_lower_compile(fn, *args, surface=surface)  # redlint: disable=RED025 -- warm IS the compile observatory's AOT probe pass: lower+compile only, no device launch to plan
            row = {"surface": surface, "error": None,
                   **(obs_compile.last_observation() or {})}
        except Exception as e:   # the report IS the product
            row = {"surface": surface, "verdict": "failed",
                   "error": f"{type(e).__name__}: {e}"[:300]}
        rows.append(row)
        ledger.emit("warm.surface", surface=surface,
                    verdict=row.get("verdict"),
                    error=row.get("error"))
        v = row.get("verdict") or "?"
        extra = ""
        if row.get("compile_s") is not None:
            # warm.py is the sanctioned human reporter of compile
            # timings (lint/rules.py COMPILE_TIMING_WHITELIST); the
            # typed record is the compile.* events + the ledger rows
            extra = (f" lower {row.get('lower_s', 0):.2f}s "
                     f"compile {row['compile_s']:.2f}s")
        log(f"  warm {surface:<16} {v:<7}{extra}"
            + (f"  {row['error']}" if row.get("error") else ""))
    cold = sum(1 for r in rows if r.get("verdict") == "cold")
    warm_n = sum(1 for r in rows if r.get("verdict") == "warm")
    failed = sum(1 for r in rows if r.get("error"))
    ledger.emit("warm.end", cold=cold, warm=warm_n, failed=failed)
    return rows


def main(argv=None) -> int:
    """CLI: the off-chip warming pass (module docstring) — the CUDA
    suite's kernels compiled at build time, so: no reference analog.
    Exit 0 when at least one surface lowered; 1 when every probe
    failed (the toolchain itself is broken — say so loudly before a
    window spends minutes discovering it)."""
    p = argparse.ArgumentParser(
        prog="tpu_reductions.bench.warm",
        description="AOT-lower every never-lowered kernel surface into "
                    "the persistent compile cache and report which "
                    "remain cold (compile observatory, ISSUE 8)")
    p.add_argument("--n", type=int, default=1 << 20,
                   help="Elements per surface (geometry only — nothing "
                        "executes)")
    p.add_argument("--platform", type=str, default=None,
                   choices=("cpu", "tpu"))
    p.add_argument("--out", type=str,
                   default=obs_compile.DEFAULT_LEDGER,
                   help="Compile-ledger artifact (default "
                        "compile_ledger.json; resumable — an "
                        "interrupted pass keeps its banked surfaces)")
    p.add_argument("--only", type=str, default=None,
                   help="Comma-separated surface ids to restrict to "
                        "(focused rehearsals/tests)")
    ns = p.parse_args(argv)
    if ns.n <= 0:
        p.error("--n must be positive")
    # k10's deepest case needs threads*128*depth elements in flight
    if ns.n < 512 * 128 * 8:
        p.error(f"--n must be >= {512 * 128 * 8} so the deepest k10 "
                "pipeline has a full working set")
    _apply_platform(ns)

    # flight recorder + watchdog, armed together (docs/OBSERVABILITY.md)
    ledger.arm_session("bench.warm",
                       argv=list(argv) if argv else sys.argv[1:])
    from tpu_reductions.exec.core import maybe_arm_for_tpu
    maybe_arm_for_tpu()   # AOT compiles still cross the tunnel on-chip

    # resume (the Checkpoint contract, observatory spelling): a prior
    # INTERRUPTED pass (complete: false) keeps its banked surfaces; a
    # complete artifact re-probes everything — that second pass is how
    # warm verdicts land (per-window freshness, bench/resume.py)
    prior = obs_compile.load(ns.out)
    skip = set()
    if prior is not None and prior.get("complete") is False:
        skip = {r.get("surface") for r in prior.get("surfaces", [])
                if isinstance(r, dict)}
    store = obs_compile.arm(ns.out)

    only = {s.strip() for s in ns.only.split(",") if s.strip()} \
        if ns.only else None
    rows = run_warm(n=ns.n, skip=skip, only=only,
                    log=lambda m: print(m, file=sys.stderr))
    cold = [r["surface"] for r in rows if r.get("verdict") == "cold"]
    warm_n = [r["surface"] for r in rows if r.get("verdict") == "warm"]
    failed = [r["surface"] for r in rows if r.get("error")]
    probed = len(rows) - len(failed)
    print(f"warm: {probed}/{len(rows)} surface(s) staged into the "
          f"cache; {len(warm_n)} already warm"
          + ("; still cold next run: none" if not cold
             else f"; cold this pass (warm next): {', '.join(cold)}")
          + (f"; FAILED to lower: {', '.join(failed)}" if failed
             else ""))
    if store is not None:
        store.finalize()
        print(f"wrote {ns.out}")
    return 0 if probed > 0 else 1


if __name__ == "__main__":
    sys.exit(main())
