"""L4/L5: benchmark drivers, sweep, aggregation, plotting. No reference analog (TPU-native)."""
